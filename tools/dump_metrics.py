"""Pretty-print / diff telemetry JSONL metric snapshots, and wrap JSONL
trace files for Perfetto.

The exporter (multiverso_tpu/telemetry/exporter.py) appends one JSON
record per interval to ``metrics-rank<r>.jsonl``; MSG_STATS replies and
``table.server_stats(rank)`` return the same shape. This tool makes those
records comparable across bench runs:

  python tools/dump_metrics.py show  <metrics.jsonl> [--record N]
  python tools/dump_metrics.py diff  <a.jsonl> <b.jsonl>
  python tools/dump_metrics.py to-perfetto <trace.jsonl> <out.json>

``show`` prints the chosen record (default: last) as a monitor table
(count / mean / p50 / p90 / p99 / max) plus the shard stats. ``diff``
aligns two records by monitor name and reports count deltas and p50/p99
ratios — the "did this bench run regress the tail" question in one
screen. ``to-perfetto`` wraps a JSONL trace-event file into the
``{"traceEvents": [...]}`` envelope the Perfetto UI / chrome://tracing
expect (events from several ranks' files may be concatenated first; the
spans carry ``pid`` = rank).

Both commands also accept the cluster aggregator's time series
(``cluster.jsonl``, records with ``kind: "cluster"`` — see
``telemetry/aggregator.py``): ``show`` adds the per-rank health block,
per-table cluster totals/rates/skew, and the hot-key table; ``diff`` of
two cluster records prints per-table RATE and SKEW deltas between the
two runs alongside the merged-monitor comparison.

Step-profiler files (``profile-rank<r>.jsonl``, records with
``kind: "step"`` — telemetry/profiler.py) are recognized too: ``show``
prints the per-step critical-path table (top phase, stall %, compile
counts) and ``diff`` compares per-phase mean times and stall fractions
between two runs. The deeper merge (profile + trace spans on one
Perfetto timeline) is ``tools/mvprof.py``.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# shared step-record aggregation (telemetry/profiler.py) — the step
# tables here and tools/mvprof.py's report must never drift
from multiverso_tpu.telemetry.profiler import (  # noqa: E402
    aggregate_step_records, step_top_phase)


def load_records(path: str) -> List[Dict]:
    """All JSON records of a JSONL file (blank lines skipped)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    if not out:
        raise ValueError(f"{path}: no records")
    return out


def pick_record(records: List[Dict], index: Optional[int] = None) -> Dict:
    return records[-1 if index is None else index]


def _fmt(v: float) -> str:
    return f"{v:>9.3f}"


def _monitor_table(mons: Dict) -> List[str]:
    """The monitor table lines (shared by per-rank and cluster shows)."""
    lines = [f"{'monitor':<44} {'count':>8} {'mean':>9} "
             f"{'p50':>9} {'p90':>9} {'p99':>9} {'max':>9}"]
    for name in sorted(mons):
        m = mons[name]
        count = m.get("count", 0)
        mean = m.get("sum_ms", 0.0) / count if count else 0.0
        row = f"{name:<44} {count:>8}"
        if m.get("timed", m.get("count")):
            row += (f" {_fmt(mean)} {_fmt(m.get('p50_ms', 0))}"
                    f" {_fmt(m.get('p90_ms', 0))}"
                    f" {_fmt(m.get('p99_ms', 0))}"
                    f" {_fmt(m.get('max_ms', 0))}")
        lines.append(row)
    return lines


def _mb(v) -> str:
    return "-" if not isinstance(v, (int, float)) else f"{v / 1e6:.2f}"


def _memory_lines(mem: Dict) -> List[str]:
    """Per-rank MSG_STATS ``memory`` block -> the component byte table
    (shared by show; telemetry/memstats.py defines the shape)."""
    lines = [
        "memory: rss %s MB (hwm %s)  device %s MB  samples %s"
        % (mem.get("rss_mb", "-"), mem.get("hwm_mb", "-"),
           _mb(mem.get("device_bytes")), mem.get("samples", 0))]
    comps = mem.get("components") or {}
    if comps:
        lines.append(f"  {'component':<34} {'bytes':>12} {'detail'}")
        for name in sorted(comps):
            g = comps[name]
            if not isinstance(g, dict):
                continue
            main = sum(v for k, v in g.items()
                       if k.endswith("_bytes")
                       and isinstance(v, (int, float))
                       and not isinstance(v, bool))
            detail = ", ".join(
                f"{k}={v}" for k, v in sorted(g.items())
                if not isinstance(v, dict))
            lines.append(f"  {name:<34} {int(main):>12} {detail}")
    for v in (mem.get("verdicts") or [])[-4:]:
        if isinstance(v, dict):
            lines.append("  verdict[%s] %s: " % (v.get("kind"),
                                                 v.get("component"))
                         + ", ".join(f"{k}={x}" for k, x in sorted(
                             v.items())
                             if k not in ("kind", "component")))
    return lines


def _devices_lines(dev: Dict) -> List[str]:
    """Per-rank MSG_STATS ``devices`` block (telemetry/devstats.py) ->
    transfer/collective/compile tables. Shared by per-rank and cluster
    shows; every field is optional — an older peer's payload without
    the block never reaches here, and a partial block renders what it
    has."""
    lines = []
    tr = dev.get("transfers") or {}
    if tr:
        lines.append("devices.transfers: " + "  ".join(
            f"{d}={_mb((g or {}).get('bytes'))} MB"
            f"/{(g or {}).get('ops', 0)} ops"
            for d, g in sorted(tr.items())))
    colls = dev.get("collectives") or {}
    if colls:
        lines.append(f"  {'collective':<24} {'calls':>7} {'mb':>9} "
                     f"{'ms':>9}")
        for op in sorted(colls):
            c = colls[op]
            if not isinstance(c, dict):
                continue
            lines.append(f"  {op:<24} {c.get('calls', 0):>7} "
                         f"{_mb(c.get('bytes')):>9} "
                         f"{c.get('ms', 0):>9}")
    comp = dev.get("compiles_by_mesh") or {}
    if comp:
        lines.append("  compiles by mesh: " + "  ".join(
            f"{label}={c.get('compiles', 0)}"
            f"/{c.get('compile_s', 0)}s"
            for label, c in sorted(comp.items())
            if isinstance(c, dict)))
    per = dev.get("per_device") or {}
    if per:
        lines.append("  live buffers: " + "  ".join(
            f"{d}={_mb(g.get('bytes'))} MB/{g.get('arrays', 0)}"
            for d, g in sorted(per.items()) if isinstance(g, dict)))
    if dev.get("hygiene_findings"):
        lines.append(f"  HYGIENE FINDINGS: {dev['hygiene_findings']} "
                     "(see compile-hygiene-rank*.json / mvprof)")
    return lines


def _tenants_lines(ten: Dict) -> List[str]:
    """MSG_STATS ``tenants`` block (telemetry/tenants.py) -> the
    per-(table, tenant) accounting table + budget decisions + verdict
    state. One renderer for both the per-rank payload and the
    aggregator's merged cluster shape (extra merged-only fields like
    ``wire`` render when present)."""
    lines = ["tenants: episodes=%s active=%s" % (
        ten.get("episodes", 0), ten.get("active", False))]
    shares = ten.get("shares") or {}
    if shares:
        lines.append("  shares: " + "  ".join(
            f"{tn}={sh}" for tn, sh in
            sorted(shares.items(), key=lambda kv: -kv[1])))
    v = ten.get("verdict")
    if isinstance(v, dict):
        lines.append("  verdict[%s] tenant=%s: " % (v.get("kind"),
                                                    v.get("tenant"))
                     + ", ".join(f"{k}={x}" for k, x in sorted(v.items())
                                 if k not in ("kind", "tenant")))
    tables = ten.get("tables") or {}
    if tables:
        lines.append(f"  {'table/tenant':<30} {'served':>8} {'shed':>7} "
                     f"{'deferred':>9} {'max_age_s':>10} {'p50':>9} "
                     f"{'p99':>9}")
        for tname in sorted(tables):
            tt = tables[tname]
            if not isinstance(tt, dict):
                continue
            for tn in sorted(tt):
                e = tt[tn]
                if not isinstance(e, dict):
                    continue
                h = e.get("infer") or {}
                lines.append(
                    f"  {tname + '/' + tn:<30} {e.get('served', 0):>8} "
                    f"{e.get('shed', 0):>7} {e.get('deferred', 0):>9} "
                    f"{e.get('max_age_s', 0):>10} "
                    f"{h.get('p50_ms', 0):>9} {h.get('p99_ms', 0):>9}")
    adm = ten.get("admission") or {}
    for k in sorted(adm):
        a = adm[k]
        if isinstance(a, dict):
            lines.append(
                f"  budget[{k}]: admitted={a.get('admitted', 0)} "
                f"shed={a.get('shed', 0)} "
                f"qps_limit={a.get('qps_limit')}")
    wire = ten.get("wire") or {}
    if wire:
        lines.append("  wire: " + "  ".join(
            f"{tn}={w.get('ops', 0)}op"
            f"/{_mb(w.get('add_bytes', 0) + w.get('get_bytes', 0))}MB"
            for tn, w in sorted(wire.items()) if isinstance(w, dict)))
    return lines


# objective kind -> SLI unit for the value column (check_obs_surface
# lint 7: every telemetry/slo.py objective kind must render here or in
# mvtop — an objective no renderer can show is a verdict into the void)
_SLO_KIND_UNITS = {
    "serve_latency_p99": "ms", "add_latency_p99": "ms",
    "staleness": "s", "shed_rate": "", "availability": "",
    "stall_fraction": "", "steady_recompiles": "",
    "recovery_s": "s", "scale_efficiency": "",
}


def _slo_lines(slo: Dict) -> List[str]:
    """MSG_STATS ``slo`` block (telemetry/slo.py sentinel snapshot) ->
    the per-objective burn-rate table + straggler + recent episodes.
    One renderer for both the per-rank payload and the aggregator's
    merged cluster record (identical shape — the merge passes the
    armed rank's snapshot through)."""
    firing = slo.get("firing") or []
    lines = ["slo: evals=%s episodes=%s %s" % (
        slo.get("evals", 0), slo.get("episodes", 0),
        ("FIRING " + ",".join(firing)) if firing else "ok")]
    objs = slo.get("objectives") or {}
    if objs:
        lines.append(f"  {'objective':<26} {'kind':<19} {'state':<7} "
                     f"{'value':>12} {'burn_f':>7} {'burn_s':>7} "
                     f"{'eps':>4}")
        for name in sorted(objs):
            o = objs[name]
            kind = o.get("kind") or "?"
            val = o.get("value")
            unit = _SLO_KIND_UNITS.get(kind, "")
            cell = "-" if val is None else f"{val:.4g}{unit}"
            bf, bs = o.get("burn_fast"), o.get("burn_slow")
            lines.append(
                f"  {name:<26} {kind:<19} "
                f"{'FIRING' if o.get('firing') else 'ok':<7} "
                f"{cell:>12} "
                f"{'-' if bf is None else format(bf, '.1f'):>7} "
                f"{'-' if bs is None else format(bs, '.1f'):>7} "
                f"{o.get('episodes', 0):>4}")
    s = slo.get("straggler")
    if isinstance(s, dict):
        lines.append(
            "  straggler: rank %s (%s%s) score=%.2f" % (
                s.get("rank"), s.get("attribution"),
                ", top phase " + s["top_phase"]
                if s.get("top_phase") else "",
                s.get("score") or 0.0))
    for ev in (slo.get("recent") or [])[-6:]:
        lines.append(
            "  %s: %s ep%s value=%s burn=%s/%s" % (
                ev.get("kind"), ev.get("objective"), ev.get("episode"),
                ev.get("value"), ev.get("burn_fast"),
                ev.get("burn_slow")))
    return lines


def format_record(rec: Dict) -> str:
    """One record -> the human table (pure function; tested directly).
    Cluster records (``kind: "cluster"``) dispatch to
    :func:`format_cluster_record`."""
    if rec.get("kind") == "cluster":
        return format_cluster_record(rec)
    lines = [f"rank {rec.get('rank', '?')}  ts {rec.get('ts', '?')}  "
             f"addr {rec.get('addr', '-')}"]
    mons = rec.get("monitors", {})
    if mons:
        lines.extend(_monitor_table(mons))
    for table in sorted(rec.get("shards", {})):
        s = dict(rec["shards"][table])
        apply_h = s.pop("apply", None)
        hot = s.pop("hotkeys", None)
        stm = s.pop("tenants", None)
        lines.append(f"shard[{table}]: " + ", ".join(
            f"{k}={v}" for k, v in sorted(s.items())))
        if isinstance(stm, dict):
            cells = [
                f"{tn}={c.get('ops', 0)}op"
                f"/+{c.get('add_bytes', 0)}B/-{c.get('get_bytes', 0)}B"
                for tn, c in sorted(stm.items())
                if tn != "~sketch" and isinstance(c, dict)]
            if cells:
                lines.append("  tenants: " + "  ".join(cells))
        if apply_h and apply_h.get("count"):
            lines.append(
                f"  apply: count={apply_h['count']} "
                f"p50={apply_h['p50_ms']:.3f} p99={apply_h['p99_ms']:.3f} "
                f"max={apply_h['max_ms']:.3f} ms")
        if hot and hot.get("items"):
            head = "  ".join(f"{k}:{c}" for k, c, _ in hot["items"][:8])
            lines.append(f"  hot rows (of {hot.get('total', 0)}): {head}")
    prof = rec.get("profile")
    if isinstance(prof, dict):
        lines.append(
            "profile: steps=%s stall=%.1f%% attributed=%.1f%% "
            "recompiles=%s" % (
                prof.get("steps"),
                100.0 * (prof.get("stall_fraction") or 0.0),
                100.0 * (prof.get("attributed_fraction") or 0.0),
                prof.get("steady_recompiles")))
        phases = prof.get("phases") or {}
        if phases:
            lines.append("  phases(ms): " + "  ".join(
                f"{n}={v}" for n, v in sorted(phases.items())))
    mem = rec.get("memory")
    if isinstance(mem, dict):
        lines.extend(_memory_lines(mem))
    dev = rec.get("devices")
    if isinstance(dev, dict):
        lines.extend(_devices_lines(dev))
    ten = rec.get("tenants")
    if isinstance(ten, dict):
        lines.extend(_tenants_lines(ten))
    slo = rec.get("slo")
    if isinstance(slo, dict):
        lines.extend(_slo_lines(slo))
    for name in sorted(rec.get("notes", {})):
        lines.append(f"note[{name}] {rec['notes'][name]}")
    return "\n".join(lines)


def format_profile_records(records: List[Dict]) -> str:
    """Step-profiler JSONL (``profile-rank<r>.jsonl``, records with
    ``kind: "step"``) -> a per-step critical-path table plus the
    aggregate phase breakdown."""
    steps = [r for r in records if r.get("kind") == "step"]
    if not steps:
        return "(no step records)"
    lines = [f"{'step':>5} {'name':<18} {'wall_ms':>9} {'top phase':<22} "
             f"{'stall%':>7} {'overlap':>8} {'compiles':>8}"]
    for r in steps:
        top_n, top_ms = step_top_phase(r)
        top_s = f"{top_n} ({top_ms:.1f} ms)" if top_n else "-"
        lines.append(
            f"{r.get('step', '?'):>5} {r.get('name', '?'):<18} "
            f"{r.get('wall_ms', 0):>9.2f} {top_s:<22} "
            f"{100 * r.get('stall_fraction', 0):>6.1f}% "
            f"{r.get('overlap_ms', 0):>8.2f} "
            f"{r.get('jax', {}).get('compiles', 0):>8}")
    agg = aggregate_step_records(steps)
    wall, stall = agg["wall_ms"], agg["stall_ms"]
    lines.append("")
    lines.append(f"{agg['steps']} steps, {wall:.1f} ms wall; exclusive "
                 "phase totals: " + "  ".join(
                     f"{n}={v:.1f}ms" for n, v in
                     sorted(agg["phases_ms"].items(),
                            key=lambda kv: -kv[1]))
                 + f"  stall={stall:.1f}ms"
                 + (f" ({100 * stall / wall:.1f}%)" if wall else ""))
    return "\n".join(lines)


def diff_profile_records(a: List[Dict], b: List[Dict]) -> str:
    """Two profile JSONL files -> per-phase mean-ms ratios and the
    stall-fraction comparison (b relative to a)."""

    def agg(records):
        g = aggregate_step_records(records)
        n = max(g["steps"], 1)
        return ({k: v / n for k, v in g["phases_ms"].items()},
                (g["stall_ms"] / g["wall_ms"] if g["wall_ms"] else 0.0),
                g["steps"])

    pa, sa, na = agg(a)
    pb, sb, nb = agg(b)
    lines = [f"{'phase':<24} {'mean ms a':>10} {'mean ms b':>10} "
             f"{'b/a':>6}"]
    for name in sorted(set(pa) | set(pb)):
        va, vb = pa.get(name), pb.get(name)
        if va is None or vb is None:
            lines.append(f"{name:<24} "
                         f"{'-' if va is None else round(va, 3):>10} "
                         f"{'-' if vb is None else round(vb, 3):>10} "
                         f"{'only ' + ('b' if va is None else 'a'):>6}")
            continue
        ratio = f"{vb / va:>6.2f}" if va else f"{'-':>6}"
        lines.append(f"{name:<24} {va:>10.3f} {vb:>10.3f} {ratio}")
    lines.append(f"stall fraction: {sa:.3f} ({na} steps) -> "
                 f"{sb:.3f} ({nb} steps)")
    return "\n".join(lines)


def format_cluster_record(rec: Dict) -> str:
    """One aggregator record -> per-rank health, per-table totals/rates/
    skew, hot keys, and the merged-monitor table."""
    lines = [f"cluster  ts {rec.get('ts', '?')}  world "
             f"{rec.get('world', '?')}  stats from {rec.get('polled', 0)}"]
    for r in sorted(rec.get("ranks", {}), key=int):
        e = rec["ranks"][r]
        lines.append(f"rank {r}: " + ", ".join(
            f"{k}={v}" for k, v in sorted(e.items()) if v is not None))
    rates = rec.get("rates", {})
    for tname in sorted(rec.get("tables", {})):
        t = dict(rec["tables"][tname])
        apply_h = t.pop("apply", None)
        t.pop("shards", None)
        lines.append(f"table[{tname}]: " + ", ".join(
            f"{k}={v}" for k, v in sorted(t.items())))
        tr = rates.get(tname)
        if tr:
            lines.append("  rates: " + ", ".join(
                f"{k}={v}" for k, v in sorted(tr.items())))
        if apply_h and apply_h.get("count"):
            lines.append(
                f"  apply(merged): count={apply_h['count']} "
                f"p50={apply_h['p50_ms']:.3f} p99={apply_h['p99_ms']:.3f} "
                f"max={apply_h['max_ms']:.3f} ms")
    for tname in sorted(rec.get("serving", {})):
        s = dict(rec["serving"][tname])
        reps = s.pop("replicas", {})
        s.pop("rates", None)
        lines.append(f"serving[{tname}]: " + ", ".join(
            f"{k}={v}" for k, v in sorted(s.items()) if v is not None))
        for r in sorted(reps, key=str):
            e = reps[r]
            lines.append(f"  replica@rank{r}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(e.items())
                if v is not None))
    for r in sorted(rec.get("profile", {}), key=str):
        p = rec["profile"][r]
        lines.append(
            "profile@rank%s: steps=%s stall=%.1f%% recompiles=%s"
            % (r, p.get("steps"),
               100.0 * (p.get("stall_fraction") or 0.0),
               p.get("steady_recompiles")))
    mem = rec.get("memory")
    if isinstance(mem, dict):
        t = mem.get("totals", {})
        lines.append("memory(cluster): " + ", ".join(
            f"{k}={v}" for k, v in sorted(t.items())))
        for r in sorted(mem.get("ranks", {}), key=str):
            e = mem["ranks"][r]
            lines.append(f"  memory@rank{r}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(e.items())
                if v not in (None, [])))
    dev = rec.get("devices")
    if isinstance(dev, dict):
        t = dev.get("totals", {})
        if t:
            lines.append("devices(cluster): " + ", ".join(
                f"{k}={v}" for k, v in sorted(t.items())))
        for r in sorted(dev.get("ranks", {}), key=str):
            d = dev["ranks"][r]
            if isinstance(d, dict):
                lines.extend("  " + ln for ln in _devices_lines(d))
    ten = rec.get("tenants")
    if isinstance(ten, dict):
        lines.extend(_tenants_lines(ten))
    slo = rec.get("slo")
    if isinstance(slo, dict):
        lines.extend(_slo_lines(slo))
    for tname in sorted(rec.get("hotkeys", {})):
        h = rec["hotkeys"][tname]
        head = "  ".join(f"{k}:{c}" for k, c, _ in h.get("top", [])[:8])
        lines.append(f"hot[{tname}] total={h.get('total', 0)} top: {head}")
        curve = h.get("hit_rate_curve") or []
        if curve:
            lines.append("  cache-hit-if-cached: " + "  ".join(
                f"top{k}={r * 100:.0f}%" for k, r in curve))
    mons = rec.get("monitors", {})
    if mons:
        lines.extend(_monitor_table(mons))
    return "\n".join(lines)


def diff_cluster_records(a: Dict, b: Dict) -> str:
    """Two cluster records (typically the last record of two runs'
    ``cluster.jsonl``) -> per-table rate and skew deltas, then the
    merged-monitor comparison."""
    at, bt = a.get("tables", {}), b.get("tables", {})
    ar, br = a.get("rates", {}), b.get("rates", {})
    names = sorted(set(at) | set(bt))
    lines = [f"{'table':<24} {'adds a':>10} {'adds b':>10} "
             f"{'gets a':>10} {'gets b':>10} {'skew a':>7} {'skew b':>7} "
             f"{'skew b/a':>8}"]
    for name in names:
        ta, tb = at.get(name), bt.get(name)
        if ta is None or tb is None:
            lines.append(f"{name:<24} {'only ' + ('b' if ta is None else 'a')}")
            continue
        sa, sb = ta.get("skew"), tb.get("skew")
        ratio = (f"{sb / sa:>8.2f}" if sa and sb else f"{'-':>8}")
        lines.append(f"{name:<24} {ta.get('adds', 0):>10} "
                     f"{tb.get('adds', 0):>10} {ta.get('gets', 0):>10} "
                     f"{tb.get('gets', 0):>10} {sa or 0:>7.2f} "
                     f"{sb or 0:>7.2f} {ratio}")
        ra, rb = ar.get(name), br.get(name)
        if ra and rb:
            deltas = []
            for k in ("adds_per_s", "gets_per_s", "applies_per_s",
                      "wire_bytes_per_s", "skew_window"):
                if k in ra or k in rb:
                    deltas.append(f"{k}: {ra.get(k, 0)} -> {rb.get(k, 0)}")
            if deltas:
                lines.append("  " + ", ".join(deltas))
    ma, mb_ = a.get("memory") or {}, b.get("memory") or {}
    if ma or mb_:
        ta, tb = ma.get("totals") or {}, mb_.get("totals") or {}
        deltas = []
        for k in sorted(set(ta) | set(tb)):
            va, vb = ta.get(k, 0), tb.get(k, 0)
            if va != vb and isinstance(va, (int, float)) \
                    and isinstance(vb, (int, float)):
                deltas.append(f"{k}: {va} -> {vb} ({vb - va:+g})")
        if deltas:
            lines.append("memory totals deltas: " + ", ".join(deltas))
    lines.append("")
    lines.append(diff_records({"monitors": a.get("monitors", {})},
                              {"monitors": b.get("monitors", {})}))
    return "\n".join(lines)


def diff_records(a: Dict, b: Dict) -> str:
    """Align two records by monitor name; report count delta and
    p50/p99 ratios (b relative to a — >1 means b is slower). Two
    cluster records dispatch to :func:`diff_cluster_records`."""
    if a.get("kind") == "cluster" and b.get("kind") == "cluster":
        return diff_cluster_records(a, b)
    mem_lines = diff_memory(a.get("memory"), b.get("memory"))
    am, bm = a.get("monitors", {}), b.get("monitors", {})
    names = sorted(set(am) | set(bm))
    lines = [f"{'monitor':<44} {'count a':>8} {'count b':>8} "
             f"{'p50 b/a':>8} {'p99 b/a':>8}"]
    for name in names:
        ma, mb = am.get(name), bm.get(name)
        if ma is None or mb is None:
            lines.append(f"{name:<44} "
                         f"{'-' if ma is None else ma.get('count', 0):>8} "
                         f"{'-' if mb is None else mb.get('count', 0):>8} "
                         f"{'only ' + ('b' if ma is None else 'a'):>8}")
            continue
        row = (f"{name:<44} {ma.get('count', 0):>8} "
               f"{mb.get('count', 0):>8}")
        if ma.get("p50_ms") and mb.get("p50_ms") is not None:
            row += f" {mb['p50_ms'] / ma['p50_ms']:>8.2f}"
            if ma.get("p99_ms"):
                row += f" {mb['p99_ms'] / ma['p99_ms']:>8.2f}"
        lines.append(row)
    lines.extend(mem_lines)
    return "\n".join(lines)


def diff_memory(ma: Optional[Dict], mb: Optional[Dict]) -> List[str]:
    """RSS / device / ledger-total deltas between two records' memory
    blocks (b relative to a); [] when either side lacks the block."""
    if not isinstance(ma, dict) or not isinstance(mb, dict):
        return []
    lines = ["memory deltas (b - a):"]
    for k, scale, unit in (("rss_mb", 1.0, "MB"),
                           ("device_bytes", 1e-6, "MB")):
        va, vb = ma.get(k), mb.get(k)
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            lines.append(f"  {k}: {va} -> {vb} "
                         f"({(vb - va) * scale:+.2f} {unit})")
    ta, tb = ma.get("totals") or {}, mb.get("totals") or {}
    for k in sorted(set(ta) | set(tb)):
        va, vb = ta.get(k, 0), tb.get(k, 0)
        if va != vb and isinstance(va, (int, float)) \
                and isinstance(vb, (int, float)):
            lines.append(f"  totals.{k}: {va} -> {vb} ({vb - va:+g})")
    return lines if len(lines) > 1 else []


def is_history_record(rec: Dict) -> bool:
    """BENCH_HISTORY.jsonl entries (tools/run_bench.py history_entry):
    the trajectory index a run appends one line to per recorded run."""
    return isinstance(rec, dict) and "record" in rec \
        and "metrics" in rec and "regressions" in rec


def format_history_records(records: List[Dict],
                           last: int = 20) -> str:
    """The bench trajectory as one table: per run the headline value,
    completeness, flag count, and every run_bench-tracked metric that
    moved — the arc BENCH_r*.json mtime-globbing used to be the only
    way to reconstruct."""
    rows = records[-last:]
    lines = [f"{'#':>3} {'record':<20} {'complete':>8} {'value':>10} "
             f"{'vs_base':>8} {'flags':>5}  tracked metrics"]
    base = len(records) - len(rows)
    for i, r in enumerate(rows):
        mets = r.get("metrics") or {}
        brief = "  ".join(f"{k}={v}" for k, v in sorted(mets.items())[:4])
        if len(mets) > 4:
            brief += f"  (+{len(mets) - 4} more)"
        lines.append(
            f"{base + i:>3} {str(r.get('record'))[:20]:<20} "
            f"{'yes' if r.get('complete') else ('TRUNC' if r.get('truncated') else 'no'):>8} "
            f"{r.get('value') if r.get('value') is not None else '-':>10} "
            f"{r.get('vs_baseline') if r.get('vs_baseline') is not None else '-':>8} "
            f"{len(r.get('regressions') or []):>5}  {brief}")
        for flag in (r.get("regressions") or [])[:3]:
            lines.append(f"      FLAG: {flag}")
    return "\n".join(lines)


def diff_history_records(a: Dict, b: Dict) -> str:
    """Two trajectory entries (default: the last two) -> every tracked
    metric's movement, b relative to a."""
    ma, mb = a.get("metrics") or {}, b.get("metrics") or {}
    lines = [f"{a.get('record')} -> {b.get('record')}",
             f"{'metric':<40} {'a':>12} {'b':>12} {'b/a':>7}"]
    for k in sorted(set(ma) | set(mb)):
        va, vb = ma.get(k), mb.get(k)
        if va is None or vb is None:
            lines.append(f"{k:<40} "
                         f"{'-' if va is None else va:>12} "
                         f"{'-' if vb is None else vb:>12} "
                         f"{'only ' + ('b' if va is None else 'a'):>7}")
            continue
        ratio = f"{vb / va:>7.2f}" if va else f"{'-':>7}"
        lines.append(f"{k:<40} {va:>12} {vb:>12} {ratio}")
    for side, r in (("a", a), ("b", b)):
        for flag in (r.get("regressions") or []):
            lines.append(f"  {side} FLAG: {flag}")
    return "\n".join(lines)


def to_perfetto(trace_jsonl: str, out_path: str) -> int:
    """JSONL trace events -> Perfetto/chrome JSON envelope; returns the
    event count."""
    events = load_records(trace_jsonl)
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def main(argv: List[str]) -> int:
    if not argv:
        print(__doc__)
        return 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "show":
        idx = None
        if "--record" in rest:
            i = rest.index("--record")
            idx = int(rest[i + 1])
            rest = rest[:i] + rest[i + 2:]
        records = load_records(rest[0])
        if records[-1].get("kind") == "step":
            # step-profiler JSONL: the per-step table IS the show (a
            # single step record says little; --record still narrows)
            if idx is not None:
                records = [records[idx]]
            print(format_profile_records(records))
            return 0
        if is_history_record(records[-1]):
            # BENCH_HISTORY.jsonl: the whole trajectory IS the show
            print(format_history_records(
                records if idx is None else records[: idx + 1]))
            return 0
        print(format_record(pick_record(records, idx)))
        return 0
    if cmd == "diff":
        ra, rb = load_records(rest[0]), load_records(rest[1])
        if (ra[-1].get("kind") == "step"
                and rb[-1].get("kind") == "step"):
            print(diff_profile_records(ra, rb))
            return 0
        if is_history_record(ra[-1]) and is_history_record(rb[-1]):
            # diffing a history file against itself compares the last
            # two runs of the trajectory; two files compare their tails
            if rest[0] == rest[1] and len(ra) >= 2:
                print(diff_history_records(ra[-2], ra[-1]))
            else:
                print(diff_history_records(pick_record(ra),
                                           pick_record(rb)))
            return 0
        print(diff_records(pick_record(ra), pick_record(rb)))
        return 0
    if cmd == "to-perfetto":
        n = to_perfetto(rest[0], rest[1])
        print(f"wrote {n} events to {rest[1]}")
        return 0
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
