"""Pretty-print / diff telemetry JSONL metric snapshots, and wrap JSONL
trace files for Perfetto.

The exporter (multiverso_tpu/telemetry/exporter.py) appends one JSON
record per interval to ``metrics-rank<r>.jsonl``; MSG_STATS replies and
``table.server_stats(rank)`` return the same shape. This tool makes those
records comparable across bench runs:

  python tools/dump_metrics.py show  <metrics.jsonl> [--record N]
  python tools/dump_metrics.py diff  <a.jsonl> <b.jsonl>
  python tools/dump_metrics.py to-perfetto <trace.jsonl> <out.json>

``show`` prints the chosen record (default: last) as a monitor table
(count / mean / p50 / p90 / p99 / max) plus the shard stats. ``diff``
aligns two records by monitor name and reports count deltas and p50/p99
ratios — the "did this bench run regress the tail" question in one
screen. ``to-perfetto`` wraps a JSONL trace-event file into the
``{"traceEvents": [...]}`` envelope the Perfetto UI / chrome://tracing
expect (events from several ranks' files may be concatenated first; the
spans carry ``pid`` = rank).

Both commands also accept the cluster aggregator's time series
(``cluster.jsonl``, records with ``kind: "cluster"`` — see
``telemetry/aggregator.py``): ``show`` adds the per-rank health block,
per-table cluster totals/rates/skew, and the hot-key table; ``diff`` of
two cluster records prints per-table RATE and SKEW deltas between the
two runs alongside the merged-monitor comparison.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional


def load_records(path: str) -> List[Dict]:
    """All JSON records of a JSONL file (blank lines skipped)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    if not out:
        raise ValueError(f"{path}: no records")
    return out


def pick_record(records: List[Dict], index: Optional[int] = None) -> Dict:
    return records[-1 if index is None else index]


def _fmt(v: float) -> str:
    return f"{v:>9.3f}"


def _monitor_table(mons: Dict) -> List[str]:
    """The monitor table lines (shared by per-rank and cluster shows)."""
    lines = [f"{'monitor':<44} {'count':>8} {'mean':>9} "
             f"{'p50':>9} {'p90':>9} {'p99':>9} {'max':>9}"]
    for name in sorted(mons):
        m = mons[name]
        count = m.get("count", 0)
        mean = m.get("sum_ms", 0.0) / count if count else 0.0
        row = f"{name:<44} {count:>8}"
        if m.get("timed", m.get("count")):
            row += (f" {_fmt(mean)} {_fmt(m.get('p50_ms', 0))}"
                    f" {_fmt(m.get('p90_ms', 0))}"
                    f" {_fmt(m.get('p99_ms', 0))}"
                    f" {_fmt(m.get('max_ms', 0))}")
        lines.append(row)
    return lines


def format_record(rec: Dict) -> str:
    """One record -> the human table (pure function; tested directly).
    Cluster records (``kind: "cluster"``) dispatch to
    :func:`format_cluster_record`."""
    if rec.get("kind") == "cluster":
        return format_cluster_record(rec)
    lines = [f"rank {rec.get('rank', '?')}  ts {rec.get('ts', '?')}  "
             f"addr {rec.get('addr', '-')}"]
    mons = rec.get("monitors", {})
    if mons:
        lines.extend(_monitor_table(mons))
    for table in sorted(rec.get("shards", {})):
        s = dict(rec["shards"][table])
        apply_h = s.pop("apply", None)
        hot = s.pop("hotkeys", None)
        lines.append(f"shard[{table}]: " + ", ".join(
            f"{k}={v}" for k, v in sorted(s.items())))
        if apply_h and apply_h.get("count"):
            lines.append(
                f"  apply: count={apply_h['count']} "
                f"p50={apply_h['p50_ms']:.3f} p99={apply_h['p99_ms']:.3f} "
                f"max={apply_h['max_ms']:.3f} ms")
        if hot and hot.get("items"):
            head = "  ".join(f"{k}:{c}" for k, c, _ in hot["items"][:8])
            lines.append(f"  hot rows (of {hot.get('total', 0)}): {head}")
    for name in sorted(rec.get("notes", {})):
        lines.append(f"note[{name}] {rec['notes'][name]}")
    return "\n".join(lines)


def format_cluster_record(rec: Dict) -> str:
    """One aggregator record -> per-rank health, per-table totals/rates/
    skew, hot keys, and the merged-monitor table."""
    lines = [f"cluster  ts {rec.get('ts', '?')}  world "
             f"{rec.get('world', '?')}  stats from {rec.get('polled', 0)}"]
    for r in sorted(rec.get("ranks", {}), key=int):
        e = rec["ranks"][r]
        lines.append(f"rank {r}: " + ", ".join(
            f"{k}={v}" for k, v in sorted(e.items()) if v is not None))
    rates = rec.get("rates", {})
    for tname in sorted(rec.get("tables", {})):
        t = dict(rec["tables"][tname])
        apply_h = t.pop("apply", None)
        t.pop("shards", None)
        lines.append(f"table[{tname}]: " + ", ".join(
            f"{k}={v}" for k, v in sorted(t.items())))
        tr = rates.get(tname)
        if tr:
            lines.append("  rates: " + ", ".join(
                f"{k}={v}" for k, v in sorted(tr.items())))
        if apply_h and apply_h.get("count"):
            lines.append(
                f"  apply(merged): count={apply_h['count']} "
                f"p50={apply_h['p50_ms']:.3f} p99={apply_h['p99_ms']:.3f} "
                f"max={apply_h['max_ms']:.3f} ms")
    for tname in sorted(rec.get("serving", {})):
        s = dict(rec["serving"][tname])
        reps = s.pop("replicas", {})
        s.pop("rates", None)
        lines.append(f"serving[{tname}]: " + ", ".join(
            f"{k}={v}" for k, v in sorted(s.items()) if v is not None))
        for r in sorted(reps, key=str):
            e = reps[r]
            lines.append(f"  replica@rank{r}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(e.items())
                if v is not None))
    for tname in sorted(rec.get("hotkeys", {})):
        h = rec["hotkeys"][tname]
        head = "  ".join(f"{k}:{c}" for k, c, _ in h.get("top", [])[:8])
        lines.append(f"hot[{tname}] total={h.get('total', 0)} top: {head}")
        curve = h.get("hit_rate_curve") or []
        if curve:
            lines.append("  cache-hit-if-cached: " + "  ".join(
                f"top{k}={r * 100:.0f}%" for k, r in curve))
    mons = rec.get("monitors", {})
    if mons:
        lines.extend(_monitor_table(mons))
    return "\n".join(lines)


def diff_cluster_records(a: Dict, b: Dict) -> str:
    """Two cluster records (typically the last record of two runs'
    ``cluster.jsonl``) -> per-table rate and skew deltas, then the
    merged-monitor comparison."""
    at, bt = a.get("tables", {}), b.get("tables", {})
    ar, br = a.get("rates", {}), b.get("rates", {})
    names = sorted(set(at) | set(bt))
    lines = [f"{'table':<24} {'adds a':>10} {'adds b':>10} "
             f"{'gets a':>10} {'gets b':>10} {'skew a':>7} {'skew b':>7} "
             f"{'skew b/a':>8}"]
    for name in names:
        ta, tb = at.get(name), bt.get(name)
        if ta is None or tb is None:
            lines.append(f"{name:<24} {'only ' + ('b' if ta is None else 'a')}")
            continue
        sa, sb = ta.get("skew"), tb.get("skew")
        ratio = (f"{sb / sa:>8.2f}" if sa and sb else f"{'-':>8}")
        lines.append(f"{name:<24} {ta.get('adds', 0):>10} "
                     f"{tb.get('adds', 0):>10} {ta.get('gets', 0):>10} "
                     f"{tb.get('gets', 0):>10} {sa or 0:>7.2f} "
                     f"{sb or 0:>7.2f} {ratio}")
        ra, rb = ar.get(name), br.get(name)
        if ra and rb:
            deltas = []
            for k in ("adds_per_s", "gets_per_s", "applies_per_s",
                      "wire_bytes_per_s", "skew_window"):
                if k in ra or k in rb:
                    deltas.append(f"{k}: {ra.get(k, 0)} -> {rb.get(k, 0)}")
            if deltas:
                lines.append("  " + ", ".join(deltas))
    lines.append("")
    lines.append(diff_records({"monitors": a.get("monitors", {})},
                              {"monitors": b.get("monitors", {})}))
    return "\n".join(lines)


def diff_records(a: Dict, b: Dict) -> str:
    """Align two records by monitor name; report count delta and
    p50/p99 ratios (b relative to a — >1 means b is slower). Two
    cluster records dispatch to :func:`diff_cluster_records`."""
    if a.get("kind") == "cluster" and b.get("kind") == "cluster":
        return diff_cluster_records(a, b)
    am, bm = a.get("monitors", {}), b.get("monitors", {})
    names = sorted(set(am) | set(bm))
    lines = [f"{'monitor':<44} {'count a':>8} {'count b':>8} "
             f"{'p50 b/a':>8} {'p99 b/a':>8}"]
    for name in names:
        ma, mb = am.get(name), bm.get(name)
        if ma is None or mb is None:
            lines.append(f"{name:<44} "
                         f"{'-' if ma is None else ma.get('count', 0):>8} "
                         f"{'-' if mb is None else mb.get('count', 0):>8} "
                         f"{'only ' + ('b' if ma is None else 'a'):>8}")
            continue
        row = (f"{name:<44} {ma.get('count', 0):>8} "
               f"{mb.get('count', 0):>8}")
        if ma.get("p50_ms") and mb.get("p50_ms") is not None:
            row += f" {mb['p50_ms'] / ma['p50_ms']:>8.2f}"
            if ma.get("p99_ms"):
                row += f" {mb['p99_ms'] / ma['p99_ms']:>8.2f}"
        lines.append(row)
    return "\n".join(lines)


def to_perfetto(trace_jsonl: str, out_path: str) -> int:
    """JSONL trace events -> Perfetto/chrome JSON envelope; returns the
    event count."""
    events = load_records(trace_jsonl)
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def main(argv: List[str]) -> int:
    if not argv:
        print(__doc__)
        return 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "show":
        idx = None
        if "--record" in rest:
            i = rest.index("--record")
            idx = int(rest[i + 1])
            rest = rest[:i] + rest[i + 2:]
        print(format_record(pick_record(load_records(rest[0]), idx)))
        return 0
    if cmd == "diff":
        a = pick_record(load_records(rest[0]))
        b = pick_record(load_records(rest[1]))
        print(diff_records(a, b))
        return 0
    if cmd == "to-perfetto":
        n = to_perfetto(rest[0], rest[1])
        print(f"wrote {n} events to {rest[1]}")
        return 0
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
