"""Benchmark harness: prints ONE JSON line for the driver.

Primary metric (BASELINE.json): WordEmbedding words/sec/chip, measured by the
fused skipgram-NS trainer on a synthetic zipf corpus (text8 stand-in; this
environment has no network egress). Secondary metrics (ArrayTable Add/Get p50
latency and bandwidth) ride along in "extra".

``vs_baseline``: the reference publishes no words/sec number
(BASELINE.json "published": {}), so the ratio is computed against a locally
recorded baseline in BENCH_BASELINE.json when present (first run writes it),
else 1.0. The recorded baseline (150,881 w/s) is this framework's first
working implementation — reference-shaped per-pair negative sampling, no
fusion or batch tuning — so the ratio reads as "TPU-first design over naive
translation" measured at equal loss (batch/pool retunes are only taken at
loss parity, see bench_wordembedding).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _percentile_ms(samples):
    return float(np.percentile(np.asarray(samples) * 1e3, 50))


def bench_wordembedding(epochs: int = 3):
    import multiverso_tpu as mv
    from multiverso_tpu.apps.word_embedding import (WEConfig, WordEmbedding,
                                                    synthetic_corpus)
    from multiverso_tpu.data.dictionary import Dictionary

    tokens = synthetic_corpus(400_000, vocab=10_000, seed=7)
    # batch/negative-pool tuned on-chip: bs=16384 with a 256-wide shared
    # pool matches the bs=4096/K'=64 loss (0.498 vs 0.497 after 5 epochs)
    # at ~1.2x the throughput — bigger scatters amortize, and the larger
    # pool keeps the negative-sharing correlation at parity
    cfg = WEConfig(size=128, min_count=5, batch_size=16384, negative=5,
                   window=5, epoch=1, shared_negatives=256)
    d = Dictionary.build(tokens, cfg.min_count)
    we = WordEmbedding(cfg, d)
    ids = we.prepare_ids(tokens)
    # warmup: compile + first dispatch; 2 epochs because the donated-table
    # epoch fn compiles twice (initial device_put layout vs donated layout)
    we.train_fused(ids, epochs=2)
    stats = we.train_fused(ids, epochs=epochs)
    n_chips = max(len(mv.mesh().devices.reshape(-1)), 1)
    return stats["words_per_sec"] / n_chips, stats


def bench_array_table(size: int = 1_000_000, iters: int = 10):
    import multiverso_tpu as mv
    from multiverso_tpu.updaters import AddOption

    t = mv.ArrayTable(size, updater="sgd", name="bench_array")
    delta = np.random.default_rng(0).normal(size=size).astype(np.float32)
    opt = AddOption(learning_rate=0.01)
    t.add(delta, opt)  # compile
    t.get()
    adds, gets = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        t.add(delta, opt)
        adds.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        t.get()
        gets.append(time.perf_counter() - t0)
    # device plane: delta already resident (the real TPU deployment shape —
    # grads are produced on device; host numbers above are tunnel-bound)
    import jax

    delta_dev = jax.device_put(t.pad_delta(delta), t.sharding)
    chain = 100

    # chain the adds inside one program: per-dispatch tunnel round-trips
    # (~10s of ms here) would otherwise swamp the ~us-scale device op
    @jax.jit
    def fadd_chain(state, d):
        return jax.lax.scan(
            lambda s, _: (t.functional_add(s, d, opt), None),
            state, None, length=chain)[0]

    state = fadd_chain(t.state, delta_dev)  # compile
    jax.block_until_ready(state["data"])
    dev_adds = []
    for _ in range(iters):
        t0 = time.perf_counter()
        state = fadd_chain(state, delta_dev)
        jax.block_until_ready(state["data"])
        dev_adds.append((time.perf_counter() - t0) / chain)
    t.adopt(state)

    nbytes = size * 4
    return {
        "add_p50_ms": _percentile_ms(adds),
        "get_p50_ms": _percentile_ms(gets),
        "add_gbps": nbytes / np.percentile(adds, 50) / 1e9,
        "get_gbps": nbytes / np.percentile(gets, 50) / 1e9,
        "device_add_p50_ms": _percentile_ms(dev_adds),
        "device_add_gbps": nbytes / np.percentile(dev_adds, 50) / 1e9,
        "size_mb": nbytes / 1e6,
    }


def bench_transformer(steps: int = 10):
    """LM train-step throughput (tokens/sec) with the fused flash-attention
    kernel on TPU (reference_attention elsewhere — interpret-mode Pallas
    would measure the interpreter, not the chip)."""
    import jax
    import jax.numpy as jnp

    from multiverso_tpu.models import transformer as tfm

    on_tpu = jax.devices()[0].platform == "tpu"
    b, s = 8, 512
    cfg = tfm.TransformerConfig(
        vocab_size=8192, dim=256, num_heads=8, num_layers=4, max_seq=s,
        attn="flash" if on_tpu else "local",
        dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    params = tfm.init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (b, s + 1)).astype(np.int32)
    tok, tgt = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    step = jax.jit(tfm.make_train_step(cfg, 1e-2))
    params, loss = step(params, tok, tgt)  # compile
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, loss = step(params, tok, tgt)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return {"lm_tokens_per_sec": b * s * steps / dt,
            "lm_step_ms": dt / steps * 1e3,
            "attn": cfg.attn, "loss": float(loss)}


def main() -> None:
    import multiverso_tpu as mv

    mv.init()
    words_per_sec_chip, we_stats = bench_wordembedding()
    array_stats = bench_array_table()
    try:
        lm_stats = bench_transformer()
    except Exception as e:  # secondary metric must never sink the bench
        lm_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    mv.shutdown()

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_BASELINE.json")
    vs_baseline = 1.0
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                recorded = json.load(f).get("we_words_per_sec_per_chip", 0)
            if recorded > 0:
                vs_baseline = words_per_sec_chip / recorded
        except (ValueError, OSError):
            pass
    else:
        try:
            with open(baseline_path, "w") as f:
                json.dump({"we_words_per_sec_per_chip": words_per_sec_chip},
                          f)
        except OSError:
            pass

    print(json.dumps({
        "metric": "WordEmbedding words/sec/chip (fused skipgram-NS, "
                  "synthetic zipf corpus, dim=128, neg=5)",
        "value": round(words_per_sec_chip, 1),
        "unit": "words/s/chip",
        "vs_baseline": round(vs_baseline, 3),
        "extra": {
            "we_loss": round(we_stats["loss"], 4),
            "array_table_4M_float32": array_stats,
            "transformer_lm_bs8_seq512_d256_L4": lm_stats,
        },
    }))


if __name__ == "__main__":
    main()
