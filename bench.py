"""Benchmark harness: prints ONE JSON line for the driver.

Primary metric (BASELINE.json): WordEmbedding words/sec/chip, measured by the
fused skipgram-NS trainer on a synthetic zipf corpus (text8 stand-in; this
environment has no network egress). Secondary metrics (ArrayTable Add/Get p50
latency and bandwidth) ride along in "extra".

``vs_baseline``: the reference publishes no words/sec number
(BASELINE.json "published": {}), so the ratio is computed against a locally
recorded baseline in BENCH_BASELINE.json when present (first run writes it),
else 1.0. The recorded baseline (150,881 w/s) is this framework's first
working implementation — reference-shaped per-pair negative sampling, no
fusion or batch tuning — so the ratio reads as "TPU-first design over naive
translation" measured at equal loss (batch/pool retunes are only taken at
loss parity, see bench_wordembedding). Methodology note: the baseline was
recorded with wall-clock timing (fixed sync cost included), which
understates the naive implementation's device rate by the intercept's share
of its ~8 s run — so the slope-vs-wall-clock ratio carries at most a
few percent of methodology inflation on top of the real speedup.

Timing methodology: the tunneled chip in this environment adds a large
(~100 ms) fixed per-sync latency, and ``jax.block_until_ready`` does not
reliably gate on it — so every metric here is measured DIFFERENTIALLY: run
the workload at two repeat counts with a host readback as the sync point and
take the slope. The slope is the steady-state device time per unit of work;
the fixed intercept (tunnel round-trip + dispatch) is reported alongside in
"extra" for transparency.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np


# Exit status of a SIGTERM-truncated run that still salvaged its headline
# JSON line: 75 (BSD EX_TEMPFAIL — "try again with more budget"). 0 means
# a COMPLETE run; 1 means the salvage itself failed (no usable line).
# tools/run_bench.py keys the recorded "truncated" field off this.
TRUNCATED_EXIT = 75


def _percentile_ms(samples):
    return float(np.percentile(np.asarray(samples) * 1e3, 50))


def _dashboard_hist(max_monitors: int = 64):
    """Histogram snapshots of every timed Dashboard monitor (count, p50/
    p90/p99/max) — the telemetry-plane replacement for ad-hoc counter
    scraping in the BENCH extra. Taken BEFORE mv.shutdown() (which
    displays and resets the dashboard). Bounded so a pathological
    monitor explosion cannot bloat the record."""
    from multiverso_tpu.utils.dashboard import Dashboard
    out = {}
    for name, snap in sorted(Dashboard.snapshot().items()):
        if not snap.timed:
            continue   # pure counters carry no latency story
        if len(out) >= max_monitors:   # only when a monitor is DROPPED
            out["_truncated"] = True
            break
        out[name] = snap.brief_dict()
    return out


def _cluster_extra():
    """Compact cluster record from the stats aggregator, when one ran
    (flag ``stats_poll_interval_s`` > 0 starts it on PS rank 0): merged
    cross-rank histograms, per-shard op counts, skew, and the hot-key
    top-K — the all-ranks view ``_dashboard_hist`` (this process's local
    monitors only) cannot give a multi-process run. None when no
    aggregator ran, so single-process records are unchanged."""
    from multiverso_tpu.telemetry import aggregator
    agg = aggregator.global_aggregator()
    if agg is None:
        return None
    # fresh final poll so the record reflects run-end counters
    return aggregator.compact_record(agg.poll_once())


# degenerate two-point measurements (t_hi < t_lo: timing noise swamped the
# signal) recorded here and surfaced in the bench record's "extra" — a
# floored slope must stay visible as a bad measurement, not pass as data
_DEGENERATE_DIFFERENTIALS = []


def _differential(run, n_lo: int, n_hi: int):
    """Two-point slope timing: ``run(n)`` performs n units of work ending in
    a host readback and returns its wall seconds. Returns
    ``(sec_per_unit, intercept_s)`` — the steady-state device time per unit
    and the fixed sync/dispatch cost the slope removed. A noise-negative
    slope (t_hi < t_lo) floors at 0 and logs the raw pair to
    ``_DEGENERATE_DIFFERENTIALS`` instead of reporting a negative ms/call."""
    t_lo = run(n_lo)
    t_hi = run(n_hi)
    slope = (t_hi - t_lo) / (n_hi - n_lo)
    if slope < 0.0:
        _DEGENERATE_DIFFERENTIALS.append(
            {"n_lo": n_lo, "n_hi": n_hi,
             "t_lo_s": round(t_lo, 6), "t_hi_s": round(t_hi, 6)})
        slope = 0.0
    return slope, max(t_lo - n_lo * slope, 0.0)


def bench_wordembedding(n_lo: int = 2, n_hi: int = 10):
    import multiverso_tpu as mv
    from multiverso_tpu.apps.word_embedding import (WEConfig, WordEmbedding,
                                                    synthetic_corpus)
    from multiverso_tpu.data.dictionary import Dictionary

    tokens = synthetic_corpus(400_000, vocab=10_000, seed=7)
    # batch/negative-pool tuned on-chip: bs=16384 with a 256-wide shared
    # pool matches the bs=4096/K'=64 loss (0.498 vs 0.497 after 5 epochs)
    # at ~1.2x the throughput — bigger scatters amortize, and the larger
    # pool keeps the negative-sharing correlation at parity. (A later sweep
    # found bs=32768 ~6% faster but at a worse 5-epoch loss — rejected.)
    cfg = WEConfig(size=128, min_count=5, batch_size=16384, negative=5,
                   window=5, epoch=1, shared_negatives=256)
    d = Dictionary.build(tokens, cfg.min_count)
    we = WordEmbedding(cfg, d)
    ids = we.prepare_ids(tokens)
    # warmup: compile + first dispatch; 2 epochs because the donated-table
    # epoch fn compiles twice (initial device_put layout vs donated layout)
    we.train_fused(ids, epochs=2)
    # differential timing: slope between n_lo and n_hi epochs removes the
    # fixed tunnel/dispatch intercept (train_fused reads the loss back on
    # the host, which is the reliable sync point here)
    last = {}

    def run(n):
        last.update(we.train_fused(ids, epochs=n))
        return last["seconds"]

    sec_per_epoch, intercept = _differential(run, n_lo, n_hi)
    words_per_sec = ids.size / sec_per_epoch
    n_chips = max(len(mv.mesh().devices.reshape(-1)), 1)
    stats = {"loss": last["loss"], "sec_per_epoch": sec_per_epoch,
             "fixed_overhead_s": intercept,
             "words_per_sec": words_per_sec}
    return words_per_sec / n_chips, stats


def bench_wordembedding_ps(num_tokens: int = 120_000):
    """The PS-parity path (train_ps_blocks: pull rows / train / push
    deltas, ref distributed_wordembedding.cpp) — benchmarked alongside the
    fused path so the Add/Get plane can't silently regress. The reference's
    words/sec was inherently a number of THIS shape. Reports the r02-
    comparable 120k-token run AND a 1M-token run where the per-run fixed
    costs (final drain RTT, first-block pipeline fill) amortize out."""
    from multiverso_tpu.apps.word_embedding import (WEConfig, WordEmbedding,
                                                    synthetic_corpus)
    from multiverso_tpu.data.dictionary import Dictionary

    cfg = WEConfig(size=128, min_count=5, batch_size=8192, negative=5,
                   window=5, epoch=1, data_block_size=50_000, use_ps="1")

    def run(n_tokens, seed, best_of):
        tokens = synthetic_corpus(n_tokens, vocab=5_000, seed=seed)
        d = Dictionary.build(tokens, cfg.min_count)
        we = WordEmbedding(cfg, d)
        ids = we.prepare_ids(tokens)
        we.train_ps_blocks(ids, epochs=1)   # compile all block programs
        runs = [we.train_ps_blocks(ids, epochs=1) for _ in range(best_of)]
        # throughput: best-of-N (link-weather noise); loss/seconds: the
        # FIRST post-warmup run, so the reported loss stays at a fixed
        # epoch count across rounds regardless of N
        return {"words_per_sec": max(r["words_per_sec"] for r in runs),
                "loss": runs[0]["loss"], "seconds": runs[0]["seconds"],
                "tokens": int(ids.size)}

    # best-of-N: the tunneled link's throughput swings several-x between
    # runs ("link weather"); more samples keep one official measurement
    # from landing on a trough (each 120k run is <1 s, each 1M run ~2-3 s)
    small = run(num_tokens, 11, 6)
    large = run(1_000_000, 12, 3)
    return {"ps_words_per_sec": small["words_per_sec"],
            "loss": small["loss"], "seconds": small["seconds"],
            "tokens": small["tokens"],
            "ps_words_per_sec_1M": large["words_per_sec"],
            "loss_1M": large["loss"], "seconds_1M": large["seconds"]}


def bench_lr_real():
    """Tier-4 convergence on REAL data (BASELINE config 1): LR test
    accuracy on MNIST idx files when present, else sklearn's bundled UCI
    handwritten digits (real data; MNIST is not downloadable here —
    provenance is recorded)."""
    from multiverso_tpu.apps.logistic_regression import LogReg, LogRegConfig
    from multiverso_tpu.io import mnist

    data = mnist.load_real()
    cfg = LogRegConfig({
        "input_size": str(data["x_train"].shape[1]), "output_size": "10",
        "minibatch_size": "64", "learning_rate": "0.05",
        "train_epoch": "30", "objective_type": "softmax",
    })
    lr = LogReg(cfg)
    stats = lr.train_arrays(data["x_train"], data["y_train"])
    acc = lr.test_arrays(data["x_test"], data["y_test"])
    return {"test_accuracy": round(acc, 4),
            "train_loss": round(stats["loss"], 4),
            "n_train": int(len(data["y_train"])),
            "n_test": int(len(data["y_test"])),
            "provenance": data["provenance"]}


def bench_we_real(n_lo: int = 1, n_hi: int = 5):
    """Tier-4 WE on REAL text (BASELINE config 2): the committed
    text8-normalized real-prose shard (or an actual text8 file when
    present — io/realtext.py). Reports words/sec + loss, and a nearest-
    neighbor probe as qualitative convergence evidence."""
    from multiverso_tpu.apps.word_embedding import WEConfig, WordEmbedding
    from multiverso_tpu.data.dictionary import Dictionary
    from multiverso_tpu.io import realtext

    tokens = realtext.load_tokens()
    cfg = WEConfig(size=128, min_count=5, batch_size=16384, negative=5,
                   window=5, shared_negatives=256)
    d = Dictionary.build(tokens, cfg.min_count)
    we = WordEmbedding(cfg, d)
    ids = we.prepare_ids(tokens)
    we.train_fused(ids, epochs=2)   # warm both compile layouts
    last = {}

    def run(n):
        last.update(we.train_fused(ids, epochs=n))
        return last["seconds"]

    sec_per_epoch, _ = _differential(run, n_lo, n_hi)
    probe = next((w for w in ("array", "matrix", "value", "data")
                  if w in d.word2id), None)
    neighbors = we.nearest(probe, 6)[1:] if probe else []
    return {"words_per_sec": ids.size / sec_per_epoch,
            "loss": round(last["loss"], 4),
            "tokens": int(ids.size), "vocab": len(d),
            "neighbors_of_" + (probe or "none"): neighbors,
            "provenance": realtext.provenance()}


def _collect_worker_results(cmds, timeout: float = 240):
    """Spawn one subprocess per argv, harvest their ``RESULT {json}``
    lines; kill stragglers on the way out (a leaked sibling would skew
    later benchmarks). Raises if a worker fails or nothing reported — an
    empty measurement must not masquerade as a recorded one."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                              env=env) for cmd in cmds]
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError(
                    f"bench worker rc={p.returncode}: {p.args[-4:]}")
            for line in out.splitlines():
                if line.startswith("RESULT "):
                    results.append(json.loads(line[len("RESULT "):]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    if not results:
        raise RuntimeError("bench workers produced no RESULT line")
    return results


def _run_async_ps_world(world: int, wire: str, seconds: float,
                        native: bool = True, pattern: str = "strided"):
    """One configuration of the uncoordinated-plane bench: ``world`` real
    OS processes (CPU) pushing/pulling 1024-row batches against each
    other's shards over loopback TCP (1/world of the traffic
    short-circuits). ``native=False`` pins the pure-Python plane
    (MV_PS_NATIVE=0) for the A/B rows."""
    import sys
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    prior = os.environ.get("MV_PS_NATIVE")   # restore, don't clobber: a
    if not native:                           # user-exported value must
        os.environ["MV_PS_NATIVE"] = "0"     # survive this helper
    try:
        with tempfile.TemporaryDirectory(prefix="mv_bench_ps_") as rdv:
            results = _collect_worker_results(
                [[sys.executable,
                  os.path.join(repo, "tools", "bench_async_ps.py"),
                  rdv, str(world), str(r), str(seconds), wire, pattern]
                 for r in range(world)])
    finally:
        if prior is None:
            os.environ.pop("MV_PS_NATIVE", None)
        else:
            os.environ["MV_PS_NATIVE"] = prior
    if all("get_lat_ms" in r for r in results):
        # plane-wide percentiles from the pooled raw samples (paced mode)
        lat = np.concatenate([np.asarray(r["get_lat_ms"])
                              for r in results])
        p50 = float(np.percentile(lat, 50))
        p99 = float(np.percentile(lat, 99))
        return {
            "rows_per_sec": round(sum(r["rows_per_sec"] for r in results)),
            "msgs_per_sec": round(sum(r.get("msgs_per_sec", 0)
                                      for r in results)),
            "mb_per_sec": round(sum(r["mb_per_sec"] for r in results), 1),
            "get_p50_ms": round(p50, 2), "get_p99_ms": round(p99, 2),
            "p99_over_p50": round(p99 / max(p50, 1e-9), 2),
            "n_lat_samples": int(lat.size),
            "batch_rows": results[0]["batch_rows"],
            "dim": results[0]["dim"],
        }
    return {
        "rows_per_sec": round(sum(r["rows_per_sec"] for r in results)),
        # aggregate request rate across the plane (each op = `world`
        # messages with these strided row sets): the metric that shows
        # server throughput RISING with worker count even when rows/s —
        # which pays world messages per batch — tilts down on a 1-core
        # host
        "msgs_per_sec": round(sum(r.get("msgs_per_sec", 0)
                                  for r in results)),
        "mb_per_sec": round(sum(r["mb_per_sec"] for r in results), 1),
        "get_p50_ms": round(float(np.median(
            [r["get_p50_ms"] for r in results])), 2),
        "get_p99_ms": round(float(np.max(
            [r["get_p99_ms"] for r in results])), 2),
        "coalesce_ratio": round(float(np.mean(
            [r.get("coalesce_ratio", 1.0) for r in results])), 2),
        "batch_rows": results[0]["batch_rows"],   # worker-reported truth
        "dim": results[0]["dim"],
    }


def bench_we_async(world: int = 4, n_tokens: int = 1_000_000):
    """WordEmbedding on the UNCOORDINATED plane at np=world — the
    reference's actual product shape (N independent processes, async
    tables, ref trainer.cpp:44-49 words/sec) — so the async plane has a
    tracked perf number, not just the sync/fused paths. Same corpus/seed
    as bench_wordembedding_ps's 1M run: the losses are comparable.

    Two stages (ISSUE 11): the measured np=world run takes the pipelined
    path (producer-thread prepared-block queue + hot-row training cache);
    a parity stage then reruns a REDUCED corpus at world=1 twice —
    pipelined vs the unpipelined/uncached oracle — and asserts the
    embedding digests match BIT-FOR-BIT (single-writer runs are
    deterministic, so any divergence is a real pipeline/cache bug, the
    class the test suite's tiny corpus might miss at bench scale)."""
    import sys
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(repo, "tools", "bench_we_async.py")
    with tempfile.TemporaryDirectory(prefix="mv_bench_we_async_") as rdv:
        results = _collect_worker_results(
            [[sys.executable, worker, rdv, str(world), str(r),
              str(n_tokens), "pipeline"]
             for r in range(world)], timeout=600)
    # parity stage: world=1, reduced corpus, pipeline vs oracle
    parity_tokens = max(30_000, n_tokens // 8)
    digests = {}
    for mode in ("pipeline", "oracle"):
        with tempfile.TemporaryDirectory(
                prefix=f"mv_bench_we_parity_{mode}_") as rdv:
            digests[mode] = _collect_worker_results(
                [[sys.executable, worker, rdv, "1", "0",
                  str(parity_tokens), mode]], timeout=600)[0]["emb_sha"]
    parity_ok = digests["pipeline"] == digests["oracle"]
    assert parity_ok, (
        "ISSUE-11 parity gate: pipelined WE run is NOT bit-identical to "
        f"the unpipelined/uncached oracle at {parity_tokens} tokens "
        f"({digests['pipeline'][:16]} != {digests['oracle'][:16]})")
    out = {
        "world": world, "tokens": n_tokens,
        "words_per_sec_aggregate": round(
            sum(r["words_per_sec"] for r in results), 1),
        "words_per_sec_per_worker": [r["words_per_sec"] for r in results],
        "loss_mean": round(float(np.mean([r["loss"] for r in results])), 4),
        "loss_per_worker": [round(r["loss"], 4) for r in results],
        "parity": {"ok": parity_ok, "tokens": parity_tokens},
        "perf_gate": results[0].get("perf_gate"),
    }
    caches = [r["train_cache"] for r in results if r.get("train_cache")]
    if caches:
        hits = sum(c["hits"] for c in caches)
        misses = sum(c["misses"] for c in caches)
        out["train_cache"] = {
            "hit_rate": (round(hits / (hits + misses), 4)
                         if hits + misses else None),
            "mode": caches[0]["mode"],
            "rows_per_worker": [c["rows"] for c in caches],
        }
    # step-profiler evidence (ISSUE 9): the worker profiles its measured
    # epoch and asserts >= 90% attribution + zero steady recompiles
    # in-run; the record keeps rank 0's per-step phase breakdown as the
    # headline plus the cross-rank stall/attribution spread. bench.main
    # lifts this to extra.profile so run_bench can flag PHASE-level
    # regressions (stall growth, steady recompiles) run-over-run.
    profs = [r["profile"] for r in results if isinstance(r, dict)
             and r.get("profile")]
    if profs:
        head = dict(profs[0])
        head["stall_fraction_per_worker"] = [
            p["stall_fraction"] for p in profs]
        head["attributed_fraction_per_worker"] = [
            p["attributed_fraction"] for p in profs]
        head["stall_fraction"] = round(float(np.max(
            [p["stall_fraction"] for p in profs])), 4)
        head["steady_recompiles"] = int(sum(
            p["steady_recompiles"] for p in profs))
        out["profile"] = head
    return out


def bench_aggregate_path(world: int = 4, mb: float = 16.0):
    """MV_Aggregate path comparison at np=world (VERDICT r3 item 7): the
    device-AllReduce process_sum vs the legacy allgather+numpy-sum on the
    same payload; per-host cost of the new path is O(size), the old one
    O(world*size)."""
    import socket
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    last = None
    for _ in range(2):   # bind-then-close port pick is TOCTOU; retry once
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        try:
            out = _collect_worker_results(
                [[sys.executable,
                  os.path.join(repo, "tools", "bench_aggregate.py"),
                  str(port), str(world), str(r), str(mb)]
                 for r in range(world)], timeout=180)[0]
            out["world"], out["mb"] = world, mb
            return out
        except RuntimeError as e:
            last = e
    raise last


def bench_async_ps(seconds: float = 4.0):
    """Uncoordinated-plane scaling curve (ref dense-perf harness intent,
    Test/main.cpp:340-495): throughput + request latency at np=2/4/8,
    plus the bf16 wire variant (the SparseFilter-analogue compression)."""
    out = {"note": "real CPU processes, add+get interleaved, loopback TCP; "
                   f"host has {os.cpu_count()} cores (np8 oversubscribes); "
                   "best-of-2 per config (oversubscription noise is "
                   "~±25% single-shot). npN = strided fanout (1 op = N "
                   "messages, conflates server capacity with O(N) client "
                   "work on this host); npN_local = owner-local batches "
                   "(1 op = 1 message, isolates the servers); npN_paced = "
                   "owner-local at a FIXED total offered load with "
                   "plane-wide pooled latency percentiles"}
    for world in (2, 4, 8):
        out[f"np{world}"] = max(
            (_run_async_ps_world(world, "none", seconds) for _ in range(2)),
            key=lambda r: r["rows_per_sec"])
        # load-controlled variant: one real TCP message per op at every
        # world size (batch lives wholly in the next rank's shard), so
        # the aggregate curve measures what the SERVERS sustain — the
        # strided rows above conflate that with O(world) per-op client
        # fanout, which on this 1-core host tilts rows/s down as np grows
        out[f"np{world}_local"] = max(
            (_run_async_ps_world(world, "none", seconds, pattern="local")
             for _ in range(2)),
            key=lambda r: r["rows_per_sec"])
        # fixed-total-offered-load: the plane sustains a constant 150
        # pairs/s at every world size (flat aggregate = the monotone
        # done-bar) and the pooled latency percentiles measure SERVING
        # latency, not saturation queueing. Best-of-2 on the tail.
        out[f"np{world}_paced"] = min(
            (_run_async_ps_world(world, "none", seconds, pattern="paced")
             for _ in range(2)),
            key=lambda r: r["get_p99_ms"])
    # A/B: the same np8 load on the pure-Python plane (ps_native off) —
    # the native transport's measured margin at the worst
    # oversubscription. Same best-of-2 protocol as the native rows (an
    # asymmetric single shot would inflate the ratio by the ±25%
    # single-run noise alone).
    from multiverso_tpu.ps import native as _ps_native
    if _ps_native.available():
        out["np8_python_plane"] = max(
            (_run_async_ps_world(8, "none", seconds, native=False)
             for _ in range(2)),
            key=lambda r: r["rows_per_sec"])
    out["np2_bf16"] = _run_async_ps_world(2, "bf16", seconds)
    # r02-comparable aliases
    out["rows_per_sec_2workers"] = out["np2"]["rows_per_sec"]
    out["mb_per_sec_2workers"] = out["np2"]["mb_per_sec"]
    return out


def _run_result_worker(script: str, args, timeout: float = 300):
    """Spawn a tools/ bench worker in a subprocess (so its 2-rank PS
    world and CPU backend never touch this process's runtime) and parse
    its "RESULT <json>" line — the one worker-spawn contract shared by
    the small-add and get-rows benches."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", script),
         *[str(a) for a in args]],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=repo)
    if out.returncode != 0:
        raise RuntimeError(f"{script} rc={out.returncode}: "
                           f"{out.stderr[-300:]}")
    for line in out.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(f"{script} produced no RESULT line")


def bench_small_add_window(iters: int = 400):
    """Small-add (1-row) p50 per-call latency with the client send window
    on vs off (ISSUE 2 acceptance metric). The worker interleaves both
    arms over the same ids/values and refuses to report latency unless
    the final states match bit-for-bit."""
    return _run_result_worker("bench_small_add.py", [iters])


def bench_get_rows_plane(iters: int = 300):
    """PS read-path bench (ISSUE 5): small-get p50/p99 with the client
    get coalescer on vs off, the concurrent fan-in dedupe ratio, and a
    large get plain vs chunk-streamed. The worker refuses to report
    latency unless both parity checks held bit-for-bit."""
    return _run_result_worker("bench_get_rows.py", [iters])


def bench_dlrm_serving(seconds: float = 10.0):
    """Online-serving bench (ISSUE 8 acceptance): DLRM training writes
    and a zipf inference storm hit the same sharded embedding table —
    reads served by a bounded-staleness ReadReplica behind admission
    control. Records served QPS, p50/p99/p999 tail latency, measured
    replica staleness (asserted <= the advertised bound in-run), shed
    rate, and the sketch-estimate-vs-measured cache hit rate; the tool
    exits nonzero — failing this sub-bench — if replica parity,
    staleness, or the overload-protection contract broke."""
    return _run_result_worker("bench_serving.py", [seconds], timeout=420)


def bench_scale_curve(seconds: float = 3.0, shards: str = "1,2,4,8"):
    """Mesh scale-curve harness (ISSUE 12 instrument, ISSUE 15 plane +
    methodology — tools/bench_scale.py): the async-PS workload at
    1->2->4->8 server shards on the 8-virtual-device host platform
    (process-per-point, CONSTANT offered load at every point), with
    the ISSUE-15 mesh data plane armed (ps_fanout routing +
    super-frames, ps_spmd_stack grouped SPMD apply/gather), plus a
    quiesced model-average collective measurement per shard count.
    Records T_n, E_n = T_n/(n*T_1) computed in-run (plus the e2/e4/e8
    per-point scalars), per-shard skew, stall fraction, and the
    per-mesh-shape transfer/compile costs from telemetry/devstats.py.
    The worker exits nonzero — failing this sub-bench — if the SPMD
    compile-hygiene report is not clean for every mesh shape, if any
    point's mesh-plane result diverges bit-for-bit from its 1-shard
    classic oracle, or if the warmed measured loop recompiled in
    steady state. run_bench flags run-over-run drops of
    extra.scale.efficiency_min / e2 / e4 / t1_rows_per_s.
    The worker bounds each point's subprocess at 120 + 30*n s; this
    outer budget exceeds the 1+2+4+8 sum (~1050 s) so a wedged point
    surfaces as the worker's structured per-point error, never a
    generic worker timeout that hides which shard count hung."""
    return _run_result_worker("bench_scale.py", [seconds, shards],
                              timeout=1200)


def bench_chaos_failover(seconds: float = 16.0):
    """Chaos scenario matrix (ISSUE 7 → ISSUE 14): partition-heal,
    dup+reorder under replay, slow-shard shed, replica kill, and the
    combined shard-SIGKILL + replica-kill storm — each with in-run
    gates (exactly-once ledger vs the acked-op oracle, staleness
    bound never exceeded on a served read, recovery-to-90%) and a
    per-scenario ``recovery_s`` under ``extra.chaos.scenarios`` that
    run_bench trend-tracks. The tool exits nonzero — failing this
    sub-bench — when any scenario's gate fails."""
    return _run_result_worker("bench_chaos.py", [seconds], timeout=900)


def bench_array_table_nontunnel(size: int = 1_000_000, iters: int = 10):
    """The BASELINE ArrayTable metric WITHOUT the tunneled device link:
    same code on the in-process CPU backend (subprocess so the parent's
    TPU backend is untouched). Turns HOSTPLANE.md's 'sub-ms off the
    tunnel' extrapolation into a measurement (VERDICT r2 item 9)."""
    import json as _json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import json, bench\n"
        "import multiverso_tpu as mv\n"
        "mv.init()\n"
        f"r = bench.bench_array_table(size={size}, iters={iters})\n"
        "print('RESULT ' + json.dumps(bench._sanitize(r)))\n")
    out = subprocess.run([sys.executable, "-c", code], cwd=repo, env=env,
                         capture_output=True, text=True, timeout=300)
    if out.returncode != 0:
        raise RuntimeError(f"cpu array bench rc={out.returncode}: "
                           f"{out.stderr[-300:]}")
    for line in out.stdout.splitlines():
        if line.startswith("RESULT "):
            r = _json.loads(line[len("RESULT "):])
            r["note"] = "CPU backend, no tunnel: the same host-plane code"
            return r
    raise RuntimeError("cpu array bench produced no RESULT line")


def bench_host_wire():
    """Measure the host<->device wire itself (BASELINE breakdown evidence):
    per-dispatch round-trip (RTT) and upload bandwidth via a two-size
    differential — every host-plane p50 decomposes against these."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a: a + 1)
    x = jnp.zeros(())
    float(f(x))

    def rtt_once():
        t0 = time.perf_counter()
        float(f(x))
        return time.perf_counter() - t0

    rtts = [rtt_once() for _ in range(12)]

    def upload(nfloats):
        h = np.ones(nfloats, np.float32)
        jax.device_put(h).block_until_ready()
        t0 = time.perf_counter()
        jax.device_put(h).block_until_ready()
        return time.perf_counter() - t0

    t_small = np.median([upload(1 << 20) for _ in range(4)])
    t_big = np.median([upload(1 << 23) for _ in range(4)])
    bw = ((1 << 23) - (1 << 20)) * 4 / max(t_big - t_small, 1e-9)
    return {"rtt_ms": _percentile_ms(rtts),
            "upload_gbps": bw / 1e9,
            "upload_4mb_ms": t_small * 1e3,
            "upload_32mb_ms": t_big * 1e3}


def bench_array_table(size: int = 1_000_000, iters: int = 10):
    import multiverso_tpu as mv
    from multiverso_tpu.updaters import AddOption

    t = mv.ArrayTable(size, updater="sgd", name="bench_array")
    delta = np.random.default_rng(0).normal(size=size).astype(np.float32)
    opt = AddOption(learning_rate=0.01)
    t.add(delta, opt)  # compile
    t.get()
    adds, gets = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        t.add(delta, opt)
        adds.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        t.get()
        gets.append(time.perf_counter() - t0)

    # pipelined plane: the app-realistic shape — N in-flight async adds,
    # one wait (ref LR pipeline AddAsync; amortizes the dispatch RTT, so
    # the steady rate is wire-bandwidth-bound, not latency-bound)
    def pipelined(n):
        mids = [t.add_async(delta, opt) for _ in range(n)]
        t.wait(mids[-1])
        return None

    pipelined(4)
    pipe = []
    for _ in range(4):
        t0 = time.perf_counter()
        pipelined(8)
        pipe.append((time.perf_counter() - t0) / 8)

    # wire-compressed plane (ref quantization_util.h filters on the MPI
    # wire; here the tunnel/PCIe wire): bf16 halves the payload, 1bit
    # sends sign bits + block scales with error feedback. Measured
    # INTERLEAVED with a plain table so tunnel-load drift between runs
    # cannot masquerade as a filter effect — compare the *_vs_plain ratios.
    wire_modes = ("bf16", "1bit", "topk")
    tables = {"plain": t}
    for mode in wire_modes:
        tables[mode] = mv.ArrayTable(size, updater="sgd",
                                     name=f"bench_array_{mode}",
                                     wire_filter=mode)
        tables[mode].add(delta, opt)   # compile
        tables[mode].get()
    samples = {k: {"add": [], "get": []} for k in tables}
    for _ in range(max(iters // 2, 5)):
        for k, tw in tables.items():   # back-to-back: shared conditions
            t0 = time.perf_counter()
            tw.add(delta, opt)
            samples[k]["add"].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            tw.get()
            samples[k]["get"].append(time.perf_counter() - t0)
    plain_add = _percentile_ms(samples["plain"]["add"])
    plain_get = _percentile_ms(samples["plain"]["get"])
    wf = {"plain_interleaved": {"add_p50_ms": plain_add,
                                "get_p50_ms": plain_get}}
    from multiverso_tpu.ops import wire_codec
    add_wire_bytes = {"bf16": 2 * size,
                      "1bit": wire_codec.onebit_compressed_nbytes(size),
                      "topk": wire_codec.topk_compressed_nbytes(
                          wire_codec.default_topk(size))}
    for mode in wire_modes:
        am = _percentile_ms(samples[mode]["add"])
        gm = _percentile_ms(samples[mode]["get"])
        wf[mode] = {"add_p50_ms": am, "get_p50_ms": gm,
                    "add_vs_plain": round(plain_add / am, 3),
                    "get_vs_plain": round(plain_get / gm, 3),
                    "add_payload_bytes": add_wire_bytes[mode],
                    "add_payload_vs_f32": round(4 * size
                                                / add_wire_bytes[mode], 1)}

    # version-cached repeat get (flag table_get_cache): no intervening
    # add, so the snapshot dispatch + device->host transfer are skipped
    # entirely — a hit costs one host memcpy
    from multiverso_tpu.utils.dashboard import Dashboard
    cache_mon = Dashboard.get("table[bench_array].get.cached")
    hits_before = cache_mon.count
    t.get()   # prime the cache at the current version
    rep = []
    for _ in range(iters):
        t0 = time.perf_counter()
        t.get()
        rep.append(time.perf_counter() - t0)
    get_cached_ms = _percentile_ms(rep)
    get_cache_hits = cache_mon.count - hits_before
    # in-run bit-parity of the read path (ISSUE 5 acceptance): whatever
    # served the gets above — blocking transfer, version cache, or the
    # write-triggered snapshot prefetch — the returned bytes must equal
    # the live table's exactly. A latency number without this is
    # meaningless, so parity failure FAILS the bench.
    host_now = t.get()
    raw_now = np.asarray(t.raw())[: size].reshape(host_now.shape)
    if not np.array_equal(host_now, raw_now):
        raise AssertionError(
            "bench_array get parity broke: the read path returned "
            "different bytes than the live device table")
    get_prefetch_hits = Dashboard.get(
        "table[bench_array].get.prefetched").count
    # device plane: delta already resident (the real TPU deployment shape —
    # grads are produced on device; host numbers above are tunnel-bound)
    import jax

    delta_dev = jax.device_put(t.pad_delta(delta), t.sharding)
    # long chain: the per-add time is ~us-scale, so the slope base must be
    # large enough that ~10 ms of sync jitter cannot swamp it
    chain = 1000

    # chain the adds inside one program: per-dispatch tunnel round-trips
    # (~10s of ms here) would otherwise swamp the ~us-scale device op
    @jax.jit
    def fadd_chain(state, d):
        return jax.lax.scan(
            lambda s, _: (t.functional_add(s, d, opt), None),
            state, None, length=chain)[0]

    state = fadd_chain(t.state, delta_dev)  # compile
    float(state["data"][0])
    box = {"state": state}

    def run(n):
        t0 = time.perf_counter()
        for _ in range(n):
            box["state"] = fadd_chain(box["state"], delta_dev)
        float(box["state"]["data"][0])  # host readback = reliable sync
        return time.perf_counter() - t0

    # differential over chained runs: slope removes the fixed sync cost
    # (wide 4->32 spread: the signal must dominate ~100 ms sync jitter)
    per_chain, dev_intercept = _differential(run, 4, 32)
    dev_add_s = per_chain / chain
    t.adopt(box["state"])

    nbytes = size * 4
    return {
        "add_p50_ms": _percentile_ms(adds),
        "get_p50_ms": _percentile_ms(gets),
        "add_gbps": nbytes / np.percentile(adds, 50) / 1e9,
        "get_gbps": nbytes / np.percentile(gets, 50) / 1e9,
        "pipelined_add_ms": _percentile_ms(pipe),
        "pipelined_add_gbps": nbytes / np.percentile(pipe, 50) / 1e9,
        "wire_filtered": wf,
        "get_repeat_cached_ms": get_cached_ms,
        "get_cache_hits": int(get_cache_hits),
        "get_prefetch_hits": int(get_prefetch_hits),
        "get_parity_bit_for_bit": True,   # asserted above, else raise
        "device_add_ms": dev_add_s * 1e3,
        "device_add_gbps": nbytes / dev_add_s / 1e9,
        "fixed_overhead_ms": dev_intercept * 1e3,
        "size_mb": nbytes / 1e6,
    }


def bench_transformer(steps: int = 40, b: int = 8, s: int = 512,
                      dim: int = 256, layers: int = 4, vocab: int = 8192,
                      heads: int = 8, repeats: int = 1,
                      attn: Optional[str] = None):
    """LM train-step throughput (tokens/sec) with the fused flash-attention
    kernel on TPU (reference_attention elsewhere — interpret-mode Pallas
    would measure the interpreter, not the chip). ``repeats`` re-runs the
    differential measurement on the SAME compiled step and records the
    best slope: the tunnel's effective FLOP rate drifts ±15% between runs,
    and a single-sample record landing in a trough once cost the round its
    ≥125 TFLOP/s bar (r4: recorded 119.99, median weather 126-133)."""
    import jax
    import jax.numpy as jnp

    from multiverso_tpu.models import transformer as tfm

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = tfm.TransformerConfig(
        vocab_size=vocab, dim=dim, num_heads=heads, num_layers=layers,
        max_seq=s, attn=attn or ("flash" if on_tpu else "local"),
        dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    params = tfm.init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (b, s + 1)).astype(np.int32)
    tok, tgt = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    # donate params: the step's output params alias the input buffers, so
    # XLA updates in place instead of allocating+copying 0.94 GB of bf16
    # weights per step (interleaved A/B: ~0.6 ms/step on the chip; safe
    # here because the loop rebinds `params` every call)
    step = jax.jit(tfm.make_train_step(cfg, 1e-2), donate_argnums=(0,))
    params, loss = step(params, tok, tgt)  # compile
    float(loss)

    last = {}

    def run(n):
        nonlocal params
        t0 = time.perf_counter()
        for _ in range(n):
            params, loss = step(params, tok, tgt)
        last["loss"] = float(loss)  # host readback = reliable sync
        return time.perf_counter() - t0

    # fwd+bwd FLOPs ~ 6 * params * tokens (dense matmul count), the
    # standard LM accounting; reported so MFU vs the chip's peak is one
    # division away, and used for the plausibility floor below
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    flops_per_step = 6.0 * n_params * b * s
    samples = [_differential(run, max(steps // 4, 1), steps)
               for _ in range(max(repeats, 1))]
    # best slope = least-congested sample; a congestion spike landing on
    # an n_lo run can push a sample's slope to ~0, negative, OR merely
    # implausibly small — min() would record a physically impossible
    # peak. Keep only samples whose implied rate is under a generous
    # chip-peak ceiling (250 TFLOP/s >> the ~197 bf16 peak); fall back
    # to the median sample only if every one is corrupt.
    floor_s = flops_per_step / 250e12
    valid = [x for x in samples if x[0] > floor_s]
    step_s, intercept = (min(valid) if valid
                         else sorted(samples)[len(samples) // 2])
    tflops = flops_per_step / step_s / 1e12
    out = {"lm_tokens_per_sec": b * s / step_s,
           "lm_step_ms": step_s * 1e3,
           "lm_tflops_per_sec": tflops,
           "fixed_overhead_ms": intercept * 1e3,
           "attn": cfg.attn, "loss": last["loss"]}
    if repeats > 1:
        out["best_of"] = repeats
        out["all_tflops"] = [
            round(flops_per_step / ss / 1e12, 2) if ss > 0 else None
            for ss, _ in samples]
    return out


def bench_matrix_rows(rows: int = 100_000, cols: int = 128,
                      batch: int = 4096):
    """Sparse row push (the PS differentiator: WE pushes only the block's
    rows, ref Test/main.cpp TestSparsePerf) — device-plane row-batch add
    through the updater, differential-timed like everything else."""
    import jax

    import multiverso_tpu as mv
    from multiverso_tpu.updaters import AddOption

    t = mv.MatrixTable(rows, cols, updater="adagrad", name="bench_rows")
    rng = np.random.default_rng(0)
    ids = jax.device_put(rng.integers(0, rows, batch).astype(np.int32))
    vals = jax.device_put(rng.normal(size=(batch, cols)).astype(np.float32))
    opt = AddOption(learning_rate=0.05, rho=0.1)
    chain = 200

    @jax.jit
    def chain_add(state, ids, vals):
        return jax.lax.scan(
            lambda s, _: (t.functional_add_rows(s, ids, vals, opt), None),
            state, None, length=chain)[0]

    box = {"state": chain_add(t.state, ids, vals)}
    float(box["state"]["data"][0, 0])

    def run(n):
        t0 = time.perf_counter()
        for _ in range(n):
            box["state"] = chain_add(box["state"], ids, vals)
        float(box["state"]["data"][0, 0])
        return time.perf_counter() - t0

    per_chain, _ = _differential(run, 2, 8)
    per_add = per_chain / chain
    t.adopt(box["state"])
    nbytes = batch * cols * 4
    return {"row_add_us": per_add * 1e6,
            "rows_per_sec": batch / per_add,
            "row_add_gbps": nbytes / per_add / 1e9,
            "batch_rows": batch, "table": f"{rows}x{cols}"}


def bench_decode(new_tokens: int = 128, b: int = 8):
    """Autoregressive decode throughput (tokens/sec) on the KV-cache scan,
    f32 weights vs weight-only int8 (ops/quantization.py) — the decode
    surface (prefill, cache, sampling) has its own perf profile distinct
    from training."""
    import jax
    import jax.numpy as jnp

    from multiverso_tpu.models import transformer as tfm
    from multiverso_tpu.ops.quantization import quantize_lm_params

    s = 64 + new_tokens
    cfg = tfm.TransformerConfig(vocab_size=8192, dim=256, num_heads=8,
                                num_layers=4, max_seq=s, attn="local")
    params = tfm.init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size,
                                      (b, 64)).astype(np.int32))
    out = {}
    for label, p in (("f32", params), ("int8", quantize_lm_params(params))):
        # jit the whole decode (the serving shape); a bare generate call
        # would re-trace its scan every invocation
        gen = jax.jit(lambda p, pr: tfm.generate(p, pr, cfg, new_tokens))
        gen(p, prompt)  # compile

        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                toks = gen(p, prompt)
            np.asarray(toks[0, -1:])  # host readback = reliable sync
            return time.perf_counter() - t0

        run(2)  # settle: secondary compiles / queue state
        per_call, _ = _differential(run, 4, 40)
        out[f"decode_tok_per_sec_{label}"] = b * new_tokens / per_call
        out[f"decode_ms_per_step_{label}"] = per_call / new_tokens * 1e3
    return out


def bench_resnet(depth: int = 32, n_images: int = 50_000):
    """CIFAR ResNet sec/epoch — the reference's published headline
    (binding BENCHMARK.md tables: Lasagne ResNet-32 100.02 s/epoch on a
    GTX TITAN X; Torch 20.366 s/epoch; see BASELINE.md). Synthetic CIFAR
    (no egress), same 50k-image epoch, batch 128, data-parallel trainer
    with all params in one Adam ArrayTable."""
    import jax.numpy as jnp

    from multiverso_tpu.apps.resnet_cifar import ResNetTrainer
    from multiverso_tpu.models import resnet as resnet_lib

    trainer = ResNetTrainer(depth=depth, batch_size=128)
    x, y = resnet_lib.synthetic_cifar(n_images, seed=1)
    # upload the dataset ONCE (the 600 MB host->device transfer would
    # otherwise dominate every timed call over the tunnel)
    x, y = jnp.asarray(x), jnp.asarray(y)
    # warm twice: the epoch fn can compile a second time when the adopted
    # (donated) buffer layout differs from the first device_put
    trainer.train(x, y, epochs=1)
    trainer.train(x, y, epochs=1)
    sec_per_epoch, intercept = _differential(
        lambda n: trainer.train(x, y, epochs=n)["seconds"], 1, 9)
    # the trainer drops the 50k % 128 remainder; count what actually ran,
    # and scale the reference comparison to a full-50k-image epoch
    n_eff = (n_images // 128) * 128
    sec_50k = sec_per_epoch * n_images / n_eff
    return {"sec_per_epoch": sec_per_epoch,
            "images_per_sec": n_eff / sec_per_epoch,
            "images_per_epoch": n_eff, "depth": depth,
            "fixed_overhead_s": intercept,
            "vs_ref_theano_titanx": 100.02 / sec_50k,
            "vs_ref_torch_titanx": 20.366 / sec_50k}


def _flightrec_salvage_dump(signum) -> "Optional[str]":
    """Flight-recorder half of the SIGTERM salvage (separate function so
    tests exercise it without a live signal): record the signal and dump
    the black box — a truncated run must leave its tape, not just its
    headline. Returns the dump path (None when no dump directory
    resolves or the recorder is unavailable)."""
    try:
        from multiverso_tpu.telemetry import flightrec
        flightrec.record(flightrec.EV_SIGNAL,
                         note=f"bench salvage: signal {signum}")
        return flightrec.dump_global(f"bench salvage: signal {signum}",
                                     stacks=True)
    except BaseException:   # noqa: BLE001 — salvage must keep going
        return None


def main() -> None:
    import signal

    import multiverso_tpu as mv

    mv.init()
    words_per_sec_chip, we_stats = bench_wordembedding()

    # Salvage path: if a driver-side timeout SIGTERMs the run after the
    # headline measurement but before the final print, emit the headline
    # (with whatever vs_baseline the baseline file gives) instead of
    # dying silently — a truncated run must not erase the record. The
    # normal path still prints exactly one JSON line (this handler never
    # fires then). The salvage exits TRUNCATED_EXIT (not 0): a truncated
    # run with a usable headline must stay distinguishable from a
    # complete one (tools/run_bench.py records the distinction).
    def _salvage(signum, frame):
        ok = False
        _flightrec_salvage_dump(signum)   # black box first: the print
        try:                              # below may be the thing that dies
            print(json.dumps(_headline(words_per_sec_chip, {
                "truncated": f"bench interrupted by signal {signum}; "
                             "secondary metrics incomplete",
            }), allow_nan=False), flush=True)
            ok = True
        except BaseException:   # noqa: BLE001 — the exit must still run
            pass                # (an exception here must not turn the
        finally:                # truncation into a silent success)
            os._exit(TRUNCATED_EXIT if ok else 1)

    signal.signal(signal.SIGTERM, _salvage)
    try:
        we_ps_stats = bench_wordembedding_ps()
    except Exception as e:
        we_ps_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        we_real_stats = bench_we_real()
    except Exception as e:
        we_real_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        lr_real_stats = bench_lr_real()
    except Exception as e:
        lr_real_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        wire_stats = bench_host_wire()
    except Exception as e:
        wire_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        async_ps_stats = bench_async_ps()
    except Exception as e:
        async_ps_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        we_async_stats = bench_we_async()
    except Exception as e:
        we_async_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        aggregate_np8_stats = bench_aggregate_path(world=8)
    except Exception as e:
        aggregate_np8_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        aggregate_stats = bench_aggregate_path()
    except Exception as e:
        aggregate_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    array_stats = bench_array_table()
    try:
        array_cpu_stats = bench_array_table_nontunnel()
    except Exception as e:
        array_cpu_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        lm_stats = bench_transformer()
    except Exception as e:  # secondary metric must never sink the bench
        lm_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    import jax as _jax
    if _jax.devices()[0].platform == "tpu":
        try:
            # MXU-saturating config: ~113-133 bf16 TFLOP/s on one chip
            # (wider models hit the remote-compile size limit in this
            # environment); steps=24 smooths within-run weather,
            # repeats=4 keeps the RECORDED number off a between-run
            # trough (one compile, four measurements, best slope)
            lm_large_stats = bench_transformer(steps=24, b=2, s=1024,
                                               dim=2048, layers=8,
                                               vocab=32768, heads=16,
                                               repeats=6)
        except Exception as e:
            lm_large_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
        try:
            # A/B: the same 472M step with XLA-native attention instead
            # of the Pallas flash kernel — the recorded evidence of what
            # the kernel buys end-to-end (r5 probes: ~46 vs ~61 ms/step)
            # SAME repeats as the flash arm: best-of-6 vs best-of-2
            # would bias the speedup toward whichever arm drew more
            # samples of the weather distribution
            xla_attn = bench_transformer(steps=24, b=2, s=1024, dim=2048,
                                         layers=8, vocab=32768, heads=16,
                                         repeats=6, attn="local")
            lm_attn_ab = {
                "xla_native_attn_step_ms": xla_attn["lm_step_ms"],
                "flash_step_ms": lm_large_stats.get("lm_step_ms"),
                "flash_speedup": round(
                    xla_attn["lm_step_ms"]
                    / lm_large_stats["lm_step_ms"], 3)
                if lm_large_stats.get("lm_step_ms") else None,
            }
        except Exception as e:
            lm_attn_ab = {"error": f"{type(e).__name__}: {e}"[:200]}
    else:
        lm_large_stats = {"skipped": "TPU-only config (472M params in f32 "
                                     "would take minutes/OOM on CPU)"}
        lm_attn_ab = {"skipped": "TPU-only"}
    try:
        resnet_stats = bench_resnet()
    except Exception as e:
        resnet_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        rows_stats = bench_matrix_rows()
    except Exception as e:
        rows_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        decode_stats = bench_decode()
    except Exception as e:
        decode_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        small_add_stats = bench_small_add_window()
    except Exception as e:
        small_add_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        get_rows_stats = bench_get_rows_plane()
    except Exception as e:
        get_rows_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        chaos_stats = bench_chaos_failover()
    except Exception as e:
        chaos_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        serving_stats = bench_dlrm_serving()
    except Exception as e:
        serving_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        scale_stats = bench_scale_curve()
    except Exception as e:
        scale_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    # telemetry-plane record: latency HISTOGRAMS of every monitored op
    # this process ran (shutdown resets the dashboard, so snapshot now)
    try:
        dashboard_hist = _dashboard_hist()
    except Exception as e:
        dashboard_hist = {"error": f"{type(e).__name__}: {e}"[:200]}
    # cluster view (aggregator flag-gated; None on the default
    # single-process config). When polling was live, the merged
    # cross-rank monitor histograms REPLACE the local-only
    # dashboard_hist snapshot — a multi-process run's record must
    # reflect every rank's latencies, not just rank 0's monitors.
    try:
        cluster_stats = _cluster_extra()
    except Exception as e:
        cluster_stats = {"error": f"{type(e).__name__}: {e}"[:200]}
    if isinstance(cluster_stats, dict) and cluster_stats.get("monitors"):
        dashboard_hist = dict(cluster_stats["monitors"])
        dashboard_hist["_source"] = "cluster_aggregator (all ranks merged)"
    # flight-recorder plane, snapshotted BEFORE shutdown: a non-zero
    # count here means a FAULT dumped during the run (watchdog trip,
    # peer death, fatal) — a diagnosable anomaly even when every
    # sub-bench "succeeded". The routine Zoo.stop tape lands AFTER this
    # snapshot, so it never pollutes the anomaly signal; it still shows
    # up in tools/run_bench.py's dump-file listing (whose headers name
    # each dump's reason).
    try:
        from multiverso_tpu.telemetry import flightrec
        flightrec_dumps = flightrec.dump_stats()
    except Exception as e:
        flightrec_dumps = {"error": f"{type(e).__name__}: {e}"[:200]}
    # memory plane (telemetry/memstats.py), snapshotted BEFORE shutdown
    # like the dashboard: one final ledger sample, then the run's peaks
    # — kernel-tracked VmHWM for RSS plus the sampled ledger/device
    # high-waters. run_bench.py flags >2x run-over-run growth of the
    # peak RSS / retained-frame bytes, never fails.
    try:
        from multiverso_tpu.telemetry import memstats as _memstats_mod
        memory_stats_rec = _memstats_mod.bench_extra()
    except Exception as e:
        memory_stats_rec = {"error": f"{type(e).__name__}: {e}"[:200]}
    mv.shutdown()

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_BASELINE.json")
    if not os.path.exists(baseline_path):
        try:
            with open(baseline_path, "w") as f:
                json.dump({"we_words_per_sec_per_chip": words_per_sec_chip},
                          f)
        except OSError:
            pass

    extra = {
        "we_loss": round(we_stats["loss"], 4),
        "we_sec_per_epoch": round(we_stats["sec_per_epoch"], 4),
        "we_ps_block_path": we_ps_stats,
        "we_realtext": we_real_stats,
        "lr_real_digits": lr_real_stats,
        "host_wire": wire_stats,
        "async_ps_plane": async_ps_stats,
        "we_async_np4": we_async_stats,
        "aggregate_np4_16MB": aggregate_stats,
        "aggregate_np8_16MB": aggregate_np8_stats,
        "array_table_4M_float32": array_stats,
        "array_table_cpu_nontunnel": array_cpu_stats,
        "transformer_lm_bs8_seq512_d256_L4": lm_stats,
        "transformer_lm_472M_bs2_seq1024_d2048_L8": lm_large_stats,
        "transformer_lm_472M_attn_ab": lm_attn_ab,
        "resnet32_cifar_50k": resnet_stats,
        "matrix_sparse_row_add": rows_stats,
        "lm_decode_b8_d256_L4": decode_stats,
        "small_add_send_window": small_add_stats,
        "get_rows_plane": get_rows_stats,
        "chaos": chaos_stats,
        "serving": serving_stats,
        # mesh scale curve (ISSUE 12): T_n / E_n per shard count, the
        # SPMD hygiene verdict, and the device-plane cost attribution —
        # run_bench flags efficiency_min / t1_rows_per_s drops
        "scale": scale_stats,
        "dashboard_hist": dashboard_hist,
        "flightrec_dumps": flightrec_dumps,
        "memory": memory_stats_rec,
    }
    # phase-level profile of the WE async measured epoch (step profiler,
    # ISSUE 9): first-class extra key so tools/run_bench.py can flag
    # stall-fraction growth and steady-state recompiles run-over-run
    if isinstance(we_async_stats, dict) and we_async_stats.get("profile"):
        extra["profile"] = we_async_stats["profile"]
    # ISSUE 11: the tracked WE scale metric — words/s plus the per-phase
    # breakdown, parity verdict, and cache hit rate, first-class under
    # extra.we so run_bench flags a >2x words/s DROP run-over-run (the
    # higher-is-better direction) and the scale trajectory has a number
    if isinstance(we_async_stats, dict) \
            and "words_per_sec_aggregate" in we_async_stats:
        we_extra = {
            "words_per_s": we_async_stats["words_per_sec_aggregate"],
            "parity_ok": int(bool(
                we_async_stats.get("parity", {}).get("ok"))),
        }
        tc = we_async_stats.get("train_cache")
        if tc and tc.get("hit_rate") is not None:
            we_extra["train_cache_hit_rate"] = tc["hit_rate"]
        prof_b = we_async_stats.get("profile") or {}
        if prof_b.get("phase_ms_per_step"):
            we_extra["phase_ms_per_step"] = prof_b["phase_ms_per_step"]
            we_extra["stall_fraction"] = prof_b.get("stall_fraction")
        extra["we"] = we_extra
    if cluster_stats is not None:
        extra["cluster"] = cluster_stats
    # SLO sentinel episode counts (ISSUE 19, telemetry/slo.py): lifted
    # first-class from the chaos matrix so run_bench can flag an
    # objective that fired this run but not last, by name
    if isinstance(chaos_stats, dict) \
            and isinstance(chaos_stats.get("slo"), dict):
        extra["slo"] = chaos_stats["slo"]
    if _DEGENERATE_DIFFERENTIALS:
        # floored noise-negative slopes (see _differential): the raw pairs
        # stay on the record so a degenerate measurement is visible
        extra["degenerate_differentials"] = list(_DEGENERATE_DIFFERENTIALS)
    extra = _sanitize(extra)
    # bulky sub-bench detail goes to a side file; the driver-parsed line
    # stays compact, strictly-valid JSON (r02's record lost its headline to
    # an unparseable final line), last and alone on stdout
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, "BENCH_EXTRA.json"), "w") as f:
            json.dump(extra, f, indent=1, allow_nan=False)
    except (OSError, ValueError, TypeError):
        pass
    # The salvage handler must not race the real line: restore default
    # SIGTERM handling before printing, so the complete headline is
    # always the last (and only) JSON line once it is out.
    import signal
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    print(json.dumps(_headline(words_per_sec_chip, {
        # 1M first: the per-run fixed costs amortize there, so it is
        # the headline PS-block number (the 120k row stays for
        # r02-comparability)
        "we_ps_block_words_per_sec_1M": _num(
            we_ps_stats.get("ps_words_per_sec_1M")),
        "we_ps_block_words_per_sec_120k": _num(
            we_ps_stats.get("ps_words_per_sec")),
        "detail": "BENCH_EXTRA.json",
    }), allow_nan=False))


def _num(x):
    """Round a possibly-missing/non-finite number for the headline line."""
    try:
        x = float(x)
    except (TypeError, ValueError):
        return None
    return round(x, 1) if np.isfinite(x) else None


def _headline(words_per_sec_chip, extra):
    """The driver-parsed JSON line — ONE builder shared by the normal
    path and the SIGTERM salvage path so the two can never drift."""
    vs_baseline = 1.0
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_BASELINE.json")
    try:
        with open(baseline_path) as f:
            recorded = float(
                json.load(f).get("we_words_per_sec_per_chip", 0) or 0)
        if recorded > 0:
            vs_baseline = words_per_sec_chip / recorded
    except (ValueError, TypeError, OSError):
        pass
    return {
        "metric": "WordEmbedding words/sec/chip (fused skipgram-NS, "
                  "synthetic zipf corpus, dim=128, neg=5)",
        "value": _num(words_per_sec_chip) or 0.0,
        "unit": "words/s/chip",
        "vs_baseline": round(vs_baseline, 3) if np.isfinite(vs_baseline)
        else 0.0,
        "extra": extra,
    }


def _sanitize(obj):
    """Make an arbitrary bench-stats tree strictly-JSON-serializable:
    numpy scalars -> python, non-finite floats -> strings."""
    if isinstance(obj, dict):
        return {str(k): _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return _sanitize(obj.tolist())
    if isinstance(obj, (np.floating, np.integer, np.bool_)):
        obj = obj.item()
    if isinstance(obj, float) and not np.isfinite(obj):
        return repr(obj)
    if not isinstance(obj, (str, int, float, bool, type(None))):
        return repr(obj)
    return obj


if __name__ == "__main__":
    main()
