"""ISSUE 11: the pipelined WordEmbedding training path.

Three layers under test:

* ``io/sample_reader.BlockPrepareQueue`` — the K-deep ordered producer
  queue: in-order delivery regardless of thread scheduling, depth
  bounding, ordered exception delivery.
* ``ops/row_assemble`` + ``serving/hotcache`` — bit-parity of the jitted
  gather/pad/scatter kernels with their numpy equivalents, and the
  TrainRowCache's write-through / invalidate / fill_since reconciliation
  contracts (including the device-mirror aliasing regression: the mirror
  must be a private copy, or in-place host mutations show through into
  lazily-evaluated device serves).
* ``apps/word_embedding.train_ps_blocks`` — the acceptance gate: the
  producer-thread pipelined path (with and without the hot-row training
  cache, both push disciplines) yields BIT-IDENTICAL training results to
  the inline prepare path, on both wire planes (sync collective tables
  and the uncoordinated async plane).
"""

import tempfile
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.io.sample_reader import BlockPrepareQueue
from multiverso_tpu.ops import row_assemble
from multiverso_tpu.serving.hotcache import (HotRowCache, TrainRowCache,
                                             make_train_cache,
                                             match_positions)
from multiverso_tpu.utils import config
from multiverso_tpu.utils.dashboard import Dashboard


# ---------------------------------------------------------------------- #
# BlockPrepareQueue
# ---------------------------------------------------------------------- #
class TestBlockPrepareQueue:
    def test_ordered_delivery_under_contention(self):
        rng = np.random.default_rng(0)
        delays = rng.uniform(0, 0.003, 40)

        def fn(item, i):
            time.sleep(delays[i])      # scramble completion order
            return item * item

        with BlockPrepareQueue(list(range(40)), fn, depth=6,
                               threads=4) as q:
            assert list(q) == [i * i for i in range(40)]

    def test_depth_bounds_outstanding_production(self):
        lock = threading.Lock()
        live = {"now": 0, "peak": 0}
        consumed = threading.Event()

        def fn(item, i):
            with lock:
                live["now"] += 1
                live["peak"] = max(live["peak"], live["now"])
            # block production until the consumer starts draining, so a
            # depth violation would have every producer pile in here
            consumed.wait(2.0)
            time.sleep(0.001)
            with lock:
                live["now"] -= 1
            return item

        with BlockPrepareQueue(list(range(12)), fn, depth=3,
                               threads=8) as q:
            time.sleep(0.1)            # let producers run to the bound
            consumed.set()
            out = list(q)
        assert out == list(range(12))
        # claimed-but-unconsumed is capped at depth: with the consumer
        # parked, at most `depth` productions may ever be in flight
        assert live["peak"] <= 3, live["peak"]

    def test_exception_delivered_in_order(self):
        def fn(item, i):
            if item == 3:
                raise ValueError("boom at 3")
            return item

        q = BlockPrepareQueue(list(range(8)), fn, depth=4, threads=3)
        assert [q.next() for _ in range(3)] == [0, 1, 2]
        with pytest.raises(ValueError, match="boom at 3"):
            q.next()
        # the failure closes the queue AND purges produced-ahead items:
        # later indices deterministically surface the close (never a
        # leftover payload won in a race against the producers)
        with pytest.raises(RuntimeError, match="closed"):
            q.next()
        with pytest.raises(RuntimeError, match="closed"):
            q.next()

    def test_validates_depth_and_exhaustion(self):
        with pytest.raises(ValueError):
            BlockPrepareQueue([1], lambda x, i: x, depth=0)
        with BlockPrepareQueue([], lambda x, i: x) as q:
            with pytest.raises(StopIteration):
                q.next()


# ---------------------------------------------------------------------- #
# ops/row_assemble: numpy bit-parity
# ---------------------------------------------------------------------- #
class TestRowAssemble:
    def test_pad_rows_matches_np_pad(self):
        rows = np.random.default_rng(1).normal(
            size=(13, 8)).astype(np.float32)
        got = np.asarray(row_assemble.pad_rows(rows, 16))
        want = np.pad(rows, [(0, 3), (0, 0)])
        assert np.array_equal(got, want)
        # exact-fit block: no pad program, values untouched
        assert np.array_equal(np.asarray(row_assemble.pad_rows(rows, 13)),
                              rows)
        with pytest.raises(ValueError):
            row_assemble.pad_rows(rows, 4)

    def test_gather_pad_matches_numpy(self):
        import jax.numpy as jnp
        store = np.random.default_rng(2).normal(
            size=(50, 6)).astype(np.float32)
        pos = np.array([4, 0, 49, 17])
        got = np.asarray(row_assemble.gather_pad_rows(
            jnp.asarray(store), pos, 8))
        want = np.zeros((8, 6), np.float32)
        want[:4] = store[pos]
        assert np.array_equal(got, want)
        with pytest.raises(ValueError):
            row_assemble.gather_pad_rows(jnp.asarray(store), pos, 3)

    def test_scatter_add_bit_parity_with_numpy(self):
        import jax.numpy as jnp
        store = np.random.default_rng(3).normal(
            size=(30, 5)).astype(np.float32)
        pos = np.array([2, 29, 11])
        delta = np.random.default_rng(4).normal(
            size=(3, 5)).astype(np.float32)
        got = np.asarray(row_assemble.scatter_add_rows(
            jnp.asarray(store), pos, delta))
        want = store.copy()
        want[pos] += delta           # unique pos: one IEEE add per row
        assert np.array_equal(got, want)


# ---------------------------------------------------------------------- #
# TrainRowCache semantics
# ---------------------------------------------------------------------- #
def _rows(n, d, seed=0):
    return np.random.default_rng(seed).normal(size=(n, d)) \
        .astype(np.float32)


class TestTrainRowCache:
    def test_fill_lookup_gather_capacity(self):
        c = TrainRowCache("t", 4, capacity=3)
        r = _rows(5, 4)
        assert c.fill(np.arange(5), r) == 3      # capacity-clipped
        pos, ok = c.lookup([0, 1, 2, 3, 4])
        assert int(np.count_nonzero(ok)) == 3
        buf = np.zeros((2, 4), np.float32)
        sel = np.flatnonzero(ok)[:2]
        assert c.gather_into(buf, np.arange(2), pos[sel])
        assert np.array_equal(buf, r[sel])
        # refresh-in-place always lands, even at capacity
        r2 = _rows(5, 4, seed=9)
        got = c.fill(np.arange(5), r2)
        assert got == 3 and len(c) == 3

    def test_writethrough_applies_exact_f32_adds(self):
        c = TrainRowCache("t", 4, capacity=16, writethrough=True)
        r = _rows(6, 4)
        c.fill(np.arange(6), r)
        d = _rows(3, 4, seed=1)
        c.on_push(np.array([1, 3, 5]), d)
        want = r.copy()
        want[[1, 3, 5]] += d
        buf = np.empty((6, 4), np.float32)
        pos, ok = c.lookup(np.arange(6))
        assert bool(ok.all())
        c.gather_into(buf, np.arange(6), pos)
        assert np.array_equal(buf, want)

    def test_invalidate_drops_pushed_rows(self):
        c = TrainRowCache("t", 4, capacity=16, writethrough=False)
        c.fill(np.arange(6), _rows(6, 4))
        c.on_push(np.array([0, 2]), None)
        assert len(c) == 4
        assert not c.covers([0])
        assert c.covers([1, 3, 4, 5])

    def test_fill_since_replays_pushes_after_token(self):
        # a get's reply lands AFTER a push that was dispatched behind it:
        # the fill must reconcile or it would cache pre-push state
        c = TrainRowCache("t", 4, capacity=16, writethrough=True)
        token = c.fill_token()
        reply = _rows(4, 4)                      # pre-push server state
        d = _rows(2, 4, seed=2)
        c.on_push(np.array([1, 2]), d)           # lands before the reply
        assert c.fill_since(np.arange(4), reply, token) == 4
        want = reply.copy()
        want[[1, 2]] += d                        # replayed, same f32 adds
        buf = np.empty((4, 4), np.float32)
        pos, _ = c.lookup(np.arange(4))
        c.gather_into(buf, np.arange(4), pos)
        assert np.array_equal(buf, want)

    def test_on_push_atomic_vs_concurrent_fill_since(self):
        # regression: on_push used to apply the delta and append the
        # push-log entry in TWO lock holds — a wait()-thread fill_since
        # landing between them saw _push_seq still at its token, replayed
        # nothing, and refreshed the just-pushed rows with pre-push reply
        # values, permanently losing the delta from the cached copy
        c = TrainRowCache("t", 4, capacity=16, writethrough=True)
        ids = np.array([1, 2])
        rows = _rows(2, 4)
        c.fill(ids, rows)
        token = c.fill_token()
        reply = rows.copy()                      # reply fetched at token
        entered = threading.Event()
        release = threading.Event()
        real_note = c._note_mutation

        def paused_note(pids, pvals):            # holds the push open
            entered.set()                        # between apply and log
            release.wait(5)
            real_note(pids, pvals)

        c._note_mutation = paused_note
        d = _rows(2, 4, seed=3)
        pusher = threading.Thread(target=c.on_push, args=(ids, d))
        pusher.start()
        assert entered.wait(5)
        filler = threading.Thread(
            target=c.fill_since, args=(ids, reply, token))
        filler.start()                           # must block on the lock
        time.sleep(0.05)
        release.set()
        pusher.join(5)
        filler.join(5)
        del c.__dict__["_note_mutation"]
        _, out = c.serve_full(ids)
        assert np.array_equal(out, rows + d)     # delta survived the race

    def test_memory_stats_counts_push_log(self):
        # the write-through push log retains full delta copies — the
        # PR-10 ledger gauge must report them, not just the cached rows
        c = TrainRowCache("t", 4, capacity=16, writethrough=True)
        c.fill(np.array([1, 2]), _rows(2, 4))
        assert c.memory_stats()["push_log_bytes"] == 0
        c.on_push(np.array([1, 2]), _rows(2, 4, seed=4))
        ms = c.memory_stats()
        assert ms["push_log_entries"] == 1
        assert ms["push_log_bytes"] == 2 * 8 + 2 * 4 * 4   # ids + f32 delta
        c.clear()                                # wildcard entry: ids=None
        assert c.memory_stats()["push_log_entries"] == 2

    def test_fill_since_excludes_nonreplayable_rows(self):
        c = TrainRowCache("t", 4, capacity=16, writethrough=False)
        token = c.fill_token()
        c.on_push(np.array([1, 2]), None)        # invalidate: no replay
        assert c.fill_since(np.arange(4), _rows(4, 4), token) == 2
        assert c.covers([0, 3]) and not c.covers([1])
        # wildcard mutation (clear/overwrite) poisons the whole fill
        c2 = TrainRowCache("t2", 4, capacity=16, writethrough=True)
        t2 = c2.fill_token()
        c2.clear()
        assert c2.fill_since(np.arange(4), _rows(4, 4), t2) == 0

    def test_fill_since_log_overflow_is_conservative(self):
        c = TrainRowCache("t", 4, capacity=16, writethrough=True)
        token = c.fill_token()
        for i in range(TrainRowCache._PUSH_LOG_DEPTH + 2):
            c.on_push(np.array([i % 4]), _rows(1, 4, seed=i))
        assert c.fill_since(np.arange(4), _rows(4, 4), token) == 0

    def test_refresh_gets_bounds_staleness(self):
        c = TrainRowCache("t", 4, capacity=16, writethrough=True,
                          refresh_gets=3)
        c.fill(np.arange(4), _rows(4, 4))
        c.on_get(), c.on_get()
        assert len(c) == 4
        c.on_get()                               # 3rd get: whole-cache drop
        assert len(c) == 0 and c.refreshes == 1

    def test_device_mirror_is_a_private_copy(self):
        """Aliasing regression (caught by the parity suite in the wild):
        jax's CPU backend may zero-copy-alias an aligned host buffer on
        device_put, and the cache mutates its host rows IN PLACE — a
        device block handed out before a push must keep serving pre-push
        values no matter when its lazy gather executes."""
        c = TrainRowCache("t", 8, capacity=64, writethrough=True)
        r = _rows(32, 8)
        c.fill(np.arange(32), r)
        blk = c.device_block(np.arange(16), 16)   # builds the mirror
        assert blk is not None
        d = _rows(16, 8, seed=5)
        c.on_push(np.arange(16), d)               # in-place host +=
        assert np.array_equal(np.asarray(blk)[:16], r[:16])
        # and a FRESH serve sees the push
        blk2 = c.device_block(np.arange(16), 16)
        assert np.array_equal(np.asarray(blk2)[:16], r[:16] + d)

    def test_device_block_requires_full_coverage(self):
        c = TrainRowCache("t", 4, capacity=16)
        c.fill(np.arange(4), _rows(4, 4))
        assert c.device_block([0, 1, 9], 8) is None       # 9 uncached
        assert c.device_block(np.arange(4), 2) is None    # > bucket
        # a miss block must not pay the mirror build it can never use
        # (in invalidate mode EVERY post-push block is such a miss —
        # rebuilding 32 MB per block under the lock was pure waste)
        assert c._dev is None
        blk = c.device_block([2, 0], 4)
        assert blk is not None and np.asarray(blk).shape == (4, 4)
        assert c._dev is not None                         # hit built it

    def test_dashboard_counters_ride_count(self):
        Dashboard.reset()
        c = TrainRowCache("ctr", 4, capacity=4)
        c.count(5, 2)
        assert Dashboard.get("table[ctr].get.train_cache_hit").count == 5
        assert Dashboard.get("table[ctr].get.train_cache_miss").count == 2

    def test_factory_flag_gating_and_eligibility(self):
        assert make_train_cache("t", 4, np.float32, True) is None  # off
        config.set_flag("train_cache_rows", 8)
        config.set_flag("train_cache_mode", "writethrough")
        with pytest.raises(ValueError, match="not .*eligible|eligible"):
            make_train_cache("t", 4, np.float32, writethrough_ok=False)
        config.set_flag("train_cache_mode", "auto")
        c = make_train_cache("t", 4, np.float32, writethrough_ok=False)
        assert c is not None and not c.writethrough
        config.set_flag("train_cache_mode", "bogus")
        with pytest.raises(ValueError):
            make_train_cache("t", 4, np.float32, True)

    def test_match_positions_edge_cases(self):
        pos, ok = match_positions(None, np.array([1, 2]))
        assert not ok.any()
        cids = np.array([2, 5, 9])
        pos, ok = match_positions(cids, np.array([5, 1, 9, 10]))
        assert list(ok) == [True, False, True, False]
        assert pos[0] == 1 and pos[2] == 2


# ---------------------------------------------------------------------- #
# async-plane eligibility: transports that break dispatch==FIFO ordering
# must disqualify write-through (auto degrades, it never diverges)
# ---------------------------------------------------------------------- #
class TestWritethroughEligibility:
    def test_get_window_disqualifies_writethrough(self, tmp_path):
        """The get coalescer may QUEUE a cold fetch behind an in-flight
        one, so a push can enter the conn FIFO between a get's token and
        its actual dispatch — write-through would replay that push onto
        a reply that already contains it (double-apply). 'auto' must
        degrade to invalidate on such a table."""
        from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                               PSService)
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        config.set_flag("ps_native", False)
        config.set_flag("train_cache_rows", 32)
        config.set_flag("train_cache_mode", "auto")
        ctx = PSContext(0, 1, PSService(
            0, 1, FileRendezvous(str(tmp_path / "rdv"))))
        try:
            t = AsyncMatrixTable(16, 4, name="wt_gw", get_window_ms=5.0,
                                 ctx=ctx)
            assert t._train_cache is not None
            assert not t._train_cache.writethrough
            # the cache/dispatch ordering lock exists in BOTH modes:
            # invalidate needs it too — a push logged but not yet in
            # the conn FIFO lets a racing get cache pre-push rows under
            # a current fill token, permanently stale
            assert t._tc_order is not None
            # same table minus the coalescer: write-through eligible
            t2 = AsyncMatrixTable(16, 4, name="wt_ok", ctx=ctx)
            assert t2._train_cache is not None
            assert t2._train_cache.writethrough
            assert t2._tc_order is not None
        finally:
            ctx.close()


# ---------------------------------------------------------------------- #
# table-level: invalidation on push (no stale device serves)
# ---------------------------------------------------------------------- #
class TestTableTrainCache:
    def _sync_table(self, name, mode):
        import multiverso_tpu as mv
        mv.init()
        config.set_flag("train_cache_rows", 64)
        config.set_flag("train_cache_mode", mode)
        return mv.MatrixTable(32, 4, name=name, updater="default",
                              seed=3, init_scale=0.1)

    @pytest.mark.parametrize("mode", ["invalidate", "auto"])
    def test_push_never_serves_stale_device_copy(self, mode):
        t = self._sync_table(f"tc_stale_{mode}", mode)
        ids = np.arange(8)
        before = t.get_rows(ids)                 # warms the cache
        blk = t.train_cache_device_block(ids, 8)
        assert blk is not None                   # warm: device serve
        assert np.array_equal(np.asarray(blk), before)
        delta = _rows(8, 4, seed=7)
        t.add_rows(ids, delta)
        # the next serve must reflect the push — stale device copy is
        # the exact bug the invalidate/writethrough disciplines prevent
        after = t.get_rows(ids)
        assert np.array_equal(after, before + delta)
        blk2 = t.train_cache_device_block(ids, 8)
        if blk2 is not None:                     # writethrough keeps rows
            assert np.array_equal(np.asarray(blk2), before + delta)

    def test_cached_get_bit_equals_uncached(self):
        import multiverso_tpu as mv
        mv.init()
        t0 = mv.MatrixTable(32, 4, name="tc_par_off", updater="default",
                            seed=11, init_scale=0.1)
        config.set_flag("train_cache_rows", 64)
        t1 = mv.MatrixTable(32, 4, name="tc_par_on", updater="default",
                            seed=11, init_scale=0.1)
        rng = np.random.default_rng(0)
        # deterministic id sets: later gets are SUBSETS of earlier ones,
        # so the sync plane's all-or-nothing serve is guaranteed to hit
        # (a full-hit must be exercised for the parity to be non-vacuous)
        for step, ids in enumerate([np.arange(24), np.arange(16),
                                    np.arange(8, 24), np.arange(4, 12),
                                    np.arange(20), np.arange(24)]):
            a, b = t0.get_rows(ids), t1.get_rows(ids)
            assert np.array_equal(a, b), f"step {step}"
            d = rng.normal(size=(ids.size, 4)).astype(np.float32)
            t0.add_rows(ids, d), t1.add_rows(ids, d)
        assert np.array_equal(t0.get_rows(np.arange(24)),
                              t1.get_rows(np.arange(24)))
        stats = t1.train_cache_stats()
        assert stats is not None and stats["hits"] > 0


# ---------------------------------------------------------------------- #
# fused-path pair-batch LRU (the _pair_cache satellite)
# ---------------------------------------------------------------------- #
class TestPairCacheLRU:
    def test_bounded_lru_with_ledger_gauge(self):
        import multiverso_tpu as mv
        from multiverso_tpu.apps.word_embedding import (WEConfig,
                                                        WordEmbedding,
                                                        synthetic_corpus)
        from multiverso_tpu.data.dictionary import Dictionary
        from multiverso_tpu.telemetry import memstats

        mv.init()
        config.set_flag("we_pair_cache_corpora", 2)
        tokens = synthetic_corpus(4_000, vocab=50, seed=0)
        cfg = WEConfig(size=8, min_count=1, batch_size=64, negative=2,
                       window=2, epoch=1)
        we = WordEmbedding(cfg, Dictionary.build(tokens, 1))
        corpora = [we.prepare_ids(synthetic_corpus(4_000, vocab=50,
                                                   seed=s))
                   for s in range(3)]
        for ids in corpora:
            we._device_pairs(ids)
        # bounded at 2: the oldest corpus evicted, not the whole cache
        assert len(we._pair_cache) == 2
        # alternating epochs over the RETAINED corpora never regenerate:
        # same two keys survive, just LRU-reordered (the old keep-one
        # cache rebuilt every epoch here)
        keys_before = set(we._pair_cache)
        hit1 = we._device_pairs(corpora[1])
        hit2 = we._device_pairs(corpora[2])
        assert set(we._pair_cache) == keys_before
        assert we._device_pairs(corpora[1]) is hit1
        assert we._device_pairs(corpora[2]) is hit2
        # the PR-10 ledger sees it (registered at construct time)
        g = we.pair_cache_memory_stats()
        assert g["corpora"] == 2 and g["device_bytes"] > 0
        snap = memstats.LEDGER.snapshot()["components"]
        assert any(k.startswith("we.pair_cache[") for k in snap)


# ---------------------------------------------------------------------- #
# end-to-end parity: pipelined vs inline, both wire planes
# ---------------------------------------------------------------------- #
def _we_run(plane, pipeline, cache_rows, mode="auto"):
    """One tiny deterministic WE training run; returns (per-block losses,
    final embed_in rows, final embed_out rows)."""
    import multiverso_tpu as mv
    from multiverso_tpu.apps.word_embedding import (WEConfig, WordEmbedding,
                                                    synthetic_corpus)
    from multiverso_tpu.data.dictionary import Dictionary

    if plane == "async":
        config.set_flag("ps_world", 1)
        config.set_flag("ps_rank", 0)
        config.set_flag("ps_rendezvous", tempfile.mkdtemp())
    config.set_flag("train_cache_rows", cache_rows)
    config.set_flag("train_cache_mode", mode)
    mv.init()
    cfg = WEConfig(size=8, min_count=2, batch_size=256, negative=3,
                   window=3, epoch=2, data_block_size=6_000,
                   use_ps="1", async_ps="1" if plane == "async" else "0",
                   ps_device_plane="auto" if plane == "async" else "0",
                   seed=7, pipeline=str(pipeline))
    tokens = synthetic_corpus(24_000, vocab=400, seed=3)
    we = WordEmbedding(cfg, Dictionary.build(tokens, 2))
    losses = []
    orig = we._train_prepared
    we._train_prepared = lambda p, nw: (losses.append(orig(p, nw))
                                        or losses[-1])
    stats = we.train_ps_blocks(we.prepare_ids(tokens))
    rin = we.table_in.get_rows(np.arange(we.table_in.shape[0]))
    rout = we.table_out.get_rows(np.arange(we.table_out.shape[0]))
    cache = we.table_in.train_cache_stats()
    mv.shutdown()
    assert np.isfinite(stats["loss"])
    return losses, np.array(rin), np.array(rout), cache


@pytest.mark.parametrize("plane", ["async", "sync"])
class TestPipelineParity:
    """The ISSUE-11 acceptance gate, per wire plane: every pipelined
    variant is BIT-IDENTICAL to the inline oracle — losses block by
    block and both embedding tables row for row."""

    def test_pipeline_and_cache_bit_parity(self, plane):
        oracle = _we_run(plane, pipeline=0, cache_rows=0)
        variants = {
            "pipeline": _we_run(plane, 1, 0),
            "pipeline+writethrough": _we_run(plane, 1, 4096, "auto"),
            "pipeline+invalidate": _we_run(plane, 1, 4096, "invalidate"),
        }
        for tag, got in variants.items():
            bad = [i for i, (a, b) in enumerate(zip(oracle[0], got[0]))
                   if a != b][:3]
            assert got[0] == oracle[0], (
                f"{plane}/{tag}: block losses diverge at {bad}")
            assert np.array_equal(got[1], oracle[1]), f"{plane}/{tag} in"
            assert np.array_equal(got[2], oracle[2]), f"{plane}/{tag} out"
        # the cache actually served: parity must not be vacuous
        wt = variants["pipeline+writethrough"][3]
        assert wt is not None and wt["hits"] > 0, wt
