"""Expert parallelism (parallel/moe.py) on the 8-device mesh: all_to_all
dispatch/combine vs a dense every-expert oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import multiverso_tpu as mv
from multiverso_tpu.parallel import moe


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


def _dense_oracle(x, params, top_k=1):
    """Every expert on every token, then combine the top-k with their
    gates (raw prob for k=1, renormalized for k>1)."""
    b, t, d = x.shape
    xf = x.reshape(-1, d)
    logits = xf @ params["router"]
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
    topv, topi = jax.lax.top_k(probs, top_k)
    gates = topv if top_k == 1 else topv / topv.sum(-1, keepdims=True)
    h = jax.nn.gelu(jnp.einsum("td,edh->eth", xf, params["w1"]))
    out_all = jnp.einsum("eth,ehd->etd", h, params["w2"])
    y = sum(out_all[topi[:, k], jnp.arange(xf.shape[0])]
            * gates[:, k, None] for k in range(top_k))
    return y.reshape(b, t, d).astype(x.dtype)


def _data(cfg, b=2, t=32, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(b, t, cfg.dim)).astype(np.float32))
    params = moe.init_experts(cfg, seed=1)
    return x, params


class TestMoE:
    def test_matches_dense_oracle_when_nothing_drops(self):
        mesh = Mesh(np.asarray(jax.devices()), ("ep",))
        mv.init(mesh=mesh)
        cfg = moe.MoEConfig(num_experts=8, dim=16, hidden=32,
                            capacity_factor=100.0, axis="ep")
        x, params = _data(cfg)
        expect = _dense_oracle(x, params)
        y, aux, dropped = moe.moe_layer(x, moe.shard_experts(params, cfg),
                                        cfg)
        assert float(dropped) == 0.0
        np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)
        assert float(aux) > 0.0

    def test_dp_ep_mesh(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "ep"))
        mv.init(mesh=mesh)
        cfg = moe.MoEConfig(num_experts=4, dim=8, hidden=16,
                            capacity_factor=100.0, axis="ep")
        x, params = _data(cfg, b=4, t=16)
        expect = _dense_oracle(x, params)
        y, aux, dropped = moe.moe_layer(
            x, moe.shard_experts(params, cfg), cfg, batch_axis="dp")
        assert float(dropped) == 0.0
        np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_top2_matches_dense_oracle(self):
        mesh = Mesh(np.asarray(jax.devices()), ("ep",))
        mv.init(mesh=mesh)
        cfg = moe.MoEConfig(num_experts=8, dim=16, hidden=32,
                            capacity_factor=100.0, axis="ep", top_k=2)
        x, params = _data(cfg)
        expect = _dense_oracle(x, params, top_k=2)
        y, aux, dropped = moe.moe_layer(x, moe.shard_experts(params, cfg),
                                        cfg)
        assert float(dropped) == 0.0
        np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)
        assert float(aux) > 0.0

    def test_top2_gradients_flow(self):
        mesh = Mesh(np.asarray(jax.devices()), ("ep",))
        mv.init(mesh=mesh)
        cfg = moe.MoEConfig(num_experts=8, dim=16, hidden=32,
                            capacity_factor=2.0, axis="ep", top_k=2)
        x, params = _data(cfg)
        sharded = moe.shard_experts(params, cfg)

        def loss(p, x):
            y, aux, _ = moe.moe_layer(x, p, cfg)
            return jnp.mean(y ** 2) + 0.01 * aux

        g = jax.jit(jax.grad(loss))(sharded, x)
        for leaf in jax.tree.leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_first_choices_win_capacity_race(self):
        # token 0 prefers e1 (2nd choice e0); tokens 1-3 prefer e0.
        # capacity 3 at e0: all three 1st choices must be kept and token
        # 0's 2nd choice dropped — GShard fill order, not arrival order.
        probs = jnp.asarray([[0.1, 0.9],
                             [0.9, 0.1],
                             [0.9, 0.1],
                             [0.9, 0.1]], jnp.float32)
        expert, gate, pos, keep, _ = moe._route(probs, kk=2, capacity=3)
        t = 4
        # k-major: assignments 0-3 are 1st choices, 4-7 are 2nd choices
        first, second = keep[:t], keep[t:]
        assert bool(first.all()), "a 1st choice lost to a 2nd choice"
        assert not bool(second[0]), "token 0's 2nd choice must overflow"

    def test_dropped_fraction_counts_tokens_not_assignments(self):
        # opposite 1st choices; capacity 1 per expert keeps every token's
        # 1st choice (2nd choices overflow), so no token is fully dropped
        probs = jnp.asarray([[0.9, 0.1], [0.1, 0.9]], jnp.float32)
        _, _, _, keep, _ = moe._route(probs, kk=2, capacity=1)
        token_dropped = 1.0 - keep.reshape(2, 2).any(axis=0)
        assert float(token_dropped.mean()) == 0.0
        assert float(keep.mean()) < 1.0  # yet some assignments did drop

    def test_rejects_bad_top_k(self):
        mesh = Mesh(np.asarray(jax.devices()), ("ep",))
        mv.init(mesh=mesh)
        cfg = moe.MoEConfig(num_experts=8, dim=8, hidden=8, axis="ep",
                            top_k=9)
        x, params = _data(cfg, t=32)
        with pytest.raises(ValueError, match="top_k"):
            moe.moe_layer(x, moe.shard_experts(params, cfg), cfg)

    def test_aux_replicated_over_batch_axis(self):
        # aux must be the global mean, so permuting which dp shard holds
        # which batch half must not change it
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mv.init(mesh=Mesh(devices, ("dp", "ep")))
        cfg = moe.MoEConfig(num_experts=4, dim=8, hidden=16,
                            capacity_factor=100.0, axis="ep")
        x, params = _data(cfg, b=4, t=16)
        sharded = moe.shard_experts(params, cfg)
        _, aux1, d1 = moe.moe_layer(x, sharded, cfg, batch_axis="dp")
        swapped = jnp.concatenate([x[2:], x[:2]], axis=0)
        _, aux2, d2 = moe.moe_layer(swapped, sharded, cfg, batch_axis="dp")
        np.testing.assert_allclose(float(aux1), float(aux2), rtol=1e-5)
        np.testing.assert_allclose(float(d1), float(d2), atol=1e-7)

    def test_capacity_truncation_drops_but_stays_finite(self):
        mesh = Mesh(np.asarray(jax.devices()), ("ep",))
        mv.init(mesh=mesh)
        cfg = moe.MoEConfig(num_experts=8, dim=16, hidden=32,
                            capacity_factor=0.1, axis="ep")
        x, params = _data(cfg)
        y, aux, dropped = moe.moe_layer(x, moe.shard_experts(params, cfg),
                                        cfg)
        assert 0.0 < float(dropped) <= 1.0
        assert np.isfinite(np.asarray(y)).all()

    def test_rejects_indivisible_experts(self):
        mesh = Mesh(np.asarray(jax.devices()), ("ep",))
        mv.init(mesh=mesh)
        cfg = moe.MoEConfig(num_experts=6, dim=8, hidden=8, axis="ep")
        x, params = _data(cfg, t=32)
        with pytest.raises(ValueError):
            moe.moe_layer(x, moe.shard_experts(params, cfg), cfg)

    def test_gradients_flow(self):
        mesh = Mesh(np.asarray(jax.devices()), ("ep",))
        mv.init(mesh=mesh)
        cfg = moe.MoEConfig(num_experts=8, dim=16, hidden=32,
                            capacity_factor=2.0, axis="ep")
        x, params = _data(cfg)
        sharded = moe.shard_experts(params, cfg)

        def loss(p, x):
            y, aux, _ = moe.moe_layer(x, p, cfg)
            return jnp.mean(y ** 2) + 0.01 * aux

        g = jax.jit(jax.grad(loss))(sharded, x)
        for leaf in jax.tree.leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()
        assert float(jnp.abs(g["router"]).sum()) > 0
