"""Cluster observability plane (PR 6): the Space-Saving heavy-hitter
sketch (recall/overestimate/memory properties), the stats aggregator's
exact cross-rank merge + skew + rates on a live 2-rank PS (both wire
planes — the native server punts MSG_STATS), the one-shot stats probe,
and the ``mvtop --once`` operator view. All tier-1 (CPU, seconds)."""

import json
import os
import sys
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from multiverso_tpu.telemetry import aggregator  # noqa: E402
from multiverso_tpu.telemetry import hotkeys  # noqa: E402
from multiverso_tpu.telemetry.histogram import Histogram  # noqa: E402
from multiverso_tpu.utils import config  # noqa: E402


# ---------------------------------------------------------------------- #
# Space-Saving sketch properties
# ---------------------------------------------------------------------- #
class TestSpaceSaving:
    def test_exact_below_capacity(self):
        sk = hotkeys.SpaceSaving(16)
        for k in [1, 1, 1, 2, 2, 7]:
            sk.offer(k)
        assert sk.items()[0] == (1, 3, 0)
        assert dict((k, c) for k, c, _ in sk.items()) == {1: 3, 2: 2, 7: 1}
        assert all(e == 0 for _, _, e in sk.items())
        assert sk.total == 6

    def test_zipf_topk_recall_and_bounded_memory(self):
        """ISSUE 6 acceptance: top-K recall >= 0.9 vs exact counts on a
        zipf stream, with memory bounded at capacity entries."""
        rng = np.random.default_rng(42)
        stream = rng.zipf(1.3, size=60_000)
        capacity, k = 256, 20
        sk = hotkeys.SpaceSaving(capacity)
        for v in stream.tolist():
            sk.offer(int(v))
        # bounded memory: exactly one dict entry + one heap entry per
        # tracked key, never more than capacity
        assert len(sk) <= capacity
        assert len(sk._heap) <= capacity
        keys, counts = np.unique(stream, return_counts=True)
        order = np.argsort(-counts, kind="stable")
        exact_top = set(int(keys[i]) for i in order[:k])
        sketch_top = set(key for key, _, _ in sk.top(k))
        recall = len(exact_top & sketch_top) / k
        assert recall >= 0.9, (recall, sorted(exact_top),
                               sorted(sketch_top))
        # Space-Saving guarantee: count - err <= true freq <= count
        true = {int(kk): int(c) for kk, c in zip(keys, counts)}
        for key, count, err in sk.items():
            assert count >= true.get(key, 0), (key, count)
            assert count - err <= true.get(key, 0), (key, count, err)

    def test_batch_observe_samples_big_batches(self):
        sk = hotkeys.SpaceSaving(8)
        big = np.arange(100_000, dtype=np.int64)
        t0 = time.perf_counter()
        sk.observe(big)
        assert time.perf_counter() - t0 < 0.5   # sampled, not 100k offers
        assert sk.observed == 100_000
        # sampled offers carry the STRIDE's weight: total stays on the
        # raw-traffic scale (within one stride of rounding)
        assert abs(sk.total - 100_000) <= hotkeys.BATCH_SAMPLE
        # offset turns shard-local ids into global ones
        sk2 = hotkeys.SpaceSaving(8)
        sk2.observe(np.array([0, 1, 0]), offset=100)
        assert sk2.items()[0][0] == 100

    def test_mixed_batch_sizes_rank_on_one_scale(self):
        """A key served through big sampled batches must rank against a
        key served through 1-row ops on the same count scale — inc=1
        sampling would undercount the batched key ~n/BATCH_SAMPLE x."""
        sk = hotkeys.SpaceSaving(8)
        sk.observe(np.full(50_000, 7, dtype=np.int64))   # sampled batch
        for _ in range(1000):                            # 1-row ops
            sk.offer(3)
        items = dict((k, c) for k, c, _ in sk.items())
        assert items[7] > items[3]                       # 50k >> 1k
        assert items[7] == pytest.approx(50_000, rel=0.02)

    def test_repeated_batches_rotate_sampling_phase(self):
        """A workload re-issuing the SAME big caller-ordered batch must
        not alias: an off-stride hot key is eventually sampled (fixed
        phase-0 striding would miss it forever)."""
        n = 4 * hotkeys.BATCH_SAMPLE          # stride 4
        batch = np.arange(n, dtype=np.int64)
        hot = 1                               # off phase-0 stride
        sk = hotkeys.SpaceSaving(4096)
        for _ in range(8):                    # phases cycle 1,2,3,0,...
            sk.observe(batch)
        items = dict((k, c) for k, c, _ in sk.items())
        assert hot in items, "off-stride key never sampled"
        # weighted back to the raw scale: ~2 of 8 batches sample index 1
        # at stride weight 4 -> ~8 == its true count across the repeats
        assert items[hot] == 8

    def test_merge_and_hit_rate_curve(self):
        a, b = hotkeys.SpaceSaving(8), hotkeys.SpaceSaving(8)
        for _ in range(30):
            a.offer(1)
        for _ in range(20):
            b.offer(2)
        b.offer(1)   # overlapping key: counts sum
        merged = hotkeys.merge_sketches([a.to_dict(), b.to_dict(), None])
        assert merged["items"][0] == [1, 31, 0]
        assert merged["items"][1] == [2, 20, 0]
        assert merged["total"] == 51
        curve = hotkeys.hit_rate_curve(merged)
        assert curve[0] == [1, round(31 / 51, 4)]
        assert curve[-1][1] == 1.0
        rates = [r for _, r in curve]
        assert rates == sorted(rates)   # monotone nondecreasing
        assert hotkeys.hit_rate_curve({"items": [], "total": 0}) == []

    def test_to_dict_json_safe(self):
        sk = hotkeys.SpaceSaving(4)
        sk.observe(np.array([5, 5, 9], dtype=np.int64))
        d = sk.to_dict()
        json.dumps(d)
        assert d["items"][0][:2] == [5, 2]
        assert d["capacity"] == 4 and d["observed"] == 3


# ---------------------------------------------------------------------- #
# pure merge math
# ---------------------------------------------------------------------- #
class TestMergeMath:
    def test_hist_merge_is_exact(self):
        """Merging two ranks' hist-dicts equals the histogram of the
        pooled samples — identical fixed buckets make it elementwise."""
        rng = np.random.default_rng(3)
        sa = rng.lognormal(0.0, 1.0, 400)
        sb = rng.lognormal(1.0, 0.5, 300)
        ha, hb, hu = Histogram(), Histogram(), Histogram()
        for s in sa:
            ha.observe(float(s))
        for s in sb:
            hb.observe(float(s))
        for s in np.concatenate([sa, sb]):
            hu.observe(float(s))
        merged = aggregator.merge_hist_dicts([ha.as_dict(), hb.as_dict()])
        union = hu.as_dict()
        assert merged["count"] == union["count"] == 700
        assert merged["timed"] == 700
        assert merged["buckets"] == union["buckets"]
        assert merged["p50_ms"] == union["p50_ms"]
        assert merged["p99_ms"] == union["p99_ms"]
        assert merged["max_ms"] == union["max_ms"]
        assert merged["min_ms"] == union["min_ms"]

    def test_hist_merge_keeps_incr_only_counts(self):
        d = {"count": 5, "timed": 0, "sum_ms": 0.0, "min_ms": 0.0,
             "max_ms": 0.0, "buckets": []}
        merged = aggregator.merge_hist_dicts([d, d])
        assert merged["count"] == 10 and merged["timed"] == 0
        assert merged["min_ms"] == 0.0   # no fake latency reconstructed

    def test_skew_metric(self):
        assert aggregator._skew([]) == 1.0
        assert aggregator._skew([0, 0]) == 1.0
        assert aggregator._skew([10, 10]) == 1.0
        assert aggregator._skew([30, 10]) == pytest.approx(1.5)
        assert aggregator._skew([40, 0, 0, 0]) == pytest.approx(4.0)

    def test_merge_cluster_with_dead_rank(self):
        st0 = {"rank": 0, "monitors": {}, "notes": {},
               "shards": {"t": {"kind": "row", "adds": 4, "gets": 2,
                                "applies": 4, "queue_depth": 0,
                                "get_bytes": 10, "add_bytes": 20,
                                "rows": 8}}}
        err = RuntimeError("boom")
        rec = aggregator.merge_cluster(
            {0: st0, 1: err},
            {0: {"status": "ok", "addr": "a:1"}, 1: err}, world=2)
        assert rec["polled"] == 1 and rec["world"] == 2
        assert rec["ranks"]["0"]["status"] == "ok"
        assert rec["ranks"]["1"]["status"] == "unreachable"
        assert "RuntimeError" in rec["ranks"]["1"]["error"]
        assert rec["tables"]["t"]["adds"] == 4
        json.dumps(rec)

    def test_probe_all_concurrent_and_deadline(self):
        """Probes fan out concurrently (N slow ranks cost ~one timeout,
        not N) and an overrunning probe becomes a per-rank TimeoutError
        placeholder instead of stalling the poll."""
        def probe_one(r, stats, health):
            if r == 2:
                time.sleep(30)   # wedged rank: never finishes
                return
            time.sleep(0.2)
            stats[r] = {"rank": r, "monitors": {}, "shards": {}}
            health[r] = {"status": "ok"}

        t0 = time.perf_counter()
        stats, health = aggregator.probe_all(range(3), probe_one,
                                             deadline_s=1.0)
        assert time.perf_counter() - t0 < 2.0   # concurrent + bounded
        assert stats[0]["rank"] == 0 and stats[1]["rank"] == 1
        assert isinstance(stats[2], TimeoutError)
        assert isinstance(health[2], TimeoutError)
        rec = aggregator.merge_cluster(stats, health, world=3)
        assert rec["ranks"]["2"]["status"] == "unreachable"
        assert rec["polled"] == 2

    def test_derive_rates(self):
        mk = lambda ts, adds, gets, q: {  # noqa: E731
            "kind": "cluster", "ts": ts, "tables": {"t": {
                "adds": adds, "gets": gets, "applies": adds,
                "add_bytes": adds * 100, "get_bytes": gets * 100,
                "queue_depth": q,
                "shards": {"0": {"adds": adds, "gets": 0,
                                 "applies": adds,
                                 "add_bytes": adds * 100,
                                 "get_bytes": 0, "queue_depth": q},
                           "1": {"adds": 0, "gets": gets, "applies": 0,
                                 "add_bytes": 0,
                                 "get_bytes": gets * 100,
                                 "queue_depth": 0}}}}}
        prev, cur = mk(100.0, 10, 10, 2), mk(102.0, 50, 10, 5)
        rates = aggregator.derive_rates(prev, cur)
        t = rates["t"]
        assert t["adds_per_s"] == pytest.approx(20.0)
        assert t["gets_per_s"] == 0.0
        assert t["wire_bytes_per_s"] == pytest.approx(2000.0)
        assert t["queue_depth_delta"] == 3
        # windowed skew: ALL interval traffic landed on shard 0
        assert t["skew_window"] == pytest.approx(2.0)
        assert cur["rates"] is rates
        assert aggregator.derive_rates(None, cur) is None

    def test_derive_rates_skips_recovered_shard_history(self):
        """A rank whose stats probe failed last poll and answered this
        one must sit the interval out — its whole cumulative history
        landing in one window would be a phantom rate/skew burst at
        exactly the degraded moment the plane observes."""
        prev = {"kind": "cluster", "ts": 100.0, "tables": {"t": {
            "adds": 10, "gets": 0, "applies": 10,
            "add_bytes": 1000, "get_bytes": 0, "queue_depth": 0,
            "shards": {"0": {"adds": 10, "gets": 0, "applies": 10,
                             "add_bytes": 1000, "get_bytes": 0,
                             "queue_depth": 0}}}}}   # rank 1 missing
        cur = {"kind": "cluster", "ts": 101.0, "tables": {"t": {
            "adds": 1_000_012, "gets": 0, "applies": 1_000_012,
            "add_bytes": 9_999_000, "get_bytes": 0, "queue_depth": 0,
            "shards": {
                "0": {"adds": 12, "gets": 0, "applies": 12,
                      "add_bytes": 1200, "get_bytes": 0,
                      "queue_depth": 0},
                # recovered rank: lifetime counters, no prev entry
                "1": {"adds": 1_000_000, "gets": 0,
                      "applies": 1_000_000, "add_bytes": 9_997_800,
                      "get_bytes": 0, "queue_depth": 0}}}}}
        rates = aggregator.derive_rates(prev, cur)
        t = rates["t"]
        assert t["adds_per_s"] == pytest.approx(2.0)     # shard 0 only
        assert t["wire_bytes_per_s"] == pytest.approx(200.0)
        assert t["skew_window"] == 1.0                   # one clean shard
        # a shard that errored in the PREVIOUS record is excluded too
        prev["tables"]["t"]["shards"]["1"] = {"error": "boom"}
        cur["tables"]["t"]["shards"]["1"]["adds"] = 1_000_000
        rates = aggregator.derive_rates(prev, cur)
        assert rates["t"]["adds_per_s"] == pytest.approx(2.0)


# ---------------------------------------------------------------------- #
# live 2-rank PS: poll, exact merge, skew, hot keys, probes
# ---------------------------------------------------------------------- #
def _zipf_workload(t0, num_row, hot_row, n=40):
    """Gets/adds against both shards with ``hot_row`` dominating —
    the known-head zipf stand-in (deterministic, no huge tail)."""
    rng = np.random.default_rng(7)
    for i in range(n):
        row = hot_row if i % 2 == 0 else int(rng.integers(0, num_row))
        t0.get_rows([row])
        t0.add_rows([row], np.ones((1, 4), np.float32))


class TestClusterLive:
    def test_poll_merges_exactly_and_finds_hot_rows(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        # adagrad: never natively registered, so every op serves on the
        # python plane and the sketch/byte counters are deterministic on
        # BOTH fixture parametrizations (MSG_STATS itself still punts
        # through the native server on the "native" one)
        t0 = AsyncMatrixTable(32, 4, updater="adagrad", name="cl",
                              ctx=two_ranks[0])
        AsyncMatrixTable(32, 4, updater="adagrad", name="cl",
                         ctx=two_ranks[1])
        hot = 19   # rank 1 owns [16, 32): remote-owned hot row
        _zipf_workload(t0, 32, hot)
        agg = aggregator.ClusterAggregator(two_ranks[0].service)
        rec = agg.poll_once()
        assert rec["kind"] == "cluster" and rec["polled"] == 2
        assert set(rec["ranks"]) == {"0", "1"}
        assert all(e["status"] == "ok" for e in rec["ranks"].values())
        table = rec["tables"]["cl"]
        assert set(table["shards"]) == {"0", "1"}
        # exact merge: cluster sums equal the per-rank payload sums
        st0 = two_ranks[0].service.stats_payload()["shards"]["cl"]
        st1 = two_ranks[1].service.stats_payload()["shards"]["cl"]
        for k in ("adds", "gets", "applies", "get_bytes", "add_bytes"):
            assert table[k] == st0[k] + st1[k], k
        assert table["adds"] == 40 and table["gets"] == 40
        assert table["get_bytes"] > 0 and table["add_bytes"] > 0
        # apply histogram: ps[cl].apply is a PROCESS-global monitor, so
        # both in-process ranks report the same pooled distribution —
        # the merge must count it once and agree with the applies
        # scalar beside it (summing per rank would report 2x)
        assert table["apply"]["count"] == st0["apply"]["count"]
        assert table["apply"]["count"] == table["applies"]
        # skew: the hot row drags traffic onto rank 1's shard
        assert table["skew"] > 1.1
        # cluster top-K head is the known hot row
        hk = rec["hotkeys"]["cl"]
        assert hk["top"][0][0] == hot
        assert hk["total"] == 80   # every get + add recorded once
        curve = hk["hit_rate_curve"]
        assert curve[0][0] == 1 and curve[0][1] >= 0.4
        json.dumps(rec)

    def test_rates_between_polls(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(32, 4, updater="adagrad", name="rt",
                              ctx=two_ranks[0])
        AsyncMatrixTable(32, 4, updater="adagrad", name="rt",
                         ctx=two_ranks[1])
        t0.add_rows([20], np.ones((1, 4), np.float32))
        agg = aggregator.ClusterAggregator(two_ranks[0].service)
        agg.poll_once()
        time.sleep(0.05)
        for _ in range(10):
            t0.get_rows([20])
        rec = agg.poll_once()
        r = rec["rates"]["rt"]
        assert r["gets_per_s"] > 0
        assert r["adds_per_s"] == 0.0
        assert rec["rates"]["_interval_s"] > 0
        # interval traffic was all gets on rank 1's shard
        assert r["skew_window"] == pytest.approx(2.0)
        assert len(agg.history()) == 2

    def test_stats_oneshot_probe_and_dead_rank_entry(self, two_ranks):
        from multiverso_tpu.ps import service as svc
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 2, updater="adagrad", name="os",
                              ctx=two_ranks[0])
        AsyncMatrixTable(16, 2, updater="adagrad", name="os",
                         ctx=two_ranks[1])
        t0.add_rows([9], np.ones((1, 2), np.float32))
        # one-shot MSG_STATS probe (never the shared data conn)
        st = two_ranks[0].service.stats_oneshot(1)
        assert st["rank"] == 1 and "os" in st["shards"]
        # local short-circuit
        assert two_ranks[0].service.stats_oneshot(0)["rank"] == 0
        # a dead rank becomes a per-rank error entry, not a failed poll
        config.set_flag("ps_connect_timeout", 2.0)
        two_ranks[1].service.close()
        agg = aggregator.ClusterAggregator(two_ranks[0].service)
        rec = agg.poll_once(timeout=2.0)
        assert rec["ranks"]["0"]["status"] == "ok"
        assert rec["ranks"]["1"]["status"] == "unreachable"
        assert rec["polled"] == 1
        assert "os" in rec["tables"]   # rank 0's shard still reported

    def test_writes_jsonl_and_prom(self, two_ranks, tmp_path):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 2, updater="adagrad", name="wf",
                              ctx=two_ranks[0])
        AsyncMatrixTable(16, 2, updater="adagrad", name="wf",
                         ctx=two_ranks[1])
        t0.add_rows([9], np.ones((1, 2), np.float32))
        agg = aggregator.ClusterAggregator(
            two_ranks[0].service, directory=str(tmp_path))
        agg.poll_once()
        agg.poll_once()
        sys.path.insert(0, _REPO)
        from tools.dump_metrics import load_records
        recs = load_records(str(tmp_path / "cluster.jsonl"))
        assert len(recs) == 2
        assert recs[1]["kind"] == "cluster"
        assert "rates" in recs[1]   # second record chains off the first
        prom = (tmp_path / "cluster.prom").read_text()
        assert 'rank="cluster"' in prom
        assert 'mv_shard_skew{table="wf",rank="cluster"}' in prom

    def test_flag_gated_lifecycle(self, two_ranks):
        """ensure_started gates on the flag + controller rank; close
        stops an aggregator bound to the closing service."""
        assert aggregator.ensure_started(two_ranks[0].service) is None
        config.set_flag("stats_poll_interval_s", 30.0)
        assert aggregator.ensure_started(two_ranks[1].service) is None
        agg = aggregator.ensure_started(two_ranks[0].service)
        assert agg is not None
        assert aggregator.ensure_started(two_ranks[0].service) is agg
        assert aggregator.global_aggregator() is agg
        two_ranks[0].service.close()
        assert aggregator.global_aggregator() is None
        # the final flush left a record
        assert len(agg.history()) >= 1


# ---------------------------------------------------------------------- #
# mvtop
# ---------------------------------------------------------------------- #
class TestMvtop:
    def test_once_smoke(self, two_ranks, tmp_path, capsys):
        """ISSUE 6 acceptance: on a 2-rank zipf get_rows workload,
        ``mvtop --once`` shows both ranks' health, merged percentiles,
        per-shard skew, and a cluster top-K headed by the hot row."""
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        from tools import mvtop
        t0 = AsyncMatrixTable(32, 4, updater="adagrad", name="mt",
                              ctx=two_ranks[0])
        AsyncMatrixTable(32, 4, updater="adagrad", name="mt",
                         ctx=two_ranks[1])
        hot = 21
        _zipf_workload(t0, 32, hot)
        rdv_dir = str(tmp_path / "rdv")   # the two_ranks rendezvous dir
        rc = mvtop.main(["--rdv", rdv_dir, "--once"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "ranks 2/2 up" in out
        lines = out.splitlines()
        assert any(line.startswith("0") and " ok " in line
                   for line in lines)
        assert any(line.startswith("1") and " ok " in line
                   for line in lines)
        assert "table[mt]" in out and "skew=" in out
        assert "p50" in out and "p99" in out
        assert f"hot rows" in out and f"{hot}:" in out
        # the hot row leads the rendered top-K
        hotline = next(line for line in lines if "hot rows" in line)
        assert hotline.split(": ", 1)[1].split(":")[0] == str(hot)
        assert "cache-hit-if-cached" in out

    def test_once_json(self, two_ranks, tmp_path, capsys):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        from tools import mvtop
        t0 = AsyncMatrixTable(16, 2, updater="adagrad", name="mj",
                              ctx=two_ranks[0])
        AsyncMatrixTable(16, 2, updater="adagrad", name="mj",
                         ctx=two_ranks[1])
        t0.add_rows([9], np.ones((1, 2), np.float32))
        rc = mvtop.main(["--rdv", str(tmp_path / "rdv"), "--once",
                         "--json"])
        rec = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert rec["kind"] == "cluster" and rec["polled"] == 2

    def test_read_addrs_and_empty_dir(self, tmp_path):
        from tools import mvtop
        d = tmp_path / "rdv"
        assert mvtop.read_addrs(str(d)) == {}
        d.mkdir()
        (d / "0.addr").write_text("127.0.0.1:1234")
        (d / "1.addr").write_text("127.0.0.1:1235")
        (d / ".0.addr.tmp").write_text("x")
        (d / "ps_quiesce.0").write_text("x")
        assert mvtop.read_addrs(str(d)) == {0: "127.0.0.1:1234",
                                            1: "127.0.0.1:1235"}
        assert mvtop.read_addrs(str(d), world=1) == {0: "127.0.0.1:1234"}

    def test_render_unreachable_rank(self):
        from tools import mvtop
        rec = aggregator.merge_cluster(
            {0: RuntimeError("refused")}, {0: RuntimeError("refused")},
            world=1)
        out = mvtop.render(rec)
        assert "unreachable" in out and "ranks 0/1 up" in out


# ---------------------------------------------------------------------- #
# dump_metrics: cluster records
# ---------------------------------------------------------------------- #
class TestDumpMetricsCluster:
    def _rec(self, ts, adds, skew, rate=None):
        rec = {"kind": "cluster", "ts": ts, "world": 2, "polled": 2,
               "ranks": {"0": {"status": "ok"}, "1": {"status": "ok"}},
               "monitors": {"m.op": {"count": adds, "sum_ms": 1.0,
                                     "timed": adds, "p50_ms": 0.5,
                                     "p90_ms": 0.8, "p99_ms": 0.9,
                                     "max_ms": 1.0, "min_ms": 0.1,
                                     "buckets": []}},
               "tables": {"t": {"shards": {"0": {}, "1": {}},
                                "adds": adds, "gets": adds * 2,
                                "applies": adds, "queue_depth": 0,
                                "rows": 8, "get_bytes": 1, "add_bytes": 1,
                                "apply": {"count": adds, "p50_ms": 0.1,
                                          "p99_ms": 0.2, "max_ms": 0.3},
                                "skew": skew}},
               "hotkeys": {"t": {"total": 10,
                                 "top": [[5, 6, 0], [1, 4, 0]],
                                 "hit_rate_curve": [[1, 0.6], [2, 1.0]]}}}
        if rate is not None:
            rec["rates"] = {"_interval_s": 1.0,
                            "t": {"adds_per_s": rate, "gets_per_s": 0.0,
                                  "applies_per_s": rate,
                                  "wire_bytes_per_s": 0.0,
                                  "queue_depth_delta": 0,
                                  "skew_window": skew}}
        return rec

    def test_show_cluster(self):
        from tools.dump_metrics import format_record
        out = format_record(self._rec(100.0, 4, 1.5, rate=4.0))
        assert "cluster" in out and "rank 0:" in out and "rank 1:" in out
        assert "table[t]:" in out and "skew=1.5" in out
        assert "rates:" in out and "adds_per_s=4.0" in out
        assert "hot[t]" in out and "5:6" in out
        assert "cache-hit-if-cached" in out
        assert "m.op" in out   # merged monitor table rides along

    def test_diff_cluster_prints_rate_and_skew_deltas(self):
        from tools.dump_metrics import diff_records
        a = self._rec(100.0, 4, 1.2, rate=4.0)
        b = self._rec(200.0, 40, 3.0, rate=40.0)
        out = diff_records(a, b)
        assert "skew b/a" in out
        assert "2.50" in out            # 3.0 / 1.2
        assert "adds_per_s: 4.0 -> 40.0" in out
        # monitor comparison still present
        assert "m.op" in out

    def test_show_per_rank_record_with_hotkeys(self):
        """Per-rank records grew a hotkeys blob; show must render its
        head, not dump the raw dict into the shard line."""
        from tools.dump_metrics import format_record
        rec = {"rank": 0, "ts": 1.0, "monitors": {},
               "shards": {"t": {"kind": "row", "adds": 3,
                                "hotkeys": {"capacity": 4, "total": 3,
                                            "observed": 3,
                                            "items": [[7, 3, 0]]}}}}
        out = format_record(rec)
        assert "hot rows (of 3): 7:3" in out
        assert "hotkeys=" not in out


# ---------------------------------------------------------------------- #
# shard stats growth
# ---------------------------------------------------------------------- #
class TestShardStatsGrowth:
    def test_row_shard_hotkeys_and_bytes(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 4, updater="adagrad", name="sg",
                              ctx=two_ranks[0])
        AsyncMatrixTable(16, 4, updater="adagrad", name="sg",
                         ctx=two_ranks[1])
        for _ in range(3):
            t0.get_rows([9])                       # remote-owned
        t0.add_rows([9], np.ones((1, 4), np.float32))
        sh = t0.server_stats(1)["shards"]["sg"]
        assert sh["get_bytes"] == 3 * 4 * 4        # 3 gets x 4 cols f32
        assert sh["add_bytes"] == 4 * 4
        hk = sh["hotkeys"]
        assert hk["items"][0][0] == 9              # GLOBAL row id
        assert hk["items"][0][1] == 4              # 3 gets + 1 add
        assert hk["capacity"] == config.get_flag("hotkeys_capacity")

    def test_byte_counters_use_encoded_wire_size(self, two_ranks):
        """wire='bf16' tables ship/receive 2-byte payloads: the byte
        counters must reflect the ENCODED blobs (what crossed the
        wire), not the decoded f32 arrays — an operator sizing network
        capacity off wire_bytes_per_s would otherwise read 2x (4x for
        1bit/topk) the real traffic."""
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 4, updater="adagrad", name="bw",
                              wire="bf16", ctx=two_ranks[0])
        AsyncMatrixTable(16, 4, updater="adagrad", name="bw",
                         wire="bf16", ctx=two_ranks[1])
        t0.add_rows([9], np.ones((1, 4), np.float32))
        t0.get_rows([9])
        sh = t0.server_stats(1)["shards"]["bw"]
        assert sh["add_bytes"] == 4 * 2   # 4 cols x bf16
        assert sh["get_bytes"] == 4 * 2

    def test_hotkeys_flag_off_disables(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        config.set_flag("hotkeys_capacity", 0)
        t0 = AsyncMatrixTable(16, 4, updater="adagrad", name="hf",
                              ctx=two_ranks[0])
        AsyncMatrixTable(16, 4, updater="adagrad", name="hf",
                         ctx=two_ranks[1])
        t0.add_rows([9], np.ones((1, 4), np.float32))
        sh = t0.server_stats(1)["shards"]["hf"]
        assert "hotkeys" not in sh

    def test_add_bytes_counts_requests_not_merged_applies(self):
        """Server-side queue coalescing merges K overlapping adds into
        ONE deduped apply; add_bytes must still count the K requests'
        payloads (the wire traffic), not the merged array's."""
        from multiverso_tpu.ps.shard import RowShard
        from multiverso_tpu.updaters import AddOption, get_updater
        sh = RowShard(0, 8, 4, np.float32, get_updater("sgd"), "ab")
        opt = AddOption(learning_rate=1.0)
        entries = [sh._prep_add_entry(
            {"opt": {"learning_rate": 1.0}},
            [np.array([2], np.int64), np.ones((1, 4), np.float32)])
            for _ in range(3)]
        with sh._lock:
            applies = sh._apply_add_group(entries, opt)
        assert applies == 1                       # merged into one apply
        assert sh.stats()["add_bytes"] == 3 * 4 * 4   # but 3 requests

    def test_monitor_merge_dedupes_shared_process(self, two_ranks):
        """Two ranks served from ONE OS process share the process-global
        Dashboard; the cluster merge must pool it once, not double every
        monitor count (the in-process fixture/bench shape)."""
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        from multiverso_tpu.utils.dashboard import Dashboard
        t0 = AsyncMatrixTable(16, 4, updater="adagrad", name="dd",
                              ctx=two_ranks[0])
        AsyncMatrixTable(16, 4, updater="adagrad", name="dd",
                         ctx=two_ranks[1])
        for _ in range(4):
            t0.add_rows([9], np.ones((1, 4), np.float32))
        agg = aggregator.ClusterAggregator(two_ranks[0].service)
        rec = agg.poll_once()
        local = Dashboard.get("table[dd].add_rows").snapshot()
        merged = rec["monitors"]["table[dd].add_rows"]
        assert merged["count"] == local.count   # once, not 2x

    def test_hash_shard_records_keys_not_slots(self, two_ranks):
        """A hash shard's sketch must rank the workload's KEYS: key
        4242 lands in slot 0, and slot-id recording would report 0."""
        from multiverso_tpu.ps.tables import AsyncSparseKVTable
        t = AsyncSparseKVTable(4, name="hs", ctx=two_ranks[0])
        AsyncSparseKVTable(4, name="hs", ctx=two_ranks[1])
        key = 4243 if (4243 % 2) == 1 else 4242    # owned by rank 1
        for _ in range(3):
            t.add_rows([key], np.ones((1, 4), np.float32))
        t.get_rows([key])
        sh = t.server_stats(1)["shards"]["hs"]
        items = sh["hotkeys"]["items"]
        assert items[0][0] == key
        assert items[0][1] >= 3


# ---------------------------------------------------------------------- #
# exporter label scheme (satellite)
# ---------------------------------------------------------------------- #
def test_prometheus_table_labels():
    from multiverso_tpu.telemetry.exporter import prometheus_text
    txt = prometheus_text({
        "rank": 3,
        "monitors": {
            "table[we].add_rows": {"count": 2, "sum_ms": 1.0, "timed": 2,
                                   "p50_ms": 0.5, "p99_ms": 0.9,
                                   "max_ms": 1.0},
            "ps[we].serve": {"count": 1, "sum_ms": 1.0, "timed": 1,
                             "p50_ms": 1.0, "p99_ms": 1.0, "max_ms": 1.0},
            "zoo.barrier": {"count": 1, "sum_ms": 0.1}},
        "shards": {"we": {"adds": 2}}})
    assert ('mv_monitor_count{name="table[we].add_rows",table="we",'
            'rank="3"} 2') in txt
    assert ('mv_monitor_count{name="ps[we].serve",table="we",rank="3"} 1'
            ) in txt
    # table-less monitors keep the two-label form
    assert 'mv_monitor_count{name="zoo.barrier",rank="3"} 1' in txt
    assert 'mv_shard_adds{table="we",rank="3"} 2' in txt
