"""FTRL updater, sparse LR push/pull path, compression filters
(ref: LR FTRL objective + SparseTable, quantization_util filters)."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.apps.logistic_regression import LogReg, LogRegConfig
from multiverso_tpu.models import logreg as model_lib
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils.filters import OneBitsFilter, SparseFilter


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


class TestFTRL:
    def test_zero_small_weights(self):
        # lambda1 drives small-|z| weights to exactly 0 (the sparsity FTRL
        # exists for)
        t = mv.ArrayTable(8, updater="ftrl", name="ftrl")
        t.add(np.full(8, 1e-4, np.float32))
        np.testing.assert_allclose(t.get(), 0.0)

    def test_descends_against_gradient(self):
        t = mv.ArrayTable(4, updater="ftrl", name="ftrl2")
        for _ in range(10):
            t.add(np.full(4, 1.0, np.float32))
        w = t.get()
        assert np.all(w < 0)  # persistent positive gradient -> negative w

    def test_state_roundtrip(self):
        import io
        t = mv.ArrayTable(16, updater="ftrl", name="ftrl3")
        t.add(np.random.default_rng(0).normal(size=16).astype(np.float32))
        buf = io.BytesIO()
        t.store(buf)
        snap = t.get().copy()
        t.add(np.ones(16, np.float32))
        buf.seek(0)
        t.load(buf)
        np.testing.assert_allclose(t.get(), snap)


def _write_svm(path, x, y):
    with open(path, "w") as f:
        for xi, yi in zip(x, y):
            nz = np.nonzero(xi)[0]
            feats = " ".join(f"{j}:{xi[j]:.5f}" for j in nz)
            f.write(f"{yi} {feats}\n")


class TestSparseLR:
    def _sparse_data(self, n=800, d=50, seed=0):
        # 10 informative features at fixed columns, randomly dropped per
        # sample (sparse but consistent layout)
        x, y = model_lib.synthetic_dataset(n, 10, 2, seed=seed)
        rng = np.random.default_rng(seed)
        cols = rng.choice(d, size=10, replace=False)
        full = np.zeros((n, d), np.float32)
        full[:, cols] = x
        drop = rng.random((n, d)) < 0.3
        full[drop] = 0.0
        return full, y

    def test_sparse_path_converges(self, tmp_path):
        x, y = self._sparse_data()
        train = tmp_path / "s.svm"
        _write_svm(train, x, y)
        cfg = LogRegConfig(dict(input_size="50", output_size="2",
                                sparse="true", updater_type="sgd",
                                minibatch_size="64", learning_rate="0.5",
                                train_epoch="4",
                                train_file=str(train),
                                test_file=str(train)))
        lr = LogReg(cfg)
        assert lr.sparse_table is not None and lr.table is None
        stats = lr.train_file()
        acc = lr.test_file()
        assert acc > 0.8, f"sparse LR acc {acc}, stats {stats}"

    def test_sparse_ftrl(self, tmp_path):
        x, y = self._sparse_data(seed=3)
        train = tmp_path / "f.svm"
        _write_svm(train, x, y)
        cfg = LogRegConfig(dict(input_size="50", output_size="2",
                                sparse="true", updater_type="ftrl",
                                objective_type="sigmoid",
                                minibatch_size="64", train_epoch="3",
                                train_file=str(train),
                                test_file=str(train)))
        lr = LogReg(cfg)
        lr.train_file()
        acc = lr.test_file()
        assert acc > 0.7, f"ftrl acc {acc}"
        # FTRL produces exact zeros somewhere (sparsity)
        w = lr.sparse_table.get()
        assert np.any(w == 0.0)


class TestFilters:
    def test_sparse_filter_roundtrip(self):
        f = SparseFilter(clip=0.1)
        data = np.zeros(100, np.float32)
        data[[3, 50, 99]] = [1.0, -2.0, 0.5]
        header, payload = f.filter_in(data)
        assert header["sparse"] and header["nnz"] == 3
        assert payload.size == 6  # (idx, val) pairs
        out = f.filter_out(header, payload)
        np.testing.assert_allclose(out, data)

    def test_sparse_filter_dense_passthrough(self):
        f = SparseFilter(clip=0.0)
        data = np.arange(1, 11, dtype=np.float32)
        header, payload = f.filter_in(data)
        assert not header["sparse"]
        np.testing.assert_allclose(f.filter_out(header, payload), data)

    def test_onebits_error_feedback_unbiased(self):
        f = OneBitsFilter(block=64)
        rng = np.random.default_rng(0)
        true_sum = np.zeros(256, np.float64)
        decoded_sum = np.zeros(256, np.float64)
        g = rng.normal(size=256).astype(np.float32) * 0.1
        for _ in range(200):
            true_sum += g
            header, bits, scales = f.filter_in(g)
            decoded_sum += f.filter_out(header, bits, scales)
        # error feedback keeps the accumulated stream close to the truth
        denom = np.abs(true_sum).mean()
        assert np.abs(decoded_sum - true_sum).mean() < 0.2 * max(denom, 1)

    def test_onebits_compression_ratio(self):
        f = OneBitsFilter(block=1024)
        assert f.compression_ratio(1 << 20) > 20


class TestWireFilteredTables:
    """wire_filter compresses the host<->device seam of whole-table Add/Get
    (the TPU analogue of the reference's MPI wire filters,
    quantization_util.h; decode runs in-graph, table.py)."""

    def test_bf16_wire_roundtrip(self):
        import multiverso_tpu as mv
        t = mv.ArrayTable(4096, name="wf_bf16", wire_filter="bf16")
        delta = np.random.default_rng(1).normal(size=4096).astype(np.float32)
        t.add(delta)
        t.add(delta)
        got = t.get()
        np.testing.assert_allclose(got, 2 * delta, rtol=2e-2, atol=2e-2)

    def test_onebit_wire_error_feedback_converges(self):
        import multiverso_tpu as mv
        t = mv.ArrayTable(4096, name="wf_1bit", wire_filter="1bit")
        rng = np.random.default_rng(2)
        delta = (rng.normal(size=4096) * 0.1).astype(np.float32)
        k = 50
        for _ in range(k):
            t.add(delta)
        got = t.get().astype(np.float64)
        true = k * delta.astype(np.float64)
        # error feedback: cumulative applied == cumulative sent - residual,
        # so the gap stays bounded by ~one payload's magnitude (a small
        # constant factor from per-block scale coupling), NOT O(k) = 50x
        assert np.abs(got - true).mean() < 4.0 * np.abs(delta).mean(), (
            np.abs(got - true).mean(), np.abs(delta).mean())

    def test_device_resident_delta_skips_filter(self):
        import jax.numpy as jnp
        import multiverso_tpu as mv
        t = mv.ArrayTable(128, name="wf_dev", wire_filter="1bit")
        dev = jnp.ones(128, jnp.float32)
        t.add(dev)   # device array: already past the wire, applied exactly
        np.testing.assert_allclose(t.get(), 1.0, rtol=1e-2)

    def test_unknown_filter_raises(self):
        import multiverso_tpu as mv
        with pytest.raises(ValueError):
            mv.ArrayTable(16, name="wf_bad", wire_filter="zstd")
