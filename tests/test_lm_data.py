"""LM data pipeline (io/lm_data.py): packing, prefetched sharded batches,
perplexity evaluation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import multiverso_tpu as mv
from multiverso_tpu.io import lm_data
from multiverso_tpu.models import transformer as tfm


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


class TestPackTokens:
    def test_windows_cover_stream_without_losing_targets(self):
        ids = np.arange(33)
        w = lm_data.pack_tokens(ids, seq_len=8)
        assert w.shape == (4, 9)
        # consecutive windows overlap by exactly one token
        np.testing.assert_array_equal(w[0], np.arange(9))
        np.testing.assert_array_equal(w[1], np.arange(8, 17))
        # every next-token target (ids[1:]) appears exactly once
        targets = np.concatenate([row[1:] for row in w])
        np.testing.assert_array_equal(np.sort(targets), np.arange(1, 33))

    def test_pad_remainder_returns_mask(self):
        w, m = lm_data.pack_tokens_padded(np.arange(20), seq_len=8)
        assert w.shape == (3, 9) and m.shape == (3, 8)
        assert (w[-1][-5:] == 0).all()  # zero-padded tail
        # exactly the 19 real targets are unmasked, all in order
        assert m.sum() == 19
        assert (m[:2] == 1).all() and (m[2][:3] == 1).all()
        assert (m[2][3:] == 0).all()

    def test_padded_accepts_short_stream(self):
        w, m = lm_data.pack_tokens_padded(np.arange(5), seq_len=8)
        assert w.shape == (1, 9) and m.sum() == 4

    def test_too_short_raises(self):
        with pytest.raises(ValueError, match="shorter"):
            lm_data.pack_tokens(np.arange(5), seq_len=8)
        with pytest.raises(ValueError, match="mask"):
            lm_data.pack_tokens(np.arange(20), seq_len=8,
                                drop_remainder=False)


class TestTokenBatches:
    def test_epoch_covers_all_windows_sharded(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        cfg = tfm.TransformerConfig(vocab_size=64, dim=16, num_heads=2,
                                    num_layers=1, max_seq=16, attn="ring",
                                    batch_axis="dp", seq_axis="sp")
        ids = np.random.default_rng(0).integers(0, 64, 16 * 20 + 1)
        windows = lm_data.pack_tokens(ids, 16)
        batches = lm_data.TokenBatches(windows, batch_size=4, cfg=cfg,
                                       mesh=mesh, seed=1)
        assert len(batches) == 5
        seen = 0
        for tok, tgt in batches:
            assert tok.shape == (4, 16) and tgt.shape == (4, 16)
            assert tok.sharding.spec == jax.sharding.PartitionSpec(
                "dp", "sp")
            np.testing.assert_array_equal(np.asarray(tok)[:, 1:],
                                          np.asarray(tgt)[:, :-1])
            seen += 1
        assert seen == 5

    def test_prefetch_matches_sync(self):
        mv.init()
        cfg = tfm.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                    num_layers=1, max_seq=8)
        windows = lm_data.pack_tokens(np.arange(8 * 12 + 1) % 32, 8)
        a = [np.asarray(t) for t, _ in lm_data.TokenBatches(
            windows, 4, cfg, seed=3, prefetch=True)]
        b = [np.asarray(t) for t, _ in lm_data.TokenBatches(
            windows, 4, cfg, seed=3, prefetch=False)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert len(a) == len(b) == 3


class TestMaskedBatches:
    def test_masked_triples_and_unbiased_perplexity(self):
        mv.init()
        cfg = tfm.TransformerConfig(vocab_size=16, dim=16, num_heads=2,
                                    num_layers=1, max_seq=8)
        # 3 full windows + a ragged tail of zeros-as-padding
        stream = np.random.default_rng(5).integers(1, 16, 8 * 3 + 4)
        w, m = lm_data.pack_tokens_padded(stream, 8)
        batches = lm_data.TokenBatches(w, 2, cfg, seed=0, masks=m)
        params = tfm.init_params(cfg, seed=0)
        for batch in batches:
            assert len(batch) == 3
        loss_m, _ = lm_data.evaluate_perplexity(params, batches, cfg)
        # the same windows with the pad targets INCLUDED give a different
        # (biased) loss, proving the mask actually reaches loss_fn
        unmasked = lm_data.TokenBatches(w, 2, cfg, seed=0)
        loss_u, _ = lm_data.evaluate_perplexity(params, unmasked, cfg)
        assert abs(loss_m - loss_u) > 1e-4

    def test_mask_shape_validated(self):
        cfg = tfm.TransformerConfig(vocab_size=16, dim=16, num_heads=2,
                                    num_layers=1, max_seq=8)
        w, m = lm_data.pack_tokens_padded(np.arange(20), 8)
        with pytest.raises(ValueError, match="masks"):
            lm_data.TokenBatches(w, 2, cfg, masks=m[:, :-1])


class TestPerplexity:
    def test_trained_model_beats_untrained(self):
        mv.init()
        cfg = tfm.TransformerConfig(vocab_size=16, dim=32, num_heads=4,
                                    num_layers=2, max_seq=16, attn="local")
        stream = np.tile(np.arange(8), 60)
        windows = lm_data.pack_tokens(stream, 16)
        batches = lm_data.TokenBatches(windows, 4, cfg, seed=0)
        params = tfm.init_params(cfg, seed=0)
        _, ppl0 = lm_data.evaluate_perplexity(params, batches, cfg)
        step = jax.jit(tfm.make_train_step(cfg, 0.5))
        for _ in range(3):
            for tok, tgt in batches:
                params, _ = step(params, tok, tgt)
        loss1, ppl1 = lm_data.evaluate_perplexity(params, batches, cfg)
        assert ppl1 < ppl0 / 3
        assert ppl1 == pytest.approx(np.exp(loss1))
