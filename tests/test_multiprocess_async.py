"""Multi-process async PS: N real OS processes, uncoordinated Add/Get.

The tier-2 fixture for the capability that defines the reference (ref
src/worker.cpp / src/server.cpp): per-worker row sets, per-worker rates,
no collectives — plus the crash case its MPI world couldn't survive."""

import json
import os
import subprocess
import sys
import time

import pytest

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)


def _spawn(tmp_path, nprocs, mode, expect_fail_rank=None, extra_env=None):
    rdv = str(tmp_path / "rdv")
    os.makedirs(rdv, exist_ok=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # an ambient MV_PS_NATIVE (e.g. left exported while debugging the
    # fallback) must not silently downgrade the native-plane tests
    env.pop("MV_PS_NATIVE", None)
    env.update(extra_env or {})
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_DIR, "async_ps_worker.py"),
             rdv, str(nprocs), str(pid), mode],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True)
        for pid in range(nprocs)
    ]
    results, errors = {}, []
    for pid, p in enumerate(procs):
        try:
            stdout, stderr = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail(f"process {pid} timed out (async plane hung?)")
        if pid == expect_fail_rank:
            assert p.returncode == 17, f"victim exited rc={p.returncode}"
            continue
        if p.returncode != 0:
            errors.append(f"pid {pid} rc={p.returncode}\n{stderr[-2000:]}")
            continue
        for line in stdout.splitlines():
            if line.startswith("RESULT "):
                results[pid] = json.loads(line[len("RESULT "):])
    if errors:
        pytest.fail("\n".join(errors))
    return results


def test_uncoordinated_rates_python_plane(tmp_path):
    """The same converged-state contract on the pure-PYTHON plane
    (ps_native off): the fallback for toolchain-less hosts must keep
    working at the real OS-process tier, not just in-process tests."""
    results = _spawn(tmp_path, 2, "rates", extra_env={"MV_PS_NATIVE": "0"})
    assert set(results) == {0, 1}
    expect_sum = sum((r + 1) * 5 for r in range(2)) * 8 * 4
    for r in results.values():
        assert r["row_sum"] == expect_sum


@pytest.mark.parametrize("nprocs", [2, 4])
def test_uncoordinated_rates(tmp_path, nprocs):
    """Every worker pushes a different row set at a different rate; all
    workers read back the identical converged state."""
    results = _spawn(tmp_path, nprocs, "rates")
    assert set(results) == set(range(nprocs))
    # total pushed mass: sum_r (r+1)*5 pushes x 8 rows x 4 cols
    expect_sum = sum((r + 1) * 5 for r in range(nprocs)) * 8 * 4
    for r in results.values():
        assert r["row_sum"] == expect_sum
        assert r["kv"] == {str(k): (k + 1) * 5.0 for k in range(nprocs)}


@pytest.mark.parametrize("nprocs", [2, 4])
def test_send_window_across_processes(tmp_path, nprocs):
    """PR-2 send window at the real OS-process tier: every rank streams
    windowed 1-row adds to its own disjoint rows (integer deltas =>
    order-independent EXACT sums), fenced gets read the rank's own
    writes mid-stream, and the converged state matches the integer
    expectation bit-for-bit on every rank."""
    results = _spawn(tmp_path, nprocs, "window")
    assert set(results) == set(range(nprocs))
    expect_sum = sum(40 + r * 10 for r in range(nprocs)) * 4
    for r in results.values():
        assert r["row_sum"] == expect_sum
        assert r["windowed"] > 0
        # frames can never exceed logical adds; equality is legal on a
        # loaded box where every 5 ms window catches one add, so don't
        # assert strict coalescing here (the single-process tests do)
        assert 0 < r["flushes"] <= r["windowed"]


def test_stats_and_trace_across_processes(tmp_path):
    """PR-3 telemetry acceptance at the real OS-process tier: a worker
    pulls the REMOTE shard's server-side stats via the MSG_STATS RPC, a
    windowed add's client spans and the owning shard's apply spans share
    one trace ID across the two ranks' JSONL trace files, and the
    dashboard histograms report p50/p99 for add_rows and get_rows."""
    metrics_dir = str(tmp_path / "metrics")
    os.makedirs(metrics_dir, exist_ok=True)
    results = _spawn(tmp_path, 2, "stats",
                     extra_env={"MV_METRICS_DIR": metrics_dir})
    assert set(results) == {0, 1}
    for rank, r in results.items():
        assert r["stats_rank"] == (rank + 1) % 2
        assert r["shard_adds"] >= 3
        assert r["spans"] > 0
        for op in ("add_rows", "get_rows"):
            m = r["monitors"][op]
            assert m["count"] > 0 and m["p99_ms"] >= m["p50_ms"] > 0
    # stitch the two ranks' trace files: a client-side span (enqueue)
    # minted on one rank must share its trace ID with a shard-side apply
    # span recorded on the OTHER rank
    events = []
    for rank in (0, 1):
        path = os.path.join(metrics_dir, f"trace-rank{rank}.jsonl")
        assert os.path.exists(path), path
        with open(path) as f:
            events += [json.loads(line) for line in f if line.strip()]
    def ids(names):
        out = set()
        for e in events:
            if e["name"] in names:
                out.add(e["args"].get("trace"))
                out.update(e["args"].get("traces", ()))
        out.discard(None)
        return out
    client = ids({"client.enqueue"})
    shard = ids({"shard.wave_apply", "shard.apply"})
    shared = client & shard
    assert shared, (sorted(e["name"] for e in events)[:20],
                    len(client), len(shard))
    # spans are trace_event "complete" events with absolute us timestamps
    for e in events:
        assert e["ph"] == "X" and e["ts"] > 0 and e["dur"] >= 0
        assert e["pid"] in (0, 1)
    # the client and shard halves of a shared trace came from DIFFERENT
    # ranks (the ID really crossed the wire)
    by_trace = {}
    for e in events:
        for tid in ([e["args"].get("trace")]
                    + list(e["args"].get("traces", ()))):
            if tid in shared:
                by_trace.setdefault(tid, set()).add(e["pid"])
    assert any(len(pids) == 2 for pids in by_trace.values()), by_trace


@pytest.mark.parametrize("nprocs", [4])
def test_uncoordinated_sparse_ftrl_lr(tmp_path, nprocs):
    """np=4 sparse FTRL LR through the app, uncoordinated: each rank trains
    on its own data shard against the hash-sharded FTRL table and the
    jointly-trained model classifies the full dataset (VERDICT r2 item 3;
    ref model/ps_model.cpp:24-41 + util/ftrl_sparse_table.h)."""
    results = _spawn(tmp_path, nprocs, "ftrl_lr")
    assert set(results) == set(range(nprocs))
    for r in results.values():
        assert r["acc"] > 0.85


@pytest.mark.parametrize("victim_pick", ["last", "zero"])
def test_kill_and_restart_recovers_shard(tmp_path, victim_pick):
    """Full elastic recovery loop (VERDICT r2 item 5): a rank dies, PS
    socket-death tombstones it in elastic's failed set, the parent
    restarts it, the new incarnation republishes via rendezvous and
    reloads ITS shard from the checkpoint (load_local — peers' newer
    state untouched), survivors re-resolve and training resumes.
    Parametrized over the victim: rank 0 dying must recover through the
    SAME machinery as the last rank (no id-space special cases)."""
    nprocs = 3
    victim = 0 if victim_pick == "zero" else nprocs - 1
    rdv = str(tmp_path / "rdv")
    os.makedirs(rdv, exist_ok=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MV_VICTIM"] = str(victim)

    def launch(pid, restarted=False):
        e = dict(env)
        if restarted:
            e["MV_RESTARTED"] = "1"
        return subprocess.Popen(
            [sys.executable, os.path.join(_DIR, "async_ps_worker.py"),
             rdv, str(nprocs), str(pid), "recover"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=e,
            text=True)

    procs = [launch(pid) for pid in range(nprocs)]
    survivors = [r for r in range(nprocs) if r != victim]
    try:
        assert procs[victim].wait(timeout=120) == 17
        # restart only after every survivor observed the death (their
        # tombstone assertion must precede the rejoin beacon). 240 s:
        # observed ~12 s nominal, but a contended 1-core box stacking
        # three jax startups + the checkpoint store can blow far past
        # it (one-off flake at 120 s in a full-tier run)
        deadline = time.monotonic() + 240
        while not all(os.path.exists(os.path.join(rdv, f"down.{r}"))
                      for r in survivors):
            assert time.monotonic() < deadline, "survivors never tombstoned"
            time.sleep(0.1)
        procs[victim] = launch(victim, restarted=True)
        results = {}
        for pid, p in enumerate(procs):
            stdout, stderr = p.communicate(timeout=150)
            assert p.returncode == 0, f"pid {pid}\n{stderr[-2000:]}"
            for line in stdout.splitlines():
                if line.startswith("RESULT "):
                    results[pid] = json.loads(line[len("RESULT "):])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert results[victim]["restarted"] is True
    for r in survivors:
        assert results[r]["tombstoned"] is True
        assert results[r]["recovered_value"] == float(nprocs)
        assert results[r]["tombstone_cleared"] is True
        assert results[r]["post_value"] >= nprocs + 1


@pytest.mark.parametrize("nprocs", [3])
def test_killed_worker_does_not_hang_peers(tmp_path, nprocs):
    """The last rank crashes mid-run (os._exit, no cleanup). Survivors keep
    full function on live shards and get a typed, time-bounded error for
    the dead shard — the elastic behavior the reference's MPI world lacked
    (SURVEY §5: 'no heartbeats, no re-registration')."""
    results = _spawn(tmp_path, nprocs, "kill",
                     expect_fail_rank=nprocs - 1)
    assert set(results) == set(range(nprocs - 1))
    for r in results.values():
        assert r["live_row0"] >= 10.0
        assert r["dead_shard_error_s"] < 15.0
