"""Unit tests for config/log/timer/dashboard (reference tier-1 analogue,
SURVEY §4: Test/unittests/)."""

import time

import pytest

from multiverso_tpu.utils import config, log
from multiverso_tpu.utils.dashboard import Dashboard, monitor
from multiverso_tpu.utils.timer import Timer


class TestConfig:
    def test_defaults(self):
        assert config.get_flag("ps_role") == "default"
        assert config.get_flag("sync") is False
        assert config.get_flag("updater_type") == "default"

    def test_set_flag_coercion(self):
        config.set_flag("sync", "true")
        assert config.get_flag("sync") is True
        config.set_flag("num_workers", "4")
        assert config.get_flag("num_workers") == 4
        with pytest.raises(config.FlagError):
            config.set_flag("sync", "maybe")
        with pytest.raises(config.FlagError):
            config.set_flag("no_such_flag", 1)

    def test_parse_cmd_flags_compacts_argv(self):
        rest = config.parse_cmd_flags(
            ["prog", "-sync=true", "positional", "-updater_type=adagrad",
             "-unknown_flag=1"])
        assert rest == ["prog", "positional", "-unknown_flag=1"]
        assert config.get_flag("sync") is True
        assert config.get_flag("updater_type") == "adagrad"

    def test_parse_config_file(self, tmp_path):
        p = tmp_path / "cfg"
        p.write_text("# comment\nupdater_type=sgd\ncustom_key=42\n\n")
        pairs = config.parse_config_file(str(p))
        assert pairs == {"updater_type": "sgd", "custom_key": "42"}
        assert config.get_flag("updater_type") == "sgd"

    def test_define_and_reset(self):
        config.define_int("test_only_flag", 7, "test")
        config.set_flag("test_only_flag", 9)
        assert config.get_flag("test_only_flag") == 9
        config.reset_flags()
        assert config.get_flag("test_only_flag") == 7


class TestLog:
    def test_check(self):
        log.check(True)
        with pytest.raises(log.FatalError):
            log.check(False, "boom")

    def test_check_notnull(self):
        assert log.check_notnull(5) == 5
        with pytest.raises(log.FatalError):
            log.check_notnull(None, "ptr")

    def test_levels(self, capsys):
        logger = log.Logger(level=log.LogLevel.ERROR, name="t")
        logger.info("hidden")
        logger.error("shown")
        captured = capsys.readouterr()
        assert "hidden" not in captured.out + captured.err
        assert "shown" in captured.err


class TestDashboard:
    def test_monitor_accumulates(self):
        with monitor("op"):
            time.sleep(0.01)
        with monitor("op"):
            pass
        mon = Dashboard.get("op")
        assert mon.count == 2
        assert mon.total_ms >= 10.0
        assert "op" in mon.info_string()

    def test_display(self, capsys):
        with monitor("x"):
            pass
        Dashboard.display()
        out = capsys.readouterr().out
        assert "Dashboard" in out and "[x]" in out

    def test_notes_in_display_and_reset(self, capsys):
        """Free-form notes (native-transport counters) print alongside
        the monitors and clear on reset."""
        Dashboard.note("ps[t].native_served", "adds = 7, applies = 7")
        Dashboard.display()
        out = capsys.readouterr().out
        assert "native_served] adds = 7" in out
        Dashboard.reset()
        Dashboard.display()
        assert "native_served" not in capsys.readouterr().out


def test_timer():
    t = Timer()
    time.sleep(0.005)
    assert t.elapse() >= 5.0
    t.start()
    assert t.elapse() < 5.0


def test_documentation_citations_resolve():
    import importlib.util
    import pathlib
    path = (pathlib.Path(__file__).resolve().parents[1]
            / "tools" / "check_parity.py")
    spec = importlib.util.spec_from_file_location("check_parity", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check() == []
