"""SparseMatrixTable dirty-row protocol + AsyncBuffer tests
(ref matrix.cpp stale-row semantics, async_buffer.h)."""

import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.utils.async_buffer import AsyncBuffer


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


class TestSparseMatrixTable:
    def test_first_get_pulls_everything(self):
        t = mv.SparseMatrixTable(20, 4, num_workers=2)
        t.add_rows([1, 2], np.ones((2, 4), np.float32))
        assert t.stale_fraction(range(20), worker_id=0) == 1.0
        rows = t.get_rows_sparse(range(20), worker_id=0)
        np.testing.assert_allclose(rows[1], 1.0)
        np.testing.assert_allclose(rows[0], 0.0)
        # now everything is fresh for worker 0 ...
        assert t.stale_fraction(range(20), worker_id=0) == 0.0
        # ... but still stale for worker 1 (per-worker bits)
        assert t.stale_fraction(range(20), worker_id=1) == 1.0

    def test_add_marks_rows_stale_again(self):
        t = mv.SparseMatrixTable(10, 4, num_workers=1)
        t.get_rows_sparse(range(10))
        t.add_rows([3], np.full((1, 4), 2.0, np.float32))
        assert t.stale_fraction(range(10)) == pytest.approx(0.1)
        rows = t.get_rows_sparse(range(10))
        np.testing.assert_allclose(rows[3], 2.0)

    def test_fresh_rows_served_from_cache(self):
        t = mv.SparseMatrixTable(10, 4, num_workers=1)
        t.add_rows([5], np.ones((1, 4), np.float32))
        first = t.get_rows_sparse([5])
        np.testing.assert_allclose(first, 1.0)
        # second sparse get transfers nothing but must return same values
        again = t.get_rows_sparse([5])
        np.testing.assert_allclose(again, 1.0)

    def test_whole_table_add_dirties_all(self):
        t = mv.SparseMatrixTable(10, 4, num_workers=1)
        t.get_rows_sparse(range(10))
        t.add(np.ones((10, 4), np.float32))
        assert t.stale_fraction(range(10)) == 1.0
        np.testing.assert_allclose(t.get_rows_sparse(range(10)), 1.0)

    def test_worker_cache_is_sparse(self):
        """The worker cache must cost O(rows pulled), not O(table): the
        reference's workload class is 21M vocab x 300 dim (ref
        Applications/WordEmbedding/README.md) — a dense host mirror per
        worker would be ~25 GB. 1M x 128 here, pulling a few hundred rows."""
        t = mv.SparseMatrixTable(1_000_000, 128, num_workers=4)
        ids = np.arange(0, 1_000_000, 4096)   # 245 rows
        rows = t.get_rows_sparse(ids, worker_id=0)
        assert rows.shape == (ids.size, 128)
        dense_bytes = 1_000_000 * 128 * 4
        assert t.cache_nbytes(0) < dense_bytes // 100   # ~512 KB vs 512 MB
        # repeat pull: served from the sparse cache, values stable
        np.testing.assert_allclose(t.get_rows_sparse(ids, worker_id=0), rows)

    def test_duplicate_ids(self):
        t = mv.SparseMatrixTable(10, 4, num_workers=1)
        t.add_rows([2], np.ones((1, 4), np.float32))
        rows = t.get_rows_sparse([2, 2, 3])
        assert rows.shape == (3, 4)
        np.testing.assert_allclose(rows[0], 1.0)
        np.testing.assert_allclose(rows[1], 1.0)
        np.testing.assert_allclose(rows[2], 0.0)


class TestAsyncBuffer:
    def test_overlapped_fills(self):
        calls = []

        def fill():
            calls.append(time.perf_counter())
            return len(calls)

        buf = AsyncBuffer(fill)
        assert buf.get() == 1
        assert buf.get() == 2
        buf.stop()

    def test_error_propagates_once(self):
        state = {"n": 0}

        def fill():
            state["n"] += 1
            if state["n"] == 1:
                raise ValueError("boom")
            return state["n"]

        buf = AsyncBuffer(fill)
        with pytest.raises(ValueError):
            buf.get()
