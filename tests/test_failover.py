"""Elastic shard failover (ISSUE 7): exactly-once replay dedupe on both
wire planes, per-shard incremental checkpoints with torn-write
skipping, the failover supervisor's detect→respawn→rejoin loop, the
stale-tombstone incarnation rule, and a fast in-process failover smoke
(kill a rank's service, respawn it in-process, restore from the shard
checkpoint, and assert the replayed state is bit-exact). The full
SIGKILL chaos bench lives in tools/bench_chaos.py and runs as a `slow`
test at the bottom."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from multiverso_tpu import checkpoint, elastic
from multiverso_tpu.ps import failover
from multiverso_tpu.ps import service as svc
from multiverso_tpu.ps import wire
from multiverso_tpu.ps.tables import AsyncMatrixTable, AsyncSparseKVTable
from multiverso_tpu.utils import config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _stamped_meta(table, cl, seq, extra=None):
    meta = {"table": table, "opt": {},
            wire.REPLAY_CLIENT_KEY: cl, wire.REPLAY_SEQ_KEY: seq}
    meta.update(extra or {})
    return meta


class TestReplayDedupe:
    """A shard receiving the same sequence-stamped frame twice applies
    it exactly once — on both the python and native-punt wire planes
    (the two_ranks fixture parametrizes them; stamped metas always punt
    off the native fast path, so dedupe is one implementation)."""

    def test_plain_frame_applies_once(self, two_ranks):
        ctxs = two_ranks
        t0 = AsyncMatrixTable(8, 2, name="dd", ctx=ctxs[0])
        AsyncMatrixTable(8, 2, name="dd", ctx=ctxs[1])
        meta = _stamped_meta("dd", "c1", 0)
        arrays = [np.array([5], np.int64), np.ones((1, 2), np.float32)]
        r1, _ = ctxs[0].service.request(
            1, svc.MSG_ADD_ROWS, meta, arrays).result(15)
        assert not r1.get(wire.REPLAY_DUP_KEY)
        assert wire.REPLAY_DURABLE_KEY in r1
        # the duplicate (replay racing a late ack) acks without applying
        r2, _ = ctxs[0].service.request(
            1, svc.MSG_ADD_ROWS, meta, arrays).result(15)
        assert r2.get(wire.REPLAY_DUP_KEY) is True
        got = t0.get_rows([5])
        assert float(got[0, 0]) == 1.0, got
        st = t0.server_stats(1)["shards"]["dd"]
        assert st["dup_frames"] >= 1
        assert st["replay_clients"] == 1

    def test_batch_frame_applies_once(self, two_ranks):
        ctxs = two_ranks
        t0 = AsyncMatrixTable(8, 2, name="db", ctx=ctxs[0])
        AsyncMatrixTable(8, 2, name="db", ctx=ctxs[1])
        blobs = [wire.encode(svc.MSG_ADD_ROWS, i,
                             {"table": "db", "opt": {}},
                             [np.array([4 + i], np.int64),
                              np.ones((1, 2), np.float32)])
                 for i in range(2)]
        meta = _stamped_meta("db", "c1", 7, {"n": 2})
        arrays = wire.pack_batch(blobs)
        r1, _ = ctxs[0].service.request(
            1, svc.MSG_BATCH, meta, arrays).result(15)
        assert not r1.get(wire.REPLAY_DUP_KEY)
        r2, _ = ctxs[0].service.request(
            1, svc.MSG_BATCH, meta, arrays).result(15)
        assert r2.get(wire.REPLAY_DUP_KEY) is True
        got = t0.get_rows([4, 5])
        assert np.array_equal(got, np.ones((2, 2), np.float32)), got

    def test_hash_shard_dedupes_too(self, two_ranks):
        ctxs = two_ranks
        t0 = AsyncSparseKVTable(2, name="dk", ctx=ctxs[0])
        AsyncSparseKVTable(2, name="dk", ctx=ctxs[1])
        meta = _stamped_meta("dk", "c2", 3)
        arrays = [np.array([11], np.int64), np.ones((1, 2), np.float32)]
        ctxs[0].service.request(1, svc.MSG_ADD_ROWS, meta,
                                arrays).result(15)
        r2, _ = ctxs[0].service.request(
            1, svc.MSG_ADD_ROWS, meta, arrays).result(15)
        assert r2.get(wire.REPLAY_DUP_KEY) is True
        assert float(t0.get_rows([11])[0, 0]) == 1.0

    def test_out_of_order_replay_not_lost(self, two_ranks):
        """A late frame arriving AFTER a higher sequence (re-send
        across a connection change) must still apply — the channel
        tracks gaps, not just a high-water mark."""
        ctxs = two_ranks
        t0 = AsyncMatrixTable(8, 2, name="oo", ctx=ctxs[0])
        AsyncMatrixTable(8, 2, name="oo", ctx=ctxs[1])
        arrays = [np.array([6], np.int64), np.ones((1, 2), np.float32)]
        ctxs[0].service.request(1, svc.MSG_ADD_ROWS,
                                _stamped_meta("oo", "c3", 2),
                                arrays).result(15)
        r, _ = ctxs[0].service.request(1, svc.MSG_ADD_ROWS,
                                       _stamped_meta("oo", "c3", 1),
                                       arrays).result(15)
        assert not r.get(wire.REPLAY_DUP_KEY)
        assert float(t0.get_rows([6])[0, 0]) == 2.0
        # ...and each of them is still deduped on a second arrival
        r, _ = ctxs[0].service.request(1, svc.MSG_ADD_ROWS,
                                       _stamped_meta("oo", "c3", 1),
                                       arrays).result(15)
        assert r.get(wire.REPLAY_DUP_KEY) is True

    def test_windowed_reflush_to_live_shard_is_noop(self, two_ranks):
        """The replay-race-vs-late-ack case end to end: force the send
        window to re-flush its retained (already acked) frames to the
        still-alive shard — every one must dedupe, state unchanged."""
        ctxs = two_ranks
        config.set_flag("ps_replay", True)
        t0 = AsyncMatrixTable(8, 2, name="rf", send_window_ms=1.0,
                              ctx=ctxs[0])
        AsyncMatrixTable(8, 2, name="rf", send_window_ms=1.0,
                         ctx=ctxs[1])
        for _ in range(4):
            t0.add_rows([5], np.ones((1, 2), np.float32))
        assert float(t0.get_rows([5])[0, 0]) == 4.0
        win = t0._window
        assert win._replay is not None
        # pretend the owner died: every retained frame re-arms...
        win._on_owner_death(1)
        # ...and the re-flush lands on the SAME live incarnation
        deadline = time.monotonic() + 10
        while win._replay.pending_send.get(1, 0) > 0:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        t0.flush()
        assert float(t0.get_rows([5])[0, 0]) == 4.0
        st = t0.server_stats(1)["shards"]["rf"]
        assert st["dup_frames"] >= 1

    def test_old_acked_frame_gets_full_retry_budget(self, two_ranks):
        """Regression: ps_replay_timeout bounds time spent RETRYING,
        measured from the replay episode's start — a frame acked long
        before its owner died must not be dropped with zero budget
        (its age is retention working as designed, not a stuck
        retry)."""
        ctxs = two_ranks
        config.set_flag("ps_replay", True)
        config.set_flag("ps_replay_backoff", 0.05)
        t0 = AsyncMatrixTable(8, 2, name="ob", send_window_ms=1.0,
                              ctx=ctxs[0])
        AsyncMatrixTable(8, 2, name="ob", send_window_ms=1.0,
                         ctx=ctxs[1])
        t0.add_rows([4], np.ones((1, 2), np.float32))
        win = t0._window
        q = win._replay.retained.get(1, {})
        assert q
        for fr in q.values():
            # simulate a frame retained far past ps_replay_timeout
            fr.created -= 10 * config.get_flag("ps_replay_timeout")
        win._on_owner_death(1)
        deadline = time.monotonic() + 10
        while win._replay.pending_send.get(1, 0) > 0:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        # the re-flush landed (dedup'd), nothing was dropped
        assert win._replay.mon_dropped.count == 0
        assert float(t0.get_rows([4])[0, 0]) == 1.0
        st = t0.server_stats(1)["shards"]["ob"]
        assert st["dup_frames"] >= 1


class TestShardCheckpoint:
    def test_roundtrip_and_durable_pruning(self, two_ranks, tmp_path):
        ctxs = two_ranks
        config.set_flag("ps_replay", True)
        t0 = AsyncMatrixTable(8, 2, name="ck", send_window_ms=1.0,
                              ctx=ctxs[0])
        t1 = AsyncMatrixTable(8, 2, name="ck", send_window_ms=1.0,
                              ctx=ctxs[1])
        ckdir = str(tmp_path / "ck")
        for _ in range(3):
            t0.add_rows([6], np.ones((1, 2), np.float32))
        path = checkpoint.save_shard_state(ckdir, 1, [t1])
        assert checkpoint.is_committed(path)
        assert checkpoint.latest_shard_tag(ckdir, 1) is not None
        # acks now carry the durable floor — retained frames prune
        t0.add_rows([6], np.ones((1, 2), np.float32))
        win = t0._window
        deadline = time.monotonic() + 10
        while len(win._replay.retained.get(1, {})) > 1:
            assert time.monotonic() < deadline, \
                dict(win._replay.retained.get(1, {}))
            time.sleep(0.05)
        # mutate past the checkpoint, then roll the shard back
        assert float(t0.get_rows([6])[0, 0]) == 4.0
        assert checkpoint.restore_shard_state(ckdir, 1, [t1]) == 1
        assert float(t0.get_rows([6])[0, 0]) == 3.0

    def test_updater_state_roundtrips(self, two_ranks, tmp_path):
        ctxs = two_ranks
        t0 = AsyncMatrixTable(8, 2, name="cs", updater="adagrad",
                              ctx=ctxs[0])
        t1 = AsyncMatrixTable(8, 2, name="cs", updater="adagrad",
                              ctx=ctxs[1])
        from multiverso_tpu.updaters import AddOption
        opt = AddOption(learning_rate=0.1, rho=0.1)
        t0.add_rows([5], np.ones((1, 2), np.float32), opt)
        before = t0.get_rows([5]).copy()
        ckdir = str(tmp_path / "ck")
        checkpoint.save_shard_state(ckdir, 1, [t1])
        t0.add_rows([5], np.ones((1, 2), np.float32), opt)
        after_two = t0.get_rows([5]).copy()
        checkpoint.restore_shard_state(ckdir, 1, [t1])
        assert np.array_equal(t0.get_rows([5]), before)
        # the restored adagrad accumulator must step exactly like the
        # original's second step — state rode the checkpoint
        t0.add_rows([5], np.ones((1, 2), np.float32), opt)
        assert np.array_equal(t0.get_rows([5]), after_two)

    def test_torn_tag_invisible(self, tmp_path, two_ranks):
        ctxs = two_ranks
        t1 = AsyncMatrixTable(8, 2, name="tt", ctx=ctxs[1])
        AsyncMatrixTable(8, 2, name="tt", ctx=ctxs[0])
        ckdir = str(tmp_path / "ck")
        checkpoint.save_shard_state(ckdir, 1, [t1])
        p2 = checkpoint.save_shard_state(ckdir, 1, [t1])
        os.remove(os.path.join(p2, checkpoint.COMMIT_MARKER))
        # the torn newest tag is skipped: latest falls back to v0
        assert checkpoint.latest_shard_tag(ckdir, 1) == "v000000000"
        # ...and prune clears the debris once a newer commit exists
        checkpoint.save_shard_state(ckdir, 1, [t1])
        checkpoint.prune_shard_tags(ckdir, 1, keep=2)
        base = os.path.dirname(p2)
        assert os.path.basename(p2) not in os.listdir(base)

    def test_partition_mismatch_raises(self, two_ranks, tmp_path):
        ctxs = two_ranks
        t1 = AsyncMatrixTable(8, 2, name="pm", ctx=ctxs[1])
        meta, arrays = t1._shard.checkpoint_state()
        meta = dict(meta, lo=0)   # claim somebody else's range
        with pytest.raises(svc.PSError):
            t1._shard.restore_checkpoint(meta, arrays)


class TestTornFullCheckpoint:
    """Satellite: checkpoint.latest()/restore() skip torn directories
    — the manifest commit marker is written last."""

    def _fake_tag(self, root, tag, committed):
        d = root / tag
        d.mkdir(parents=True)
        (d / "manifest.json").write_text(
            json.dumps({"tables": {}, "version": 1}))
        if committed:
            (d / checkpoint.COMMIT_MARKER).write_text("1")

    def test_latest_skips_uncommitted(self, tmp_path):
        self._fake_tag(tmp_path, "step_000000009", committed=True)
        time.sleep(0.02)
        # newer but TORN (writer died before the marker): invisible
        self._fake_tag(tmp_path, "step_000000019", committed=False)
        assert checkpoint.latest(str(tmp_path)) == "step_000000009"

    def test_restore_rejects_uncommitted(self, tmp_path):
        self._fake_tag(tmp_path, "step_000000009", committed=False)
        with pytest.raises(ValueError, match="commit marker"):
            checkpoint.restore(str(tmp_path), "step_000000009")

    def test_truncated_mid_write_regression(self, tmp_path):
        """The literal mid-write truncation: manifest half-written, no
        marker — latest() must fall back to the previous good tag."""
        self._fake_tag(tmp_path, "step_000000009", committed=True)
        d = tmp_path / "step_000000019"
        d.mkdir()
        (d / "manifest.json").write_text('{"tables": {"0": {"na')
        assert checkpoint.latest(str(tmp_path)) == "step_000000009"


class TestStaleTombstone:
    """Satellite: a respawned rank's fresh beacon is never shadowed by
    its predecessor's tombstone — beacons and tombstones carry the
    incarnation address."""

    def test_fresh_incarnation_clears_out_stamped_tombstone(
            self, tmp_path):
        hb = str(tmp_path / "hb")
        # the predecessor kept beating while wedged: its last beacon
        # carries a timestamp AHEAD of anything the replacement writes
        pred = elastic.Heartbeat(hb, rank=3, addr="10.0.0.1:7001")
        pred.beat()
        path = pred.path
        with open(path) as f:
            raw = json.load(f)
        raw["ts"] = time.time() + 1000.0
        with open(path, "w") as f:
            json.dump(raw, f)
        elastic.mark_failed(hb, 3)
        assert 3 in elastic.failed(hb, timeout=1e9)
        # replacement incarnation: NEW address, ordinary (older) clock
        elastic.Heartbeat(hb, rank=3, addr="10.0.0.1:7002").beat()
        assert 3 not in elastic.failed(hb, timeout=1e9)
        assert elastic.health(hb, timeout=1e9)[3] == "ok"

    def test_addr_less_beacons_keep_timestamp_rule(self, tmp_path):
        hb = str(tmp_path / "hb")
        b = elastic.Heartbeat(hb, rank=2)
        b.beat()
        elastic.mark_failed(hb, 2)
        assert 2 in elastic.failed(hb, timeout=1e9)
        b.beat()   # newer beacon, same (absent) identity: clears by ts
        assert 2 not in elastic.failed(hb, timeout=1e9)

    def test_tombstone_records_beacon_addr(self, tmp_path):
        hb = str(tmp_path / "hb")
        elastic.Heartbeat(hb, rank=1, addr="h:1").beat()
        elastic.mark_failed(hb, 1)
        tomb = elastic._tombstones(hb)[1]
        assert tomb["addr"] == "h:1"


class TestSupervisor:
    def test_detect_respawn_rejoin(self, tmp_path):
        hb = str(tmp_path / "hb")
        calls = {"spawn": [], "kill": []}
        elastic.Heartbeat(hb, rank=0, addr="h:1").beat()
        victim = elastic.Heartbeat(hb, rank=1, addr="h:2")
        victim.beat()
        sup = failover.FailoverSupervisor(
            hb, 2, spawn=lambda r, g: calls["spawn"].append((r, g)),
            kill=lambda r: calls["kill"].append(r),
            timeout=1e9, poll_s=60, confirm=False, respawn_grace=0.2)
        assert sup.check_once()[1] == "ok"
        assert calls["spawn"] == []
        # the PS plane observes the death (tombstone short-circuits
        # the staleness timeout entirely)
        elastic.mark_failed(hb, 1)
        v = sup.check_once()
        assert v[1] == "dead"
        assert calls["kill"] == [1] and calls["spawn"] == [(1, 1)]
        phases = [p for _, p, _ in sup.events]
        assert phases == ["detect", "respawn"]
        # within the grace: no re-respawn even though still dead
        assert sup.check_once()[1] == "dead"
        assert calls["spawn"] == [(1, 1)]
        # the replacement beacons with a fresh incarnation address
        elastic.Heartbeat(hb, rank=1, addr="h:3").beat()
        assert sup.check_once()[1] == "ok"
        assert [p for _, p, _ in sup.events] == ["detect", "respawn",
                                                 "rejoin"]
        spans = sup.recovery_spans()
        assert len(spans) == 1 and spans[0]["rank"] == 1

    def test_confirm_probe_vetoes_false_positive(self, tmp_path):
        """A stale beacon alone (wedged NFS, slow clock) must not kill
        a rank whose MSG_HEALTH probe still answers ok."""
        from multiverso_tpu.ps.service import FileRendezvous, PSService
        rdv_dir = str(tmp_path / "rdv")
        service = PSService(1, 1, FileRendezvous(rdv_dir))
        try:
            hb = str(tmp_path / "hb")
            b = elastic.Heartbeat(hb, rank=1, addr=service.addr)
            b.beat()
            calls = []
            sup = failover.FailoverSupervisor(
                hb, 2, rendezvous_dir=rdv_dir,
                spawn=lambda r, g: calls.append((r, g)),
                timeout=0.0, poll_s=60, confirm=True, ranks=[1])
            time.sleep(0.05)   # beacon goes "stale" at timeout=0
            sup.check_once()
            assert calls == []   # probe answered: verdict vetoed
        finally:
            service.close()

    def test_never_seen_rank_not_respawned(self, tmp_path):
        hb = str(tmp_path / "hb")
        calls = []
        sup = failover.FailoverSupervisor(
            hb, 4, spawn=lambda r, g: calls.append(r), confirm=False,
            timeout=1e9, poll_s=60)
        sup.check_once()
        assert calls == []   # nobody ever beaconed: not ours to spawn


class TestInProcessFailoverSmoke:
    """Tier-1 failover smoke: kill a rank's service in-process, respawn
    it (fresh PSService + shard), restore from the per-shard
    checkpoint, and assert the survivor's replayed state is bit-exact —
    the full SIGKILL/OS-process version is the `slow` chaos bench."""

    def test_kill_respawn_restore_replay(self, tmp_path):
        from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                               PSService)
        config.set_flag("ps_native", False)
        config.set_flag("ps_replay", True)
        config.set_flag("ps_timeout", 30.0)
        config.set_flag("ps_connect_timeout", 5.0)
        config.set_flag("ps_reconnect_backoff", 0.2)
        config.set_flag("ps_replay_backoff", 0.1)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ckdir = str(tmp_path / "ck")
        ctx0 = PSContext(0, 2, PSService(0, 2, rdv))
        ctx1 = PSContext(1, 2, PSService(1, 2, rdv))
        ctx1b = None
        try:
            t = AsyncMatrixTable(8, 2, name="sm", send_window_ms=1.0,
                                 ctx=ctx0)
            t1 = AsyncMatrixTable(8, 2, name="sm", send_window_ms=1.0,
                                  ctx=ctx1)
            ck = failover.ShardCheckpointer(ckdir, 1, [t1],
                                            interval_s=999)
            for _ in range(3):
                t.add_rows([5], np.ones((1, 2), np.float32))
            ck.checkpoint_now()
            for _ in range(2):   # acked but NOT durable
                t.add_rows([5], np.ones((1, 2), np.float32))
            assert float(t.get_rows([5])[0, 0]) == 5.0
            ctx1.service.close()   # the "crash"
            # ops issued mid-outage must survive too
            mids = [t.add_rows_async([5], np.ones((1, 2), np.float32))
                    for _ in range(2)]
            time.sleep(0.3)
            # respawn: fresh service (new port, publish DEFERRED until
            # the restore — a survivor must never reach the empty
            # shard), fresh shard, restore
            config.set_flag("ps_generation", 1)
            svc1b = PSService(1, 2, rdv, defer_publish=True)
            ctx1b = PSContext(1, 2, svc1b)
            t1b = AsyncMatrixTable(8, 2, name="sm", send_window_ms=1.0,
                                   ctx=ctx1b)
            assert failover.rejoin(ckdir, 1, [t1b], service=svc1b) == 1
            for m in mids:
                t.wait(m)
            t.flush()
            # 3 checkpointed + 2 acked-replayed + 2 mid-outage, exactly
            assert float(t.get_rows([5])[0, 0]) == 7.0
            assert svc1b.health_payload()["gen"] == 1
        finally:
            ctx0.close()
            if ctx1b is not None:
                ctx1b.close()


class TestObservability:
    def test_merge_cluster_carries_generation(self):
        from multiverso_tpu.telemetry import aggregator
        health = {0: {"status": "ok", "addr": "h:1", "gen": 0},
                  1: {"status": "ok", "addr": "h:9", "gen": 2}}
        rec = aggregator.merge_cluster({}, health, world=2)
        assert rec["ranks"]["1"]["gen"] == 2
        assert rec["ranks"]["0"]["gen"] == 0

    def test_mvtop_renders_generation(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import mvtop
        rec = {"ts": time.time(), "world": 2, "polled": 2,
               "ranks": {"0": {"status": "ok", "gen": 0, "addr": "h:1"},
                         "1": {"status": "ok", "gen": 3,
                               "addr": "h:9"}},
               "tables": {}, "monitors": {}}
        out = mvtop.render(rec)
        assert "gen" in out.splitlines()[1]
        assert any(" 3 " in line for line in out.splitlines())

    def test_postmortem_recovery_timeline(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import postmortem
        dumps = [{
            "header": {"rank": 9, "mono_to_wall": 100.0},
            "events": [
                {"ev": "failover.detect", "mono": 1.0, "peer": 1},
                {"ev": "recv", "mono": 1.2, "msg_id": 4},
                {"ev": "failover.respawn", "mono": 2.0, "peer": 1,
                 "note": "gen=1"},
                {"ev": "failover.restore", "mono": 4.0,
                 "note": "sm v3"},
                {"ev": "failover.replay", "mono": 4.5, "peer": 1},
                {"ev": "failover.rejoin", "mono": 5.0, "peer": 1},
            ],
            "inflight": [], "stacks": [], "path": "x",
        }]
        rec = postmortem.recovery_timeline(dumps)
        assert [e["phase"] for e in rec] == [
            "detect", "respawn", "restore", "replay", "rejoin"]
        assert rec[-1]["t_plus_s"] == pytest.approx(4.0)
        report = postmortem.render_report(dumps)
        assert "recovery timeline" in report
        assert "rejoin" in report


@pytest.mark.slow
class TestChaosBench:
    """The ISSUE 7 acceptance run: SIGKILL one of two server shards
    under sustained windowed traffic; the job must recover to >= 90%
    of pre-fault throughput with zero acked ops lost and zero double
    applies (final state bit-for-bit vs the acked-op oracle)."""

    def test_sigkill_chaos_recovers_exactly_once(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        # the exactly-once ledger must hold on EVERY run; the 90%
        # throughput ratio compares rates measured ~10 s apart on a
        # shared CI box whose load drifts more than 10% by itself, so
        # that one check gets a second attempt before failing
        last = None
        for attempt in range(2):
            # --scenario=combined: just the SIGKILL(+replica-kill)
            # storm this test owns; the full five-scenario matrix has
            # its own slow test in tests/test_chaos.py
            out = subprocess.run(
                [sys.executable, os.path.join(REPO, "tools",
                                              "bench_chaos.py"), "16",
                 "--scenario=combined"],
                capture_output=True, text=True, timeout=400, env=env,
                cwd=REPO)
            res = None
            for line in out.stdout.splitlines():
                if line.startswith("RESULT "):
                    res = json.loads(line[len("RESULT "):])
            assert out.returncode == 0, (out.returncode,
                                         out.stderr[-1500:])
            assert res is not None
            assert res["ops_lost"] == 0
            assert res["ops_double_applied"] == 0
            assert res["parity_bit_for_bit"] is True
            comb = res["scenarios"]["combined"]
            phases = [e["phase"] for e in comb["supervisor"]["events"]]
            assert phases[:2] == ["detect", "respawn"]
            assert "rejoin" in phases
            last = res
            if res["recovered_to_90pct"]:
                break
        assert last["recovered_to_90pct"] is True, last
        assert last["recovery_s"] is not None
