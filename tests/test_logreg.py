"""LogisticRegression app end-to-end (ref tier-4 example-as-test, SURVEY §4:
LR MNIST convergence). Synthetic blobs stand in for MNIST (zero-egress)."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.apps.logistic_regression import LogReg, LogRegConfig
from multiverso_tpu.models import logreg as model_lib


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


def _cfg(**over):
    base = dict(input_size="20", output_size="4", objective_type="softmax",
                updater_type="sgd", minibatch_size="32",
                learning_rate="0.5", train_epoch="3", sync_frequency="1")
    base.update({k: str(v) for k, v in over.items()})
    return LogRegConfig(base)


def test_fused_path_converges():
    x, y = model_lib.synthetic_dataset(2048, 20, 4, seed=1)
    xt, yt = model_lib.synthetic_dataset(512, 20, 4, seed=2)
    lr = LogReg(_cfg())
    before = lr.test_arrays(xt, yt)
    stats = lr.train_arrays(x, y, epochs=5)
    after = lr.test_arrays(xt, yt)
    assert after > 0.85, f"accuracy {after} (before {before})"
    assert stats["samples_per_sec"] > 0


def test_ps_file_path_converges(tmp_path):
    x, y = model_lib.synthetic_dataset(1024, 10, 2, seed=3)
    train = tmp_path / "train.svm"
    with open(train, "w") as f:
        for xi, yi in zip(x, y):
            feats = " ".join(f"{j}:{v:.5f}" for j, v in enumerate(xi))
            f.write(f"{yi} {feats}\n")
    cfg = _cfg(input_size=10, output_size=2, train_file=str(train),
               test_file=str(train), train_epoch=2, sync_frequency=1)
    lr = LogReg(cfg)
    stats = lr.train_file()
    acc = lr.test_file()
    assert acc > 0.9, f"accuracy {acc}, stats {stats}"


def test_async_ps_path_converges(tmp_path):
    """The LR app on the uncoordinated async plane (-async_ps): same
    use_ps host loop, deltas land on owning shards as they arrive (ref
    src/server.cpp:36-58 default async server mode)."""
    x, y = model_lib.synthetic_dataset(1024, 10, 2, seed=6)
    train = tmp_path / "train.svm"
    with open(train, "w") as f:
        for xi, yi in zip(x, y):
            feats = " ".join(f"{j}:{v:.5f}" for j, v in enumerate(xi))
            f.write(f"{yi} {feats}\n")
    cfg = _cfg(input_size=10, output_size=2, train_file=str(train),
               test_file=str(train), train_epoch=2, sync_frequency=1,
               async_ps="true")
    lr = LogReg(cfg)
    lr.train_file()
    acc = lr.test_file()
    assert acc > 0.9, f"accuracy {acc}"
    # the fused path is functional-plane-only: typed error, not a crash
    with pytest.raises(ValueError, match="async_ps"):
        lr.train_arrays(x, y)


@pytest.mark.parametrize("updater,pipeline", [("sgd", "false"),
                                              ("sgd", "true"),
                                              ("ftrl", "false"),
                                              ("ftrl", "true")])
def test_async_sparse_lr_converges(tmp_path, updater, pipeline):
    """sparse=true + async_ps=true: hash-sharded keys with the updater
    (incl. FTRL z/n) living on the uncoordinated shard — the reference's
    flagship sparse-LR workload (ref model/ps_model.cpp:24-41,
    util/sparse_table.h, util/ftrl_sparse_table.h)."""
    x, y = model_lib.synthetic_dataset(1024, 10, 2, seed=8)
    train = tmp_path / "train.svm"
    with open(train, "w") as f:
        for xi, yi in zip(x, y):
            feats = " ".join(f"{j}:{v:.5f}" for j, v in enumerate(xi))
            f.write(f"{yi} {feats}\n")
    cfg = _cfg(input_size=10, output_size=2, train_file=str(train),
               test_file=str(train), train_epoch=3, sync_frequency=1,
               async_ps="true", sparse="true", updater_type=updater,
               pipeline=pipeline,   # "true" overlaps the sparse pulls
               learning_rate="0.5" if updater == "sgd" else "0.1")
    lr = LogReg(cfg)
    lr.train_file()
    acc = lr.test_file()
    assert acc > 0.9, f"accuracy {acc} (updater={updater})"
    from multiverso_tpu.ps.tables import AsyncSparseKVTable
    assert isinstance(lr.sparse_table, AsyncSparseKVTable)


def test_pipeline_and_sync_frequency(tmp_path):
    x, y = model_lib.synthetic_dataset(512, 10, 2, seed=4)
    train = tmp_path / "train.svm"
    with open(train, "w") as f:
        for xi, yi in zip(x, y):
            feats = " ".join(f"{j}:{v:.4f}" for j, v in enumerate(xi))
            f.write(f"{yi} {feats}\n")
    cfg = _cfg(input_size=10, output_size=2, train_file=str(train),
               sync_frequency=3, pipeline="true", train_epoch=2)
    lr = LogReg(cfg)
    stats = lr.train_file()
    assert stats["loss"] < 1.0


def test_model_save_load(tmp_path):
    x, y = model_lib.synthetic_dataset(512, 10, 2, seed=5)
    lr = LogReg(_cfg(input_size=10, output_size=2))
    lr.train_arrays(x, y, epochs=2)
    acc = lr.test_arrays(x, y)
    path = str(tmp_path / "model.bin")
    lr.save_model(path)

    lr2 = LogReg(_cfg(input_size=10, output_size=2))
    lr2.load_model(path)
    assert lr2.test_arrays(x, y) == pytest.approx(acc)


def test_dense_reader(tmp_path):
    f = tmp_path / "d.txt"
    f.write_text("1 0.5 0.25\n0 -1 2\n")
    from multiverso_tpu.io.sample_reader import SampleReader
    batches = list(SampleReader(str(f), 2, 2, fmt="dense"))
    assert len(batches) == 1
    xb, yb, keys = batches[0]
    np.testing.assert_allclose(xb, [[0.5, 0.25], [-1, 2]])
    np.testing.assert_array_equal(yb, [1, 0])
    assert keys is None


def test_libsvm_reader_keys(tmp_path):
    f = tmp_path / "s.svm"
    f.write_text("1 0:1.0 5:2.0\n0 3:1.0\n")
    from multiverso_tpu.io.sample_reader import SampleReader
    (xb, yb, keys), = list(SampleReader(str(f), 8, 4))
    assert xb.shape == (2, 8)
    np.testing.assert_array_equal(keys, [0, 3, 5])


def test_weighted_reader_scales_values(tmp_path):
    """reader_type=weight (ref reader.h:96-114): ``label:weight`` head,
    every feature value multiplied by the per-sample importance weight."""
    from multiverso_tpu.io.sample_reader import SampleReader
    f = tmp_path / "w.svm"
    f.write_text("1:2.0 0:1.0 3:0.5\n0:0.25 1:4.0\n0 2:1.0\n")  # bare=w 1
    (xb, yb, keys), = list(SampleReader(str(f), 5, 4, fmt="weight"))
    np.testing.assert_allclose(xb, [[2.0, 0, 0, 1.0, 0],
                                    [0, 1.0, 0, 0, 0],
                                    [0, 0, 1.0, 0, 0]])
    np.testing.assert_array_equal(yb, [1, 0, 0])
    np.testing.assert_array_equal(keys, [0, 1, 2, 3])


def test_weighted_dense_reader(tmp_path):
    from multiverso_tpu.io.sample_reader import SampleReader
    f = tmp_path / "wd.txt"
    f.write_text("1:3.0 1.0 2.0\n0 0.5 0.5\n")
    (xb, yb, keys), = list(SampleReader(str(f), 2, 2, fmt="weight_dense"))
    np.testing.assert_allclose(xb, [[3.0, 6.0], [0.5, 0.5]])
    assert keys is None


def test_bsparse_reader_roundtrip(tmp_path):
    """fmt=bsparse (ref reader.h:118-146): binary presence-only records
    round-trip through the writer helper; values = per-sample weight."""
    from multiverso_tpu.io.sample_reader import (SampleReader,
                                                 write_bsparse_sample)
    f = tmp_path / "b.bin"
    with open(f, "wb") as s:
        write_bsparse_sample(s, 1, [0, 4, 7], 2.5)
        write_bsparse_sample(s, 0, [2], 1.0)
        write_bsparse_sample(s, 1, [], 9.0)          # empty key set
    (xb, yb, keys), = list(SampleReader(str(f), 8, 4, fmt="bsparse"))
    np.testing.assert_allclose(xb[0], [2.5, 0, 0, 0, 2.5, 0, 0, 2.5])
    np.testing.assert_allclose(xb[1], [0, 0, 1.0, 0, 0, 0, 0, 0])
    np.testing.assert_allclose(xb[2], 0.0)
    np.testing.assert_array_equal(yb, [1, 0, 1])
    np.testing.assert_array_equal(keys, [0, 2, 4, 7])


def test_bsparse_truncated_fails_loudly(tmp_path):
    from multiverso_tpu.io.sample_reader import (SampleReader,
                                                 write_bsparse_sample)
    import io as _io
    buf = _io.BytesIO()
    write_bsparse_sample(buf, 1, [0, 1, 2], 1.0)
    f = tmp_path / "t.bin"
    f.write_bytes(buf.getvalue()[:-4])               # cut the key block
    with pytest.raises(ValueError, match="truncated"):
        list(SampleReader(str(f), 8, 4, fmt="bsparse"))


def test_unknown_format_rejected(tmp_path):
    from multiverso_tpu.io.sample_reader import SampleReader
    with pytest.raises(ValueError, match="unknown sample format"):
        SampleReader(str(tmp_path / "x"), 4, 2, fmt="protobuf")


def test_lr_app_trains_with_weighted_reader(tmp_path, capsys):
    """reader_type=weight through the full app config path (ref
    configure.cpp:70 + reader factory reader.cpp:222-237): a weighted
    file with unit weights trains exactly like the unweighted one."""
    from multiverso_tpu.apps import logistic_regression as lr_app
    from multiverso_tpu.models import logreg as lrmod
    x, y = lrmod.synthetic_dataset(256, 6, 2, seed=3)
    train = tmp_path / "w.svm"
    with open(train, "w") as f:
        for xi, yi in zip(x, y):
            feats = " ".join(f"{j}:{v:.5f}" for j, v in enumerate(xi))
            f.write(f"{yi}:1.0 {feats}\n")           # weighted head
    cfg = tmp_path / "lr.config"
    cfg.write_text(f"input_size=6\noutput_size=2\nreader_type=weight\n"
                   f"sparse=true\nminibatch_size=32\nlearning_rate=0.5\n"
                   f"train_epoch=3\ntrain_file={train}\ntest_file={train}\n")
    assert lr_app.main([str(cfg)]) == 0


def test_mnist_idx_loader(tmp_path):
    """Write tiny synthetic idx files and read them back (BASELINE config 1
    data path; real MNIST unavailable in a zero-egress environment)."""
    import gzip
    import struct

    from multiverso_tpu.io import mnist

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (5, 28, 28), dtype=np.uint8)
    labels = rng.integers(0, 10, 5, dtype=np.uint8)
    with open(tmp_path / "train-images-idx3-ubyte", "wb") as f:
        f.write(struct.pack(">I", 0x00000803))
        f.write(struct.pack(">III", 5, 28, 28))
        f.write(images.tobytes())
    # labels gzipped, to exercise the .gz path
    with gzip.open(tmp_path / "train-labels-idx1-ubyte.gz", "wb") as f:
        f.write(struct.pack(">I", 0x00000801))
        f.write(struct.pack(">I", 5))
        f.write(labels.tobytes())
    assert mnist.available(str(tmp_path))
    x, y = mnist.load(str(tmp_path), "train")
    assert x.shape == (5, 784) and x.max() <= 1.0
    np.testing.assert_array_equal(y, labels)
    x2, _ = mnist.load(str(tmp_path), "train", flatten=False)
    assert x2.shape == (5, 28, 28, 1)
