"""Send-window layer (PR 2): MSG_BATCH framing, client-side coalescing,
ordering fences, dashboard counters, and the get_rows(out=) reply
scatter — the tier-1 smoke coverage so framing/window regressions
surface without a full bench run."""

import concurrent.futures as cf

import numpy as np
import pytest

from multiverso_tpu.ps import service as svc
from multiverso_tpu.ps import wire
from multiverso_tpu.ps.tables import AsyncMatrixTable, AsyncSparseKVTable
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import config
from multiverso_tpu.utils.dashboard import Dashboard


# ---------------------------------------------------------------------- #
# MSG_BATCH framing (pure wire layer, no sockets)
# ---------------------------------------------------------------------- #
class TestBatchFraming:
    def test_pack_unpack_round_trip(self):
        rng = np.random.default_rng(3)
        subs = []
        for i in range(5):
            ids = rng.integers(0, 100, rng.integers(1, 9)).astype(np.int64)
            vals = rng.normal(size=(ids.size, 7)).astype(np.float32)
            meta = {"table": "t", "opt": AddOption()._asdict()}
            subs.append((meta, [ids, vals]))
        blobs = [wire.encode(svc.MSG_ADD_ROWS, i, m, arrs)
                 for i, (m, arrs) in enumerate(subs)]
        out = wire.unpack_batch(wire.pack_batch(blobs))
        assert len(out) == len(subs)
        for (meta, arrs), (mt, m, got) in zip(subs, out):
            assert mt == svc.MSG_ADD_ROWS
            assert m == meta
            assert len(got) == len(arrs)
            for a, b in zip(arrs, got):
                assert a.dtype == b.dtype and a.shape == b.shape
                assert np.array_equal(a, b)

    def test_round_trip_preserves_codec_payloads(self):
        """A sub-op carrying a compressed wire (1bit bits+scales) must
        come back byte-identical — the shard decodes straight from the
        batch blobs."""
        from multiverso_tpu.utils import filters
        rng = np.random.default_rng(4)
        vals = rng.normal(size=4 * 32).astype(np.float32)
        bits, scales = filters.onebit_encode_np(vals, wire.ONEBIT_BLOCK)
        ids = np.arange(4, dtype=np.int64)
        blob = wire.encode(svc.MSG_ADD_ROWS, 0,
                           {"table": "t", "wire": "1bit"},
                           [ids, bits, scales])
        [(mt, meta, arrs)] = wire.unpack_batch(wire.pack_batch([blob]))
        assert meta["wire"] == "1bit"
        assert np.array_equal(arrs[1], bits)
        assert np.array_equal(arrs[2], scales)
        dec = filters.onebit_decode_np(arrs[1], arrs[2], vals.size,
                                       wire.ONEBIT_BLOCK)
        ref = filters.onebit_decode_np(bits, scales, vals.size,
                                       wire.ONEBIT_BLOCK)
        assert np.array_equal(dec, ref)

    def test_empty_and_oversize_batches_rejected(self):
        with pytest.raises(wire.WireError):
            wire.pack_batch([])
        big = [b"x"] * (wire.MAX_BATCH_OPS + 1)
        with pytest.raises(wire.WireError):
            wire.pack_batch(big)
        arrs = [np.zeros(4, np.uint8)] * (wire.MAX_BATCH_OPS + 1)
        with pytest.raises(wire.WireError):
            wire.unpack_batch(arrs)

    def test_corrupt_sub_frame_raises(self):
        with pytest.raises(wire.WireError):
            wire.unpack_batch([np.zeros(64, np.uint8)])


# ---------------------------------------------------------------------- #
# window behavior on a live 2-rank plane
# ---------------------------------------------------------------------- #
def test_window_off_by_default(two_ranks):
    t = AsyncMatrixTable(8, 2, name="nw", ctx=two_ranks[0])
    assert t._window is None


def test_flag_installs_window(two_ranks):
    config.set_flag("batch_window_ms", 1.5)
    t = AsyncMatrixTable(8, 2, name="fw", ctx=two_ranks[0])
    assert t._window is not None
    assert t._window.window_s == pytest.approx(1.5e-3)
    # per-table override beats the flag, including turning it OFF
    t2 = AsyncMatrixTable(8, 2, name="fw2", send_window_ms=0.0,
                          ctx=two_ranks[0])
    assert t2._window is None


def test_windowed_adds_read_your_writes(two_ranks):
    """A get issued right after windowed async adds must observe them —
    the fence ships the queue before the get's own frame (per-conn
    FIFO), with NO explicit flush/wait from the caller."""
    t = AsyncMatrixTable(16, 3, name="ryw", send_window_ms=60_000.0,
                         ctx=two_ranks[0])
    AsyncMatrixTable(16, 3, name="ryw", ctx=two_ranks[1])
    ones = np.ones((1, 3), np.float32)
    for row in (1, 9, 9, 15):   # both shards, duplicates included
        t.add_rows_async([row], ones)
    got = t.get_rows(np.arange(16))
    expect = np.zeros((16, 3), np.float32)
    for row in (1, 9, 9, 15):
        expect[row] += 1.0
    assert np.array_equal(got, expect)


def test_window_counters_surface_in_dashboard(two_ranks):
    """The zoo shutdown report prints every registered monitor — the
    window's three counters must exist (and tick) alongside the PR-1
    ``.get.cached`` counter."""
    t = AsyncMatrixTable(8, 2, name="wc", send_window_ms=60_000.0,
                         ctx=two_ranks[0])
    AsyncMatrixTable(8, 2, name="wc", ctx=two_ranks[1])
    names = [f"table[wc].add_rows.{k}"
             for k in ("windowed", "flushes", "merged_rows")]
    snap = Dashboard.snapshot()
    assert all(n in snap for n in names)   # registered eagerly
    t.add_rows_async([2], np.ones((1, 2), np.float32))
    t.add_rows_async([3], np.ones((1, 2), np.float32))   # same owner: merges
    t.flush()
    snap = Dashboard.snapshot()
    assert snap["table[wc].add_rows.windowed"].count == 2
    assert snap["table[wc].add_rows.flushes"].count >= 1
    # the two disjoint single-row adds merged into one frame
    assert snap["table[wc].add_rows.merged_rows"].count >= 1


def test_window_op_bound_ships_inline(two_ranks):
    """Hitting batch_window_ops flushes the owner's queue immediately —
    no timer involved (window_ms set huge)."""
    config.set_flag("batch_window_ops", 4)
    t = AsyncMatrixTable(8, 2, name="ob", send_window_ms=60_000.0,
                         ctx=two_ranks[0])
    AsyncMatrixTable(8, 2, name="ob", ctx=two_ranks[1])
    flushes = Dashboard.get("table[ob].add_rows.flushes")
    for row in range(4):   # rank 0 owns rows [0, 4)
        t.add_rows_async([row], np.ones((1, 2), np.float32))
    assert flushes.count == 1
    t.flush()


def test_batch_frames_carry_adds_only(two_ranks):
    """A MSG_BATCH with a non-add sub-op is a framing error: the shard
    rejects it with a typed PSError reply."""
    AsyncMatrixTable(8, 2, name="bo", ctx=two_ranks[0])
    AsyncMatrixTable(8, 2, name="bo", ctx=two_ranks[1])
    blob = wire.encode(svc.MSG_GET_ROWS, 0, {"table": "bo"},
                       [np.arange(2, dtype=np.int64)])
    fut = two_ranks[0].service.request(
        1, svc.MSG_BATCH, {"table": "bo"}, wire.pack_batch([blob]))
    with pytest.raises(svc.PSError):
        svc.await_reply(fut, 20.0, "batch")


def test_kv_window_parity(two_ranks):
    """The hash-sharded plane windows too: keyed adds coalesce per owner
    and land bit-for-bit identical to the window-off table."""
    rng = np.random.default_rng(11)
    tw = AsyncSparseKVTable(3, name="kvw", send_window_ms=60_000.0,
                            ctx=two_ranks[0])
    AsyncSparseKVTable(3, name="kvw", ctx=two_ranks[1])
    tr = AsyncSparseKVTable(3, name="kvr", ctx=two_ranks[0])
    AsyncSparseKVTable(3, name="kvr", ctx=two_ranks[1])
    keys = np.unique(rng.integers(0, 5000, 40))
    for i in range(30):
        k = rng.choice(keys, rng.integers(1, 6), replace=False)
        v = rng.normal(size=(k.size, 3)).astype(np.float32)
        tw.add_rows_async(k, v)
        tr.add_rows_async(k, v)
        if i % 9 == 0:
            assert np.array_equal(tw.get_rows(keys), tr.get_rows(keys))
    tw.flush()
    tr.flush()
    assert np.array_equal(tw.get_rows(keys), tr.get_rows(keys))


def test_wait_completes_windowed_add(two_ranks):
    """wait(msg_id) on a still-queued windowed add fences the window and
    blocks until the ack — the placeholder futures are real futures."""
    t = AsyncMatrixTable(8, 2, name="ww", send_window_ms=60_000.0,
                         ctx=two_ranks[0])
    AsyncMatrixTable(8, 2, name="ww", ctx=two_ranks[1])
    mid = t.add_rows_async([5], np.ones((1, 2), np.float32))
    t.wait(mid)   # must not hang; add durably applied after
    got = t.get_rows([5])
    assert got[0, 0] == 1.0


def test_batch_partial_failure_reports_per_subop(two_ranks):
    """A sub-op that fails mid-batch fails ONLY its own placeholder
    future (via the reply meta's "failed" indices): deltas that durably
    applied are never reported lost — a blanket error would invite a
    retry that double-applies them."""
    t = AsyncMatrixTable(8, 2, name="pf", send_window_ms=60_000.0,
                         ctx=two_ranks[0])
    t1 = AsyncMatrixTable(8, 2, name="pf", ctx=two_ranks[1])
    shard = t1._shard   # rank 1 owns rows [4, 8)
    orig = type(shard)._apply_rows

    def boom(self, local, vals, opt):
        if (5 - self.lo) in np.asarray(local):
            raise RuntimeError("synthetic apply failure")
        return orig(self, local, vals, opt)

    shard._apply_rows = boom.__get__(shard)
    ones = np.ones((1, 2), np.float32)
    # three sub-ops, forced into separate waves by the row-4 conflicts:
    # [4] applies, [4, 5] fails (synthetic), [4] applies
    m_ok1 = t.add_rows_async([4], ones)
    m_bad = t.add_rows_async([4, 5], np.ones((2, 2), np.float32))
    m_ok2 = t.add_rows_async([4], ones)
    t.wait(m_ok1)
    t.wait(m_ok2)
    with pytest.raises(svc.PSError):
        t.wait(m_bad)
    shard._apply_rows = orig.__get__(shard)
    # the two successful adds landed exactly once each; the failed
    # sub-op's rows are untouched
    got = t.get_rows([4, 5])
    assert np.array_equal(
        got, np.array([[2.0, 2.0], [0.0, 0.0]], np.float32)), got


def test_windowed_add_failure_surfaces_at_flush(two_ranks):
    """An unreachable owner fails the windowed add's placeholder future;
    flush() raises it like any other lost delta."""
    t = AsyncMatrixTable(8, 2, name="wf", send_window_ms=60_000.0,
                         ctx=two_ranks[0])
    AsyncMatrixTable(8, 2, name="wf", ctx=two_ranks[1])
    config.set_flag("ps_timeout", 4.0)
    config.set_flag("ps_connect_timeout", 4.0)
    two_ranks[1].close()   # rank 1 (rows [4, 8)) goes away
    t.add_rows_async([6], np.ones((1, 2), np.float32))
    with pytest.raises((svc.PSPeerError, cf.TimeoutError)):
        t.flush()


def test_window_ops_knob_clamped_to_wire_bound(two_ranks):
    """batch_window_ops set past wire.MAX_BATCH_OPS must not make
    windows unsendable: the knob clamps, and an over-full window would
    chunk into multiple frames rather than fail every queued delta."""
    config.set_flag("batch_window_ops", wire.MAX_BATCH_OPS * 2)
    t = AsyncMatrixTable(8, 2, name="clamp", send_window_ms=60_000.0,
                         ctx=two_ranks[0])
    AsyncMatrixTable(8, 2, name="clamp", ctx=two_ranks[1])
    assert t._window.max_ops == wire.MAX_BATCH_OPS
    # unmergeable sub-ops (same row repeatedly): a burst still applies
    for _ in range(40):
        t.add_rows_async([0], np.ones((1, 2), np.float32))
    t.flush()
    assert t.get_rows([0])[0, 0] == 40.0


def test_windowed_add_owns_values_buffer(two_ranks):
    """A training loop that reuses one gradient scratch buffer between
    windowed adds must not corrupt queued deltas: the window copies
    anything it defers (the single-owner fast path used to queue a
    zero-copy view of the caller's array)."""
    t = AsyncMatrixTable(8, 2, name="alias", send_window_ms=60_000.0,
                         ctx=two_ranks[0])
    AsyncMatrixTable(8, 2, name="alias", ctx=two_ranks[1])
    buf = np.ones((1, 2), np.float32)
    t.add_rows_async([1], buf)
    buf[:] = 100.0            # caller reuses the scratch buffer
    t.add_rows_async([2], buf)
    buf[:] = -5.0
    got = t.get_rows([1, 2])
    assert np.array_equal(
        got, np.array([[1.0, 1.0], [100.0, 100.0]], np.float32)), got


def test_flusher_thread_exits_with_table(two_ranks, monkeypatch):
    """The window's daemon flusher holds its table only via weakref: once
    the table is garbage, the thread exits at its next bounded wakeup
    instead of pinning the table (conns, monitors) for process life."""
    import gc
    import time as _time

    from multiverso_tpu.ps import tables as tables_mod
    monkeypatch.setattr(tables_mod._SendWindow, "_IDLE_WAIT_S", 0.05)
    t = AsyncMatrixTable(8, 2, name="thx", send_window_ms=60_000.0,
                         ctx=two_ranks[0])
    AsyncMatrixTable(8, 2, name="thx", ctx=two_ranks[1])
    t.add_rows_async([1], np.ones((1, 2), np.float32))
    t.flush()
    th = t._window._thread
    assert th is not None and th.is_alive()
    del t
    gc.collect()
    deadline = _time.monotonic() + 5.0
    while th.is_alive() and _time.monotonic() < deadline:
        _time.sleep(0.05)
    assert not th.is_alive()


# ---------------------------------------------------------------------- #
# get_rows(out=) reply scatter (PR-2 satellite)
# ---------------------------------------------------------------------- #
class TestGetRowsOut:
    def test_out_buffer_is_filled_and_returned(self, two_ranks):
        t = AsyncMatrixTable(10, 4, name="go", ctx=two_ranks[0])
        AsyncMatrixTable(10, 4, name="go", ctx=two_ranks[1])
        t.add_rows(np.arange(10), np.arange(40, dtype=np.float32)
                   .reshape(10, 4))
        ids = np.array([1, 4, 7, 9])
        buf = np.full((4, 4), -1.0, np.float32)
        got = t.get_rows(ids, out=buf)
        assert got is buf   # replies scattered into the CALLER's buffer
        ref = t.get_rows(ids)
        assert np.array_equal(buf, ref)

    def test_out_with_duplicate_ids(self, two_ranks):
        t = AsyncMatrixTable(10, 4, name="gd", ctx=two_ranks[0])
        AsyncMatrixTable(10, 4, name="gd", ctx=two_ranks[1])
        t.add_rows(np.arange(10), np.arange(40, dtype=np.float32)
                   .reshape(10, 4))
        ids = np.array([3, 8, 3, 1])
        buf = np.empty((4, 4), np.float32)
        got = t.get_rows(ids, out=buf)
        assert got is buf
        assert np.array_equal(buf, t.get_rows(ids))

    def test_mismatched_out_still_correct(self, two_ranks):
        """A non-contiguous / wrong-dtype out cannot take the scatter
        directly; the fallback copy path must still fill it."""
        t = AsyncMatrixTable(10, 4, name="gm", ctx=two_ranks[0])
        AsyncMatrixTable(10, 4, name="gm", ctx=two_ranks[1])
        t.add_rows(np.arange(10), np.arange(40, dtype=np.float32)
                   .reshape(10, 4))
        ids = np.array([0, 5, 9])
        wide = np.empty((3, 8), np.float32)
        buf = wide[:, ::2]   # non-contiguous view
        got = t.get_rows(ids, out=buf)
        assert got is buf
        assert np.array_equal(np.ascontiguousarray(buf), t.get_rows(ids))


# ---------------------------------------------------------------------- #
# multi-owner fan-out (ISSUE 15, ps/spmd.py): windowed adds coalesced
# into one super-frame per destination process, and exactly-once replay
# surviving a routed shard's kill/respawn — across 4 shards, on BOTH
# wire planes, bit-identical to the 1-shard oracle
# ---------------------------------------------------------------------- #
class TestMultiOwnerFanout:
    ROWS, DIM = 64, 4

    def _stream(self):
        rng = np.random.default_rng(11)
        out = []
        for _ in range(10):
            k = int(rng.integers(3, self.ROWS // 2))
            ids = np.sort(rng.choice(self.ROWS, size=k, replace=False))
            out.append((ids,
                        rng.normal(size=(k, self.DIM))
                        .astype(np.float32)))
        return out

    def _oracle(self, tmp_path):
        config.set_flag("ps_fanout", False)
        rdv = svc.FileRendezvous(str(tmp_path / "orc"))
        ctx = svc.PSContext(0, 1, svc.PSService(0, 1, rdv))
        t = AsyncMatrixTable(self.ROWS, self.DIM, name="fw_o",
                             send_window_ms=2.0, ctx=ctx)
        for ids, vals in self._stream():
            t.add_rows_async(ids, vals)
        t.flush()
        want = t.get_rows(np.arange(self.ROWS))
        ctx.close()
        return want

    @pytest.mark.parametrize("plane", ["native", "python"])
    def test_windowed_fanout_parity_four_shards(self, tmp_path, plane):
        want = self._oracle(tmp_path)
        config.set_flag("ps_native", plane == "native")
        config.set_flag("ps_fanout", True)
        rdv = svc.FileRendezvous(str(tmp_path / "w"))
        ctxs = [svc.PSContext(r, 4, svc.PSService(r, 4, rdv))
                for r in range(4)]
        tabs = [AsyncMatrixTable(self.ROWS, self.DIM, name="fw_t",
                                 send_window_ms=2.0, ctx=c)
                for c in ctxs]
        t = tabs[0]
        for ids, vals in self._stream():
            t.add_rows_async(ids, vals)
        t.flush()
        flushes = Dashboard.get("table[fw_t].add_rows.flushes")
        assert flushes.snapshot().count > 0
        got = tabs[2].get_rows(np.arange(self.ROWS))
        np.testing.assert_array_equal(got, want)
        for c in ctxs:
            c.close()

    @pytest.mark.parametrize("plane", ["native", "python"])
    def test_replay_after_kill_four_shards(self, tmp_path, plane):
        """Exactly-once replay over the ROUTED plane: kill one of four
        colocated shards mid-stream, respawn + restore it, and the
        final table must be bit-identical to the 1-shard oracle — no
        acked op lost, no frame double-applied."""
        import time as _time

        from multiverso_tpu.ps import failover

        want = self._oracle(tmp_path)
        config.set_flag("ps_native", plane == "native")
        config.set_flag("ps_fanout", True)
        config.set_flag("ps_replay", True)
        config.set_flag("ps_timeout", 30.0)
        config.set_flag("ps_connect_timeout", 5.0)
        config.set_flag("ps_reconnect_backoff", 0.2)
        config.set_flag("ps_replay_backoff", 0.05)
        rdv = svc.FileRendezvous(str(tmp_path / "k"))
        ckdir = str(tmp_path / "ck")
        ctxs = [svc.PSContext(r, 4, svc.PSService(r, 4, rdv))
                for r in range(4)]
        tabs = [AsyncMatrixTable(self.ROWS, self.DIM, name="fk_t",
                                 send_window_ms=1.0, ctx=c)
                for c in ctxs]
        ctx3b = None
        try:
            t = tabs[0]
            stream = self._stream()
            # checkpoint rank 3's EMPTY shard so the respawn has a
            # restorable base (seq channels start empty; replay covers
            # everything after)
            ck = failover.ShardCheckpointer(ckdir, 3, [tabs[3]],
                                            interval_s=999)
            ck.checkpoint_now()
            for ids, vals in stream[:5]:
                t.add_rows_async(ids, vals)
            t.flush()
            ctxs[3].service.close()   # the "crash" of a routed shard
            # mid-outage traffic: frames to rank 3 arm for replay
            for ids, vals in stream[5:]:
                t.add_rows_async(ids, vals)
            _time.sleep(0.3)
            config.set_flag("ps_generation", 1)
            svc3b = svc.PSService(3, 4, rdv, defer_publish=True)
            ctx3b = svc.PSContext(3, 4, svc3b)
            t3b = AsyncMatrixTable(self.ROWS, self.DIM, name="fk_t",
                                   send_window_ms=1.0, ctx=ctx3b)
            assert failover.rejoin(ckdir, 3, [t3b],
                                   service=svc3b) == 1
            t.flush()
            # every pre-kill acked frame for rank 3 REPLAYS (its
            # checkpoint was empty) and every mid-outage frame lands:
            # final state must be exactly the oracle's
            deadline = _time.monotonic() + 20.0
            got = None
            while _time.monotonic() < deadline:
                got = tabs[1].get_rows(np.arange(self.ROWS))
                if np.array_equal(got, want):
                    break
                _time.sleep(0.2)
            np.testing.assert_array_equal(got, want)
        finally:
            for c in [ctxs[0], ctxs[1], ctxs[2]]:
                c.close()
            if ctx3b is not None:
                ctx3b.close()
