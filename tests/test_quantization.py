"""Weight-only int8 quantization (ops/quantization.py) + quantized decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models import transformer as tfm
from multiverso_tpu.ops import quantization as qz


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


class TestQuantize:
    def test_roundtrip_error_bound(self):
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(0, 3.0, (64, 32)), jnp.float32)
        t = qz.quantize(w, keep_axes=(-1,))
        assert t.q.dtype == jnp.int8
        assert t.scale.shape == (1, 32)
        err = jnp.abs(qz.dequantize(t) - w)
        assert float((err <= t.scale / 2 + 1e-6).all())

    def test_stacked_per_layer_scales(self):
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=(3, 16, 8)), jnp.float32)
        t = qz.quantize(w, keep_axes=(0, -1))
        assert t.scale.shape == (3, 1, 8)
        np.testing.assert_allclose(np.asarray(qz.dequantize(t)),
                                   np.asarray(w), atol=0.05)

    def test_lm_tree_quantizes_matrices_keeps_norms(self):
        cfg = tfm.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                    num_layers=2, max_seq=8)
        params = tfm.init_params(cfg, seed=0)
        qp = qz.quantize_lm_params(params)
        assert isinstance(qp["embed"], qz.QuantizedTensor)
        assert isinstance(qp["layers"]["wqkv"], qz.QuantizedTensor)
        assert not isinstance(qp["layers"]["ln1"], qz.QuantizedTensor)
        assert qp["layers"]["wqkv"].scale.shape == (2, 1, 48)


class TestQuantizedDecode:
    def test_trained_lm_generates_identically_after_quantization(self):
        mv.init()
        cfg = tfm.TransformerConfig(vocab_size=16, dim=32, num_heads=4,
                                    num_layers=2, max_seq=32, attn="local")
        params = tfm.init_params(cfg, seed=0)
        seq = np.tile(np.arange(8), 5)[:33]
        tok = jnp.asarray(np.stack([seq[:-1]] * 4), jnp.int32)
        tgt = jnp.asarray(np.stack([seq[1:]] * 4), jnp.int32)
        step = jax.jit(tfm.make_train_step(cfg, 0.5))
        for _ in range(150):
            params, loss = step(params, tok, tgt)
        prompt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
        full = tfm.generate(params, prompt, cfg, max_new_tokens=12)
        quant = tfm.generate(qz.quantize_lm_params(params), prompt, cfg,
                             max_new_tokens=12)
        # a confidently-trained model must survive int8: same continuation
        np.testing.assert_array_equal(np.asarray(full), np.asarray(quant))
        expect = [(i % 8) for i in range(16)]
        assert np.asarray(quant)[0].tolist() == expect

    def test_bf16_quantized_decode_runs(self):
        mv.init()
        cfg = tfm.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                    num_layers=1, max_seq=8, attn="local",
                                    dtype=jnp.bfloat16)
        qp = qz.quantize_lm_params(tfm.init_params(cfg, seed=2))
        out = tfm.generate(qp, jnp.zeros((1, 2), jnp.int32), cfg,
                           max_new_tokens=3)
        arr = np.asarray(out)
        assert arr.shape == (1, 5) and arr.max() < 32 and arr.min() >= 0

    def test_generate_rejects_wrong_scale_layout(self):
        mv.init()
        cfg = tfm.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                    num_layers=1, max_seq=8, attn="local")
        params = tfm.init_params(cfg, seed=3)
        bad = dict(params)
        bad["embed"] = qz.quantize(params["embed"])  # per-column: wrong
        with pytest.raises(ValueError, match="per-row"):
            tfm.generate(bad, jnp.zeros((1, 2), jnp.int32), cfg, 2)
