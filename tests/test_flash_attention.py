"""Flash attention Pallas kernel (ops/attention_kernels.py) vs the dense
oracle, in interpreter mode on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.ops.attention_kernels import flash_attention
from multiverso_tpu.parallel.ring import reference_attention


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


def _qkv(b=2, h=2, s=256, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, h, s, d)), dtype)
                 for _ in range(3))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference_multi_block(self, causal):
        q, k, v = _qkv(s=256, d=64)  # 2 q blocks x 2 k blocks
        expect = reference_attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_small_sequence_clamps_blocks(self, ):
        q, k, v = _qkv(s=32, d=16, seed=1)
        expect = reference_attention(q, k, v, causal=True)
        got = flash_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_uneven_blocks(self):
        # 4 k blocks per q block exercises the running-softmax carry
        q, k, v = _qkv(s=512, d=32, seed=2)
        expect = reference_attention(q, k, v, causal=True)
        got = flash_attention(q, k, v, True, 256, 128)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_bfloat16(self):
        q, k, v = _qkv(s=128, d=64, seed=3, dtype=jnp.bfloat16)
        expect = reference_attention(q, k, v, causal=True)
        got = flash_attention(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(expect, np.float32),
            rtol=5e-2, atol=5e-2)

    def test_grad_matches_reference(self):
        q, k, v = _qkv(s=128, d=32, seed=4)

        def loss_flash(q, k, v):
            return jnp.mean(flash_attention(q, k, v, True) ** 2)

        def loss_ref(q, k, v):
            return jnp.mean(reference_attention(q, k, v, causal=True) ** 2)

        with jax.default_matmul_precision("float32"):
            gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_matches_reference_multi_block(self, causal):
        """The Pallas backward's cross-block accumulation (dq over k
        blocks, dk/dv over q blocks, causal block skipping) against the
        XLA reference vjp."""
        q, k, v = _qkv(s=256, d=32, seed=7)
        g = jnp.asarray(
            np.random.default_rng(8).normal(size=q.shape), q.dtype)

        def run(fn):
            out, vjp = jax.vjp(
                lambda q, k, v: fn(q, k, v), q, k, v)
            return (out,) + vjp(g)

        with jax.default_matmul_precision("float32"):
            ff = run(lambda q, k, v: flash_attention(
                q, k, v, causal, 64, 64))
            rr = run(lambda q, k, v: reference_attention(
                q, k, v, causal=causal))
        for a, b in zip(ff, rr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=3e-4)

    def test_rejects_indivisible_seq(self):
        q, k, v = _qkv(s=192, d=32, seed=5)
        with pytest.raises(ValueError, match="not divisible"):
            flash_attention(q, k, v, False)

    def test_transformer_flash_matches_local(self):
        from multiverso_tpu.models import transformer as tfm
        mv.init()
        base = tfm.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                     num_layers=2, max_seq=32, attn="local")
        params = tfm.init_params(base, seed=0)
        rng = np.random.default_rng(6)
        tok = jnp.asarray(rng.integers(0, 64, (2, 32)), jnp.int32)
        tgt = jnp.asarray(rng.integers(0, 64, (2, 32)), jnp.int32)
        with jax.default_matmul_precision("float32"):
            expect = tfm.loss_fn(params, tok, tgt, base)
            got = tfm.loss_fn(params, tok, tgt, base._replace(attn="flash"))
        np.testing.assert_allclose(float(got), float(expect),
                                   rtol=1e-4, atol=1e-5)

    def test_transformer_flash_dp_tp_mesh(self):
        from jax.sharding import Mesh

        from multiverso_tpu.models import transformer as tfm
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "tp"))
        mv.init(mesh=mesh)
        base = tfm.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                     num_layers=2, max_seq=16, attn="local")
        params = tfm.init_params(base, seed=1)
        rng = np.random.default_rng(7)
        toks = rng.integers(0, 64, (4, 17)).astype(np.int32)
        with jax.default_matmul_precision("float32"):
            expect = tfm.loss_fn(params, jnp.asarray(toks[:, :-1]),
                                 jnp.asarray(toks[:, 1:]), base)
        cfg = base._replace(attn="flash", batch_axis="dp", tp_axis="tp")
        sharded = tfm.shard_params_tp(params, cfg, mesh)
        tok = tfm.shard_batch(toks[:, :-1], cfg, mesh)
        tgt = tfm.shard_batch(toks[:, 1:], cfg, mesh)
        with jax.default_matmul_precision("float32"):
            got = jax.jit(lambda p, a, b: tfm.loss_fn(p, a, b, cfg))(
                sharded, tok, tgt)
        np.testing.assert_allclose(float(got), float(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_transformer_flash_rejects_seq_axis(self):
        from multiverso_tpu.models import transformer as tfm
        mv.init()
        cfg = tfm.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                    num_layers=1, max_seq=8, attn="flash",
                                    seq_axis="mv")
        with pytest.raises(ValueError, match="flash"):
            tfm.forward(tfm.init_params(cfg), jnp.zeros((1, 8), jnp.int32),
                        cfg)
