"""ops package surface: the surviving hand-written kernels.

The Pallas embedding gather/scatter kernels were REMOVED (r4): XLA's
native gather/scatter measured faster at every bucket size on-chip, so
the package no longer carries them. The winning kernels — flash
attention forward AND backward — are covered in depth by
tests/test_flash_attention.py; this file pins the public ops surface.
"""

import multiverso_tpu.ops as ops


def test_ops_surface():
    assert set(ops.__all__) == {"QuantizedTensor", "dequantize",
                                "flash_attention", "quantize",
                                "quantize_lm_params"}
    for name in ops.__all__:
        assert hasattr(ops, name)


def test_no_embedding_kernels():
    """The measured-slower kernels must not silently return."""
    import importlib
    import pytest
    with pytest.raises(ImportError):
        importlib.import_module("multiverso_tpu.ops.embedding_kernels")
