"""Pallas embedding kernels vs XLA reference (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from multiverso_tpu.ops import embedding_kernels as ek


class TestEmbeddingKernels:
    def _data(self, v=64, d=128, b=16, seed=0):
        rng = np.random.default_rng(seed)
        table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        ids = jnp.asarray(rng.choice(v, size=b, replace=False)
                          .astype(np.int32))
        deltas = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
        return table, ids, deltas

    def test_gather_matches_xla(self):
        table, ids, _ = self._data()
        out = ek.embedding_gather(table, ids, interpret=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(ek.gather_reference(table, ids)))

    def test_scatter_add_matches_xla(self):
        table, ids, deltas = self._data()
        expect = ek.scatter_add_reference(table, ids, deltas)
        out = ek.embedding_scatter_add(table.copy(), ids, deltas,
                                       interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=1e-6)

    def test_pallas_supported_gate(self):
        assert not ek.pallas_supported(100)  # not lane-aligned
