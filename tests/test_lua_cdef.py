"""Mechanical validation of the Lua FFI shim against the C ABI.

No LuaJIT exists in this image (the reference's runnable Lua tier,
binding/lua/test.lua:1-79, cannot execute here), so the next-best
guarantee is structural: every function the Lua cdef declares must be an
exported symbol of libmultiverso.so with the same name, and every MV_*
export of the C ABI must appear in the cdef — the shim cannot silently
drift from the surface the C driver (native/mv_capi_test.c) proves.
"""

import ctypes
import os
import re
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LUA = os.path.join(_REPO, "examples", "lua", "multiverso.lua")
_SO = os.path.join(_REPO, "multiverso_tpu", "native", "libmultiverso.so")
_CAPI = os.path.join(_REPO, "multiverso_tpu", "native", "mv_capi.cpp")


def _cdef_functions():
    src = open(_LUA).read()
    m = re.search(r"ffi\.cdef\[\[(.*?)\]\]", src, re.DOTALL)
    assert m, "multiverso.lua has no ffi.cdef block"
    body = m.group(1)
    # function declarations: <ret> NAME(args);  (skip typedefs)
    names = re.findall(r"\b(MV_\w+)\s*\(", body)
    assert names, "cdef block declares no MV_ functions"
    return set(names)


def _exported_symbols():
    if not os.path.exists(_SO):
        pytest.skip("libmultiverso.so not built (make -C native capi)")
    out = subprocess.run(["nm", "-D", "--defined-only", _SO],
                         capture_output=True, text=True, check=True)
    return {m.group(1) for m in
            re.finditer(r"\sT\s+(MV_\w+)", out.stdout)}


def _capi_source_functions():
    src = open(_CAPI).read()
    # definitions inside the extern "C" surface: `void MV_Foo(...)` etc.
    return set(re.findall(r"^\s*(?:void|int|float|double)\s+(MV_\w+)\s*\(",
                          src, re.MULTILINE))


def test_cdef_matches_exported_symbols():
    cdef = _cdef_functions()
    exported = _exported_symbols()
    missing = cdef - exported
    assert not missing, (f"Lua cdef declares symbols the .so does not "
                         f"export: {sorted(missing)}")


def test_capi_surface_fully_mirrored():
    """Every MV_* function in mv_capi.cpp appears in the Lua cdef — a new
    C ABI entry point cannot be added without extending the shim."""
    cdef = _cdef_functions()
    source = _capi_source_functions()
    unmirrored = source - cdef
    assert not unmirrored, (f"C ABI functions missing from the Lua cdef: "
                            f"{sorted(unmirrored)}")


def test_generated_mirrors_are_current():
    """The Lua cdef and the C driver's declaration block are GENERATED
    from mv_capi.cpp (tools/gen_capi_surface.py) — a new C-ABI entry
    point cannot be added without this test demanding a regeneration
    (the round-4 failure mode: entries added by hand in one place)."""
    r = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "tools", "gen_capi_surface.py"), "--check"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


def test_capi_test_driver_invokes_every_symbol():
    """Declaration parity is not enough — every MV_* export must actually
    be CALLED by the C driver (the reference's standard: test.lua:1-79
    exercises its full surface). Parses the driver body below the
    generated declaration block for call sites."""
    src = open(os.path.join(_REPO, "multiverso_tpu", "native",
                            "mv_capi_test.c")).read()
    body = src[src.index("END generated ABI declarations"):]
    called = set(re.findall(r"\b(MV_\w+)\s*\(", body))
    missing = _capi_source_functions() - called
    assert not missing, (f"C ABI functions never invoked by "
                         f"mv_capi_test.c: {sorted(missing)}")


def _normalize_sig(decl: str) -> str:
    """``ret name(args)`` -> ``ret(type,type,...)`` with parameter names
    stripped (``int row_ids[]`` -> ``int[]``, ``float* data`` ->
    ``float*``) so the cdef and the C++ source compare by TYPES."""
    decl = re.sub(r"/\*.*?\*/", " ", decl)     # comment-style param names
    decl = " ".join(decl.split())
    m = re.match(r"(\w[\w\s\*]*?)\s+MV_\w+\s*\((.*)\)\s*;?$", decl)
    assert m, decl
    ret, args = m.group(1), m.group(2).strip()
    out = []
    for a in (args.split(",") if args else []):
        a = a.strip()
        arr = "[]" if "[" in a else ""
        a = re.sub(r"\[[^\]]*\]", "", a)
        toks = a.replace("*", " * ").split()
        if len(toks) > 1 and re.fullmatch(r"\w+", toks[-1]) \
                and toks[-1] not in ("int", "float", "void", "char"):
            toks = toks[:-1]          # drop the parameter name
        out.append("".join(toks) + arr)
    return f"{ret}({','.join(out)})"


def test_cdef_signatures_match_capi_source():
    """Name parity is not enough — a drifted ARGUMENT LIST would corrupt
    the FFI call frame silently. Every declaration in the Lua cdef must
    match the extern "C" definition in mv_capi.cpp type-for-type."""
    cdef_src = re.search(r"ffi\.cdef\[\[(.*?)\]\]", open(_LUA).read(),
                         re.DOTALL).group(1)
    cpp_src = open(_CAPI).read()
    cdef_sigs = {re.search(r"(MV_\w+)", d).group(1): _normalize_sig(d)
                 for d in re.findall(r"[^;{}]*\bMV_\w+\s*\([^)]*\)\s*;",
                                     cdef_src)}
    cpp_sigs = {}
    for d in re.findall(
            r"^\s*(?:void|int|float|double)[\w\s\*]*?\bMV_\w+\s*\([^)]*\)",
            cpp_src, re.MULTILINE):
        name = re.search(r"(MV_\w+)", d).group(1)
        cpp_sigs[name] = _normalize_sig(d)
    for name, sig in cdef_sigs.items():
        assert name in cpp_sigs, f"{name} not defined in mv_capi.cpp"
        assert sig == cpp_sigs[name], (
            f"{name}: cdef {sig!r} != C++ {cpp_sigs[name]!r}")


def test_cdef_symbols_resolve_through_dynamic_loader():
    """Every cdef name resolves through an actual dlopen/dlsym — the load
    path LuaJIT's ffi.load would take (nm reads the symbol table
    statically; this catches a library that can't be dlopen'd at all).
    NOTE: C has no runtime arity/type info, so signatures themselves are
    covered by the compiled C driver (native/mv_capi_test.c), not here."""
    if not os.path.exists(_SO):
        pytest.skip("libmultiverso.so not built")
    lib = ctypes.CDLL(_SO)
    for name in _cdef_functions():
        assert hasattr(lib, name), name
