"""DLRM recommender family (models/dlrm.py): PS-table training on the
8-device mesh — convergence on planted CTR structure, duplicate-id
gradient accumulation, updater-state evolution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models import dlrm
from multiverso_tpu.updaters import AddOption


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


def _setup(cfg, seed=0):
    mv.init()
    emb = mv.MatrixTable(dlrm.total_rows(cfg), cfg.embed_dim,
                         updater="adagrad", seed=seed, init_scale=0.05,
                         name="dlrm_emb")
    flat, meta = dlrm.flatten_mlp(dlrm.init_mlp_params(cfg, seed))
    mlp = mv.ArrayTable(flat.size, updater="adagrad", init=flat,
                        name="dlrm_mlp")
    return emb, mlp, meta


class TestDLRM:
    def test_learns_planted_structure(self):
        cfg = dlrm.DLRMConfig(vocab_sizes=(40, 40, 20), embed_dim=8,
                              dense_dim=4, bottom_mlp=(16, 8),
                              top_mlp=(16, 1))
        emb, mlp, meta = _setup(cfg)
        cat, dense, labels = dlrm.synthetic_ctr(cfg, 4096, seed=1)
        opt = AddOption(learning_rate=0.2, rho=0.1)
        step = jax.jit(dlrm.make_train_step(cfg, emb, mlp, meta,
                                            emb_opt=opt, mlp_opt=opt),
                       donate_argnums=(0, 1))
        # donated chain starts from copies so the live table buffers
        # survive (same pattern as the word2vec fused path)
        es = jax.tree.map(jnp.copy, emb.state)
        ms = jax.tree.map(jnp.copy, mlp.state)
        bs = 256
        first = last = None
        for epoch in range(12):
            ep_losses = []
            for i in range(0, len(labels), bs):
                es, ms, loss = step(es, ms,
                                    jnp.asarray(cat[i:i + bs]),
                                    jnp.asarray(dense[i:i + bs]),
                                    jnp.asarray(labels[i:i + bs]))
                ep_losses.append(float(loss))
            if first is None:
                first = np.mean(ep_losses)
            last = np.mean(ep_losses)
        assert last < first - 0.05, (first, last)
        emb.adopt(es)
        mlp.adopt(ms)
        # post-training accuracy beats the base rate
        flat_size = dlrm.flatten_mlp(dlrm.init_mlp_params(cfg))[0].size
        mlp_params = dlrm.unflatten_mlp(jnp.asarray(mlp.get()[:flat_size]),
                                        meta)
        ids = cat + dlrm.field_offsets(cfg)[None, :]
        rows = emb.get_rows(ids.reshape(-1)).reshape(
            len(labels), len(cfg.vocab_sizes), cfg.embed_dim)
        logits = dlrm.forward(mlp_params, jnp.asarray(rows),
                              jnp.asarray(dense), cfg)
        acc = float(np.mean((np.asarray(logits) > 0) == (labels > 0.5)))
        base = max(labels.mean(), 1 - labels.mean())
        assert acc > base + 0.03, (acc, base)

    def test_duplicate_ids_accumulate(self):
        cfg = dlrm.DLRMConfig(vocab_sizes=(8, 8), embed_dim=4, dense_dim=2,
                              bottom_mlp=(4,), top_mlp=(4, 1))
        mv.init()
        # plain += updater: the expected update is exactly before + sum of
        # per-sample row grads, so duplicate handling is oracle-checkable
        emb = mv.MatrixTable(dlrm.total_rows(cfg), cfg.embed_dim,
                             updater="default", seed=3, init_scale=0.05,
                             name="dlrm_emb_dup")
        flat, meta = dlrm.flatten_mlp(dlrm.init_mlp_params(cfg, 3))
        mlp = mv.ArrayTable(flat.size, updater="default", init=flat,
                            name="dlrm_mlp_dup")
        # every sample hits row 5 of field 0: gradients must SUM before the
        # updater applies (scatter-add, not last-write-wins)
        cat = np.asarray([[5, 1], [5, 2], [5, 3], [5, 4]], np.int32)
        dense = np.ones((4, 2), np.float32)
        labels = np.asarray([1, 0, 1, 0], np.float32)
        step = jax.jit(dlrm.make_train_step(cfg, emb, mlp, meta))

        mlp_params = dlrm.unflatten_mlp(mlp.state["data"][:flat.size], meta)
        ids = (cat + dlrm.field_offsets(cfg)[None, :]).reshape(-1)
        rows = jnp.take(emb.state["data"], ids, axis=0).reshape(4, 2, 4)
        g_rows = jax.grad(dlrm.loss_fn, argnums=1)(
            mlp_params, rows, jnp.asarray(dense), jnp.asarray(labels), cfg)
        expect = np.asarray(emb.state["data"]).copy()
        np.add.at(expect, np.asarray(ids),
                  np.asarray(g_rows.reshape(8, 4)))

        es, ms, _ = step(emb.state, mlp.state, jnp.asarray(cat),
                         jnp.asarray(dense), jnp.asarray(labels))
        np.testing.assert_allclose(np.asarray(es["data"]), expect,
                                   rtol=1e-5, atol=1e-6)

    def test_sharded_tables_on_mesh(self):
        cfg = dlrm.DLRMConfig(vocab_sizes=(64, 64, 32), embed_dim=8,
                              dense_dim=4, bottom_mlp=(8,), top_mlp=(8, 1))
        emb, mlp, meta = _setup(cfg, seed=5)
        assert len(jax.devices()) == 8
        cat, dense, labels = dlrm.synthetic_ctr(cfg, 256, seed=2)
        step = jax.jit(dlrm.make_train_step(cfg, emb, mlp, meta),
                       donate_argnums=(0, 1))
        es, ms, loss = step(emb.state, mlp.state, jnp.asarray(cat),
                            jnp.asarray(dense), jnp.asarray(labels))
        assert np.isfinite(float(loss))
        emb.adopt(es)
        mlp.adopt(ms)

    def test_config_validation(self):
        with pytest.raises(ValueError, match="bottom_mlp"):
            dlrm._mlp_shapes(dlrm.DLRMConfig(bottom_mlp=(32, 8),
                                             embed_dim=16))
        with pytest.raises(ValueError, match="top_mlp"):
            dlrm._mlp_shapes(dlrm.DLRMConfig(top_mlp=(32, 2)))


class TestDLRMExample:
    def test_dlrm_ctr_example_smoke(self):
        """examples/dlrm_ctr.py end to end at tier-1 scale (the ISSUE-8
        smoke the example never had): a short real run on the CPU mesh
        must exit 0, report epoch BCE lines that DECREASE, and beat the
        label base rate at eval — the example IS the documented entry
        point for the DLRM family, so a bitrot here is a user-facing
        break even when models/dlrm.py's own tests pass."""
        import os
        import re
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("JAX_PLATFORMS", None)   # the example forces its own mesh
        out = subprocess.run(
            [sys.executable, os.path.join(repo, "examples", "dlrm_ctr.py"),
             "--epochs", "3", "--samples", "3072"],
            capture_output=True, text=True, timeout=240, env=env, cwd=repo)
        assert out.returncode == 0, out.stderr[-1500:]
        bces = [float(m.group(1)) for m in
                re.finditer(r"epoch \d+\s+bce ([0-9.]+)", out.stdout)]
        assert len(bces) == 3, out.stdout[-800:]
        assert bces[-1] < bces[0], bces
        m = re.search(r"train accuracy ([0-9.]+)\s+\(base rate ([0-9.]+)",
                      out.stdout)
        assert m is not None, out.stdout[-800:]
        assert float(m.group(1)) >= float(m.group(2)), out.stdout[-400:]
