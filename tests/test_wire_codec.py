"""Device wire-codec parity + table get-cache tests (ISSUE 1 tentpole).

The jitted kernels in ``ops/wire_codec.py`` must match the numpy
reference filters in ``utils/filters.py`` **bit-for-bit** on the encoded
bits and per-block scales — a payload encoded by either side must decode
identically at the other (the PS wire ships the same frames). These are
the property tests that pin that contract, plus the version-stamped get
cache's monitor-counter behavior (a repeated Get with no intervening Add
must not dispatch a device transfer).
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.ops import wire_codec
from multiverso_tpu.utils import config, filters
from multiverso_tpu.utils.dashboard import Dashboard


def _cases(seed=0):
    """Random + adversarial flat f32 payloads: odd sizes (padding tail),
    denormals (scale underflow territory), all-negative and all-positive
    blocks (the empty-side scale is defined as 0), zeros, and huge
    magnitudes."""
    rng = np.random.default_rng(seed)
    cases = []
    for n in (1, 7, 1024, 1025, 4096, 10_000):
        cases.append(rng.normal(size=n).astype(np.float32))
    cases.append(np.full(3000, -0.25, np.float32))          # all-negative
    cases.append(np.full(2048, 1e-3, np.float32))           # all-positive
    cases.append(np.zeros(1536, np.float32))                # no signal
    denorm = rng.normal(size=2048).astype(np.float32) * np.float32(1e-41)
    cases.append(denorm)                                    # denormal blocks
    cases.append((rng.normal(size=1024) * 1e30).astype(np.float32))
    mixed = rng.normal(size=5000).astype(np.float32)
    mixed[::7] = 0.0
    cases.append(mixed)
    return cases


class TestOneBitParity:
    @pytest.mark.parametrize("block", [8, 256, 1024])
    def test_encode_bit_for_bit(self, block):
        for flat in _cases():
            ref_bits, ref_scales = filters.onebit_encode_np(flat, block)
            zeros = np.zeros_like(flat)
            bits, scales, _ = wire_codec.onebit_encode(flat, zeros,
                                                       block=block)
            bits, scales = np.asarray(bits), np.asarray(scales)
            assert bits.dtype == np.uint8
            np.testing.assert_array_equal(bits, ref_bits)
            # bit-for-bit: scales are f32-identical, not just close
            assert scales.tobytes() == ref_scales.astype(np.float32
                                                         ).tobytes()

    def test_decode_roundtrip_matches_numpy(self):
        for flat in _cases(seed=1):
            n = flat.size
            bits, scales = filters.onebit_encode_np(flat, 1024)
            ref = filters.onebit_decode_np(bits, scales, n, 1024)
            dev = np.asarray(wire_codec.onebit_decode(bits, scales, n=n,
                                                      block=1024))
            assert dev.tobytes() == ref.tobytes()

    def test_block_must_be_multiple_of_8(self):
        with pytest.raises(ValueError):
            filters.onebit_encode_np(np.ones(16, np.float32), 12)
        with pytest.raises(ValueError):
            filters.OneBitsFilter(block=12)

    def test_residuals_converge_identically(self):
        """Error feedback carried on device vs the numpy filter: the two
        residual streams stay bit-identical over 100 steps (same adds, same
        quantization error accrual)."""
        rng = np.random.default_rng(2)
        n, block = 2048, 256
        filt = filters.OneBitsFilter(block=block)
        residual = np.zeros(n, np.float32)
        for step in range(100):
            delta = rng.normal(size=n).astype(np.float32)
            _, ref_bits, ref_scales = filt.filter_in(delta)
            bits, scales, residual = wire_codec.onebit_encode(
                delta, residual, block=block)
            bits, scales, residual = (np.asarray(bits), np.asarray(scales),
                                      np.asarray(residual))
            np.testing.assert_array_equal(bits, ref_bits, err_msg=f"{step}")
            assert scales.tobytes() == ref_scales.tobytes(), step
            assert residual.tobytes() == filt._residual.astype(
                np.float32).tobytes(), step


class TestTopKParity:
    @pytest.mark.parametrize("k", [1, 32, 500])
    def test_encode_matches_numpy(self, k):
        for flat in _cases(seed=3):
            kk = min(k, flat.size)
            filt = filters.TopKFilter(kk)
            _, ref_idx, ref_vals = filt.filter_in(flat)
            zeros = np.zeros_like(flat)
            idx, vals, res = wire_codec.topk_encode(flat, zeros, k=kk)
            idx, vals, res = (np.asarray(idx), np.asarray(vals),
                              np.asarray(res))
            np.testing.assert_array_equal(idx, ref_idx)
            assert vals.tobytes() == ref_vals.tobytes()
            assert res.tobytes() == filt._residual.astype(
                np.float32).tobytes()

    def test_decode_roundtrip(self):
        rng = np.random.default_rng(4)
        flat = rng.normal(size=1000).astype(np.float32)
        idx, vals, _ = wire_codec.topk_encode(flat, np.zeros_like(flat),
                                              k=100)
        out = np.asarray(wire_codec.topk_decode(idx, vals, n=1000))
        ref = filters.TopKFilter(100)
        header, ridx, rvals = ref.filter_in(flat)
        np.testing.assert_array_equal(out, ref.filter_out(header, ridx,
                                                          rvals))

    def test_error_feedback_preserves_sum(self):
        """EF property: after N payloads, decoded-sum + residual == the
        true running sum (nothing is ever lost, only deferred)."""
        rng = np.random.default_rng(5)
        n, k = 512, 16
        residual = np.zeros(n, np.float32)
        decoded_sum = np.zeros(n, np.float64)
        true_sum = np.zeros(n, np.float64)
        for _ in range(50):
            delta = rng.normal(size=n).astype(np.float32) * 0.01
            true_sum += delta
            idx, vals, residual = wire_codec.topk_encode(delta, residual,
                                                         k=k)
            decoded_sum += np.asarray(
                wire_codec.topk_decode(idx, vals, n=n))
            residual = np.asarray(residual)
        np.testing.assert_allclose(decoded_sum + residual, true_sum,
                                   atol=1e-3)


class TestPSWirePayload:
    """ps/wire.encode_payload must produce the SAME frames as the device
    codec, and decode_payload must invert them (either endpoint)."""

    def test_onebit_frame_parity(self):
        from multiverso_tpu.ps import wire as ps_wire
        rng = np.random.default_rng(6)
        arr = rng.normal(size=(33, 40)).astype(np.float32)
        blobs = ps_wire.encode_payload(arr, "1bit")
        assert len(blobs) == 2
        flat = arr.reshape(-1)
        bits, scales, _ = wire_codec.onebit_encode(
            flat, np.zeros_like(flat), block=ps_wire.ONEBIT_BLOCK)
        np.testing.assert_array_equal(blobs[0], np.asarray(bits))
        assert blobs[1].tobytes() == np.asarray(scales).tobytes()
        out = ps_wire.decode_payload(blobs, "1bit", arr.shape, np.float32)
        ref = filters.onebit_decode_np(blobs[0], blobs[1], arr.size,
                                       ps_wire.ONEBIT_BLOCK)
        assert out.tobytes() == ref.tobytes()

    def test_none_and_bf16_roundtrip(self):
        from multiverso_tpu.ps import wire as ps_wire
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        for mode in ("none", "bf16"):
            blobs = ps_wire.encode_payload(arr, mode)
            out = ps_wire.decode_payload(blobs, mode, arr.shape, np.float32)
            np.testing.assert_allclose(out, arr, rtol=1e-2)

    def test_compressed_frame_is_smaller(self):
        from multiverso_tpu.ps import wire as ps_wire
        arr = np.ones(100_000, np.float32)
        plain = sum(b.nbytes for b in ps_wire.encode_payload(arr, "none"))
        onebit = sum(b.nbytes for b in ps_wire.encode_payload(arr, "1bit"))
        assert onebit * 20 < plain   # ~29x fewer bytes on the wire
        # the size-contract helpers predict the frame exactly
        assert onebit == wire_codec.onebit_compressed_nbytes(
            arr.size, ps_wire.ONEBIT_BLOCK)
        idx, vals, _ = wire_codec.topk_encode(arr, np.zeros_like(arr),
                                              k=64)
        assert (np.asarray(idx).nbytes + np.asarray(vals).nbytes
                == wire_codec.topk_compressed_nbytes(64))


class TestGetCache:
    def test_repeated_get_skips_transfer(self):
        """Acceptance: a repeated get with no intervening add is served
        from the version cache — the `.get.cached` monitor counts the hit
        and the snapshot/transfer is skipped."""
        mv.init()
        t = mv.ArrayTable(1000, updater="sgd", name="cache_t")
        mon = Dashboard.get("table[cache_t].get.cached")
        t.add(np.ones(1000, np.float32))
        a = t.get()
        base = mon.count
        b = t.get()           # no intervening add: cache hit
        c = t.get()
        assert mon.count == base + 2
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
        t.add(np.ones(1000, np.float32))
        d = t.get()           # version bumped: miss, fresh transfer
        assert mon.count == base + 2
        assert not np.array_equal(a, d)
        t.get()               # and the fresh value is cached again
        assert mon.count == base + 3

    def test_cache_returns_private_copy(self):
        mv.init()
        t = mv.ArrayTable(16, updater="sgd", name="cache_copy_t")
        t.add(np.ones(16, np.float32))
        t.get()              # prime the cache (a miss hands out the
        a = t.get()          # read-only device view; hits are writable)
        expect = a.copy()
        a[:] = -1            # caller mutates its hit...
        b = t.get()          # ...the next hit must not see it
        np.testing.assert_array_equal(b, expect)

    def test_get_async_populates_and_hits_cache(self):
        mv.init()
        t = mv.ArrayTable(64, updater="sgd", name="cache_async_t")
        t.add(np.ones(64, np.float32))
        mon = Dashboard.get("table[cache_async_t].get.cached")
        first = t.read(t.get_async())
        base = mon.count
        second = t.read(t.get_async())   # unchanged: served from cache
        assert mon.count == base + 1
        np.testing.assert_array_equal(first, second)

    def test_flag_disables_cache(self):
        mv.init()
        config.set_flag("table_get_cache", False)
        t = mv.ArrayTable(32, updater="sgd", name="cache_off_t")
        t.add(np.ones(32, np.float32))
        mon = Dashboard.get("table[cache_off_t].get.cached")
        t.get()
        t.get()
        assert mon.count == 0

    def test_version_property_monotonic(self):
        mv.init()
        t = mv.ArrayTable(8, updater="sgd", name="ver_t")
        v0 = t.version
        t.add(np.ones(8, np.float32))
        assert t.version > v0


class TestAsyncBufferVersionSkip:
    def test_unchanged_version_skips_fill(self):
        from multiverso_tpu.utils.async_buffer import AsyncBuffer
        calls = []
        state = {"v": 0}

        def fill():
            calls.append(1)
            return len(calls)

        buf = AsyncBuffer(fill, version_fn=lambda: state["v"])
        assert buf.get() == 1
        assert buf.get() == 1          # version unchanged: fill skipped
        assert buf.get() == 1
        assert buf.skipped_fills == 3
        assert len(calls) == 1
        state["v"] = 1
        buf.get()                      # stale serve + refill kicked off
        assert buf.get() == 2          # the refill's result
        buf.stop()

    def test_no_version_fn_always_fills(self):
        from multiverso_tpu.utils.async_buffer import AsyncBuffer
        calls = []

        def fill():
            calls.append(1)
            return len(calls)

        buf = AsyncBuffer(fill)
        assert buf.get() == 1
        assert buf.get() == 2
        buf.stop()


class TestWireFilteredTable:
    """End-to-end through the sync Table's compressed host<->device wire:
    the device encode + in-graph decode must agree with the numpy
    reference semantics."""

    def test_1bit_add_matches_reference_decode(self):
        mv.init()
        rng = np.random.default_rng(7)
        t = mv.ArrayTable(4096, updater="sgd", name="w1bit_t")
        tw = mv.ArrayTable(4096, updater="sgd", name="w1bit_tw",
                           wire_filter="1bit")
        delta = rng.normal(size=4096).astype(np.float32)
        # reference: what one EF-encoded payload should apply ("sgd"
        # subtracts the delta as-is; callers pre-scale by lr)
        filt = filters.OneBitsFilter(block=1024)
        header, bits, scales = filt.filter_in(delta)
        expected = -filters.onebit_decode_np(bits, scales, 4096, 1024)
        tw.add(delta)
        np.testing.assert_allclose(tw.get(), expected, rtol=1e-2,
                                   atol=1e-6)
        del t

    def test_1bit_error_feedback_converges(self):
        """100 identical adds through the 1bit wire. Two properties:

        (1) EF conservation, end-to-end through the table: decoded sum
        (the table) plus the table's carried residual equals the true
        sum — quantization error is deferred, never lost.
        (2) EF beats no-EF: without feedback the per-payload bias is
        constant (same delta -> same decode every step) and accumulates
        linearly; with feedback the error stays well under half of it.

        (Per-element error is NOT tiny here — an above-block-scale
        element lags until the scales adapt, so max|err| can reach ~1 of
        ~3-magnitude entries at step 100. That is expected 1-bit SGD
        behavior, identical in the numpy reference — see
        test_residuals_converge_identically.)"""
        mv.init()
        rng = np.random.default_rng(8)
        n = 2048
        tw = mv.ArrayTable(n, updater="default", name="w1bit_conv",
                           wire_filter="1bit")
        # ArrayTable default updater is a plain sum (delta applied as-is)
        delta = rng.normal(size=n).astype(np.float32) * 0.01
        steps = 100
        for _ in range(steps):
            tw.add(delta)
        got = np.asarray(tw.get(), np.float64)
        true = delta.astype(np.float64) * steps   # entries ~ N(0, 1)
        residual = np.asarray(
            tw._wire_residual if tw._wire_residual is not None
            else tw._one_bit._residual, np.float64)
        # (1) conservation: table + residual == true sum, up to the bf16
        # Get-reply rounding of ~3-magnitude entries
        np.testing.assert_allclose(got + residual, true, atol=0.05)
        # (2) linear no-EF bias for this constant delta, for comparison
        bits, scales = filters.onebit_encode_np(delta, 1024)
        no_ef = np.abs(true - steps * filters.onebit_decode_np(
            bits, scales, n, 1024).astype(np.float64)).max()
        assert np.abs(got - true).max() < 0.5 * no_ef

    def test_topk_add_applies_support_exactly(self):
        mv.init()
        rng = np.random.default_rng(9)
        n = 4096
        tw = mv.ArrayTable(n, updater="default", name="wtopk_t",
                           wire_filter="topk")
        delta = np.zeros(n, np.float32)
        hot = rng.choice(n, size=32, replace=False)
        delta[hot] = rng.normal(size=32).astype(np.float32)
        tw.add(delta)   # sparse delta fits entirely in the top-k support
        got = tw.get()
        np.testing.assert_allclose(got[hot], delta[hot], rtol=1e-2,
                                   atol=1e-6)


class TestAsyncTableOneBitWire:
    """The PS (socket) plane with wire="1bit": encoded frames cross the
    wire and decode exactly once at the owning shard."""

    def test_whole_table_add_get(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        tables = [AsyncMatrixTable(8, 4, name="onebit_ps", wire="1bit",
                                   updater="default", ctx=c)
                  for c in two_ranks]
        rng = np.random.default_rng(10)
        # uniform magnitude, random sign: the per-block mean EQUALS every
        # entry's magnitude, so each 1bit payload decodes exactly and the
        # EF residual stays zero — the sum is exact, only bf16 reply
        # rounding remains (mixed magnitudes would exercise EF stability,
        # which small 16-element blocks do not guarantee; the EF-sum
        # invariant is covered by test_error_feedback_preserves_sum)
        delta = (0.5 * rng.choice([-1.0, 1.0], size=(8, 4))
                 ).astype(np.float32)
        steps = 60
        for _ in range(steps):
            tables[0].add(delta)
        got = tables[0].get()
        # exact local short-circuit + exactly-decoding remote payloads:
        # both halves land on the true sum (remote half read back bf16)
        np.testing.assert_allclose(got, delta * steps, rtol=1e-2)
        # a fresh get from the OTHER rank sees the same state (its local
        # shard exactly, the peer's through the bf16 reply wire)
        np.testing.assert_allclose(tables[1].get(), got, rtol=1e-2)

    def test_row_add_roundtrip(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        tables = [AsyncMatrixTable(8, 4, name="onebit_rows", wire="1bit",
                                   updater="default", ctx=c)
                  for c in two_ranks]
        vals = np.full((2, 4), 0.5, np.float32)
        # rows 6,7 live on rank 1: the payload crosses the socket 1bit-
        # encoded; all values equal => block scale reproduces them exactly
        tables[0].add_rows([6, 7], vals)
        got = tables[0].get_rows([6, 7])
        np.testing.assert_allclose(got, vals, rtol=1e-2)


class TestAsyncTableTopkWire:
    """wire="topk" on the PS plane: the sparsification applies to ADD
    deltas only — get replies must carry the FULL value block (bf16)."""

    def test_get_replies_are_not_sparsified(self, two_ranks):
        """Regression: _reply_wire used to pass "topk" through for gets,
        so a remote pull returned a ~3% top-k skeleton of the weights
        (everything else zeroed) — destructive for parameter VALUES, the
        same rule 1bit already followed."""
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        tables = [AsyncMatrixTable(8, 32, name="topk_get", wire="topk",
                                   updater="default", ctx=c)
                  for c in two_ranks]
        # set_rows ships raw (no add codec): the table holds exactly vals
        vals = np.linspace(1.0, 2.0, 8 * 32,
                           dtype=np.float32).reshape(8, 32)
        tables[0].set_rows(np.arange(8), vals)
        for t in tables:   # both ranks: local short-circuit AND remote
            got = t.get_rows(np.arange(8))
            assert np.count_nonzero(got) == got.size, \
                "get reply was sparsified"
            np.testing.assert_allclose(got, vals, rtol=1e-2)
            np.testing.assert_allclose(t.get(), vals, rtol=1e-2)
