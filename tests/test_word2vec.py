"""word2vec model + WordEmbedding app tests (ref tier-4: WE text8 analogue)."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.apps.word_embedding import (WEConfig, WordEmbedding,
                                                synthetic_corpus)
from multiverso_tpu.data.dictionary import Dictionary, build_huffman
from multiverso_tpu.models import word2vec as w2v


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


class TestDictionary:
    def test_build_prunes_and_sorts(self):
        d = Dictionary.build("a a a b b c".split(), min_count=2)
        assert d.words == ["a", "b"]
        assert d.word2id == {"a": 0, "b": 1}
        np.testing.assert_array_equal(d.counts, [3, 2])

    def test_encode_drops_oov(self):
        d = Dictionary.build("a a b b".split(), min_count=2)
        np.testing.assert_array_equal(d.encode("a x b".split()), [0, 1])

    def test_subsample_keeps_rare(self):
        counts = ["common"] * 10000 + ["rare"] * 10
        d = Dictionary.build(counts, min_count=5)
        ids = d.encode(counts)
        kept = d.subsample(ids, t=1e-4, seed=0)
        rare_id = d.word2id["rare"]
        rare_rate = np.sum(kept == rare_id) / 10
        common_rate = np.sum(kept == d.word2id["common"]) / 10000
        # rare words survive at a much higher rate than common ones
        assert rare_rate > common_rate * 3
        assert common_rate < 0.2

    def test_unigram_table(self):
        d = Dictionary.build("a a a a b b".split(), min_count=1)
        p = d.unigram_table()
        assert p.sum() == pytest.approx(1.0)
        assert p[0] > p[1]


class TestHuffman:
    def test_tree_shapes(self):
        counts = np.array([50, 30, 10, 5, 5])
        codes, points, lengths = build_huffman(counts)
        assert codes.shape == points.shape
        assert lengths.min() >= 1
        # frequent words get shorter codes
        assert lengths[0] <= lengths[-1]
        # points index inner nodes only
        assert points.max() <= len(counts) - 2

    def test_codes_unique(self):
        counts = np.array([8, 4, 2, 1, 1])
        codes, points, lengths = build_huffman(counts)
        paths = set()
        for w in range(len(counts)):
            paths.add(tuple(codes[w, :lengths[w]]))
        assert len(paths) == len(counts)


class TestSteps:
    def test_skipgram_ns_reduces_loss(self):
        rng = np.random.default_rng(0)
        v, d, b, k = 50, 16, 32, 4
        win, wout = w2v.init_embeddings(w2v.W2VConfig(v, d))
        win, wout = np.asarray(win), np.asarray(wout)
        centers = rng.integers(0, v, b).astype(np.int32)
        contexts = ((centers + 1) % v).astype(np.int32)
        negs = rng.integers(0, v, (b, k)).astype(np.int32)
        import jax.numpy as jnp
        win, wout = jnp.asarray(win), jnp.asarray(wout)
        losses = []
        for _ in range(30):
            win, wout, loss = w2v.skipgram_ns_step(
                win, wout, jnp.asarray(centers), jnp.asarray(contexts),
                jnp.asarray(negs), 0.2)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7

    def test_cbow_ns_runs(self):
        import jax.numpy as jnp
        v, d, b, w, k = 30, 8, 16, 4, 3
        rng = np.random.default_rng(1)
        win, wout = map(jnp.asarray, w2v.init_embeddings(w2v.W2VConfig(v, d)))
        windows = jnp.asarray(rng.integers(0, v, (b, w)), jnp.int32)
        mask = jnp.ones((b, w), bool)
        tgt = jnp.asarray(rng.integers(0, v, b), jnp.int32)
        negs = jnp.asarray(rng.integers(0, v, (b, k)), jnp.int32)
        l0 = None
        for i in range(20):
            win, wout, loss = w2v.cbow_ns_step(win, wout, windows, mask, tgt,
                                               negs, 0.2)
            l0 = l0 or float(loss)
        assert float(loss) < l0

    def test_hs_step_runs(self):
        import jax.numpy as jnp
        counts = np.array([40, 20, 10, 8, 6, 4])
        codes, points, lengths = build_huffman(counts)
        v, d, b = len(counts), 8, 12
        rng = np.random.default_rng(2)
        win, _ = map(jnp.asarray, w2v.init_embeddings(w2v.W2VConfig(v, d)))
        hs_out = jnp.zeros((v - 1, d))
        centers = rng.integers(0, v, b).astype(np.int32)
        ctx = ((centers + 1) % v)
        c = jnp.asarray(codes[ctx]); p = jnp.asarray(points[ctx])
        m = jnp.arange(codes.shape[1])[None, :] < jnp.asarray(lengths[ctx])[:, None]
        l0 = None
        for _ in range(20):
            win, hs_out, loss = w2v.skipgram_hs_step(
                win, hs_out, jnp.asarray(centers), c, p, m, 0.2)
            l0 = l0 or float(loss)
        assert float(loss) < l0

    def test_generate_pairs(self):
        ids = np.arange(5)
        c, x = w2v.generate_pairs(ids, window=1, dynamic=False)
        # each interior token pairs with both neighbors
        assert (c == 2).sum() == 2
        assert set(x[c == 2]) == {1, 3}

    def test_shared_neg_step_matches_numpy(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        v_sz, d, b, k = 30, 8, 16, 6
        win = rng.normal(size=(v_sz, d)).astype(np.float32)
        wout = rng.normal(size=(v_sz, d)).astype(np.float32) * 0.1
        c = rng.integers(0, v_sz, b).astype(np.int32)
        x = rng.integers(0, v_sz, b).astype(np.int32)
        nid = rng.choice(v_sz, k, replace=False).astype(np.int32)
        lr, nw = 0.05, 0.5

        def sigmoid(z):
            return 1.0 / (1.0 + np.exp(-z))

        vv, up, un = win[c], wout[x], wout[nid]
        pos = (vv * up).sum(-1)
        negs = vv @ un.T
        gp = (1.0 - sigmoid(pos)) * lr
        gn = -sigmoid(negs) * lr * nw
        exp_win, exp_wout = win.copy(), wout.copy()
        np.add.at(exp_win, c, gp[:, None] * up + gn @ un)
        np.add.at(exp_wout, x, gp[:, None] * vv)
        np.add.at(exp_wout, nid, gn.T @ vv)

        got_win, got_wout, loss = w2v.shared_neg_step(
            jnp.asarray(win), jnp.asarray(wout), jnp.asarray(c),
            jnp.asarray(x), jnp.asarray(nid), lr, nw,
            compute_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(got_win), exp_win, atol=1e-5)
        np.testing.assert_allclose(np.asarray(got_wout), exp_wout, atol=1e-5)
        exp_loss = (-np.mean(np.log(sigmoid(pos)))
                    - nw * np.mean(np.log(sigmoid(-negs)).sum(-1)))
        assert abs(float(loss) - exp_loss) < 1e-4

    def test_shared_epoch_reduces_loss(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(1)
        v_sz, d, b = 50, 16, 64
        cfg = w2v.W2VConfig(v_sz, d, negatives=4, shared_negatives=8,
                            learning_rate=0.1)
        win, wout = w2v.init_embeddings(cfg, seed=0)
        # corpus where context == center makes loss trivially reducible
        cs = rng.integers(0, v_sz, (20, b)).astype(np.int32)
        epoch_fn = w2v.make_fused_shared_epoch(
            cfg, np.ones(v_sz), compute_dtype=jnp.float32)
        win, wout = jnp.asarray(win), jnp.asarray(wout)
        lcg = jnp.asarray(w2v.init_lcg_state(8, 0))
        losses = []
        for _ in range(6):
            win, wout, loss, lcg = epoch_fn(win, wout, jnp.asarray(cs),
                                            jnp.asarray(cs), lcg)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestWordEmbeddingApp:
    def _make(self, **kw):
        tokens = synthetic_corpus(30_000, vocab=200, seed=3)
        cfg = WEConfig(size=32, min_count=5, batch_size=256, negative=4,
                       epoch=1, **kw)
        d = Dictionary.build(tokens, cfg.min_count)
        we = WordEmbedding(cfg, d)
        return we, we.prepare_ids(tokens)

    def test_fused_training_learns(self):
        we, ids = self._make()
        stats = we.train_fused(ids, epochs=2)
        assert stats["words_per_sec"] > 0
        assert stats["loss"] < 3.0
        emb = we.embeddings()
        assert np.linalg.norm(emb) > 0

    def test_ps_block_training(self):
        we, ids = self._make(data_block_size=5000)
        stats = we.train_ps_blocks(ids[:10_000], epochs=1)
        assert stats["loss"] > 0
        assert we.word_count[0] > 0

    def test_save_and_nearest(self, tmp_path):
        we, ids = self._make()
        we.train_fused(ids, epochs=1)
        out = tmp_path / "vec.txt"
        we.save_embeddings(str(out))
        header = out.read_text().splitlines()[0].split()
        assert int(header[0]) == len(we.dict)
        assert int(header[1]) == 32
        word = we.dict.words[0]
        nbrs = we.nearest(word, k=3)
        assert len(nbrs) == 3 and word not in nbrs

    def test_binary_output_roundtrips_bit_exact(self, tmp_path):
        """-binary 1 (ref util.h:26, writer
        distributed_wordembedding.cpp:310-325): classic word2vec .bin —
        raw float32 rows reload bit-exact; text mode loads too (lossy)."""
        from multiverso_tpu.apps.word_embedding import load_embeddings
        we, ids = self._make()
        we.train_fused(ids, epochs=1)
        emb = we.embeddings()
        bpath, tpath = tmp_path / "vec.bin", tmp_path / "vec.txt"
        we.save_embeddings(str(bpath), binary=True)
        we.save_embeddings(str(tpath), binary=False)
        words_b, emb_b = load_embeddings(str(bpath))
        assert words_b == list(we.dict.words)
        np.testing.assert_array_equal(emb_b, np.asarray(emb, np.float32))
        words_t, emb_t = load_embeddings(str(tpath))
        assert words_t == words_b
        np.testing.assert_allclose(emb_t, emb_b, atol=1e-6)

    def test_stopwords_dropped_from_training_stream(self, tmp_path):
        """-stopwords 1 -sw_file (ref reader.cpp:11-47): listed words stay
        in the vocab but never reach the training stream."""
        from multiverso_tpu.apps.word_embedding import (WEConfig,
                                                        load_corpus)
        corpus = tmp_path / "c.txt"
        toks = (["the", "cat", "sat"] * 400) + (["dog"] * 100)
        corpus.write_text(" ".join(toks))
        sw = tmp_path / "sw.txt"
        sw.write_text("the\nsat\n")
        cfg = WEConfig(train_file=str(corpus), min_count=5, sample=0,
                       stopwords="1", sw_file=str(sw))
        d, ids = load_corpus(cfg)
        assert "the" in d.word2id and "sat" in d.word2id   # vocab keeps them
        banned = {d.word2id["the"], d.word2id["sat"]}
        assert not banned & set(np.unique(ids).tolist())   # stream drops them
        assert d.word2id["cat"] in set(np.unique(ids).tolist())

    def test_stopwords_flag_requires_sw_file(self):
        from multiverso_tpu.apps.word_embedding import WEConfig
        with pytest.raises(ValueError, match="sw_file"):
            WEConfig(stopwords="1")


class TestModesAndRegressions:
    def _tokens(self):
        return synthetic_corpus(20_000, vocab=150, seed=5)

    def test_cbow_fused(self):
        tokens = self._tokens()
        cfg = WEConfig(size=16, min_count=5, batch_size=256, negative=3,
                       cbow=1)
        d = Dictionary.build(tokens, cfg.min_count)
        we = WordEmbedding(cfg, d)
        stats = we.train_fused(we.prepare_ids(tokens), epochs=1)
        assert stats["loss"] > 0
        assert np.linalg.norm(we.embeddings()) > 0

    def test_hs_fused(self):
        tokens = self._tokens()
        cfg = WEConfig(size=16, min_count=5, batch_size=256, hs=1)
        d = Dictionary.build(tokens, cfg.min_count)
        we = WordEmbedding(cfg, d)
        stats = we.train_fused(we.prepare_ids(tokens), epochs=1)
        assert stats["loss"] > 0
        # the HS output table actually trained
        assert np.linalg.norm(we.table_hs.get()) > 0

    def test_cbow_hs_step_reduces_loss_and_matches_grad(self):
        import jax
        import jax.numpy as jnp
        counts = np.array([40, 20, 10, 8, 6, 4])
        codes, points, lengths = build_huffman(counts)
        v, d, b, w = len(counts), 8, 12, 4
        rng = np.random.default_rng(3)
        win, _ = map(jnp.asarray, w2v.init_embeddings(w2v.W2VConfig(v, d)))
        hs_out = jnp.asarray(rng.normal(0, 0.1, (v - 1, d)), jnp.float32)
        windows = jnp.asarray(rng.integers(0, v, (b, w)), jnp.int32)
        wmask = jnp.asarray(rng.random((b, w)) > 0.2)
        targets = rng.integers(0, v, b)
        c = jnp.asarray(codes[targets]); p = jnp.asarray(points[targets])
        m = (jnp.arange(codes.shape[1])[None, :]
             < jnp.asarray(lengths[targets])[:, None])

        # the manual ascent deltas must equal -lr * d(sum-loss)/d(params)
        def total_loss(win, hs_out):
            ctx = jnp.take(win, windows, axis=0)
            mm = wmask.astype(ctx.dtype)[..., None]
            vvec = (ctx * mm).sum(1) / jnp.maximum(mm.sum(1), 1.0)
            u = jnp.take(hs_out, p, axis=0)
            s = jnp.einsum("bd,bld->bl", vvec, u)
            masked = jnp.where(m, s * (1 - 2 * c), 0.0)
            # per-sample sum (the step's g has no 1/B factor)
            return -jnp.sum(jax.nn.log_sigmoid(masked) * m)

        lr = 0.2
        gw, gh = jax.grad(total_loss, argnums=(0, 1))(win, hs_out)
        win2, hs2, _ = w2v.cbow_hs_step(win, hs_out, windows, wmask,
                                        c, p, m, lr)
        np.testing.assert_allclose(np.asarray(win2 - win),
                                   np.asarray(-lr * gw), atol=1e-5)
        np.testing.assert_allclose(np.asarray(hs2 - hs_out),
                                   np.asarray(-lr * gh), atol=1e-5)

        l0 = None
        for _ in range(30):
            win, hs_out, loss = w2v.cbow_hs_step(
                win, hs_out, windows, wmask, c, p, m, lr)
            l0 = l0 or float(loss)
        assert float(loss) < l0

    def test_cbow_hs_fused(self):
        tokens = self._tokens()
        cfg = WEConfig(size=16, min_count=5, batch_size=256, cbow=1, hs=1)
        d = Dictionary.build(tokens, cfg.min_count)
        we = WordEmbedding(cfg, d)
        stats = we.train_fused(we.prepare_ids(tokens), epochs=1)
        assert stats["loss"] > 0
        assert np.linalg.norm(we.table_hs.get()) > 0
        assert np.linalg.norm(we.embeddings()) > 0

    @pytest.mark.parametrize("cbow,hs", [(1, 0), (0, 1), (1, 1)])
    def test_ps_blocks_all_variants(self, cbow, hs):
        # the reference's distributed path trains every variant; so does
        # the PS block path here (skipgram-NS is covered elsewhere)
        tokens = self._tokens()
        cfg = WEConfig(size=16, min_count=5, batch_size=128, cbow=cbow,
                       hs=hs, negative=3, data_block_size=4000)
        d = Dictionary.build(tokens, cfg.min_count)
        we = WordEmbedding(cfg, d)
        stats = we.train_ps_blocks(we.prepare_ids(tokens), epochs=1)
        assert stats["loss"] > 0
        assert np.linalg.norm(we.embeddings()) > 0
        if hs:
            assert np.linalg.norm(we.table_hs.get()) > 0
        else:
            assert np.linalg.norm(we.table_out.get()) > 0

    @pytest.mark.parametrize("cbow,hs", [(0, 0), (0, 1), (1, 0), (1, 1)])
    def test_ps_device_plane_matches_host_plane(self, cbow, hs):
        # the fused single-dispatch device plane and the host Get/Add plane
        # must train to the same state: same seed => identical pair/negative
        # draws => the only divergence allowed is float reassociation
        tokens = self._tokens()
        emb = {}
        for mode in ("0", "1"):
            cfg = WEConfig(size=16, min_count=5, batch_size=128, negative=3,
                           cbow=cbow, hs=hs, data_block_size=4000,
                           ps_device_plane=mode, seed=9)
            d = Dictionary.build(tokens, cfg.min_count)
            we = WordEmbedding(cfg, d)
            stats = we.train_ps_blocks(we.prepare_ids(tokens), epochs=1)
            assert stats["loss"] > 0
            emb[mode] = (we.embeddings(),
                         (we.table_hs if hs else we.table_out).get())
        np.testing.assert_allclose(emb["0"][0], emb["1"][0], atol=1e-3)
        np.testing.assert_allclose(emb["0"][1], emb["1"][1], atol=1e-3)

    def test_ps_block_dtype_bf16_trains_close_to_f32(self):
        # bf16 scan mode: same draws, loss lands near the f32 run (deltas
        # are measured against the bf16-rounded baseline, so untrained
        # rows get exactly-zero deltas — regression for the phantom-delta
        # bug) and bad values are a typed config error
        tokens = self._tokens()
        losses = {}
        for dt in ("f32", "bf16"):
            cfg = WEConfig(size=16, min_count=5, batch_size=128, negative=3,
                           data_block_size=4000, seed=9, ps_block_dtype=dt)
            d = Dictionary.build(tokens, cfg.min_count)
            we = WordEmbedding(cfg, d)
            st = we.train_ps_blocks(we.prepare_ids(tokens), epochs=1)
            losses[dt] = st["loss"]
        assert abs(losses["bf16"] - losses["f32"]) < 0.15, losses
        with pytest.raises(ValueError, match="ps_block_dtype"):
            WEConfig(ps_block_dtype="bf61")

    def test_words_per_sec_counts_tokens(self):
        tokens = self._tokens()
        cfg = WEConfig(size=16, min_count=5, batch_size=256, negative=3)
        d = Dictionary.build(tokens, cfg.min_count)
        we = WordEmbedding(cfg, d)
        ids = we.prepare_ids(tokens)
        stats = we.train_fused(ids, epochs=1)
        implied_words = stats["words_per_sec"] * stats["seconds"]
        assert implied_words == pytest.approx(ids.size, rel=0.01)
        assert stats["pairs"] > ids.size  # pairs are reported separately
