"""Mesh data plane (ISSUE 15, ps/spmd.py): process-coalesced fan-out
routing + multi-owner super-frames + mesh-stacked SPMD shard groups.

The contract under test everywhere: with the plane armed, every result
is BIT-IDENTICAL to the classic path — fan-out adds/gets, grouped SPMD
applies/gathers, windowed adds, and the failure/eviction edges all
included. The 1-shard classic world is the oracle throughout.
"""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.ps import spmd
from multiverso_tpu.ps import wire as wire_mod
from multiverso_tpu.ps.service import (MSG_ADD_ROWS, MSG_GET_ROWS,
                                       MSG_MULTI, MSG_REPLY_ERR,
                                       FileRendezvous, PSContext,
                                       PSError, PSPeerError, PSService)
from multiverso_tpu.ps.tables import AsyncMatrixTable
from multiverso_tpu.utils import config


def _world(tmp_path, n, sub="rdv"):
    rdv = FileRendezvous(str(tmp_path / sub))
    return [PSContext(r, n, PSService(r, n, rdv)) for r in range(n)]


def _close(ctxs):
    for c in ctxs:
        c.close()


def _drive(table, rows, dim, steps=12, seed=7, sort_ids=True):
    """A deterministic add stream (mixed batch shapes, spanning every
    shard); returns nothing — the caller compares final tables."""
    rng = np.random.default_rng(seed)
    for step in range(steps):
        k = int(rng.integers(3, rows // 2))
        ids = rng.choice(rows, size=k, replace=False)
        if sort_ids:
            ids = np.sort(ids)
        vals = rng.normal(size=(k, dim)).astype(np.float32)
        table.add_rows(ids, vals)


def _oracle(tmp_path, rows, dim, updater=None, steps=12, seed=7,
            sort_ids=True, sub="oracle"):
    """The 1-shard classic world's final table for the same stream."""
    config.set_flag("ps_fanout", False)
    config.set_flag("ps_spmd_stack", False)
    ctxs = _world(tmp_path, 1, sub=sub)
    t = AsyncMatrixTable(rows, dim, name="oracle_t", updater=updater,
                         ctx=ctxs[0])
    _drive(t, rows, dim, steps=steps, seed=seed, sort_ids=sort_ids)
    out = t.get_rows(np.arange(rows))
    _close(ctxs)
    return out


class TestRegistry:
    def test_register_and_colocated_ranks(self, tmp_path):
        ctxs = _world(tmp_path, 3)
        key = ctxs[0].service._proc_key
        assert sorted(spmd.colocated_ranks(key)) == [0, 1, 2]
        assert spmd.colocated_service(key, 1) is ctxs[1].service
        ctxs[1].close()
        # a closed service leaves the registry (death is observable)
        assert spmd.colocated_service(key, 1) is None
        assert sorted(spmd.colocated_ranks(key)) == [0, 2]
        _close([ctxs[0], ctxs[2]])

    def test_worlds_never_cross_route(self, tmp_path):
        """Two independent in-process worlds (different rendezvous)
        must not see each other — same ranks, different keys."""
        a = _world(tmp_path, 2, sub="a")
        b = _world(tmp_path, 2, sub="b")
        ka = a[0].service._proc_key
        kb = b[0].service._proc_key
        assert ka != kb
        assert spmd.colocated_service(ka, 1) is a[1].service
        assert spmd.colocated_service(kb, 1) is b[1].service
        _close(a)
        _close(b)


class TestOwnerSlices:
    """The vectorized partition (ISSUE 15 satellite): every shape must
    partition identically to the per-owner mask reference."""

    @pytest.fixture()
    def table(self, tmp_path):
        ctxs = _world(tmp_path, 8)
        t = AsyncMatrixTable(100_000, 4, name="part", ctx=ctxs[0])
        yield t
        _close(ctxs)

    def _ref(self, t, uids):
        owners = uids // t._rows_per
        return {int(r): uids[owners == r].tolist()
                for r in np.unique(owners)}

    @pytest.mark.parametrize("make", [
        lambda rng: np.unique(rng.integers(0, 100_000, 5000)),
        lambda rng: rng.permutation(
            np.unique(rng.integers(0, 100_000, 5000))),
        lambda rng: (np.arange(256) * 390 + 3) % 100_000,
        lambda rng: np.array([7]),
        lambda rng: np.array([12_500]),       # single non-zero owner
        lambda rng: np.array([0, 99_999]),    # extremes
    ])
    def test_matches_mask_reference(self, table, make):
        uids = np.asarray(make(np.random.default_rng(3)), np.int64)
        got = {r: uids[ix].tolist()
               for r, ix in table._owner_slices(uids)}
        assert got == self._ref(table, uids)

    def test_empty(self, table):
        assert table._owner_slices(np.array([], np.int64)) == []

    def test_sorted_batches_get_zero_copy_slices(self, table):
        uids = np.unique(np.random.default_rng(0).integers(0, 100_000,
                                                           4000))
        assert all(isinstance(ix, slice)
                   for _r, ix in table._owner_slices(uids))


class TestFanoutRouting:
    """Flag ps_fanout: in-process routing + multi-owner super-frames,
    bit-identical to the classic plane."""

    @pytest.mark.parametrize("plane", ["native", "python"])
    def test_fanout_parity_four_shards(self, tmp_path, plane):
        rows, dim = 80, 6
        want = _oracle(tmp_path, rows, dim)
        config.set_flag("ps_native", plane == "native")
        config.set_flag("ps_fanout", True)
        ctxs = _world(tmp_path, 4, sub="fan")
        tabs = [AsyncMatrixTable(rows, dim, name="fan_t", ctx=c)
                for c in ctxs]
        assert tabs[0]._fanout and tabs[0]._routed_set == {1, 2, 3}
        assert not tabs[0]._native_ok   # routing pins python ordering
        rng = np.random.default_rng(7)
        for step in range(12):
            k = int(rng.integers(3, rows // 2))
            ids = np.sort(rng.choice(rows, size=k, replace=False))
            vals = rng.normal(size=(k, dim)).astype(np.float32)
            tabs[step % 4].add_rows(ids, vals)
        got = tabs[1].get_rows(np.arange(rows))
        np.testing.assert_array_equal(got, want)
        # multi-owner get with caller-order duplicate ids and out=
        ids = np.array([71, 3, 25, 3, 60, 71])
        out = np.empty((ids.size, dim), np.float32)
        res = tabs[2].get_rows(ids, out=out)
        np.testing.assert_array_equal(res, want[ids])
        assert res is out
        _close(ctxs)

    def test_fanout_unsorted_caller_order_ids(self, tmp_path):
        """_prep's no-dup fast path keeps caller order — the fan-out
        partition must still route and reassemble exactly."""
        rows, dim = 64, 5
        want = _oracle(tmp_path, rows, dim, sort_ids=False)
        config.set_flag("ps_fanout", True)
        ctxs = _world(tmp_path, 4, sub="uns")
        tabs = [AsyncMatrixTable(rows, dim, name="uns_t", ctx=c)
                for c in ctxs]
        for t in [tabs[0]]:
            _drive(t, rows, dim, sort_ids=False)
        got = tabs[3].get_rows(np.arange(rows))
        np.testing.assert_array_equal(got, want)
        _close(ctxs)

    def test_read_your_writes_inline(self, tmp_path):
        config.set_flag("ps_fanout", True)
        ctxs = _world(tmp_path, 4, sub="ryw")
        tabs = [AsyncMatrixTable(40, 3, name="ryw_t", ctx=c)
                for c in ctxs]
        ids = np.arange(40)
        ones = np.ones((40, 3), np.float32)
        for k in range(5):
            tabs[0].add_rows_async(ids, ones)
            got = tabs[0].get_rows(ids)
            np.testing.assert_array_equal(
                got, np.full((40, 3), float(k + 1), np.float32))
        _close(ctxs)

    def test_routed_rank_death_fails_fast_and_fires_hooks(self,
                                                         tmp_path):
        config.set_flag("ps_fanout", True)
        ctxs = _world(tmp_path, 2, sub="die")
        tabs = [AsyncMatrixTable(40, 3, name="die_t", ctx=c)
                for c in ctxs]
        deaths = []
        ctxs[0].service.add_death_hook(deaths.append)
        ids = np.arange(40)
        tabs[0].add_rows(ids, np.ones((40, 3), np.float32))
        ctxs[1].close()
        with pytest.raises(PSPeerError):
            # rows 20..39 belong to the dead rank 1
            tabs[0].get_rows(np.arange(20, 40))
        assert deaths == [1]
        # a MULTI-owner op spanning the dead rank keeps the TYPED
        # peer error through the super-frame (code-review finding:
        # callers branch on PSPeerError vs PSError)
        with pytest.raises(PSPeerError):
            tabs[0].get_rows(np.arange(40))
        # rank 0's own shard keeps serving
        got = tabs[0].get_rows(np.arange(0, 20))
        np.testing.assert_array_equal(got,
                                      np.ones((20, 3), np.float32))
        ctxs[0].close()

    def test_multi_local_per_sub_error_independence(self, tmp_path):
        config.set_flag("ps_fanout", True)
        ctxs = _world(tmp_path, 2, sub="err")
        tabs = [AsyncMatrixTable(40, 3, name="err_t", ctx=c)
                for c in ctxs]
        ones = np.ones((3, 3), np.float32)
        subs = [
            (MSG_ADD_ROWS,
             {"table": "err_t", "opt": {}, "ow": 0},
             [np.array([1, 2, 3]), ones]),
            (MSG_ADD_ROWS,
             {"table": "err_t", "opt": {}, "ow": 1},
             [np.array([999, 1000, 1001]), ones]),   # out of range
        ]
        futs = ctxs[0].service.multi_local(subs)
        futs[0].result(timeout=10)
        with pytest.raises(PSError):
            futs[1].result(timeout=10)
        got = tabs[0].get_rows(np.array([1, 2, 3]))
        np.testing.assert_array_equal(got, ones)
        _close(ctxs)


class TestWireMulti:
    """MSG_MULTI over a REAL socket (the cross-process form): the
    native server punts it like MSG_BATCH; the python server serves it
    in _serve_conn. Sub-ops resolve by owner meta."""

    @pytest.mark.parametrize("plane", ["native", "python"])
    def test_super_frame_over_socket(self, tmp_path, plane):
        config.set_flag("ps_native", plane == "native")
        ctxs = _world(tmp_path, 2, sub="wire")
        tabs = [AsyncMatrixTable(40, 4, name="wire_t", ctx=c)
                for c in ctxs]
        ids = np.array([25, 30])          # rank 1's rows
        vals = np.ones((2, 4), np.float32)
        blobs = [wire_mod.encode(
            MSG_ADD_ROWS, 0,
            {"table": "wire_t", "opt": {},
             wire_mod.OWNER_META_KEY: 1}, [ids, vals]),
            wire_mod.encode(
            MSG_GET_ROWS, 1,
            {"table": "wire_t", "wire": "none",
             wire_mod.OWNER_META_KEY: 1}, [ids])]
        # rank 0 -> rank 1 over the real socket (no routing armed)
        fut = ctxs[0].service.request(1, MSG_MULTI, {"n": 2},
                                      wire_mod.pack_batch(blobs))
        rmeta, rarrays = fut.result(timeout=20)
        assert rmeta["n"] == 2
        subs = wire_mod.unpack_batch(rarrays)
        assert len(subs) == 2
        assert subs[0][0] != MSG_REPLY_ERR
        rows = np.asarray(subs[1][2][0], np.float32).reshape(2, 4)
        np.testing.assert_array_equal(rows, vals)
        _close(ctxs)


def _stack_world(tmp_path, n, rows, dim, updater="adagrad", sub="st",
                 name="st_t"):
    config.set_flag("ps_fanout", True)
    config.set_flag("ps_spmd_stack", True)
    ctxs = _world(tmp_path, n, sub=sub)
    tabs = [AsyncMatrixTable(rows, dim, name=name, updater=updater,
                             ctx=c) for c in ctxs]
    return ctxs, tabs


class TestMeshStack:
    """The stacked SPMD shard groups (flag ps_spmd_stack)."""

    @pytest.mark.parametrize("updater", ["adagrad", "momentum_sgd"])
    def test_grouped_parity_vs_oracle(self, tmp_path, updater):
        rows, dim = 96, 5
        want = _oracle(tmp_path, rows, dim, updater=updater)
        ctxs, tabs = _stack_world(tmp_path, 4, rows, dim,
                                  updater=updater)
        sh = tabs[0]._shard
        assert sh._plane is not None and sh._plane.active
        assert sh._plane.mesh is not None   # real 4-device placement
        for i, t in enumerate(tabs):
            assert t._shard._plane is sh._plane
            assert t._shard._plane_slot == i
        _drive(tabs[0], rows, dim)
        got = tabs[2].get_rows(np.arange(rows))
        np.testing.assert_array_equal(got, want)
        _close(ctxs)

    def test_uneven_last_shard_parity(self, tmp_path):
        """rows not divisible by world: the last shard is smaller and
        its slab pads to the group's max — ids near the boundary must
        still route and apply exactly."""
        rows, dim = 70, 3   # 4 shards: 18/18/18/16
        want = _oracle(tmp_path, rows, dim, updater="adagrad")
        ctxs, tabs = _stack_world(tmp_path, 4, rows, dim, sub="odd",
                                  name="odd_t")
        _drive(tabs[0], rows, dim)
        np.testing.assert_array_equal(
            tabs[1].get_rows(np.arange(rows)), want)
        _close(ctxs)

    def test_np_shards_never_group(self, tmp_path):
        ctxs, tabs = _stack_world(tmp_path, 2, 40, 3,
                                  updater="default", sub="np",
                                  name="np_t")
        assert tabs[0]._shard._plane is None   # np_mode stays classic
        _close(ctxs)

    def test_grouped_dispatch_counts(self, tmp_path):
        """A multi-owner fan-out add lands as ONE plane dispatch, not
        one per shard — the whole point."""
        rows, dim = 64, 4
        ctxs, tabs = _stack_world(tmp_path, 4, rows, dim, sub="disp",
                                  name="disp_t")
        plane = tabs[0]._shard._plane
        before = plane._dispatches
        ids = np.arange(rows)   # spans all 4 shards
        tabs[0].add_rows(ids, np.ones((rows, dim), np.float32))
        assert plane._dispatches == before + 1
        sp = tabs[0].server_stats()["shards"]["disp_t"]["spmd"]
        assert sp["members"] == 4 and sp["dispatches"] >= 1
        assert sp["applies"] >= 1
        _close(ctxs)

    def test_zero_steady_recompiles(self, tmp_path):
        """Same-bucket grouped applies/gathers reuse ONE compiled
        program — the program cache is keyed by bucket only."""
        rows, dim = 64, 4
        ctxs, tabs = _stack_world(tmp_path, 2, rows, dim, sub="re",
                                  name="re_t")
        plane = tabs[0]._shard._plane
        ids = np.arange(0, 48)
        vals = np.ones((48, dim), np.float32)
        tabs[0].add_rows(ids, vals)
        tabs[0].get_rows(ids)
        progs = dict(plane._progs)
        for _ in range(5):
            tabs[0].add_rows(ids, vals)
            tabs[0].get_rows(ids)
        assert dict(plane._progs) == progs   # no new programs
        _close(ctxs)

    def test_eviction_on_exotic_mutations(self, tmp_path):
        rows, dim = 48, 3
        ctxs, tabs = _stack_world(tmp_path, 2, rows, dim, sub="ev",
                                  name="ev_t")
        sh0 = tabs[0]._shard
        plane = sh0._plane
        assert plane is not None
        ids = np.arange(rows)
        ones = np.ones((rows, dim), np.float32)
        zero10 = np.zeros((10, dim), np.float32)

        def scenario(t_add, t_set):
            t_add.add_rows(ids, ones)
            t_set.set_rows(np.arange(0, 10), zero10)
            t_add.add_rows(ids, ones)           # post-evict apply
            return t_add.get_rows(ids)

        got = scenario(tabs[0], tabs[1])
        # set_rows targeted shard 0's rows: IT evicted, sibling stayed
        assert sh0._plane is None
        assert tabs[1]._shard._plane is plane
        # oracle: the same op sequence on a 1-shard classic world
        config.set_flag("ps_fanout", False)
        config.set_flag("ps_spmd_stack", False)
        octx = _world(tmp_path, 1, sub="evo")
        ot = AsyncMatrixTable(rows, dim, name="ev_o",
                              updater="adagrad", ctx=octx[0])
        want = scenario(ot, ot)
        _close(octx)
        np.testing.assert_array_equal(got, want)
        _close(ctxs)

    def test_grouped_checkpoint_roundtrip(self, tmp_path):
        """checkpoint_state of a grouped shard is an OWNED consistent
        snapshot; restore lands in classic storage and serves the same
        bytes."""
        rows, dim = 48, 3
        ctxs, tabs = _stack_world(tmp_path, 2, rows, dim,
                                  updater="adagrad", sub="ck",
                                  name="ck_t")
        _drive(tabs[0], rows, dim, steps=6)
        sh = tabs[0]._shard
        before = tabs[0].get_rows(np.arange(rows))
        meta, arrays = sh.checkpoint_state()
        # mutate, then restore: the shard must return to the snapshot
        tabs[0].add_rows(np.arange(rows),
                         np.ones((rows, dim), np.float32))
        sh.restore_checkpoint(meta, arrays)
        assert sh._plane is None   # restore evicts
        after = tabs[0].get_rows(np.arange(rows))
        np.testing.assert_array_equal(
            after[: sh.n], before[: sh.n])
        _close(ctxs)

    def test_concurrent_mixed_clients_sum_exactly(self, tmp_path):
        """Two client threads hammering a grouped table through the
        fan-out plane: the grand total must be exact (the plane lock
        serializes grouped dispatches; per-shard waves stay ordered)."""
        rows, dim = 64, 4
        ctxs, tabs = _stack_world(tmp_path, 2, rows, dim,
                                  updater="adagrad", sub="hm",
                                  name="hm_t")
        # adagrad is deterministic only per-order; use disjoint rows
        # per thread so order across threads cannot matter
        halves = [np.arange(0, 32), np.arange(32, 64)]
        ones = np.ones((32, dim), np.float32)

        def work(w):
            for _ in range(10):
                tabs[w].add_rows(halves[w], ones)

        ths = [threading.Thread(target=work, args=(w,))
               for w in range(2)]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        got = tabs[0].get_rows(np.arange(rows))
        # oracle: 10 sequential adagrad applies of ones per row block
        config.set_flag("ps_fanout", False)
        config.set_flag("ps_spmd_stack", False)
        octx = _world(tmp_path, 1, sub="hmo")
        ot = AsyncMatrixTable(rows, dim, name="hm_o",
                              updater="adagrad", ctx=octx[0])
        for w in range(2):
            for _ in range(10):
                ot.add_rows(halves[w], ones)
        want = ot.get_rows(np.arange(rows))
        _close(octx)
        np.testing.assert_array_equal(got, want)
        _close(ctxs)

    def test_snapshot_rpc_on_grouped_shard(self, tmp_path):
        """MSG_SNAPSHOT (the serving plane's pull) over a grouped
        shard: the advertised version and the copied bytes are read
        under the plane lock — one epoch, exact rows — and the
        since-version dedupe still answers 'unchanged'."""
        rows, dim = 48, 3
        ctxs, tabs = _stack_world(tmp_path, 2, rows, dim,
                                  updater="adagrad", sub="sn",
                                  name="sn_t")
        _drive(tabs[0], rows, dim, steps=5)
        sh = tabs[0]._shard
        assert sh._plane is not None
        meta, payload = sh.export_snapshot({})
        want = tabs[0].get_rows(np.arange(sh.lo, sh.hi))
        got = np.asarray(payload[0], np.float32).reshape(sh.n, dim)
        np.testing.assert_array_equal(got, want)
        meta2, _ = sh.export_snapshot(
            {"since": meta["version"], "since_gen": meta["gen"]})
        assert meta2.get("unchanged") is True
        _close(ctxs)

    def test_memory_gauges(self, tmp_path):
        rows, dim = 64, 4
        ctxs, tabs = _stack_world(tmp_path, 2, rows, dim, sub="mem",
                                  name="mem_t")
        sh = tabs[0]._shard
        ms = sh.memory_stats()
        assert ms["table_bytes"] > 0 and ms.get("spmd") is True
        pm = sh._plane.memory_stats()
        assert pm["stack_bytes"] > 0 and pm["live_slots"] == 2
        _close(ctxs)


class TestWindowFanout:
    """Windowed adds through the coalesced multi-owner flush."""

    def test_windowed_fanout_parity(self, tmp_path):
        rows, dim = 80, 4
        want = _oracle(tmp_path, rows, dim)
        config.set_flag("ps_fanout", True)
        ctxs = _world(tmp_path, 4, sub="win")
        tabs = [AsyncMatrixTable(rows, dim, name="win_t",
                                 send_window_ms=4.0, ctx=c)
                for c in ctxs]
        # ONE client drives the stream (cross-CLIENT arrival order was
        # never promised; per-client window order is the contract the
        # coalesced multi-owner flush must preserve)
        t = tabs[0]
        rng = np.random.default_rng(7)
        for step in range(12):
            k = int(rng.integers(3, rows // 2))
            ids = np.sort(rng.choice(rows, size=k, replace=False))
            vals = rng.normal(size=(k, dim)).astype(np.float32)
            t.add_rows_async(ids, vals)
            if step % 3 == 2:
                t.flush()
        t.flush()
        got = tabs[1].get_rows(np.arange(rows))
        np.testing.assert_array_equal(got, want)
        _close(ctxs)


class TestPlacementSurfaces:
    def test_mvtop_placement_panel(self, tmp_path):
        import sys
        sys.path.insert(0, "tools")
        import mvtop
        from multiverso_tpu.telemetry import aggregator
        rows, dim = 64, 4
        ctxs, tabs = _stack_world(tmp_path, 2, rows, dim, sub="top",
                                  name="top_t")
        tabs[0].add_rows(np.arange(rows),
                         np.ones((rows, dim), np.float32))
        stats = {c.rank: c.service.stats_payload() for c in ctxs}
        health = {c.rank: c.service.health_payload() for c in ctxs}
        rec = aggregator.merge_cluster(stats, health, world=2)
        txt = mvtop.render(rec)
        assert "placement:" in txt
        assert "slot0" in txt and "slot1" in txt
        assert "spmd group: 2 shards stacked" in txt
        _close(ctxs)

    def test_placement_panel_renders_without_spmd(self, tmp_path):
        """Classic multi-shard tables render the panel too (apply
        share from the plain counters; device 'classic')."""
        import sys
        sys.path.insert(0, "tools")
        import mvtop
        from multiverso_tpu.telemetry import aggregator
        ctxs = _world(tmp_path, 2, sub="cls")
        tabs = [AsyncMatrixTable(40, 3, name="cls_t", ctx=c)
                for c in ctxs]
        tabs[0].add_rows(np.arange(40), np.ones((40, 3), np.float32))
        stats = {c.rank: c.service.stats_payload() for c in ctxs}
        health = {c.rank: c.service.health_payload() for c in ctxs}
        rec = aggregator.merge_cluster(stats, health, world=2)
        txt = mvtop.render(rec)
        assert "placement:" in txt and "@classic" in txt
        _close(ctxs)


class TestObsLint:
    def test_obs_surface_clean(self):
        import sys
        sys.path.insert(0, "tools")
        import check_obs_surface
        findings = check_obs_surface.check()
        assert findings == []
