"""lightLDA-style topic model (models/lda.py): sparse push/pull training
over SparseMatrixTable recovers planted topics on the 8-device mesh."""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models import lda


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


def _purity(word_topics, labels, k):
    """Best-case agreement after matching learned topics to planted ones
    (greedy by confusion-matrix mass)."""
    conf = np.zeros((k, k))
    for w, t in enumerate(word_topics):
        conf[labels[w], t] += 1
    return conf.max(axis=1).sum() / conf.sum()


def test_recovers_planted_topics():
    cfg = lda.LDAConfig(vocab_size=400, num_topics=4, doc_len=32,
                        em_iters=4)
    table = mv.SparseMatrixTable(cfg.vocab_size, cfg.num_topics,
                                 name="lda_phi", num_workers=1)
    trainer = lda.LDATrainer(cfg, table)
    docs, labels = lda.synthetic_corpus(cfg, 600, seed=3)
    lls = []
    for epoch in range(3):
        for lo in range(0, len(docs), 64):
            lls.append(trainer.train_batch(docs[lo: lo + 64]))
    # likelihood ascends over training
    assert np.mean(lls[-5:]) > np.mean(lls[:5]) + 0.1, (
        np.mean(lls[:5]), np.mean(lls[-5:]))
    purity = _purity(trainer.word_topics(), labels, cfg.num_topics)
    assert purity > 0.85, purity


def test_sparse_pull_moves_only_stale_rows():
    cfg = lda.LDAConfig(vocab_size=256, num_topics=4, doc_len=16)
    table = mv.SparseMatrixTable(cfg.vocab_size, cfg.num_topics,
                                 name="lda_stale", num_workers=1)
    trainer = lda.LDATrainer(cfg, table)
    docs, _ = lda.synthetic_corpus(cfg, 64, seed=5)
    trainer.train_batch(docs[:32])
    # rows untouched by the first batch are still stale; touched rows that
    # were pulled and not re-added since are fresh for this worker
    touched = np.unique(docs[:32].reshape(-1))
    untouched = np.setdiff1d(np.arange(cfg.vocab_size), touched)[:10]
    if untouched.size:
        assert table.stale_fraction(untouched) == 1.0
    # after the add, the touched rows are stale again (the push dirtied
    # them for every worker, ref matrix.cpp up_to_date_ reset)
    assert table.stale_fraction(touched) == 1.0


def test_batch_step_counts_are_conserved():
    """Each token contributes exactly one expected count: the delta's
    total mass equals the number of tokens in the batch."""
    cfg = lda.LDAConfig(vocab_size=64, num_topics=4, doc_len=8, em_iters=3)
    step = lda.make_batch_step(cfg)
    rng = np.random.default_rng(0)
    u = 20
    phi_rows = rng.uniform(0.0, 2.0, (u, cfg.num_topics)).astype(np.float32)
    docs_local = rng.integers(0, u, (6, cfg.doc_len)).astype(np.int32)
    delta, theta, ll = step(phi_rows, docs_local)
    np.testing.assert_allclose(float(np.sum(np.asarray(delta))),
                               6 * cfg.doc_len, rtol=1e-4)
    np.testing.assert_allclose(np.sum(np.asarray(theta), axis=1), 1.0,
                               rtol=1e-5)
    assert np.isfinite(float(ll))


def test_recovers_planted_topics_on_async_plane(two_ranks):
    """The LDA sparse push/pull loop runs UNCHANGED over the uncoordinated
    plane: two workers, each training its own document subset against
    AsyncSparseMatrixTable shards (stale-only pulls over real sockets),
    recover the planted topics — the third app family on the async PS."""
    from multiverso_tpu.ps.tables import AsyncSparseMatrixTable

    cfg = lda.LDAConfig(vocab_size=400, num_topics=4, doc_len=32,
                        em_iters=4)
    tables = [AsyncSparseMatrixTable(
                  cfg.vocab_size, cfg.num_topics, name="lda_async",
                  num_workers=2, ctx=two_ranks[r]) for r in range(2)]
    trainers = [lda.LDATrainer(cfg, tables[r], worker_id=r)
                for r in range(2)]
    docs, labels = lda.synthetic_corpus(cfg, 600, seed=3)
    lls = []
    for epoch in range(3):
        for lo in range(0, len(docs), 64):
            w = (lo // 64) % 2          # alternate batches per worker
            lls.append(trainers[w].train_batch(docs[lo: lo + 64]))
    assert np.mean(lls[-5:]) > np.mean(lls[:5]) + 0.1
    # both workers read the same converged global table
    for r in range(2):
        purity = _purity(trainers[r].word_topics(), labels,
                         cfg.num_topics)
        assert purity > 0.85, (r, purity)
