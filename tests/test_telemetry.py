"""Telemetry plane (PR 3): histogram math vs a numpy reference, the
Monitor upgrade (percentiles, thread-safe begin/end, immutable
snapshots, functools.wraps), trace-ID round-trips through the wire
(including MSG_BATCH inner frames), the MSG_STATS remote-dashboard RPC
against a live 2-rank PS, and the exporter file formats. All tier-1
(CPU, seconds)."""

import json
import os
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.ps import service as svc
from multiverso_tpu.ps import wire
from multiverso_tpu.telemetry import trace as ttrace
from multiverso_tpu.telemetry.exporter import (MetricsExporter,
                                               prometheus_text)
from multiverso_tpu.telemetry.histogram import (BOUNDS, NBUCKETS,
                                                Histogram, bucket_index)
from multiverso_tpu.utils import config
from multiverso_tpu.utils.dashboard import (Dashboard, Monitor,
                                            MonitorSnapshot, monitor,
                                            monitored)


# ---------------------------------------------------------------------- #
# histogram math
# ---------------------------------------------------------------------- #
class TestHistogram:
    def test_bucket_index_monotone_and_bounded(self):
        idxs = [bucket_index(ms) for ms in
                (0.0, 1e-9, 1e-5, 0.001, 0.1, 1.0, 42.0, 1e4, 1e9)]
        assert idxs == sorted(idxs)
        assert all(0 <= i < NBUCKETS for i in idxs)
        # every bound maps inside its own bucket's range
        for i in (0, 7, NBUCKETS // 2, NBUCKETS - 1):
            assert bucket_index(BOUNDS[i] * 0.999) == i

    @pytest.mark.parametrize("sigma", [0.5, 1.5])
    def test_percentiles_vs_numpy(self, sigma):
        """Bucket-interpolated quantiles vs np.percentile on the raw
        samples: within one bucket width (~19% relative) everywhere, and
        min/max exact."""
        rng = np.random.default_rng(7)
        samples = rng.lognormal(mean=0.0, sigma=sigma, size=20_000)
        h = Histogram()
        for s in samples:
            h.observe(float(s))
        assert h.count == samples.size
        assert h.max == samples.max() and h.min == samples.min()
        np.testing.assert_allclose(h.sum, samples.sum(), rtol=1e-9)
        for q in (1, 25, 50, 90, 99, 99.9):
            ref = float(np.percentile(samples, q))
            assert abs(h.percentile(q) - ref) / ref < 0.19, q

    def test_merge_equals_union(self):
        rng = np.random.default_rng(8)
        a, b = Histogram(), Histogram()
        sa = rng.exponential(2.0, 500)
        sb = rng.exponential(0.1, 700)
        for s in sa:
            a.observe(float(s))
        for s in sb:
            b.observe(float(s))
        a.merge(b)
        u = Histogram()
        for s in np.concatenate([sa, sb]):
            u.observe(float(s))
        assert a.counts == u.counts
        assert a.count == u.count and a.max == u.max and a.min == u.min

    def test_sparse_round_trip(self):
        h = Histogram()
        for s in (0.01, 0.02, 5.0, 5.1, 900.0):
            h.observe(s)
        d = h.as_dict()
        back = Histogram.from_nonzero(d["buckets"], count=d["count"],
                                      total=d["sum_ms"],
                                      min_ms=d["min_ms"],
                                      max_ms=d["max_ms"])
        assert back.counts == h.counts
        assert back.count == h.count and back.max == h.max

    def test_out_of_range_clamps(self):
        h = Histogram()
        h.observe(0.0)       # below range -> bucket 0, still counted
        h.observe(1e12)      # above range -> last bucket
        assert h.count == 2
        assert h.counts[0] == 1 and h.counts[-1] == 1


# ---------------------------------------------------------------------- #
# Monitor upgrade
# ---------------------------------------------------------------------- #
class TestMonitor:
    def test_percentiles_in_info_string(self):
        m = Monitor("t")
        for ms in (1.0, 2.0, 100.0):
            m.observe_ms(ms)
        s = m.info_string()
        assert "p50 =" in s and "p99 =" in s and "max =" in s
        assert m.p99_ms >= m.p50_ms > 0
        assert m.max_ms == 100.0

    def test_incr_does_not_pollute_histogram(self):
        """Counter-style monitors (window flushes etc.) bump count only;
        the percentile line must not appear for pure counters."""
        m = Monitor("c")
        m.incr(5)
        assert m.count == 5
        assert m.snapshot().timed == 0
        assert "p50" not in m.info_string()

    def test_begin_end_thread_safe(self):
        """Regression (satellite): the paired begin/end API used one
        shared slot — two threads interleaving begin/end dropped or
        corrupted samples. Per-thread stamps must give exactly one
        sample per begin/end pair, each with ITS thread's duration."""
        m = Monitor("r")
        n_per = 200
        barrier = threading.Barrier(2)

        def worker(sleep_s):
            barrier.wait()
            for _ in range(n_per):
                m.begin()
                if sleep_s:
                    time.sleep(sleep_s)
                m.end()

        t1 = threading.Thread(target=worker, args=(0.0,))
        t2 = threading.Thread(target=worker, args=(0.001,))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert m.count == 2 * n_per
        # the slow thread's ~1ms samples survive interleaving: the p90
        # of the pooled distribution must see them (the old shared slot
        # lost/mixed them)
        assert m.percentile(90) >= 0.5

    def test_end_without_begin_is_noop(self):
        m = Monitor("x")
        m.end()
        assert m.count == 0

    def test_monitored_preserves_metadata(self):
        @monitored("api.fn")
        def fn(a, b=2):
            """the docstring"""
            return a + b

        assert fn.__name__ == "fn"
        assert fn.__doc__ == "the docstring"
        assert fn.__wrapped__ is not None
        assert fn(1) == 3
        assert Dashboard.get("api.fn").count == 1

    def test_snapshot_is_immutable_and_detached(self):
        with monitor("s"):
            pass
        snap = Dashboard.snapshot()["s"]
        assert isinstance(snap, MonitorSnapshot)
        with pytest.raises(Exception):   # frozen dataclass
            snap.count = 99
        before = snap.count
        with monitor("s"):
            pass
        assert snap.count == before          # detached from the live mon
        assert Dashboard.get("s").count == before + 1
        d = snap.hist_dict()
        json.dumps(d)                        # JSON-safe
        assert d["count"] == before


# ---------------------------------------------------------------------- #
# trace IDs: wire round-trip
# ---------------------------------------------------------------------- #
class TestTraceWire:
    def test_meta_round_trip(self):
        tid = 0x1234_5678_9ABC
        meta = wire.with_trace({"table": "t"}, tid)
        frame = wire.encode(svc.MSG_ADD_ROWS, 7, meta,
                            [np.arange(3, dtype=np.int64)])
        mt, mid, m, arrs = wire.parse_frame(frame)
        assert m[wire.TRACE_META_KEY] == tid
        assert mt == svc.MSG_ADD_ROWS and mid == 7

    def test_with_trace_none_is_passthrough(self):
        meta = {"table": "t"}
        assert wire.with_trace(meta, None) is meta

    def test_batch_inner_frames_keep_per_op_trace(self):
        """Every MSG_BATCH sub-op carries its OWN trace ID through
        pack/unpack — per-logical-op correlation survives windowing."""
        tids = [ttrace.TRACER.new_id() for _ in range(4)]
        blobs = [wire.encode(svc.MSG_ADD_ROWS, i,
                             wire.with_trace({"table": "t"}, tid),
                             [np.array([i], np.int64),
                              np.ones((1, 2), np.float32)])
                 for i, tid in enumerate(tids)]
        subs = wire.unpack_batch(wire.pack_batch(blobs))
        assert [m[wire.TRACE_META_KEY] for _, m, _ in subs] == tids
        assert len(set(tids)) == 4   # IDs are distinct

    def test_new_id_embeds_rank(self):
        tr = ttrace.Tracer()
        tr.rank = 5
        a, b = tr.new_id(), tr.new_id()
        assert a != b
        assert (a >> 32) & 0xFFFF == 5


class TestTracer:
    def test_disabled_records_nothing(self):
        tr = ttrace.Tracer()
        tr.add_span("x", 0.0, 1.0, trace=1)
        with tr.span("y"):
            pass
        assert tr.events() == []

    def test_span_shape_and_dump(self, tmp_path):
        tr = ttrace.Tracer()
        tr.enabled = True
        tr.rank = 3
        t0 = time.time()
        tr.add_span("op", t0, t0 + 0.001, trace=42, args={"k": "v"})
        [e] = tr.events()
        assert e["ph"] == "X" and e["pid"] == 3
        assert e["args"]["trace"] == 42 and e["args"]["k"] == "v"
        assert e["dur"] >= 900   # us
        path = str(tmp_path / "t.jsonl")
        assert tr.dump(path) == 1
        assert tr.dump(path) == 0      # buffer drained
        with open(path) as f:
            lines = [json.loads(x) for x in f if x.strip()]
        assert lines == [e]


# ---------------------------------------------------------------------- #
# MSG_STATS against a live 2-rank PS (in-process, real sockets)
# ---------------------------------------------------------------------- #
class TestMsgStats:
    def test_remote_dashboard_pull(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 4, name="st", ctx=two_ranks[0])
        AsyncMatrixTable(16, 4, name="st", ctx=two_ranks[1])
        t0.add_rows([9], np.ones((1, 4), np.float32))   # remote-owned
        st = t0.server_stats(1)
        assert st["rank"] == 1 and st["world"] == 2
        sh = st["shards"]["st"]
        assert sh["kind"] == "row" and sh["rows"] == 8 and sh["lo"] == 8
        assert sh["adds"] >= 1 and sh["applies"] >= 1
        assert sh["version"] >= 1
        assert sh["queue_depth"] == 0 and sh["pending_bytes"] == 0
        json.dumps(st)   # whole payload is wire/JSON-safe
        # local short-circuit returns this rank's own registry
        local = t0.server_stats()
        assert local["rank"] == 0 and "st" in local["shards"]

    def test_windowed_adds_tick_wave_stats(self, two_ranks):
        """MSG_BATCH frames apply as python-side waves (the native
        server punts them), so the wave-size distribution and apply
        histogram must tick — the server-side view of the send window's
        realized batching."""
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 4, name="wv", send_window_ms=30_000.0,
                              ctx=two_ranks[0])
        AsyncMatrixTable(16, 4, name="wv", ctx=two_ranks[1])
        for _ in range(3):   # same row: conflicting ops -> 3 sub-ops
            t0.add_rows_async([9], np.ones((1, 4), np.float32))
        t0.flush()
        sh = t0.server_stats(1)["shards"]["wv"]
        assert sh["adds"] >= 3
        assert sh["wave_max_ops"] >= 1
        assert sum(sh["wave_ops"].values()) >= 3
        assert sh["apply"]["count"] >= 3
        assert sh["apply"]["p50_ms"] > 0

    def test_stats_of_dead_rank_raises_typed(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 4, name="sd", ctx=two_ranks[0])
        AsyncMatrixTable(16, 4, name="sd", ctx=two_ranks[1])
        config.set_flag("ps_timeout", 4.0)
        config.set_flag("ps_connect_timeout", 2.0)
        two_ranks[1].service.close()
        with pytest.raises(svc.PSPeerError):
            t0.server_stats(1)

    def test_hash_and_kv_shards_report(self, two_ranks):
        from multiverso_tpu.ps.tables import (AsyncKVTable,
                                              AsyncSparseKVTable)
        t = AsyncSparseKVTable(4, name="hk", ctx=two_ranks[0])
        AsyncSparseKVTable(4, name="hk", ctx=two_ranks[1])
        kv = AsyncKVTable(name="kvt", ctx=two_ranks[0])
        AsyncKVTable(name="kvt", ctx=two_ranks[1])
        t.add_rows([3], np.ones((1, 4), np.float32))   # key 3 -> rank 1
        kv.add([0, 1], [1.0, 2.0])
        st = t.server_stats(1)
        assert st["shards"]["hk"]["kind"] == "hash"
        assert st["shards"]["hk"]["keys"] >= 1
        assert st["shards"]["kvt"]["kind"] == "kv"
        assert st["shards"]["kvt"]["keys"] >= 1


# ---------------------------------------------------------------------- #
# exporter file formats
# ---------------------------------------------------------------------- #
class TestExporter:
    def _payload(self):
        with monitor("e.op"):
            time.sleep(0.001)
        return {
            "rank": 0,
            "monitors": {n: s.hist_dict()
                         for n, s in Dashboard.snapshot().items()},
            "notes": Dashboard.notes(),
            "shards": {"t": {"kind": "row", "adds": 3, "queue_depth": 0}},
        }

    def test_jsonl_and_prom_files(self, tmp_path):
        exp = MetricsExporter(0, str(tmp_path), 0.0, self._payload)
        rec = exp.export_once()
        assert rec["monitors"]["e.op"]["count"] == 1
        jpath = tmp_path / "metrics-rank0.jsonl"
        ppath = tmp_path / "metrics-rank0.prom"
        assert jpath.exists() and ppath.exists()
        exp.export_once()   # JSONL appends; prom replaces
        with open(jpath) as f:
            recs = [json.loads(x) for x in f if x.strip()]
        assert len(recs) == 2
        assert recs[0]["ts"] <= recs[1]["ts"]
        assert recs[1]["monitors"]["e.op"]["p50_ms"] > 0
        prom = ppath.read_text()
        assert 'mv_monitor_count{name="e.op",rank="0"} ' in prom
        assert "mv_monitor_p50_ms" in prom
        assert 'mv_shard_adds{table="t",rank="0"} 3' in prom

    def test_stop_writes_final_snapshot(self, tmp_path):
        exp = MetricsExporter(1, str(tmp_path), 0.0, self._payload)
        exp.start()       # interval 0: no thread
        assert exp._thread is None
        exp.stop()
        assert (tmp_path / "metrics-rank1.jsonl").exists()

    def test_interval_thread_exports(self, tmp_path):
        exp = MetricsExporter(2, str(tmp_path), 0.05, self._payload)
        exp.start()
        deadline = time.monotonic() + 5.0
        jpath = tmp_path / "metrics-rank2.jsonl"
        while time.monotonic() < deadline and not jpath.exists():
            time.sleep(0.02)
        exp.stop()
        assert jpath.exists()

    def test_prometheus_text_escapes_quotes(self):
        txt = prometheus_text({"rank": 0, "monitors": {
            'bad"name': {"count": 1, "sum_ms": 1.0}}, "shards": {}})
        assert '"bad\'name"' in txt


# ---------------------------------------------------------------------- #
# exporter wiring: the service starts it from flags
# ---------------------------------------------------------------------- #
def test_service_flag_gated_exporter(tmp_path):
    from multiverso_tpu.ps.service import PSContext, PSService
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    mdir = str(tmp_path / "m")
    config.set_flag("metrics_dir", mdir)
    config.set_flag("metrics_interval_s", 0.0)   # final snapshot only
    ctx = PSContext(0, 1, PSService(0, 1))
    t = AsyncMatrixTable(8, 2, name="exp", ctx=ctx)
    t.add_rows([1], np.ones((1, 2), np.float32))
    ctx.close()
    path = os.path.join(mdir, "metrics-rank0.jsonl")
    assert os.path.exists(path)
    with open(path) as f:
        rec = json.loads(f.readlines()[-1])
    assert "exp" in rec["shards"]
    assert rec["shards"]["exp"]["adds"] >= 1
    assert any(n.startswith("table[exp]") for n in rec["monitors"])
