"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch ring vs a
sequential oracle on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import multiverso_tpu as mv
from multiverso_tpu.parallel import pipeline


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


def _stages(n, d, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(0, 0.5, (n, d, d)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(0, 0.1, (n, d)).astype(np.float32)),
    }


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _oracle(params, x):
    for i in range(params["w"].shape[0]):
        x = _stage_fn({"w": params["w"][i], "b": params["b"][i]}, x)
    return x


class TestPipeline:
    def test_matches_sequential(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(8, 16)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(32, 16)).astype(np.float32))
        expect = _oracle(params, x)
        got = pipeline.pipeline_apply(
            _stage_fn, pipeline.shard_stages(params), x, n_micro=4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_single_microbatch_and_many(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(8, 8)
        x = jnp.asarray(np.random.default_rng(2)
                        .normal(size=(16, 8)).astype(np.float32))
        expect = _oracle(params, x)
        for n_micro in (1, 2, 8, 16):
            got = pipeline.pipeline_apply(
                _stage_fn, pipeline.shard_stages(params), x, n_micro=n_micro)
            np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                       rtol=2e-4, atol=2e-5)

    def test_rejects_indivisible_microbatch(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(8, 8)
        x = jnp.zeros((10, 8), jnp.float32)
        with pytest.raises(ValueError):
            pipeline.pipeline_apply(_stage_fn,
                                    pipeline.shard_stages(params), x,
                                    n_micro=4)

    def test_under_jit_and_grad(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(8, 8)
        sharded = pipeline.shard_stages(params)
        x = jnp.asarray(np.random.default_rng(3)
                        .normal(size=(16, 8)).astype(np.float32))

        @jax.jit
        def loss(p, x):
            y = pipeline.pipeline_apply(_stage_fn, p, x, n_micro=4)
            return jnp.mean(y ** 2)

        g = jax.grad(loss)(sharded, x)
        for leaf in jax.tree.leaves(g):
            arr = np.asarray(leaf)
            assert np.isfinite(arr).all()
            assert np.abs(arr).sum() > 0

    def test_rejects_stage_count_mismatch(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(16, 8)  # 16 layers on an 8-stage axis
        x = jnp.zeros((16, 8), jnp.float32)
        with pytest.raises(ValueError, match="n_stages"):
            pipeline.pipeline_apply(_stage_fn, params, x, n_micro=4)

    def test_dp_pp_mesh_with_batch_axis(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "pp"))
        mv.init(mesh=mesh)
        params = _stages(4, 8)
        x = jnp.asarray(np.random.default_rng(5)
                        .normal(size=(16, 8)).astype(np.float32))
        expect = _oracle(params, x)
        got = pipeline.pipeline_apply(
            _stage_fn, pipeline.shard_stages(params, mesh=mesh), x,
            n_micro=4, mesh=mesh, batch_axis="dp")
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_dp_pp_mesh(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "pp"))
        mv.init(mesh=mesh)
        params = _stages(4, 8)
        x = jnp.asarray(np.random.default_rng(4)
                        .normal(size=(16, 8)).astype(np.float32))
        expect = _oracle(params, x)
        got = pipeline.pipeline_apply(
            _stage_fn, pipeline.shard_stages(params, mesh=mesh), x,
            n_micro=4, mesh=mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)


def _lm_cfg(**kw):
    from multiverso_tpu.models import transformer as tfm
    base = dict(vocab_size=61, dim=32, num_heads=4, num_layers=8,
                max_seq=16, attn="local")
    base.update(kw)
    return tfm.TransformerConfig(**base)


def _lm_batch(cfg, b=8, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab_size, (b, cfg.max_seq + 1))
    return (jnp.asarray(toks[:, :-1].astype(np.int32)),
            jnp.asarray(toks[:, 1:].astype(np.int32)))


class TestPipelinedTransformerLM:
    """make_pp_train_step vs the plain single-program train step: same
    params, same batch => same loss and same updated parameters (GPipe
    fwd+bwd through the ppermute ring is exact, not approximate)."""

    def test_matches_single_program_step(self):
        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        cfg = _lm_cfg()
        lr = 0.05
        params = tfm.init_params(cfg, seed=3)
        tok, tgt = _lm_batch(cfg)

        expect_loss = tfm.loss_fn(params, tok, tgt, cfg)
        grads = jax.grad(tfm.loss_fn)(params, tok, tgt, cfg)
        expect = jax.tree.map(lambda p, g: p - lr * g, params, grads)

        stacked = tfm.shard_params_pp(
            tfm.stack_pp_params(params, cfg, 8), mesh=mesh)
        step = jax.jit(tfm.make_pp_train_step(cfg, n_micro=4,
                                              learning_rate=lr, mesh=mesh))
        new, loss = step(stacked, tok, tgt)
        np.testing.assert_allclose(float(loss), float(expect_loss),
                                   rtol=1e-5)
        got = tfm.unstack_pp_params(new)
        for k in ("embed", "pos", "ln_f"):
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(expect[k]),
                                       rtol=5e-4, atol=1e-5)
        for k, v in got["layers"].items():
            np.testing.assert_allclose(np.asarray(v),
                                       np.asarray(expect["layers"][k]),
                                       rtol=5e-4, atol=1e-5,
                                       err_msg=f"layers[{k}]")

    def test_dp_pp_remat_trains(self):
        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "pp"))
        mv.init(mesh=mesh)
        cfg = _lm_cfg(batch_axis="dp", remat=True)
        params = tfm.init_params(cfg, seed=1)
        tok, tgt = _lm_batch(cfg, b=8, seed=4)
        expect_loss = float(tfm.loss_fn(params, tok, tgt, cfg))

        stacked = tfm.shard_params_pp(
            tfm.stack_pp_params(params, cfg, 4), mesh=mesh)
        step = jax.jit(tfm.make_pp_train_step(cfg, n_micro=2,
                                              learning_rate=0.1, mesh=mesh))
        new, first = step(stacked, tok, tgt)
        np.testing.assert_allclose(float(first), expect_loss, rtol=1e-5)
        losses = [float(first)]
        for _ in range(6):
            new, l = step(new, tok, tgt)
            losses.append(float(l))
        assert losses[-1] < losses[0] - 0.1, losses

    def test_validation(self):
        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        with pytest.raises(ValueError, match="divisible"):
            tfm.stack_pp_params(tfm.init_params(_lm_cfg(num_layers=6)),
                                _lm_cfg(num_layers=6), 4)
        with pytest.raises(ValueError, match="attend"):
            tfm.make_pp_train_step(_lm_cfg(attn="ring"), 4, mesh=mesh)
        with pytest.raises(ValueError, match="strategies"):
            tfm.make_pp_train_step(_lm_cfg(moe_experts=4), 4, mesh=mesh)
        with pytest.raises(ValueError, match="divisible"):
            tfm.make_pp_train_step(_lm_cfg(num_layers=12), 4, mesh=mesh)

    def test_optax_step_matches_single_program(self):
        import optax

        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        cfg = _lm_cfg()
        opt = optax.adamw(1e-2)
        params = tfm.init_params(cfg, seed=7)
        tok, tgt = _lm_batch(cfg, seed=9)

        ref_step = jax.jit(tfm.make_optax_train_step(cfg, opt),
                           static_argnums=())
        expect, _, expect_loss = ref_step(params, opt.init(params), tok, tgt)

        stacked = tfm.shard_params_pp(
            tfm.stack_pp_params(params, cfg, 8), mesh=mesh)
        step = jax.jit(tfm.make_pp_optax_train_step(cfg, n_micro=4,
                                                    optimizer=opt,
                                                    mesh=mesh))
        new, _, loss = step(stacked, opt.init(stacked), tok, tgt)
        np.testing.assert_allclose(float(loss), float(expect_loss),
                                   rtol=1e-5)
        got = tfm.unstack_pp_params(new)
        for k, v in got["layers"].items():
            np.testing.assert_allclose(np.asarray(v),
                                       np.asarray(expect["layers"][k]),
                                       rtol=2e-2, atol=1e-3,
                                       err_msg=f"layers[{k}]")
        np.testing.assert_allclose(np.asarray(got["embed"]),
                                   np.asarray(expect["embed"]),
                                   rtol=2e-2, atol=1e-3)

    def test_pp_tp_matches_single_program(self):
        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()).reshape(4, 2), ("pp", "tp"))
        mv.init(mesh=mesh)
        cfg = _lm_cfg(tp_axis="tp")
        lr = 0.05
        params = tfm.init_params(cfg, seed=5)
        tok, tgt = _lm_batch(cfg, seed=11)

        # oracle on the plain (unsharded) single-program path
        ref_cfg = cfg._replace(tp_axis=None)
        expect_loss = tfm.loss_fn(params, tok, tgt, ref_cfg)
        grads = jax.grad(tfm.loss_fn)(params, tok, tgt, ref_cfg)
        expect = jax.tree.map(lambda p, g: p - lr * g, params, grads)

        stacked = tfm.shard_params_pp(
            tfm.stack_pp_params(params, cfg, 4, tp=True), mesh=mesh,
            cfg=cfg)
        step = jax.jit(tfm.make_pp_train_step(cfg, n_micro=4,
                                              learning_rate=lr, mesh=mesh))
        new, loss = step(stacked, tok, tgt)
        np.testing.assert_allclose(float(loss), float(expect_loss),
                                   rtol=1e-5)
        got = tfm.unstack_pp_params(new, cfg=cfg, tp=True)
        for k in ("embed", "pos", "ln_f"):
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(expect[k]),
                                       rtol=5e-4, atol=1e-5)
        for k, v in got["layers"].items():
            np.testing.assert_allclose(np.asarray(v),
                                       np.asarray(expect["layers"][k]),
                                       rtol=5e-4, atol=1e-5,
                                       err_msg=f"layers[{k}]")

    def test_dp_pp_tp_trains(self):
        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                    ("dp", "pp", "tp"))
        mv.init(mesh=mesh)
        cfg = _lm_cfg(batch_axis="dp", tp_axis="tp", num_layers=4)
        params = tfm.init_params(cfg, seed=2)
        tok, tgt = _lm_batch(cfg, b=8, seed=13)
        expect_loss = float(
            tfm.loss_fn(params, tok, tgt, cfg._replace(tp_axis=None,
                                                       batch_axis=None)))
        stacked = tfm.shard_params_pp(
            tfm.stack_pp_params(params, cfg, 2, tp=True), mesh=mesh,
            cfg=cfg)
        step = jax.jit(tfm.make_pp_train_step(cfg, n_micro=2,
                                              learning_rate=0.1, mesh=mesh))
        new, first = step(stacked, tok, tgt)
        np.testing.assert_allclose(float(first), expect_loss, rtol=1e-5)
        losses = [float(first)]
        for _ in range(6):
            new, l = step(new, tok, tgt)
            losses.append(float(l))
        assert losses[-1] < losses[0] - 0.1, losses


class TestInterleavedPipeline:
    """pipeline_apply_interleaved vs the sequential oracle: chunked stage
    placement (global stage g -> device g % S, chunk g // S) must compute
    the same stack."""

    def test_matches_sequential(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(16, 12)  # 16 global stages = 8 devices x 2 chunks
        rng = np.random.default_rng(21)
        x = jnp.asarray(rng.normal(size=(32, 12)).astype(np.float32))
        expect = _oracle(params, x)
        placed = pipeline.shard_stages_interleaved(params, 8)
        got = pipeline.pipeline_apply_interleaved(_stage_fn, placed, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_single_chunk_equals_gpipe(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(8, 8, seed=3)
        x = jnp.asarray(np.random.default_rng(22)
                        .normal(size=(16, 8)).astype(np.float32))
        expect = pipeline.pipeline_apply(
            _stage_fn, pipeline.shard_stages(params), x, n_micro=8)
        placed = pipeline.shard_stages_interleaved(params, 8)
        got = pipeline.pipeline_apply_interleaved(_stage_fn, placed, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_four_chunks_under_grad(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(32, 8, seed=5)  # 8 devices x 4 chunks
        x = jnp.asarray(np.random.default_rng(23)
                        .normal(size=(16, 8)).astype(np.float32))
        placed = pipeline.shard_stages_interleaved(params, 8)

        def loss_pipe(p, x):
            return jnp.mean(pipeline.pipeline_apply_interleaved(
                _stage_fn, p, x) ** 2)

        def loss_ref(p, x):
            return jnp.mean(_oracle(p, x) ** 2)

        np.testing.assert_allclose(float(jax.jit(loss_pipe)(placed, x)),
                                   float(loss_ref(params, x)), rtol=1e-5)
        g = jax.jit(jax.grad(loss_pipe))(placed, x)
        g_ref = jax.grad(loss_ref)(params, x)
        # regroup reference grads into the interleaved layout
        for k in ("w", "b"):
            ref = np.asarray(g_ref[k])
            v = ref.shape[0] // 8
            ref = ref.reshape(v, 8, *ref.shape[1:]).swapaxes(0, 1)
            np.testing.assert_allclose(np.asarray(g[k]), ref,
                                       rtol=2e-4, atol=2e-5, err_msg=k)

    def test_validation(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        with pytest.raises(ValueError, match="divisible"):
            pipeline.shard_stages_interleaved(_stages(12, 8), 8)
        placed = pipeline.shard_stages_interleaved(_stages(16, 8), 8)
        with pytest.raises(ValueError, match="n_micro"):
            pipeline.pipeline_apply_interleaved(
                _stage_fn, placed, jnp.zeros((12, 8), jnp.float32))

    def test_interleaved_lm_matches_single_program(self):
        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        cfg = _lm_cfg(num_layers=16)  # 8 devices x 2 chunks x 1 layer
        lr = 0.05
        params = tfm.init_params(cfg, seed=8)
        tok, tgt = _lm_batch(cfg, seed=15)

        expect_loss = tfm.loss_fn(params, tok, tgt, cfg)
        grads = jax.grad(tfm.loss_fn)(params, tok, tgt, cfg)
        expect = jax.tree.map(lambda p, g: p - lr * g, params, grads)

        stacked = tfm.shard_params_pp(
            tfm.stack_pp_params(params, cfg, 8, pp_chunks=2), mesh=mesh)
        step = jax.jit(tfm.make_pp_train_step(cfg, n_micro=8,
                                              learning_rate=lr, mesh=mesh,
                                              pp_chunks=2))
        new, loss = step(stacked, tok, tgt)
        np.testing.assert_allclose(float(loss), float(expect_loss),
                                   rtol=1e-5)
        got = tfm.unstack_pp_params(new, pp_chunks=2)
        for k, v in got["layers"].items():
            np.testing.assert_allclose(np.asarray(v),
                                       np.asarray(expect["layers"][k]),
                                       rtol=5e-4, atol=2e-5,
                                       err_msg=f"layers[{k}]")
        np.testing.assert_allclose(np.asarray(got["embed"]),
                                   np.asarray(expect["embed"]),
                                   rtol=5e-4, atol=2e-5)

    def test_interleaved_validation(self):
        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        with pytest.raises(ValueError, match="n_micro == pp"):
            tfm.make_pp_train_step(_lm_cfg(num_layers=16), n_micro=4,
                                   mesh=mesh, pp_chunks=2)

    def test_interleaved_pp_tp_matches_single_program(self):
        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()).reshape(4, 2), ("pp", "tp"))
        mv.init(mesh=mesh)
        cfg = _lm_cfg(tp_axis="tp", num_layers=8, pp_chunks=2)
        lr = 0.05
        params = tfm.init_params(cfg, seed=17)
        tok, tgt = _lm_batch(cfg, b=8, seed=19)

        ref_cfg = cfg._replace(tp_axis=None, pp_chunks=1)
        expect_loss = tfm.loss_fn(params, tok, tgt, ref_cfg)
        grads = jax.grad(tfm.loss_fn)(params, tok, tgt, ref_cfg)
        expect = jax.tree.map(lambda p, g: p - lr * g, params, grads)

        stacked = tfm.shard_params_pp(
            tfm.stack_pp_params(params, cfg, 4), mesh=mesh, cfg=cfg)
        step = jax.jit(tfm.make_pp_train_step(cfg, n_micro=4,
                                              learning_rate=lr, mesh=mesh))
        new, loss = step(stacked, tok, tgt)
        np.testing.assert_allclose(float(loss), float(expect_loss),
                                   rtol=1e-5)
        got = tfm.unstack_pp_params(new, cfg=cfg)
        for k, v in got["layers"].items():
            np.testing.assert_allclose(np.asarray(v),
                                       np.asarray(expect["layers"][k]),
                                       rtol=5e-4, atol=2e-5,
                                       err_msg=f"layers[{k}]")
        np.testing.assert_allclose(np.asarray(got["embed"]),
                                   np.asarray(expect["embed"]),
                                   rtol=5e-4, atol=2e-5)

    def test_interleaved_dp_pp_matches_oracle(self):
        mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "pp"))
        mv.init(mesh=mesh)
        params = _stages(8, 8, seed=9)  # 4 devices x 2 chunks
        x = jnp.asarray(np.random.default_rng(31)
                        .normal(size=(16, 8)).astype(np.float32))
        expect = _oracle(params, x)
        placed = pipeline.shard_stages_interleaved(params, 4, mesh=mesh)
        got = pipeline.pipeline_apply_interleaved(
            _stage_fn, placed, x, mesh=mesh, batch_axis="dp")
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("n_dev,chunks", [(2, 4), (4, 2), (8, 4)])
    def test_mesh_and_chunk_extents(self, n_dev, chunks):
        mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(n_dev * chunks, 8, seed=n_dev)
        x = jnp.asarray(np.random.default_rng(n_dev * 10)
                        .normal(size=(n_dev * 2, 8)).astype(np.float32))
        expect = _oracle(params, x)
        placed = pipeline.shard_stages_interleaved(params, n_dev, mesh=mesh)
        got = pipeline.pipeline_apply_interleaved(_stage_fn, placed, x,
                                                  mesh=mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_masked_loss_matches_oracle_both_schedules(self):
        from multiverso_tpu.models import transformer as tfm
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        cfg = _lm_cfg(num_layers=16)
        params = tfm.init_params(cfg, seed=4)
        tok, tgt = _lm_batch(cfg, seed=23)
        rng = np.random.default_rng(29)
        mask = jnp.asarray(
            (rng.uniform(size=tok.shape) > 0.3).astype(np.float32))
        expect = float(tfm.loss_fn(params, tok, tgt, cfg, mask))
        # GPipe schedule
        stacked = tfm.shard_params_pp(
            tfm.stack_pp_params(params, cfg, 8), mesh=mesh)
        step = jax.jit(tfm.make_pp_train_step(cfg, n_micro=4, mesh=mesh))
        _, loss = step(stacked, tok, tgt, mask)
        np.testing.assert_allclose(float(loss), expect, rtol=1e-5)
        # interleaved schedule (2 chunks/device, fixed n_micro == pp)
        icfg = cfg._replace(pp_chunks=2)
        istacked = tfm.shard_params_pp(
            tfm.stack_pp_params(params, icfg, 8), mesh=mesh, cfg=icfg)
        istep = jax.jit(tfm.make_pp_train_step(icfg, n_micro=8, mesh=mesh))
        _, iloss = istep(istacked, tok, tgt, mask)
        np.testing.assert_allclose(float(iloss), expect, rtol=1e-5)
