"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch ring vs a
sequential oracle on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import multiverso_tpu as mv
from multiverso_tpu.parallel import pipeline


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


def _stages(n, d, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(0, 0.5, (n, d, d)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(0, 0.1, (n, d)).astype(np.float32)),
    }


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _oracle(params, x):
    for i in range(params["w"].shape[0]):
        x = _stage_fn({"w": params["w"][i], "b": params["b"][i]}, x)
    return x


class TestPipeline:
    def test_matches_sequential(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(8, 16)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(32, 16)).astype(np.float32))
        expect = _oracle(params, x)
        got = pipeline.pipeline_apply(
            _stage_fn, pipeline.shard_stages(params), x, n_micro=4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_single_microbatch_and_many(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(8, 8)
        x = jnp.asarray(np.random.default_rng(2)
                        .normal(size=(16, 8)).astype(np.float32))
        expect = _oracle(params, x)
        for n_micro in (1, 2, 8, 16):
            got = pipeline.pipeline_apply(
                _stage_fn, pipeline.shard_stages(params), x, n_micro=n_micro)
            np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                       rtol=2e-4, atol=2e-5)

    def test_rejects_indivisible_microbatch(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(8, 8)
        x = jnp.zeros((10, 8), jnp.float32)
        with pytest.raises(ValueError):
            pipeline.pipeline_apply(_stage_fn,
                                    pipeline.shard_stages(params), x,
                                    n_micro=4)

    def test_under_jit_and_grad(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(8, 8)
        sharded = pipeline.shard_stages(params)
        x = jnp.asarray(np.random.default_rng(3)
                        .normal(size=(16, 8)).astype(np.float32))

        @jax.jit
        def loss(p, x):
            y = pipeline.pipeline_apply(_stage_fn, p, x, n_micro=4)
            return jnp.mean(y ** 2)

        g = jax.grad(loss)(sharded, x)
        for leaf in jax.tree.leaves(g):
            arr = np.asarray(leaf)
            assert np.isfinite(arr).all()
            assert np.abs(arr).sum() > 0

    def test_rejects_stage_count_mismatch(self):
        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        mv.init(mesh=mesh)
        params = _stages(16, 8)  # 16 layers on an 8-stage axis
        x = jnp.zeros((16, 8), jnp.float32)
        with pytest.raises(ValueError, match="n_stages"):
            pipeline.pipeline_apply(_stage_fn, params, x, n_micro=4)

    def test_dp_pp_mesh_with_batch_axis(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "pp"))
        mv.init(mesh=mesh)
        params = _stages(4, 8)
        x = jnp.asarray(np.random.default_rng(5)
                        .normal(size=(16, 8)).astype(np.float32))
        expect = _oracle(params, x)
        got = pipeline.pipeline_apply(
            _stage_fn, pipeline.shard_stages(params, mesh=mesh), x,
            n_micro=4, mesh=mesh, batch_axis="dp")
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_dp_pp_mesh(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "pp"))
        mv.init(mesh=mesh)
        params = _stages(4, 8)
        x = jnp.asarray(np.random.default_rng(4)
                        .normal(size=(16, 8)).astype(np.float32))
        expect = _oracle(params, x)
        got = pipeline.pipeline_apply(
            _stage_fn, pipeline.shard_stages(params, mesh=mesh), x,
            n_micro=4, mesh=mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)
