"""Collectives, multi-worker BSP, ring/Ulysses attention on the 8-device mesh
(ref tier-2 allreduce tests + the long-context additions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu import parallel
from multiverso_tpu.parallel.ring import reference_attention, sequence_shard
from multiverso_tpu.parallel.worker_map import make_worker_mesh, worker_step


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


class TestCollectives:
    def test_all_reduce(self):
        # 8 shards of 4 elements; result = sum of the 8 chunks
        x = np.arange(32, dtype=np.float32)
        out = parallel.all_reduce(x)
        expect = x.reshape(8, 4).sum(axis=0)
        np.testing.assert_allclose(np.asarray(out), expect)

    def test_all_gather_roundtrip(self):
        x = np.arange(16, dtype=np.float32)
        out = parallel.all_gather(x)
        np.testing.assert_allclose(np.asarray(out), x)

    def test_reduce_scatter_then_gather(self):
        x = np.arange(32, dtype=np.float32)
        scattered = parallel.reduce_scatter(x)
        gathered = parallel.all_gather(scattered)
        np.testing.assert_allclose(np.asarray(gathered), x)

    def test_broadcast(self):
        x = np.arange(32, dtype=np.float32)
        out = parallel.broadcast(x, root=3)
        np.testing.assert_allclose(np.asarray(out), x.reshape(8, 4)[3])


class TestWorkerStep:
    def test_bsp_equals_large_batch(self):
        """4 workers x local batches == single large batch (the SyncServer
        guarantee: every worker sees identical merged state)."""
        mesh = make_worker_mesh(4, shard_axis="mv")
        mv.shutdown()
        mv.init(mesh=mesh)
        table = mv.ArrayTable(8, updater="sgd", name="bsp")

        def grad_fn(params, batch):
            # linear least squares on y = <w, x>
            x, y = batch["x"], batch["y"]
            w = params[:4]
            pred = x @ w
            loss = jnp.mean((pred - y) ** 2)
            grad = 2 * (x.T @ (pred - y)) / x.shape[0]
            g = jnp.zeros_like(params).at[:4].set(grad)
            return loss, g

        step = worker_step(table, grad_fn, learning_rate=0.1)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        w_true = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
        y = x @ w_true
        batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
        state = table.state
        jit_step = jax.jit(step)
        for _ in range(60):
            state, loss = jit_step(state, batch)
        table.adopt(state)
        got = table.get()[:4]
        np.testing.assert_allclose(got, w_true, atol=0.05)


class TestRingAttention:
    def _qkv(self, b=2, h=4, s=32, d=16, seed=0):
        rng = np.random.default_rng(seed)
        mk = lambda: jnp.asarray(rng.normal(size=(b, h, s, d))
                                 .astype(np.float32))
        return mk(), mk(), mk()

    def test_matches_reference(self):
        q, k, v = self._qkv()
        expect = reference_attention(q, k, v)
        qs, ks, vs = map(sequence_shard, (q, k, v))
        out = parallel.ring_attention(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_output_stays_sequence_sharded(self):
        q, k, v = self._qkv()
        out = parallel.ring_attention(*map(sequence_shard, (q, k, v)))
        assert len(out.sharding.device_set) == 8

    def test_ulysses_matches_reference(self):
        q, k, v = self._qkv(h=8)
        expect = reference_attention(q, k, v)
        out = parallel.ulysses_attention(*map(sequence_shard, (q, k, v)))
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_ulysses_rejects_bad_heads(self):
        q, k, v = self._qkv(h=4)  # 4 heads, 8 shards
        with pytest.raises(ValueError):
            parallel.ulysses_attention(*map(sequence_shard, (q, k, v)))

    def test_long_sequence_scales(self):
        # 8 chips x 64 local = 512 sequence; just verifies compile+run
        q, k, v = self._qkv(b=1, h=2, s=512, d=8)
        out = parallel.ring_attention(*map(sequence_shard, (q, k, v)))
        assert np.isfinite(np.asarray(out)).all()
