"""Randomized differential test: a long random op sequence against every
table type must match a plain numpy model exactly (the catch-all for
sharding/padding/bucketing/async edge cases)."""

import jax
import numpy as np
import pytest

import multiverso_tpu as mv


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


def test_array_table_matches_numpy_model():
    rng = np.random.default_rng(0)
    size = 613  # awkward: not divisible by 8 shards, exercises padding
    t = mv.ArrayTable(size, name="fuzz_a")
    model = np.zeros(size, np.float32)
    pending = []
    for step in range(60):
        op = rng.choice(["add", "add_async", "get", "wait"])
        if op == "add":
            d = rng.normal(size=size).astype(np.float32)
            t.add(d)
            model += d
        elif op == "add_async":
            d = rng.normal(size=size).astype(np.float32)
            pending.append(t.add_async(d))
            model += d
        elif op == "wait" and pending:
            t.wait(pending.pop(rng.integers(len(pending))))
        else:
            np.testing.assert_allclose(t.get(), model, rtol=2e-5,
                                       atol=2e-4)
    for msg_id in pending:
        t.wait(msg_id)
    np.testing.assert_allclose(t.get(), model, rtol=2e-5, atol=2e-4)


def test_matrix_table_matches_numpy_model():
    rng = np.random.default_rng(1)
    rows, cols = 207, 12  # awkward row count
    t = mv.MatrixTable(rows, cols, name="fuzz_m")
    model = np.zeros((rows, cols), np.float32)
    for step in range(50):
        op = rng.choice(["add", "add_rows", "get", "get_rows", "get_row"])
        if op == "add":
            d = rng.normal(size=(rows, cols)).astype(np.float32)
            t.add(d)
            model += d
        elif op == "add_rows":
            k = int(rng.integers(1, 40))
            # duplicates allowed: the table accumulates them (+=), so the
            # model must too (np.add.at, not fancy-index +=)
            ids = rng.choice(rows, size=k, replace=True)
            d = rng.normal(size=(k, cols)).astype(np.float32)
            t.add_rows(ids, d)
            np.add.at(model, ids, d)
        elif op == "get_rows":
            k = int(rng.integers(1, 40))
            ids = rng.choice(rows, size=k, replace=False)
            np.testing.assert_allclose(t.get_rows(ids), model[ids],
                                       rtol=2e-5, atol=2e-4)
        elif op == "get_row":
            i = int(rng.integers(rows))
            np.testing.assert_allclose(t.get_row(i), model[i],
                                       rtol=2e-5, atol=2e-4)
        else:
            np.testing.assert_allclose(t.get(), model, rtol=2e-5,
                                       atol=2e-4)


def test_kv_table_matches_dict_model():
    rng = np.random.default_rng(2)
    t = mv.KVTable(name="fuzz_kv")
    model = {}
    for step in range(80):
        if rng.random() < 0.7:
            keys = rng.integers(0, 50, size=rng.integers(1, 6)).tolist()
            vals = rng.integers(-5, 6, size=len(keys)).tolist()
            t.add(keys, vals)
            for k, v in zip(keys, vals):
                model[k] = model.get(k, 0) + v
        else:
            for k, v in model.items():
                assert t[k] == v, (k, t[k], v)


def test_send_window_parity_local_plane():
    """Window-on vs window-off bit-for-bit parity on the LOCAL
    short-circuit (world=1 default context): MSG_BATCH frames dispatch
    through the in-process executor instead of a socket, and the fences
    must still give read-your-writes. Complements the two-rank socket
    variant in test_async_table_fuzz.py."""
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    rng = np.random.default_rng(5)
    rows, cols = 53, 6
    tw = AsyncMatrixTable(rows, cols, name="fz_w", send_window_ms=30.0)
    tr = AsyncMatrixTable(rows, cols, name="fz_r")
    assert tw._window is not None
    model = np.zeros((rows, cols), np.float64)
    for step in range(80):
        op = rng.choice(["add_rows", "add_rows_async", "get_rows",
                         "flush"])
        if op in ("add_rows", "add_rows_async"):
            k = int(rng.integers(1, 10))
            ids = rng.integers(0, rows, k)
            vals = rng.normal(size=(k, cols)).astype(np.float32)
            if op == "add_rows":
                tw.add_rows(ids, vals)
                tr.add_rows(ids, vals)
            else:
                tw.add_rows_async(ids, vals)
                tr.add_rows_async(ids, vals)
            np.add.at(model, ids, vals.astype(np.float64))
        elif op == "get_rows":
            ids = rng.integers(0, rows, int(rng.integers(1, 8)))
            a, b = tw.get_rows(ids), tr.get_rows(ids)
            assert np.array_equal(a, b), f"step {step}"
        else:
            tw.flush()
            tr.flush()
    tw.flush()
    tr.flush()
    a, b = tw.get(), tr.get()
    assert np.array_equal(a, b)
    np.testing.assert_allclose(a, model, rtol=2e-5, atol=2e-4)


@pytest.mark.parametrize("updater", ["sgd", "momentum_sgd", "adagrad"])
def test_stateful_updaters_match_numpy_model(updater):
    """Random add/get sequences through each server-side updater against
    the updater's own recurrence replayed in numpy (the optimizer-state
    analogue of the plain += fuzz above)."""
    from multiverso_tpu.updaters import AddOption
    rng = np.random.default_rng(7)
    size = 331  # awkward size: padding + 8-way sharding
    t = mv.ArrayTable(size, updater=updater, name=f"fuzz_{updater}")
    model = np.zeros(size, np.float64)
    smooth = np.zeros(size, np.float64)
    g_sqr = np.zeros(size, np.float64)
    lr, m, rho = 0.1, 0.9, 0.05
    opt = AddOption(learning_rate=lr, momentum=m, rho=rho)
    for step in range(40):
        if rng.uniform() < 0.7:
            d = rng.normal(size=size).astype(np.float32)
            t.add(d, opt)
            d64 = d.astype(np.float64)
            if updater == "sgd":
                model -= d64
            elif updater == "momentum_sgd":
                smooth = m * smooth + (1.0 - m) * d64
                model -= smooth
            else:  # adagrad (ref adagrad_updater.h sign/scale quirks)
                g_sqr += np.square(d64) / lr ** 2
                model -= d64 * rho / (np.sqrt(g_sqr) + 1e-10)
        else:
            np.testing.assert_allclose(t.get(), model, rtol=5e-4,
                                       atol=5e-5, err_msg=f"step {step}")
    np.testing.assert_allclose(t.get(), model, rtol=5e-4, atol=5e-5)
