"""WordEmbedding on the uncoordinated async plane, np=4 — the VERDICT
round-1 'done when': train_ps_blocks runs multi-process with per-worker
data blocks over per-worker row sets, no collectives."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)


def test_we_ps_blocks_np4(tmp_path):
    nprocs = 4
    rdv = str(tmp_path / "rdv")
    os.makedirs(rdv, exist_ok=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_DIR, "we_async_worker.py"),
             rdv, str(nprocs), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True)
        for pid in range(nprocs)
    ]
    results, errors = {}, []
    for pid, p in enumerate(procs):
        try:
            stdout, stderr = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail(f"WE worker {pid} timed out")
        if p.returncode != 0:
            errors.append(f"pid {pid} rc={p.returncode}\n{stderr[-2000:]}")
            continue
        for line in stdout.splitlines():
            if line.startswith("RESULT "):
                results[pid] = json.loads(line[len("RESULT "):])
    if errors:
        pytest.fail("\n".join(errors))
    assert set(results) == set(range(nprocs))
    total_trained = sum(r["words"] for r in results.values())
    assert total_trained == 40_000            # blocks partitioned, disjoint
    for r in results.values():
        # every worker reads the same aggregated word count off the shards
        assert r["total_words"] == 3 * total_trained  # all 3 epochs counted
        assert np.isfinite(r["loss"]) and r["loss"] > 0
        assert np.isfinite(r["loss_epoch2"]) and r["loss_epoch2"] > 0
        assert r["emb_norm"] > 0
    # CONVERGENCE, not just liveness: epoch 2 over the jointly-trained
    # shards must beat epoch 1 on average (uncoordinated updates that
    # raced to finite garbage would fail this)
    l1 = np.mean([r["loss"] for r in results.values()])
    l2 = np.mean([r["loss_epoch2"] for r in results.values()])
    assert l2 < 0.9 * l1, (l1, l2)


def test_we_cli_async_np2(tmp_path):
    """The app's own CLI entry point runs the uncoordinated plane end to
    end: -ps_* runtime flags flow through mv.init (ref MV_Init argv), and
    a fast-finishing rank keeps serving until peers reach shutdown
    (ps_shutdown_grace quiesce — the reference's MV_ShutDown barrier;
    without it the slow rank dies with PSPeerError mid-pull)."""
    rng = np.random.default_rng(1)
    corpus = tmp_path / "c.txt"
    corpus.write_text(" ".join(f"w{t}" for t in rng.integers(0, 80, 30_000)))
    rdv = tmp_path / "rdv"
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"   # two processes cannot share the chip
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "multiverso_tpu.apps.word_embedding",
             "-train_file", str(corpus), "-size", "16", "-epoch", "1",
             "-batch_size", "128", "-min_count", "1", "-sample", "0",
             "-use_ps", "1", "-async_ps", "1", "-data_block_size", "5000",
             "-output", str(tmp_path / f"vec{r}.txt"),
             f"-ps_rank={r}", "-ps_world=2", f"-ps_rendezvous={rdv}",
             "-ps_timeout=60"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True)
        for r in range(2)
    ]
    for r, p in enumerate(procs):
        try:
            _, stderr = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail(f"CLI rank {r} hung (shutdown quiesce broken?)")
        assert p.returncode == 0, f"rank {r} rc={p.returncode}\n{stderr[-1500:]}"
        out = tmp_path / f"vec{r}.txt"
        assert out.exists()
        assert int(out.read_text().split(None, 1)[0]) > 0
