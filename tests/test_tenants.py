"""ISSUE 18 battery: the tenant attribution plane end-to-end.

Identity resolution (flag < scope), wire-meta stamping on BOTH wire
planes (the two-tenant shard oracle), per-tenant send-window budgets
(deferred-never-dropped), admission budget isolation (a tenant shed
never burns the table-wide bucket), the noisy-neighbor verdict
lifecycle (fires once, stays open, clears, re-fires), the aggregator's
dedupe/sum merge, every renderer (mvtop, dump_metrics, exporter),
lint 6 of check_obs_surface, flightrec EV coverage + the postmortem
tenant timeline, run_bench's victim-tenant regression keys, and the
tier-1 noisy_neighbor chaos smoke. All tier-1 (CPU, seconds)."""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

from multiverso_tpu.ps import wire  # noqa: E402
from multiverso_tpu.serving.admission import (AdmissionController,  # noqa: E402
                                              tenant_stats_all)
from multiverso_tpu.telemetry import aggregator  # noqa: E402
from multiverso_tpu.telemetry import flightrec  # noqa: E402
from multiverso_tpu.telemetry import hotkeys  # noqa: E402
from multiverso_tpu.telemetry import tenants  # noqa: E402
from multiverso_tpu.utils import config  # noqa: E402


def _tools():
    if TOOLS not in sys.path:
        sys.path.insert(0, TOOLS)


def _hist(count=3, sum_ms=9.0):
    return {"count": count, "timed": count, "sum_ms": sum_ms,
            "min_ms": 1.0, "max_ms": 5.0, "buckets": []}


# ---------------------------------------------------------------------- #
# identity resolution
# ---------------------------------------------------------------------- #
class TestIdentity:
    def test_default_is_none(self):
        assert tenants.current() is None
        assert tenants.label(None) == "default"
        assert tenants.label("acme") == "acme"

    def test_flag_then_scope_precedence(self):
        config.set_flag("tenant_id", "acme")
        assert tenants.current() == "acme"
        with tenants.tenant_scope("storm"):
            assert tenants.current() == "storm"
            with tenants.tenant_scope("inner"):
                assert tenants.current() == "inner"
            assert tenants.current() == "storm"
            # "" explicitly selects the default tenant OVER the flag
            with tenants.tenant_scope(""):
                assert tenants.current() is None
        assert tenants.current() == "acme"

    def test_reset_clears_thread_local(self):
        with tenants.tenant_scope("leak"):
            tenants.reset()
            assert tenants.current() is None


# ---------------------------------------------------------------------- #
# wire meta stamping
# ---------------------------------------------------------------------- #
class TestWireMeta:
    def test_default_tenant_is_a_passthrough(self):
        m = {"table": "t"}
        assert wire.with_tenant(m, None) is m
        assert wire.with_tenant(m, "") is m

    def test_named_tenant_stamps_and_round_trips(self):
        m = wire.with_tenant({"table": "t"}, "acme")
        assert m[wire.TENANT_META_KEY] == "acme"
        back = json.loads(wire.pack_meta(m).decode())
        assert back[wire.TENANT_META_KEY] == "acme"


# ---------------------------------------------------------------------- #
# shard-side meter (pure)
# ---------------------------------------------------------------------- #
class TestTenantMeter:
    def test_empty_meter_omits_block(self):
        assert tenants.TenantMeter().to_dict() == {}

    def test_default_and_named_exact(self):
        m = tenants.TenantMeter()
        m.note(None, add_bytes=10)
        m.note(None, get_bytes=4)
        m.note("a", ops=2, add_bytes=7)
        m.note("b", get_bytes=5)
        d = m.to_dict()
        assert d["default"] == {"ops": 2, "add_bytes": 10, "get_bytes": 4}
        assert d["a"] == {"ops": 2, "add_bytes": 7, "get_bytes": 0}
        assert d["b"] == {"ops": 1, "add_bytes": 0, "get_bytes": 5}
        assert d["~sketch"]["total"] == 3   # named ops only

    def test_cap_folds_into_other_sketch_keeps_ranking(self):
        m = tenants.TenantMeter(track_max=2, sketch_capacity=8)
        for tn, n in (("a", 1), ("b", 1), ("c", 3), ("d", 2)):
            m.note(tn, ops=n)
        d = m.to_dict()
        assert set(d) == {"a", "b", "~other", "~sketch"}
        assert d["~other"]["ops"] == 5   # c + d folded
        ranked = {it[0]: it[1] for it in d["~sketch"]["items"]}
        assert ranked["c"] == 3 and ranked["d"] == 2


# ---------------------------------------------------------------------- #
# two-tenant oracle over the real wire (both planes via two_ranks)
# ---------------------------------------------------------------------- #
class TestShardOracle:
    def test_two_tenant_oracle_both_planes(self, two_ranks):
        """Named tenants are EXACT on both wire planes: stamped frames
        punt off the native fast path, so one Python meter counts them
        either way. Every op targets the remote rank's rows — the
        local short-circuit must not hide traffic from the meter."""
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 8, name="tor", ctx=two_ranks[0])
        AsyncMatrixTable(16, 8, name="tor", ctx=two_ranks[1])
        ones = np.ones((1, 8), np.float32)
        with tenants.tenant_scope("a"):
            for r in (8, 9, 10):
                t0.add_rows([r], ones)
            t0.get_rows(np.array([12]))
        with tenants.tenant_scope("b"):
            for r in (11, 12):
                t0.add_rows([r], ones)
            for _ in range(4):
                t0.get_rows(np.array([13]))
        st = t0.server_stats(1)["shards"]["tor"]["tenants"]
        a, b = st["a"], st["b"]
        assert a["ops"] == 4 and b["ops"] == 6
        # byte exactness as a cross-tenant ratio (independent of the
        # wire encoding): 3 vs 2 one-row adds, 1 vs 4 one-row gets
        assert a["add_bytes"] > 0 and a["get_bytes"] > 0
        assert a["add_bytes"] * 2 == b["add_bytes"] * 3
        assert b["get_bytes"] == 4 * a["get_bytes"]
        assert st["~sketch"]["total"] == 10

    def test_default_tenant_counts_on_python_plane(self, tmp_path):
        """Unstamped frames keep the native fast path (invisible to the
        Python meter, by design); on the python plane the same
        chokepoint counts them under "default"."""
        from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                               PSService)
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        config.set_flag("ps_native", False)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        try:
            t0 = AsyncMatrixTable(16, 8, name="tdf", ctx=ctxs[0])
            AsyncMatrixTable(16, 8, name="tdf", ctx=ctxs[1])
            t0.add_rows([9], np.ones((1, 8), np.float32))
            t0.get_rows(np.array([9]))
            st = t0.server_stats(1)["shards"]["tdf"]["tenants"]
            assert st["default"]["ops"] == 2
            assert st["default"]["add_bytes"] > 0
            assert st["default"]["get_bytes"] > 0
            assert "~sketch" not in st   # default traffic is not ranked
        finally:
            for c in ctxs:
                c.close()


# ---------------------------------------------------------------------- #
# send-window tenant budgets: deferred, never dropped
# ---------------------------------------------------------------------- #
class TestSendWindowBudget:
    def test_over_budget_adds_deferred_not_dropped(self, tmp_path):
        from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                               PSService)
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        config.set_flag("tenant_add_qps", 5.0)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        try:
            t0 = AsyncMatrixTable(16, 4, name="twin", send_window_ms=2.0,
                                  ctx=ctxs[0])
            AsyncMatrixTable(16, 4, name="twin", ctx=ctxs[1])
            ones = np.ones((1, 4), np.float32)
            with tenants.tenant_scope("w"):
                for _ in range(40):
                    t0.add_rows_async([12], ones)
            t0.flush()
            snap = tenants.LEDGER.stats_snapshot()
            deferred = snap["tables"]["twin"]["w"]["deferred"]
            # ~5-token burst against 40 instant adds: most defer
            assert deferred >= 30
            # writes are sacred: every add still applied
            final = t0.get_rows(np.arange(16))
            assert final[12, 0] == 40.0
        finally:
            for c in ctxs:
                c.close()

    def test_window_never_merges_across_tenants(self, tmp_path):
        """Two tenants adding the SAME row inside one open window stay
        two attribution records at the shard — coalescing must not blur
        who wrote."""
        from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                               PSService)
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        try:
            t0 = AsyncMatrixTable(16, 4, name="tmix", send_window_ms=50.0,
                                  ctx=ctxs[0])
            AsyncMatrixTable(16, 4, name="tmix", ctx=ctxs[1])
            ones = np.ones((1, 4), np.float32)
            with tenants.tenant_scope("x"):
                t0.add_rows_async([12], ones)
            with tenants.tenant_scope("y"):
                t0.add_rows_async([12], 2 * ones)
            t0.flush()
            st = t0.server_stats(1)["shards"]["tmix"]["tenants"]
            assert st["x"]["ops"] >= 1 and st["y"]["ops"] >= 1
            assert t0.get_rows(np.array([12]))[0, 0] == 3.0
        finally:
            for c in ctxs:
                c.close()


# ---------------------------------------------------------------------- #
# admission: per-tenant budgets judged before the table-wide bucket
# ---------------------------------------------------------------------- #
class TestAdmissionBudgets:
    def test_tenant_shed_never_burns_aggregate_tokens(self):
        ctl = AdmissionController()
        ctl.set_limit("t", "infer", 10.0, burst=10.0)
        ctl.set_tenant_limit("t", "storm", "infer", 1.0, burst=1.0)
        storm_ok = sum(ctl.admit("t", tenant="storm") for _ in range(50))
        assert storm_ok <= 2   # 1-token burst (+refill jitter)
        # 49 storm sheds burned ZERO aggregate tokens: the victim
        # still gets the 9 the storm's admits left in the 10-burst
        victim_ok = sum(ctl.admit("t", tenant="victim")
                        for _ in range(10 - storm_ok))
        assert victim_ok == 10 - storm_ok
        ts = ctl.tenant_stats()
        s = ts["t/storm/infer"]
        assert s["admitted"] + s["shed"] == 50
        assert s["admitted"] == storm_ok and s["qps_limit"] == 1.0

    def test_lazy_flag_default_named_tenants_only(self):
        config.set_flag("tenant_infer_qps", 2.0)
        ctl = AdmissionController()
        ok = sum(ctl.admit("t", tenant="n") for _ in range(10))
        assert 2 <= ok <= 3   # burst = max(2 * serving_burst_s, 1)
        # the DEFAULT tenant is governed by the table-wide budget only
        assert all(ctl.admit("t") for _ in range(10))

    def test_tombstone_exempts_over_flag(self):
        config.set_flag("tenant_infer_qps", 1.0)
        ctl = AdmissionController()
        ctl.set_tenant_limit("t", "vip", "infer", 0.0)   # exemption
        assert all(ctl.admit("t", tenant="vip") for _ in range(20))

    def test_validation(self):
        ctl = AdmissionController()
        with pytest.raises(ValueError):
            ctl.set_tenant_limit("t", "", "infer", 1.0)
        with pytest.raises(ValueError):
            ctl.set_tenant_limit("t", "a", "nope", 1.0)

    def test_tenant_stats_all_merges_controllers(self):
        a, b = AdmissionController(), AdmissionController()
        for ctl in (a, b):
            ctl.set_tenant_limit("t", "s", "infer", 100.0)
            ctl.admit("t", tenant="s")
        merged = tenant_stats_all()
        assert merged["t/s/infer"]["admitted"] == 2
        assert merged["t/s/infer"]["qps_limit"] == 100.0


# ---------------------------------------------------------------------- #
# the noisy-neighbor verdict lifecycle (pure ledger)
# ---------------------------------------------------------------------- #
class TestLedgerVerdict:
    def _interval(self, led, storm_serves=20, victim_sheds=1,
                  victim_serves=2):
        for _ in range(storm_serves):
            led.note_serve("t", "storm", ms=1.0)
        for _ in range(victim_serves):
            led.note_serve("t", "victim", ms=1.0)
        if victim_sheds:
            led.note_shed("t", "victim", n=victim_sheds)

    def test_fires_once_stays_open_clears_refires(self):
        led = tenants.TenantLedger()
        self._interval(led)
        fired = led.sweep(now=100.0)
        assert fired is not None
        assert fired["kind"] == "noisy-neighbor"
        assert fired["tenant"] == "storm"
        assert fired["victims"] == ["victim"] and fired["why"] == ["shed"]
        assert led.episodes() == 1
        # condition persists -> episode stays open, NO refire
        self._interval(led)
        assert led.sweep() is None and led.episodes() == 1
        # zero-delta interval -> clears
        assert led.sweep() is None
        snap = led.stats_snapshot()
        assert snap["active"] is False and snap["episodes"] == 1
        assert snap["verdict"]["tenant"] == "storm"   # retained
        # storm returns -> a NEW episode
        self._interval(led)
        assert led.sweep() is not None and led.episodes() == 2

    def test_single_active_tenant_never_fires(self):
        led = tenants.TenantLedger()
        for _ in range(50):
            led.note_serve("t", "storm")
        led.note_shed("t", "storm")
        assert led.sweep() is None and led.episodes() == 0

    def test_stale_serving_is_a_degradation(self):
        led = tenants.TenantLedger()
        for _ in range(20):
            led.note_serve("t", "storm")
        led.note_serve("t", "victim", age_s=0.95, bound_s=1.0)
        fired = led.sweep()
        assert fired is not None and fired["why"] == ["stale"]

    def test_below_storm_share_never_fires(self):
        led = tenants.TenantLedger()
        for _ in range(5):
            led.note_serve("t", "storm")
        for _ in range(5):
            led.note_serve("t", "victim")
        led.note_shed("t", "victim")
        assert led.sweep() is None   # 6/11 < 0.6 with the shed counted

    def test_flightrec_records_shed_and_verdict(self):
        flightrec.reset()
        led = tenants.TenantLedger()
        self._interval(led)
        led.sweep()
        kinds = [s[2] for s in flightrec.RECORDER.snapshot()]
        assert flightrec.EV_TENANT_SHED in kinds
        assert flightrec.EV_TENANT_VERDICT in kinds

    def test_snapshot_shape_and_admission_block(self):
        tenants.LEDGER.note_serve("t", "a", ms=2.0)
        tenants.LEDGER.note_serve("t", "a", ms=4.0)
        ctl = AdmissionController()
        ctl.set_tenant_limit("t", "a", "infer", 9.0)
        ctl.admit("t", tenant="a")
        snap = tenants.stats_snapshot()
        e = snap["tables"]["t"]["a"]
        assert e["served"] == 2 and e["shed"] == 0 and e["deferred"] == 0
        assert e["infer"]["count"] == 2
        assert snap["shares"] == {"a": 1.0}
        assert snap["admission"]["t/a/infer"]["admitted"] == 1

    def test_track_max_folds_ledger_entries(self):
        config.set_flag("tenant_track_max", 2)
        led = tenants.TenantLedger()
        for tn in ("a", "b", "c", "d"):
            led.note_serve("t", tn)
        t = led.stats_snapshot()["tables"]["t"]
        assert set(t) == {"a", "b", "~other"}
        assert t["~other"]["served"] == 2


# ---------------------------------------------------------------------- #
# aggregator merge: proc-dedupe serve ledger, sum shard meters
# ---------------------------------------------------------------------- #
def _ten_block(ts=100.0, tenant="storm"):
    return {
        "tables": {"t": {
            "storm": {"served": 80, "shed": 40, "deferred": 0,
                      "max_age_s": 0.5, "infer": _hist()},
            "victim": {"served": 4, "shed": 1, "deferred": 2,
                       "max_age_s": 0.1, "infer": _hist(1, 2.0)},
        }},
        "shares": {"storm": 0.9, "victim": 0.1},
        "episodes": 1, "active": True,
        "verdict": {"kind": "noisy-neighbor", "tenant": tenant,
                    "share": 0.9, "victims": ["victim"],
                    "why": ["shed"], "ts": ts},
        "admission": {"t/storm/infer": {"admitted": 80, "shed": 40,
                                        "qps_limit": 50.0}},
    }


def _rank_stats(rank, pid=11, ten=None, sketch=True):
    sk = hotkeys.SpaceSaving(4)
    sk.offer_key("acme", 2)
    shard = {"kind": "row", "adds": 4, "gets": 2, "applies": 4,
             "queue_depth": 0, "get_bytes": 6, "add_bytes": 10,
             "rows": 8,
             "tenants": {"acme": {"ops": 2, "add_bytes": 10,
                                  "get_bytes": 6}}}
    if sketch:
        shard["tenants"]["~sketch"] = sk.to_dict()
    st = {"rank": rank, "addr": f"h:{rank}", "pid": pid,
          "monitors": {}, "notes": {}, "shards": {"t": shard}}
    if ten is not None:
        st["tenants"] = ten
    return st


class TestAggregatorMerge:
    def _merge(self, st0, st1):
        return aggregator.merge_cluster(
            {0: st0, 1: st1},
            {0: {"status": "ok", "addr": "h:0"},
             1: {"status": "ok", "addr": "h:1"}}, world=2)

    def test_same_process_dedupes_ledger_sums_shards(self):
        ten = _ten_block()
        rec = self._merge(_rank_stats(0, ten=ten), _rank_stats(1, ten=ten))
        tb = rec["tenants"]
        # serve ledger (process-global): ONE process -> counted once
        assert tb["tables"]["t"]["storm"]["served"] == 80
        assert tb["episodes"] == 1 and tb["active"] is True
        # shard meters (per-shard objects): summed across ranks
        assert tb["wire"]["acme"] == {"ops": 4, "add_bytes": 20,
                                      "get_bytes": 12}
        assert tb["sketch"]["total"] == 4
        # merged extras: shed_rate + merged infer hist + recomputed shares
        assert tb["tables"]["t"]["storm"]["shed_rate"] == 0.3333
        assert tb["tables"]["t"]["storm"]["infer"]["count"] == 3
        assert tb["shares"]["storm"] == round(120 / 125, 4)
        assert tb["admission"]["t/storm/infer"]["admitted"] == 80
        json.dumps(rec)

    def test_distinct_processes_sum_and_latest_verdict_wins(self):
        rec = self._merge(
            _rank_stats(0, pid=11, ten=_ten_block(ts=100.0)),
            _rank_stats(1, pid=22, ten=_ten_block(ts=200.0,
                                                  tenant="other")))
        tb = rec["tenants"]
        assert tb["tables"]["t"]["storm"]["served"] == 160
        assert tb["episodes"] == 2
        assert tb["verdict"]["tenant"] == "other"   # ts=200 wins
        assert tb["admission"]["t/storm/infer"]["admitted"] == 160

    def test_absent_block_is_additive(self):
        rec = self._merge(_rank_stats(0, sketch=False),
                          _rank_stats(1, sketch=False))
        # shard meters alone still surface as the wire sub-block
        assert rec["tenants"]["wire"]["acme"]["ops"] == 4
        assert not rec["tenants"].get("tables")

    def test_derive_rates_per_tenant(self):
        def rec_at(ts, served):
            return {"kind": "cluster", "ts": ts, "tables": {},
                    "tenants": {"tables": {"t": {
                        "storm": {"served": served, "shed": 0,
                                  "deferred": 0}}}}}
        prev, cur = rec_at(100.0, 10), rec_at(102.0, 50)
        assert aggregator.derive_rates(prev, cur) is not None
        r = cur["tenants"]["tables"]["t"]["storm"]["rates"]
        assert r["served_per_s"] == pytest.approx(20.0)
        assert r["shed_per_s"] == 0.0

    def test_compact_record_keeps_tenants(self):
        rec = self._merge(_rank_stats(0, ten=_ten_block()),
                          _rank_stats(1, ten=_ten_block()))
        out = aggregator.compact_record(rec)
        assert out["tenants"]["episodes"] == 1


# ---------------------------------------------------------------------- #
# renderers: mvtop panel, dump_metrics block, exporter gauges
# ---------------------------------------------------------------------- #
class TestRenderers:
    def _rec(self):
        ten = _ten_block()
        return aggregator.merge_cluster(
            {0: _rank_stats(0, ten=ten), 1: _rank_stats(1, ten=ten)},
            {0: {"status": "ok", "addr": "h:0"},
             1: {"status": "ok", "addr": "h:1"}}, world=2)

    def test_mvtop_tenant_panel(self):
        _tools()
        import mvtop
        out = mvtop.render(self._rec())
        assert "tenants: episodes 1  NOISY-NEIGHBOR ACTIVE" in out
        assert ("verdict: noisy-neighbor tenant=storm share=0.900 "
                "victims=victim why=shed") in out
        assert "t/storm" in out and "t/victim" in out
        assert "budgets (admitted/shed): t/storm/infer 80/40@50.0qps" in out
        assert "wire ops: acme:4op/0.00MB" in out

    def test_mvtop_renders_without_tenant_block(self):
        _tools()
        import mvtop
        rec = aggregator.merge_cluster(
            {0: {"rank": 0, "monitors": {}, "shards": {}}},
            {0: {"status": "ok", "addr": "h:0"}}, world=1)
        assert "tenants:" not in mvtop.render(rec)

    def test_dump_metrics_tenant_lines(self):
        _tools()
        import dump_metrics
        out = "\n".join(dump_metrics._tenants_lines(
            self._rec()["tenants"]))
        assert "tenants: episodes=1 active=True" in out
        assert "verdict[noisy-neighbor] tenant=storm:" in out
        assert "budget[t/storm/infer]: admitted=80 shed=40" in out
        assert "wire: acme=4op/0.00MB" in out
        # both entry points route through the same renderer
        assert ("tenants: episodes=1 active=True"
                in dump_metrics.format_cluster_record(self._rec()))
        per_rank = dump_metrics.format_record(
            {"rank": 0, "monitors": {}, "shards": {},
             "tenants": _ten_block()})
        assert "tenants: episodes=1 active=True" in per_rank

    def test_dump_metrics_renders_without_block(self):
        _tools()
        import dump_metrics
        out = dump_metrics.format_record(
            {"rank": 0, "monitors": {}, "shards": {}})
        assert "tenants:" not in out

    def test_exporter_mv_tenant_gauges(self):
        from multiverso_tpu.telemetry.exporter import prometheus_text
        txt = prometheus_text({"rank": 0, "monitors": {}, "shards": {},
                               "tenants": _ten_block()})
        assert ('mv_tenant_served_total{table="t",tenant="storm",'
                'rank="0"} 80') in txt
        assert ('mv_tenant_shed_total{table="t",tenant="storm",'
                'rank="0"} 40') in txt
        assert 'mv_tenant_p99_ms{table="t",tenant="storm",rank="0"}' in txt
        assert 'mv_tenant_share{tenant="storm",rank="0"} 0.9' in txt
        assert "mv_tenant_budget_admitted" in txt
        assert 'mv_tenant_episodes{rank="0"} 1' in txt
        assert 'mv_tenant_verdict_active{rank="0"} 1' in txt

    def test_exporter_no_series_without_block(self):
        from multiverso_tpu.telemetry.exporter import prometheus_text
        txt = prometheus_text({"rank": 0, "monitors": {}, "shards": {}})
        assert "mv_tenant_" not in txt


# ---------------------------------------------------------------------- #
# check_obs_surface lint 6
# ---------------------------------------------------------------------- #
class TestLintSix:
    def test_real_surface_is_clean(self):
        _tools()
        import check_obs_surface
        assert check_obs_surface.tenant_surface_findings() == []

    def test_catches_a_dark_key(self):
        _tools()
        import check_obs_surface
        fs = check_obs_surface.tenant_surface_findings(
            keys_by_src={"fake.py:f()": {"darkkey123"}},
            renderer_text='lines.append("nothing relevant")')
        assert len(fs) == 1
        assert "darkkey123" in fs[0] and "fake.py:f()" in fs[0]

    def test_quoted_key_passes(self):
        _tools()
        import check_obs_surface
        assert check_obs_surface.tenant_surface_findings(
            keys_by_src={"fake.py:f()": {"brightkey"}},
            renderer_text="x.get('brightkey')") == []


# ---------------------------------------------------------------------- #
# flightrec coverage + postmortem timeline
# ---------------------------------------------------------------------- #
class TestFlightrecAndPostmortem:
    def test_ev_names_and_msg_coverage(self):
        assert flightrec.EV_NAMES[flightrec.EV_TENANT_SHED] == "tenant.shed"
        assert (flightrec.EV_NAMES[flightrec.EV_TENANT_VERDICT]
                == "tenant.verdict")
        cov = flightrec.MSG_EV_COVERAGE
        assert flightrec.EV_TENANT_SHED in cov["MSG_GET_ROWS"]
        assert flightrec.EV_TENANT_SHED in cov["MSG_SNAPSHOT"]
        assert flightrec.EV_TENANT_VERDICT in cov["MSG_STATS"]

    def test_postmortem_tenant_timeline(self, tmp_path):
        _tools()
        import postmortem
        config.set_flag("flightrec_dir", str(tmp_path))
        flightrec.configure(0)
        led = tenants.TenantLedger()
        for _ in range(20):
            led.note_serve("t", "storm")
        led.note_shed("t", "victim", n=2)
        led.note_serve("t", "victim")
        assert led.sweep() is not None
        path = flightrec.dump_global("tenant verdict test")
        dumps = [postmortem.load_dump(path)]
        tl = postmortem.tenant_timeline(dumps)
        evs = {e["ev"] for e in tl}
        assert evs == {"tenant.shed", "tenant.verdict"}
        rep = postmortem.render_report(dumps)
        assert "tenant plane (telemetry/tenants.py): sheds" in rep
        assert "VERDICT noisy-neighbor storm" in rep


# ---------------------------------------------------------------------- #
# run_bench victim-tenant regression keys
# ---------------------------------------------------------------------- #
class TestRunBenchFlags:
    def _headline(self, p99, shed):
        return {"extra": {"serving": {"tenants": {"victim": {
            "infer_p99_ms": p99, "shed_rate": shed}}}}}

    def test_victim_growth_flags(self):
        _tools()
        import run_bench
        flags = run_bench.flag_regressions(
            self._headline(1.0, 0.06), self._headline(2.5, 0.2))
        assert any("victim-tenant serving p99" in f for f in flags)
        assert any("victim-tenant shed rate" in f for f in flags)

    def test_shed_rate_baseline_floor(self):
        """A 0.0 shed baseline must not flag every first nonzero shed:
        the floor (0.05) absorbs noise, growth past 2 x floor flags."""
        _tools()
        import run_bench
        assert run_bench.flag_regressions(
            self._headline(1.0, 0.0), self._headline(1.0, 0.08)) == []
        flags = run_bench.flag_regressions(
            self._headline(1.0, 0.0), self._headline(1.0, 0.2))
        assert any("victim-tenant shed rate" in f for f in flags)


# ---------------------------------------------------------------------- #
# the chaos scenario smoke (tier-1)
# ---------------------------------------------------------------------- #
class TestNoisyNeighborSmoke:
    def test_noisy_neighbor_smoke(self, tmp_path):
        """Strict gates (budget cap, staleness, exactly-one verdict)
        hold on every attempt; the victim-p99 gate compares latencies
        measured seconds apart on a shared box, so that ONE gate gets
        a second attempt — the scenario-smoke weather rule."""
        _tools()
        import bench_chaos
        last = None
        for attempt in range(2):
            r = bench_chaos.scenario_noisy_neighbor(
                seconds=8.0, tmp=os.path.join(str(tmp_path), str(attempt)))
            strict = {g: ok for g, ok in r["gates"].items()
                      if g != "victim_p99"}
            assert all(strict.values()), r["gates"]
            last = r
            if r["gates"]["victim_p99"]:
                break
        assert last["gates"]["victim_p99"], last["gates"]
        assert last["episodes"] == 1 and last["flight_verdicts"] == 1
        assert last["tenants_block"]["verdict"]["tenant"] == "storm"
        assert last["tenants_block"]["active"] is False
