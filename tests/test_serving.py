"""Serving plane (ISSUE 8): MSG_SNAPSHOT subscription RPC, bounded-
staleness ReadReplica (parity, staleness enforcement, hot-row cache),
admission control, the MSG_STATS serving block, cluster merge + mvtop
panel, and the DLRM serving app."""

import os
import sys
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.ps import service as svc
from multiverso_tpu.ps.tables import AsyncMatrixTable, AsyncSparseKVTable
from multiverso_tpu.serving import (AdmissionController, ReadReplica,
                                    SheddingError, TokenBucket)
from multiverso_tpu.serving import replica as replica_mod
from multiverso_tpu.utils import config

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tables(ctxs, rows=64, cols=4, name="srv", **kw):
    """The same sharded table on both ranks of an in-process world."""
    return [AsyncMatrixTable(rows, cols, name=name, ctx=c, seed=0,
                             init_scale=0.1, **kw) for c in ctxs]


# ---------------------------------------------------------------------- #
# MSG_SNAPSHOT: the replica subscription RPC
# ---------------------------------------------------------------------- #
class TestSnapshotRPC:
    def test_snapshot_versions_and_rows(self, two_ranks):
        t0, _t1 = _tables(two_ranks)
        # remote shard (rank 1 owns rows [32, 64))
        meta, arrays = svc.await_reply(
            two_ranks[0].service.request(
                1, svc.MSG_SNAPSHOT, {"table": "srv", "since": -1}),
            30.0, "snapshot")
        assert meta["lo"] == 32 and meta["rows"] == 32
        v0 = meta["version"]
        got = np.asarray(arrays[0], np.float32).reshape(32, 4)
        direct = t0.get_rows(np.arange(32, 64))
        np.testing.assert_array_equal(got, direct)
        # unchanged since (gen, v0): tiny meta-only reply
        meta2, arrays2 = svc.await_reply(
            two_ranks[0].service.request(
                1, svc.MSG_SNAPSHOT, {"table": "srv", "since": v0,
                                      "since_gen": meta["gen"]}),
            30.0, "snapshot")
        assert meta2["unchanged"] and meta2["version"] == v0
        assert arrays2 == [] or len(arrays2) == 0
        # a write bumps the version; since=v0 now ships rows again
        t0.add_rows([40], np.ones((1, 4), np.float32))
        meta3, arrays3 = svc.await_reply(
            two_ranks[0].service.request(
                1, svc.MSG_SNAPSHOT, {"table": "srv", "since": v0,
                                      "since_gen": meta["gen"]}),
            30.0, "snapshot")
        assert meta3["version"] > v0 and not meta3.get("unchanged")
        got3 = np.asarray(arrays3[0], np.float32).reshape(32, 4)
        np.testing.assert_array_equal(got3, t0.get_rows(np.arange(32, 64)))
        # the shard counted both pulls apart from row gets
        sh = t0.server_stats(1)["shards"]["srv"]
        assert sh["snapshots"] == 3 and sh["snapshots_unchanged"] == 1

    def test_unchanged_requires_matching_generation(self, two_ranks):
        """A respawned incarnation restores an older checkpoint and
        re-applies DIFFERENT ops — its version counter can coincide
        with a replica's pre-crash version while the content diverged.
        The dedupe token is therefore (generation, version): a stale
        generation's version must be shipped rows, never 'unchanged'."""
        _tables(two_ranks, name="srv_gen")
        config.set_flag("ps_generation", 3)
        meta, _ = svc.await_reply(
            two_ranks[0].service.request(
                1, svc.MSG_SNAPSHOT,
                {"table": "srv_gen", "since": -1, "since_gen": 3}),
            30.0, "snapshot")
        v, g = meta["version"], meta["gen"]
        assert g == 3
        # matching (gen, version): deduped
        m2, a2 = svc.await_reply(
            two_ranks[0].service.request(
                1, svc.MSG_SNAPSHOT,
                {"table": "srv_gen", "since": v, "since_gen": g}),
            30.0, "snapshot")
        assert m2["unchanged"]
        # same version, STALE generation: rows ship
        m3, a3 = svc.await_reply(
            two_ranks[0].service.request(
                1, svc.MSG_SNAPSHOT,
                {"table": "srv_gen", "since": v, "since_gen": g - 1}),
            30.0, "snapshot")
        assert not m3.get("unchanged") and len(a3) == 1

    def test_snapshot_chunked_stream(self, two_ranks):
        t0, _t1 = _tables(two_ranks, rows=200, cols=3, name="srv_big")
        buf = np.empty((100, 3), np.float32)

        def sink(cmeta, arrays):
            r0, n = int(cmeta["row0"]), int(cmeta["rows"])
            buf[r0:r0 + n] = np.asarray(arrays[0], np.float32).reshape(
                n, 3)

        meta, _ = svc.await_reply(
            two_ranks[0].service.request(
                1, svc.MSG_SNAPSHOT,
                {"table": "srv_big", "since": -1, "chunk": 16},
                chunk_sink=sink),
            30.0, "snapshot")
        assert meta["chunks"] == -(-100 // 16)
        np.testing.assert_array_equal(buf, t0.get_rows(
            np.arange(100, 200)))

    def test_hash_shard_refuses_snapshot(self, two_ranks):
        kv = AsyncSparseKVTable(4, name="srv_kv", ctx=two_ranks[0])
        kv.add_rows([0], np.ones((1, 4), np.float32))   # key 0 -> rank 0
        fut = two_ranks[0].service.request(
            two_ranks[0].rank, svc.MSG_SNAPSHOT,
            {"table": "srv_kv", "since": -1})
        with pytest.raises(svc.PSError, match="row-partitioned"):
            svc.await_reply(fut, 30.0, "snapshot")


# ---------------------------------------------------------------------- #
# ReadReplica
# ---------------------------------------------------------------------- #
class TestReadReplica:
    def test_parity_and_versions(self, two_ranks):
        t0, _t1 = _tables(two_ranks)
        rep = ReadReplica(t0, start=False, staleness_s=30.0)
        rep.refresh()
        ids = np.arange(64)
        np.testing.assert_array_equal(rep.get_rows(ids), t0.get_rows(ids))
        # writes on both shards, refresh, exact parity again
        t0.add_rows([3, 40], np.full((2, 4), 0.25, np.float32))
        rep.refresh()
        np.testing.assert_array_equal(rep.get_rows(ids), t0.get_rows(ids))
        st = rep.stats()
        for rank in (0, 1):
            shard_v = t0.server_stats(rank)["shards"]["srv"]["version"]
            assert st["versions"][str(rank)] == shard_v
        rep.close()

    def test_unchanged_pulls_are_deduped(self, two_ranks):
        t0, _t1 = _tables(two_ranks)
        rep = ReadReplica(t0, start=False, staleness_s=30.0)
        rep.refresh()
        rep.refresh()   # nothing applied: both shards answer unchanged
        assert rep.stats()["unchanged_pulls"] == 2
        # the snapshot buffer is REUSED on an all-unchanged epoch (no
        # copy churn), and the epoch still advances
        assert rep.stats()["epoch"] == 2
        rep.close()

    def test_staleness_bound_enforced(self, two_ranks):
        # bound 0.5s: comfortably above a loaded box's pull time (a
        # bound near the pull cost would test scheduler weather, not
        # the enforcement)
        t0, _t1 = _tables(two_ranks)
        rep = ReadReplica(t0, start=False, staleness_s=0.5)
        rep.refresh()
        t0.add_rows([5], np.ones((1, 4), np.float32))
        time.sleep(0.7)   # snapshot now over bound
        rows, age = rep.get_rows([5], with_age=True)
        # the read REFRESHED before serving: fresh data, in-bound age
        assert age <= 0.5
        np.testing.assert_array_equal(rows, t0.get_rows([5]))
        assert rep.stats()["deferred"] >= 1
        rep.close()

    def test_concurrent_stale_readers_share_one_pull(self, two_ranks):
        """K readers finding the snapshot over bound must be satisfied
        by ONE pull, not perform K serialized full-table pulls against
        the (already slow) owner: the deferred-refresh path relaxes
        the single-flight dedupe to 'any pull started within the
        bound'."""
        t0, _t1 = _tables(two_ranks, name="srv_share")
        rep = ReadReplica(t0, start=False, staleness_s=0.5)
        rep.refresh()
        time.sleep(0.7)   # over bound for everyone at once
        e0 = rep.stats()["epoch"]
        errs = []

        def read():
            try:
                rep.get_rows([1], cls="train")
            except Exception as e:   # noqa: BLE001
                errs.append(e)

        ths = [threading.Thread(target=read) for _ in range(6)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=30)
        assert not errs, errs[:2]
        # one pull (two at most, if a reader raced the bound edge)
        assert rep.stats()["epoch"] - e0 <= 2, rep.stats()["epoch"] - e0
        assert rep.stats()["deferred"] >= 1
        rep.close()

    def test_background_refresh_thread(self, two_ranks):
        t0, _t1 = _tables(two_ranks)
        rep = ReadReplica(t0, refresh_s=0.05, staleness_s=5.0)
        try:
            t0.add_rows([9], np.ones((1, 4), np.float32))
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if rep.stats()["epoch"] >= 2 and np.array_equal(
                        rep.get_rows([9]), t0.get_rows([9])):
                    break
                time.sleep(0.05)
            else:
                pytest.fail("background refresh never caught up")
        finally:
            rep.close()

    def test_out_buffer_fill(self, two_ranks):
        t0, _t1 = _tables(two_ranks)
        rep = ReadReplica(t0, start=False, staleness_s=30.0)
        rep.refresh()
        out = np.empty((5, 4), np.float32)
        got = rep.get_rows([1, 2, 33, 40, 63], out=out)
        assert got is out
        np.testing.assert_array_equal(out,
                                      t0.get_rows([1, 2, 33, 40, 63]))
        rep.close()

    def test_reads_served_while_writes_flow(self, two_ranks):
        """Concurrent writer + replica reader: every read returns an
        internally consistent epoch (rows from one adopted snapshot,
        never a torn mix) — checked via a row pair written atomically
        in one add frame, which must always agree."""
        t0, _t1 = _tables(two_ranks, rows=16, cols=2, name="srv_tear")
        # establish the invariant before any reader runs: the random
        # init's two columns differ, writes keep them equal
        t0.set_rows([2], np.zeros((1, 2), np.float32))
        rep = ReadReplica(t0, start=False, staleness_s=30.0)
        rep.refresh()
        stop = threading.Event()
        errs = []

        def writer():
            k = 0.0
            while not stop.is_set():
                k += 1.0
                # rows 2 (rank 0) is written with a single value; the
                # replica must serve each snapshot's bytes verbatim
                t0.set_rows([2], np.full((1, 2), k, np.float32))
                rep.refresh()

        def reader():
            while not stop.is_set():
                r = rep.get_rows([2], cls="train")
                if r[0, 0] != r[0, 1]:   # torn within one row/epoch
                    errs.append(r.copy())

        ths = [threading.Thread(target=writer, daemon=True),
               threading.Thread(target=reader, daemon=True)]
        for t in ths:
            t.start()
        time.sleep(0.7)
        stop.set()
        for t in ths:
            t.join(timeout=10)
        assert not errs, errs[:3]
        rep.close()


# ---------------------------------------------------------------------- #
# hot-row cache (sketch-seeded, epoch-consistent)
# ---------------------------------------------------------------------- #
class TestHotRowCache:
    def test_cache_seeded_and_counted(self, two_ranks):
        # adagrad shards never register natively (PR-6 rule), so the
        # serve path — and therefore the hot-key sketch that seeds the
        # cache — stays python-deterministic on BOTH wire planes
        t0, _t1 = _tables(two_ranks, rows=64, cols=4, name="srv_hot",
                          updater="adagrad")
        # make rows 7 and 50 hot on the shards' sketches (shard traffic
        # is what seeds the cache)
        for _ in range(20):
            t0.get_rows([7, 50])
        rep = ReadReplica(t0, start=False, staleness_s=30.0,
                          cache_rows=8)
        rep.refresh()
        st = rep.stats()
        assert st["cache_rows"] > 0
        # a fully-cached request serves from the device array, bytes
        # equal to the host snapshot (same epoch by construction)
        dev = rep.cache_lookup([7, 50])
        assert dev is not None
        np.testing.assert_array_equal(np.asarray(dev),
                                      t0.get_rows([7, 50]))
        # an uncached id misses the device path
        cold = int(np.setdiff1d(np.arange(64),
                                np.asarray(rep._cache.ids()))[0])
        assert rep.cache_lookup([7, cold]) is None
        # hit/miss accounting over a mixed request
        h0, m0 = rep.stats()["cache_hits"], rep.stats()["cache_misses"]
        rep.get_rows([7, 50, cold])
        st = rep.stats()
        assert st["cache_hits"] - h0 == 2
        assert st["cache_misses"] - m0 == 1
        rep.close()

    def test_cache_follows_snapshot_epoch(self, two_ranks):
        t0, _t1 = _tables(two_ranks, rows=64, cols=4, name="srv_hot2",
                          updater="adagrad")
        for _ in range(10):
            t0.get_rows([3])
        rep = ReadReplica(t0, start=False, staleness_s=30.0,
                          cache_rows=4)
        rep.refresh()
        assert rep.cache_lookup([3]) is not None
        t0.add_rows([3], np.ones((1, 4), np.float32))
        rep.refresh()
        np.testing.assert_array_equal(np.asarray(rep.cache_lookup([3])),
                                      t0.get_rows([3]))
        rep.close()

    def test_stale_device_cache_dropped_at_swap_commit(self, two_ranks):
        """Regression (ISSUE 10 satellite): when the snapshot content
        moves but no same-epoch cache was built (no hot ids / build
        failure), the swap must DROP the previous device cache — not
        keep an old-epoch device array pinned (the PR-5 ``_pin_buf``
        anchor shape) and serving retired rows — while an UNCHANGED
        epoch keeps it (same content, still epoch-consistent)."""
        t0, _t1 = _tables(two_ranks, rows=64, cols=4, name="srv_hot3",
                          updater="adagrad")
        for _ in range(10):
            t0.get_rows([3])
        rep = ReadReplica(t0, start=False, staleness_s=30.0,
                          cache_rows=4)
        rep.refresh()
        assert rep._cache.memory_stats()["device_bytes"] > 0
        # unchanged epoch + no rebuild: keeping the cache is safe
        rep._hot_ids = None
        rep.refresh()
        assert rep._cache.memory_stats()["device_bytes"] > 0
        # content moved + no rebuild: the old-epoch cache must go
        t0.add_rows([3], np.ones((1, 4), np.float32))
        rep.refresh()
        assert (rep._cache.memory_stats()["device_bytes"] == 0
                and len(rep._cache) == 0)
        assert rep.cache_lookup([3]) is None
        rep.close()

    def test_gc_census_no_device_array_growth_across_refreshes(
            self, two_ranks):
        """gc-census regression (ISSUE 10 satellite): 3 refresh cycles
        with content changes and cache rebuilds must hold the live
        device-array census flat — each swap's rebind releases the
        previous epoch's device cache, nothing accumulates."""
        import gc

        import jax
        t0, _t1 = _tables(two_ranks, rows=64, cols=4, name="srv_gc",
                          updater="adagrad")
        for _ in range(10):
            t0.get_rows([5, 9])
        rep = ReadReplica(t0, start=False, staleness_s=30.0,
                          cache_rows=4)
        rep.refresh()
        gc.collect()
        baseline = len(jax.live_arrays())
        for i in range(3):
            t0.add_rows([5], np.full((1, 4), float(i + 1), np.float32))
            rep.refresh()
            gc.collect()
            count = len(jax.live_arrays())
            assert count <= baseline, (
                f"live device arrays grew across refresh {i}: "
                f"{baseline} -> {count} (old-epoch cache retained?)")
        rep.close()


# ---------------------------------------------------------------------- #
# admission control
# ---------------------------------------------------------------------- #
class TestAdmission:
    def test_token_bucket_refill(self):
        b = TokenBucket(10.0, burst=2.0)
        t = 1000.0
        assert b.try_acquire(now=t) and b.try_acquire(now=t)
        assert not b.try_acquire(now=t)          # burst drained
        assert b.try_acquire(now=t + 0.1)        # 1 token refilled
        assert not b.try_acquire(now=t + 0.1)
        # refill caps at burst even after a long idle
        assert b.try_acquire(now=t + 100.0, n=2.0)
        assert not b.try_acquire(now=t + 100.0)

    def test_clock_never_rewinds_tokens(self):
        b = TokenBucket(10.0, burst=1.0)
        assert b.try_acquire(now=1000.0)
        # an out-of-order timestamp must not mint negative refill
        assert not b.try_acquire(now=999.0)
        assert b.try_acquire(now=1000.2)

    def test_priority_classes(self):
        adm = AdmissionController()
        adm.set_limit("t", "infer", 1.0, burst=1.0)
        assert adm.admit("t", "infer")
        assert not adm.admit("t", "infer")       # over budget: shed
        for _ in range(50):                       # train NEVER sheds
            assert adm.admit("t", "train")
        st = adm.stats()
        assert st["t/infer"]["shed"] == 1
        assert st["t/infer"]["admitted"] == 1
        assert st["t/train"]["shed"] == 0
        assert st["t/train"]["qps_limit"] is None

    def test_flag_default_limit(self):
        config.set_flag("serving_infer_qps", 1.0)
        adm = AdmissionController()
        assert adm.admit("x", "infer")            # burst of 1
        assert not adm.admit("x", "infer")
        assert adm.admit("x", "train")            # flag gates infer only

    def test_explicit_exemption_beats_flag_default(self):
        """set_limit(table, 'infer', 0) is an EXEMPTION, not just a
        removal: it must override the serving_infer_qps flag default,
        or the lazy default silently reinstalls the limit on the next
        admit and one table can never be opted out."""
        config.set_flag("serving_infer_qps", 1.0)
        adm = AdmissionController()
        adm.set_limit("x", "infer", 0)
        for _ in range(20):
            assert adm.admit("x", "infer")
        # other tables still get the flag default
        assert adm.admit("y", "infer")
        assert not adm.admit("y", "infer")

    def test_unknown_class_rejected(self):
        with pytest.raises(ValueError, match="admission class"):
            AdmissionController().set_limit("t", "batch", 1.0)

    def test_replica_integration_sheds_and_counts(self, two_ranks):
        t0, _t1 = _tables(two_ranks, name="srv_adm")
        adm = AdmissionController()
        adm.set_limit("srv_adm", "infer", 1.0, burst=1.0)
        rep = ReadReplica(t0, start=False, staleness_s=30.0,
                          admission=adm)
        rep.refresh()
        rep.get_rows([1])
        with pytest.raises(SheddingError):
            rep.get_rows([1])
        rep.get_rows([1], cls="train")   # priority traffic unaffected
        st = rep.stats()
        assert st["shed"] == 1 and st["served"] == 2
        assert st["admission"]["srv_adm/infer"]["shed"] == 1
        # the Dashboard counters behind the zoo shutdown report
        from multiverso_tpu.utils.dashboard import Dashboard
        assert Dashboard.get("table[srv_adm].get.shed").count == 1
        assert Dashboard.get("table[srv_adm].get.replica").count == 2
        rep.close()


# ---------------------------------------------------------------------- #
# telemetry surfaces: MSG_STATS block, cluster merge, mvtop panel
# ---------------------------------------------------------------------- #
class TestServingTelemetry:
    def test_stats_payload_and_msg_stats(self, two_ranks):
        t0, _t1 = _tables(two_ranks, name="srv_tel")
        rep = ReadReplica(t0, start=False, staleness_s=30.0)
        rep.refresh()
        rep.get_rows([1], cls="train")
        # local payload
        local = two_ranks[0].service.stats_payload()
        assert local["serving"]["srv_tel"]["served"] == 1
        # over the socket: rank 1 pulls rank 0's stats via MSG_STATS
        remote = two_ranks[1].service.stats(0)
        assert remote["serving"]["srv_tel"]["epoch"] == 1
        assert remote["serving"]["srv_tel"]["bound_s"] == 30.0
        rep.close()

    def test_merge_cluster_serving_block(self):
        from multiverso_tpu.telemetry import aggregator
        rep_stats = {"epoch": 5, "age_s": 0.1, "bound_s": 2.0,
                     "refresh_ms": 3.0, "cache_rows": 8,
                     "cache_hit_rate": 0.5, "served": 100, "shed": 10,
                     "deferred": 1, "cache_hits": 50, "cache_misses": 50}
        mk = lambda rank, pid: {   # noqa: E731
            "rank": rank, "pid": pid, "addr": f"127.0.0.1:{9000 + rank}",
            "monitors": {}, "shards": {},
            "serving": {"emb": dict(rep_stats)}}
        # two ranks, same process: the block dedupes by (host, pid)
        rec = aggregator.merge_cluster(
            {0: mk(0, 42), 1: mk(1, 42)}, {0: {}, 1: {}}, world=2)
        assert rec["serving"]["emb"]["served"] == 100
        # two processes: counters sum
        rec2 = aggregator.merge_cluster(
            {0: mk(0, 42), 1: mk(1, 43)}, {0: {}, 1: {}}, world=2)
        ent = rec2["serving"]["emb"]
        assert ent["served"] == 200 and ent["shed"] == 20
        assert ent["shed_rate"] == round(20 / 220, 4)
        assert ent["cache_hit_rate"] == 0.5
        assert set(ent["replicas"]) == {"0", "1"}

    def test_derive_rates_serving(self):
        from multiverso_tpu.telemetry import aggregator
        prev = {"kind": "cluster", "ts": 100.0, "tables": {},
                "serving": {"emb": {"served": 100, "shed": 0}}}
        cur = {"kind": "cluster", "ts": 102.0, "tables": {},
               "serving": {"emb": {"served": 300, "shed": 20}}}
        aggregator.derive_rates(prev, cur)
        assert cur["serving"]["emb"]["rates"]["served_per_s"] == 100.0
        assert cur["serving"]["emb"]["rates"]["shed_per_s"] == 10.0

    def test_mvtop_serving_panel(self):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        import mvtop
        rec = {
            "kind": "cluster", "ts": time.time(), "world": 2,
            "polled": 2,
            "ranks": {"0": {"status": "ok", "addr": "a:1"},
                      "1": {"status": "ok", "addr": "b:2"}},
            "monitors": {},
            "tables": {"emb": {"shards": {"0": {}, "1": {}},
                               "adds": 5, "gets": 9, "applies": 5,
                               "queue_depth": 0, "skew": 1.0,
                               "apply": {}}},
            "serving": {"emb": {
                "replicas": {"0": {"epoch": 7, "age_s": 0.12,
                                   "bound_s": 2.0, "refresh_ms": 3.1,
                                   "cache_rows": 64,
                                   "cache_hit_rate": 0.83}},
                "served": 1234, "shed": 26, "deferred": 1,
                "cache_hits": 100, "cache_misses": 20,
                "cache_hit_rate": 0.8333, "shed_rate": 0.0206,
                "rates": {"served_per_s": 45.2, "shed_per_s": 1.0}}},
        }
        out = mvtop.render(rec)
        assert "serving: replicas=1" in out
        assert "served 1234 (45.2/s)" in out
        assert "shed_rate 2.1%" in out
        assert "replica@rank0: epoch 7  lag 0.120s/2.000s bound" in out
        assert "cache 64 rows (83.0% hit)" in out
        # a serving-only table (owners unreachable this poll) renders
        rec2 = dict(rec, tables={})
        assert "(serving only)" in mvtop.render(rec2)

    def test_dump_metrics_cluster_serving_section(self):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        import dump_metrics
        rec = {"kind": "cluster", "ts": 1.0, "world": 1, "polled": 1,
               "ranks": {"0": {"status": "ok"}}, "tables": {},
               "serving": {"emb": {
                   "replicas": {"0": {"epoch": 3, "age_s": 0.1,
                                      "bound_s": 2.0}},
                   "served": 10, "shed": 1, "deferred": 0,
                   "cache_hits": 4, "cache_misses": 6,
                   "cache_hit_rate": 0.4, "shed_rate": 0.0909}}}
        out = dump_metrics.format_cluster_record(rec)
        assert "serving[emb]:" in out and "served=10" in out
        assert "replica@rank0:" in out and "epoch=3" in out

    def test_hit_rate_curve_conservative(self):
        from multiverso_tpu.telemetry import hotkeys
        sk = {"capacity": 4, "total": 100, "observed": 100,
              "items": [[1, 50, 0], [2, 30, 20], [3, 10, 10]]}
        up = dict(hotkeys.hit_rate_curve(sk))
        lo = dict(hotkeys.hit_rate_curve(sk, conservative=True))
        assert up[1] == 0.5 and lo[1] == 0.5
        assert up[2] == 0.8 and lo[2] == 0.6    # err-discounted
        assert lo[2] <= up[2]


# ---------------------------------------------------------------------- #
# the DLRM serving app
# ---------------------------------------------------------------------- #
class TestDLRMServingApp:
    def test_train_while_serve(self, two_ranks):
        from multiverso_tpu.apps.dlrm_serving import DLRMServing
        from multiverso_tpu.models import dlrm
        cfg = dlrm.DLRMConfig(vocab_sizes=(32, 16), embed_dim=8,
                              dense_dim=4, bottom_mlp=(8,),
                              top_mlp=(8, 1))
        app = DLRMServing(cfg, ctx=two_ranks[0], name="app_t", lr=0.2,
                          staleness_s=30.0, start_replica=False)
        peer = AsyncMatrixTable(dlrm.total_rows(cfg), cfg.embed_dim,
                                updater="adagrad", seed=0,
                                init_scale=0.05, name=app.emb.name,
                                ctx=two_ranks[1])
        cat, dense, labels = dlrm.synthetic_ctr(cfg, 512, seed=3)
        losses = []
        for i in range(8):
            loss, write_ms = app.train_step(cat[i * 64:(i + 1) * 64],
                                            dense[i * 64:(i + 1) * 64],
                                            labels[i * 64:(i + 1) * 64])
            assert write_ms >= 0
            losses.append(loss)
        assert losses[-1] < losses[0], losses
        # the inference path: replica rows -> forward -> probabilities
        app.replica.refresh()
        scores = app.infer(cat[:16], dense[:16])
        assert scores.shape == (16,)
        assert np.all((scores >= 0) & (scores <= 1))
        # replica parity against the trained table
        ids = np.arange(dlrm.total_rows(cfg))
        np.testing.assert_array_equal(
            app.replica.get_rows(ids, cls="train"), app.emb.get_rows(ids))
        assert app.serving_stats()["served"] >= 2
        app.close()
        del peer


# ---------------------------------------------------------------------- #
# the bench tool (acceptance smoke at toy scale)
# ---------------------------------------------------------------------- #
def test_bench_serving_smoke():
    """tools/bench_serving.py end to end at tier-1 scale through the
    real subprocess contract: every acceptance gate (replica parity,
    staleness bound, overload shed + bounded train degradation) is an
    IN-RUN assert, so rc 0 means the serving plane held its whole
    contract under real two-class traffic."""
    import json
    import subprocess

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")

    def run_once():
        return subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "bench_serving.py"),
             "5", "3", "2"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=_REPO)

    out = run_once()
    if out.returncode != 0:
        # the overload-degradation ratio is weather-bound (GIL
        # scheduling on a loaded CI box): retry ONCE, same pattern as
        # the chaos bench's slow test — the parity/staleness gates
        # stay strict per run
        out = run_once()
    assert out.returncode == 0, (out.stdout[-500:], out.stderr[-800:])
    line = [x for x in out.stdout.splitlines()
            if x.startswith("RESULT ")][-1]
    r = json.loads(line[len("RESULT "):])
    assert r["parity_bit_for_bit"] and r["versions_match"]
    assert r["staleness_ok"]
    assert r["staleness_max_s"] <= r["staleness_bound_s"]
    assert r["overload_contract_ok"] and r["shed_overload"] > 0
    assert r["served_qps"] > 0 and r["infer_p99_ms"] > 0
    assert r["cache"]["measured_hit_rate"] is not None
    assert r["cache"]["estimated_hit_rate"] is not None
