"""Subprocess body for the multi-process async-PS integration tests.

Unlike tests/multiprocess_worker.py (which exercises the *collective* host
plane under jax.distributed), this runs the uncoordinated PS plane with NO
JAX coordinator at all — rank/world come from argv and peers meet through a
file rendezvous, proving the async plane stands alone (the reference's PS
likewise needed only its own transport, src/zoo.cpp).

Invoked as:  python async_ps_worker.py <rdv_dir> <world> <rank> <mode>
Modes:
  rates — every rank pushes a DIFFERENT row set at a DIFFERENT rate
          (ref WordEmbedding traffic, communicator.cpp:104-142); asserts
          the converged global state.
  kill  — the last rank dies abruptly mid-run; survivors keep trading
          rows on live shards and see a typed PSPeerError (bounded time)
          for the dead shard.
  ftrl_lr — the reference's flagship sparse workload: every rank trains
          sparse FTRL LR through the app on ITS OWN data shard,
          uncoordinated, against the hash-sharded AsyncSparseKVTable
          (ref model/ps_model.cpp:24-41, util/ftrl_sparse_table.h);
          asserts the jointly-trained model classifies well.
  window — the PR-2 client send window at the real OS-process tier:
          every rank streams 1-row windowed adds (integer deltas, so
          float sums are order-independent and EXACT) to its own
          disjoint row set, interleaved with fenced gets that must
          read its own writes; the converged state must equal the
          integer expectation bit-for-bit on every rank.
  flightrec — the PR-4 black box at the real OS-process tier: rank 1
          wedges itself (SIGSTOP: alive, sockets open, serving nothing)
          while rank 0 has gets in flight to it; rank 0's watchdog must
          trip "stuck" and dump its flight recorder, whose in-flight
          table names rank 1's oldest unacked msg id — the parent
          SIGKILLs rank 1 and runs tools/postmortem.py over the dumps.
  stats — the PR-3 telemetry plane end to end: trace_ids on, windowed
          adds to the REMOTE shard, then (a) rank 0 pulls rank 1's
          server-side stats via the MSG_STATS RPC
          (table.server_stats), (b) every rank dumps its trace spans
          as JSONL to MV_METRICS_DIR, (c) the dashboard p50/p99 for
          add_rows/get_rows land in RESULT. The parent test stitches
          the two ranks' trace files and asserts a client span and a
          shard span share one trace ID.
Prints "RESULT <json>" on success.
"""

import json
import os
import sys
import time

import numpy as np


def _sync_point(rdv_dir, world, rank, tag):
    """Test-harness sync via files (NOT a framework barrier — the plane
    under test has none); shared helper in utils/filesync."""
    from multiverso_tpu.utils.filesync import file_barrier
    file_barrier(rdv_dir, world, rank, tag, timeout=60)


def main():
    rdv_dir, world, rank, mode = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), sys.argv[4])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSPeerError, PSService)
    from multiverso_tpu.ps.tables import AsyncKVTable, AsyncMatrixTable
    from multiverso_tpu.utils import config

    config.set_flag("ps_timeout", 20.0)
    config.set_flag("ps_connect_timeout", 10.0)
    if os.environ.get("MV_PS_NATIVE", "") == "0":   # python-plane variant
        config.set_flag("ps_native", False)
    ctx = None
    if mode != "ftrl_lr":   # ftrl_lr goes through the app's default context
        ctx = PSContext(rank, world,
                        PSService(rank, world, FileRendezvous(rdv_dir)))
    out = {"rank": rank}

    if mode == "rates":
        num_row = 8 * world
        t = AsyncMatrixTable(num_row, 4, name="mp_async", ctx=ctx)
        kv = AsyncKVTable(name="mp_kv", ctx=ctx)
        _sync_point(rdv_dir, world, rank, "tables")
        # rank r pushes rows {r, world + r, ..., 7*world + r} — pairwise
        # DISJOINT sets — (r+1)*5 times at rank-dependent pace, with a mix
        # of fire-and-forget and waited adds
        my_rows = np.arange(8) * world + rank
        n_pushes = (rank + 1) * 5
        mids = []
        for i in range(n_pushes):
            mids.append(t.add_rows_async(
                my_rows, np.full((8, 4), 1.0, np.float32)))
            kv.add([rank], [1.0])
            time.sleep(0.002 * (world - rank))
        for m in mids:
            t.wait(m)
        _sync_point(rdv_dir, world, rank, "pushed")
        got = t.get_rows(np.arange(num_row))
        expect = np.zeros(num_row)
        for r in range(world):
            expect[np.arange(8) * world + r] = (r + 1) * 5
        assert np.allclose(got, expect[:, None]), (got[:, 0], expect)
        counts = kv.get()
        assert counts == {r: (r + 1) * 5.0 for r in range(world)}, counts
        out["row_sum"] = float(got.sum())
        out["kv"] = {str(k): v for k, v in sorted(counts.items())}
        # hold the service up until every rank has finished reading (the
        # reference's MV_ShutDown barriers for the same reason)
        _sync_point(rdv_dir, world, rank, "done")

    elif mode == "kill":
        num_row = 5 * world
        t = AsyncMatrixTable(num_row, 2, name="kill_async", ctx=ctx)
        _sync_point(rdv_dir, world, rank, "tables")
        if rank == world - 1:
            # die abruptly, mid-conversation (no cleanup, like a real crash)
            os._exit(17)
        config.set_flag("ps_timeout", 6.0)
        config.set_flag("ps_connect_timeout", 6.0)
        # wait until the victim is certainly gone
        time.sleep(0.5)
        # live shards keep working at full function
        live_rows = [rank * 5, rank * 5 + 1, 0]
        for _ in range(10):
            t.add_rows(live_rows, np.ones((3, 2), np.float32))
        got = t.get_rows([0])
        assert got[0, 0] >= 10.0, got
        # dead shard: typed error within the timeout bound, no hang
        start = time.monotonic()
        try:
            t.get_rows([num_row - 1])
            raise AssertionError("expected PSPeerError for dead shard")
        except PSPeerError:
            pass
        elapsed = time.monotonic() - start
        assert elapsed < 15.0, elapsed
        out["dead_shard_error_s"] = round(elapsed, 2)
        out["live_row0"] = float(got[0, 0])
        # survivors sync among themselves before teardown
        open(os.path.join(rdv_dir, f"alive.{rank}"), "w").close()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not all(
                os.path.exists(os.path.join(rdv_dir, f"alive.{r}"))
                for r in range(world - 1)):
            time.sleep(0.01)
    elif mode == "recover":
        # kill-and-restart shard recovery: PS deaths feed elastic
        # tombstones; the restarted rank republishes via rendezvous,
        # reloads ITS shard from the checkpoint, and peers resume
        # (VERDICT r2 item 5 — the story the reference only declared via
        # its dead backup_worker_ratio flag, src/server.cpp:21)
        from multiverso_tpu import elastic
        restarted = os.environ.get("MV_RESTARTED") == "1"
        # the victim is parametrizable (MV_VICTIM): recovery must not be
        # special-cased to the last rank — rank 0 dying exercises the
        # same machinery from the other end of the id space
        victim = int(os.environ.get("MV_VICTIM", world - 1))
        survivors = [r for r in range(world) if r != victim]
        saver = survivors[0]          # checkpoint writer (was rank 0)
        num_row = 4 * world
        ck = os.path.join(rdv_dir, "recover.ck")
        hb_dir = os.path.join(rdv_dir, "heartbeats")
        elastic.bind_ps(hb_dir, ctx)
        hb = elastic.Heartbeat(hb_dir, interval=0.3, rank=rank).start()
        t = AsyncMatrixTable(num_row, 2, name="rec", ctx=ctx)
        if restarted:
            with open(ck, "rb") as f:
                t.load_local(f)   # ONLY this rank's shard; peers are newer
            hb.beat()
            # serve until every survivor reports done
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline and not all(
                    os.path.exists(os.path.join(rdv_dir, f"done.{r}"))
                    for r in survivors):
                time.sleep(0.05)
            out["restarted"] = True
        else:
            _sync_point(rdv_dir, world, rank, "tables")
            t.add_rows(np.arange(num_row), np.ones((num_row, 2), np.float32))
            t.flush()
            _sync_point(rdv_dir, world, rank, "pushed")
            if rank == saver:
                with open(ck, "wb") as f:
                    t.store(f)
                open(os.path.join(rdv_dir, "saved"), "w").close()
            else:
                deadline = time.monotonic() + 30
                while not os.path.exists(os.path.join(rdv_dir, "saved")):
                    assert time.monotonic() < deadline
                    time.sleep(0.02)
            if rank == victim:
                os._exit(17)
            config.set_flag("ps_timeout", 8.0)
            config.set_flag("ps_connect_timeout", 4.0)
            config.set_flag("ps_reconnect_backoff", 0.5)
            vrow = victim * 4
            # 1) observe the death (typed error, bounded)
            deadline = time.monotonic() + 40
            while True:
                try:
                    t.get_rows([vrow])
                    time.sleep(0.1)
                except Exception:
                    break
                assert time.monotonic() < deadline
            # 2) the PS death fed elastic's failed set (tombstone); generous
            # deadline — this can run on a heavily loaded CI box
            deadline = time.monotonic() + 60
            while victim not in elastic.failed(hb_dir, timeout=1e9):
                assert time.monotonic() < deadline
                time.sleep(0.05)
            out["tombstoned"] = True
            open(os.path.join(rdv_dir, f"down.{rank}"), "w").close()
            # 3) retry until the RESTARTED incarnation serves the restored
            #    value (world ranks each added 1.0 before the checkpoint)
            deadline = time.monotonic() + 90
            val = None
            while time.monotonic() < deadline:
                try:
                    val = float(t.get_rows([vrow])[0, 0])
                    if val == float(world):
                        break
                except Exception:
                    pass
                time.sleep(0.3)
            assert val == float(world), f"recovered value {val}"
            out["recovered_value"] = val
            # 4) a beacon newer than the tombstone clears failed()
            deadline = time.monotonic() + 20
            while victim in elastic.failed(hb_dir, timeout=1e9):
                assert time.monotonic() < deadline
                time.sleep(0.2)
            out["tombstone_cleared"] = True
            # survivors-only barrier: every survivor must OBSERVE the
            # restored checkpoint value before anyone's step-5 add bumps
            # it past world (a fast peer used to race slower pollers).
            # Participant ids are the rank's index in the survivor list,
            # so the barrier works for ANY victim, not just the last.
            _sync_point(rdv_dir, len(survivors), survivors.index(rank),
                        "recovered")
            # 5) training continues against the recovered shard
            t.add_rows([vrow], np.ones((1, 2), np.float32))
            t.flush()
            got = float(t.get_rows([vrow])[0, 0])
            assert got >= world + 1, got
            out["post_value"] = got
            open(os.path.join(rdv_dir, f"done.{rank}"), "w").close()
        hb.stop()

    elif mode == "window":
        from multiverso_tpu.utils.dashboard import Dashboard
        num_row = 8 * world
        t = AsyncMatrixTable(num_row, 4, name="mp_win",
                             send_window_ms=5.0, ctx=ctx)
        assert t._window is not None
        _sync_point(rdv_dir, world, rank, "tables")
        # rank r adds ONLY to rows {r, world + r, ...} — disjoint across
        # ranks — with integer deltas: float addition of small ints is
        # exact and order-independent, so the final state is a BIT-exact
        # expectation even though ranks race
        my_rows = np.arange(8) * world + rank
        n_pushes = 40 + rank * 10
        rng = np.random.default_rng(rank)
        counts = np.zeros(8, np.int64)
        for i in range(n_pushes):
            j = int(rng.integers(8))
            t.add_rows_async([my_rows[j]], np.ones((1, 4), np.float32))
            counts[j] += 1
            if i % 9 == 0:
                # fenced read-your-writes: no flush/wait issued, yet the
                # get must observe every add THIS rank queued so far
                got = t.get_rows(my_rows)
                assert np.array_equal(
                    got, counts[:, None] * np.ones((8, 4), np.float32)), \
                    (i, got[:, 0], counts)
        t.flush()
        _sync_point(rdv_dir, world, rank, "pushed")
        got = t.get_rows(np.arange(num_row))
        expect = np.zeros(num_row, np.int64)
        for r in range(world):
            # replay rank r's draws for the exact expectation
            rr = np.random.default_rng(r)
            c = np.zeros(8, np.int64)
            for _ in range(40 + r * 10):
                c[int(rr.integers(8))] += 1
            expect[np.arange(8) * world + r] = c
        assert np.array_equal(
            got, expect[:, None].astype(np.float32)
            * np.ones((1, 4), np.float32)), got[:, 0]
        out["row_sum"] = float(got.sum())
        out["windowed"] = Dashboard.get(
            "table[mp_win].add_rows.windowed").count
        out["flushes"] = Dashboard.get(
            "table[mp_win].add_rows.flushes").count
        _sync_point(rdv_dir, world, rank, "done")

    elif mode == "flightrec":
        import signal as _signal

        from multiverso_tpu.telemetry import flightrec, watchdog
        frdir = os.environ["MV_FLIGHTREC_DIR"]
        config.set_flag("flightrec_dir", frdir)
        config.set_flag("watchdog_slow_ms", 100.0)
        config.set_flag("watchdog_stuck_s", 0.8)
        config.set_flag("watchdog_interval_s", 0.1)
        watchdog.ensure_started()   # service already started it; idempotent
        num_row = 8 * world
        t = AsyncMatrixTable(num_row, 2, name="fr", ctx=ctx)
        _sync_point(rdv_dir, world, rank, "tables")
        peer = (rank + 1) % world
        # warm + ack the python conn to the peer's shard
        t.add_rows([peer * 8], np.ones((1, 2), np.float32))
        _sync_point(rdv_dir, world, rank, "warm")
        if rank == world - 1:
            # wedge, don't die: SIGSTOP freezes every thread with the
            # sockets OPEN — the "alive but stuck" failure that leaves
            # no error anywhere. The parent SIGKILLs this rank later.
            os.kill(os.getpid(), _signal.SIGSTOP)
            out["wedged"] = True
        else:
            time.sleep(1.0)   # let the victim reach its SIGSTOP
            # two unacked gets: "oldest per (src,dst)" must pick the first
            t.get_rows_async([peer * 8])
            t.get_rows_async([peer * 8 + 1])
            path = os.path.join(frdir, f"flightrec-rank{rank}.jsonl")
            deadline = time.monotonic() + 25
            while time.monotonic() < deadline:
                v = watchdog.last_verdict()
                if v.get("status") == "stuck" and os.path.exists(path):
                    break
                time.sleep(0.05)
            v = watchdog.last_verdict()
            assert v["status"] == "stuck", v
            h = t.server_health()   # local probe sees the wedge too
            assert h["status"] == "stuck" and h["inflight"] >= 2, h
            age, p, mid, _ = flightrec.RECORDER.oldest_inflight()
            out["stuck_peer"] = p
            out["stuck_msg_id"] = mid
            out["oldest_age_s"] = round(age, 3)
            out["dump"] = path
        # NOT syncing here: the victim is frozen and never reaches a
        # barrier; survivors just finish (their dumps are on disk)

    elif mode == "stats":
        from multiverso_tpu.telemetry import trace as ttrace
        from multiverso_tpu.utils.dashboard import Dashboard
        metrics_dir = os.environ["MV_METRICS_DIR"]
        config.set_flag("trace_ids", True)
        config.set_flag("metrics_dir", metrics_dir)
        ttrace.configure(rank)   # ctx (and its service) already exist
        num_row = 8 * world
        t = AsyncMatrixTable(num_row, 4, name="mp_stats",
                             send_window_ms=5.0, ctx=ctx)
        _sync_point(rdv_dir, world, rank, "tables")
        # windowed adds to the NEXT rank's rows: every span chain crosses
        # a real socket (overlapping rows force MSG_BATCH sub-ops too)
        peer = (rank + 1) % world
        peer_rows = np.arange(8) * world + peer
        for i in range(20):
            t.add_rows_async([int(peer_rows[i % 8])],
                             np.ones((1, 4), np.float32))
        t.flush()
        got = t.get_rows(peer_rows)   # fenced read (adds are acked)
        # all 20 unit deltas landed (window merging may have shipped
        # them as fewer wire-level sub-ops — that's the point of it)
        assert float(got.sum()) >= 20 * 4, got
        _sync_point(rdv_dir, world, rank, "pushed")
        # (a) remote dashboard: pull the peer's snapshot over MSG_STATS
        st = t.server_stats(peer)
        assert st["rank"] == peer, st["rank"]
        shard = st["shards"]["mp_stats"]
        assert shard["adds"] >= 3, shard
        assert shard["applies"] >= 1, shard
        assert shard["version"] >= 1, shard
        assert "wave_ops" in shard and "queue_depth" in shard, shard
        # the peer's serve monitors crossed its dashboard
        assert any(n.startswith("ps[mp_stats].") for n in st["monitors"])
        # (c) local client latency histograms: p50/p99 present and sane
        out["monitors"] = {}
        for op in ("add_rows", "get_rows"):
            snap = Dashboard.get(f"table[mp_stats].{op}").snapshot()
            assert snap.timed > 0 and snap.p99_ms >= snap.p50_ms > 0, snap
            assert "p50" in snap.info_string(), snap.info_string()
            out["monitors"][op] = snap.brief_dict()
        out["shard_adds"] = shard["adds"]
        out["stats_rank"] = st["rank"]
        # (b) dump this rank's spans for the parent to stitch
        n = ttrace.dump_to(metrics_dir)
        out["spans"] = n
        assert n > 0
        _sync_point(rdv_dir, world, rank, "done")

    elif mode == "ftrl_lr":
        # the app builds its tables against the default context — point it
        # at this world via the ps_* flags (no JAX coordinator involved)
        config.set_flag("ps_rendezvous", rdv_dir)
        config.set_flag("ps_rank", rank)
        config.set_flag("ps_world", world)
        from multiverso_tpu.apps.logistic_regression import (LogReg,
                                                             LogRegConfig)
        from multiverso_tpu.models import logreg as model_lib
        x, y = model_lib.synthetic_dataset(2048, 12, 2, seed=42)
        train = os.path.join(rdv_dir, f"train_{rank}.svm")
        with open(train, "w") as f:
            for xi, yi in zip(x[rank::world], y[rank::world]):
                feats = " ".join(f"{j}:{v:.5f}" for j, v in enumerate(xi))
                f.write(f"{yi} {feats}\n")
        cfg = LogRegConfig({
            "input_size": "12", "output_size": "2", "sparse": "true",
            "async_ps": "true", "updater_type": "ftrl",
            "learning_rate": "0.1", "train_file": train,
            "train_epoch": "3", "minibatch_size": "64"})
        lr = LogReg(cfg)
        _sync_point(rdv_dir, world, rank, "tables")
        lr.train_file()
        _sync_point(rdv_dir, world, rank, "trained")
        acc = lr.test_arrays(x, y)   # full dataset, jointly-trained model
        assert acc > 0.85, f"accuracy {acc}"
        out["acc"] = round(float(acc), 4)
        _sync_point(rdv_dir, world, rank, "done")
        from multiverso_tpu.ps.service import reset_default_context
        reset_default_context()
    else:
        raise ValueError(mode)

    if ctx is not None:
        ctx.close()
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
