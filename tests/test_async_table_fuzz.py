"""Randomized differential test for the UNCOORDINATED plane: a long
random op sequence through two live PSContexts (riding the native C++
transport where built) must match a plain numpy model exactly — the
async twin of tests/test_table_fuzz.py, catching row-partitioning,
dedupe-in-batch, FIFO-per-owner, and reply-scatter edge cases that the
scripted tests don't reach.

Ordering contract exercised: all ops issue from ONE thread, and every
owner's traffic (including the self shard — a real loopback conn on the
native plane) is per-connection FIFO, so a get issued after an async
add must observe it.
"""

import numpy as np
import pytest

from multiverso_tpu.ps.service import FileRendezvous, PSContext, PSService
from multiverso_tpu.ps.tables import (AsyncArrayTable, AsyncKVTable,
                                      AsyncMatrixTable)


@pytest.fixture
def two_ranks(tmp_path):
    rdv = FileRendezvous(str(tmp_path / "rdv"))
    ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
    yield ctxs
    for c in ctxs:
        c.close()


def test_async_matrix_matches_numpy_model(two_ranks):
    rng = np.random.default_rng(7)
    rows, cols = 37, 5            # awkward split: ceil(37/2)=19 vs 18
    t = AsyncMatrixTable(rows, cols, name="fz_m", ctx=two_ranks[0])
    AsyncMatrixTable(rows, cols, name="fz_m", ctx=two_ranks[1])
    model = np.zeros((rows, cols), np.float32)
    pending = []
    for step in range(120):
        op = rng.choice(["add_rows", "add_rows_async", "get_rows",
                         "add_full", "get_full", "flush"])
        if op in ("add_rows", "add_rows_async"):
            k = int(rng.integers(1, 12))
            ids = rng.integers(0, rows, k)      # duplicates welcome
            vals = rng.normal(size=(k, cols)).astype(np.float32)
            if op == "add_rows":
                t.add_rows(ids, vals)
            else:
                pending.append(t.add_rows_async(ids, vals))
            np.add.at(model, ids, vals)
        elif op == "add_full":
            d = rng.normal(size=(rows, cols)).astype(np.float32)
            t.add(d)
            model += d
        elif op == "get_rows":
            k = int(rng.integers(1, 10))
            ids = np.unique(rng.integers(0, rows, k))
            np.testing.assert_allclose(t.get_rows(ids), model[ids],
                                       rtol=2e-5, atol=2e-4)
        elif op == "get_full":
            np.testing.assert_allclose(t.get(), model, rtol=2e-5,
                                       atol=2e-4)
        else:
            t.flush()
            pending.clear()
    t.flush()
    np.testing.assert_allclose(t.get(), model, rtol=2e-5, atol=2e-4)


def test_async_array_matches_numpy_model(two_ranks):
    rng = np.random.default_rng(11)
    size = 101
    t = AsyncArrayTable(size, name="fz_a", ctx=two_ranks[0])
    AsyncArrayTable(size, name="fz_a", ctx=two_ranks[1])
    model = np.zeros(size, np.float32)
    for step in range(80):
        op = rng.choice(["add", "add_async", "get"])
        if op in ("add", "add_async"):
            d = rng.normal(size=size).astype(np.float32)
            (t.add if op == "add" else t.add_async)(d)
            model += d
        else:
            np.testing.assert_allclose(t.get(), model, rtol=2e-5,
                                       atol=2e-4)
    t.flush()
    np.testing.assert_allclose(t.get(), model, rtol=2e-5, atol=2e-4)


def test_async_sparse_matrix_matches_numpy_model(two_ranks):
    """The stale-row protocol (C++-served dirty-bit GET) is an
    optimization, not a semantics change: get_rows_sparse must always
    equal the model's rows, for EITHER worker's cache, interleaved with
    adds from both ranks' table objects at random."""
    from multiverso_tpu.ps.tables import AsyncSparseMatrixTable
    rng = np.random.default_rng(23)
    rows, cols = 29, 3
    t0 = AsyncSparseMatrixTable(rows, cols, name="fz_s", ctx=two_ranks[0])
    t1 = AsyncSparseMatrixTable(rows, cols, name="fz_s", ctx=two_ranks[1])
    model = np.zeros((rows, cols), np.float32)
    for step in range(100):
        op = rng.choice(["add0", "add1", "sparse0", "sparse1", "plain"])
        if op in ("add0", "add1"):
            k = int(rng.integers(1, 8))
            ids = rng.integers(0, rows, k)
            vals = rng.normal(size=(k, cols)).astype(np.float32)
            (t0 if op == "add0" else t1).add_rows(ids, vals)
            np.add.at(model, ids, vals)
        elif op in ("sparse0", "sparse1"):
            t = t0 if op == "sparse0" else t1
            k = int(rng.integers(1, 10))
            ids = np.unique(rng.integers(0, rows, k))
            got = t.get_rows_sparse(ids)
            np.testing.assert_allclose(got, model[ids], rtol=2e-5,
                                       atol=2e-4)
        else:
            ids = np.unique(rng.integers(0, rows, 6))
            np.testing.assert_allclose(t0.get_rows(ids), model[ids],
                                       rtol=2e-5, atol=2e-4)
    # final full check from both workers' caches
    all_ids = np.arange(rows)
    np.testing.assert_allclose(t0.get_rows_sparse(all_ids), model,
                               rtol=2e-5, atol=2e-4)
    np.testing.assert_allclose(t1.get_rows_sparse(all_ids), model,
                               rtol=2e-5, atol=2e-4)


@pytest.mark.parametrize("wire", ["none", "bf16", "1bit", "topk"])
def test_send_window_bit_for_bit_parity(two_ranks, wire):
    """PR-2 acceptance: a windowed table fed a random interleaving of
    add_rows / add_rows_async / get_rows / flush / wait must be
    BIT-FOR-BIT identical to a window-off table fed the same sequence —
    across the plain wire AND every codec wire (1bit/topk sub-ops keep
    their own payloads inside a MSG_BATCH; none/bf16 merge by exact
    disjoint concat)."""
    rng = np.random.default_rng(91 + len(wire))
    rows, cols = 37, 5
    tw = AsyncMatrixTable(rows, cols, name=f"wz_{wire}", wire=wire,
                          updater="default", send_window_ms=30.0,
                          ctx=two_ranks[0])
    AsyncMatrixTable(rows, cols, name=f"wz_{wire}", wire=wire,
                     updater="default", ctx=two_ranks[1])
    tr = AsyncMatrixTable(rows, cols, name=f"wr_{wire}", wire=wire,
                          updater="default", ctx=two_ranks[0])
    AsyncMatrixTable(rows, cols, name=f"wr_{wire}", wire=wire,
                     updater="default", ctx=two_ranks[1])
    assert tw._window is not None and tr._window is None
    pending = []
    for step in range(90):
        op = rng.choice(["add_rows", "add_rows_async", "get_rows",
                         "flush", "wait"])
        if op in ("add_rows", "add_rows_async"):
            k = int(rng.integers(1, 9))
            ids = rng.integers(0, rows, k)      # duplicates welcome
            vals = rng.normal(size=(k, cols)).astype(np.float32)
            if op == "add_rows":
                tw.add_rows(ids, vals)
                tr.add_rows(ids, vals)
            else:
                pending.append((tw.add_rows_async(ids, vals),
                                tr.add_rows_async(ids, vals)))
        elif op == "get_rows":
            k = int(rng.integers(1, 10))
            ids = rng.integers(0, rows, k)
            a, b = tw.get_rows(ids), tr.get_rows(ids)
            assert np.array_equal(a, b), f"step {step}: window diverged"
        elif op == "wait" and pending:
            mw, mr = pending.pop(rng.integers(len(pending)))
            tw.wait(mw)
            tr.wait(mr)
        else:
            tw.flush()
            tr.flush()
            pending.clear()
    tw.flush()
    tr.flush()
    assert np.array_equal(tw.get(), tr.get())


@pytest.mark.parametrize("updater", ["adagrad", "adam"])
def test_send_window_parity_stateful_updater(two_ranks, updater):
    """Same parity contract through STATEFUL server-side updaters.
    adagrad (row-local state) exercises the shard's wave apply — merged
    disjoint sub-ops in one jitted update must leave data AND optimizer
    state bit-identical to per-op applies. adam exercises the merge
    GATE: its global step counter advances once per apply, so windowed
    sub-ops must NOT merge (a merged window used to end with t=K/2 and
    visibly diverged parameters)."""
    from multiverso_tpu.updaters import AddOption
    rng = np.random.default_rng(17)
    rows, cols = 29, 4
    opt = AddOption(learning_rate=0.1, rho=0.05)
    tw = AsyncMatrixTable(rows, cols, name=f"w_{updater}", updater=updater,
                          send_window_ms=30.0, ctx=two_ranks[0])
    AsyncMatrixTable(rows, cols, name=f"w_{updater}", updater=updater,
                     ctx=two_ranks[1])
    tr = AsyncMatrixTable(rows, cols, name=f"r_{updater}", updater=updater,
                          ctx=two_ranks[0])
    AsyncMatrixTable(rows, cols, name=f"r_{updater}", updater=updater,
                     ctx=two_ranks[1])
    for step in range(40):
        k = int(rng.integers(1, 7))
        ids = rng.integers(0, rows, k)
        vals = rng.normal(size=(k, cols)).astype(np.float32)
        tw.add_rows_async(ids, vals, opt)
        tr.add_rows_async(ids, vals, opt)
        if step % 11 == 0:
            q = rng.integers(0, rows, 6)
            assert np.array_equal(tw.get_rows(q), tr.get_rows(q))
    tw.flush()
    tr.flush()
    assert np.array_equal(tw.get(), tr.get())


def test_async_kv_matches_dict_model(two_ranks):
    rng = np.random.default_rng(13)
    t = AsyncKVTable(name="fz_kv", ctx=two_ranks[0])
    AsyncKVTable(name="fz_kv", ctx=two_ranks[1])
    model = {}
    for step in range(60):
        if rng.random() < 0.7:
            keys = rng.integers(0, 40, rng.integers(1, 5)).tolist()
            vals = rng.normal(size=len(keys)).tolist()
            t.add(keys, vals)
            for k, v in zip(keys, vals):
                model[k] = model.get(k, 0.0) + v
        else:
            got = t.get()
            assert set(got) == set(model)
            for k, v in model.items():
                assert abs(got[k] - v) < 1e-3, (k, got[k], v)
    got = t.get()
    for k, v in model.items():
        assert abs(got[k] - v) < 1e-3
