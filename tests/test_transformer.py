"""Causal context parallelism + the transformer LM family on the 8-device
mesh (long-context tier; the reference has no sequence axis — SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import multiverso_tpu as mv
from multiverso_tpu import parallel
from multiverso_tpu.models import transformer as tf
from multiverso_tpu.parallel.ring import reference_attention, sequence_shard


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


def _qkv(b=2, h=4, s=32, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32))
    return mk(), mk(), mk()


class TestCausalAttention:
    def test_ring_causal_matches_oracle(self):
        mv.init()
        q, k, v = _qkv()
        expect = reference_attention(q, k, v, causal=True)
        out = parallel.ring_attention(*map(sequence_shard, (q, k, v)),
                                      causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_ulysses_causal_matches_oracle(self):
        mv.init()
        q, k, v = _qkv(h=8)
        expect = reference_attention(q, k, v, causal=True)
        out = parallel.ulysses_attention(*map(sequence_shard, (q, k, v)),
                                         causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_causal_dp_sp_mesh(self):
        """Batch on dp AND sequence on sp in one shard_map."""
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        q, k, v = _qkv(b=4, s=32)
        expect = reference_attention(q, k, v, causal=True)
        from jax.sharding import NamedSharding, PartitionSpec as P
        put = lambda x: jax.device_put(
            x, NamedSharding(mesh, P("dp", None, "sp", None)))
        out = parallel.ring_attention(put(q), put(k), put(v), axis_name="sp",
                                      causal=True, batch_axis="dp", mesh=mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)


class TestTransformerLM:
    def _cfg(self, **kw):
        base = dict(vocab_size=64, dim=32, num_heads=4, num_layers=2,
                    max_seq=64)
        base.update(kw)
        return tf.TransformerConfig(**base)

    def test_forward_ring_matches_local(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        cfg_local = self._cfg(attn="local")
        cfg_ring = self._cfg(attn="ring", seq_axis="sp", batch_axis="dp")
        params = tf.init_params(cfg_local, seed=1)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 64, (4, 32)).astype(np.int32)
        with jax.default_matmul_precision("float32"):
            ref = jax.jit(lambda p, t: tf.forward(p, t, cfg_local))(
                params, jnp.asarray(tokens))
            out = jax.jit(lambda p, t: tf.forward(p, t, cfg_ring))(
                params, tf.shard_batch(tokens, cfg_ring, mesh))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-4)

    def test_train_step_learns(self):
        """Memorize a fixed repeating sequence: loss must drop well below
        the uniform-prediction floor."""
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        cfg = self._cfg(attn="ring", seq_axis="sp", batch_axis="dp")
        params = tf.init_params(cfg, seed=0)
        pattern = np.tile(np.arange(8, dtype=np.int32), 5)[:33]
        tokens = np.tile(pattern[:-1], (4, 1))
        targets = np.tile(pattern[1:], (4, 1))
        step = jax.jit(tf.make_train_step(cfg, learning_rate=0.2))
        tok = tf.shard_batch(tokens, cfg, mesh)
        tgt = tf.shard_batch(targets, cfg, mesh)
        losses = []
        for _ in range(80):
            params, loss = step(params, tok, tgt)
            losses.append(float(loss))
        assert losses[-1] < 0.5, losses[::5]
        assert losses[-1] < losses[0] / 3

    def test_loss_mask(self):
        mv.init()
        cfg = self._cfg(attn="local")
        params = tf.init_params(cfg, seed=0)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
        targets = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
        full = tf.loss_fn(params, tokens, targets, cfg)
        masked = tf.loss_fn(params, tokens, targets, cfg,
                            mask=jnp.ones((2, 16), jnp.float32))
        np.testing.assert_allclose(float(full), float(masked), rtol=1e-6)
        # a real mask: zero out the second sequence entirely -> must equal
        # the loss of the first sequence alone
        half = tf.loss_fn(params, tokens, targets, cfg,
                          mask=jnp.asarray([[1.0] * 16, [0.0] * 16]))
        first = tf.loss_fn(params, tokens[:1], targets[:1], cfg)
        np.testing.assert_allclose(float(half), float(first), rtol=1e-5)


class TestMoETransformer:
    def test_single_expert_equals_dense_mlp(self):
        # E=1, top_k=1: the gate is softmax over one expert == 1.0, so the
        # MoE MLP is exactly the dense MLP with that expert's weights; the
        # only loss difference is the constant aux term (1.0 per layer)
        devices = np.asarray(jax.devices()).reshape(8, 1)
        mesh = Mesh(devices, ("dp", "ep"))
        mv.init(mesh=mesh)
        L = 2
        mcfg = tf.TransformerConfig(
            vocab_size=64, dim=16, num_heads=2, num_layers=L, max_seq=8,
            attn="local", moe_experts=1, moe_axis="ep",
            moe_capacity_factor=100.0)
        mparams = tf.init_params(mcfg, seed=0)
        dcfg = mcfg._replace(moe_experts=0)
        dparams = tf.init_params(dcfg, seed=0)
        dparams["layers"]["w1"] = mparams["layers"]["moe_w1"][:, 0]
        dparams["layers"]["w2"] = mparams["layers"]["moe_w2"][:, 0]
        # identical attention weights come from the same seed ordering only
        # for the shared keys; copy to be safe
        for k in ("wqkv", "wo", "ln1", "ln2"):
            dparams["layers"][k] = mparams["layers"][k]
        for k in ("embed", "pos", "ln_f"):
            dparams[k] = mparams[k]

        rng = np.random.default_rng(1)
        tok = jnp.asarray(rng.integers(0, 64, (4, 8)), jnp.int32)
        tgt = jnp.asarray(rng.integers(0, 64, (4, 8)), jnp.int32)
        with jax.default_matmul_precision("float32"):
            moe_loss = tf.loss_fn(tf.shard_params_moe(mparams, mcfg),
                                  tok, tgt, mcfg)
            dense_loss = tf.loss_fn(dparams, tok, tgt, dcfg)
        np.testing.assert_allclose(
            float(moe_loss) - mcfg.moe_aux_coef * L, float(dense_loss),
            rtol=1e-4, atol=1e-5)

    def test_moe_lm_trains_over_dp_ep(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "ep"))
        mv.init(mesh=mesh)
        cfg = tf.TransformerConfig(
            vocab_size=64, dim=32, num_heads=4, num_layers=2, max_seq=16,
            attn="local", batch_axis="dp", moe_experts=4, moe_axis="ep",
            moe_top_k=2, moe_capacity_factor=4.0)
        params = tf.shard_params_moe(tf.init_params(cfg, seed=0), cfg)
        step = jax.jit(tf.make_train_step(cfg, 0.5))
        rng = np.random.default_rng(2)
        toks = rng.integers(0, 64, (8, 17)).astype(np.int32)
        tok = tf.shard_batch(toks[:, :-1], cfg, mesh)
        tgt = tf.shard_batch(toks[:, 1:], cfg, mesh)
        losses = []
        for _ in range(25):
            params, loss = step(params, tok, tgt)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.6, losses[::6]
        # expert weights really are distributed over ep
        shards = params["layers"]["moe_w1"].addressable_shards
        assert {s.data.shape[1] for s in shards} == {1}

    def test_moe_rejects_seq_or_tp_axis(self):
        mv.init(mesh=Mesh(np.asarray(jax.devices()), ("ep",)))
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=1, max_seq=8, attn="ring",
                                   seq_axis="ep", moe_experts=8)
        with pytest.raises(ValueError, match="moe"):
            tf.forward(tf.init_params(cfg), jnp.zeros((1, 8), jnp.int32),
                       cfg)

    def test_shard_params_moe_rejects_dense_cfg(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=1, max_seq=8)
        with pytest.raises(ValueError, match="moe_experts"):
            tf.shard_params_moe(tf.init_params(cfg), cfg)


class TestZigzagRing:
    def _check(self, b=2, h=4, s=64, d=16, seed=0, axes=("sp",),
               head_axis=None, mesh_shape=None):
        devices = np.asarray(jax.devices())
        if mesh_shape:
            devices = devices.reshape(mesh_shape)
        mesh = Mesh(devices, axes)
        mv.init(mesh=mesh)
        n = mesh.shape[axes[-1] if head_axis is None else "sp"]
        rng = np.random.default_rng(seed)
        q, k, v = (jnp.asarray(rng.normal(size=(b, h, s, d)),
                               jnp.float32) for _ in range(3))
        expect = reference_attention(q, k, v, causal=True)
        perm = parallel.zigzag_shard_ids(s, n)
        inv = jnp.argsort(perm)
        zq, zk, zv = (t[:, :, perm] for t in (q, k, v))
        out = parallel.zigzag_ring_attention(
            zq, zk, zv, axis_name="sp", head_axis=head_axis,
            precision="float32")
        np.testing.assert_allclose(np.asarray(out[:, :, inv]),
                                   np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_matches_causal_oracle(self):
        self._check()

    def test_with_head_sharding(self):
        self._check(mesh_shape=(2, 4), axes=("tp", "sp"), head_axis="tp")

    def test_under_grad(self):
        mesh = Mesh(np.asarray(jax.devices()), ("sp",))
        mv.init(mesh=mesh)
        rng = np.random.default_rng(3)
        s = 32
        q, k, v = (jnp.asarray(rng.normal(size=(1, 2, s, 8)),
                               jnp.float32) for _ in range(3))
        perm = parallel.zigzag_shard_ids(s, 8)
        inv = np.argsort(np.asarray(perm))

        def loss_zig(q, k, v):
            o = parallel.zigzag_ring_attention(q[:, :, perm], k[:, :, perm],
                                               v[:, :, perm], axis_name="sp")
            return jnp.mean(o[:, :, inv] ** 2)

        def loss_ref(q, k, v):
            return jnp.mean(reference_attention(q, k, v, causal=True) ** 2)

        with jax.default_matmul_precision("float32"):
            gz = jax.grad(loss_zig, argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gz, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-4)

    def test_rejects_indivisible_seq(self):
        mv.init(mesh=Mesh(np.asarray(jax.devices()), ("sp",)))
        q = jnp.zeros((1, 2, 24, 8), jnp.float32)  # 24 % 16 != 0
        with pytest.raises(ValueError, match="not divisible"):
            parallel.zigzag_ring_attention(q, q, q, axis_name="sp")


class TestZigzagTransformer:
    def test_zigzag_lm_loss_matches_local(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        base = tf.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                    num_layers=2, max_seq=32, attn="local")
        params = tf.init_params(base, seed=0)
        rng = np.random.default_rng(9)
        toks = rng.integers(0, 64, (4, 33)).astype(np.int32)
        with jax.default_matmul_precision("float32"):
            expect = tf.loss_fn(params, jnp.asarray(toks[:, :-1]),
                                jnp.asarray(toks[:, 1:]), base)
        cfg = base._replace(attn="zigzag", batch_axis="dp", seq_axis="sp")
        tok = tf.shard_batch(toks[:, :-1], cfg, mesh)
        tgt = tf.shard_batch(toks[:, 1:], cfg, mesh)
        with jax.default_matmul_precision("float32"):
            got = jax.jit(lambda p, a, b: tf.loss_fn(p, a, b, cfg))(
                params, tok, tgt)
        np.testing.assert_allclose(float(got), float(expect),
                                   rtol=1e-4, atol=1e-5)

    def test_zigzag_lm_trains(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        cfg = tf.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                   num_layers=2, max_seq=32, attn="zigzag",
                                   batch_axis="dp", seq_axis="sp")
        params = tf.init_params(cfg, seed=1)
        step = jax.jit(tf.make_train_step(cfg, 0.5))
        rng = np.random.default_rng(10)
        toks = rng.integers(0, 64, (4, 33)).astype(np.int32)
        tok = tf.shard_batch(toks[:, :-1], cfg, mesh)
        tgt = tf.shard_batch(toks[:, 1:], cfg, mesh)
        losses = []
        for _ in range(25):
            params, loss = step(params, tok, tgt)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::6]

    def test_zigzag_masked_loss_matches_local(self):
        # the mask is supplied in ORIGINAL order; loss_fn must permute it
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        base = tf.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                    num_layers=1, max_seq=32, attn="local")
        params = tf.init_params(base, seed=2)
        rng = np.random.default_rng(11)
        toks = rng.integers(0, 64, (4, 33)).astype(np.int32)
        mask = (rng.random((4, 32)) > 0.3).astype(np.float32)
        with jax.default_matmul_precision("float32"):
            expect = tf.loss_fn(params, jnp.asarray(toks[:, :-1]),
                                jnp.asarray(toks[:, 1:]), base,
                                mask=jnp.asarray(mask))
        cfg = base._replace(attn="zigzag", batch_axis="dp", seq_axis="sp")
        tok = tf.shard_batch(toks[:, :-1], cfg, mesh)
        tgt = tf.shard_batch(toks[:, 1:], cfg, mesh)
        with jax.default_matmul_precision("float32"):
            got = tf.loss_fn(params, tok, tgt, cfg, mask=jnp.asarray(mask))
        np.testing.assert_allclose(float(got), float(expect),
                                   rtol=1e-4, atol=1e-5)

    def test_shard_batch_rejects_mismatched_mesh(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mv.init(mesh=Mesh(devices, ("dp", "sp")))
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=1, max_seq=16, attn="zigzag",
                                   batch_axis="dp", seq_axis="sp")
        other = Mesh(devices.reshape(4, 2), ("dp", "sp"))
        with pytest.raises(ValueError, match="Zoo mesh"):
            tf.shard_batch(np.zeros((2, 16), np.int32), cfg, other)


class TestGenerate:
    def test_greedy_matches_full_forward(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=2, max_seq=24, attn="local")
        params = tf.init_params(cfg, seed=0)
        rng = np.random.default_rng(12)
        prompt = jnp.asarray(rng.integers(0, 32, (2, 4)), jnp.int32)
        with jax.default_matmul_precision("float32"):
            out = tf.generate(params, prompt, cfg, max_new_tokens=6)
            # oracle: re-run the full forward on each growing prefix
            seq = np.asarray(prompt)
            for _ in range(6):
                logits = tf.forward(params, jnp.asarray(seq), cfg)
                nxt = np.argmax(np.asarray(logits[:, -1]), -1)
                seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), seq)

    def test_sampling_reproducible_and_in_range(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=1, max_seq=16, attn="local")
        params = tf.init_params(cfg, seed=1)
        prompt = jnp.zeros((1, 2), jnp.int32)
        k = jax.random.key(7)
        a = tf.generate(params, prompt, cfg, 8, temperature=1.0, key=k)
        b = tf.generate(params, prompt, cfg, 8, temperature=1.0, key=k)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.asarray(a).max() < 32 and np.asarray(a).min() >= 0
        assert a.shape == (1, 10)

    def test_bfloat16_generate_matches_forward(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=2, max_seq=16, attn="local",
                                   dtype=jnp.bfloat16)
        params = tf.init_params(cfg, seed=3)
        prompt = jnp.asarray([[5, 9, 2]], jnp.int32)
        out = tf.generate(params, prompt, cfg, max_new_tokens=4)
        assert out.shape == (1, 7)
        seq = np.asarray(prompt)
        for _ in range(4):
            logits = tf.forward(params, jnp.asarray(seq), cfg)
            nxt = np.argmax(np.asarray(logits[:, -1], np.float32), -1)
            seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), seq)

    def test_single_token_and_empty_prompt(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=1, max_seq=8, attn="local")
        params = tf.init_params(cfg)
        out = tf.generate(params, jnp.zeros((1, 2), jnp.int32), cfg, 1)
        assert out.shape == (1, 3)
        with pytest.raises(ValueError, match="at least one token"):
            tf.generate(params, jnp.zeros((1, 0), jnp.int32), cfg, 2)

    def test_rejects_overlong_and_missing_key(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=1, max_seq=8, attn="local")
        params = tf.init_params(cfg)
        prompt = jnp.zeros((1, 6), jnp.int32)
        with pytest.raises(ValueError, match="max_seq"):
            tf.generate(params, prompt, cfg, 4)
        with pytest.raises(ValueError, match="PRNG"):
            tf.generate(params, prompt, cfg, 1, temperature=0.5)


def test_transformer_ps_example_trains():
    import pathlib
    import runpy
    path = (pathlib.Path(__file__).resolve().parents[1]
            / "examples" / "transformer_ps.py")
    mod = runpy.run_path(str(path))
    final = mod["main"](steps=30, sync_every=5)
    # untrained loss is ln(64) ~= 4.16; demand real learning
    assert np.isfinite(final) and final < 3.0


class TestRematAndOptax:
    def test_remat_loss_and_grads_match(self):
        mv.init()
        base = tf.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                    num_layers=3, max_seq=16, attn="local")
        params = tf.init_params(base, seed=4)
        rng = np.random.default_rng(13)
        tok = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
        tgt = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
        rcfg = base._replace(remat=True)
        with jax.default_matmul_precision("float32"):
            l0, g0 = jax.value_and_grad(tf.loss_fn)(params, tok, tgt, base)
            l1, g1 = jax.value_and_grad(tf.loss_fn)(params, tok, tgt, rcfg)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_optax_adamw_trains_sharded(self):
        import optax
        devices = np.asarray(jax.devices())
        mesh = Mesh(devices, ("fsdp",))
        mv.init(mesh=mesh)
        cfg = tf.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                   num_layers=2, max_seq=16, attn="local",
                                   batch_axis="fsdp", remat=True)
        params = tf.shard_params_fsdp(tf.init_params(cfg, seed=5), cfg)
        optimizer = optax.adamw(3e-3)
        opt_state = optimizer.init(params)
        step = jax.jit(tf.make_optax_train_step(cfg, optimizer))
        rng = np.random.default_rng(14)
        toks = rng.integers(0, 64, (8, 17)).astype(np.int32)
        tok = tf.shard_batch(toks[:, :-1], cfg, mesh)
        tgt = tf.shard_batch(toks[:, 1:], cfg, mesh)
        losses = []
        for _ in range(40):
            params, opt_state, loss = step(params, opt_state, tok, tgt)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses[::10]
        mu = opt_state[0].mu["embed"]
        assert {s.data.shape[0] for s in mu.addressable_shards} == {64 // 8}


class TestGenerateMoEAndTopP:
    def test_moe_single_expert_decode_equals_dense(self):
        devices = np.asarray(jax.devices()).reshape(8, 1)
        mv.init(mesh=Mesh(devices, ("dp", "ep")))
        mcfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                    num_layers=2, max_seq=16, attn="local",
                                    moe_experts=1, moe_axis="ep")
        mparams = tf.init_params(mcfg, seed=0)
        dcfg = mcfg._replace(moe_experts=0)
        dparams = tf.init_params(dcfg, seed=0)
        dparams["layers"]["w1"] = mparams["layers"]["moe_w1"][:, 0]
        dparams["layers"]["w2"] = mparams["layers"]["moe_w2"][:, 0]
        for k in ("wqkv", "wo", "ln1", "ln2"):
            dparams["layers"][k] = mparams["layers"][k]
        for k in ("embed", "pos", "ln_f"):
            dparams[k] = mparams[k]
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        with jax.default_matmul_precision("float32"):
            dense = tf.generate(dparams, prompt, dcfg, 5)
            moe = tf.generate(mparams, prompt, mcfg, 5)
        np.testing.assert_array_equal(np.asarray(dense), np.asarray(moe))

    def test_moe_top2_decode_matches_forward_argmax(self):
        # ep axis of size 1 so the forward oracle accepts every prefix
        # length (decode itself never touches the mesh)
        devices = np.asarray(jax.devices()).reshape(8, 1)
        mv.init(mesh=Mesh(devices, ("dp", "ep")))
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=1, max_seq=16, attn="local",
                                   moe_experts=8, moe_axis="ep",
                                   moe_top_k=2, moe_capacity_factor=100.0)
        params = tf.init_params(cfg, seed=1)
        sharded = tf.shard_params_moe(params, cfg)
        prompt = jnp.asarray([[4, 7]], jnp.int32)
        with jax.default_matmul_precision("float32"):
            out = tf.generate(params, prompt, cfg, 4)
            # oracle: full forward (generous capacity -> no drops) on each
            # growing prefix
            seq = np.asarray(prompt)
            for _ in range(4):
                logits = tf.forward(sharded, jnp.asarray(seq), cfg)
                nxt = np.argmax(np.asarray(logits[:, -1]), -1)
                seq = np.concatenate([seq, nxt[:, None]], 1)
        np.testing.assert_array_equal(np.asarray(out), seq)

    def test_top_p_sampling(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=1, max_seq=32, attn="local")
        params = tf.init_params(cfg, seed=2)
        prompt = jnp.zeros((2, 2), jnp.int32)
        k = jax.random.key(3)
        a = tf.generate(params, prompt, cfg, 8, temperature=1.0, key=k,
                        top_p=0.9)
        b = tf.generate(params, prompt, cfg, 8, temperature=1.0, key=k,
                        top_p=0.9)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # top_p -> 0 collapses to greedy (only the top token survives)
        g = tf.generate(params, prompt, cfg, 8)
        s = tf.generate(params, prompt, cfg, 8, temperature=1.0, key=k,
                        top_p=1e-6)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(s))
        with pytest.raises(ValueError, match="top_p"):
            tf.generate(params, prompt, cfg, 2, temperature=1.0, key=k,
                        top_p=0.0)

    def test_moe_decode_rejects_bad_top_k(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=1, max_seq=8, attn="local",
                                   moe_experts=8, moe_top_k=0)
        params = tf.init_params(cfg, seed=0)
        with pytest.raises(ValueError, match="top_k"):
            tf.generate(params, jnp.zeros((1, 2), jnp.int32), cfg, 2)

    def test_eos_latches(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=8, dim=32, num_heads=4,
                                   num_layers=2, max_seq=32, attn="local")
        params = tf.init_params(cfg, seed=0)
        # train to emit the cycle 0..7; token 3 will appear mid-cycle
        seq = np.tile(np.arange(8), 5)[:33]
        tok = jnp.asarray(np.stack([seq[:-1]] * 4), jnp.int32)
        tgt = jnp.asarray(np.stack([seq[1:]] * 4), jnp.int32)
        step = jax.jit(tf.make_train_step(cfg, 0.5))
        for _ in range(150):
            params, _ = step(params, tok, tgt)
        prompt = jnp.asarray([[0, 1]], jnp.int32)
        out = np.asarray(tf.generate(params, prompt, cfg, 10, eos_id=3))[0]
        assert (out == 3).any(), f"model never emitted eos: {out.tolist()}"
        # first emission of 3 latches: everything after stays 3
        first = int(np.argmax(out == 3))
        assert out[first] == 3
        assert (out[first:] == 3).all(), out.tolist()
        # without eos the cycle continues past 3
        out2 = np.asarray(tf.generate(params, prompt, cfg, 10))[0]
        assert not (out2[first:] == 3).all()

    def test_eos_out_of_vocab_rejected(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=8, dim=16, num_heads=2,
                                   num_layers=1, max_seq=8, attn="local")
        params = tf.init_params(cfg)
        with pytest.raises(ValueError, match="eos_id"):
            tf.generate(params, jnp.zeros((1, 2), jnp.int32), cfg, 2,
                        eos_id=8)


class TestBeamSearch:
    def _seq_logprob(self, params, cfg, seq, p):
        """Total log-prob of seq[p:] under the model, via full forward."""
        logits = tf.forward(params, jnp.asarray(seq[:, :-1]), cfg)
        logp = jax.nn.log_softmax(np.asarray(logits, np.float32), -1)
        total = 0.0
        for t in range(p - 1, seq.shape[1] - 1):
            total += float(logp[0, t, seq[0, t + 1]])
        return total

    def test_single_beam_equals_greedy(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                   num_layers=2, max_seq=24, attn="local")
        params = tf.init_params(cfg, seed=0)
        prompt = jnp.asarray([[3, 1], [9, 4]], jnp.int32)
        with jax.default_matmul_precision("float32"):
            greedy = tf.generate(params, prompt, cfg, 6)
            beam1 = tf.generate_beam(params, prompt, cfg, 6, num_beams=1)
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(beam1))

    def test_wide_beam_finds_global_optimum(self):
        # V=4, T=3, W=16 >= V^(T-1): the search is exhaustive, so the
        # result must be the brute-force argmax continuation
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=4, dim=16, num_heads=2,
                                   num_layers=2, max_seq=8, attn="local")
        params = tf.init_params(cfg, seed=5)
        prompt = np.asarray([[1, 2]], np.int32)
        with jax.default_matmul_precision("float32"):
            beam, score = tf.generate_beam(params, jnp.asarray(prompt),
                                           cfg, 3, num_beams=16,
                                           return_score=True)
            best_lp, best_seq = -1e30, None
            for a in range(4):
                for bb in range(4):
                    for c in range(4):
                        seq = np.concatenate(
                            [prompt, [[a, bb, c]]], axis=1)
                        lp = self._seq_logprob(params, cfg, seq, 2)
                        if lp > best_lp:
                            best_lp, best_seq = lp, seq
        np.testing.assert_array_equal(np.asarray(beam), best_seq)
        # the internal accumulated score equals the true sequence log-prob
        np.testing.assert_allclose(float(score[0]), best_lp, atol=1e-4)

    def test_beam_validation(self):
        mv.init()
        cfg = tf.TransformerConfig(vocab_size=16, dim=16, num_heads=2,
                                   num_layers=1, max_seq=8, attn="local")
        params = tf.init_params(cfg)
        with pytest.raises(ValueError, match="num_beams"):
            tf.generate_beam(params, jnp.zeros((1, 2), jnp.int32), cfg, 2,
                             num_beams=0)
        with pytest.raises(ValueError, match="max_seq"):
            tf.generate_beam(params, jnp.zeros((1, 6), jnp.int32), cfg, 4)


class TestBatchedPrefill:
    @pytest.mark.parametrize("variant", ["dense", "bf16", "moe", "int8"])
    def test_batched_prefill_matches_sequential(self, variant):
        mv.init()
        kw = dict(vocab_size=32, dim=16, num_heads=2, num_layers=2,
                  max_seq=24, attn="local")
        if variant == "bf16":
            kw["dtype"] = jnp.bfloat16
        if variant == "moe":
            kw.update(moe_experts=4, moe_top_k=2)
        cfg = tf.TransformerConfig(**kw)
        params = tf.init_params(cfg, seed=6)
        if variant == "int8":
            from multiverso_tpu.ops import quantize_lm_params
            params = quantize_lm_params(params)
        prompt = jnp.asarray([[4, 9, 1, 7, 2], [8, 8, 3, 0, 5]], jnp.int32)
        with jax.default_matmul_precision("float32"):
            cb, lb = tf._prefill(params, prompt, cfg, 10, batched=True)
            cs, ls = tf._prefill(params, prompt, cfg, 10, batched=False)
        np.testing.assert_allclose(np.asarray(lb), np.asarray(ls),
                                   rtol=2e-4, atol=2e-4)
        for k in ("k", "v"):
            np.testing.assert_allclose(
                np.asarray(cb[k], np.float32), np.asarray(cs[k], np.float32),
                rtol=2e-4, atol=2e-4)
