"""Causal context parallelism + the transformer LM family on the 8-device
mesh (long-context tier; the reference has no sequence axis — SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import multiverso_tpu as mv
from multiverso_tpu import parallel
from multiverso_tpu.models import transformer as tf
from multiverso_tpu.parallel.ring import reference_attention, sequence_shard


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


def _qkv(b=2, h=4, s=32, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32))
    return mk(), mk(), mk()


class TestCausalAttention:
    def test_ring_causal_matches_oracle(self):
        mv.init()
        q, k, v = _qkv()
        expect = reference_attention(q, k, v, causal=True)
        out = parallel.ring_attention(*map(sequence_shard, (q, k, v)),
                                      causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_ulysses_causal_matches_oracle(self):
        mv.init()
        q, k, v = _qkv(h=8)
        expect = reference_attention(q, k, v, causal=True)
        out = parallel.ulysses_attention(*map(sequence_shard, (q, k, v)),
                                         causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_causal_dp_sp_mesh(self):
        """Batch on dp AND sequence on sp in one shard_map."""
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        q, k, v = _qkv(b=4, s=32)
        expect = reference_attention(q, k, v, causal=True)
        from jax.sharding import NamedSharding, PartitionSpec as P
        put = lambda x: jax.device_put(
            x, NamedSharding(mesh, P("dp", None, "sp", None)))
        out = parallel.ring_attention(put(q), put(k), put(v), axis_name="sp",
                                      causal=True, batch_axis="dp", mesh=mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)


class TestTransformerLM:
    def _cfg(self, **kw):
        base = dict(vocab_size=64, dim=32, num_heads=4, num_layers=2,
                    max_seq=64)
        base.update(kw)
        return tf.TransformerConfig(**base)

    def test_forward_ring_matches_local(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        cfg_local = self._cfg(attn="local")
        cfg_ring = self._cfg(attn="ring", seq_axis="sp", batch_axis="dp")
        params = tf.init_params(cfg_local, seed=1)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 64, (4, 32)).astype(np.int32)
        with jax.default_matmul_precision("float32"):
            ref = jax.jit(lambda p, t: tf.forward(p, t, cfg_local))(
                params, jnp.asarray(tokens))
            out = jax.jit(lambda p, t: tf.forward(p, t, cfg_ring))(
                params, tf.shard_batch(tokens, cfg_ring, mesh))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-4)

    def test_train_step_learns(self):
        """Memorize a fixed repeating sequence: loss must drop well below
        the uniform-prediction floor."""
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "sp"))
        mv.init(mesh=mesh)
        cfg = self._cfg(attn="ring", seq_axis="sp", batch_axis="dp")
        params = tf.init_params(cfg, seed=0)
        pattern = np.tile(np.arange(8, dtype=np.int32), 5)[:33]
        tokens = np.tile(pattern[:-1], (4, 1))
        targets = np.tile(pattern[1:], (4, 1))
        step = jax.jit(tf.make_train_step(cfg, learning_rate=0.2))
        tok = tf.shard_batch(tokens, cfg, mesh)
        tgt = tf.shard_batch(targets, cfg, mesh)
        losses = []
        for _ in range(80):
            params, loss = step(params, tok, tgt)
            losses.append(float(loss))
        assert losses[-1] < 0.5, losses[::5]
        assert losses[-1] < losses[0] / 3

    def test_loss_mask(self):
        mv.init()
        cfg = self._cfg(attn="local")
        params = tf.init_params(cfg, seed=0)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
        targets = jnp.asarray(rng.integers(0, 64, (2, 16)), jnp.int32)
        full = tf.loss_fn(params, tokens, targets, cfg)
        masked = tf.loss_fn(params, tokens, targets, cfg,
                            mask=jnp.ones((2, 16), jnp.float32))
        np.testing.assert_allclose(float(full), float(masked), rtol=1e-6)
        # a real mask: zero out the second sequence entirely -> must equal
        # the loss of the first sequence alone
        half = tf.loss_fn(params, tokens, targets, cfg,
                          mask=jnp.asarray([[1.0] * 16, [0.0] * 16]))
        first = tf.loss_fn(params, tokens[:1], targets[:1], cfg)
        np.testing.assert_allclose(float(half), float(first), rtol=1e-5)
