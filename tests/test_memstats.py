"""Memory observability plane (ISSUE 10): the byte ledger, deliberate-
leak verdicts (epoch-hoard, retention-leak), the flag-off null path,
the MSG_STATS "memory" block through aggregator/mvtop/exporter/
dump_metrics, OOM forensics through the flight-recorder dump path +
postmortem's memory timeline, the stats-surface lint, and the
run_bench memory regression flags. All tier-1 (CPU, seconds)."""

import gc
import json
import os
import sys
import time
import tracemalloc

import numpy as np
import pytest

from multiverso_tpu.ps.shard import RowShard
from multiverso_tpu.ps.tables import AsyncMatrixTable
from multiverso_tpu.telemetry import flightrec, memstats, watchdog
from multiverso_tpu.updaters import AddOption, get_updater
from multiverso_tpu.utils import config

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _ring_kinds(last=32):
    return [s[2] for s in flightrec.RECORDER.snapshot(last=last)]


# ---------------------------------------------------------------------- #
# the ledger itself
# ---------------------------------------------------------------------- #
class TestLedger:
    def test_register_snapshot_totals_and_dead_prune(self):
        class C:
            def __init__(self, n):
                self.n = n

            def memory_stats(self):
                return {"x_bytes": self.n, "pins": 1, "note": "raw"}

        a, b = C(100), C(28)
        na = memstats.register("comp", a)
        nb = memstats.register("comp", b)   # collision -> suffixed
        assert na == "comp" and nb != "comp"
        snap = memstats.LEDGER.snapshot()
        assert snap["components"][na]["x_bytes"] == 100
        assert snap["totals"]["x_bytes"] == 128   # *_bytes summed
        assert snap["totals"]["pins"] == 2        # count-total key
        assert "note" not in snap["totals"]       # strings never sum
        del b
        gc.collect()
        snap = memstats.LEDGER.snapshot()
        assert nb not in snap["components"]       # dead ref pruned
        assert snap["totals"]["x_bytes"] == 100

    def test_bad_gauge_isolated(self):
        class Bad:
            def memory_stats(self):
                raise RuntimeError("boom")

        class Good:
            def memory_stats(self):
                return {"y_bytes": 7}

        bad, good = Bad(), Good()
        memstats.register("bad", bad)
        memstats.register("good", good)
        snap = memstats.LEDGER.snapshot()
        assert "error" in snap["components"]["bad"]
        assert snap["totals"]["y_bytes"] == 7

    def test_reset_keeps_importtime_registrations(self):
        """reset() (the per-test isolation hook) must NOT unregister
        components: checkpoint.py registers its gauges once at module
        import, and clearing them would leave that plane dark for
        every test after the first."""
        import multiverso_tpu.checkpoint   # noqa: F401 — registers
        assert "checkpoint" in memstats.LEDGER.snapshot()["components"]
        memstats.sample_once()
        memstats.reset()
        assert memstats.LEDGER.samples() == []          # history gone
        snap = memstats.LEDGER.snapshot()
        assert "checkpoint" in snap["components"]       # gauges stay

    def test_sample_and_stats_snapshot_json_safe(self):
        s = memstats.sample_once()
        assert s["rss_mb"] is None or s["rss_mb"] > 0
        blk = memstats.stats_snapshot()
        json.dumps(blk)   # must be wire-safe (MSG_STATS meta)
        assert blk["samples"] >= 1
        assert "totals" in blk and "components" in blk

    def test_read_rss_and_device_census(self):
        rss, hwm = memstats.read_rss()
        if rss is not None:   # /proc present (linux CI)
            assert rss > 0
            # VmHWM can be absent on stripped kernels; when present
            # (or ru_maxrss fell in) it bounds the live reading
            assert hwm is None or hwm >= rss
        import jax.numpy as jnp
        keep = jnp.ones((64, 64), jnp.float32)
        census = memstats.device_census()
        assert census is not None and census["bytes"] >= keep.nbytes
        assert any(g["shape"] == "(64, 64)" for g in census["top"])


# ---------------------------------------------------------------------- #
# shard gauges: pins, retired epochs, queue bytes
# ---------------------------------------------------------------------- #
class TestShardGauges:
    def _shard(self, name="mem_sh"):
        return RowShard(0, 64, 8, np.float32, get_updater("sgd"), name)

    def test_pin_registry_and_retired_bytes(self):
        sh = self._shard()
        g0 = sh.memory_stats()
        assert g0["table_bytes"] > 0 and g0["pins"] == 0
        pin = sh._pin_data()
        g1 = sh.memory_stats()
        assert g1["pins"] == 1 and g1["pinned_epochs"] == 1
        assert g1["retired_epochs"] == 0
        # COW applies while pinned: the pinned buffer retires, and the
        # gauge counts it (deduped by buffer identity — many applies,
        # ONE retired epoch)
        for _ in range(3):
            sh._apply_rows(np.array([1, 2, 3]),
                           np.ones((3, 8), np.float32), AddOption())
        g2 = sh.memory_stats()
        assert g2["retired_epochs"] == 1
        assert g2["retired_bytes"] == g1["table_bytes"]
        assert g2["oldest_pin_age_s"] >= 0.0
        sh._release_data(pin)
        g3 = sh.memory_stats()
        assert g3["pins"] == 0 and g3["retired_bytes"] == 0

    def test_two_pins_same_epoch_dedupe(self):
        sh = self._shard("mem_sh2")
        p1, p2 = sh._pin_data(), sh._pin_data()
        sh._apply_rows(np.array([1]), np.ones((1, 8), np.float32),
                       AddOption())
        g = sh.memory_stats()
        assert g["pins"] == 2 and g["retired_epochs"] == 1
        # same retired buffer under both pins: bytes counted ONCE
        assert g["retired_bytes"] == g["table_bytes"]
        sh._release_data(p1)
        sh._release_data(p2)

    def test_contended_lock_serves_stale_cache_nonblocking(self):
        """The watchdog sweep drives gauge pulls: a pull racing a held
        shard lock (a long/wedged apply) must return the last reading
        marked stale IMMEDIATELY, never block."""
        import threading

        sh = self._shard("mem_stale")
        fresh = sh.memory_stats()
        assert "stale" not in fresh
        holding = threading.Event()
        release = threading.Event()

        def hold():
            with sh._lock:
                holding.set()
                release.wait(10.0)

        th = threading.Thread(target=hold, daemon=True)
        th.start()
        holding.wait(5.0)
        t0 = time.monotonic()
        g = sh.memory_stats()
        assert time.monotonic() - t0 < 1.0   # did not block
        assert g.get("stale") is True
        assert g["table_bytes"] == fresh["table_bytes"]   # cached core
        assert "queue_depth" in g   # queue gauges still live
        release.set()
        th.join(5.0)
        assert "stale" not in sh.memory_stats()

    def test_ledger_sees_shard(self):
        sh = self._shard("mem_sh3")
        snap = memstats.LEDGER.snapshot()
        assert any(k.startswith("shard[mem_sh3:")
                   for k in snap["components"])
        assert snap["totals"]["table_bytes"] >= sh.memory_stats()[
            "table_bytes"]


# ---------------------------------------------------------------------- #
# deliberate-leak suite: the verdicts
# ---------------------------------------------------------------------- #
class TestEpochHoardVerdict:
    def test_hoard_detected_via_watchdog_and_ring(self):
        """Hold a get pin while applies COW: the watchdog sweep must
        call epoch-hoard, with the gauge counting the retired buffers
        and one mem.epoch_hoard event on the ring."""
        sh = RowShard(0, 64, 8, np.float32, get_updater("sgd"), "hoard")
        config.set_flag("memstats_pin_age_s", 0.01)
        pin = sh._pin_data()
        for _ in range(4):
            sh._apply_rows(np.array([0, 1]),
                           np.ones((2, 8), np.float32), AddOption())
        time.sleep(0.03)
        watchdog.check_once()   # the PR-4 sweep drives the verdicts
        verdicts = memstats.LEDGER.verdicts()
        hoard = [v for v in verdicts if v["kind"] == "epoch-hoard"]
        assert hoard and hoard[-1]["component"].startswith(
            "shard[hoard:")
        assert hoard[-1]["retired_bytes"] == sh.memory_stats()[
            "table_bytes"]
        assert hoard[-1]["retired_epochs"] == 1
        assert flightrec.EV_MEM_HOARD in _ring_kinds()
        # one event per episode: a second sweep stays silent
        n = len(memstats.LEDGER.verdicts())
        watchdog.check_once()
        assert len(memstats.LEDGER.verdicts()) == n
        # release clears the episode; a fresh hoard re-fires
        sh._release_data(pin)
        watchdog.check_once()
        pin2 = sh._pin_data()
        sh._apply_rows(np.array([0]), np.ones((1, 8), np.float32),
                       AddOption())
        time.sleep(0.03)
        watchdog.check_once()
        assert len(memstats.LEDGER.verdicts()) == n + 1
        sh._release_data(pin2)


class TestRetentionLeakVerdict:
    def test_growing_retained_tail_with_live_owner(self, two_ranks):
        """Wedge a replay owner's retention: with ps_replay on and NO
        failover checkpointer advancing the durable floor, every acked
        window frame stays retained — monotonic growth across
        RETENTION_K samples with a live owner must call
        retention-leak."""
        config.set_flag("ps_replay", True)
        t0 = AsyncMatrixTable(64, 8, name="ret", ctx=two_ranks[0],
                              send_window_ms=1.0)
        AsyncMatrixTable(64, 8, name="ret", ctx=two_ranks[1])
        series = []
        for i in range(memstats.RETENTION_K):
            # remote-owned rows: rank 1 owns [32, 64)
            t0.add_rows_async([40 + i], np.ones((1, 8), np.float32))
            t0.flush()
            s = memstats.sample_once()
            w = [g for n, g in memstats.LEDGER.snapshot()[
                "components"].items() if n == "window[ret]"][0]
            series.append(w["retained_bytes"])
        assert series[0] > 0
        assert all(a < b for a, b in zip(series, series[1:])), series
        leaks = [v for v in memstats.LEDGER.verdicts()
                 if v["kind"] == "retention-leak"]
        # the verdict judges PER OWNER (rank 1 owns the hoarded tail)
        assert leaks and leaks[-1]["component"] == "window[ret]@1"
        assert flightrec.EV_MEM_LEAK in _ring_kinds(last=64)
        # the sample history carried component AND per-owner series
        assert s["retained"]["window[ret]"] == series[-1]
        assert s["retained"]["window[ret]@1"] == series[-1]

    def test_armed_frames_suppress_the_verdict(self):
        """A dead owner's re-armed tail is failover WORKING: growth
        with armed_frames > 0 must stay verdict-free."""

        class FakeWindow:
            def __init__(self):
                self.rb = 1

            def memory_stats(self):
                self.rb *= 2
                return {"retained_bytes": self.rb, "retained_frames": 1,
                        "armed_frames": 3, "pending_bytes": 0}

        w = FakeWindow()
        memstats.register("window[dead]", w)
        for _ in range(memstats.RETENTION_K + 1):
            memstats.sample_once()
        assert not [v for v in memstats.LEDGER.verdicts()
                    if v["kind"] == "retention-leak"]

    def test_dead_owner_does_not_mask_live_owner(self):
        """Per-owner granularity: owner 1's re-armed tail (dead, being
        failed over) must not suppress the verdict for owner 0, whose
        acked frames are growing with nothing pruning them."""

        class TwoOwnerWindow:
            def __init__(self):
                self.rb = 64

            def memory_stats(self):
                self.rb *= 2
                return {
                    "pending_bytes": 0, "retained_frames": 2,
                    "retained_bytes": 2 * self.rb,
                    "armed_frames": 3,   # window aggregate: nonzero
                    "owners": {
                        "0": {"retained_frames": 1,
                              "retained_bytes": self.rb,
                              "armed_frames": 0},       # live hoarder
                        "1": {"retained_frames": 1,
                              "retained_bytes": self.rb,
                              "armed_frames": 3},       # dead, re-armed
                    }}

        w = TwoOwnerWindow()
        memstats.register("window[mixed]", w)
        for _ in range(memstats.RETENTION_K):
            memstats.sample_once()
        leaks = {v["component"] for v in memstats.LEDGER.verdicts()
                 if v["kind"] == "retention-leak"}
        assert "window[mixed]@0" in leaks
        assert "window[mixed]@1" not in leaks
        assert "window[mixed]" not in leaks   # owners granularity wins


class TestFlagOffNullPath:
    def test_no_sampler_no_samples(self):
        assert config.get_flag("memstats_interval_s") == 0
        assert memstats.maybe_sample() is None
        assert memstats.ensure_started() is None
        assert memstats.LEDGER._thread is None
        assert memstats.LEDGER.samples() == []

    def test_zero_memstats_allocations_on_small_add_hot_path(
            self, two_ranks):
        """The ledger is registration-only: with the sampler flag off
        (the default), the windowed small-add hot path must execute
        ZERO lines of memstats.py — tracemalloc, filtered to the
        module, sees no allocations across 50 windowed adds.

        The probe runs against a quiesced world: the watchdog thread
        is stopped (its 0.5 s sweep legitimately runs memstats'
        verdict code on its OWN thread and would pollute — or, on
        3.10, race — the trace), and the send window is held wide
        open so the probe measures exactly the client enqueue path
        with no concurrent wire traffic."""
        watchdog.stop_global()
        t0 = AsyncMatrixTable(64, 8, name="null", ctx=two_ranks[0],
                              send_window_ms=10_000.0)
        AsyncMatrixTable(64, 8, name="null", ctx=two_ranks[1])
        for i in range(8):   # warm conns/compile outside the probe
            t0.add_rows_async([40], np.ones((1, 8), np.float32))
        t0.flush()
        tracemalloc.start()
        try:
            s1 = tracemalloc.take_snapshot()
            for i in range(50):
                t0.add_rows_async([40 + (i % 8)],
                                  np.ones((1, 8), np.float32))
            s2 = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        t0.flush()
        flt = [tracemalloc.Filter(True, "*memstats.py")]
        stats = s2.filter_traces(flt).compare_to(
            s1.filter_traces(flt), "filename")
        grew = [st for st in stats if st.size_diff > 0
                or st.count_diff > 0]
        assert not grew, f"memstats allocated on the hot path: {grew}"
        assert memstats.LEDGER.samples() == []


# ---------------------------------------------------------------------- #
# window / table / replica gauges
# ---------------------------------------------------------------------- #
class TestComponentGauges:
    def test_window_pending_and_retained_gauges(self, two_ranks):
        config.set_flag("ps_replay", True)
        t0 = AsyncMatrixTable(64, 8, name="wg", ctx=two_ranks[0],
                              send_window_ms=500.0)
        AsyncMatrixTable(64, 8, name="wg", ctx=two_ranks[1])
        t0.add_rows_async([40], np.ones((1, 8), np.float32))
        w = t0._window
        g = w.memory_stats()
        assert g["pending_ops"] == 1 and g["pending_bytes"] > 0
        t0.flush()
        g = w.memory_stats()
        assert g["pending_ops"] == 0
        assert g["retained_frames"] == 1 and g["retained_bytes"] > 0
        assert g["armed_frames"] == 0
        assert g["owners"]["1"]["retained_frames"] == 1

    def test_sync_table_cache_gauges(self):
        from multiverso_tpu import api as mv
        mv.init()
        try:
            from multiverso_tpu.table import Table
            t = Table((16, 4), name="syncmem")
            g = t.memory_stats()
            assert g == {"cache_bytes": 0, "prefetch_bytes": 0}
            t.get()
            g = t.memory_stats()
            assert g["cache_bytes"] == 16 * 4 * 4
            assert any(k.startswith("table[syncmem]") for k in
                       memstats.LEDGER.snapshot()["components"])
        finally:
            mv.shutdown()

    def test_replica_gauges(self, two_ranks):
        from multiverso_tpu.serving import ReadReplica
        t0 = AsyncMatrixTable(64, 4, name="repm", ctx=two_ranks[0],
                              seed=0, init_scale=0.1)
        AsyncMatrixTable(64, 4, name="repm", ctx=two_ranks[1])
        rep = ReadReplica(t0, start=False, staleness_s=30.0)
        rep.refresh()
        g = rep.memory_stats()
        assert g["snapshot_bytes"] == 64 * 4 * 4
        assert g["staging_bytes"] == 0   # transient, cleared at swap
        rep.close()


# ---------------------------------------------------------------------- #
# MSG_STATS block -> aggregator -> mvtop / exporter / dump_metrics
# ---------------------------------------------------------------------- #
class TestStatsSurface:
    def test_stats_payload_memory_block_and_cluster_merge(
            self, two_ranks):
        from multiverso_tpu.telemetry import aggregator
        t0 = AsyncMatrixTable(64, 8, name="memtab", ctx=two_ranks[0])
        AsyncMatrixTable(64, 8, name="memtab", ctx=two_ranks[1])
        t0.add_rows([40], np.ones((1, 8), np.float32))
        payload = two_ranks[0].service.stats_payload()
        mem = payload["memory"]
        assert mem["totals"]["table_bytes"] > 0
        json.dumps(payload)
        stats = {r: two_ranks[r].service.stats_payload()
                 for r in range(2)}
        health = {r: two_ranks[r].service.health_payload()
                  for r in range(2)}
        rec = aggregator.merge_cluster(stats, health, world=2)
        assert set(rec["memory"]["ranks"]) == {"0", "1"}
        # in-process 2-rank world: ONE process, totals summed once
        assert (rec["memory"]["totals"]["table_bytes"]
                == mem["totals"]["table_bytes"])
        # compact_record keeps the block for bench extra
        assert aggregator.compact_record(rec)["memory"] == rec["memory"]
        # mvtop renders the panel
        from tools import mvtop
        out = mvtop.render(rec)
        assert "memory:" in out and "rss_mb" in out

    def test_mvtop_once_live_memory_panel(self, two_ranks, tmp_path,
                                          capsys):
        """ISSUE 10 acceptance: mvtop --once against a live 2-rank
        world renders the memory panel with nonzero per-rank table
        bytes and RSS."""
        from tools import mvtop
        t0 = AsyncMatrixTable(64, 8, name="mvm", ctx=two_ranks[0])
        AsyncMatrixTable(64, 8, name="mvm", ctx=two_ranks[1])
        t0.add_rows([40], np.ones((1, 8), np.float32))
        # the fixture's FileRendezvous already published <rank>.addr
        rc = mvtop.main(["--rdv", str(tmp_path / "rdv"), "--once",
                         "--json"])
        assert rc == 0
        rec = json.loads(capsys.readouterr().out)
        ranks = rec["memory"]["ranks"]
        assert set(ranks) == {"0", "1"}
        for e in ranks.values():
            assert e["table_bytes"] > 0
            assert e["rss_mb"] is None or e["rss_mb"] > 0
        assert rec["memory"]["totals"]["table_bytes"] > 0
        out = mvtop.render(rec)
        assert "memory:" in out and "pinned epochs" in out

    def test_exporter_prometheus_memory_gauges(self):
        from multiverso_tpu.telemetry.exporter import prometheus_text
        sh = RowShard(0, 64, 8, np.float32, get_updater("sgd"), "prom")
        txt = prometheus_text({"rank": 3,
                               "memory": memstats.stats_snapshot()})
        assert 'mv_mem_total_table_bytes{rank="3"}' in txt
        assert 'component="shard[prom:0-64]"' in txt
        assert 'field="table_bytes"' in txt
        if memstats.read_rss()[0] is not None:
            assert 'mv_mem_rss_mb{rank="3"}' in txt

    def test_dump_metrics_show_and_diff_memory(self):
        from tools import dump_metrics
        sh = RowShard(0, 64, 8, np.float32, get_updater("sgd"), "dmem")
        assert sh is not None   # keep the weakref'd component alive
        a = {"rank": 0, "memory": memstats.stats_snapshot()}
        out = dump_metrics.format_record(a)
        assert "memory: rss" in out and "shard[dmem:0-64]" in out
        b = json.loads(json.dumps(a))
        b["memory"]["rss_mb"] = (a["memory"]["rss_mb"] or 0) + 100
        b["memory"]["totals"] = dict(b["memory"]["totals"])
        b["memory"]["totals"]["table_bytes"] = (
            a["memory"]["totals"]["table_bytes"] + 4096)
        diff = dump_metrics.diff_records(a, b)
        assert "memory deltas" in diff
        assert "totals.table_bytes" in diff
        # cluster records carry the block through format/diff too
        rec = {"kind": "cluster", "ts": 1.0, "world": 1, "ranks": {},
               "memory": {"ranks": {"0": {"rss_mb": 10.0}},
                          "totals": {"table_bytes": 2080}}}
        assert "memory(cluster)" in dump_metrics.format_cluster_record(
            rec)


# ---------------------------------------------------------------------- #
# OOM forensics + postmortem memory timeline
# ---------------------------------------------------------------------- #
class TestOOMForensics:
    def test_rss_soft_limit_trips_fault_dump(self, tmp_path):
        config.set_flag("flightrec_dir", str(tmp_path))
        config.set_flag("memstats_rss_limit_mb", 0.5)   # any RSS trips
        rss, _ = memstats.read_rss()
        if rss is None:
            pytest.skip("no /proc RSS on this platform")
        memstats.sample_once()
        path = tmp_path / "flightrec-rank0.jsonl"
        assert path.exists()
        kinds = [json.loads(ln)["kind"]
                 for ln in path.read_text().splitlines()]
        assert "memory" in kinds and "memsample" in kinds
        assert flightrec.EV_MEM_RSS in _ring_kinds()
        assert flightrec.EV_MEM_DUMP in _ring_kinds()
        # one dump per episode: sampling again does not re-trip
        n = len([v for v in memstats.LEDGER.verdicts()
                 if v["kind"] == "rss-limit"])
        memstats.sample_once()
        assert len([v for v in memstats.LEDGER.verdicts()
                    if v["kind"] == "rss-limit"]) == n
        # and a SAMPLE-LESS sweep (the watchdog path) must not clear
        # the episode either — a sustained over-limit RSS would then
        # re-dump forensics on every sampler tick
        memstats.check_verdicts()
        memstats.sample_once()
        assert len([v for v in memstats.LEDGER.verdicts()
                    if v["kind"] == "rss-limit"]) == n

    def test_postmortem_memory_timeline(self, tmp_path):
        from tools import postmortem
        sh = RowShard(0, 64, 8, np.float32, get_updater("sgd"), "pmort")
        assert sh is not None   # keep the weakref'd component alive
        for _ in range(3):
            memstats.sample_once()
            time.sleep(0.01)
        p = flightrec.RECORDER.dump("test fault", str(tmp_path),
                                    stacks=True)
        d = postmortem.load_dump(p)
        assert d["memory"] and len(d["memsamples"]) == 3
        rep = postmortem.memory_report([d])
        assert "0" in rep["ranks"]
        comp = rep["ranks"]["0"]["components"]
        assert any(k.startswith("shard[pmort:") for k in comp)
        assert len(rep["timeline"]) == 3
        assert rep["timeline"] == sorted(rep["timeline"],
                                         key=lambda s: s["ts"])
        txt = postmortem.render_report([d])
        assert "memory at dump time" in txt
        assert "memory timeline" in txt
        json.dumps(rep)   # --json key shape

    def test_rss_creep_verdict(self):
        config.set_flag("memstats_rss_slope_mb_s", 1.0)
        base = time.time()
        with memstats.LEDGER._lock:
            memstats.LEDGER._history.clear()
            for i in range(3):
                memstats.LEDGER._history.append(
                    {"ts": base + i, "rss_mb": 100.0 + 50.0 * i,
                     "totals": {}, "retained": {}})
        memstats.LEDGER.check_verdicts()
        creeps = [v for v in memstats.LEDGER.verdicts()
                  if v["kind"] == "rss-creep"]
        assert creeps and creeps[-1]["slope_mb_s"] > 1.0
        assert flightrec.EV_MEM_RSS in _ring_kinds()


# ---------------------------------------------------------------------- #
# stats-surface lint + run_bench memory flags + bench extra
# ---------------------------------------------------------------------- #
class TestObsSurfaceStatsRule:
    def test_full_tree_clean(self):
        from tools import check_obs_surface
        assert check_obs_surface.stats_surface_findings() == []

    def test_catches_a_dark_key(self):
        from tools import check_obs_surface
        findings = check_obs_surface.stats_surface_findings(
            keys_by_src={"fake.py:stats()": ["shiny_new_block"]},
            renderer_text='print(rec.get("memory"))')
        assert findings and "shiny_new_block" in findings[0]
        # a rendered key passes either quote style
        assert check_obs_surface.stats_surface_findings(
            keys_by_src={"fake.py:stats()": ["memory"]},
            renderer_text="rec.get('memory')") == []

    def test_key_extraction_sees_all_emission_shapes(self):
        from tools import check_obs_surface
        keys = check_obs_surface.stats_keys(
            "multiverso_tpu/ps/service.py", "stats_payload")
        # update() kwargs, subscript assigns, and the memory block
        for k in ("rank", "world", "shards", "serving", "profile",
                  "memory"):
            assert k in keys, keys
        shard_keys = check_obs_surface.stats_keys(
            "multiverso_tpu/ps/shard.py", "stats")
        for k in ("adds", "gets", "hotkeys", "dirty_rows", "keys"):
            assert k in shard_keys

    def test_check_runs_clean_on_tree(self):
        from tools import check_obs_surface
        assert check_obs_surface.check() == []


class TestRunBenchMemoryFlags:
    def _headline(self, rss, retained):
        return {"extra": {"memory": {"peak_rss_mb": rss,
                                     "peak_retained_bytes": retained}}}

    def test_peak_rss_growth_flagged(self):
        from tools.run_bench import flag_regressions
        out = flag_regressions(self._headline(400.0, 0),
                               self._headline(1000.0, 0))
        assert any("peak RSS" in f for f in out)
        assert not flag_regressions(self._headline(400.0, 0),
                                    self._headline(500.0, 0))

    def test_retained_bytes_floored_baseline(self):
        from tools.run_bench import (_RETAINED_BASELINE_FLOOR_BYTES,
                                     flag_regressions)
        # healthy 0 prior must NOT suppress a real retention spike
        out = flag_regressions(
            self._headline(400.0, 0),
            self._headline(400.0, 4 * _RETAINED_BASELINE_FLOOR_BYTES))
        assert any("retained-frame bytes" in f for f in out)
        # under 2x the floor: no flag
        assert not flag_regressions(
            self._headline(400.0, 0),
            self._headline(400.0, _RETAINED_BASELINE_FLOOR_BYTES))

    def test_missing_memory_keys_skipped(self):
        from tools.run_bench import flag_regressions
        assert flag_regressions({"extra": {}}, {"extra": {}}) == []


class TestBenchExtra:
    def test_peaks_shape_and_json(self):
        sh = RowShard(0, 64, 8, np.float32, get_updater("sgd"), "bx")
        pin = sh._pin_data()
        memstats.sample_once()
        sh._release_data(pin)
        rec = memstats.bench_extra()
        json.dumps(rec)
        assert rec["peak_pinned_epochs"] >= 1
        assert rec["samples"] >= 2
        if memstats.read_rss()[0] is not None:
            assert rec["peak_rss_mb"] >= rec["rss_mb"]
