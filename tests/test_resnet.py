"""ResNet model + data-parallel PS trainer (BASELINE config 5 analogue)."""

import jax
import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.apps.resnet_cifar import ResNetTrainer
from multiverso_tpu.models import resnet as resnet_lib


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


class TestResNetModel:
    def test_forward_shapes(self):
        params, bn = resnet_lib.init_resnet(jax.random.key(0), depth=8,
                                            num_classes=4, width=8)
        x = np.random.default_rng(0).normal(size=(2, 16, 16, 3)).astype(
            np.float32)
        logits, new_bn = resnet_lib.apply_resnet(params, bn, x)
        assert logits.shape == (2, 4)
        # bn running stats moved
        assert not np.allclose(np.asarray(new_bn["stem"]["mean"]), 0.0)

    def test_depth_validation(self):
        with pytest.raises(ValueError):
            resnet_lib.init_resnet(jax.random.key(0), depth=9)

    def test_flatten_roundtrip(self):
        params, _ = resnet_lib.init_resnet(jax.random.key(1), depth=8,
                                           width=8)
        flat, meta = resnet_lib.flatten_params(params)
        back = resnet_lib.unflatten_params(flat, meta)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))


class TestResNetTrainer:
    def test_loss_decreases_and_learns(self):
        trainer = ResNetTrainer(depth=8, num_classes=4, image_size=16,
                                batch_size=16, learning_rate=3e-3)
        x, y = resnet_lib.synthetic_cifar(256, size=16, classes=4, seed=1)
        first = trainer.train(x, y, epochs=1)
        later = trainer.train(x, y, epochs=4)
        assert later["loss"] < first["loss"]
        acc = trainer.evaluate(*resnet_lib.synthetic_cifar(128, size=16,
                                                           classes=4,
                                                           seed=2))
        assert acc > 0.4  # 4 classes, synthetic patterns: well above chance

    def test_batch_actually_sharded(self):
        trainer = ResNetTrainer(depth=8, num_classes=4, batch_size=16)
        x, y = resnet_lib.synthetic_cifar(64, size=16, classes=4, seed=0)
        xb, yb = trainer._shard_batches(x, y)
        assert len(xb.sharding.device_set) == 8
