"""Subprocess body: WordEmbedding PS-block training on the async plane.

Four independent OS processes (no JAX coordinator), each training its own
subset of the data blocks against uncoordinated async tables — the full
reference workflow (ref distributed_wordembedding.cpp:147-252 block
pipeline + communicator.cpp row pulls/pushes + server.cpp async applies).

Invoked as: python we_async_worker.py <rdv_dir> <world> <rank>
Prints "RESULT <json>" on success.
"""

import json
import os
import sys
import time

import numpy as np


def _sync(rdv_dir, world, rank, tag, timeout=120):
    from multiverso_tpu.utils.filesync import file_barrier
    file_barrier(rdv_dir, world, rank, tag, timeout=timeout, poll=0.02)


def main():
    rdv_dir, world, rank = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from multiverso_tpu.utils import config
    import multiverso_tpu as mv
    from multiverso_tpu.apps.word_embedding import (WEConfig, WordEmbedding,
                                                    synthetic_corpus)
    from multiverso_tpu.data.dictionary import Dictionary

    config.set_flag("ps_rank", rank)
    config.set_flag("ps_world", world)
    config.set_flag("ps_rendezvous", rdv_dir)
    config.set_flag("ps_timeout", 120.0)
    if os.environ.get("MV_PS_NATIVE", "") == "0":   # plane A/B (bench use)
        config.set_flag("ps_native", False)
    mv.init()

    cfg = WEConfig(size=16, epoch=1, min_count=1, batch_size=128,
                   data_block_size=5000, negative=2, sample=0, alpha=0.08,
                   async_ps="1", use_ps="1", seed=7)
    tokens = synthetic_corpus(40_000, vocab=300, seed=7)  # shared corpus
    dictionary = Dictionary.build(tokens, cfg.min_count, None)
    we = WordEmbedding(cfg, dictionary)
    ids = we.prepare_ids(tokens)
    _sync(rdv_dir, world, rank, "tables")
    stats = we.train_ps_blocks(ids)          # trains blocks[rank::world]
    _sync(rdv_dir, world, rank, "epoch1")
    # second epoch over the SAME blocks against the jointly-trained shards:
    # convergence evidence, not just liveness (VERDICT r2 weak #6)
    stats2 = we.train_ps_blocks(ids, epochs=2)
    _sync(rdv_dir, world, rank, "trained")
    total = we.total_word_count()
    emb = we.embeddings()                    # pulled off the async shards
    _sync(rdv_dir, world, rank, "read")
    mv.shutdown()
    print("RESULT " + json.dumps({
        "rank": rank,
        "words": int(stats["words_per_sec"] * stats["seconds"] + 0.5),
        "words_per_sec": round(stats["words_per_sec"], 1),
        "loss": stats["loss"],
        "loss_epoch2": stats2["loss"],
        "total_words": total,
        "emb_norm": float(np.linalg.norm(emb)),
    }), flush=True)


if __name__ == "__main__":
    main()
