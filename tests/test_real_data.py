"""Tier-4 convergence on REAL data (SURVEY §4 tier 4; BASELINE configs
1-2). MNIST/text8 are not downloadable in a zero-egress image, so the real
stand-ins are sklearn's bundled UCI handwritten digits and the committed
text8-normalized real-prose shard (data/realtext.txt.gz) — genuinely real
data with recorded provenance, not synthetic generators."""

import numpy as np
import pytest

import multiverso_tpu as mv


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


class TestLRDigits:
    def test_converges_to_high_accuracy(self):
        """ref BENCHMARK.md MNIST-LR ballpark is ~92%; UCI digits is an
        easier 8x8 task — softmax LR lands well above 90%."""
        from multiverso_tpu.apps.logistic_regression import (LogReg,
                                                             LogRegConfig)
        from multiverso_tpu.io import mnist

        data = mnist.load_real()
        assert "real" in data["provenance"] or "idx" in data["provenance"]
        cfg = LogRegConfig({
            "input_size": str(data["x_train"].shape[1]),
            "output_size": "10", "minibatch_size": "64",
            "learning_rate": "0.05", "train_epoch": "30",
        })
        lr = LogReg(cfg)
        lr.train_arrays(data["x_train"], data["y_train"])
        acc = lr.test_arrays(data["x_test"], data["y_test"])
        assert acc >= 0.90, acc


class TestRealText:
    def test_shard_loads_and_is_natural_language(self):
        from multiverso_tpu.io import realtext

        tokens = realtext.load_tokens(max_tokens=200_000)
        assert len(tokens) == 200_000
        # Zipf sanity: 'the' dominates, vocab is natural-language sized
        from collections import Counter
        c = Counter(tokens)
        assert c["the"] > 0.03 * sum(c.values())
        assert len(c) > 3_000

    def test_we_trains_on_real_text(self):
        from multiverso_tpu.apps.word_embedding import (WEConfig,
                                                        WordEmbedding)
        from multiverso_tpu.data.dictionary import Dictionary
        from multiverso_tpu.io import realtext

        tokens = realtext.load_tokens(max_tokens=120_000)
        cfg = WEConfig(size=32, min_count=5, batch_size=1024, negative=3,
                       window=5, shared_negatives=32)
        d = Dictionary.build(tokens, cfg.min_count)
        we = WordEmbedding(cfg, d)
        ids = we.prepare_ids(tokens)
        first = we.train_fused(ids, epochs=1)
        later = we.train_fused(ids, epochs=4)
        assert np.isfinite(later["loss"])
        assert later["loss"] < first["loss"]   # actually learning
        probe = next(w for w in ("array", "the", "value", "data")
                     if w in d.word2id)
        assert len(we.nearest(probe, 5)) == 5
