"""The tier-2 battery module itself (multiverso_tpu.harness) — the
reference's Test/main.cpp dispatcher run the way Docker CI ran it
(ref deploy/docker/Dockerfile battery; SURVEY §4 tier 2)."""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # Unconditional (not setdefault): the ambient environment may point JAX
    # at real hardware, but the tier-2 battery is defined to run on the
    # virtual CPU mesh (SURVEY §4's "mpirun on one host" analogue).
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    env["XLA_FLAGS"] = flags
    return subprocess.run(
        [sys.executable, "-m", "multiverso_tpu.harness", *args],
        capture_output=True, text=True, timeout=timeout, env=env)


def test_battery_single_process(tmp_path):
    r = _run(["kv", "array", "net", "ip", "matrix", "checkpoint", "restore",
              "allreduce", f"-checkpoint_dir={tmp_path}"])
    assert r.returncode == 0, r.stderr[-2000:]
    passed = [l for l in r.stdout.splitlines() if l.startswith("HARNESS PASS")]
    assert len(passed) == 8, r.stdout


def test_battery_perf_smoke(tmp_path):
    r = _run(["dense_perf", "sparse_perf", "-rows=512"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert r.stdout.count("HARNESS PASS") == 2, r.stdout


def test_battery_two_process(tmp_path):
    r = _run(["kv", "matrix", "-nprocs=2", f"-checkpoint_dir={tmp_path}"],
             timeout=900)
    if r.returncode == 77:  # harness skip code: jax.distributed unavailable
        import pytest
        pytest.skip("jax.distributed unavailable: " + r.stderr[-200:])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "HARNESS PASS kv (nprocs=2)" in r.stdout, r.stdout
    assert "HARNESS PASS matrix (nprocs=2)" in r.stdout, r.stdout
