"""Binding surfaces: handlers (python-binding parity), sharedvar delta sync,
C ABI shim, checkpoint (ref tier-3 binding tests, SURVEY §4:
binding/python/multiverso/tests/test_multiverso.py)."""

import ctypes
import os

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu import checkpoint
from multiverso_tpu.handlers import ArrayTableHandler, MatrixTableHandler
from multiverso_tpu.sharedvar import mv_shared


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


class TestHandlers:
    def test_array_handler_roundtrip(self):
        # ref test_multiverso.py TestArray: get returns what was added,
        # scaled by workers_num (1 here)
        h = ArrayTableHandler(100, init_value=np.arange(100, dtype=np.float32))
        np.testing.assert_allclose(h.get(), np.arange(100))
        h.add(np.ones(100))
        np.testing.assert_allclose(h.get(), np.arange(100) + 1)

    def test_matrix_handler(self):
        h = MatrixTableHandler(10, 4)
        h.add(np.ones((10, 4)))
        np.testing.assert_allclose(h.get(), 1.0)
        h.add_rows([2, 3], np.full((2, 4), 2.0))
        np.testing.assert_allclose(h.get_rows([2]), 3.0)


class TestSharedVar:
    def test_delta_sync(self):
        # ref sharedvar.py mv_sync: Add(current - last) then Get
        params = {"w": np.ones((3, 2), np.float32),
                  "b": np.zeros(3, np.float32)}
        shared = mv_shared(params)
        got = shared.get()
        np.testing.assert_allclose(got["w"], 1.0)
        # local update then sync: global state reflects the delta
        local = {"w": got["w"] + 0.5, "b": got["b"] - 1.0}
        merged = shared.sync(local)
        np.testing.assert_allclose(merged["w"], 1.5)
        np.testing.assert_allclose(merged["b"], -1.0)
        # second sync with no local change is a no-op
        merged2 = shared.sync(merged)
        np.testing.assert_allclose(merged2["w"], 1.5)

    def test_preserves_tree_structure(self):
        import jax.numpy as jnp
        params = {"layers": [{"k": jnp.ones((2, 2))},
                             {"k": jnp.zeros((1, 3))}]}
        shared = mv_shared(params)
        out = shared.get()
        assert out["layers"][0]["k"].shape == (2, 2)
        assert out["layers"][1]["k"].shape == (1, 3)


class TestCheckpoint:
    def test_save_restore_all_tables(self, tmp_path):
        t1 = mv.ArrayTable(64, updater="adagrad", name="ckpt_a")
        t2 = mv.MatrixTable(8, 4, name="ckpt_m")
        kv = mv.KVTable(name="ckpt_kv")
        t1.add(np.ones(64, np.float32), mv.AddOption(learning_rate=0.1))
        t2.add_rows([3], np.full((1, 4), 5.0, np.float32))
        kv.add([9], [42])
        path = checkpoint.save(str(tmp_path), tag="t0")
        snap1, snap2 = t1.get().copy(), t2.get().copy()

        t1.add(np.ones(64, np.float32))
        t2.add(np.ones((8, 4), np.float32))
        kv.add([9], [1])
        n = checkpoint.restore(str(tmp_path), tag="t0")
        assert n == 3
        np.testing.assert_allclose(t1.get(), snap1)
        np.testing.assert_allclose(t2.get(), snap2)
        assert kv[9] == 42
        assert checkpoint.latest(str(tmp_path)) == "t0"

    def test_save_restore_orbax_backend(self, tmp_path):
        t1 = mv.ArrayTable(64, updater="adagrad", name="ob_a")
        t2 = mv.MatrixTable(8, 4, name="ob_m")
        kv = mv.KVTable(name="ob_kv")
        t1.add(np.ones(64, np.float32), mv.AddOption(learning_rate=0.1))
        t2.add_rows([3], np.full((1, 4), 5.0, np.float32))
        kv.add([9], [42])
        checkpoint.save(str(tmp_path), tag="t0", backend="orbax")
        snap1, snap2 = t1.get().copy(), t2.get().copy()

        t1.add(np.ones(64, np.float32))
        t2.add(np.ones((8, 4), np.float32))
        kv.add([9], [1])
        # what one more identical add yields from the checkpointed state
        # (captures the adagrad history's effect), for the ustate check
        t1.add(np.ones(64, np.float32))  # state now diverged from snap
        # restore auto-detects the backend from the manifest
        n = checkpoint.restore(str(tmp_path), tag="t0")
        assert n == 3
        np.testing.assert_allclose(t1.get(), snap1)
        np.testing.assert_allclose(t2.get(), snap2)
        assert kv[9] == 42
        # updater state came back too: replay the same add twice from the
        # restored point and the adagrad trajectories must agree
        t1.add(np.ones(64, np.float32), mv.AddOption(learning_rate=0.1))
        after_first = t1.get().copy()
        checkpoint.restore(str(tmp_path), tag="t0")
        t1.add(np.ones(64, np.float32), mv.AddOption(learning_rate=0.1))
        np.testing.assert_allclose(t1.get(), after_first)
        assert checkpoint.latest(str(tmp_path)) == "t0"

    def test_orbax_file_uri_roundtrip(self, tmp_path):
        # file:// URIs must put arrays inside the checkpoint dir, not in a
        # cwd-relative stray path
        t = mv.ArrayTable(16, name="uri_t")
        t.add(np.ones(16, np.float32))
        uri = f"file://{tmp_path}"
        checkpoint.save(uri, tag="u0", backend="orbax")
        assert (tmp_path / "u0" / "arrays").is_dir()
        snap = t.get().copy()
        t.add(np.ones(16, np.float32))
        checkpoint.restore(uri, tag="u0")
        np.testing.assert_allclose(t.get(), snap)

    def test_orbax_restore_skips_tables_added_since_save(self, tmp_path):
        t = mv.ArrayTable(8, name="old_t")
        t.add(np.ones(8, np.float32))
        checkpoint.save(str(tmp_path), tag="t1", backend="orbax")
        snap = t.get().copy()
        extra = mv.ArrayTable(8, name="new_t")  # registered after the save
        extra.add(np.full(8, 3.0, np.float32))
        t.add(np.ones(8, np.float32))
        n = checkpoint.restore(str(tmp_path), tag="t1")
        assert n == 1
        np.testing.assert_allclose(t.get(), snap)
        np.testing.assert_allclose(extra.get(), np.full(8, 3.0))

    def test_async_orbax_save_finalizes_on_wait(self, tmp_path):
        t = mv.ArrayTable(32, name="async_t")
        t.add(np.ones(32, np.float32))
        snap = t.get().copy()
        checkpoint.save(str(tmp_path), tag="a0", backend="orbax",
                        block=False)
        # invisible until finalized: no manifest yet
        assert checkpoint.latest(str(tmp_path)) is None
        assert checkpoint.wait_pending() == 1
        assert checkpoint.latest(str(tmp_path)) == "a0"
        t.add(np.ones(32, np.float32))
        checkpoint.restore(str(tmp_path), tag="a0")
        np.testing.assert_allclose(t.get(), snap)

    def test_restore_waits_for_inflight_async_save(self, tmp_path):
        t = mv.ArrayTable(16, name="async_u")
        t.add(np.full(16, 2.0, np.float32))
        checkpoint.save(str(tmp_path), tag="u0", backend="orbax",
                        block=False)
        t.add(np.ones(16, np.float32))
        # restore finalizes the pending save itself, no explicit wait
        checkpoint.restore(str(tmp_path), tag="u0")
        np.testing.assert_allclose(t.get(), np.full(16, 2.0))

    def test_async_requires_orbax(self, tmp_path):
        mv.ArrayTable(8, name="async_v")
        with pytest.raises(ValueError, match="orbax"):
            checkpoint.save(str(tmp_path), tag="x", block=False)

    def test_unknown_backend_raises(self, tmp_path):
        mv.ArrayTable(8, name="bk")
        with pytest.raises(ValueError, match="backend"):
            checkpoint.save(str(tmp_path), tag="t", backend="pickle")
        from multiverso_tpu import elastic
        with pytest.raises(ValueError, match="backend"):
            elastic.ElasticLoop(str(tmp_path), backend="orbx")

    def test_restore_mismatch_raises(self, tmp_path):
        mv.ArrayTable(16, name="first")
        checkpoint.save(str(tmp_path), tag="x")
        mv.shutdown()
        mv.init()
        mv.ArrayTable(16, name="different")
        with pytest.raises(ValueError):
            checkpoint.restore(str(tmp_path), tag="x")


_CAPI = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "multiverso_tpu", "native",
    "libmultiverso.so")


@pytest.mark.skipif(not os.path.exists(_CAPI),
                    reason="libmultiverso.so not built")
class TestCAPI:
    """Drive the C ABI end-to-end from ctypes (the Lua-binding load path,
    ref c_api.h). The shim attaches to this already-running interpreter."""

    def _lib(self):
        lib = ctypes.CDLL(_CAPI)
        lib.MV_NewArrayTable.argtypes = [ctypes.c_int,
                                         ctypes.POINTER(ctypes.c_void_p)]
        lib.MV_GetArrayTable.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_float),
                                         ctypes.c_int]
        lib.MV_AddArrayTable.argtypes = lib.MV_GetArrayTable.argtypes
        lib.MV_NewMatrixTable.argtypes = [ctypes.c_int, ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_void_p)]
        lib.MV_GetMatrixTableByRows.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int]
        lib.MV_AddMatrixTableByRows.argtypes = lib.MV_GetMatrixTableByRows.argtypes
        return lib

    def test_array_table_via_c_abi(self):
        lib = self._lib()
        lib.MV_Init(None, None)
        assert lib.MV_NumWorkers() == 1
        assert lib.MV_WorkerId() == 0
        h = ctypes.c_void_p()
        lib.MV_NewArrayTable(32, ctypes.byref(h))
        data = (ctypes.c_float * 32)(*([2.0] * 32))
        lib.MV_AddArrayTable(h, data, 32)
        out = (ctypes.c_float * 32)()
        lib.MV_GetArrayTable(h, out, 32)
        np.testing.assert_allclose(list(out), 2.0)
        lib.MV_Barrier()

    def test_matrix_rows_via_c_abi(self):
        lib = self._lib()
        lib.MV_Init(None, None)
        h = ctypes.c_void_p()
        lib.MV_NewMatrixTable(6, 3, ctypes.byref(h))
        ids = (ctypes.c_int * 2)(1, 4)
        vals = (ctypes.c_float * 6)(*([1.5] * 6))
        lib.MV_AddMatrixTableByRows(h, vals, 6, ids, 2)
        out = (ctypes.c_float * 6)()
        lib.MV_GetMatrixTableByRows(h, out, 6, ids, 2)
        np.testing.assert_allclose(list(out), 1.5)


def test_stream_save_finalizes_pending_async(tmp_path):
    import multiverso_tpu as mv
    t = mv.ArrayTable(16, name="mix_t")
    t.add(np.ones(16, np.float32))
    checkpoint.save(str(tmp_path), tag="a", backend="orbax", block=False)
    # a stream save must finalize 'a' first so latest() ordering holds
    checkpoint.save(str(tmp_path), tag="b", backend="stream")
    assert checkpoint.latest(str(tmp_path)) == "b"
    assert checkpoint.wait_pending() == 0  # already finalized


def test_reference_binding_name_parity():
    """The verbatim names a reference TUTORIAL.md user types (ref
    binding/python/multiverso/api.py:12-68) all exist and agree."""
    import multiverso_tpu as mv
    mv.init()
    try:
        assert mv.workers_num() == mv.num_workers() == mv.MV_NumWorkers()
        assert mv.servers_num() == mv.num_servers() == mv.MV_NumServers()
        assert mv.worker_id() == mv.MV_WorkerId()
        assert isinstance(mv.is_master_worker(), bool)
        assert mv.MV_Rank() == mv.rank()
    finally:
        mv.shutdown()


def test_matrix_handler_row_ids_dispatch():
    """Reference tables.py single-method surface: get(row_ids)/add(data,
    row_ids) route to the row ops (ref tables.py:108,132)."""
    import multiverso_tpu as mv
    from multiverso_tpu.handlers import MatrixTableHandler
    mv.init()
    try:
        h = MatrixTableHandler(8, 4, name="mth_rows")
        h.add(np.ones((2, 4), np.float32), row_ids=[1, 5])
        got = h.get(row_ids=[1, 5])
        np.testing.assert_allclose(got, np.ones((2, 4)), rtol=1e-6)
        whole = h.get()
        assert whole.shape == (8, 4)
        np.testing.assert_allclose(whole[[0, 2]], np.zeros((2, 4)))
    finally:
        mv.shutdown()


def test_matrix_handler_rejects_ambiguous_positional():
    import pytest

    import multiverso_tpu as mv
    from multiverso_tpu.handlers import MatrixTableHandler
    mv.init()
    try:
        h = MatrixTableHandler(4, 4, name="mth_guard")
        with pytest.raises(TypeError, match="row_ids must be integers"):
            h.get(np.zeros((4, 4), np.float32))  # legacy positional out=
        with pytest.raises(TypeError):
            h.add(np.ones((4, 4), np.float32), False)  # legacy sync=
    finally:
        mv.shutdown()


def test_async_handler_adds_do_not_leak_pending():
    """Fire-and-forget handler adds (sync=False default, ref semantics)
    must not grow Table._pending unboundedly — completed add tokens are
    swept opportunistically."""
    import multiverso_tpu as mv
    from multiverso_tpu.handlers import ArrayTableHandler
    mv.init()
    try:
        h = ArrayTableHandler(64, name="leak_check")
        for i in range(50):
            h.add(np.ones(64, np.float32))
        # drain the device queue, then one more tracked op triggers a sweep
        np.asarray(h.get())
        h.add(np.ones(64, np.float32))
        assert len(h._table._pending) < 10, len(h._table._pending)
        # gets are never swept: their results stay claimable
        mid = h._table.get_async()
        h.add(np.ones(64, np.float32))
        assert h._table.wait(mid) is not None
    finally:
        mv.shutdown()


@pytest.mark.slow
def test_c_abi_driver_end_to_end():
    """Build and run the plain-C driver over EVERY exported MV_* symbol
    (ref binding/lua/test.lua:1-79 had this role; ours asserts). Covers the
    ABI with no Python on the caller side — the embedded interpreter is the
    implementation detail under test."""
    import shutil
    import subprocess

    if shutil.which("g++") is None or shutil.which("cc") is None:
        pytest.skip("no C toolchain")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(repo, "multiverso_tpu", "native")
    build = subprocess.run(["make", "-C", native, "mv_capi_test"],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]
    env = dict(os.environ)
    env["MV_CAPI_PLATFORM"] = "cpu"   # keep off the single TPU chip
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    run = subprocess.run([os.path.join(native, "mv_capi_test")],
                         capture_output=True, text=True, timeout=300,
                         env=env, cwd=native)
    assert run.returncode == 0, (run.stdout[-1000:], run.stderr[-2000:])
    assert "MV_CAPI_TEST PASS" in run.stdout
