"""SLO sentinel + autoscaling signal bus (ISSUE 19): the burn-rate
math against an independent integer-grid oracle, spec validation at
arm time, the availability SLI's reachability/progress/pent-demand
semantics, the fire -> hold -> clear -> refire episode lifecycle with
its artifacts (alerts.jsonl + flightrec ring), the false-fire guard on
quiet histories, straggler naming on a skewed 2-rank record (synthetic
AND the real merged-record shape), the typed signal bus +
``mvautoscale.recommend`` on a live pool with a warm spare, mvtop's
SLO panel / ``--assert-slo`` exit, run_bench's fired-now-not-before
flag, and the check_obs_surface lint-7 dark-key rule."""

import json
import os
import sys
import time

import numpy as np
import pytest

from multiverso_tpu.ps.service import FileRendezvous, PSContext, PSService
from multiverso_tpu.ps.tables import AsyncMatrixTable
from multiverso_tpu.serving.pool import ReplicaPool
from multiverso_tpu.telemetry import aggregator
from multiverso_tpu.telemetry import flightrec
from multiverso_tpu.telemetry import signals
from multiverso_tpu.telemetry import slo
from multiverso_tpu.utils import config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


@pytest.fixture(autouse=True)
def _clean_sentinel():
    """The sentinel and bus are process-global (the aggregator drives
    them on every poll anywhere in this test process) — every test
    starts and ends disarmed."""
    slo.reset()
    signals.reset()
    config.set_flag("slo_spec", "")
    yield
    slo.reset()
    signals.reset()
    config.set_flag("slo_spec", "")


def _stall_obj(**kw):
    """The oracle tests' workhorse objective: stall_fraction is the
    simplest SLI (max over profile blocks), so the burn math — not the
    measurement — is what the grid exercises."""
    base = {"name": "stall", "kind": "stall_fraction", "target": 0.9,
            "max": 0.5, "fast_window_s": 4.0, "slow_window_s": 10.0,
            "fast_burn": 1.0, "slow_burn": 0.1}
    base.update(kw)
    return base


def _stall_rec(ts, v=None):
    """One synthetic poll: ``v=None`` is a record with no evidence
    (profile absent — the poll must sit out, not count as good)."""
    rec = {"ts": float(ts), "ranks": {"0": {"status": "serving"},
                                     "1": {"status": "serving"}}}
    if v is not None:
        rec["profile"] = {"0": {"stall_fraction": float(v)}}
    return rec


# ---------------------------------------------------------------------- #
# spec loading + validation (arm-time failure, not judge-time garbage)
# ---------------------------------------------------------------------- #
class TestSpec:
    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown SLO objective"):
            slo.normalize_spec({"objectives": [
                {"name": "x", "kind": "made_up_kind"}]})

    def test_duplicate_name_raises(self):
        with pytest.raises(ValueError, match="duplicate"):
            slo.normalize_spec({"objectives": [
                {"name": "x", "kind": "staleness", "max": 1.0},
                {"name": "x", "kind": "shed_rate", "max": 0.1}]})

    def test_bad_target_raises(self):
        with pytest.raises(ValueError, match="target"):
            slo.normalize_spec({"objectives": [
                {"name": "x", "kind": "staleness", "target": 1.0,
                 "max": 1.0}]})

    def test_threshold_ms_alias_and_floor_default(self):
        spec = slo.normalize_spec({"objectives": [
            {"name": "lat", "kind": "serve_latency_p99",
             "threshold_ms": 5.0},
            {"name": "avail", "kind": "availability"}]})
        lat, avail = spec["objectives"]
        assert lat["max"] == 5.0
        assert avail["min"] == 1.0       # floor kinds default min=1.0
        assert lat["fast_window_s"] == 60.0   # spec-level defaults fill

    def test_load_spec_inline_and_path(self, tmp_path):
        raw = {"objectives": [{"name": "s", "kind": "staleness",
                               "max": 2.0}]}
        assert slo.load_spec(json.dumps(raw)) == raw
        p = tmp_path / "slo.json"
        p.write_text(json.dumps(raw))
        assert slo.load_spec(str(p)) == raw

    def test_every_declared_kind_normalizes(self):
        """OBJECTIVE_KINDS is the promise the lint enforces — every
        kind must actually be armable."""
        spec = slo.normalize_spec({"objectives": [
            {"name": f"o{i}", "kind": k, "max": 1.0}
            for i, k in enumerate(slo.OBJECTIVE_KINDS)]})
        assert len(spec["objectives"]) == len(slo.OBJECTIVE_KINDS)


# ---------------------------------------------------------------------- #
# burn-rate math vs an independent integer-grid oracle
# ---------------------------------------------------------------------- #
def _oracle(obj, grid, now):
    """Brute-force reference: same definition, independent code path.
    ``grid`` is [(ts, value-or-None)]."""
    budget = max(1.0 - obj["target"], 1e-4)
    out = {}
    for label, window in (("fast", obj["fast_window_s"]),
                          ("slow", obj["slow_window_s"])):
        hits = [(ts, v) for ts, v in grid
                if now - window <= ts <= now and v is not None]
        bad = sum(1 for _ts, v in hits if v > obj["max"])
        out[label] = round((bad / len(hits)) / budget, 4) if hits \
            else 0.0
    return out


class TestBurnOracle:
    # bad polls at ts 7 and 8, a no-evidence hole at ts 5
    GRID = [(t, (0.9 if t in (7, 8) else None if t == 5 else 0.1))
            for t in range(11)]

    def _history(self):
        return [_stall_rec(ts, v) for ts, v in self.GRID]

    def test_grid_matches_oracle_at_every_now(self):
        obj = slo.normalize_spec(
            {"objectives": [_stall_obj()]})["objectives"][0]
        hist = self._history()
        for now in range(3, 14):
            br = slo.burn_rates(obj, hist, now=float(now))
            exp = _oracle(obj, self.GRID, now)
            assert br["fast"] == exp["fast"], f"fast @ now={now}"
            assert br["slow"] == exp["slow"], f"slow @ now={now}"

    def test_hand_computed_point(self):
        """One point fully by hand so the oracle itself is anchored:
        now=10, fast window [6,10] -> 5 measured, 2 bad ->
        (2/5)/0.1 = 4.0; slow window [0,10] -> 10 measured (ts 5 sat
        out), 2 bad -> (2/10)/0.1 = 2.0."""
        obj = slo.normalize_spec(
            {"objectives": [_stall_obj()]})["objectives"][0]
        br = slo.burn_rates(obj, self._history(), now=10.0)
        assert (br["fast"], br["slow"]) == (4.0, 2.0)
        assert (br["n_fast"], br["bad_fast"]) == (5, 2)
        assert (br["n_slow"], br["bad_slow"]) == (10, 2)
        assert br["value"] == 0.1       # newest measured value

    def test_empty_window_burns_zero(self):
        obj = slo.normalize_spec(
            {"objectives": [_stall_obj()]})["objectives"][0]
        br = slo.burn_rates(obj, self._history(), now=30.0)
        assert br["fast"] == 0.0 and br["n_fast"] == 0
        assert slo.burn_rates(obj, [], now=0.0)["fast"] == 0.0

    def test_floor_kind_violates_below_min(self):
        obj = slo.normalize_spec({"objectives": [
            {"name": "a", "kind": "availability", "target": 0.9,
             "min": 1.0}]})["objectives"][0]
        assert slo.violates(obj, 0.5) and not slo.violates(obj, 1.0)
        mx = slo.normalize_spec({"objectives": [
            {"name": "s", "kind": "staleness",
             "max": 2.0}]})["objectives"][0]
        assert slo.violates(mx, 2.5) and not slo.violates(mx, 2.0)


# ---------------------------------------------------------------------- #
# the availability SLI: reachability AND progress-vs-demand
# ---------------------------------------------------------------------- #
class TestAvailability:
    OBJ = {"name": "a", "kind": "availability", "table": "tb",
           "target": 0.9, "min": 1.0}

    def test_unreachable_rank_is_the_fraction(self):
        rec = {"ts": 1.0, "world": 2,
               "ranks": {"0": {"status": "serving"},
                         "1": {"status": "unreachable"}}}
        assert slo.measure(self.OBJ, rec) == 0.5

    def test_progress_is_available(self):
        rec = {"ts": 1.0, "world": 2,
               "ranks": {"0": {"status": "serving"},
                         "1": {"status": "serving"}},
               "rates": {"tb": {"adds_per_s": 12.0}}}
        assert slo.measure(self.OBJ, rec) == 1.0

    def test_pent_demand_without_progress_is_outage(self):
        rec = {"ts": 1.0, "world": 2,
               "ranks": {"0": {"status": "serving"},
                         "1": {"status": "serving"}},
               "rates": {"tb": {"adds_per_s": 0.0, "gets_per_s": 0.0}},
               "memory": {"totals": {"retained_bytes": 4096}}}
        assert slo.measure(self.OBJ, rec) == 0.0

    def test_idle_sits_out(self):
        rec = {"ts": 1.0, "world": 2,
               "ranks": {"0": {"status": "serving"},
                         "1": {"status": "serving"}},
               "rates": {"tb": {"adds_per_s": 0.0}}}
        assert slo.measure(self.OBJ, rec) is None

    def test_first_poll_without_rates_sits_out(self):
        rec = {"ts": 1.0, "world": 2,
               "ranks": {"0": {"status": "serving"},
                         "1": {"status": "serving"}}}
        assert slo.measure(self.OBJ, rec) is None


# ---------------------------------------------------------------------- #
# episode lifecycle: fire once -> hold -> clear -> refire + artifacts
# ---------------------------------------------------------------------- #
class TestLifecycle:
    def _drive(self, sentinel, values, directory=""):
        """Feed (ts, value) polls one at a time, history growing the
        way the aggregator's does; returns the snapshot stream."""
        hist, snaps = [], []
        for ts, v in values:
            rec = _stall_rec(ts, v)
            hist.append(rec)
            snaps.append(sentinel.on_poll(rec, list(hist), directory))
        return snaps

    def test_fire_hold_clear_refire(self, tmp_path):
        s = slo.SLOSentinel({"objectives": [_stall_obj()]})
        ring_before = len([e for e in flightrec.RECORDER.snapshot()
                           if e[2] in (flightrec.EV_SLO_FIRED,
                                       flightrec.EV_SLO_CLEARED)])
        # good 0-3, bad 4-5 (fire at 4, hold at 5), good 6-10 (the bad
        # polls age out of the 4 s fast window -> clear at 10), bad 11
        # (refire: slow window still remembers the first episode)
        vals = [(t, 0.9 if t in (4, 5, 11) else 0.1) for t in range(12)]
        snaps = self._drive(s, vals, directory=str(tmp_path))
        firing = [bool(sn["firing"]) for sn in snaps]
        assert firing == [False] * 4 + [True] * 6 + [False] + [True]
        assert snaps[4]["episodes"] == 1
        assert snaps[5]["episodes"] == 1        # HOLD is not a refire
        assert snaps[11]["episodes"] == 2
        kinds = [e["kind"] for e in snaps[-1]["recent"]]
        assert kinds == ["slo.fired", "slo.cleared", "slo.fired"]
        # artifacts: one alerts.jsonl line per transition, same order
        with open(tmp_path / "alerts.jsonl") as f:
            alerts = [json.loads(ln) for ln in f]
        assert [a["kind"] for a in alerts] == kinds
        assert [a["ts"] for a in alerts] == [4.0, 10.0, 11.0]
        assert all(a["objective"] == "stall" for a in alerts)
        # and one flightrec EV pair + refire in the always-on ring
        ring = [e for e in flightrec.RECORDER.snapshot()
                if e[2] in (flightrec.EV_SLO_FIRED,
                            flightrec.EV_SLO_CLEARED)][ring_before:]
        assert [e[2] for e in ring] == [flightrec.EV_SLO_FIRED,
                                        flightrec.EV_SLO_CLEARED,
                                        flightrec.EV_SLO_FIRED]
        assert "stall" in ring[0][7]    # the note names the objective

    def test_false_fire_guard_on_quiet_history(self, tmp_path):
        """A healthy/idle stream must end with evals > 0 and ZERO
        episodes — availability polls with no evidence sit out rather
        than count against the budget."""
        s = slo.SLOSentinel({"objectives": [
            {"name": "avail", "kind": "availability", "table": "tb",
             "target": 0.9, "fast_burn": 1.0, "slow_burn": 0.1}]})
        hist = []
        for t in range(30):
            rec = {"ts": float(t), "world": 2,
                   "ranks": {"0": {"status": "serving"},
                             "1": {"status": "serving"}}}
            if t % 2:    # alternate progressing and idle polls
                rec["rates"] = {"tb": {"adds_per_s": 9.0}}
            hist.append(rec)
            snap = s.on_poll(rec, list(hist), str(tmp_path))
        assert snap["evals"] == 30
        assert snap["episodes"] == 0 and snap["firing"] == []
        assert not os.path.exists(tmp_path / "alerts.jsonl")

    def test_disarmed_is_none_and_flag_arms_lazily(self):
        s = slo.SLOSentinel()
        assert s.on_poll(_stall_rec(0, 0.1), [_stall_rec(0, 0.1)]) \
            is None
        config.set_flag("slo_spec", json.dumps(
            {"objectives": [_stall_obj()]}))
        snap = slo.SLOSentinel().on_poll(
            _stall_rec(1, 0.1), [_stall_rec(1, 0.1)])
        assert snap is not None and "stall" in snap["objectives"]

    def test_note_value_feeds_external_kinds(self):
        s = slo.SLOSentinel({"objectives": [
            {"name": "rec", "kind": "recovery_s", "target": 0.5,
             "max": 3.0, "fast_window_s": 10.0, "slow_window_s": 10.0,
             "fast_burn": 1.0, "slow_burn": 0.5}]})
        s.note_value("rec", 9.0)         # measured where it happened
        hist = [_stall_rec(t) for t in range(3)]
        for i, rec in enumerate(hist):
            snap = s.on_poll(rec, hist[:i + 1])
        assert snap["firing"] == ["rec"]
        assert snap["objectives"]["rec"]["value"] == 9.0


# ---------------------------------------------------------------------- #
# straggler naming on a skewed 2-rank record
# ---------------------------------------------------------------------- #
class TestStraggler:
    def test_compute_skew_names_rank_and_phase(self):
        rec = {"ranks": {"0": {"status": "serving"},
                         "1": {"status": "serving"}},
               "profile": {"0": {"phases": {"serve": 1.0}},
                           "1": {"phases": {"serve": 3.0,
                                            "apply": 9.0}}}}
        st = slo.straggler(rec)
        assert st["rank"] == 1 and st["attribution"] == "compute"
        assert st["top_phase"] == "apply"

    def test_wire_skew_names_the_backlogged_rank(self):
        rec = {"ranks": {"0": {"status": "serving", "queue_depth": 0},
                         "1": {"status": "serving", "queue_depth": 64,
                               "oldest_inflight_s": 2.0}}}
        st = slo.straggler(rec)
        assert st["rank"] == 1 and st["attribution"] == "wire"

    def test_quiet_or_single_rank_has_no_straggler(self):
        assert slo.straggler({"ranks": {"0": {"status": "serving"}}}) \
            is None
        quiet = {"ranks": {"0": {"status": "serving"},
                           "1": {"status": "serving"}}}
        assert slo.straggler(quiet) is None   # nothing moved: no blame

    def test_real_merged_record_shape(self, tmp_path):
        """The detector runs on the aggregator's ACTUAL merged record
        (key spellings, health-entry fields), skewed on the real
        record rather than a hand-built lookalike."""
        ctx0, ctx1 = _live_world(tmp_path)
        try:
            agg = aggregator.ClusterAggregator(ctx0.service)
            rec = agg.poll_once()
            ranks = rec.get("ranks") or {}
            assert len(ranks) == 2
            slow = sorted(ranks)[1]
            ranks[slow]["queue_depth"] = 128     # skew the real record
            st = slo.straggler(rec)
            assert st is not None
            assert str(st["rank"]) == str(slow)
            assert st["attribution"] in ("wire", "compute", "stall")
        finally:
            ctx0.close()
            ctx1.close()


# ---------------------------------------------------------------------- #
# signal bus + mvautoscale on a live pool with a warm spare
# ---------------------------------------------------------------------- #
def _live_world(tmp_path, table=False):
    for k, v in dict(ps_native=False, ps_timeout=30.0,
                     ps_connect_timeout=5.0, ps_replay=False,
                     ps_reconnect_backoff=0.2).items():
        config.set_flag(k, v)
    rdv = FileRendezvous(str(tmp_path / "rdv"))
    ctx0 = PSContext(0, 2, PSService(0, 2, rdv))
    ctx1 = PSContext(1, 2, PSService(1, 2, rdv))
    if not table:
        return ctx0, ctx1
    t0 = AsyncMatrixTable(16, 4, name="pl", ctx=ctx0)
    AsyncMatrixTable(16, 4, name="pl", ctx=ctx1)
    return ctx0, ctx1, t0


class TestSignalsAndAutoscale:
    def _mvautoscale(self):
        if TOOLS not in sys.path:
            sys.path.insert(0, TOOLS)
        import mvautoscale
        return mvautoscale

    def test_bus_subscribe_latest_and_filter(self):
        bus = signals.SignalBus()
        seen, shed_only = [], []
        unsub = bus.subscribe(seen.append)
        bus.subscribe(shed_only.append, name="shed_rate")
        sigs = [signals.Signal("shed_rate", "pl", 0.5, 1.0, {}),
                signals.Signal("queue_depth", "pl", 3.0, 1.0, {})]
        bus.publish(sigs)
        assert [s.name for s in seen] == ["shed_rate", "queue_depth"]
        assert [s.name for s in shed_only] == ["shed_rate"]
        assert bus.latest("queue_depth", "pl").value == 3.0
        snap = bus.snapshot()
        assert snap["shed_rate"]["pl"]["value"] == 0.5
        unsub()
        bus.publish([signals.Signal("shed_rate", "pl", 0.1, 2.0, {})])
        assert len(seen) == 2            # unsubscribed: no new delivery

    def test_from_record_burn_rate_rides_the_slo_block(self):
        rec = {"ts": 5.0, "slo": {
            "objectives": {"a": {"burn_fast": 3.0},
                           "b": {"burn_fast": 7.0}},
            "firing": ["b"]}}
        sigs = {s.name: s for s in signals.from_record(rec)}
        assert sigs["burn_rate"].value == 7.0
        assert sigs["burn_rate"].detail["objective"] == "b"
        assert sigs["burn_rate"].detail["firing"] == ["b"]

    def test_live_pool_publishes_and_recommends(self, tmp_path):
        """The whole seam on a real 2-rank world: pool with one warm
        spare -> aggregator polls publish typed signals on the process
        bus -> mvautoscale.recommend turns the snapshot into a
        verdict. Quiet 2-active pool = an actionable shrink; injected
        shed pressure = grow (spare available) or a non-actionable
        hold (spares exhausted)."""
        mvautoscale = self._mvautoscale()
        ctx0, ctx1, t0 = _live_world(tmp_path, table=True)
        pool = ReplicaPool(t0, replicas=2, spares=1, refresh_s=0.1,
                           probe_s=0.1, staleness_s=5.0, start=True)
        try:
            t0.add_rows(np.arange(16),
                        np.ones((16, 4), np.float32))
            t0.flush()
            time.sleep(0.25)
            pool.get_rows([1, 2, 3])
            agg = aggregator.ClusterAggregator(ctx0.service)
            agg.poll_once()
            time.sleep(0.15)
            rec = agg.poll_once()       # second poll: windowed rates
            snap = signals.snapshot()   # the aggregator published it
            assert snap["spares_left"]["pl"]["value"] == 1.0
            assert snap["active_replicas"]["pl"]["value"] == 2.0
            assert "queue_depth" in snap
            # the CLI's derivation is the same pure path
            cli_snap = mvautoscale.snapshot_from_record(rec)
            assert cli_snap["spares_left"]["pl"]["value"] == 1.0
            verdict = mvautoscale.recommend(snap)
            assert verdict["action"] == "shrink"    # quiet 2>1 pool
            assert verdict["actionable"]
            # inject shed pressure: grow while the warm spare lasts
            snap["shed_rate"] = {"pl": {"value": 0.4, "ts": 0.0,
                                        "detail": {}}}
            grow = mvautoscale.recommend(snap)
            assert grow["action"] == "grow" and grow["actionable"]
            assert "shed_rate[pl]" in grow["reason"]
            snap["spares_left"]["pl"]["value"] = 0.0
            starved = mvautoscale.recommend(snap)
            assert starved["action"] == "hold"
            assert not starved["actionable"]
            assert "no warm spares" in starved["reason"]
        finally:
            pool.close()
            ctx0.close()
            ctx1.close()

    def test_recommend_is_conservative_without_signals(self):
        mvautoscale = self._mvautoscale()
        verdict = mvautoscale.recommend({})
        assert verdict["action"] == "hold"
        assert not verdict["actionable"]

    def test_cli_refuses_without_dry_run(self, capsys):
        mvautoscale = self._mvautoscale()
        assert mvautoscale.main(["--rdv", "/nonexistent"]) == 2
        assert "dry-run" in capsys.readouterr().err


# ---------------------------------------------------------------------- #
# mvtop SLO panel + --assert-slo
# ---------------------------------------------------------------------- #
class TestMvtopSlo:
    def _mvtop(self):
        if TOOLS not in sys.path:
            sys.path.insert(0, TOOLS)
        import mvtop
        return mvtop

    def test_render_shows_objectives_straggler_and_signals(self,
                                                          tmp_path):
        mvtop = self._mvtop()
        ctx0, ctx1 = _live_world(tmp_path)
        try:
            agg = aggregator.ClusterAggregator(ctx0.service)
            rec = agg.poll_once()
            rec["slo"] = {
                "objectives": {"embed-avail": {
                    "kind": "availability", "table": "embed",
                    "firing": True, "episodes": 2, "burn_fast": 6.1,
                    "burn_slow": 1.4, "value": 0.0}},
                "firing": ["embed-avail"], "episodes": 2, "evals": 40,
                "straggler": {"rank": 1, "attribution": "wire",
                              "top_phase": None, "score": 1.7,
                              "components": {}},
                "recent": [{"kind": "slo.fired",
                            "objective": "embed-avail", "episode": 2,
                            "ts": 9.5}]}
            out = mvtop.render(rec)
            assert "slo:" in out and "embed-avail" in out
            assert "FIRING" in out
            assert "straggler" in out and "wire" in out
        finally:
            ctx0.close()
            ctx1.close()

    def test_assert_slo_exit_codes(self, tmp_path, capsys):
        """``--once --assert-slo`` against a LIVE world: exit 0 while
        the (armed) sentinel is clean, 3 the moment an objective
        fires — the per-rank stats payload carries the sentinel block
        through mvtop's one-shot merge."""
        mvtop = self._mvtop()
        ctx0, ctx1 = _live_world(tmp_path)
        rdv_dir = str(tmp_path / "rdv")
        try:
            slo.arm({"objectives": [_stall_obj()]})
            argv = ["--rdv", rdv_dir, "--once", "--assert-slo"]
            assert mvtop.main(argv) == 0         # armed but clean
            # drive the global sentinel into firing on synthetic polls
            hist = [_stall_rec(t, 0.9) for t in range(5)]
            for i in range(len(hist)):
                slo.SENTINEL.on_poll(hist[i], hist[:i + 1])
            assert slo.stats_snapshot()["firing"] == ["stall"]
            assert mvtop.main(argv) == 3
            assert "SLO firing" in capsys.readouterr().err
        finally:
            ctx0.close()
            ctx1.close()


# ---------------------------------------------------------------------- #
# run_bench: an objective that fired now-but-not-before is flagged
# ---------------------------------------------------------------------- #
class TestRunBenchFlag:
    def _flag(self, old_eps, new_eps):
        if TOOLS not in sys.path:
            sys.path.insert(0, TOOLS)
        import run_bench
        mk = lambda eps: {"extra": {"slo": {"episodes": eps}}}  # noqa
        return [f for f in run_bench.flag_regressions(
            mk(old_eps), mk(new_eps)) if "SLO objective" in f]

    def test_new_episode_flags_by_name(self):
        out = self._flag({"avail": 0}, {"avail": 2})
        assert len(out) == 1 and "'avail'" in out[0]

    def test_known_or_absent_episodes_stay_silent(self):
        assert self._flag({"avail": 1}, {"avail": 3}) == []
        assert self._flag({"avail": 0}, {"avail": 0}) == []


# ---------------------------------------------------------------------- #
# check_obs_surface lint 7: no dark kinds, no dark signals
# ---------------------------------------------------------------------- #
class TestLint7:
    def _lint(self):
        if TOOLS not in sys.path:
            sys.path.insert(0, TOOLS)
        import check_obs_surface
        return check_obs_surface

    def test_repo_surface_is_clean(self):
        assert self._lint().slo_surface_findings() == []

    def test_registries_read_by_ast_match_the_modules(self):
        lint = self._lint()
        assert tuple(lint.module_tuple(
            "multiverso_tpu/telemetry/slo.py", "OBJECTIVE_KINDS")) \
            == slo.OBJECTIVE_KINDS
        assert tuple(lint.module_tuple(
            "multiverso_tpu/telemetry/signals.py", "SIGNAL_NAMES")) \
            == signals.SIGNAL_NAMES

    def test_dark_kind_and_dark_signal_are_caught(self):
        lint = self._lint()
        found = lint.slo_surface_findings(
            kinds=["made_up_dark_kind"], signal_names=["shed_rate"])
        assert len(found) == 1 and "made_up_dark_kind" in found[0]
        # against an empty renderer EVERYTHING goes dark
        dark = lint.slo_surface_findings(renderer_text="")
        assert len(dark) == (len(slo.OBJECTIVE_KINDS)
                             + len(signals.SIGNAL_NAMES))
