"""Failure detection + elastic resume (SURVEY §5: the reference left this
at 'checkpoint files only'; here heartbeats/stragglers/resume are real)."""

import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu import elastic


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


class TestHeartbeat:
    def test_beat_and_peers(self, tmp_path):
        hb = elastic.Heartbeat(str(tmp_path), interval=60)
        hb.set_step(7)
        hb.beat()
        entries = elastic.peers(str(tmp_path))
        assert entries[hb.rank]["step"] == 7
        assert elastic.failed(str(tmp_path), timeout=30) == []

    def test_stale_rank_detected(self, tmp_path):
        hb = elastic.Heartbeat(str(tmp_path), interval=60, rank=3)
        hb.beat()
        time.sleep(0.05)
        assert elastic.failed(str(tmp_path), timeout=0.01) == [3]

    def test_stragglers(self, tmp_path):
        for rank, step in [(0, 100), (1, 98), (2, 50)]:
            hb = elastic.Heartbeat(str(tmp_path), interval=60, rank=rank)
            hb.set_step(step)
            hb.beat()
        assert elastic.stragglers(str(tmp_path), lag=10) == [2]
        assert elastic.stragglers(str(tmp_path), lag=60) == []

    def test_background_thread_beats(self, tmp_path):
        hb = elastic.Heartbeat(str(tmp_path), interval=0.02).start()
        try:
            time.sleep(0.1)
            first = elastic.peers(str(tmp_path))[hb.rank]["ts"]
            time.sleep(0.1)
            second = elastic.peers(str(tmp_path))[hb.rank]["ts"]
            assert second > first
        finally:
            hb.stop()

    def test_torn_write_ignored(self, tmp_path):
        (tmp_path / "heartbeat.9.json").write_text("{not json")
        assert elastic.peers(str(tmp_path)) == {}

    def test_beacon_carries_watchdog_verdict(self, tmp_path):
        """PR-4 satellite: the beacon embeds the local watchdog verdict
        (last_health) once the watchdog has run — an ALIVE beacon can
        then still report a wedged PS plane."""
        import time as _time

        from multiverso_tpu.telemetry import flightrec, watchdog
        from multiverso_tpu.utils import config
        hb = elastic.Heartbeat(str(tmp_path), interval=60, rank=1)
        hb.beat()
        assert "last_health" not in elastic.peers(str(tmp_path))[1]
        config.set_flag("watchdog_slow_ms", 50.0)
        config.set_flag("watchdog_stuck_s", 2.0)
        flightrec.RECORDER.begin_op(0, 5, 0x12)
        with flightrec.RECORDER._lock:   # backdate: wedged for 5 s
            t0, *rest = flightrec.RECORDER._inflight[(0, 5)]
            flightrec.RECORDER._inflight[(0, 5)] = (t0 - 5.0, *rest)
        assert watchdog.check_once()["status"] == "stuck"
        hb.beat()
        lh = elastic.peers(str(tmp_path))[1]["last_health"]
        assert lh["status"] == "stuck" and lh["oldest_inflight_s"] >= 5.0

    def test_health_distinguishes_dead_from_stuck(self, tmp_path):
        """PR-4 satellite regression, both paths: a STALE beacon is dead
        (elastic.failed semantics unchanged), a FRESH beacon carrying a
        stuck last_health is 'stuck' — alive, never in failed(), but a
        supervisor can act on it."""
        import json
        import os

        now = time.time()
        rows = [
            (0, {"rank": 0, "step": 1, "ts": now}),                # ok
            (1, {"rank": 1, "step": 1, "ts": now - 999}),          # dead
            (2, {"rank": 2, "step": 1, "ts": now,                  # stuck
                 "last_health": {"status": "stuck",
                                 "oldest_inflight_s": 42.0,
                                 "inflight": 3}}),
        ]
        for rank, entry in rows:
            with open(os.path.join(tmp_path,
                                   f"heartbeat.{rank}.json"), "w") as f:
                json.dump(entry, f)
        assert elastic.failed(str(tmp_path), timeout=30) == [1]
        verdicts = elastic.health(str(tmp_path), timeout=30)
        assert verdicts == {0: "ok", 1: "dead", 2: "stuck"}


class TestElasticLoop:
    def _train(self, table, loop, start, stop):
        for step in range(start, stop):
            table.add(np.full(table.shape, 1.0, np.float32))
            loop.completed(step)

    @pytest.mark.parametrize("backend,block", [("stream", True),
                                               ("orbax", True),
                                               ("orbax", False)])
    def test_resume_restores_table_state(self, tmp_path, backend, block):
        ckpt = str(tmp_path / "run")
        table = mv.ArrayTable(16, name="elastic_t")
        loop = elastic.ElasticLoop(ckpt, every=3, heartbeat_interval=60,
                                   backend=backend, block=block)
        assert loop.resume() == 0
        self._train(table, loop, 0, 10)  # checkpoints after steps 2,5,8
        loop.stop()
        mv.shutdown()

        # "restart the job": fresh runtime, same table creation order
        mv.init()
        table2 = mv.ArrayTable(16, name="elastic_t")
        loop2 = elastic.ElasticLoop(ckpt, every=3, heartbeat_interval=60,
                                    backend=backend, block=block)
        start = loop2.resume()
        assert start == 9  # step 8 was the last checkpoint
        np.testing.assert_allclose(table2.get(), np.full(16, 9.0))
        # finish the run; state ends identical to an uninterrupted one
        self._train(table2, loop2, start, 12)
        np.testing.assert_allclose(table2.get(), np.full(16, 12.0))
        loop2.stop()

    def test_prune_keeps_newest(self, tmp_path):
        ckpt = str(tmp_path / "run")
        mv.ArrayTable(4, name="elastic_p")
        loop = elastic.ElasticLoop(ckpt, every=1, keep=2,
                                   heartbeat_interval=60, backend="orbax")
        for step in range(5):
            loop.completed(step)
        import os
        tags = sorted(t for t in os.listdir(ckpt) if t.startswith("step_"))
        assert tags == ["step_000000003", "step_000000004"]
        loop.stop()
