"""Failure detection + elastic resume (SURVEY §5: the reference left this
at 'checkpoint files only'; here heartbeats/stragglers/resume are real)."""

import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu import elastic


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


class TestHeartbeat:
    def test_beat_and_peers(self, tmp_path):
        hb = elastic.Heartbeat(str(tmp_path), interval=60)
        hb.set_step(7)
        hb.beat()
        entries = elastic.peers(str(tmp_path))
        assert entries[hb.rank]["step"] == 7
        assert elastic.failed(str(tmp_path), timeout=30) == []

    def test_stale_rank_detected(self, tmp_path):
        hb = elastic.Heartbeat(str(tmp_path), interval=60, rank=3)
        hb.beat()
        time.sleep(0.05)
        assert elastic.failed(str(tmp_path), timeout=0.01) == [3]

    def test_stragglers(self, tmp_path):
        for rank, step in [(0, 100), (1, 98), (2, 50)]:
            hb = elastic.Heartbeat(str(tmp_path), interval=60, rank=rank)
            hb.set_step(step)
            hb.beat()
        assert elastic.stragglers(str(tmp_path), lag=10) == [2]
        assert elastic.stragglers(str(tmp_path), lag=60) == []

    def test_background_thread_beats(self, tmp_path):
        hb = elastic.Heartbeat(str(tmp_path), interval=0.02).start()
        try:
            time.sleep(0.1)
            first = elastic.peers(str(tmp_path))[hb.rank]["ts"]
            time.sleep(0.1)
            second = elastic.peers(str(tmp_path))[hb.rank]["ts"]
            assert second > first
        finally:
            hb.stop()

    def test_torn_write_ignored(self, tmp_path):
        (tmp_path / "heartbeat.9.json").write_text("{not json")
        assert elastic.peers(str(tmp_path)) == {}


class TestElasticLoop:
    def _train(self, table, loop, start, stop):
        for step in range(start, stop):
            table.add(np.full(table.shape, 1.0, np.float32))
            loop.completed(step)

    @pytest.mark.parametrize("backend,block", [("stream", True),
                                               ("orbax", True),
                                               ("orbax", False)])
    def test_resume_restores_table_state(self, tmp_path, backend, block):
        ckpt = str(tmp_path / "run")
        table = mv.ArrayTable(16, name="elastic_t")
        loop = elastic.ElasticLoop(ckpt, every=3, heartbeat_interval=60,
                                   backend=backend, block=block)
        assert loop.resume() == 0
        self._train(table, loop, 0, 10)  # checkpoints after steps 2,5,8
        loop.stop()
        mv.shutdown()

        # "restart the job": fresh runtime, same table creation order
        mv.init()
        table2 = mv.ArrayTable(16, name="elastic_t")
        loop2 = elastic.ElasticLoop(ckpt, every=3, heartbeat_interval=60,
                                    backend=backend, block=block)
        start = loop2.resume()
        assert start == 9  # step 8 was the last checkpoint
        np.testing.assert_allclose(table2.get(), np.full(16, 9.0))
        # finish the run; state ends identical to an uninterrupted one
        self._train(table2, loop2, start, 12)
        np.testing.assert_allclose(table2.get(), np.full(16, 12.0))
        loop2.stop()

    def test_prune_keeps_newest(self, tmp_path):
        ckpt = str(tmp_path / "run")
        mv.ArrayTable(4, name="elastic_p")
        loop = elastic.ElasticLoop(ckpt, every=1, keep=2,
                                   heartbeat_interval=60, backend="orbax")
        for step in range(5):
            loop.completed(step)
        import os
        tags = sorted(t for t in os.listdir(ckpt) if t.startswith("step_"))
        assert tags == ["step_000000003", "step_000000004"]
        loop.stop()
