"""Fault-injection wire plane + replica pools (ISSUE 14): seeded
determinism vs a recorded golden, the flag-off null path, live
injection e2e on the real wire (dup/reorder/partition/reset/slow-serve
with the exactly-once ledger asserted), the shared retry policy, the
ReplicaPool's routing/demotion/spare/bound-failover contracts, the
observability surfaces (serving block, aggregator pool passthrough,
mvtop pool panel, postmortem injected-vs-organic section), the
run_bench per-scenario recovery flag, and tier-1 smokes of the
in-process chaos scenarios. The full matrix incl. the OS-process
combined SIGKILL scenario runs as `slow` at the bottom."""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.ps import faults
from multiverso_tpu.ps import service as svc
from multiverso_tpu.ps.service import FileRendezvous, PSContext, PSService
from multiverso_tpu.ps.tables import AsyncMatrixTable
from multiverso_tpu.serving.pool import ReplicaPool
from multiverso_tpu.serving.replica import (BoundUnsatisfiableError,
                                            ReadReplica)
from multiverso_tpu.utils import config
from multiverso_tpu.utils import retry as retry_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _flags(**kw):
    base = dict(ps_native=False, ps_timeout=30.0,
                ps_connect_timeout=5.0, ps_reconnect_backoff=0.2)
    base.update(kw)
    for k, v in base.items():
        config.set_flag(k, v)


def _world(tmp_path, replay=True):
    _flags(ps_replay=replay, ps_replay_backoff=0.1,
           ps_replay_backoff_cap=0.5)
    rdv = FileRendezvous(str(tmp_path / "rdv"))
    ctx0 = PSContext(0, 2, PSService(0, 2, rdv))
    ctx1 = PSContext(1, 2, PSService(1, 2, rdv))
    t0 = AsyncMatrixTable(16, 4, name="ch", send_window_ms=1.0,
                          ctx=ctx0)
    t1 = AsyncMatrixTable(16, 4, name="ch", send_window_ms=1.0,
                          ctx=ctx1)
    return ctx0, ctx1, t0, t1


# ---------------------------------------------------------------------- #
# determinism + the null path (ISSUE 14 satellite)
# ---------------------------------------------------------------------- #
class TestDeterminism:
    SPEC = {"seed": 5, "rules": [
        {"kind": "duplicate", "src": 0, "dst": 1, "p": 0.4},
        {"kind": "drop", "src": 0, "dst": 1, "p": 0.2,
         "msg_types": ["MSG_ADD_ROWS"]},
    ]}

    def _drive(self, plane, n=64):
        for _ in range(n):
            plane.plan_send(1, svc.MSG_ADD_ROWS)
        return plane.log_snapshot()

    def test_same_seed_same_sequence(self):
        a = self._drive(faults.FaultPlane(self.SPEC, rank=0))
        b = self._drive(faults.FaultPlane(self.SPEC, rank=0))
        assert a == b and len(a) > 0

    def test_golden_sequence(self):
        """The injected sequence is a recorded GOLDEN, not merely
        self-consistent: a change to the decision function (hash, rule
        ordering, stream keying) must fail this test loudly — silent
        drift would un-reproduce every previously recorded chaos
        run."""
        log = self._drive(faults.FaultPlane(self.SPEC, rank=0), n=16)
        # note msg index 6: BOTH rules fire there, and the log records
        # only the drop — a dropped frame's duplicate never hits the
        # wire, and the injected log records what took effect
        assert log == [
            (0, "duplicate", 0, 1, svc.MSG_ADD_ROWS),
            (1, "duplicate", 0, 1, svc.MSG_ADD_ROWS),
            (2, "drop", 0, 1, svc.MSG_ADD_ROWS),
            (4, "duplicate", 0, 1, svc.MSG_ADD_ROWS),
            (5, "duplicate", 0, 1, svc.MSG_ADD_ROWS),
            (6, "drop", 0, 1, svc.MSG_ADD_ROWS),
            (7, "duplicate", 0, 1, svc.MSG_ADD_ROWS),
            (9, "duplicate", 0, 1, svc.MSG_ADD_ROWS),
            (14, "duplicate", 0, 1, svc.MSG_ADD_ROWS),
            (15, "duplicate", 0, 1, svc.MSG_ADD_ROWS),
        ]

    def test_different_seed_different_sequence(self):
        spec2 = dict(self.SPEC, seed=6)
        a = self._drive(faults.FaultPlane(self.SPEC, rank=0))
        b = self._drive(faults.FaultPlane(spec2, rank=0))
        assert a != b

    def test_rule_activation_never_shifts_other_streams(self):
        """A phase-gated rule flipping active must not change another
        rule's decisions for the same messages (counter-hash draws,
        not a shared stateful stream)."""
        # delay: effective alongside duplicate (no suppression), so
        # the duplicate stream must be IDENTICAL with the phased rule
        # active or not — the draws are per-rule counter-hashes, never
        # a shared stateful stream
        spec = {"seed": 5, "rules": [
            {"kind": "delay", "src": 0, "dst": 1, "p": 0.3,
             "delay_ms": 0.01, "phase": "on"},
            {"kind": "duplicate", "src": 0, "dst": 1, "p": 0.4}]}
        p1 = faults.FaultPlane(spec, rank=0)
        p2 = faults.FaultPlane(spec, rank=0)
        p2.phase = "on"   # direct: set_phase records a ring event
        for _ in range(64):
            p1.plan_send(1, svc.MSG_ADD_ROWS)
            p2.plan_send(1, svc.MSG_ADD_ROWS)
        dups1 = [e for e in p1.log_snapshot() if e[1] == "duplicate"]
        dups2 = [e for e in p2.log_snapshot() if e[1] == "duplicate"]
        assert dups1 == dups2
        assert any(e[1] == "delay" for e in p2.log_snapshot())
        assert not any(e[1] == "delay" for e in p1.log_snapshot())

    def test_bad_spec_fails_at_arm(self):
        with pytest.raises(ValueError):
            faults.FaultPlane({"rules": [{"kind": "nope"}]})
        with pytest.raises(ValueError):
            faults.FaultPlane({"rules": [
                {"kind": "drop", "msg_types": ["MSG_NOT_A_THING"]}]})
        with pytest.raises(ValueError):
            faults.FaultPlane({"rules": []})


class TestNullPath:
    def test_flag_off_is_null_object(self):
        assert faults.PLANE is faults.NULL
        assert faults.PLANE.armed is False
        assert faults.enabled() is False
        # the null object exposes NO injection surface at all — a hook
        # site that forgot the armed guard would crash loudly in tests
        # rather than silently injecting nothing
        assert not hasattr(faults.NULL, "plan_send")
        assert not hasattr(faults.NULL, "plan_serve")

    def test_configure_without_spec_stays_null(self):
        faults.configure(3)
        assert faults.PLANE is faults.NULL

    def test_flag_off_live_wire_records_no_faults(self, tmp_path):
        """Flag off ⇒ zero injection codepaths reachable on a live
        2-rank wire: no fault events on the ring, no held frames, no
        counters anywhere."""
        from multiverso_tpu.telemetry import flightrec
        ctx0, ctx1, t0, _t1 = _world(tmp_path, replay=False)
        try:
            ones = np.ones((1, 4), np.float32)
            for _ in range(20):
                t0.add_rows([9], ones)
            assert float(t0.get_rows([9])[0, 0]) == 20.0
            evs = {e[2] for e in flightrec.RECORDER.snapshot()}
            assert flightrec.EV_FAULT_INJECT not in evs
            assert flightrec.EV_FAULT_PLANE not in evs
            assert faults.PLANE.stats() == {}
        finally:
            ctx0.close()
            ctx1.close()

    def test_arm_disarm_records_plane_events(self):
        from multiverso_tpu.telemetry import flightrec
        faults.arm({"seed": 1, "rules": [
            {"kind": "drop", "p": 0.0}]})
        assert faults.enabled()
        faults.disarm()
        assert faults.PLANE is faults.NULL
        evs = [e for e in flightrec.RECORDER.snapshot()
               if e[2] == flightrec.EV_FAULT_PLANE]
        assert len(evs) >= 2

    def test_arm_from_flag_spec(self):
        config.set_flag("faults_spec", json.dumps(
            {"seed": 2, "rules": [{"kind": "drop", "p": 0.0}]}))
        faults.configure(0)
        try:
            assert faults.enabled()
            assert faults.PLANE.seed == 2
        finally:
            faults.disarm()

    def test_bad_flag_spec_is_loud_but_nonfatal(self):
        config.set_flag("faults_spec", "{not json")
        faults.configure(0)   # must not raise
        assert faults.PLANE is faults.NULL


# ---------------------------------------------------------------------- #
# shared retry policy (utils/retry.py)
# ---------------------------------------------------------------------- #
class TestBackoff:
    def test_capped_exponential(self):
        bo = retry_mod.Backoff(base_s=0.1, cap_s=0.8, jitter=0.0)
        assert [bo.delay_s(k) for k in range(5)] == \
            [0.1, 0.2, 0.4, 0.8, 0.8]

    def test_jitter_bounds(self):
        bo = retry_mod.Backoff(base_s=1.0, cap_s=1.0, jitter=0.5,
                               seed=3)
        for k in range(50):
            d = bo.delay_s(k)
            assert 0.5 <= d <= 1.5

    def test_seeded_jitter_reproducible(self):
        a = retry_mod.Backoff(base_s=1.0, cap_s=8.0, jitter=0.5, seed=7)
        b = retry_mod.Backoff(base_s=1.0, cap_s=8.0, jitter=0.5, seed=7)
        assert [a.delay_s(k) for k in range(8)] == \
            [b.delay_s(k) for k in range(8)]

    def test_deadline_propagation(self):
        bo = retry_mod.Backoff(base_s=10.0, cap_s=10.0, jitter=0.0)
        dl = retry_mod.deadline_in(0.05)
        # the delay clamps to the remaining budget, never past it
        assert bo.delay_s(0, dl) <= 0.05
        time.sleep(0.06)
        assert retry_mod.Backoff.expired(dl)
        assert bo.sleep(0, dl) is False   # nothing slept
        assert retry_mod.remaining_s(dl) == 0.0
        assert retry_mod.remaining_s(None, default=3.0) == 3.0

    def test_call_with_retries_last_error_raises(self):
        calls = []

        def fn():
            calls.append(1)
            raise OSError("nope")

        bo = retry_mod.Backoff(base_s=0.001, cap_s=0.001, jitter=0.0)
        with pytest.raises(OSError):
            retry_mod.call_with_retries(fn, attempts=3, backoff=bo)
        assert len(calls) == 3

    def test_call_with_retries_succeeds_midway(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 2:
                raise TimeoutError("again")
            return "ok"

        bo = retry_mod.Backoff(base_s=0.001, cap_s=0.001, jitter=0.0)
        assert retry_mod.call_with_retries(fn, attempts=3,
                                           backoff=bo) == "ok"
        assert len(calls) == 2

    def test_replay_episode_attempts_drive_exponent(self):
        """The replay plane's scheduling uses episode attempts as the
        backoff exponent (tables._replay_backoff) — delays grow, then
        reset with the episode."""
        from multiverso_tpu.ps.tables import (_replay_backoff,
                                              _RetainedFrame)
        config.set_flag("ps_replay_backoff", 0.1)
        config.set_flag("ps_replay_backoff_cap", 0.4)
        bo = _replay_backoff()
        assert bo.base_s == pytest.approx(0.1)
        assert bo.cap_s == pytest.approx(0.4)
        fr = _RetainedFrame(1, 0, 0x11, {}, [], [])
        assert fr.episode_attempts == 0


# ---------------------------------------------------------------------- #
# live injection e2e (python wire plane)
# ---------------------------------------------------------------------- #
class TestLiveInjection:
    def test_dup_reorder_exactly_once(self, tmp_path):
        ctx0, ctx1, t0, _t1 = _world(tmp_path)
        try:
            plane = faults.arm({"seed": 3, "rules": [
                {"kind": "duplicate", "src": 0, "dst": 1, "p": 0.5,
                 "msg_types": ["MSG_ADD_ROWS", "MSG_BATCH"]},
                {"kind": "reorder", "src": 0, "dst": 1, "p": 0.3,
                 "msg_types": ["MSG_ADD_ROWS", "MSG_BATCH"]},
            ]}, rank=0)
            ones = np.ones((1, 4), np.float32)
            for i in range(80):
                t0.add_rows([8 + (i % 4)], ones)   # rank 1's rows
            t0.flush()
            final = t0.get_rows(np.arange(16))
            assert int(final[8:12, 0].sum()) == 80
            st = t0.server_stats(1)["shards"]["ch"]
            assert st.get("dup_frames", 0) > 0   # dups reached the
            inj = plane.stats()["injected"]      # shard and deduped
            assert inj.get("duplicate", 0) > 0
            assert inj.get("reorder", 0) > 0
        finally:
            ctx0.close()
            ctx1.close()

    def test_partition_heal_exactly_once(self, tmp_path):
        from multiverso_tpu.telemetry import flightrec
        ctx0, ctx1, t0, _t1 = _world(tmp_path)
        try:
            plane = faults.arm({"seed": 9, "rules": [
                {"kind": "partition", "src": 0, "dst": 1,
                 "phase": "cut"}]}, rank=0)
            ones = np.ones((1, 4), np.float32)
            for _ in range(10):
                t0.add_rows([9], ones)
            plane.set_phase("cut")
            mids = [t0.add_rows_async([9], ones) for _ in range(4)]
            time.sleep(0.5)
            # partitioned: the acks are still pending (replay armed)
            plane.set_phase(None)
            for m in mids:
                t0.wait(m)
            t0.flush()
            assert float(t0.get_rows([9])[0, 0]) == 14.0
            assert plane.stats()["injected"].get("partition", 0) > 0
            # the injected faults are on the ring, distinguishable
            evs = [e for e in flightrec.RECORDER.snapshot()
                   if e[2] == flightrec.EV_FAULT_INJECT]
            assert any((e[7] or "").startswith("partition")
                       for e in evs)
        finally:
            ctx0.close()
            ctx1.close()

    def test_reset_injection_replays(self, tmp_path):
        ctx0, ctx1, t0, _t1 = _world(tmp_path)
        try:
            faults.arm({"seed": 1, "rules": [
                {"kind": "reset", "src": 0, "dst": 1, "max_count": 2,
                 "msg_types": ["MSG_ADD_ROWS", "MSG_BATCH"]}]}, rank=0)
            ones = np.ones((1, 4), np.float32)
            for _ in range(20):
                t0.add_rows([10], ones)
            t0.flush()
            assert float(t0.get_rows([10])[0, 0]) == 20.0
            assert faults.PLANE.stats()["injected"]["reset"] == 2
        finally:
            ctx0.close()
            ctx1.close()

    def test_slow_serve_injection(self, tmp_path):
        ctx0, ctx1, t0, _t1 = _world(tmp_path, replay=False)
        try:
            ones = np.ones((1, 4), np.float32)
            t0.add_rows([8], ones)   # warm the path uninjected
            t1 = time.perf_counter()
            t0.get_rows([8])
            fast = time.perf_counter() - t1
            faults.arm({"seed": 2, "rules": [
                {"kind": "slow_serve", "rank": 1, "delay_ms": 120,
                 "msg_types": ["MSG_GET_ROWS"]}]}, rank=0)
            t2 = time.perf_counter()
            t0.get_rows([8])
            slow = time.perf_counter() - t2
            assert slow > fast + 0.1
            assert faults.PLANE.stats()["injected"][
                "slow_serve"] >= 1
        finally:
            ctx0.close()
            ctx1.close()

    def test_drop_reply_applies_once_under_replay(self, tmp_path):
        """drop_reply = the ack lost AFTER the apply: the client's
        replayed frame must dedupe at the shard — the op lands exactly
        once even though it was served twice."""
        _flags(ps_replay=True, ps_replay_backoff=0.1,
               ps_replay_backoff_cap=0.3, ps_replay_timeout=20.0,
               ps_timeout=3.0)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctx0 = PSContext(0, 2, PSService(0, 2, rdv))
        ctx1 = PSContext(1, 2, PSService(1, 2, rdv))
        t0 = AsyncMatrixTable(16, 4, name="ch", send_window_ms=1.0,
                              ctx=ctx0)
        AsyncMatrixTable(16, 4, name="ch", send_window_ms=1.0,
                         ctx=ctx1)
        try:
            faults.arm({"seed": 4, "rules": [
                {"kind": "drop_reply", "rank": 1, "max_count": 1,
                 "msg_types": ["MSG_ADD_ROWS", "MSG_BATCH"]}]},
                rank=0)
            mid = t0.add_rows_async([8], np.ones((1, 4), np.float32))
            # the first serve's reply is dropped; the waiter times out
            # at ps_timeout=3s, the frame re-arms (PSPeerError inside
            # the replay window)... but timeouts alone do NOT re-arm —
            # only the conn death does. Force it by closing the peer:
            time.sleep(0.3)   # let the (reply-dropped) serve apply
            with ctx0.service._peers_lock:
                peer = ctx0.service._peers.get(1)
            assert peer is not None
            import socket as socket_mod
            peer._sock.shutdown(socket_mod.SHUT_RDWR)   # wake the recv
            peer._sock.close()   # loop: conn dies -> replay re-arms
            t0.wait(mid)
            t0.flush()
            assert float(t0.get_rows([8])[0, 0]) == 1.0   # once, not 2
            st = t0.server_stats(1)["shards"]["ch"]
            assert st.get("dup_frames", 0) >= 1
        finally:
            faults.disarm()
            ctx0.close()
            ctx1.close()

    def test_injection_hook_cost_when_disarmed(self, tmp_path):
        """The armed-guard is one attribute read: a disarmed plane adds
        nothing measurable to the windowed add path (the band gate
        itself lives in bench_small_add; this is the sanity check)."""
        ctx0, ctx1, t0, _t1 = _world(tmp_path, replay=False)
        try:
            ones = np.ones((1, 4), np.float32)
            for _ in range(5):
                t0.add_rows([8], ones)
            t1 = time.perf_counter()
            for _ in range(50):
                t0.add_rows([8], ones)
            dt = (time.perf_counter() - t1) / 50
            assert dt < 0.05   # sanity ceiling, not the band
        finally:
            ctx0.close()
            ctx1.close()


# ---------------------------------------------------------------------- #
# ReplicaPool
# ---------------------------------------------------------------------- #
def _pool_world(tmp_path, rows=16, dim=4, **pool_kw):
    _flags(ps_replay=False)
    rdv = FileRendezvous(str(tmp_path / "rdv"))
    ctx0 = PSContext(0, 2, PSService(0, 2, rdv))
    ctx1 = PSContext(1, 2, PSService(1, 2, rdv))
    t0 = AsyncMatrixTable(rows, dim, name="pl", ctx=ctx0)
    AsyncMatrixTable(rows, dim, name="pl", ctx=ctx1)
    kw = dict(replicas=2, refresh_s=0.1, staleness_s=2.0,
              probe_s=0.1, start=True)
    kw.update(pool_kw)
    pool = ReplicaPool(t0, **kw)
    return ctx0, ctx1, t0, pool


class TestReplicaPool:
    def test_least_staleness_routing_and_parity(self, tmp_path):
        ctx0, ctx1, t0, pool = _pool_world(tmp_path)
        try:
            t0.add_rows(np.arange(16),
                        np.arange(64, dtype=np.float32).reshape(16, 4))
            t0.flush()
            time.sleep(0.3)
            rows, age = pool.get_rows(np.arange(16), with_age=True)
            direct = t0.get_rows(np.arange(16))
            assert np.array_equal(rows, direct)
            assert age <= pool.staleness_s
            ent = pool.stats_entry()
            assert ent["pool"]["active"] == 2
            assert sum(m["routed"]
                       for m in ent["pool"]["members"]) == 1
        finally:
            pool.close()
            ctx0.close()
            ctx1.close()

    def test_kill_replica_demotes_and_routes_around(self, tmp_path):
        ctx0, ctx1, t0, pool = _pool_world(tmp_path, spares=1)
        try:
            t0.add_rows([3], np.ones((1, 4), np.float32))
            t0.flush()
            time.sleep(0.3)
            pool.kill_replica(0)
            # reads keep serving (sibling + activated spare)
            for _ in range(5):
                rows = pool.get_rows([3])
                assert float(rows[0, 0]) == 1.0
            phases = [p for _, p, _ in pool.events]
            assert "demote" in phases
            assert "spare_activated" in phases
            ent = pool.stats_entry()["pool"]
            assert ent["degraded"] == 1
            assert ent["spares_left"] == 0
            # the killed member is never routed to again
            killed = ent["members"][0]
            routed_before = killed["routed"]
            pool.get_rows([3])
            assert pool.stats_entry()["pool"]["members"][0][
                "routed"] == routed_before
        finally:
            pool.close()
            ctx0.close()
            ctx1.close()

    def test_bound_unsatisfiable_fails_over_to_sibling(self, tmp_path):
        """ISSUE 14 satellite: a member raising BoundUnsatisfiable
        (pull slower than its private bound) is demoted and the
        sibling serves — the caller never sees the error while ANY
        member is in bound."""
        ctx0, ctx1, t0, pool = _pool_world(tmp_path)
        try:
            t0.add_rows([5], np.ones((1, 4), np.float32))
            t0.flush()
            time.sleep(0.3)
            # wedge member 0 into bound-unsatisfiable: a private
            # absurdly-small bound, pulls can't keep it
            sick = pool._members[0].replica
            sick.staleness_s = 1e-9
            ok = pool.get_rows([5])
            assert float(ok[0, 0]) == 1.0
            # and when the WHOLE pool is over bound, the typed error
            # surfaces
            for m in pool._members:
                m.replica.staleness_s = 1e-9
            pool.staleness_s = 1e-9
            with pytest.raises(Exception) as ei:
                for _ in range(4):   # burn through every candidate
                    pool.get_rows([5])
            assert isinstance(ei.value,
                              (BoundUnsatisfiableError, RuntimeError))
        finally:
            pool.close()
            ctx0.close()
            ctx1.close()

    def test_health_loop_demotes_on_pull_failures_and_repromotes(
            self, tmp_path):
        # probe_s huge: this test drives check_health() by hand, and
        # the background loop's own probe (which succeeds against the
        # healthy service) must not re-promote between the two calls
        ctx0, ctx1, t0, pool = _pool_world(tmp_path, demote_after=2,
                                           probe_s=999.0)
        try:
            t0.add_rows([2], np.ones((1, 4), np.float32))
            t0.flush()
            time.sleep(0.3)
            m0 = pool._members[0]
            # simulate failing background pulls
            m0.replica._consec_pull_failures = 5
            pool.check_health()
            assert m0.degraded
            # recovery: failures clear, a probe refresh re-promotes
            m0.replica._consec_pull_failures = 0
            pool.check_health()
            assert not m0.degraded
            assert [p for _, p, _ in pool.events] == ["demote",
                                                      "promote"]
        finally:
            pool.close()
            ctx0.close()
            ctx1.close()

    def test_admission_enforced_once_at_pool_surface(self, tmp_path):
        from multiverso_tpu.serving.admission import (
            AdmissionController, SheddingError)
        adm = AdmissionController()
        adm.set_limit("pl", "infer", 0.001, burst=1.0)
        ctx0, ctx1, t0, pool = _pool_world(tmp_path, admission=adm)
        try:
            t0.add_rows([1], np.ones((1, 4), np.float32))
            t0.flush()
            time.sleep(0.3)
            pool.get_rows([1])          # burst token
            with pytest.raises(SheddingError):
                for _ in range(50):
                    pool.get_rows([1])
            # a shed never demotes anyone (policy, not health)
            assert pool.stats_entry()["pool"]["degraded"] == 0
        finally:
            pool.close()
            ctx0.close()
            ctx1.close()

    def test_bind_failover_rejoin_kicks_resync(self, tmp_path):
        # probe_s huge: check_health() is driven by hand, so the
        # epoch delta below is attributable to the rejoin kick alone
        ctx0, ctx1, t0, pool = _pool_world(tmp_path, probe_s=999.0)
        try:
            t0.add_rows([7], np.ones((1, 4), np.float32))
            t0.flush()
            time.sleep(0.3)
            # quiesce the background refresh threads too — the kick
            # must be the only thing that can advance the epoch
            for m in pool._members:
                m.replica._stop.set()
                m.replica._thread.join(timeout=5)
            time.sleep(0.05)

            class _Sup:   # supervisor-shaped: events list
                events = []

            sup = _Sup()
            pool.bind_failover(sup)
            e0 = pool._members[0].replica._epoch
            # no rejoin event: no kick, epoch must NOT advance
            pool.check_health()
            assert pool._members[0].replica._epoch == e0
            # a rejoin forces a FRESH pull even though the snapshot is
            # comfortably inside the bound
            sup.events.append((time.time(), "rejoin", 1))
            pool.check_health()
            assert pool._members[0].replica._epoch > e0
        finally:
            pool.close()
            ctx0.close()
            ctx1.close()

    def test_serving_block_carries_pool_entry(self, tmp_path):
        from multiverso_tpu.serving import replica as replica_mod
        ctx0, ctx1, t0, pool = _pool_world(tmp_path)
        try:
            t0.add_rows([1], np.ones((1, 4), np.float32))
            t0.flush()
            time.sleep(0.3)
            pool.get_rows([1])
            snap = replica_mod.stats_snapshot()
            assert "pl" in snap
            ent = snap["pl"]
            # the POOL entry won (not a bare member's): it carries the
            # merged counters AND the pool detail block
            assert "pool" in ent
            assert ent["served"] >= 1
            assert len(ent["pool"]["members"]) == 2
            # and it rides MSG_STATS end-to-end
            payload = ctx0.service.stats_payload()
            assert payload["serving"]["pl"]["pool"]["active"] == 2
        finally:
            pool.close()
            ctx0.close()
            ctx1.close()


# ---------------------------------------------------------------------- #
# observability surfaces
# ---------------------------------------------------------------------- #
class TestObservability:
    def test_aggregator_passes_pool_through(self):
        from multiverso_tpu.telemetry import aggregator
        pool_block = {"members": [
            {"idx": 0, "active": True, "degraded": False,
             "routed": 7, "share": 0.7, "age_s": 0.1,
             "pull_failures": 0}],
            "active": 1, "degraded": 0, "spares_left": 1,
            "failovers": 0, "demotions": 0}
        stats = {0: {"rank": 0, "addr": "h:1", "pid": 11,
                     "monitors": {}, "shards": {},
                     "serving": {"pl": {
                         "epoch": 3, "age_s": 0.1, "bound_s": 2.0,
                         "served": 7, "shed": 0, "deferred": 0,
                         "cache_hits": 0, "cache_misses": 0,
                         "pool": pool_block}}}}
        health = {0: {"status": "ok", "addr": "h:1"}}
        rec = aggregator.merge_cluster(stats, health, world=1)
        srv = rec["serving"]["pl"]
        assert srv["pools"]["0"] == pool_block
        assert srv["replicas"]["0"]["pool"] == pool_block
        assert srv["served"] == 7

    def test_mvtop_renders_pool_panel(self):
        sys.path.insert(0, TOOLS)
        import mvtop
        rec = {"ts": time.time(), "world": 1, "polled": 1,
               "ranks": {"0": {"status": "ok", "addr": "h:1"}},
               "tables": {}, "monitors": {},
               "serving": {"pl": {
                   "replicas": {}, "served": 9, "shed": 0,
                   "deferred": 0, "cache_hits": 0, "cache_misses": 0,
                   "pools": {"0": {
                       "members": [
                           {"idx": 0, "active": True,
                            "degraded": False, "routed": 6,
                            "share": 0.667, "age_s": 0.12,
                            "pull_failures": 0},
                           {"idx": 1, "active": True,
                            "degraded": True, "routed": 3,
                            "share": 0.333, "age_s": 1.5,
                            "pull_failures": 4}],
                       "active": 2, "degraded": 1, "spares_left": 0,
                       "failovers": 2, "demotions": 1}}}}}
        out = mvtop.render(rec)
        assert "pool@rank0" in out
        assert "DEGRADED" in out
        assert "share 66.7%" in out
        assert "spares 0" in out

    def test_mvtop_renders_without_pool_block(self):
        sys.path.insert(0, TOOLS)
        import mvtop
        rec = {"ts": time.time(), "world": 1, "polled": 1,
               "ranks": {"0": {"status": "ok", "addr": "h:1"}},
               "tables": {}, "monitors": {},
               "serving": {"pl": {"replicas": {"0": {"epoch": 1}},
                                  "served": 1, "shed": 0}}}
        out = mvtop.render(rec)   # no KeyError without pools
        assert "serving" in out

    def test_postmortem_separates_injected_from_organic(self, tmp_path):
        sys.path.insert(0, TOOLS)
        import postmortem
        from multiverso_tpu.telemetry import flightrec
        config.set_flag("flightrec_dir", str(tmp_path))
        flightrec.configure(0)
        flightrec.record(flightrec.EV_FAULT_PLANE, note="armed seed=3")
        flightrec.record(flightrec.EV_FAULT_INJECT, peer=1,
                         msg_type=0x11, note="drop src=0")
        flightrec.record(flightrec.EV_FAULT_INJECT, peer=1,
                         msg_type=0x11, note="duplicate src=0")
        flightrec.record(flightrec.EV_PEER_DEAD, peer=1,
                         note="organic-looking death")
        path = flightrec.dump_global("chaos test")
        dumps = [postmortem.load_dump(path)]
        inj = postmortem.injected_faults(dumps)
        assert inj["injected"] == 2
        assert inj["by_kind"] == {"drop": 1, "duplicate": 1}
        report = postmortem.render_report(dumps)
        assert "INJECTED faults" in report
        assert "drop=1" in report and "duplicate=1" in report

    def test_msg_ev_coverage_has_fault_events(self):
        from multiverso_tpu.telemetry import flightrec
        assert flightrec.EV_FAULT_INJECT in \
            flightrec.MSG_EV_COVERAGE["MSG_ADD_ROWS"]
        assert flightrec.EV_FAULT_INJECT in \
            flightrec.MSG_EV_COVERAGE["MSG_BATCH"]
        assert flightrec.EV_NAMES[flightrec.EV_FAULT_INJECT] == \
            "fault.inject"
        assert flightrec.EV_NAMES[flightrec.EV_FAULT_PLANE] == \
            "fault.plane"

    def test_obs_surface_lint_clean(self):
        sys.path.insert(0, TOOLS)
        import check_obs_surface
        assert check_obs_surface.check() == []

    def test_run_bench_flags_scenario_recovery_growth(self):
        sys.path.insert(0, TOOLS)
        import run_bench
        prev = {"extra": {"chaos": {"scenarios": {
            "partition_heal": {"recovery_s": 0.4},
            "combined": {"recovery_s": 2.0}}}}}
        new = {"extra": {"chaos": {"scenarios": {
            "partition_heal": {"recovery_s": 3.0},   # >2x of 0.4
            "combined": {"recovery_s": 2.2},          # within band
            "brand_new": {"recovery_s": 9.9}}}}}      # no baseline
        flags = run_bench.flag_regressions(prev, new)
        assert any("partition_heal" in f for f in flags)
        assert not any("combined" in f for f in flags)
        assert not any("brand_new" in f for f in flags)

    def test_run_bench_scenario_flag_floors_baseline(self):
        sys.path.insert(0, TOOLS)
        import run_bench
        prev = {"extra": {"chaos": {"scenarios": {
            "replica_kill": {"recovery_s": 0.0}}}}}   # instant prior
        new = {"extra": {"chaos": {"scenarios": {
            "replica_kill": {"recovery_s": 1.0}}}}}   # 2x floor = .5
        flags = run_bench.flag_regressions(prev, new)
        assert any("replica_kill" in f for f in flags)
        # within the floored band: no flag
        new2 = {"extra": {"chaos": {"scenarios": {
            "replica_kill": {"recovery_s": 0.4}}}}}
        assert not any("replica_kill" in f
                       for f in run_bench.flag_regressions(prev, new2))


# ---------------------------------------------------------------------- #
# chaos scenario smokes (tier-1: short in-process runs through the
# REAL bench scenario bodies incl. their in-run gates)
# ---------------------------------------------------------------------- #
class TestScenarioSmokes:
    def _bc(self):
        sys.path.insert(0, TOOLS)
        import bench_chaos
        return bench_chaos

    def _run(self, fn, tmp_path, seconds):
        """Correctness gates (exactly-once, staleness, injection) are
        STRICT on every run; the recovery-to-90% gate compares rates
        measured seconds apart on a shared box whose load drifts more
        than 10% by itself, so that ONE gate gets a second attempt
        before failing — the same weather rule the PR-7 slow chaos
        test established."""
        last = None
        for attempt in range(2):
            r = fn(seconds=seconds,
                   tmp=os.path.join(str(tmp_path), str(attempt)))
            strict = {g: ok for g, ok in r["gates"].items()
                      if g != "recovery"}
            assert all(strict.values()), r["gates"]
            last = r
            if r["gates"].get("recovery", True):
                break
        assert last["gates"].get("recovery", True), last["gates"]
        return last

    def test_partition_heal_smoke(self, tmp_path):
        r = self._run(self._bc().scenario_partition_heal, tmp_path,
                      seconds=9.0)
        assert r["ops_lost"] == 0 and r["ops_double_applied"] == 0
        assert r["parity_bit_for_bit"]
        assert isinstance(r["recovery_s"], float)

    def test_dup_reorder_smoke(self, tmp_path):
        r = self._bc().scenario_dup_reorder(seconds=5.0,
                                            tmp=str(tmp_path))
        assert all(r["gates"].values()), r["gates"]
        assert r["dup_frames_deduped"] > 0

    def test_replica_kill_smoke(self, tmp_path):
        r = self._run(self._bc().scenario_replica_kill, tmp_path,
                      seconds=8.0)
        assert r["serving"]["over_bound_serves"] == 0
        assert r["serving"]["served"] > 0


@pytest.mark.slow
class TestFullMatrix:
    def test_full_chaos_matrix(self):
        """The whole matrix through the CLI, incl. the OS-process
        combined SIGKILL + replica-kill scenario — the ISSUE 14
        acceptance run."""
        import subprocess
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get(
            "PYTHONPATH", "")
        out = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "bench_chaos.py"),
             "14"], capture_output=True, text=True, timeout=900,
            env=env)
        res = None
        for line in out.stdout.splitlines():
            if line.startswith("RESULT "):
                res = json.loads(line[len("RESULT "):])
        assert out.returncode == 0, (out.stdout[-2000:],
                                     out.stderr[-2000:])
        assert res is not None
        assert res["gates_failed"] == []
        assert set(res["scenarios"]) == {
            "partition_heal", "dup_reorder", "slow_shard_shed",
            "replica_kill", "noisy_neighbor", "combined"}
        assert res["ops_lost"] == 0
        assert res["ops_double_applied"] == 0
        assert res["parity_bit_for_bit"]
