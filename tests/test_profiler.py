"""Step-profiler tier-1 coverage (ISSUE 9).

The profiler's value IS its math — so the interval-union, overlap, and
exclusive-nesting numbers are checked against brute-force oracles, the
recompile counters against a real jit forced to retrace mid-run, the
cross-thread attribution against threads contributing to another
thread's step, and the flag-off path against the zero-allocation
contract. The mvprof report/Perfetto tooling smokes on a LIVE 2-rank
PS world, and ``tools/check_obs_surface.py`` (the opcode/flag lint)
runs here so tier-1 fails when an opcode or flag ships without its
observability/doc surface.
"""

import json
import os
import sys
import threading
import time
import warnings

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from multiverso_tpu.telemetry import profiler as prof  # noqa: E402
from multiverso_tpu.utils import config  # noqa: E402


def _enable(rank=0):
    config.set_flag("step_profile", True)
    prof.configure(rank)


# ---------------------------------------------------------------------- #
# interval math vs brute-force oracles
# ---------------------------------------------------------------------- #
def _oracle_union(intervals, hi=1000):
    covered = np.zeros(hi, bool)
    for a, b in intervals:
        covered[int(a):int(b)] = True
    return int(covered.sum())


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_union_length_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 40))
    ivs = []
    for _ in range(n):
        a = int(rng.integers(0, 1000))
        b = int(rng.integers(0, 1000))
        ivs.append((min(a, b), max(a, b)))
    # integer endpoints -> the boolean-grid oracle is EXACT
    assert prof.union_length(ivs) == _oracle_union(ivs)


@pytest.mark.parametrize("seed", [10, 11, 12])
def test_intersect_length_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    ivs = []
    for _ in range(int(rng.integers(1, 30))):
        a = int(rng.integers(0, 1000))
        b = int(rng.integers(0, 1000))
        ivs.append((min(a, b), max(a, b)))
    s0, s1 = sorted(int(x) for x in rng.integers(0, 1000, 2))
    covered = np.zeros(1000, bool)
    for a, b in ivs:
        covered[a:b] = True
    oracle = int(covered[s0:s1].sum())
    assert prof.intersect_length((s0, s1), ivs) == oracle


def test_union_degenerate_cases():
    assert prof.union_length([]) == 0.0
    assert prof.union_length([(5, 5), (7, 3)]) == 0.0   # empty/reversed
    assert prof.union_length([(0, 10), (10, 20)]) == 20.0  # touching


# ---------------------------------------------------------------------- #
# step / phase / async semantics
# ---------------------------------------------------------------------- #
def test_nested_phase_exclusive_time():
    _enable()
    with prof.step("s"):
        with prof.phase("outer"):
            time.sleep(0.04)
            with prof.phase("inner"):
                time.sleep(0.03)
    r = prof.records()[-1]
    outer = r["phases"]["outer"]["ms"]
    inner = r["phases"]["inner"]["ms"]
    # inner's span debits outer: exclusive outer ~40 ms, inner ~30 ms
    assert 25 <= inner <= 60
    assert 25 <= outer <= 60
    # the union math still counts the overlapping second once
    assert r["attributed_ms"] <= r["wall_ms"] * 1.001
    assert r["attributed_fraction"] > 0.9


def test_overlap_credit_and_stall():
    _enable()
    with prof.step("s"):
        sp = prof.async_begin("ps.get")
        with prof.phase("compute"):
            time.sleep(0.05)
        sp.end()
        time.sleep(0.04)    # deliberate unmarked gap = stall
    r = prof.records()[-1]
    # the async span ran concurrently with compute: near-full credit
    assert r["async"]["ps.get"]["overlap_ms"] == pytest.approx(
        r["phases"]["compute"]["ms"], rel=0.25)
    # the 40 ms gap is stall, not attributed
    assert r["stall_ms"] > 25
    assert 0.25 < r["stall_fraction"] < 0.65


def test_async_span_open_at_step_end_is_clipped():
    _enable()
    with prof.step("s"):
        sp = prof.async_begin("ps.add")
        time.sleep(0.02)
        # NOT ended before the step closes
    r = prof.records()[-1]
    d = r["async"]["ps.add"]
    assert d["open"] == 1
    assert d["ms"] <= r["wall_ms"] * 1.001
    sp.end()   # late end after finalize: silently ignored
    assert prof.records()[-1] is r or prof.records()[-1] == r


def test_cross_thread_phase_and_async_attribution():
    _enable()
    with prof.step("consumer") as s:
        done = threading.Event()

        def producer():
            with prof.phase("io.produce", step=s):
                time.sleep(0.03)
            prof.note_async("io.batch", time.time() - 0.01, time.time(),
                            step=s)
            done.set()

        t = threading.Thread(target=producer)
        t.start()
        with prof.phase("compute"):
            time.sleep(0.04)
        done.wait(5)
        t.join(5)
    r = prof.records()[-1]
    # the producer thread's work landed on the consumer's step
    assert r["phases"]["io.produce"]["ms"] > 20
    assert "io.batch" in r["async"]
    # and overlapped compute (both slept concurrently)
    assert r["attributed_ms"] < (r["phases"]["io.produce"]["ms"]
                                 + r["phases"]["compute"]["ms"]) * 1.001


def test_note_async_attaches_to_current_any_step():
    """A thread with NO step of its own (sample_reader producer shape)
    attaches via attach="any" to the process's open step."""
    _enable()
    with prof.step("train"):
        t0 = time.time()
        time.sleep(0.01)

        def from_bare_thread():
            prof.note_async("io.produce", t0, time.time(), attach="any")

        t = threading.Thread(target=from_bare_thread)
        t.start()
        t.join(5)
    r = prof.records()[-1]
    assert "io.produce" in r["async"]


def test_phase_without_step_is_noop():
    _enable()
    with prof.phase("orphan"):
        time.sleep(0.001)
    assert prof.records() == []


# ---------------------------------------------------------------------- #
# flag-off zero-overhead path
# ---------------------------------------------------------------------- #
def test_flag_off_null_contexts_and_no_records():
    config.set_flag("step_profile", False)
    prof.configure(0)
    assert not prof.enabled()
    # the SAME shared null object every call: no per-call allocation
    assert prof.step() is prof.step()
    assert prof.phase("x") is prof.step("y")
    with prof.step("s") as s:
        assert s is None
        with prof.phase("p"):
            pass
    assert prof.async_begin("a") is None
    prof.note_async("n", 0.0, 1.0)
    prof.note_transfer(123)
    assert prof.records() == []
    assert prof.summary()["steps"] == 0
    assert prof.stats_snapshot() is None


# ---------------------------------------------------------------------- #
# jax counters: recompile attribution, donation, transfers
# ---------------------------------------------------------------------- #
def test_recompile_attribution_mid_run():
    import jax
    import jax.numpy as jnp
    _enable()
    f = jax.jit(lambda x: x * 2 + 1)
    prof.watch_jit("f", f)
    with prof.step("warm"):
        float(f(jnp.ones(8))[0])
    with prof.step("steady"):
        float(f(jnp.ones(8))[0])
    with prof.step("retrace"):
        float(f(jnp.ones(9))[0])    # new shape -> forced retrace
    recs = {r["name"]: r for r in prof.records()}
    assert recs["warm"]["jax"]["compiles"] >= 1
    assert recs["warm"]["jax"].get("retraces_by_fn", {}).get("f") == 1
    # the steady step triggered NOTHING
    assert recs["steady"]["jax"]["compiles"] == 0
    assert "retraces_by_fn" not in recs["steady"]["jax"]
    # the retrace is attributed to the step that triggered it
    assert recs["retrace"]["jax"]["compiles"] >= 1
    assert recs["retrace"]["jax"]["retraces_by_fn"]["f"] == 1
    # steady-state recompiles (past step index 0) flagged in summary
    assert prof.summary()["steady_recompiles"] >= 1


def test_concurrent_warmup_compiles_are_not_steady():
    """Two trainer threads whose FIRST steps overlap share one warm
    compile of the same jitted fn — window-delta classification would
    count it (possibly twice) as a steady recompile; the per-event
    rule (no steady while any thread's first step is open) must not."""
    import jax
    import jax.numpy as jnp
    _enable()
    f = jax.jit(lambda x: x * 3)
    start = threading.Barrier(2)

    def trainer():
        start.wait(5)
        with prof.step("train"):
            float(f(jnp.ones(16))[0])   # both threads race the compile
            time.sleep(0.05)            # keep the steps overlapping

    with prof.step("main_warm"):        # the MAIN thread's warmup step
        pass
    ts = [threading.Thread(target=trainer) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert prof.summary()["steady_recompiles"] == 0
    # but a compile fired AFTER every first step closed IS steady
    # (the main thread already spent its warmup exemption above)
    with prof.step("later"):
        float(f(jnp.ones(17))[0])       # new shape -> real retrace
    assert prof.summary()["steady_recompiles"] >= 1


def test_donation_rejection_and_transfer_counters():
    _enable()   # configure() re-wraps showwarning over pytest's capture
    with prof.step("s"):
        prof.note_transfer(1 << 20)
        old = warnings.filters[:]
        warnings.simplefilter("always")
        try:
            # catch_warnings would REPLACE showwarning and bypass the
            # hook — exactly the save/restore cycle install() re-wraps
            # after, but not DURING; plain warn goes through the hook
            warnings.warn("Some donated buffers were not usable: f32[8]")
        finally:
            warnings.filters[:] = old
    r = prof.records()[-1]
    assert r["jax"]["transfer_bytes"] == 1 << 20
    assert r["jax"]["donation_rejected"] >= 1


def test_jax_counters_public_hook():
    c = prof.jax_counters()
    for k in ("compiles", "compile_s", "traces", "donation_rejected",
              "transfer_bytes", "watched"):
        assert k in c


# ---------------------------------------------------------------------- #
# records / dumps / stats surfaces
# ---------------------------------------------------------------------- #
def test_dump_to_drains_and_appends(tmp_path):
    _enable(rank=2)
    for _ in range(3):
        with prof.step("s"):
            with prof.phase("p"):
                time.sleep(0.001)
    n = prof.dump_to(str(tmp_path))
    assert n == 3
    assert prof.dump_to(str(tmp_path)) == 0    # drained
    path = tmp_path / "profile-rank2.jsonl"
    recs = [json.loads(x) for x in path.read_text().splitlines()]
    assert len(recs) == 3 and all(r["kind"] == "step" for r in recs)
    assert all(r["rank"] == 2 for r in recs)
    # summary survives the drain
    assert prof.summary()["steps"] == 3


def test_stats_snapshot_shape_and_service_payload(two_ranks):
    _enable()
    with prof.step("s"):
        with prof.phase("compute"):
            time.sleep(0.002)
    snap = prof.stats_snapshot()
    assert snap["steps"] >= 1
    assert 0.0 <= snap["stall_fraction"] <= 1.0
    assert "compute" in snap["phases"]
    # the MSG_STATS payload carries the block (local + over the socket)
    payload = two_ranks[0].service.stats_payload()
    assert payload["profile"]["steps"] >= 1
    remote = two_ranks[0].service.stats(1)
    assert remote["profile"]["steps"] >= 1


def test_merge_cluster_passes_profile_and_mvtop_renders():
    from multiverso_tpu.telemetry import aggregator
    stats = {0: {"rank": 0, "addr": "h:1", "pid": 11, "monitors": {},
                 "shards": {},
                 "profile": {"steps": 5, "stall_fraction": 0.25,
                             "attributed_fraction": 0.9,
                             "steady_recompiles": 2, "compiles": 7,
                             "phases": {"compute": 10.0}}},
             1: {"rank": 1, "addr": "h:2", "pid": 12, "monitors": {},
                 "shards": {}}}
    health = {0: {"status": "ok", "addr": "h:1"},
              1: {"status": "ok", "addr": "h:2"}}
    rec = aggregator.merge_cluster(stats, health, world=2)
    assert rec["profile"]["0"]["steps"] == 5
    assert rec["ranks"]["0"]["stall_pct"] == 25.0
    assert rec["ranks"]["0"]["recompiles"] == 2
    assert "stall_pct" not in rec["ranks"]["1"]
    # compact record keeps the block for bench extra
    assert aggregator.compact_record(rec)["profile"]["0"]["steps"] == 5
    # mvtop's rank table shows the columns
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import mvtop
    text = mvtop.render(rec)
    assert "stall%" in text and "recomp" in text
    assert "25.0" in text


def test_dump_metrics_renders_profile_records(tmp_path):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import dump_metrics
    recs = [{"kind": "step", "step": i, "name": "we.block", "rank": 0,
             "ts": 100.0 + i, "wall_ms": 100.0, "attributed_ms": 95.0,
             "attributed_fraction": 0.95, "overlap_ms": 20.0,
             "stall_ms": 5.0, "stall_fraction": 0.05,
             "phases": {"prepare": {"ms": 60.0, "count": 1},
                        "compute": {"ms": 35.0, "count": 1}},
             "async": {}, "jax": {"compiles": 0}, "spans": []}
            for i in range(4)]
    p = tmp_path / "profile-rank0.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    text = dump_metrics.format_profile_records(recs)
    assert "prepare" in text and "stall" in text.lower()
    d = dump_metrics.diff_profile_records(recs, recs)
    assert "1.00" in d            # identical runs -> ratio 1.00
    # the CLI show path dispatches on kind == "step"
    assert dump_metrics.main(["show", str(p)]) == 0
    # per-rank stats records render an embedded profile block
    srec = {"rank": 0, "monitors": {}, "shards": {},
            "profile": {"steps": 3, "stall_fraction": 0.1,
                        "attributed_fraction": 0.9,
                        "steady_recompiles": 0,
                        "phases": {"compute": 12.0}}}
    out = dump_metrics.format_record(srec)
    assert "profile:" in out and "compute" in out


# ---------------------------------------------------------------------- #
# mvprof on a live 2-rank world (report + perfetto smoke)
# ---------------------------------------------------------------------- #
def test_mvprof_live_two_rank_world(tmp_path, two_ranks):
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.telemetry import trace as ttrace
    mdir = tmp_path / "metrics"
    config.set_flag("metrics_dir", str(mdir))
    config.set_flag("trace_ids", True)
    ttrace.configure()
    _enable()
    # a send window pins the client to the python conns (the native
    # fast path is untraced by design), so spans exist on BOTH fixture
    # planes — and the windowed ps.add async span path is exercised
    t0 = AsyncMatrixTable(64, 8, name="prof_t", send_window_ms=1.0,
                          ctx=two_ranks[0])
    AsyncMatrixTable(64, 8, name="prof_t", ctx=two_ranks[1])
    rng = np.random.default_rng(0)
    for i in range(3):
        with prof.step("train"):
            ids = rng.integers(32, 64, 4)   # remote rank's rows
            with prof.phase("prepare"):
                vals = rng.normal(size=(4, 8)).astype(np.float32)
            mid = t0.add_rows_async(ids, vals)
            with prof.phase("compute"):
                time.sleep(0.005)
            with prof.phase("ps_wait"):
                t0.wait(mid)
                rows = t0.get_rows(ids)
        assert rows.shape == (4, 8)
    recs = prof.records()
    assert len(recs) == 3
    # the table layer opened real ps.add / ps.get async spans
    assert any("ps.add" in r["async"] or "ps.get" in r["async"]
               for r in recs)
    prof.dump_to(str(mdir))
    ttrace.dump_to(str(mdir))

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import mvprof
    steps, spans = mvprof.collect([str(mdir)])
    assert len(steps) == 3 and len(spans) > 0
    report = mvprof.render_report(steps)
    assert "critical path" in report and "rank 0" in report
    data = mvprof.report_data(steps)
    assert data["ranks"]["0"]["steps"] == 3
    assert data["ranks"]["0"]["attributed_fraction"] > 0.5
    out = tmp_path / "prof.json"
    assert mvprof.main([str(mdir), "--to-perfetto", str(out),
                        "--report"]) == 0
    env = json.loads(out.read_text())
    evs = env["traceEvents"]
    # one track per phase per rank: named thread metadata + X spans
    names = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert {"step", "prepare", "compute", "ps_wait"} <= names
    assert any(e.get("ph") == "X" and e.get("cat") == "phase"
               for e in evs)
    # PR-3 trace spans merged onto the same timeline
    assert any(e.get("cat") in ("ps", "client") or "trace" in
               json.dumps(e.get("args", {})) for e in evs
               if e.get("ph") == "X")


def test_mvprof_no_records_exits_1(tmp_path):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import mvprof
    assert mvprof.main([str(tmp_path)]) == 1


def test_logreg_pipeline_steps_reach_io_wait(tmp_path):
    """The shipped LR file-training loop brackets steps, so the
    sample_reader io_wait phase (and the producer's io.produce spans)
    are reachable from a real pipeline — not only from tests."""
    import multiverso_tpu as mv
    from multiverso_tpu.apps.logistic_regression import (LogReg,
                                                         LogRegConfig)
    rng = np.random.default_rng(0)
    train = tmp_path / "train.txt"
    with open(train, "w") as f:
        for _ in range(200):
            w = rng.normal(size=6)
            f.write(f"{int(w[0] > 0)} " + " ".join(
                f"{i}:{v:.3f}" for i, v in enumerate(w)) + "\n")
    mv.init()
    _enable()
    cfg = LogRegConfig({"input_size": "6", "output_size": "2",
                        "minibatch_size": "64", "learning_rate": "0.1",
                        "train_epoch": "1", "objective_type": "softmax",
                        "train_file": str(train)})
    LogReg(cfg).train_file()
    recs = [r for r in prof.records() if r["name"] == "lr.minibatch"]
    assert recs, "LR file training produced no step records"
    assert all("io_wait" in r["phases"] for r in recs)


def test_dlrm_train_step_profiled(two_ranks):
    """The instrumented DLRM serving train_step produces a full step
    record: prepare/ps_wait/compute/push phases + the table layer's
    ps.get/ps.add async spans, attribution near 1."""
    from multiverso_tpu.apps.dlrm_serving import DLRMServing
    from multiverso_tpu.models import dlrm
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    _enable()
    cfg = dlrm.DLRMConfig(vocab_sizes=(32, 16), embed_dim=8,
                          dense_dim=4, bottom_mlp=(8,), top_mlp=(8, 1))
    app = DLRMServing(cfg, ctx=two_ranks[0], name="prof_dlrm", lr=0.2,
                      staleness_s=30.0, start_replica=False)
    peer = AsyncMatrixTable(dlrm.total_rows(cfg), cfg.embed_dim,
                            updater="adagrad", seed=0, init_scale=0.05,
                            name=app.emb.name, ctx=two_ranks[1])
    cat, dense, labels = dlrm.synthetic_ctr(cfg, 64, seed=3)
    for _ in range(2):
        app.train_step(cat, dense, labels)
    recs = [r for r in prof.records() if r["name"] == "dlrm.train_step"]
    assert len(recs) == 2
    r = recs[-1]
    for ph in ("prepare", "ps_wait", "compute", "push"):
        assert ph in r["phases"], r["phases"]
    assert "ps.get" in r["async"] and "ps.add" in r["async"]
    assert r["attributed_fraction"] > 0.9
    app.close()
    del peer


# ---------------------------------------------------------------------- #
# PR-8 coverage gap closed: snapshot serves / replica pulls on the tape
# ---------------------------------------------------------------------- #
def test_replica_pull_and_snapshot_serve_on_the_timeline(two_ranks):
    """MSG_SNAPSHOT serves and ReadReplica refreshes must emit PR-3
    trace spans and flightrec events like gets/adds (the satellite that
    motivated the check_obs_surface lint)."""
    from multiverso_tpu.ps.tables import AsyncMatrixTable
    from multiverso_tpu.serving.replica import ReadReplica
    from multiverso_tpu.telemetry import flightrec
    from multiverso_tpu.telemetry import trace as ttrace
    config.set_flag("trace_ids", True)
    ttrace.configure()
    t0 = AsyncMatrixTable(64, 8, name="rp_t", ctx=two_ranks[0])
    AsyncMatrixTable(64, 8, name="rp_t", ctx=two_ranks[1])
    t0.add_rows(np.arange(40, 44), np.ones((4, 8), np.float32))
    rep = ReadReplica(t0, start=False)
    try:
        rep.refresh()
        kinds = {s[2] for s in flightrec.RECORDER.snapshot()}
        assert flightrec.EV_REPLICA_PULL in kinds
        # both ranks live in this process: the serve side's event is on
        # the same ring (remote rank 1's shard served a real socket
        # snapshot; rank 0's local shard served in-process)
        assert flightrec.EV_SNAPSHOT_SERVE in kinds
        names = {e["name"] for e in ttrace.TRACER.events()}
        assert "replica.pull" in names
        assert "snapshot.serve" in names
        # the refresh's spans share ONE trace id (client/shard stitch)
        pulls = [e for e in ttrace.TRACER.events()
                 if e["name"] == "replica.pull"]
        serves = [e for e in ttrace.TRACER.events()
                  if e["name"] == "snapshot.serve"]
        assert pulls and serves
        assert any(s["args"].get("trace") == pulls[-1]["args"]["trace"]
                   for s in serves)
    finally:
        rep.close()


# ---------------------------------------------------------------------- #
# the obs-surface lint (satellite: tier-1 wraps the static check)
# ---------------------------------------------------------------------- #
def test_check_obs_surface_clean():
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import check_obs_surface
    findings = check_obs_surface.check()
    assert findings == [], "\n".join(findings)
    # the scanners actually see the surface (not vacuously clean)
    ops = check_obs_surface.wire_opcodes()
    assert "MSG_SNAPSHOT" in ops and "MSG_BATCH" in ops
    flags = check_obs_surface.defined_flags()
    assert "step_profile" in flags and "ps_timeout" in flags


def test_check_obs_surface_catches_gaps(monkeypatch, tmp_path):
    """A new opcode without coverage / a new flag without a TUNING row
    must be findings — the lint's reason to exist."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import check_obs_surface
    monkeypatch.setattr(
        check_obs_surface, "wire_opcodes",
        lambda: check_obs_surface.__dict__["_FAKE_OPS"], raising=False)
    check_obs_surface._FAKE_OPS = (
        sorted(set(list(__import__(
            "multiverso_tpu.telemetry.flightrec",
            fromlist=["x"]).MSG_EV_COVERAGE) + ["MSG_BRAND_NEW"])))
    findings = check_obs_surface.check()
    assert any("MSG_BRAND_NEW" in f for f in findings)


# ---------------------------------------------------------------------- #
# run_bench regression flags (satellite 6)
# ---------------------------------------------------------------------- #
def test_run_bench_flags_stall_and_recompiles():
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import run_bench

    def hl(stall, recompiles):
        return {"extra": {"profile": {"stall_fraction": stall,
                                      "steady_recompiles": recompiles}}}

    # >2x stall growth flagged
    out = run_bench.flag_regressions(hl(0.05, 0), hl(0.15, 0))
    assert any("stall" in f for f in out)
    # within band: silent
    assert run_bench.flag_regressions(hl(0.05, 0), hl(0.08, 0)) == []
    # a healthy 0.0 baseline must NOT suppress the flag forever: the
    # comparison floors the prior at _STALL_BASELINE_FLOOR
    out = run_bench.flag_regressions(hl(0.0, 0), hl(0.35, 0))
    assert any("stall" in f for f in out)
    assert run_bench.flag_regressions(hl(0.0, 0), hl(0.08, 0)) == []
    # ANY nonzero steady recompile count flagged, even with no prior
    out = run_bench.flag_regressions(None, hl(0.05, 3))
    assert any("recompile" in f for f in out)
    # never fails (returns strings, raises nothing) and zero is quiet
    assert run_bench.flag_regressions(hl(0.05, 0), hl(0.05, 0)) == []
