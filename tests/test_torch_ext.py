"""PyTorch binding (torch_ext) against the reference's param-manager
semantics (ref theano_ext/lasagne_ext/param_manager.py, sharedvar.py)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import multiverso_tpu as mv
from multiverso_tpu.torch_ext import TorchParamManager


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


def _model(seed=0):
    torch.manual_seed(seed)
    return torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.ReLU(),
                               torch.nn.Linear(8, 1))


def _flat(m):
    return np.concatenate([p.detach().numpy().reshape(-1)
                           for p in m.parameters()])


def test_master_init_seeds_table():
    m = _model()
    init = _flat(m).copy()
    mgr = TorchParamManager(m, name="tp_init")
    np.testing.assert_allclose(mgr.table.get()[: mgr.numel()], init,
                               rtol=1e-6)
    # write-back keeps the module identical
    np.testing.assert_allclose(_flat(m), init, rtol=1e-6)


def test_sync_pushes_delta_and_merges():
    m = _model()
    mgr = TorchParamManager(m, name="tp_sync")
    before = _flat(m).copy()
    with torch.no_grad():
        for p in m.parameters():
            p.add_(0.5)
    mgr.sync()
    # single worker: merged = before + delta
    np.testing.assert_allclose(_flat(m), before + 0.5, rtol=1e-5)
    # second sync with no local change is a no-op
    mgr.sync()
    np.testing.assert_allclose(_flat(m), before + 0.5, rtol=1e-5)


def test_training_through_sync_converges():
    """SGD on y = <w, x> with a sync every step still converges — i.e. the
    write-back path preserves optimizer progress."""
    torch.manual_seed(1)
    m = torch.nn.Linear(4, 1, bias=False)
    mgr = TorchParamManager(m, name="tp_train")
    opt = torch.optim.SGD(m.parameters(), lr=0.1)
    w_true = torch.tensor([[1.0, -2.0, 0.5, 3.0]])
    x = torch.randn(256, 4)
    y = x @ w_true.T
    for _ in range(100):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(m(x), y)
        loss.backward()
        opt.step()
        mgr.sync()
    np.testing.assert_allclose(m.weight.detach().numpy(),
                               w_true.numpy(), atol=0.05)


def test_pull_refreshes_from_global():
    m = _model()
    init = _flat(m).copy()
    mgr = TorchParamManager(m, name="tp_pull")
    # an out-of-band push (another worker in real deployments)
    delta = np.zeros(mgr.table.shape[0], np.float32)
    delta[: mgr.numel()] = 1.0
    mgr.table.add(delta)
    mgr.pull()
    np.testing.assert_allclose(_flat(m), init + 1.0, rtol=1e-5)


def test_paramless_module_ok():
    mgr = TorchParamManager(torch.nn.ReLU(), name="tp_empty")
    assert mgr.numel() == 0
    mgr.sync()  # no-op but must not crash
