"""Subprocess body for the multi-process integration test (tier-2 fixture:
the reference runs the same binary under ``mpirun -np N`` — here the same
script runs under N coordinated JAX processes; ref Test/main.cpp:497-518).

Invoked as: python multiprocess_worker.py <coordinator> <nprocs> <pid>
Prints one line of JSON results that the parent asserts on.
"""

import json
import sys


def main():
    coordinator, nprocs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)
    from multiverso_tpu.utils.platform import enable_cpu_collectives
    enable_cpu_collectives()   # gloo: cross-process CPU computations
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=nprocs, process_id=pid)
    import numpy as np

    import multiverso_tpu as mv
    from multiverso_tpu.sharedvar import mv_shared

    mv.init()
    out = {"rank": mv.rank(), "size": mv.size(),
           "num_workers": mv.num_workers(),
           "num_servers": mv.num_servers(),
           "devices": len(jax.devices())}

    # barrier (ref TestArray barrier fencing)
    mv.barrier()

    # aggregate: each process contributes rank+1 -> sum = N(N+1)/2
    data = np.full(4, float(pid + 1), np.float32)
    agg = mv.aggregate(data)
    out["aggregate"] = agg.tolist()

    # KV aggregated Get (ref kv_table.h:44-99 server-summed read): repeatable
    # and non-destructive, so two calls must agree and must not perturb the
    # local store that allreduce() then commits
    kv = mv.KVTable(name="mp_kv")
    kv.add(list(range(pid + 1)), [10] * (pid + 1))  # rank r adds r+1 keys
    gview = kv.get(global_=True)
    assert kv.get(global_=True) == gview
    out["kv_global"] = {str(k): float(v) for k, v in sorted(gview.items())}
    merged = kv.allreduce()
    out["kv"] = {str(k): float(v) for k, v in sorted(merged.items())}

    # collective matrix row add: same ids everywhere, vals summed
    mt = mv.MatrixTable(16, 4, name="mp_matrix")
    mt.add_rows([1, 3], np.full((2, 4), float(pid + 1), np.float32))
    out["matrix_rows"] = mt.get_rows([1, 3]).tolist()

    # collective row add with DIFFERENT id sets per process (the
    # WordEmbedding pattern): union semantics
    mt2 = mv.MatrixTable(16, 4, name="mp_matrix_union")
    mt2.add_rows([pid, pid + 1], np.full((2, 4), float(pid + 1), np.float32))
    out["matrix_union"] = mt2.get_rows(list(range(nprocs + 1)))[:, 0].tolist()

    # sparse stale-row protocol under DIFFERING per-rank id sets: rank p
    # adds only row p, but the dirty bits must cover the cross-process
    # union, or every other rank serves row p stale from its cache
    smt = mv.SparseMatrixTable(nprocs + 1, 4, name="mp_sparse_union",
                               num_workers=nprocs)
    all_rows = list(range(nprocs + 1))
    smt.get_rows_sparse(all_rows, worker_id=pid)      # warm the cache
    smt.add_rows([pid], np.ones((1, 4), np.float32))  # collective, union ids
    out["sparse_union"] = smt.get_rows_sparse(
        all_rows, worker_id=pid)[:, 0].tolist()

    # uncoordinated async plane over the jax.distributed coordinator's KV
    # store: each rank pushes its OWN disjoint rows at its own pace
    from multiverso_tpu.ps import AsyncMatrixTable
    at = AsyncMatrixTable(8 * nprocs, 4, name="mp_async_jx")
    # the default context under jax.distributed must have taken the
    # coordinator-KV rendezvous (ref Controller registration,
    # src/controller.cpp:38-80) — the multi-host path, explicitly
    from multiverso_tpu.ps.service import JaxRendezvous
    rdv = at.ctx.service._rendezvous
    out["rendezvous"] = type(rdv).__name__ if rdv is not None else None
    if nprocs > 1:
        assert isinstance(rdv, JaxRendezvous), rdv
        # publish/lookup round-trip through the coordinator KV store
        rdv.publish(1000 + pid, f"probe:{pid}")
        assert rdv.lookup(1000 + ((pid + 1) % nprocs), 20.0) == (
            f"probe:{(pid + 1) % nprocs}")
    my_rows = np.arange(8) * nprocs + pid
    for _ in range(pid + 1):   # per-rank rate
        at.add_rows(my_rows, np.ones((8, 4), np.float32))
    at.flush()
    mv.barrier()               # test determinism only: all pushes landed
    got = at.get_rows(np.arange(8 * nprocs))
    out["async_row_sum"] = float(got.sum())

    # sharedvar delta-sync across processes: every worker adds +1 to its
    # local copy; after sync the shared value reflects all workers' deltas
    shared = mv_shared({"w": np.zeros(4, np.float32)}, name="mp_shared")
    local = shared.get()
    local["w"] = local["w"] + 1.0
    merged_params = shared.sync(local)
    mv.barrier()
    final = shared.get()
    out["sharedvar"] = final["w"].tolist()

    mv.shutdown()
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
