"""Flight recorder + watchdog + health plane (PR 4): ring wrap/overflow
semantics, dump-on-fatal, watchdog slow/stuck thresholds against a
deliberately wedged op, the MSG_HEALTH RPC on a live 2-rank PS (native
punt + python path via the two_ranks params), postmortem merging on
synthetic dumps, and the 2-OS-process kill-one-rank acceptance: the
survivor's dump names the dead rank's oldest unacked (src, dst, msg id)
and tools/postmortem.py reads it out with no other logs."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from multiverso_tpu.telemetry import flightrec, watchdog
from multiverso_tpu.utils import config
from multiverso_tpu.utils import log as mvlog

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)
sys.path.insert(0, _REPO)

from tools import postmortem  # noqa: E402


# ---------------------------------------------------------------------- #
# ring-buffer semantics
# ---------------------------------------------------------------------- #
class TestRing:
    def test_wrap_keeps_last_n_in_order(self):
        fr = flightrec.FlightRecorder(slots=16)
        for i in range(40):
            fr.record(flightrec.EV_STATE, msg_id=i)
        snap = fr.snapshot()
        assert len(snap) == 16
        assert [s[5] for s in snap] == list(range(24, 40))   # last 16
        assert [s[0] for s in snap] == sorted(s[0] for s in snap)
        # monotonic stamps never go backwards within the ring
        ts = [s[1] for s in snap]
        assert ts == sorted(ts)

    def test_partial_fill_returns_only_recorded(self):
        fr = flightrec.FlightRecorder(slots=16)
        fr.record(flightrec.EV_SEND, peer=2, msg_id=7, nbytes=64)
        [s] = fr.snapshot()
        assert (s[2], s[3], s[5], s[6]) == (flightrec.EV_SEND, 2, 7, 64)

    def test_fixed_slots_no_growth(self):
        fr = flightrec.FlightRecorder(slots=16)
        before = len(fr._slots)
        for _ in range(1000):
            fr.record(flightrec.EV_STATE)
        assert len(fr._slots) == before == 16

    def test_inflight_begin_end(self):
        fr = flightrec.FlightRecorder(slots=32)
        fr.begin_op(1, 10, 0x12, nbytes=100)
        fr.begin_op(2, 11, 0x11, nbytes=200)
        assert len(fr.inflight_snapshot()) == 2
        fr.end_op(1, 10)
        (age, peer, mid, mt) = fr.oldest_inflight()
        assert (peer, mid, mt) == (2, 11, 0x11) and age >= 0
        fr.end_op(2, 11, ok=False)
        assert fr.oldest_inflight() is None
        evs = [s[2] for s in fr.snapshot()]
        assert evs == [flightrec.EV_SEND, flightrec.EV_SEND,
                       flightrec.EV_ACK, flightrec.EV_ERR]

    def test_fail_peer_drops_only_that_peer(self):
        fr = flightrec.FlightRecorder(slots=32)
        fr.begin_op(1, 1, 0x12)
        fr.begin_op(2, 1, 0x12)
        assert fr.fail_peer(1) == 1
        [(peer, *_)] = fr.inflight_snapshot()
        assert peer == 2


# ---------------------------------------------------------------------- #
# dumps
# ---------------------------------------------------------------------- #
class TestDump:
    def test_dump_contents_and_atomicity(self, tmp_path):
        fr = flightrec.FlightRecorder(slots=32)
        fr.rank = 3
        fr.record(flightrec.EV_STATE, note="hello")
        fr.begin_op(1, 42, 0x12, nbytes=512)
        path = fr.dump("unit test", directory=str(tmp_path), stacks=True)
        assert path.endswith("flightrec-rank3.jsonl")
        assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
        with open(path) as f:
            recs = [json.loads(x) for x in f]
        header = recs[0]
        assert header["kind"] == "header" and header["rank"] == 3
        assert header["reason"] == "unit test"
        assert any(r["kind"] == "event" and r.get("note") == "hello"
                   for r in recs)
        infl = [r for r in recs if r["kind"] == "inflight"]
        assert infl and infl[0]["peer"] == 1 and infl[0]["msg_id"] == 42
        stacks = [r for r in recs if r["kind"] == "stack"]
        assert stacks and any("test_dump_contents" in ln
                              for s in stacks for ln in s["frames"])

    def test_dump_without_directory_is_noop(self):
        fr = flightrec.FlightRecorder(slots=16)
        fr.record(flightrec.EV_STATE)
        assert fr.dump("nowhere") is None   # no flag/env/metrics_dir

    def test_second_dump_replaces_with_full_ring(self, tmp_path):
        fr = flightrec.FlightRecorder(slots=32)
        fr.record(flightrec.EV_STATE, note="a")
        fr.dump("first", directory=str(tmp_path))
        fr.record(flightrec.EV_STATE, note="b")
        path = fr.dump("second", directory=str(tmp_path))
        d = postmortem.load_dump(path)
        assert d["header"]["reason"] == "second"
        assert [e["note"] for e in d["events"]] == ["a", "b"]

    def test_routine_dump_never_replaces_fault_evidence(self, tmp_path):
        """Review regression: the Zoo.stop last tape (routine=True) must
        not overwrite a fault dump's stacks/in-flight evidence — and
        must still write when nothing ever faulted."""
        fr = flightrec.FlightRecorder(slots=16)
        fr.begin_op(1, 3, 0x12)
        path = fr.dump("watchdog stuck: x", directory=str(tmp_path),
                       stacks=True)
        assert fr.dump("Zoo.stop", directory=str(tmp_path),
                       routine=True) is None
        assert postmortem.load_dump(path)["header"]["reason"] \
            == "watchdog stuck: x"
        # a later FAULT dump still refreshes the tape
        assert fr.dump("fatal: y", directory=str(tmp_path)) == path
        assert postmortem.load_dump(path)["header"]["reason"] == "fatal: y"
        # no fault ever: the routine tape writes normally
        fr2 = flightrec.FlightRecorder(slots=16)
        fr2.rank = 7
        fr2.record(flightrec.EV_STATE)
        p2 = fr2.dump("Zoo.stop", directory=str(tmp_path), routine=True)
        assert p2 and postmortem.load_dump(p2)["header"]["rank"] == 7

    def test_fatal_triggers_dump_before_raising(self, tmp_path):
        config.set_flag("flightrec_dir", str(tmp_path))
        with pytest.raises(mvlog.FatalError):
            mvlog.fatal("shard exploded (%d)", 7)
        path = tmp_path / "flightrec-rank0.jsonl"
        assert path.exists()
        d = postmortem.load_dump(str(path))
        assert d["header"]["reason"].startswith("fatal:")
        assert any(e["ev"] == "fatal" and "shard exploded (7)" in
                   (e.get("note") or "") for e in d["events"])


# ---------------------------------------------------------------------- #
# structured JSONL log sink (satellite)
# ---------------------------------------------------------------------- #
class TestJsonlLogSink:
    def test_jsonl_records_and_text_default(self, tmp_path):
        lg = mvlog.Logger(kill_fatal=False, name="t")
        lg.rank = 2
        jpath = str(tmp_path / "run.jsonl")
        lg.reset_log_file(jpath, jsonl=True)
        lg.info("step %d done", 5)
        lg.error("bad thing")
        with open(jpath) as f:
            recs = [json.loads(x) for x in f]
        assert [r["level"] for r in recs] == ["INFO", "ERROR"]
        assert recs[0]["msg"] == "step 5 done"
        assert recs[0]["rank"] == 2
        assert recs[0]["ts"] > 0 and recs[0]["mono"] > 0
        # default stays text
        tpath = str(tmp_path / "run.log")
        lg.reset_log_file(tpath)
        lg.info("plain")
        with open(tpath) as f:
            assert "[INFO]" in f.read()

    def test_postmortem_interleaves_log_lines(self, tmp_path):
        fr = flightrec.FlightRecorder(slots=16)
        fr.record(flightrec.EV_STATE, note="ring event")
        dump = fr.dump("mix", directory=str(tmp_path))
        lg = mvlog.Logger(kill_fatal=False)
        lg.reset_log_file(str(tmp_path / "worker.jsonl"), jsonl=True)
        lg.info("a log line")
        dumps, logs = postmortem._expand([str(tmp_path)])
        assert dumps == [dump]
        assert logs == [str(tmp_path / "worker.jsonl")]
        lines = [rec for p in logs for rec in postmortem.load_log_lines(p)]
        tl = postmortem.timeline(postmortem.load_dumps(dumps), lines)
        kinds = {r.get("ev") for r in tl}
        assert "state" in kinds and "log.info" in kinds
        assert [r["ts"] for r in tl] == sorted(r["ts"] for r in tl)


# ---------------------------------------------------------------------- #
# watchdog thresholds (deterministic: a deliberately wedged op)
# ---------------------------------------------------------------------- #
class TestWatchdog:
    def _wedge(self, age_s, peer=3, msg_id=9):
        """Backdate an in-flight op so thresholds trip without sleeping."""
        flightrec.RECORDER.begin_op(peer, msg_id, 0x12, nbytes=128)
        with flightrec.RECORDER._lock:
            t0, *rest = flightrec.RECORDER._inflight[(peer, msg_id)]
            flightrec.RECORDER._inflight[(peer, msg_id)] = (
                t0 - age_s, *rest)

    def test_ok_when_nothing_in_flight(self):
        v = watchdog.check_once()
        assert v["status"] == "ok" and v["inflight"] == 0 and v["checked"]

    def test_slow_threshold_logs_once(self):
        config.set_flag("watchdog_slow_ms", 50.0)
        config.set_flag("watchdog_stuck_s", 1e6)
        self._wedge(0.5)
        v = watchdog.check_once()
        assert v["status"] == "slow"
        assert v["oldest_inflight_s"] >= 0.5
        slow = [s for s in flightrec.RECORDER.snapshot()
                if s[2] == flightrec.EV_SLOW]
        assert len(slow) == 1 and slow[0][3] == 3 and slow[0][5] == 9
        watchdog.check_once()   # same op: no second structured record
        assert len([s for s in flightrec.RECORDER.snapshot()
                    if s[2] == flightrec.EV_SLOW]) == 1

    def test_stuck_threshold_dumps_ring_and_stacks(self, tmp_path):
        config.set_flag("flightrec_dir", str(tmp_path))
        config.set_flag("watchdog_slow_ms", 50.0)
        config.set_flag("watchdog_stuck_s", 2.0)
        self._wedge(5.0, peer=1, msg_id=4)
        v = watchdog.check_once()
        assert v["status"] == "stuck"
        path = tmp_path / "flightrec-rank0.jsonl"
        assert path.exists()
        d = postmortem.load_dump(str(path))
        assert d["header"]["reason"].startswith("watchdog stuck")
        assert any(e["ev"] == "watchdog.stuck" for e in d["events"])
        assert d["inflight"] and d["inflight"][0]["msg_id"] == 4
        assert d["stacks"]   # sys._current_frames made it to disk
        # verdict is what MSG_HEALTH / heartbeats serve
        assert watchdog.last_verdict()["status"] == "stuck"


# ---------------------------------------------------------------------- #
# MSG_HEALTH on a live 2-rank PS (native punt + python path via params)
# ---------------------------------------------------------------------- #
class TestHealthRPC:
    def test_round_trip(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 4, name="hl", ctx=two_ranks[0])
        AsyncMatrixTable(16, 4, name="hl", ctx=two_ranks[1])
        t0.add_rows([9], np.ones((1, 4), np.float32))   # remote-owned
        h = t0.server_health(1)
        assert h["rank"] == 1
        assert h["status"] in ("ok", "slow")
        assert h["queue_depth"] == 0
        assert h["oldest_inflight_s"] >= 0.0
        assert "watchdog" in h and "apply_age_s" in h
        # the add above was data-plane traffic: on the PYTHON plane it
        # beats the serve loop (the native C++ fast path is unrecorded,
        # same rule as tracing, so serve_age_s stays None there) — and
        # the health PROBE itself must never refresh the beat (review
        # regression: a wedged-but-probing server must AGE, not reset)
        if not config.get_flag("ps_native") or h["serve_age_s"] is not None:
            assert h["serve_age_s"] is not None
            assert t0.server_health(1)["serve_age_s"] >= h["serve_age_s"]
        json.dumps(h)   # pure JSON meta
        # local short-circuit: no socket, same shape
        local = t0.server_health()
        assert local["rank"] == 0 and "status" in local

    def test_probe_answers_while_data_conn_wedged(self, two_ranks):
        """Review regression: the health probe rides its OWN one-shot
        connection — a data op blocked in its handler on the shared
        conn (per-conn FIFO) must not starve the probe into a
        ps_timeout, or 'alive but stuck' would read as unreachable."""
        import threading as th

        release = th.Event()

        def blocking_handler(msg_type, meta, arrays):
            release.wait(20.0)
            return {}, []

        two_ranks[1].service.register_handler("wedge", blocking_handler)
        try:
            # occupy the shared conn's serving thread (fire-and-forget)
            two_ranks[0].service.request(
                1, 0x11, {"table": "wedge"}, [np.zeros(1)])
            t0 = time.monotonic()
            h = two_ranks[0].service.health(1)
            took = time.monotonic() - t0
            assert h["rank"] == 1
            assert took < 5.0, f"probe starved behind wedged conn ({took}s)"
        finally:
            release.set()

    def test_dead_rank_raises_typed(self, two_ranks):
        from multiverso_tpu.ps import service as svc
        from multiverso_tpu.ps.tables import AsyncMatrixTable
        t0 = AsyncMatrixTable(16, 4, name="hd", ctx=two_ranks[0])
        AsyncMatrixTable(16, 4, name="hd", ctx=two_ranks[1])
        config.set_flag("ps_timeout", 4.0)
        config.set_flag("ps_connect_timeout", 2.0)
        two_ranks[1].service.close()
        with pytest.raises(svc.PSPeerError):
            t0.server_health(1)


# ---------------------------------------------------------------------- #
# postmortem on synthetic dumps
# ---------------------------------------------------------------------- #
class TestPostmortem:
    def test_stuck_pairs_and_suspects(self, tmp_path):
        fr = flightrec.FlightRecorder(slots=32)
        fr.rank = 0
        fr.begin_op(1, 3, 0x12, nbytes=64)    # newer
        fr.begin_op(1, 2, 0x12, nbytes=64)
        with fr._lock:                         # backdate msg 2: oldest
            t0, *rest = fr._inflight[(1, 2)]
            fr._inflight[(1, 2)] = (t0 - 9.0, *rest)
        fr.record(flightrec.EV_PEER_DEAD, peer=1)
        fr.dump("test", directory=str(tmp_path))
        dumps = postmortem.load_dumps(str(tmp_path))
        assert len(dumps) == 1
        pairs = postmortem.stuck_pairs(dumps)
        assert pairs[0]["src"] == 0 and pairs[0]["dst"] == 1
        assert pairs[0]["msg_id"] == 2   # the OLDEST unacked, not the last
        suspects = postmortem.dead_suspects(dumps)
        assert [s["rank"] for s in suspects] == [1]
        report = postmortem.render_report(dumps)
        assert "rank 0 -> rank 1: msg 2" in report
        assert "suspect dead/stuck" in report
        assert postmortem.main([str(tmp_path)]) == 0
        assert postmortem.main([str(tmp_path), "--json"]) == 0

    def test_multi_rank_timeline_merges_by_wall_clock(self, tmp_path):
        a = flightrec.FlightRecorder(slots=16)
        a.rank = 0
        a.record(flightrec.EV_STATE, note="first")
        time.sleep(0.02)
        b = flightrec.FlightRecorder(slots=16)
        b.rank = 1
        b.record(flightrec.EV_STATE, note="second")
        pa = a.dump("a", directory=str(tmp_path / "a"))
        pb = b.dump("b", directory=str(tmp_path / "b"))
        tl = postmortem.timeline(postmortem.load_dumps([pa, pb]))
        assert [e["note"] for e in tl] == ["first", "second"]
        assert tl[0]["rank"] == 0 and tl[1]["rank"] == 1

    def test_no_dumps_exits_nonzero(self, tmp_path):
        assert postmortem.main([str(tmp_path)]) == 1


# ---------------------------------------------------------------------- #
# the acceptance: kill one rank of a 2-process run, postmortem from dumps
# ---------------------------------------------------------------------- #
def test_kill_one_rank_postmortem(tmp_path):
    """Rank 1 wedges (SIGSTOP — alive but serving nothing) with rank 0's
    gets in flight; rank 0's watchdog trips stuck and dumps. The parent
    SIGKILLs rank 1 and must identify the dead rank and the oldest
    unacked (src, dst, msg id) from the dumps ALONE — no stdout, no
    other logs."""
    rdv = str(tmp_path / "rdv")
    frdir = str(tmp_path / "fr")
    os.makedirs(rdv)
    os.makedirs(frdir)
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MV_FLIGHTREC_DIR"] = frdir
    env["MV_PS_NATIVE"] = "0"   # in-flight tracking lives on the python
    #                             conns (native fast path is unrecorded
    #                             by design, like tracing)
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(_DIR, "async_ps_worker.py"),
         rdv, "2", str(r), "flightrec"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
        for r in range(2)]
    try:
        out0, err0 = procs[0].communicate(timeout=120)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        pytest.fail("survivor rank timed out")
    finally:
        procs[1].kill()   # SIGKILL the wedged victim
        procs[1].communicate(timeout=30)
    assert procs[0].returncode == 0, f"{err0[-2000:]}"
    result = next(json.loads(ln[len("RESULT "):])
                  for ln in out0.splitlines() if ln.startswith("RESULT "))
    assert result["stuck_peer"] == 1
    # --- postmortem from the dump directory alone ---
    dumps = postmortem.load_dumps(frdir)
    assert [d["header"]["rank"] for d in dumps] == [0]   # victim left none
    pairs = postmortem.stuck_pairs(dumps)
    pair = next(p for p in pairs if p["src"] == 0 and p["dst"] == 1)
    assert pair["msg_id"] == result["stuck_msg_id"]
    suspects = postmortem.dead_suspects(dumps)
    assert any(s["rank"] == 1 for s in suspects)
    report = postmortem.render_report(dumps)
    assert f"rank 0 -> rank 1: msg {result['stuck_msg_id']}" in report
    assert "MSG_GET_ROWS" in report   # type resolved, not a raw code
