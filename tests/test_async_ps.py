"""Uncoordinated async PS: wire, shards, client tables, failure semantics.

Single-process tier: two standalone PSService instances stand in for two
ranks, talking over real localhost sockets (the reference exercised its
Worker/Server actors the same way before mpirun, Test/main.cpp). The
multi-process tier lives in test_multiprocess_async.py.
"""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.ps import wire
from multiverso_tpu.ps.service import (FileRendezvous, PSContext, PSPeerError,
                                       PSService)
from multiverso_tpu.ps.tables import (AsyncArrayTable, AsyncKVTable,
                                      AsyncMatrixTable,
                                      AsyncSparseMatrixTable)
from multiverso_tpu.updaters import AdaGradUpdater, AddOption


# the shared two_ranks fixture lives in conftest.py (used here and by the
# async-plane LDA test)


class TestWire:
    def test_roundtrip_via_socket(self):
        import socket
        a, b = socket.socketpair()
        meta = {"table": "t", "opt": {"worker_id": 3}}
        arrays = [np.arange(6, dtype=np.float32).reshape(2, 3),
                  np.array(7, dtype=np.int64),
                  np.zeros(0, dtype=np.float64)]
        wire.send(a, 0x11, 42, meta, arrays)
        msg_type, msg_id, meta2, arrays2 = wire.recv(b)
        assert (msg_type, msg_id, meta2) == (0x11, 42, meta)
        for x, y in zip(arrays, arrays2):
            assert x.dtype == y.dtype and x.shape == y.shape
            np.testing.assert_array_equal(x, y)
        a.close(), b.close()

    def test_negative_dim_rejected(self):
        """A frame claiming a negative shape dim must raise WireError —
        np.frombuffer would read count=-1 as 'the rest of the buffer' and
        the cursor would walk backwards."""
        import socket
        import struct as st
        a, b = socket.socketpair()
        payload = (b"{}" + st.pack("<B", 3) + b"<i8" + st.pack("<B", 1)
                   + st.pack("<q", -1) + bytes(24))
        a.sendall(wire._HEADER.pack(wire.MAGIC, 0x11, 0, 1, 2, 1,
                                    len(payload)) + payload)
        with pytest.raises(wire.WireError, match="negative dim"):
            wire.recv(b)
        a.close(), b.close()

    def test_corrupt_meta_json_is_wire_error(self):
        """Garbage meta bytes must surface as WireError, not leak
        json.JSONDecodeError — the native plane's punt path keys its
        fail-fast ERR reply on WireError (review finding: corrupt JSON,
        the likeliest malformed body, used to bypass it and park the
        peer for the full ps_timeout)."""
        bad_meta = b"{not json"
        frame = wire._HEADER.pack(wire.MAGIC, 0x11, 0, 7, len(bad_meta),
                                  0, len(bad_meta)) + bad_meta
        with pytest.raises(wire.WireError, match="meta json"):
            wire.parse_frame(frame)
        assert wire.peek_msg_id(frame) == 7  # ERR reply stays bindable

    def test_bad_magic_raises(self):
        import socket
        a, b = socket.socketpair()
        a.sendall(b"XXXX" + bytes(wire._HEADER.size - 4))
        with pytest.raises(wire.WireError):
            wire.recv(b)
        a.close(), b.close()

    def test_service_survives_garbage_connections(self, two_ranks):
        """A network-facing server must shrug off malformed frames: random
        bytes, truncated frames, oversized length fields — the offending
        connection dies, the service keeps serving real clients."""
        import socket

        t0 = AsyncMatrixTable(10, 2, name="g", ctx=two_ranks[0])
        AsyncMatrixTable(10, 2, name="g", ctx=two_ranks[1])
        host, port = two_ranks[1].service.addr.rsplit(":", 1)
        rng = np.random.default_rng(0)
        for payload in (
                rng.integers(0, 256, 64, dtype=np.uint8).tobytes(),
                b"MVPS" + bytes(4),                       # truncated header
                wire.encode(0x11, 1, {"table": "g"})[:10],  # cut mid-frame
                # huge meta length field: must be rejected, not allocated
                wire._HEADER.pack(wire.MAGIC, 0x11, 0, 1,
                                  wire.MAX_META + 1, 0, wire.MAX_META + 1),
                # huge/negative frame length: rejected before allocation
                wire._HEADER.pack(wire.MAGIC, 0x11, 0, 1, 4, 0,
                                  wire.MAX_FRAME + 1),
                wire._HEADER.pack(wire.MAGIC, 0x11, 0, 1, 4, 0, -8),
        ):
            s = socket.create_connection((host, int(port)), timeout=5)
            s.sendall(payload)
            s.close()
        time.sleep(0.2)
        # the real client plane is unaffected
        t0.add_rows([9], np.ones((1, 2), np.float32))
        np.testing.assert_allclose(t0.get_rows([9])[0], 1.0)


class TestAsyncMatrixTable:
    def test_different_row_sets_per_worker(self, two_ranks):
        """THE capability the sync plane lacks (ref worker.cpp:30-76 +
        server.cpp:36-58): each worker pushes its OWN row set, no
        coordination, and the global state converges to the sum."""
        t0 = AsyncMatrixTable(10, 4, name="m", ctx=two_ranks[0])
        t1 = AsyncMatrixTable(10, 4, name="m", ctx=two_ranks[1])
        # rows 0-4 owned by rank 0, rows 5-9 by rank 1
        t0.add_rows([0, 7], np.full((2, 4), 1.0, np.float32))
        t1.add_rows([3, 7, 9], np.full((3, 4), 2.0, np.float32))
        t1.add_rows([7], np.full((1, 4), 0.5, np.float32))
        got = t0.get_rows([0, 3, 7, 9])
        np.testing.assert_allclose(got[0], 1.0)
        np.testing.assert_allclose(got[1], 2.0)
        np.testing.assert_allclose(got[2], 3.5)   # 1 + 2 + 0.5
        np.testing.assert_allclose(got[3], 2.0)
        # the other worker sees the same state (server truth, not caches)
        np.testing.assert_allclose(t1.get_rows([7])[0], 3.5)

    def test_uncoordinated_rates(self, two_ranks):
        """Workers at wildly different rates; nobody waits for anybody
        (no collective): total = sum of all pushes."""
        t0 = AsyncMatrixTable(8, 2, name="r", ctx=two_ranks[0])
        t1 = AsyncMatrixTable(8, 2, name="r", ctx=two_ranks[1])

        def fast():
            for _ in range(50):
                t0.add_rows([1, 6], np.ones((2, 2), np.float32))

        def slow():
            for _ in range(5):
                t1.add_rows([1], np.ones((1, 2), np.float32))
                time.sleep(0.01)

        th = [threading.Thread(target=fast), threading.Thread(target=slow)]
        [x.start() for x in th]
        [x.join() for x in th]
        t0.flush(), t1.flush()
        got = t0.get_rows([1, 6])
        np.testing.assert_allclose(got[0], 55.0)   # 50 + 5
        np.testing.assert_allclose(got[1], 50.0)

    def test_async_msg_ids_and_wait(self, two_ranks):
        t0 = AsyncMatrixTable(6, 3, name="w", ctx=two_ranks[0])
        AsyncMatrixTable(6, 3, name="w", ctx=two_ranks[1])
        mids = [t0.add_rows_async([i % 6], np.ones((1, 3), np.float32))
                for i in range(7)]
        gid = t0.get_rows_async([0, 1, 2, 3, 4, 5])
        for m in mids:
            t0.wait(m)
        rows = t0.wait(gid)
        assert rows.shape == (6, 3)
        # re-waiting a consumed id returns None (ref Waiter semantics)
        assert t0.wait(mids[0]) is None

    def test_duplicates_and_order(self, two_ranks):
        t0 = AsyncMatrixTable(10, 2, name="d", ctx=two_ranks[0])
        AsyncMatrixTable(10, 2, name="d", ctx=two_ranks[1])
        # duplicate ids in one add accumulate (ref per-row accumulation)
        t0.add_rows([8, 2, 8], np.ones((3, 2), np.float32))
        got = t0.get_rows([8, 2, 8, 2])
        np.testing.assert_allclose(got[0], 2.0)
        np.testing.assert_allclose(got[1], 1.0)
        np.testing.assert_allclose(got[2], 2.0)   # original order preserved

    def test_whole_table_and_array(self, two_ranks):
        t0 = AsyncMatrixTable(7, 3, name="f", ctx=two_ranks[0])
        t1 = AsyncMatrixTable(7, 3, name="f", ctx=two_ranks[1])
        t0.add(np.ones((7, 3), np.float32))
        t1.add(2 * np.ones((7, 3), np.float32))
        np.testing.assert_allclose(t1.get(), 3.0)

        a0 = AsyncArrayTable(9, name="arr", ctx=two_ranks[0])
        a1 = AsyncArrayTable(9, name="arr", ctx=two_ranks[1])
        a0.add(np.arange(9, dtype=np.float32))
        a1.add(np.arange(9, dtype=np.float32))
        np.testing.assert_allclose(a0.get(), 2 * np.arange(9))

    def test_per_worker_adagrad_state(self, two_ranks):
        """ref adagrad_updater.h:19 — per-worker historic g² on the server,
        keyed by the AddOption worker_id each worker sends."""
        ts = [AsyncMatrixTable(
                  4, 2, name="ag",
                  updater=AdaGradUpdater(num_workers=2, per_worker=True),
                  ctx=two_ranks[r]) for r in range(2)]
        opt = dict(learning_rate=1.0, rho=1.0)
        ts[0].add_rows([0], np.ones((1, 2), np.float32),
                       AddOption(worker_id=0, **opt))
        before = ts[0].get_rows([0])[0].copy()
        # worker 1's first add must use ITS OWN fresh g² (not worker 0's)
        ts[1].add_rows([0], np.ones((1, 2), np.float32),
                       AddOption(worker_id=1, **opt))
        after = ts[1].get_rows([0])[0]
        # both first-adds step by the same magnitude (fresh g² each):
        # w0: 0 - 1*1/(sqrt(1)+eps) = -1 ; w1: -1 - 1 = -2
        np.testing.assert_allclose(before, -1.0, rtol=1e-5)
        np.testing.assert_allclose(after, -2.0, rtol=1e-5)

    def test_random_init_consistent_across_clients(self, two_ranks):
        t0 = AsyncMatrixTable(10, 4, name="ri", seed=3, init_scale=0.5,
                              ctx=two_ranks[0])
        t1 = AsyncMatrixTable(10, 4, name="ri", seed=3, init_scale=0.5,
                              ctx=two_ranks[1])
        a, b = t0.get(), t1.get()
        np.testing.assert_array_equal(a, b)
        assert np.abs(a).max() <= 0.5 and np.abs(a).std() > 0

    def test_set_rows_and_store_load(self, two_ranks, tmp_path):
        t0 = AsyncMatrixTable(6, 2, name="ck", ctx=two_ranks[0])
        AsyncMatrixTable(6, 2, name="ck", ctx=two_ranks[1])
        t0.set_rows([5, 1], np.array([[5, 5], [1, 1]], np.float32))
        np.testing.assert_allclose(t0.get_row(5), 5.0)
        np.testing.assert_allclose(t0.get_row(1), 1.0)
        with open(tmp_path / "ck.npy", "wb") as f:
            t0.store(f)
        t0.add(np.ones((6, 2), np.float32))
        with open(tmp_path / "ck.npy", "rb") as f:
            t0.load(f)
        np.testing.assert_allclose(t0.get_row(5), 5.0)

    def test_errors_are_typed(self, two_ranks):
        t0 = AsyncMatrixTable(5, 2, name="e", ctx=two_ranks[0])
        with pytest.raises(IndexError):
            t0.add_rows([5], np.ones((1, 2), np.float32))
        with pytest.raises(TypeError):
            t0.get_rows([0.5])
        with pytest.raises(ValueError):
            t0.get_rows([])


class TestCoalescing:
    """Server-side request coalescing (ps_coalesce): concurrent adds to a
    shard merge into batched jitted updates, with per-message results
    identical to sequential application for linear updaters — the server-
    side scaling fix the reference never had (its server applied strictly
    per-message, src/server.cpp:36-58)."""

    def _shard(self, n=32, cols=4, updater=None, num_workers=0):
        from multiverso_tpu.ps.shard import RowShard
        from multiverso_tpu.updaters import Updater
        return RowShard(0, n, cols, np.float32, updater or Updater(),
                        "coal", num_workers=num_workers)

    @staticmethod
    def _block_applier_and_queue(shard, requests):
        """Deterministic merge setup: while holding the shard lock, start a
        zero-delta dummy add (it becomes the applier and blocks on the
        lock), then start ``requests``, which all queue behind it. On lock
        release the dummy applies alone and the rest drain as one batch."""
        import multiverso_tpu.ps.service as svc
        cols = shard.num_col
        zero = np.zeros((1, cols), np.float32)
        threads = []
        with shard._lock:
            dummy = threading.Thread(
                target=shard.handle,
                args=(svc.MSG_ADD_ROWS, {"table": shard.name},
                      [np.array([0]), zero]))
            dummy.start()
            threads.append(dummy)
            deadline = time.monotonic() + 5
            # the dummy is draining (popped its own entry) once the flag is
            # up and the queue is empty again
            while ((not shard._addq_draining or shard._addq)
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            for meta, arrays in requests:
                t = threading.Thread(target=shard.handle,
                                     args=(svc.MSG_ADD_ROWS, meta, arrays))
                t.start()
                threads.append(t)
            while (len(shard._addq) < len(requests)
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert len(shard._addq) == len(requests)
        for t in threads:
            t.join(timeout=10)

    def test_queued_adds_merge_into_one_update(self):
        """Adds queued behind a blocked applier must apply as ONE merged
        update, summing exactly."""
        shard = self._shard()
        ids = np.arange(8)
        one = np.ones((8, 4), np.float32)
        self._block_applier_and_queue(
            shard, [({"table": "coal"}, [ids, one]) for _ in range(6)])
        assert shard.stat_adds == 7             # dummy + 6
        assert shard.stat_applies == 2          # dummy + one merged batch
        got = np.asarray(shard._data)[:8]
        np.testing.assert_allclose(got, 6 * one)    # sum is exact
        assert shard._dirty is None

    def test_cross_worker_adds_merge_for_stateless_updaters(self):
        """The client default opt stamps worker_id=rank; stateless
        updaters ignore opt, so adds from DIFFERENT workers must still
        merge into one update — the cross-worker case coalescing exists
        for."""
        shard = self._shard()
        ids = np.arange(8)
        one = np.ones((8, 4), np.float32)
        self._block_applier_and_queue(
            shard,
            [({"table": "coal", "opt": {"worker_id": w}}, [ids, one])
             for w in range(6)])
        assert shard.stat_applies == 2      # dummy + ONE merged batch
        np.testing.assert_allclose(np.asarray(shard._data)[:8], 6 * one)

    def test_distinct_opts_stay_separate_updates(self):
        """Per-worker AdaGrad state keys on opt.worker_id — merged applies
        must group by opt so each worker's g² accumulates its own deltas."""
        from multiverso_tpu.updaters import AdaGradUpdater
        shard = self._shard(updater=AdaGradUpdater(num_workers=2,
                                                   per_worker=True))
        ids = np.arange(4)
        one = np.ones((4, 4), np.float32)
        self._block_applier_and_queue(
            shard,
            [({"table": "coal", "opt": {"worker_id": wid,
                                        "learning_rate": 1.0}}, [ids, one])
             for wid in (0, 0, 1)])
        g2 = np.asarray(shard._ustate["g_sqr"])
        # worker 0's two adds merged (delta 2 -> g2 += 4), worker 1's one
        # add stayed its own group (g2 += 1): buffers stayed per-worker
        np.testing.assert_allclose(g2[0, :4], 4.0)
        np.testing.assert_allclose(g2[1, :4], 1.0)

    def test_disabled_flag_applies_per_message(self):
        from multiverso_tpu.utils import config
        import multiverso_tpu.ps.service as svc
        config.set_flag("ps_coalesce", False)
        shard = self._shard()
        ids = np.arange(4)
        one = np.ones((4, 4), np.float32)
        for _ in range(3):
            shard.handle(svc.MSG_ADD_ROWS, {"table": "coal"}, [ids, one])
        assert shard.stat_adds == shard.stat_applies == 3
        np.testing.assert_allclose(np.asarray(shard._data)[:4], 3 * one)

    def test_concurrent_hammer_sums_exactly(self, two_ranks):
        """End-to-end over the sockets: many client threads adding random
        disjoint-and-overlapping batches; the grand total must be exact
        (linear updater) — coalescing must never drop or double a delta."""
        t0 = AsyncMatrixTable(64, 8, name="hammer", ctx=two_ranks[0])
        t1 = AsyncMatrixTable(64, 8, name="hammer", ctx=two_ranks[1])
        rng = np.random.default_rng(7)
        batches = [(rng.choice(64, size=16, replace=False),
                    rng.integers(-3, 4, size=(16, 8)).astype(np.float32))
                   for _ in range(24)]
        expect = np.zeros((64, 8), np.float32)
        for ids, vals in batches:
            np.add.at(expect, ids, vals)

        def work(table, chunk):
            for ids, vals in chunk:
                table.add_rows(ids, vals)

        threads = [threading.Thread(target=work,
                                    args=(t, batches[i::4]))
                   for i, t in enumerate([t0, t1, t0, t1])]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        np.testing.assert_allclose(t0.get_rows(np.arange(64)), expect,
                                   rtol=1e-5, atol=1e-5)

    def test_hash_shard_coalesces_outside_lock(self, two_ranks):
        """AsyncSparseKVTable adds (HashShard) ride the same queue; key->
        slot translation must not deadlock against a blocked applier."""
        from multiverso_tpu.ps.tables import AsyncSparseKVTable
        t0 = AsyncSparseKVTable(4, name="kvcoal", updater="default",
                                ctx=two_ranks[0])
        AsyncSparseKVTable(4, name="kvcoal", updater="default",
                           ctx=two_ranks[1])
        keys = np.array([5, 1000003, 17, 2**40 + 3])
        one = np.ones((4, 4), np.float32)
        threads = [threading.Thread(target=t0.add_rows, args=(keys, one))
                   for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        np.testing.assert_allclose(t0.get_rows(keys), 8 * one)


class TestWireBf16:
    def test_bf16_wire_roundtrip(self, two_ranks):
        """wire="bf16" halves the TCP payload both directions (the role
        the reference's filters played on its MPI wire); values come back
        in table dtype with bf16 precision."""
        t0 = AsyncMatrixTable(10, 4, name="wb", wire="bf16",
                              ctx=two_ranks[0])
        t1 = AsyncMatrixTable(10, 4, name="wb", wire="bf16",
                              ctx=two_ranks[1])
        rng = np.random.default_rng(0)
        vals = rng.normal(size=(4, 4)).astype(np.float32)
        t0.add_rows([0, 3, 7, 9], vals)       # spans both shards
        got = t1.get_rows([0, 3, 7, 9])
        assert got.dtype == np.float32
        np.testing.assert_allclose(got, vals, rtol=2e-2, atol=2e-2)
        t0.add(np.ones((10, 4), np.float32))  # full-table path too
        np.testing.assert_allclose(t0.get()[1], 1.0, rtol=2e-2)

    def test_unknown_wire_raises(self, two_ranks):
        with pytest.raises(ValueError):
            AsyncMatrixTable(4, 2, name="wx", wire="zstd",
                             ctx=two_ranks[0])

    def test_store_keeps_full_precision_despite_wire(self, two_ranks,
                                                     tmp_path):
        """Checkpoints are durable state: store() must bypass the bf16
        wire (values below bf16 resolution survive a save round-trip)."""
        t0 = AsyncMatrixTable(6, 2, name="ws", wire="bf16",
                              ctx=two_ranks[0])
        AsyncMatrixTable(6, 2, name="ws", wire="bf16", ctx=two_ranks[1])
        exact = np.full((6, 2), 1.0009765625, np.float32)  # not bf16-exact
        t0.set_rows(np.arange(6), exact)                   # exact path in
        with open(tmp_path / "ws.npy", "wb") as f:
            t0.store(f)
        saved = np.load(tmp_path / "ws.npy")
        np.testing.assert_array_equal(saved, exact)        # bit-exact
        assert t0._wire == "bf16"                          # mode restored


class TestLocalDeviceSharding:
    def test_shard_spans_local_devices(self, two_ranks):
        """On a multi-chip host the owned row range itself shards over the
        local devices (device-level partition composing with the
        process-level one) — here the 8-device CPU mesh stands in for an
        8-chip host."""
        import jax

        from multiverso_tpu.utils import config
        config.set_flag("ps_local_shard_min_mb", 0.0)  # force for tiny table
        t0 = AsyncMatrixTable(64, 8, name="lds", ctx=two_ranks[0])
        AsyncMatrixTable(64, 8, name="lds", ctx=two_ranks[1])
        ndev = len(jax.local_devices())
        if ndev == 1:
            pytest.skip("single local device")
        data = t0.raw()
        assert len(data.sharding.device_set) == ndev
        # padded row count divides evenly over the device axis
        assert data.shape[0] % ndev == 0
        # ops stay correct over the sharded storage
        t0.add_rows([0, 40], np.ones((2, 8), np.float32))
        got = t0.get_rows([0, 40, 63])
        np.testing.assert_allclose(got[0], 1.0)
        np.testing.assert_allclose(got[2], 0.0)


class TestAsyncSparse:
    """Stale-row protocol on the uncoordinated plane (ref matrix.cpp
    :432-572: the reference async server's sparse mode)."""

    def test_stale_only_transfer(self, two_ranks):
        ts = [AsyncSparseMatrixTable(10, 4, name="sp", num_workers=2,
                                     ctx=two_ranks[r]) for r in range(2)]
        ids = np.arange(10)
        # first pull: everything is stale -> all 10 rows cross the wire
        rows = ts[0].get_rows_sparse(ids, worker_id=0)
        assert ts[0].last_transfer_rows == 10
        np.testing.assert_allclose(rows, 0.0)
        # nothing changed: second pull transfers NOTHING
        rows = ts[0].get_rows_sparse(ids, worker_id=0)
        assert ts[0].last_transfer_rows == 0
        # worker 1 (via the other client) is tracked independently
        rows1 = ts[1].get_rows_sparse(ids, worker_id=1)
        assert ts[1].last_transfer_rows == 10
        # a remote add dirties exactly its rows for worker 0
        ts[1].add_rows([2, 7], np.ones((2, 4), np.float32))
        rows = ts[0].get_rows_sparse(ids, worker_id=0)
        assert ts[0].last_transfer_rows == 2
        np.testing.assert_allclose(rows[2], 1.0)
        np.testing.assert_allclose(rows[7], 1.0)
        np.testing.assert_allclose(rows[3], 0.0)

    def test_sparse_needs_num_workers(self, two_ranks):
        t = AsyncMatrixTable(6, 2, name="nosp", ctx=two_ranks[0])
        AsyncMatrixTable(6, 2, name="nosp", ctx=two_ranks[1])
        from multiverso_tpu.ps import service as svc
        with pytest.raises(svc.PSError, match="num_workers"):
            # plain table has no dirty bits; typed error end-to-end
            t.ctx.service.request(
                0, svc.MSG_GET_ROWS, {"table": "nosp", "sparse": True,
                                      "worker_id": 0},
                [np.array([0], np.int64)]).result(timeout=10)


class TestCreateTableParity:
    def test_options_via_create_table(self):
        """Async tables ride the same MV_CreateTable option surface as the
        collective tables (single-process default context)."""
        import multiverso_tpu as mv
        mv.init()
        try:
            from multiverso_tpu.ps import (AsyncArrayTableOption,
                                           AsyncMatrixTableOption)
            t = mv.create_table(AsyncMatrixTableOption(6, 3), name="opt_m")
            t.add_rows([1], np.ones((1, 3), np.float32))
            np.testing.assert_allclose(t.get_row(1), 1.0)
            a = mv.create_table(AsyncArrayTableOption(8), name="opt_a")
            a.add(np.arange(8, dtype=np.float32))
            np.testing.assert_allclose(a.get(), np.arange(8))
        finally:
            mv.shutdown()


class TestAsyncKV:
    def test_hash_sharded_aggregated_get(self, two_ranks):
        k0 = AsyncKVTable(name="kv", ctx=two_ranks[0])
        k1 = AsyncKVTable(name="kv", ctx=two_ranks[1])
        k0.add([0, 1, 2], [1.0, 1.0, 1.0])
        k1.add([1, 2, 3], [2.0, 2.0, 2.0])
        # uncoordinated aggregated read — no collective, either side
        assert k0.get() == {0: 1.0, 1: 3.0, 2: 3.0, 3: 2.0}
        assert k1.get([1, 9]) == {1: 3.0, 9: 0}
        assert k0[2] == 3.0

    def test_duplicate_request_keys_not_double_counted(self, two_ranks):
        k0 = AsyncKVTable(name="kvd", ctx=two_ranks[0])
        AsyncKVTable(name="kvd", ctx=two_ranks[1])
        k0.add([5], [2.0])
        assert k0.get([5, 5, 5]) == {5: 2.0}


class TestFailureSemantics:
    def test_idle_connection_survives_timeout(self, tmp_path):
        """A healthy-but-quiet peer must not be declared dead: the io
        timeout bounds blocked replies, not connection lifetime."""
        from multiverso_tpu.utils import config
        config.set_flag("ps_timeout", 1.0)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        try:
            t0 = AsyncMatrixTable(10, 2, name="idle", ctx=ctxs[0])
            AsyncMatrixTable(10, 2, name="idle", ctx=ctxs[1])
            t0.add_rows([9], np.ones((1, 2), np.float32))  # open the conn
            time.sleep(2.5)                                # > ps_timeout idle
            np.testing.assert_allclose(t0.get_rows([9])[0], 1.0)
        finally:
            for c in ctxs:
                c.close()

    def test_first_contact_dead_peer_yields_failed_future(self, tmp_path):
        """A rank that died before we EVER connected to it: async ops must
        not raise (failed future instead), the wait is typed and bounded,
        and live-shard traffic keeps working (regression: the first-contact
        path used to raise synchronously out of fire-and-forget calls)."""
        from multiverso_tpu.utils import config
        config.set_flag("ps_timeout", 4.0)
        config.set_flag("ps_connect_timeout", 3.0)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        try:
            t0 = AsyncMatrixTable(10, 2, name="fc", ctx=ctxs[0])
            AsyncMatrixTable(10, 2, name="fc", ctx=ctxs[1])
            ctxs[1].close()   # dies before rank 0 ever dials it
            time.sleep(0.1)
            start = time.monotonic()
            mid = t0.add_rows_async([1, 9],           # spans live + dead
                                    np.ones((2, 2), np.float32))
            with pytest.raises(PSPeerError):
                t0.wait(mid)
            assert time.monotonic() - start < 12.0
            # the live half landed; later live traffic unaffected
            np.testing.assert_allclose(t0.get_rows([1])[0], 1.0)
        finally:
            for c in ctxs:
                c.close()

    def test_flush_surfaces_swept_failures_deterministically(self, tmp_path):
        """A fire-and-forget push to a dead shard must be reported by the
        NEXT flush even if the sweep already logged-and-dropped it — a
        training loop pushing async and flushing at the end (the WE block
        path) gets a deterministic error, never silent delta loss."""
        from multiverso_tpu.utils import config
        config.set_flag("ps_timeout", 5.0)
        config.set_flag("ps_connect_timeout", 5.0)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        try:
            t0 = AsyncMatrixTable(10, 2, name="sf", ctx=ctxs[0])
            AsyncMatrixTable(10, 2, name="sf", ctx=ctxs[1])
            t0.add_rows([9], np.ones((1, 2), np.float32))
            ctxs[1].close()
            time.sleep(0.1)
            t0.add_rows_async([8], np.ones((1, 2), np.float32))  # will fail
            time.sleep(0.3)
            # trigger sweeps so the failed op is popped before the flush
            for _ in range(3):
                t0.add_rows([1], np.ones((1, 2), np.float32))
            with pytest.raises(PSPeerError):
                t0.flush()
            t0.flush()   # failure consumed; table stays usable
            np.testing.assert_allclose(t0.get_rows([1])[0], 3.0)
        finally:
            for c in ctxs:
                c.close()

    def test_failed_fire_and_forget_does_not_poison_table(self, tmp_path):
        """A dead shard's unawaited add is logged, not re-raised: later ops
        on live shards keep working (the elasticity contract)."""
        from multiverso_tpu.utils import config
        config.set_flag("ps_timeout", 5.0)
        config.set_flag("ps_connect_timeout", 5.0)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        try:
            t0 = AsyncMatrixTable(10, 2, name="poison", ctx=ctxs[0])
            AsyncMatrixTable(10, 2, name="poison", ctx=ctxs[1])
            t0.add_rows([9], np.ones((1, 2), np.float32))
            ctxs[1].close()                      # rank 1 dies
            time.sleep(0.1)
            t0.add_rows_async([8], np.ones((1, 2), np.float32))  # never waited
            time.sleep(0.3)                      # let the failure land
            for _ in range(3):                   # sweeps must not raise
                t0.add_rows([1], np.ones((1, 2), np.float32))
            np.testing.assert_allclose(t0.get_rows([1])[0], 3.0)
        finally:
            for c in ctxs:
                c.close()

    def test_dead_peer_does_not_hang_live_traffic(self, tmp_path):
        """A killed worker/server must not block peers: ops on live shards
        proceed, ops on the dead shard raise PSPeerError quickly (the
        elastic behavior the reference lacked — its MPI world just hung)."""
        from multiverso_tpu.utils import config
        config.set_flag("ps_timeout", 5.0)
        config.set_flag("ps_connect_timeout", 5.0)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        try:
            t0 = AsyncMatrixTable(10, 2, name="dp", ctx=ctxs[0])
            AsyncMatrixTable(10, 2, name="dp", ctx=ctxs[1])
            t0.add_rows([0, 9], np.ones((2, 2), np.float32))
            t0.flush()
            ctxs[1].close()           # rank 1 dies
            time.sleep(0.1)
            # rows 0-4 live on rank 0: still fully functional
            t0.add_rows([1], np.ones((1, 2), np.float32))
            np.testing.assert_allclose(t0.get_rows([1])[0], 1.0)
            # rows 5-9 lived on rank 1: typed error, bounded time
            start = time.monotonic()
            with pytest.raises(PSPeerError):
                t0.get_rows([9])
            assert time.monotonic() - start < 10.0
        finally:
            for c in ctxs:
                c.close()


class TestDeathBookkeeping:
    def test_stale_incarnation_death_is_ignored(self, two_ranks):
        """A late on_death from a superseded peer object must not
        re-tombstone a rank whose fresh connection is healthy (the
        reconnect race: stale.close() fires its recv-loop death AFTER the
        new incarnation already cleared the rank)."""
        import types
        svc0 = two_ranks[0].service
        assert svc0.ping(1)
        cur = svc0._peers[1]
        svc0._note_death(1, peer=types.SimpleNamespace())   # stale object
        assert 1 not in svc0.dead_ranks()
        svc0._note_death(1, peer=cur)   # the live incarnation does count
        assert 1 in svc0.dead_ranks()
        # ...and the healthy fast path clears the stale tombstone
        assert svc0._peer(1) is cur
        assert 1 not in svc0.dead_ranks()


class TestAsyncCheckpoint:
    def test_corrupt_updater_trailer_fails_loudly(self, two_ranks,
                                                  tmp_path):
        """A checkpoint whose updater-state trailer is truncated MID-READ
        must fail the restore — only a CLEAN end-of-stream means 'legacy
        checkpoint without updater state'. Silently accepting a torn
        trailer would leave optimizer accumulators at whatever they were."""
        import io
        t0 = AsyncMatrixTable(6, 2, name="ctrl", updater="adagrad",
                              ctx=two_ranks[0])
        AsyncMatrixTable(6, 2, name="ctrl", updater="adagrad",
                         ctx=two_ranks[1])
        t0.add_rows(np.arange(6), np.ones((6, 2), np.float32))
        buf = io.BytesIO()
        t0.store(buf)
        raw = buf.getvalue()
        # cut inside the trailer HEADER (the second .npy magic): the exact
        # window the old code misread as "legacy stream"
        second_magic = raw.index(b"\x93NUMPY", raw.index(b"\x93NUMPY") + 1)
        with pytest.raises(ValueError):
            t0.load(io.BytesIO(raw[: second_magic + 4]))
        # a clean data-only stream (true legacy) still loads fine
        legacy = io.BytesIO()
        np.save(legacy, t0.get(), allow_pickle=False)
        legacy.seek(0)
        t0.load(legacy)
        np.testing.assert_allclose(t0.get_rows(np.arange(6)).shape, (6, 2))

    def test_checkpoint_walks_async_tables(self, tmp_path):
        """checkpoint.save/restore covers async tables through the same Zoo
        registry walk as the collective tables (store pulls the whole table
        off the shards; load pushes ranges back)."""
        import multiverso_tpu as mv
        from multiverso_tpu import checkpoint
        mv.init()
        try:
            t = mv.AsyncMatrixTable(8, 3, name="ck_async")
            a = mv.AsyncArrayTable(5, name="ck_async_arr")
            t.add_rows([2, 6], np.ones((2, 3), np.float32))
            a.add(np.arange(5, dtype=np.float32))
            checkpoint.save(str(tmp_path), tag="s1")
            t.add(np.full((8, 3), 9.0, np.float32))     # diverge
            a.add(np.ones(5, np.float32))
            n = checkpoint.restore(str(tmp_path), tag="s1")
            assert n >= 2
            np.testing.assert_allclose(t.get_row(2), 1.0)
            np.testing.assert_allclose(t.get_row(0), 0.0)
            np.testing.assert_allclose(a.get(), np.arange(5))
        finally:
            mv.shutdown()

    def test_checkpoint_restores_updater_state(self, tmp_path):
        """Async store/load round-trips the shard's optimizer accumulators
        (adagrad g²) — restoring must NOT silently reset them (sync-table
        parity: table.py store() persists ustate)."""
        import jax
        import multiverso_tpu as mv
        from multiverso_tpu import checkpoint
        mv.init()
        try:
            t = mv.AsyncMatrixTable(8, 3, name="ck_async_ada",
                                    updater="adagrad")
            t.add_rows([1, 2], np.ones((2, 3), np.float32))
            t.flush()
            before = [np.asarray(l) for l in jax.tree.leaves(t._shard._ustate)]
            assert any(np.abs(b).sum() > 0 for b in before)  # g² accumulated
            checkpoint.save(str(tmp_path), tag="u1")
            t.add_rows([1, 2], np.ones((2, 3), np.float32))  # diverge
            t.flush()
            checkpoint.restore(str(tmp_path), tag="u1")
            after = [np.asarray(l) for l in jax.tree.leaves(t._shard._ustate)]
            assert len(after) == len(before)
            for b, a in zip(before, after):
                np.testing.assert_allclose(a, b, rtol=1e-6)
        finally:
            mv.shutdown()


class TestAsyncSparseKVTable:
    """Hash-sharded sparse keys + FTRL payloads on the uncoordinated plane
    (ref sparse_table.h:1-306, ftrl_sparse_table.h:1-90,
    model/ps_model.cpp:24-41 — the reference's flagship sparse-LR tables)."""

    def _pair(self, two_ranks, **kw):
        from multiverso_tpu.ps.tables import AsyncSparseKVTable
        return [AsyncSparseKVTable(3, name="skv", ctx=c, **kw)
                for c in two_ranks]

    def test_hash_partition_and_accumulation(self, two_ranks):
        t0, t1 = self._pair(two_ranks)
        # arbitrary sparse keys, both parities (owner = key % 2)
        keys = np.array([7, 1_000_003, 42, 88])
        t0.add_rows(keys, np.ones((4, 3), np.float32))
        t1.add_rows(keys[:2], 2 * np.ones((2, 3), np.float32))
        got = t0.get_rows(keys)
        np.testing.assert_allclose(got[:2], 3.0)   # 1 + 2
        np.testing.assert_allclose(got[2:], 1.0)
        # a never-touched key reads as zeros (fresh slot)
        np.testing.assert_allclose(t1.get_rows([555])[0], 0.0)
        # duplicate keys in one call pre-accumulate
        t1.add_rows([9, 9], np.ones((2, 3), np.float32))
        np.testing.assert_allclose(t0.get_rows([9])[0], 2.0)

    def test_negative_and_float_keys_rejected(self, two_ranks):
        t0, _ = self._pair(two_ranks)
        with pytest.raises(IndexError):
            t0.add_rows([-1], np.ones((1, 3), np.float32))
        with pytest.raises(TypeError):
            t0.get_rows(np.array([1.5]))

    def test_ftrl_over_the_wire(self, two_ranks):
        """FTRL z/n live as shard state; pushing raw gradients moves the
        stored weight the way the proximal update says (sign-opposite to
        the gradient, zero until |z| clears lambda1)."""
        t0, t1 = self._pair(two_ranks, updater="ftrl")
        g = np.full((1, 3), 0.5, np.float32)
        key = [12345]
        for _ in range(20):
            t0.add_rows(key, g)
        w = t0.get_rows(key)[0]
        assert np.all(w < 0)                     # steady +g pushes w negative
        assert np.all(np.abs(w) < 10)
        # the other rank sees the same uncoordinated state
        np.testing.assert_allclose(t1.get_rows(key)[0], w, rtol=1e-6)

    def test_sparse_get_stale_protocol(self, two_ranks):
        t0, t1 = self._pair(two_ranks, num_workers=2)
        keys = np.array([3, 4, 5, 6])
        first = t0.get_rows_sparse(keys, worker_id=0)
        np.testing.assert_allclose(first, 0.0)
        assert t0.last_transfer_rows == 4        # first pull: everything
        again = t0.get_rows_sparse(keys, worker_id=0)
        assert t0.last_transfer_rows == 0        # all fresh now
        np.testing.assert_allclose(again, 0.0)
        # rank 1 touches ONE key -> exactly one row re-crosses the wire
        t1.add_rows([5], np.ones((1, 3), np.float32))
        got = t0.get_rows_sparse(keys, worker_id=0)
        assert t0.last_transfer_rows == 1
        np.testing.assert_allclose(got[2], 1.0)

    def test_dense_get_and_bound(self, two_ranks):
        t0, _ = self._pair(two_ranks, num_row=10)
        t0.add_rows([2, 9], np.ones((2, 3), np.float32))
        dense = t0.get()
        assert dense.shape == (10, 3)
        np.testing.assert_allclose(dense[[2, 9]], 1.0)
        np.testing.assert_allclose(dense[0], 0.0)
        with pytest.raises(IndexError):
            t0.get_rows([10])

    def test_checkpoint_roundtrip_with_state(self, two_ranks, tmp_path):
        t0, t1 = self._pair(two_ranks, updater="adagrad")
        t0.add_rows([1, 2, 1001], np.ones((3, 3), np.float32))
        t1.flush(), t0.flush()
        saved_rows = t0.get_rows([1, 2, 1001])
        with open(tmp_path / "skv.ck", "wb") as f:
            t0.store(f)
        t0.add_rows([1, 7], np.ones((2, 3), np.float32))  # diverge
        with open(tmp_path / "skv.ck", "rb") as f:
            t0.load(f)
        np.testing.assert_allclose(t0.get_rows([1, 2, 1001]), saved_rows)
        np.testing.assert_allclose(t0.get_rows([7])[0], 0.0)
        # adagrad accumulators restored: the next identical add moves the
        # weight by the same amount it did the first time after the save
        before = t0.get_rows([1])[0].copy()
        t0.add_rows([1], np.ones((1, 3), np.float32))
        step_after_restore = t0.get_rows([1])[0] - before
        assert np.all(np.abs(step_after_restore) > 0)

    def test_slot_growth_past_capacity(self, two_ranks):
        from multiverso_tpu.ps.tables import AsyncSparseKVTable
        t0 = AsyncSparseKVTable(2, name="skv_grow", ctx=two_ranks[0])
        AsyncSparseKVTable(2, name="skv_grow", ctx=two_ranks[1])
        n = 3000   # > initial 1024-slot capacity per shard
        keys = np.arange(n)
        t0.add_rows(keys, np.ones((n, 2), np.float32))
        got = t0.get_rows(keys[::7])
        np.testing.assert_allclose(got, 1.0)


class TestPipelineSparseGets:
    """Prefetch-overlapped sparse pulls (ref matrix.cpp:407-418 is_pipeline
    doubled its per-worker slots for exactly this; here overlapped pulls are
    first-class). Exact rows-transferred assertions."""

    def test_two_pulls_in_flight(self, two_ranks):
        t0 = AsyncSparseMatrixTable(12, 2, num_workers=2, name="pp",
                                    ctx=two_ranks[0])
        t1 = AsyncSparseMatrixTable(12, 2, num_workers=2, name="pp",
                                    ctx=two_ranks[1])
        lo, hi = np.arange(6), np.arange(6, 12)
        # double-buffer: both pulls dispatched before either is consumed
        a = t0.get_rows_sparse_async(lo, worker_id=0)
        b = t0.get_rows_sparse_async(hi, worker_id=0)
        ra = t0.wait(a)
        n_a = t0.last_transfer_rows
        rb = t0.wait(b)
        n_b = t0.last_transfer_rows
        np.testing.assert_allclose(ra, 0.0)
        np.testing.assert_allclose(rb, 0.0)
        assert n_a == 6 and n_b == 6          # first epoch: everything stale
        # steady state: overlapped pulls of fresh rows transfer NOTHING
        a = t0.get_rows_sparse_async(lo, worker_id=0)
        b = t0.get_rows_sparse_async(hi, worker_id=0)
        t0.wait(a); assert t0.last_transfer_rows == 0
        t0.wait(b); assert t0.last_transfer_rows == 0
        # a peer dirties one row per block -> exactly one row per pull
        t1.add_rows([2, 8], np.ones((2, 2), np.float32))
        a = t0.get_rows_sparse_async(lo, worker_id=0)
        b = t0.get_rows_sparse_async(hi, worker_id=0)
        ra = t0.wait(a); assert t0.last_transfer_rows == 1
        rb = t0.wait(b); assert t0.last_transfer_rows == 1
        np.testing.assert_allclose(ra[2], 1.0)
        np.testing.assert_allclose(rb[2], 1.0)   # row 8 -> position 2 in hi

    def test_out_of_order_wait_stays_correct(self, two_ranks):
        """Waiting the second pull before the first, with OVERLAPPING rows:
        worst case the client self-heals with a plain re-pull — values are
        always right."""
        t0 = AsyncSparseMatrixTable(8, 2, num_workers=2, name="oo",
                                    ctx=two_ranks[0])
        t1 = AsyncSparseMatrixTable(8, 2, num_workers=2, name="oo",
                                    ctx=two_ranks[1])
        t1.add_rows(np.arange(8), np.ones((8, 2), np.float32))
        a = t0.get_rows_sparse_async(np.arange(8), worker_id=0)
        b = t0.get_rows_sparse_async(np.arange(4), worker_id=0)
        rb = t0.wait(b)    # consumed before a
        ra = t0.wait(a)
        np.testing.assert_allclose(ra, 1.0)
        np.testing.assert_allclose(rb, 1.0)

    def test_threaded_prefetch_against_training(self, two_ranks):
        """An AsyncBuffer-style prefetch thread pulls while the main thread
        pushes — no corruption, final state exact."""
        t0 = AsyncSparseMatrixTable(16, 2, num_workers=2, name="th",
                                    ctx=two_ranks[0])
        AsyncSparseMatrixTable(16, 2, num_workers=2, name="th",
                               ctx=two_ranks[1])
        stop, errs = threading.Event(), []

        def prefetch():
            try:
                while not stop.is_set():
                    t0.get_rows_sparse(np.arange(16), worker_id=0)
            except Exception as e:   # pragma: no cover
                errs.append(e)

        th = threading.Thread(target=prefetch)
        th.start()
        for _ in range(30):
            t0.add_rows([1, 9], np.ones((2, 2), np.float32))
        t0.flush()
        stop.set()
        th.join(timeout=30)
        assert not errs, errs
        got = t0.get_rows_sparse(np.arange(16), worker_id=0)
        np.testing.assert_allclose(got[1], 30.0)
        np.testing.assert_allclose(got[9], 30.0)
        np.testing.assert_allclose(got[0], 0.0)

    def test_out_of_order_wait_does_not_revert_newer_data(self, two_ranks):
        """An older pull consumed AFTER a newer one must not overwrite the
        newer cached rows (the server bit is clear by then — a revert would
        be served forever)."""
        t0 = AsyncSparseMatrixTable(8, 2, num_workers=2, name="rv",
                                    ctx=two_ranks[0])
        t1 = AsyncSparseMatrixTable(8, 2, num_workers=2, name="rv",
                                    ctx=two_ranks[1])
        t0.get_rows_sparse(np.arange(8), worker_id=0)          # warm
        t1.add_rows([1], np.ones((1, 2), np.float32))          # v = 1
        a = t0.get_rows_sparse_async([1, 2], worker_id=0)
        with t0._lock:   # stage: A fully processed server-side before B
            futs_a = t0._pending[a][0]
        for f in futs_a:
            f.result(timeout=10)
        t1.add_rows([1], np.ones((1, 2), np.float32))          # v = 2
        b = t0.get_rows_sparse_async([1, 2, 3], worker_id=0)
        rb = t0.wait(b)                                        # newer first
        ra = t0.wait(a)                                        # older second
        np.testing.assert_allclose(rb[0], 2.0)
        np.testing.assert_allclose(ra[0], 2.0)   # not reverted to 1.0
        again = t0.get_rows_sparse([1], worker_id=0)
        assert t0.last_transfer_rows == 0        # cache kept the newer row
        np.testing.assert_allclose(again[0], 2.0)


class TestShutdownQuiesce:
    """The MV_ShutDown-barrier analogue (ref src/zoo.cpp:103-115): a rank
    keeps serving until live peers also reach shutdown."""

    def test_both_ranks_converge_quickly(self, two_ranks, tmp_path):
        from multiverso_tpu.utils import config
        config.set_flag("ps_shutdown_grace", 30.0)
        t0 = time.monotonic()
        th = threading.Thread(target=lambda: two_ranks[0].quiesce())
        th.start()
        time.sleep(0.15)            # rank 0 waits on rank 1's mark
        two_ranks[1].quiesce()
        th.join(timeout=10)
        assert not th.is_alive()
        assert time.monotonic() - t0 < 10

    def test_timeout_proceeds_without_peer(self, two_ranks):
        from multiverso_tpu.utils import config
        config.set_flag("ps_shutdown_grace", 0.4)
        t0 = time.monotonic()
        two_ranks[0].quiesce()      # rank 1 never marks
        dt = time.monotonic() - t0
        assert 0.3 < dt < 5.0       # bounded by the grace, no hang

    def test_observed_dead_peer_skipped(self, two_ranks):
        from multiverso_tpu.utils import config
        config.set_flag("ps_shutdown_grace", 30.0)
        t0_ctx, t1_ctx = two_ranks
        t = AsyncMatrixTable(8, 2, name="qd", ctx=t0_ctx)
        AsyncMatrixTable(8, 2, name="qd", ctx=t1_ctx)
        t.add_rows([7], np.ones((1, 2), np.float32))  # rank-1-owned: connect
        t1_ctx.service.close()      # rank 1 "dies"
        config.set_flag("ps_timeout", 3.0)
        with pytest.raises(Exception):
            t.get_rows([7])         # observe the death -> dead_ranks
        assert 1 in t0_ctx.service.dead_ranks()
        t0 = time.monotonic()
        t0_ctx.quiesce()            # dead peer skipped, returns immediately
        assert time.monotonic() - t0 < 5.0

    def test_stale_markers_from_previous_run_ignored(self, tmp_path):
        """A reused rendezvous dir's leftover quiesce markers must not
        satisfy the current run's barrier: markers are stamped with the
        incarnation's published address."""
        rdv = FileRendezvous(str(tmp_path / "r"))
        rdv.mark(1, "ps_quiesce", "127.0.0.1:1111")   # previous run
        rdv.publish(1, "127.0.0.1:2222")              # current incarnation
        assert not rdv.wait_mark(1, "ps_quiesce", 0.2,
                                 expect="127.0.0.1:2222")
        rdv.mark(1, "ps_quiesce", "127.0.0.1:2222")   # current run quiesces
        assert rdv.wait_mark(1, "ps_quiesce", 1.0,
                             expect="127.0.0.1:2222")


class TestMultiHostBind:
    def test_wildcard_bind_publishes_routable_addr(self, tmp_path):
        """-ps_host 0.0.0.0 (the multi-host setting) must publish a
        ROUTABLE address, never the wildcard itself — peers connect to
        what the rendezvous says."""
        from multiverso_tpu.ps.service import _routable_ip
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        s0 = PSService(0, 2, rdv, host="0.0.0.0")
        s1 = PSService(1, 2, rdv, host="0.0.0.0")
        try:
            host0 = s0.addr.rsplit(":", 1)[0]
            assert host0 not in ("0.0.0.0", "", "::")
            assert host0 == _routable_ip()
            assert rdv.lookup(0, 5.0) == s0.addr
            # a real connection works through the published address
            c0 = PSContext(0, 2, s0)
            c1 = PSContext(1, 2, s1)
            t0 = AsyncMatrixTable(8, 2, name="wb", ctx=c0)
            AsyncMatrixTable(8, 2, name="wb", ctx=c1)
            t0.add_rows([6], np.ones((1, 2), np.float32))  # rank-1-owned
            np.testing.assert_allclose(t0.get_rows([6])[0], 1.0)
        finally:
            s0.close()
            s1.close()
