"""Execute the Lua binding under a real Lua interpreter (lupa).

Ports the reference Lua test battery (ref binding/lua/test.lua:1-79 —
array add/get loops and matrix full+row adds with closed-form expected
values) to drive the REAL shim (examples/lua/multiverso.lua) against the
REAL C ABI (native/libmultiverso.so).

This image has no Lua runtime and zero egress (``pip download lupa``
finds nothing cached), so here the module SKIPS; anywhere lupa is
installed it runs for real. lupa embeds plain Lua, not LuaJIT, so the
shim's ``require('ffi')`` is satisfied by a faithful ffi->ctypes bridge
(cdef/load/new covering exactly the constructs multiverso.lua uses);
the binding file itself is executed unmodified. The always-on in-image
guarantees remain: the compiled C driver (native/mv_capi_test.c) calls
every ABI symbol with assertions, and tests/test_lua_cdef.py pins the
cdef to the .so exports and the C++ signatures type-for-type.
"""

import ctypes
import os

import numpy as np
import pytest

lupa = pytest.importorskip("lupa")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LUA = os.path.join(_REPO, "examples", "lua", "multiverso.lua")
_SO = os.path.join(_REPO, "multiverso_tpu", "native", "libmultiverso.so")

pytestmark = pytest.mark.skipif(not os.path.exists(_SO),
                                reason="libmultiverso.so not built "
                                       "(make -C multiverso_tpu/native capi)")


class _CLib:
    """ctypes stand-in for LuaJIT's ``ffi.load`` result: typed MV_*
    callables (argtypes mirror tests/test_bindings.py's proven setup)."""

    def __init__(self, path: str):
        lib = ctypes.CDLL(path)
        fp = ctypes.POINTER(ctypes.c_float)
        ip = ctypes.POINTER(ctypes.c_int)
        hp = ctypes.POINTER(ctypes.c_void_p)
        lib.MV_NewArrayTable.argtypes = [ctypes.c_int, hp]
        lib.MV_GetArrayTable.argtypes = [ctypes.c_void_p, fp, ctypes.c_int]
        lib.MV_AddArrayTable.argtypes = lib.MV_GetArrayTable.argtypes
        lib.MV_AddAsyncArrayTable.argtypes = lib.MV_GetArrayTable.argtypes
        lib.MV_NewAsyncArrayTable.argtypes = [ctypes.c_int, hp]
        lib.MV_NewMatrixTable.argtypes = [ctypes.c_int, ctypes.c_int, hp]
        lib.MV_NewAsyncMatrixTable.argtypes = lib.MV_NewMatrixTable.argtypes
        lib.MV_GetMatrixTableAll.argtypes = [ctypes.c_void_p, fp,
                                             ctypes.c_int]
        lib.MV_AddMatrixTableAll.argtypes = lib.MV_GetMatrixTableAll.argtypes
        lib.MV_AddAsyncMatrixTableAll.argtypes = \
            lib.MV_GetMatrixTableAll.argtypes
        lib.MV_GetMatrixTableByRows.argtypes = [
            ctypes.c_void_p, fp, ctypes.c_int, ip, ctypes.c_int]
        lib.MV_AddMatrixTableByRows.argtypes = \
            lib.MV_GetMatrixTableByRows.argtypes
        lib.MV_AddAsyncMatrixTableByRows.argtypes = \
            lib.MV_GetMatrixTableByRows.argtypes
        self._lib = lib

    def __getattr__(self, name):
        if name.startswith("MV_"):
            return getattr(self._lib, name)
        raise AttributeError(name)


class _FFIShim:
    """The subset of LuaJIT's ffi module that multiverso.lua uses."""

    def cdef(self, src):
        assert "MV_Init" in src   # sanity: the real cdef block arrived

    def load(self, name):
        assert name == "multiverso"
        return _CLib(_SO)

    def new(self, spec, n=None):
        if spec == "TableHandler[1]":
            return (ctypes.c_void_p * 1)()
        if spec == "float[?]":
            return (ctypes.c_float * int(n))()
        if spec == "int[?]":
            return (ctypes.c_int * int(n))()
        raise NotImplementedError(spec)


def _load_binding():
    rt = lupa.LuaRuntime(unpack_returned_tuples=True)
    shim = _FFIShim()
    rt.globals()["__py_ffi"] = shim
    rt.execute("package.preload['ffi'] = function() return __py_ffi end")
    src = open(_LUA).read()
    module = rt.execute("return (function()\n" + src + "\nend)()")
    # let demo scripts require('multiverso') and find the shim
    rt.globals()["__py_mv"] = module
    rt.execute("package.preload['multiverso'] = function()"
               " return __py_mv end")
    return rt, module


def _farray(*vals):
    return (ctypes.c_float * len(vals))(*vals)


def test_lua_array_table_roundtrip():
    """ref test.lua testArray: add twice, read back the doubled range."""
    rt, M = _load_binding()
    M["init"]()
    assert int(M["num_workers"]()) == 1
    size = 64
    t = M["new_array_table"](size)
    delta = _farray(*range(1, size + 1))
    t["add"](t, delta)
    t["add"](t, delta)
    M["barrier"]()
    out = (ctypes.c_float * size)()
    t["get"](t, out)
    np.testing.assert_allclose(list(out),
                               2.0 * np.arange(1, size + 1))


def test_lua_async_tables_same_accessor_surface():
    """The uncoordinated-plane constructors (beyond the reference C API)
    return handles the ordinary accessors drive unchanged; MV_Barrier
    flushes the async ops so the Lua-side fence semantics match
    test.lua's barrier-then-get pattern."""
    rt, M = _load_binding()
    M["init"]()
    size = 32
    t = M["new_async_array_table"](size)
    delta = _farray(*range(1, size + 1))
    t["add"](t, delta)
    t["add_async"](t, delta)
    M["barrier"]()
    out = (ctypes.c_float * size)()
    t["get"](t, out)
    np.testing.assert_allclose(list(out), 2.0 * np.arange(1, size + 1))

    num_row, num_col = 6, 4
    m = M["new_async_matrix_table"](num_row, num_col)
    full = _farray(*([1.0] * (num_row * num_col)))
    m["add"](m, full)
    m["add_async"](m, full)
    M["barrier"]()
    mo = (ctypes.c_float * (num_row * num_col))()
    m["get"](m, mo)
    np.testing.assert_allclose(list(mo), 2.0)


def test_lua_xor_demo_converges():
    """The reference's Lua demo tier (ref binding/lua/demos/xor/
    xor-multiverso.lua — an MLP whose params live in an ArrayTable):
    the plain-Lua port must train XOR to low error through the real
    shim + C ABI, delta-push convention included."""
    rt, _ = _load_binding()
    src = open(os.path.join(_REPO, "examples", "lua",
                            "xor_demo.lua")).read()
    demo = rt.execute("return (function()\n" + src + "\nend)()")
    final_loss = float(demo["run"](3000, 2.0))
    assert final_loss < 0.05, final_loss


def test_lua_matrix_table_full_and_rows():
    """ref test.lua testMatrix (single worker): one full-table add + one
    row add; touched rows read back doubled, untouched rows single."""
    rt, M = _load_binding()
    M["init"]()
    num_row, num_col = 11, 10
    size = num_row * num_col
    t = M["new_matrix_table"](num_row, num_col)
    full = _farray(*range(1, size + 1))
    t["add"](t, full)
    row_ids = [0, 1, 5, 10]
    rows_c = (ctypes.c_int * len(row_ids))(*row_ids)
    row_vals = _farray(*[r * num_col + c + 1
                         for r in row_ids for c in range(num_col)])
    t["add_rows"](t, row_vals, rows_c, len(row_ids))
    M["barrier"]()
    out = (ctypes.c_float * size)()
    t["get"](t, out)
    got = np.asarray(list(out)).reshape(num_row, num_col)
    base = np.arange(1, size + 1, dtype=np.float64).reshape(num_row,
                                                            num_col)
    expect = base.copy()
    expect[row_ids] *= 2           # touched rows got the value twice
    np.testing.assert_allclose(got, expect)
    # row-batch get agrees
    out_rows = (ctypes.c_float * (len(row_ids) * num_col))()
    t["get_rows"](t, rows_c, len(row_ids), out_rows)
    np.testing.assert_allclose(
        np.asarray(list(out_rows)).reshape(len(row_ids), num_col),
        expect[row_ids])
