"""Tensor parallelism (parallel/tp.py + transformer tp_axis): sharded
compute vs the unsharded oracle on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import multiverso_tpu as mv
from multiverso_tpu.models import transformer as tfm
from multiverso_tpu.parallel import tp


@pytest.fixture(autouse=True)
def _init():
    yield
    if mv.Zoo.get().started:
        mv.shutdown()


class TestPrimitives:
    def test_column_then_row_matches_dense(self):
        mesh = Mesh(np.asarray(jax.devices()), ("tp",))
        mv.init(mesh=mesh)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(16, 32)), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
        expect = jax.nn.gelu(x @ w1) @ w2
        got = jax.jit(lambda x, a, b: tp.mlp_block(x, a, b))(x, w1, w2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_dp_sharded_input_stays_sharded(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("dp", "tp"))
        mv.init(mesh=mesh)
        rng = np.random.default_rng(2)
        x = jax.device_put(
            jnp.asarray(rng.normal(size=(8, 16)), jnp.float32),
            NamedSharding(mesh, P("dp", None)))
        w1 = jnp.asarray(rng.normal(size=(16, 32)), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
        expect = jax.nn.gelu(x @ w1) @ w2
        got = tp.mlp_block(x, w1, w2, x_spec=P("dp"))
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)
        # batch dim must stay dp-sharded end to end, not gathered
        h = tp.column_parallel(x, w1, x_spec=P("dp"))
        assert {s.data.shape for s in h.addressable_shards} == {(4, 8)}

    def test_column_output_stays_sharded(self):
        mesh = Mesh(np.asarray(jax.devices()), ("tp",))
        mv.init(mesh=mesh)
        x = jnp.ones((4, 16), jnp.float32)
        w = jnp.ones((16, 32), jnp.float32)
        y = tp.column_parallel(x, w)
        assert y.shape == (4, 32)
        shard_cols = {s.data.shape[1] for s in y.addressable_shards}
        assert shard_cols == {32 // 8}


class TestTransformerTP:
    def _params_and_batch(self, cfg, seed=0):
        params = tfm.init_params(cfg, seed=seed)
        rng = np.random.default_rng(seed + 1)
        toks = rng.integers(0, cfg.vocab_size, (4, cfg.max_seq + 1))
        tok = jnp.asarray(toks[:, :-1], jnp.int32)
        tgt = jnp.asarray(toks[:, 1:], jnp.int32)
        return params, tok, tgt

    def test_pure_tp_matches_unsharded(self):
        mesh = Mesh(np.asarray(jax.devices()), ("tp",))
        mv.init(mesh=mesh)
        base = tfm.TransformerConfig(vocab_size=64, dim=32, num_heads=8,
                                     num_layers=2, max_seq=16, attn="local")
        params, tok, tgt = self._params_and_batch(base)
        expect = tfm.loss_fn(params, tok, tgt, base)

        cfg = base._replace(tp_axis="tp")
        sharded = tfm.shard_params_tp(params, cfg)
        # params must really be distributed: vocab-dim shard of embed
        emb_rows = {s.data.shape[0]
                    for s in sharded["embed"].addressable_shards}
        assert emb_rows == {base.vocab_size // 8}
        got = jax.jit(lambda p, a, b: tfm.loss_fn(p, a, b, cfg))(
            sharded, tok, tgt)
        np.testing.assert_allclose(float(got), float(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_dp_tp_sp_train_step_matches_local(self):
        devices = np.asarray(jax.devices()).reshape(2, 2, 2)
        mesh = Mesh(devices, ("dp", "tp", "sp"))
        mv.init(mesh=mesh)
        base = tfm.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                     num_layers=2, max_seq=16, attn="local")
        params, tok, tgt = self._params_and_batch(base, seed=3)
        with jax.default_matmul_precision("float32"):
            _, expect_loss = tfm.make_train_step(base, 0.1)(params, tok, tgt)

        cfg = base._replace(attn="ring", batch_axis="dp", seq_axis="sp",
                            tp_axis="tp")
        sharded = tfm.shard_params_tp(params, cfg, mesh)
        stok = tfm.shard_batch(np.asarray(tok), cfg, mesh)
        stgt = tfm.shard_batch(np.asarray(tgt), cfg, mesh)
        with jax.default_matmul_precision("float32"):
            step = jax.jit(tfm.make_train_step(cfg, 0.1))
            new_params, loss = step(sharded, stok, stgt)
        np.testing.assert_allclose(float(loss), float(expect_loss),
                                   rtol=1e-4, atol=1e-5)
        for leaf in jax.tree.leaves(new_params):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_ring_head_sharding_matches_oracle(self):
        from multiverso_tpu.parallel import ring
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mesh = Mesh(devices, ("tp", "sp"))
        mv.init(mesh=mesh)
        rng = np.random.default_rng(5)
        q, k, v = (jnp.asarray(rng.normal(size=(2, 4, 16, 8)), jnp.float32)
                   for _ in range(3))
        expect = ring.reference_attention(q, k, v, causal=True)
        got = ring.ring_attention(q, k, v, axis_name="sp", causal=True,
                                  head_axis="tp", precision="float32")
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_rejects_indivisible_heads(self):
        from multiverso_tpu.parallel import ring
        devices = np.asarray(jax.devices()).reshape(8, 1)
        mesh = Mesh(devices, ("tp", "sp"))
        mv.init(mesh=mesh)
        q = jnp.zeros((2, 6, 8, 4), jnp.float32)  # 6 heads on 8 tp shards
        with pytest.raises(ValueError, match="heads"):
            ring.ring_attention(q, q, q, axis_name="sp", head_axis="tp")

    def test_shard_params_tp_rejects_unset_axis(self):
        mv.init(mesh=Mesh(np.asarray(jax.devices()), ("tp",)))
        cfg = tfm.TransformerConfig(vocab_size=32, dim=16, num_heads=2,
                                    num_layers=1, max_seq=8)
        with pytest.raises(ValueError, match="tp_axis"):
            tfm.shard_params_tp(tfm.init_params(cfg), cfg)

    def test_ring_default_axis_fallback_still_shards(self):
        # attn='ring' with seq_axis=None must fall back to the Zoo default
        # axis (sequence-parallel), not silently run dense attention
        from multiverso_tpu.parallel import ring
        mv.init(mesh=Mesh(np.asarray(jax.devices()), ("mv",)))
        rng = np.random.default_rng(6)
        q, k, v = (jnp.asarray(rng.normal(size=(2, 2, 16, 8)), jnp.float32)
                   for _ in range(3))
        expect = ring.reference_attention(q, k, v, causal=True)
        got = ring.ring_attention(q, k, v, causal=True, precision="float32")
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_ulysses_rejects_tp_axis(self):
        devices = np.asarray(jax.devices()).reshape(2, 4)
        mv.init(mesh=Mesh(devices, ("tp", "sp")))
        cfg = tfm.TransformerConfig(vocab_size=32, dim=16, num_heads=4,
                                    num_layers=1, max_seq=8, attn="ulysses",
                                    seq_axis="sp", tp_axis="tp")
        params = tfm.init_params(cfg)
        tok = jnp.zeros((2, 8), jnp.int32)
        with pytest.raises(ValueError, match="ulysses"):
            tfm.forward(params, tok, cfg)


class TestFSDP:
    def test_fsdp_train_matches_local_and_stores_shards(self):
        devices = np.asarray(jax.devices())
        mesh = Mesh(devices, ("fsdp",))
        mv.init(mesh=mesh)
        base = tfm.TransformerConfig(
            vocab_size=64, dim=32, num_heads=4, num_layers=2, max_seq=16,
            attn="local")
        params = tfm.init_params(base, seed=7)
        rng = np.random.default_rng(8)
        toks = rng.integers(0, 64, (8, 17)).astype(np.int32)
        tok, tgt = (jnp.asarray(toks[:, :-1], jnp.int32),
                    jnp.asarray(toks[:, 1:], jnp.int32))
        with jax.default_matmul_precision("float32"):
            _, expect_loss = tfm.make_train_step(base, 0.1)(params, tok, tgt)

        cfg = base._replace(batch_axis="fsdp")
        sharded = tfm.shard_params_fsdp(params, cfg, mesh)
        # every chip stores 1/8 of the big leaves
        emb = sharded["embed"].addressable_shards
        assert {s.data.shape[0] for s in emb} == {64 // 8}
        w1 = sharded["layers"]["w1"].addressable_shards
        assert {s.data.shape[1] for s in w1} == {32 // 8}
        stok = tfm.shard_batch(np.asarray(tok), cfg, mesh)
        stgt = tfm.shard_batch(np.asarray(tgt), cfg, mesh)
        with jax.default_matmul_precision("float32"):
            new_params, loss = jax.jit(tfm.make_train_step(cfg, 0.1))(
                sharded, stok, stgt)
        np.testing.assert_allclose(float(loss), float(expect_loss),
                                   rtol=1e-4, atol=1e-5)
        # updated params keep the FSDP layout (no silent re-replication)
        emb2 = new_params["embed"].addressable_shards
        assert {s.data.shape[0] for s in emb2} == {64 // 8}

    def test_fsdp_moe_param_tree(self):
        devices = np.asarray(jax.devices())
        mesh = Mesh(devices, ("fsdp",))
        mv.init(mesh=mesh)
        cfg = tfm.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                    num_layers=2, max_seq=16, attn="local",
                                    moe_experts=4)
        sharded = tfm.shard_params_fsdp(tfm.init_params(cfg, seed=1), cfg,
                                        mesh)
        w1 = sharded["layers"]["moe_w1"].addressable_shards
        assert {s.data.shape[2] for s in w1} == {32 // 8}
