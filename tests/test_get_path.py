"""Read-path overhaul (ISSUE 5): off-lock snapshot serving (epoch pins,
copy-on-write applies, donate gating), chunk-streamed get replies, the
client get coalescer, the sparse dirty-bit/epoch atomicity fix, and the
get_rows(out=) shape validation — tier-1 coverage so a regression in any
layer surfaces without a full bench run."""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.ps import service as svc
from multiverso_tpu.ps.shard import RowShard
from multiverso_tpu.ps.tables import AsyncMatrixTable
from multiverso_tpu.updaters import AddOption, get_updater
from multiverso_tpu.utils import config
from multiverso_tpu.utils.dashboard import Dashboard


def _row_shard(n=32, cols=4, updater="sgd", workers=0):
    return RowShard(0, n, cols, np.float32,
                    get_updater(updater, num_workers=max(workers, 1),
                                dtype=np.float32),
                    f"shard_{updater}_{workers}", num_workers=workers)


def _add(shard, ids, vals, opt=None):
    shard.handle(svc.MSG_ADD_ROWS,
                 {"table": shard.name,
                  "opt": (opt or AddOption())._asdict()},
                 [np.asarray(ids, np.int64),
                  np.asarray(vals, np.float32)])


def _get(shard, ids, **meta):
    _, arrays = shard.handle(svc.MSG_GET_ROWS,
                             dict({"table": shard.name}, **meta),
                             [np.asarray(ids, np.int64)])
    return np.asarray(arrays[0])


# ---------------------------------------------------------------------- #
# epoch pins: refcounting, copy-on-write, donate gating (no sockets)
# ---------------------------------------------------------------------- #
class TestEpochPins:
    def test_pin_release_refcount(self):
        s = _row_shard()
        pin = s._pin_data()
        assert s._cur_pins == 1 and s._data_pinned()
        pin2 = s._pin_data()
        assert s._cur_pins == 2
        s._release_data(pin)
        s._release_data(pin2)
        assert s._cur_pins == 0 and not s._data_pinned()

    def test_np_mode_apply_cows_while_pinned(self):
        """An in-place numpy apply racing a pinned read must copy: the
        pinned snapshot keeps its pre-apply bytes, the shard moves on."""
        s = _row_shard(updater="sgd")
        assert s._np_mode
        _add(s, [1], [[1, 1, 1, 1]])
        pin = s._pin_data()
        before = np.asarray(pin.data).copy()
        buf_id = id(s._data)
        _add(s, [1], [[2, 2, 2, 2]])          # must NOT touch the pin
        assert id(s._data) != buf_id           # copy-on-write swapped
        assert s._stat_cow == 1
        assert np.array_equal(np.asarray(pin.data), before)
        assert s._data[1, 0] == -3.0           # sgd: 0 - 1 - 2
        s._release_data(pin)
        # stale release against a swapped buffer is a no-op, and the
        # NEXT apply (no pins) mutates in place again
        buf_id = id(s._data)
        _add(s, [1], [[1, 0, 0, 0]])
        assert id(s._data) == buf_id and s._stat_cow == 1
        # the last release of a CURRENT pin drops the identity anchor
        # too — a retired buffer must free on release, not linger in
        # _pin_buf until the next get (a full extra table of memory)
        pin2 = s._pin_data()
        _add(s, [1], [[1, 0, 0, 0]])     # COW retires pin2's buffer
        s._release_data(pin2)
        assert s._pin_buf is None and s._cur_pins == 0

    def test_jit_apply_skips_donation_while_pinned(self):
        """Device-backed shards (stateful updater -> jitted apply with
        buffer donation) must compile the non-donating variant while a
        reader pins the epoch — the pinned array stays readable."""
        s = _row_shard(updater="adagrad")
        assert not s._np_mode
        _add(s, [2], [[1, 1, 1, 1]])
        pin = s._pin_data()
        before = np.asarray(pin.data).copy()
        _add(s, [2], [[1, 1, 1, 1]])
        assert s._stat_cow == 1
        # the pinned buffer was NOT donated: still materializable
        assert np.array_equal(np.asarray(pin.data), before)
        s._release_data(pin)
        _add(s, [2], [[1, 1, 1, 1]])           # donating path again

    def test_get_serves_pinned_epoch_while_applies_flow(self):
        """The stress shape, deterministically: a get stuck mid-gather
        (injected) must neither block concurrent applies nor see any of
        their effects — it serves the pinned epoch bit-for-bit."""
        for updater in ("sgd", "adagrad"):
            s = _row_shard(n=64, updater=updater)
            _add(s, np.arange(64), np.ones((64, 4)))
            expected = (np.asarray(s._data)[:64].copy())
            in_gather = threading.Event()
            unblock = threading.Event()
            orig = s._gather_rows

            def slow_gather(local, data=None, _orig=orig):
                in_gather.set()
                assert unblock.wait(10)
                return _orig(local, data=data)

            s._gather_rows = slow_gather
            got = {}

            def getter():
                got["rows"] = _get(s, np.arange(64))

            th = threading.Thread(target=getter)
            th.start()
            assert in_gather.wait(10)
            # applies must complete while the get is mid-gather
            appliers = [threading.Thread(
                target=_add, args=(s, np.arange(64), np.full((64, 4), i)))
                for i in range(1, 4)]
            for a in appliers:
                a.start()
            for a in appliers:
                a.join(timeout=10)
            assert not any(a.is_alive() for a in appliers), \
                "applies stalled behind an in-flight get"
            unblock.set()
            th.join(timeout=10)
            assert not th.is_alive()
            # epoch consistency: the reply is the PRE-apply snapshot
            assert np.array_equal(got["rows"], expected), updater
            # ...and the applies all landed
            final = _get(s, np.arange(64))
            if updater == "sgd":
                assert np.array_equal(
                    final, expected - np.full((64, 4), 6.0))

    def test_get_full_and_set_rows_respect_pins(self):
        s = _row_shard(updater="sgd")
        _add(s, [0], [[5, 5, 5, 5]])
        pin = s._pin_data()
        before = np.asarray(pin.data).copy()
        s.handle(svc.MSG_SET_ROWS, {"table": s.name},
                 [np.array([0], np.int64),
                  np.zeros((1, 4), np.float32)])
        assert np.array_equal(np.asarray(pin.data), before)
        s._release_data(pin)
        _, arrays = s.handle(svc.MSG_GET_FULL, {"table": s.name}, [])
        assert arrays[0][0, 0] == 0.0


# ---------------------------------------------------------------------- #
# sparse dirty bits: mask snapshot/clear atomic with the epoch pin
# ---------------------------------------------------------------------- #
class TestSparseDirtyAtomicity:
    def _sparse_get(self, s, ids, wid=0):
        _, (mask, rows) = s.handle(
            svc.MSG_GET_ROWS,
            {"table": s.name, "sparse": True, "worker_id": wid},
            [np.asarray(ids, np.int64)])
        return np.asarray(mask).astype(bool), np.asarray(rows)

    def test_two_thread_no_lost_update(self):
        """Regression for the set-then-lose window: a reader thread
        keeps a mirror from stale-only pulls while a writer thread
        applies adds. Whatever interleaving happened, a final pull must
        leave the mirror EXACTLY equal to the shard — a lost dirty bit
        would leave a stale row forever."""
        n, cols, rounds = 16, 4, 60
        s = _row_shard(n=n, cols=cols, updater="sgd", workers=1)
        mirror = np.zeros((n, cols), np.float32)
        ids = np.arange(n)
        stop = threading.Event()
        errs = []

        def reader():
            try:
                while not stop.is_set():
                    mask, rows = self._sparse_get(s, ids)
                    mirror[ids[mask]] = rows
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        def writer():
            try:
                rng = np.random.default_rng(0)
                for i in range(rounds):
                    rid = rng.integers(0, n, 3)
                    _add(s, np.unique(rid),
                         rng.normal(size=(np.unique(rid).size, cols))
                         .astype(np.float32))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        rt = threading.Thread(target=reader)
        wt = threading.Thread(target=writer)
        rt.start()
        wt.start()
        wt.join(timeout=30)
        stop.set()
        rt.join(timeout=30)
        assert not errs, errs
        # one final settle pull, then the mirror must be exact
        mask, rows = self._sparse_get(s, ids)
        mirror[ids[mask]] = rows
        assert np.array_equal(mirror, np.asarray(s._data)[:n])

    def test_bit_set_after_pin_survives(self):
        """An add landing AFTER the mask clear + epoch pin re-dirties
        its rows: the reply carries the older epoch, and the set bit
        makes the next pull fetch the newer one — by construction, not
        by luck (the pin and the clear share one lock hold)."""
        s = _row_shard(n=8, updater="sgd", workers=1)
        _add(s, [3], [[1, 1, 1, 1]])
        mask, rows = self._sparse_get(s, np.arange(8))
        assert mask.all()          # first pull: everything stale
        _add(s, [3], [[1, 1, 1, 1]])
        mask2, rows2 = self._sparse_get(s, np.arange(8))
        assert mask2[3] and not mask2[0]
        assert rows2[0, 0] == -2.0


# ---------------------------------------------------------------------- #
# chunk-streamed replies + coalescer, end to end over real sockets
# ---------------------------------------------------------------------- #
def test_chunked_get_parity(two_ranks):
    """A chunk-streamed get (bf16 wire keeps the serve on the python
    plane under both fixture params) returns bit-identical bytes to the
    one-frame reply, for row gets AND the whole-table pull."""
    rows, cols = 64, 4
    vals = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
    t = AsyncMatrixTable(rows, cols, name="ckp", wire="bf16",
                         ctx=two_ranks[0])
    t2 = AsyncMatrixTable(rows, cols, name="ckp", wire="bf16",
                          ctx=two_ranks[1])
    t.set_rows(np.arange(rows), vals)
    plain = t.get_rows(np.arange(rows))
    full_plain = t.get()
    config.set_flag("get_chunk_rows", 8)
    chunked = t.get_rows(np.arange(rows))
    full_chunked = t.get()
    assert np.array_equal(plain, chunked)
    assert np.array_equal(full_plain, full_chunked)
    assert t2._shard._stat_chunks >= 8   # both pulls streamed


def test_chunked_get_with_out_buffer(two_ranks):
    rows, cols = 48, 4
    vals = np.random.default_rng(0).normal(size=(rows, cols)) \
        .astype(np.float32)
    t = AsyncMatrixTable(rows, cols, name="cko", wire="bf16",
                         ctx=two_ranks[0])
    AsyncMatrixTable(rows, cols, name="cko", wire="bf16",
                     ctx=two_ranks[1])
    t.set_rows(np.arange(rows), vals)
    ref = t.get_rows(np.arange(rows))
    config.set_flag("get_chunk_rows", 8)
    buf = np.empty((rows, cols), np.float32)
    got = t.get_rows(np.arange(rows), out=buf)
    assert got is buf and np.array_equal(buf, ref)


def test_chunked_failure_leaves_out_untouched(two_ranks):
    """A stream dying mid-way must raise with the caller's out= buffer
    UNTOUCHED — the sinks scatter into a private buffer that commits
    only on full success (a torn mix of two epochs in a caller's weight
    buffer would be silently trained on)."""
    rows, cols = 64, 4
    t = AsyncMatrixTable(rows, cols, name="ckf", wire="bf16",
                         ctx=two_ranks[0])
    t2 = AsyncMatrixTable(rows, cols, name="ckf", wire="bf16",
                          ctx=two_ranks[1])
    t.set_rows(np.arange(rows),
               np.ones((rows, cols), np.float32))
    config.set_flag("get_chunk_rows", 8)
    orig = t2._shard._chunked_reply

    def dies_mid_stream(rows_arr, w, chunk, tr):
        meta, reply = orig(rows_arr, w, chunk, tr)
        inner = reply.chunks

        def gen():
            yield next(inner)
            raise RuntimeError("stream died mid-way")

        reply.chunks = gen()
        return meta, reply

    t2._shard._chunked_reply = dies_mid_stream
    buf = np.full((rows, cols), -7.0, np.float32)
    with pytest.raises(svc.PSError):
        t.get_rows(np.arange(rows), out=buf)
    assert np.all(buf == -7.0), "caller's buffer was torn by the stream"
    # recovery: the unbroken path fills it
    t2._shard._chunked_reply = orig
    got = t.get_rows(np.arange(rows), out=buf)
    assert got is buf and np.all(buf[rows // 2:] == 1.0)


@pytest.fixture
def py_ranks(tmp_path):
    """2-rank world pinned to the pure-python plane: these tests inject
    delays into the python serve path, which the native C++ fast path
    would bypass."""
    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    config.set_flag("ps_native", False)
    rdv = FileRendezvous(str(tmp_path / "rdv"))
    ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
    yield ctxs
    for c in ctxs:
        c.close()


def test_get_window_single_flight(py_ranks):
    """Concurrent gets to one owner collapse into single-flight batches:
    with the serve path slowed, 8 threads' gets reach the shard as far
    fewer serves, and every caller still gets its exact rows."""
    rows, cols = 64, 4
    vals = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
    t = AsyncMatrixTable(rows, cols, name="sf", get_window_ms=50.0,
                         ctx=py_ranks[0])
    t2 = AsyncMatrixTable(rows, cols, name="sf", get_window_ms=50.0,
                          ctx=py_ranks[1])
    t.set_rows(np.arange(rows), vals)
    t.get_rows([40])   # warm the conn
    orig = t2._shard._gather_rows

    def slow(local, data=None):
        time.sleep(0.08)
        return orig(local, data=data)

    t2._shard._gather_rows = slow
    served_before = t2._shard._stat_gets
    results = [None] * 8
    start = threading.Barrier(8)

    def getter(i):
        start.wait()
        results[i] = t.get_rows(np.array([40 + (i % 4)]))

    ths = [threading.Thread(target=getter, args=(i,)) for i in range(8)]
    for th in ths:
        th.start()
    for th in ths:
        th.join(timeout=30)
    assert not any(th.is_alive() for th in ths)
    for i in range(8):
        assert np.array_equal(results[i][0], vals[40 + (i % 4)]), i
    served = t2._shard._stat_gets - served_before
    assert served < 8, f"coalescer shipped {served} frames for 8 gets"
    assert Dashboard.get("table[sf].get_rows.fetches").count < 8


def test_get_window_serial_and_duplicates(py_ranks):
    """Serial gets through the window dispatch immediately and return
    exact values — including unsorted ids and duplicates (the re-expand
    path)."""
    rows, cols = 32, 3
    vals = np.random.default_rng(1).normal(size=(rows, cols)) \
        .astype(np.float32)
    t = AsyncMatrixTable(rows, cols, name="swd", get_window_ms=5.0,
                         ctx=py_ranks[0])
    AsyncMatrixTable(rows, cols, name="swd", get_window_ms=5.0,
                     ctx=py_ranks[1])
    t.set_rows(np.arange(rows), vals)
    ids = np.array([30, 17, 2, 17, 30])   # unsorted + duplicates
    got = t.get_rows(ids)
    assert np.array_equal(got, vals[ids])
    # cross-owner batch, unsorted
    ids2 = np.array([31, 1, 16, 0])
    assert np.array_equal(t.get_rows(ids2), vals[ids2])


def test_get_window_read_your_writes(py_ranks):
    """A windowed add followed by a coalesced get must observe the add
    (both fences compose: send-window flush, then the get joins a batch
    that reaches the conn after it)."""
    t = AsyncMatrixTable(16, 2, name="ryw", send_window_ms=50.0,
                         get_window_ms=50.0, ctx=py_ranks[0])
    AsyncMatrixTable(16, 2, name="ryw", send_window_ms=50.0,
                     get_window_ms=50.0, ctx=py_ranks[1])
    for i in range(4):
        t.add_rows_async([12], np.full((1, 2), 1.0, np.float32))
        got = t.get_rows([12])
        assert got[0, 0] == float(i + 1)


def test_apply_waves_dont_stall_behind_big_get_e2e(py_ranks):
    """End-to-end stress (python serve path): a big get from rank 0 is
    held mid-gather at the owner while ANOTHER client (rank 1's own
    worker plane, the local short-circuit — a different lane than the
    get's conn, whose FIFO necessarily queues same-conn traffic) keeps
    pushing add waves. The adds must complete while the get is stuck —
    the old locked path serialized them behind it — and the final state
    must equal the locked-path oracle bit-for-bit."""
    rows, cols = 256, 8
    t = AsyncMatrixTable(rows, cols, name="stall", ctx=py_ranks[0])
    t2 = AsyncMatrixTable(rows, cols, name="stall", ctx=py_ranks[1])
    rng = np.random.default_rng(2)
    init = rng.normal(size=(rows, cols)).astype(np.float32)
    t.set_rows(np.arange(rows), init)
    t.get_rows(np.arange(rows))   # warm
    in_gather = threading.Event()
    unblock = threading.Event()
    orig = t2._shard._gather_rows

    def slow(local, data=None):
        if local.size > 100:       # only the big get blocks
            in_gather.set()
            assert unblock.wait(20)
        return orig(local, data=data)

    t2._shard._gather_rows = slow
    got = {}

    def getter():
        got["rows"] = t.get_rows(np.arange(rows))

    th = threading.Thread(target=getter)
    th.start()
    assert in_gather.wait(20)
    # oracle: deltas applied with plain numpy in issue order — pushed by
    # rank 1 into its OWN rows [128, 256) while the get is mid-gather
    oracle = init.copy()
    deltas = [rng.normal(size=(rows // 2, cols)).astype(np.float32)
              for _ in range(3)]
    t_waves0 = time.monotonic()
    for d in deltas:
        t2.add_rows(np.arange(rows // 2, rows), d)
        oracle[rows // 2:] += d
    waves_s = time.monotonic() - t_waves0
    assert th.is_alive(), "the big get should still be held"
    assert waves_s < 10, "add waves stalled behind the in-flight get"
    unblock.set()
    th.join(timeout=30)
    assert not th.is_alive()
    # the held get served ONE consistent epoch: pre-wave bytes
    assert np.array_equal(got["rows"], init)
    # bit-parity with the oracle after the waves
    assert np.array_equal(t.get_rows(np.arange(rows)), oracle)


def test_apply_waves_with_big_get_native_parity(two_ranks):
    """Native-plane variant (no delay injection possible in C++): a big
    get racing add waves still returns SOME consistent epoch, and the
    final state matches the oracle bit-for-bit."""
    rows, cols = 512, 8
    t = AsyncMatrixTable(rows, cols, name="npar", ctx=two_ranks[0])
    AsyncMatrixTable(rows, cols, name="npar", ctx=two_ranks[1])
    rng = np.random.default_rng(3)
    init = rng.normal(size=(rows, cols)).astype(np.float32)
    t.set_rows(np.arange(rows), init)
    oracle = init.copy()
    errs = []
    stop = threading.Event()

    def getter():
        try:
            while not stop.is_set():
                t.get_rows(np.arange(rows))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    th = threading.Thread(target=getter)
    th.start()
    for _ in range(10):
        d = rng.normal(size=(rows, cols)).astype(np.float32)
        t.add_rows(np.arange(rows), d)
        oracle += d
    stop.set()
    th.join(timeout=30)
    assert not errs, errs
    assert np.array_equal(t.get_rows(np.arange(rows)), oracle)


# ---------------------------------------------------------------------- #
# get_rows(out=) shape validation (satellite fix)
# ---------------------------------------------------------------------- #
class TestGetRowsOutValidation:
    def test_wrong_shape_raises_even_when_reshapable(self, two_ranks):
        t = AsyncMatrixTable(10, 4, name="ov", ctx=two_ranks[0])
        AsyncMatrixTable(10, 4, name="ov", ctx=two_ranks[1])
        ids = np.array([1, 8])
        with pytest.raises(ValueError, match="shape"):
            t.get_rows(ids, out=np.empty((4, 2), np.float32))  # transposed
        with pytest.raises(ValueError, match="shape"):
            t.get_rows(ids, out=np.empty((3, 4), np.float32))  # wrong rows
        with pytest.raises(ValueError, match="shape"):
            t.get_rows(ids, out=np.empty(7, np.float32))   # wrong flat size
        # strided flat view: reshape would COPY and the fill would be
        # lost — must raise, not silently no-op
        with pytest.raises(ValueError, match="shape"):
            t.get_rows(ids, out=np.empty(16, np.float32)[::2])

    def test_flat_contiguous_out_still_fills(self, two_ranks):
        """The legacy reference-binding surface (handlers.py) passes flat
        buffers; a C-contiguous (n*cols,) out is unambiguous row-major
        and keeps working."""
        t = AsyncMatrixTable(10, 4, name="of", ctx=two_ranks[0])
        AsyncMatrixTable(10, 4, name="of", ctx=two_ranks[1])
        t.add_rows(np.arange(10),
                   np.arange(40, dtype=np.float32).reshape(10, 4))
        ids = np.array([1, 8])
        flat = np.empty(8, np.float32)
        got = t.get_rows(ids, out=flat)
        assert got is flat
        assert np.array_equal(flat.reshape(2, 4), t.get_rows(ids))

    def test_right_shape_wrong_dtype_still_fills(self, two_ranks):
        t = AsyncMatrixTable(10, 4, name="od", ctx=two_ranks[0])
        AsyncMatrixTable(10, 4, name="od", ctx=two_ranks[1])
        t.add_rows(np.arange(10),
                   np.arange(40, dtype=np.float32).reshape(10, 4))
        ids = np.array([2, 7])
        buf = np.empty((2, 4), np.float64)   # dtype fallback, shape OK
        got = t.get_rows(ids, out=buf)
        assert got is buf
        assert np.array_equal(buf, t.get_rows(ids).astype(np.float64))


# ---------------------------------------------------------------------- #
# sync-table write-triggered get prefetch (table.py)
# ---------------------------------------------------------------------- #
class TestSyncGetPrefetch:
    def test_prefetch_parity_and_arming(self):
        import multiverso_tpu as mv
        from multiverso_tpu.updaters import AddOption as AO

        mv.init()
        t = mv.ArrayTable(512, updater="sgd", name="pf_t")
        delta = np.random.default_rng(4).normal(size=512) \
            .astype(np.float32)
        t.add(delta, AO())
        t.get()                         # arms the get-after-add pattern
        t.add(delta, AO())
        assert t._get_prefetch is not None
        got = t.get()                   # consumes the prefetched snapshot
        assert np.array_equal(got, np.asarray(t.raw())[:512])
        assert Dashboard.get("table[pf_t].get.prefetched").count == 1
        # two adds with no get between: self-disarm, snapshot dropped
        t.add(delta, AO())
        t.add(delta, AO())
        assert t._get_prefetch is None and not t._prefetch_armed
        assert np.array_equal(t.get(), np.asarray(t.raw())[:512])

    def test_prefetch_backoff_on_thrash_cadence(self):
        """The original disarm logic made an add,add,get cadence pay one
        wasted table-sized snapshot EVERY cycle with zero hits; with the
        unconsumed-drop backoff the skip phase-shifts the dispatch onto
        the LAST add of the cycle — at most every other cycle wastes a
        snapshot, and the shifted ones become real hits."""
        import multiverso_tpu as mv
        from multiverso_tpu.updaters import AddOption as AO

        mv.init()
        t = mv.ArrayTable(256, updater="sgd", name="pf_bk")
        delta = np.ones(256, np.float32)
        wasted = 0
        for _ in range(8):
            t.add(delta, AO())
            first = t._get_prefetch is not None
            t.add(delta, AO())
            if first and t._get_prefetch is None:
                wasted += 1      # first add's snapshot was dropped
            t.get()
        hits = Dashboard.get("table[pf_bk].get.prefetched").count
        assert wasted <= 4, wasted           # not 1 per cycle (was 8)
        assert hits >= 2, hits               # and the cadence still wins
        # pure add-only runs decay exponentially: a long add burst after
        # arming wastes O(log N) snapshots, not O(N)
        dispatched = 0
        for _ in range(16):
            t.add(delta, AO())
            if t._get_prefetch is not None:
                dispatched += 1
        assert dispatched <= 5, dispatched
        # a consumed prefetch resets the backoff: clean alternation
        # restores the fast path
        t.get()
        for _ in range(6):
            t.add(delta, AO())
            t.get()
        assert t._prefetch_backoff == 0

    def test_prefetch_flag_off(self):
        import multiverso_tpu as mv
        from multiverso_tpu.updaters import AddOption as AO

        mv.init()
        config.set_flag("table_get_prefetch", False)
        t = mv.ArrayTable(128, updater="sgd", name="pf_off")
        delta = np.ones(128, np.float32)
        t.add(delta, AO())
        t.get()
        t.add(delta, AO())
        assert t._get_prefetch is None
        assert np.array_equal(t.get(), np.asarray(t.raw())[:128])


# ---------------------------------------------------------------------- #
# multi-owner fan-out gets (ISSUE 15): chunk-eligible big gets across 4
# colocated shards — routed parts serve in-process (chunking is a
# network-overlap device, skipped for in-process destinations), and the
# result must stay bit-identical to the 1-shard oracle, out= included
# ---------------------------------------------------------------------- #
class TestFanoutChunkedGets:
    ROWS, DIM = 512, 8

    def _fill(self, t):
        rng = np.random.default_rng(5)
        vals = rng.normal(size=(self.ROWS, self.DIM)).astype(np.float32)
        t.add_rows(np.arange(self.ROWS), vals)
        return vals

    @pytest.mark.parametrize("plane", ["native", "python"])
    def test_chunk_flag_fanout_parity(self, tmp_path, plane):
        from multiverso_tpu.ps.service import (FileRendezvous,
                                               PSContext, PSService)
        config.set_flag("ps_native", plane == "native")
        config.set_flag("ps_fanout", True)
        config.set_flag("get_chunk_rows", 32)   # far below every part
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 4, PSService(r, 4, rdv))
                for r in range(4)]
        tabs = [AsyncMatrixTable(self.ROWS, self.DIM, name="fc_t",
                                 ctx=c) for c in ctxs]
        want = self._fill(tabs[0])
        got = tabs[1].get_rows(np.arange(self.ROWS))
        np.testing.assert_array_equal(got, want)
        # out= commits only on full success, exact bytes
        out = np.empty((self.ROWS, self.DIM), np.float32)
        res = tabs[2].get_rows(np.arange(self.ROWS), out=out)
        assert res is out
        np.testing.assert_array_equal(out, want)
        # duplicate caller-order ids re-expand exactly
        ids = np.array([400, 3, 130, 3, 511, 400])
        np.testing.assert_array_equal(tabs[3].get_rows(ids),
                                      want[ids])
        for c in ctxs:
            c.close()

    def test_mixed_routed_and_socket_parts_chunk(self, tmp_path):
        """A world where only SOME owners are colocated: routed parts
        serve in-process, the non-colocated one still chunk-streams
        over its socket — one get, both transports, exact bytes."""
        from multiverso_tpu.ps import spmd
        from multiverso_tpu.ps.service import (FileRendezvous,
                                               PSContext, PSService)
        config.set_flag("ps_native", False)
        config.set_flag("ps_fanout", True)
        config.set_flag("get_chunk_rows", 32)
        rdv = FileRendezvous(str(tmp_path / "rdv"))
        ctxs = [PSContext(r, 4, PSService(r, 4, rdv))
                for r in range(4)]
        # hide rank 3 from the colocation registry BEFORE tables
        # resolve their routes: its traffic keeps the socket path
        spmd.unregister_service(ctxs[3].service)
        tabs = [AsyncMatrixTable(self.ROWS, self.DIM, name="mx_t",
                                 ctx=c) for c in ctxs]
        assert tabs[0]._routed_set == {1, 2}
        want = self._fill(tabs[0])
        got = tabs[0].get_rows(np.arange(self.ROWS))
        np.testing.assert_array_equal(got, want)
        for c in ctxs:
            c.close()
