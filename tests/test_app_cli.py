"""App CLI entry points (ref src/main.cpp one-arg/argv shapes): the mains
parse their own keys AND route ``-key=value`` runtime flags through
mv.init, exactly the reference's MV_Init(&argc, argv) compaction
(ref src/multiverso.cpp:10, src/util/configure.cpp:9-54)."""

import os

import numpy as np

from multiverso_tpu.utils import config

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny_corpus(path, n=3000, vocab=50):
    rng = np.random.default_rng(0)
    toks = [f"w{t}" for t in rng.integers(0, vocab, n)]
    path.write_text(" ".join(toks))


def test_we_main_routes_runtime_flags(tmp_path):
    from multiverso_tpu.apps import word_embedding as we_app
    corpus = tmp_path / "corpus.txt"
    _tiny_corpus(corpus)
    out = tmp_path / "vec.txt"
    rc = we_app.main(["-train_file", str(corpus), "-size", "16",
                      "-epoch", "1", "-batch_size", "128",
                      "-min_count", "1", "-sample", "0",
                      "-output", str(out),
                      "-ps_timeout=33.5"])       # runtime flag, = form
    assert rc == 0
    header = out.read_text().splitlines()[0].split()
    assert int(header[1]) == 16
    # the "=" entry reached the flag registry, not the app config
    assert config.get_flag("ps_timeout") == 33.5


def test_lr_main_routes_runtime_flags(tmp_path):
    from multiverso_tpu.apps import logistic_regression as lr_app
    from multiverso_tpu.models import logreg as model_lib
    x, y = model_lib.synthetic_dataset(256, 8, 2, seed=3)
    train = tmp_path / "train.svm"
    with open(train, "w") as f:
        for xi, yi in zip(x, y):
            feats = " ".join(f"{j}:{v:.5f}" for j, v in enumerate(xi))
            f.write(f"{yi} {feats}\n")
    cfg = tmp_path / "lr.config"
    cfg.write_text(f"input_size=8\noutput_size=2\nminibatch_size=64\n"
                   f"learning_rate=0.5\ntrain_epoch=2\n"
                   f"train_file={train}\ntest_file={train}\n")
    rc = lr_app.main([str(cfg), "-ps_timeout=44.0"])
    assert rc == 0
    assert config.get_flag("ps_timeout") == 44.0


def test_lr_main_usage_error_without_config():
    from multiverso_tpu.apps import logistic_regression as lr_app
    assert lr_app.main(["-ps_timeout=44.0"]) == 2
    assert lr_app.main([]) == 2


def test_we_vocab_preprocess_roundtrip(tmp_path):
    """tools/word_count.py -> -read_vocab: the preprocess tool's vocab
    file drives training without re-counting (ref preprocess/
    word_count.cpp + -read_vocab, distributed_wordembedding.cpp:415-446),
    and -save_vocab writes the same format back."""
    import subprocess
    import sys
    from multiverso_tpu.apps import word_embedding as we_app

    corpus = tmp_path / "c.txt"
    _tiny_corpus(corpus, n=5000, vocab=40)
    vocab = tmp_path / "vocab.txt"
    rc = subprocess.run(
        [sys.executable, "tools/word_count.py", "-train_file", str(corpus),
         "-save_vocab", str(vocab), "-min_count", "2"],
        cwd=_REPO_ROOT, capture_output=True, text=True).returncode
    assert rc == 0
    lines = vocab.read_text().splitlines()
    assert len(lines) > 10
    counts = [int(l.split()[-1]) for l in lines]
    assert counts == sorted(counts, reverse=True)   # count-desc

    out = tmp_path / "vec.txt"
    vocab2 = tmp_path / "vocab2.txt"
    rc = we_app.main(["-train_file", str(corpus), "-read_vocab", str(vocab),
                      "-size", "8", "-epoch", "1", "-batch_size", "64",
                      "-min_count", "2", "-sample", "0",
                      "-save_vocab", str(vocab2), "-output", str(out)])
    assert rc == 0
    assert int(out.read_text().split(None, 1)[0]) == len(lines)
    assert vocab2.read_text().splitlines() == lines   # format round-trip


def test_word_count_chunk_boundaries_and_max_vocab(tmp_path):
    """Tokens straddling read-chunk boundaries count once (carry-tail),
    and -read_vocab honors -max_vocab like Dictionary.build."""
    import collections
    import sys
    sys.path.insert(0, os.path.join(_REPO_ROOT, "tools"))
    import word_count as wc

    corpus = tmp_path / "c.txt"
    _tiny_corpus(corpus, n=4000, vocab=30)
    whole = collections.Counter(corpus.read_text().split())
    for chunk in (7, 64, 1 << 22):   # tiny chunks force mid-token splits
        assert wc.count_file(str(corpus), chunk_bytes=chunk) == whole

    from multiverso_tpu.apps.word_embedding import read_vocab_file
    vocab = tmp_path / "v.txt"
    wc.write_vocab(whole, str(vocab), min_count=1)
    d = read_vocab_file(str(vocab), min_count=1, max_vocab=10)
    assert len(d.words) == 10
    top = sorted(whole.items(), key=lambda kv: (-kv[1], kv[0]))[:10]
    assert d.words == [w for w, _ in top]   # count-desc cap, like build()
