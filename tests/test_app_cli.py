"""App CLI entry points (ref src/main.cpp one-arg/argv shapes): the mains
parse their own keys AND route ``-key=value`` runtime flags through
mv.init, exactly the reference's MV_Init(&argc, argv) compaction
(ref src/multiverso.cpp:10, src/util/configure.cpp:9-54)."""

import numpy as np

from multiverso_tpu.utils import config


def _tiny_corpus(path, n=3000, vocab=50):
    rng = np.random.default_rng(0)
    toks = [f"w{t}" for t in rng.integers(0, vocab, n)]
    path.write_text(" ".join(toks))


def test_we_main_routes_runtime_flags(tmp_path):
    from multiverso_tpu.apps import word_embedding as we_app
    corpus = tmp_path / "corpus.txt"
    _tiny_corpus(corpus)
    out = tmp_path / "vec.txt"
    rc = we_app.main(["-train_file", str(corpus), "-size", "16",
                      "-epoch", "1", "-batch_size", "128",
                      "-min_count", "1", "-sample", "0",
                      "-output", str(out),
                      "-ps_timeout=33.5"])       # runtime flag, = form
    assert rc == 0
    header = out.read_text().splitlines()[0].split()
    assert int(header[1]) == 16
    # the "=" entry reached the flag registry, not the app config
    assert config.get_flag("ps_timeout") == 33.5


def test_lr_main_routes_runtime_flags(tmp_path):
    from multiverso_tpu.apps import logistic_regression as lr_app
    from multiverso_tpu.models import logreg as model_lib
    x, y = model_lib.synthetic_dataset(256, 8, 2, seed=3)
    train = tmp_path / "train.svm"
    with open(train, "w") as f:
        for xi, yi in zip(x, y):
            feats = " ".join(f"{j}:{v:.5f}" for j, v in enumerate(xi))
            f.write(f"{yi} {feats}\n")
    cfg = tmp_path / "lr.config"
    cfg.write_text(f"input_size=8\noutput_size=2\nminibatch_size=64\n"
                   f"learning_rate=0.5\ntrain_epoch=2\n"
                   f"train_file={train}\ntest_file={train}\n")
    rc = lr_app.main([str(cfg), "-ps_timeout=44.0"])
    assert rc == 0
    assert config.get_flag("ps_timeout") == 44.0


def test_lr_main_usage_error_without_config():
    from multiverso_tpu.apps import logistic_regression as lr_app
    assert lr_app.main(["-ps_timeout=44.0"]) == 2
    assert lr_app.main([]) == 2
