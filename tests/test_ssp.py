"""Bounded-staleness SSP clocks (ssp.py): lockstep, bounded lead, straggler
exclusion, timeout."""

import threading
import time

import pytest

from multiverso_tpu.ssp import SSPClock, SSPTimeout


def _run_workers(tmp_path, n, steps, staleness, delays, ignore=None,
                 timeout=10.0):
    """Run n worker threads; record (worker, clock, min_peer_at_return)."""
    history = []
    lock = threading.Lock()
    errors = []

    def worker(wid):
        try:
            clk = SSPClock(str(tmp_path), staleness=staleness,
                           num_workers=n, worker_id=wid, poll=0.005,
                           timeout=timeout, ignore=ignore)
            for _ in range(steps):
                time.sleep(delays[wid])
                c = clk.tick()
                with lock:
                    history.append((wid, c, min(clk.peer_clocks().values())))
        except Exception as e:  # propagate to the main thread
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return history


class TestSSPClock:
    def test_bsp_lockstep(self, tmp_path):
        # staleness=0: nobody returns from tick(c) before everyone hits c
        hist = _run_workers(tmp_path, n=3, steps=10, staleness=0,
                            delays=[0.0, 0.002, 0.01])
        for wid, clock, min_peer in hist:
            assert min_peer >= clock, (wid, clock, min_peer)

    def test_bounded_lead(self, tmp_path):
        s = 2
        hist = _run_workers(tmp_path, n=2, steps=12, staleness=s,
                            delays=[0.0, 0.01])
        for wid, clock, min_peer in hist:
            assert min_peer >= clock - s, (wid, clock, min_peer)
        # the fast worker must actually use its slack: it should at some
        # point be observed ahead of the slow one
        leads = [clock - min_peer for wid, clock, min_peer in hist
                 if wid == 0]
        assert max(leads) >= 1

    def test_ignore_dead_worker(self, tmp_path):
        # worker 1 never starts; with it ignored, worker 0 sails through
        clk = SSPClock(str(tmp_path), staleness=0, num_workers=2,
                       worker_id=0, poll=0.005, timeout=5.0,
                       ignore=lambda: [1])
        for _ in range(5):
            clk.tick()
        assert clk.clock == 5

    def test_timeout_raises(self, tmp_path):
        clk = SSPClock(str(tmp_path), staleness=0, num_workers=2,
                       worker_id=0, poll=0.005, timeout=0.2)
        with pytest.raises(SSPTimeout, match="stragglers"):
            clk.tick()

    def test_rejects_negative_staleness(self, tmp_path):
        with pytest.raises(ValueError, match="staleness"):
            SSPClock(str(tmp_path), staleness=-1, num_workers=1, worker_id=0)

    def test_resume_from_existing_beacon(self, tmp_path):
        # a restarted worker must not re-publish clock 0 (it would stall
        # every peer at the staleness bound until it caught back up)
        clk = SSPClock(str(tmp_path), staleness=5, num_workers=1,
                       worker_id=0)
        for _ in range(3):
            clk.tick()
        resumed = SSPClock(str(tmp_path), staleness=5, num_workers=1,
                           worker_id=0)
        assert resumed.clock == 3
        assert resumed.tick() == 4

    def test_lr_config_rejects_staleness_without_ssp_dir(self):
        from multiverso_tpu.apps.logistic_regression import LogRegConfig
        with pytest.raises(ValueError, match="ssp_dir"):
            LogRegConfig({"input_size": "4", "staleness": "0"})
        with pytest.raises(ValueError, match="use_ps"):
            LogRegConfig({"input_size": "4", "staleness": "0",
                          "ssp_dir": "/tmp/x", "use_ps": "false"})
