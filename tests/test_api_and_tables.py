"""API + ArrayTable/MatrixTable/KVTable behavior on an 8-device mesh.

Mirrors the reference integration harness semantics (SURVEY §4 tier 2:
Test/main.cpp TestKV/TestArray/TestMatrix) — correctness of Add/Get across
shards, sync semantics, updaters, and checkpoint Store/Load.
"""

import io

import jax
import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.tables.array_table import ArrayTableOption
from multiverso_tpu.tables.matrix_table import MatrixTableOption
from multiverso_tpu.updaters import AddOption


@pytest.fixture(autouse=True)
def _init():
    mv.init()
    yield
    mv.shutdown()


class TestTopology:
    def test_basic(self):
        assert mv.rank() == 0
        assert mv.size() == 1
        assert mv.num_servers() == 8  # 8 virtual devices
        assert mv.num_workers() == 1
        assert mv.mesh().size == 8
        mv.barrier()

    def test_create_table_option(self):
        t = mv.create_table(ArrayTableOption(100))
        assert t.size == 100
        m = mv.create_table(MatrixTableOption(10, 4))
        assert (m.num_row, m.num_col) == (10, 4)


class TestArrayTable:
    def test_add_get(self):
        # ref Test/main.cpp TestArray: delta accumulates across adds.
        t = mv.ArrayTable(1000)
        delta = np.arange(1000, dtype=np.float32)
        t.add(delta)
        t.add(delta)
        got = t.get()
        np.testing.assert_allclose(got, 2 * delta, rtol=1e-6)

    def test_sharding_layout(self):
        t = mv.ArrayTable(1000)
        # padded to a multiple of 8 shards, actually sharded over devices
        assert t.padded_shape[0] % 8 == 0
        assert len(t.raw().sharding.device_set) == 8

    def test_async_wait(self):
        t = mv.ArrayTable(64)
        ids = [t.add_async(np.ones(64, np.float32)) for _ in range(5)]
        for i in ids:
            t.wait(i)
        np.testing.assert_allclose(t.get(), 5.0)

    def test_async_adds_coalesce_into_one_apply(self):
        """Pipelined host adds on a stateless-linear table merge into one
        summed upload (transfers do not overlap on a tunneled link, so
        fewer transfers is the only pipelining lever): all queued entries
        share one completion token, and the sum is exact."""
        t = mv.ArrayTable(64, updater="sgd")
        base = t._m if hasattr(t, "_m") else t
        delta = np.full(64, 2.0, np.float32)
        # hold the dispatch lock so the applier can't run: all three adds
        # queue, then one drain applies them as one batch
        with base._dispatch_lock:
            mids = [base.add_async(delta.reshape(base.shape))
                    for _ in range(3)]
            assert base._addq_inflight == 3
        toks = [base.wait(m) for m in mids]
        assert toks[0] is toks[1] is toks[2]     # ONE merged apply
        np.testing.assert_allclose(t.get(), -6.0)   # sgd sign, exact sum

    def test_reads_flush_queued_adds_even_under_dispatch_lock(self):
        """Reading .state/get while holding the dispatch lock (the fused
        WE path does exactly this) must drain the queue inline, not
        deadlock against the applier thread."""
        t = mv.ArrayTable(32, updater="sgd")
        base = t._m if hasattr(t, "_m") else t
        delta = np.ones(32, np.float32)
        with base._dispatch_lock:
            base.add_async(delta.reshape(base.shape))
            st = base.state                     # flushes inline
            host = np.asarray(st["data"]).reshape(-1)[:32]
        np.testing.assert_allclose(host, -1.0)
        np.testing.assert_allclose(t.get(), -1.0)

    def test_momentum_adds_do_not_coalesce(self):
        """Stateful updaters must keep per-add sequencing (N sequential
        momentum applies != one summed apply)."""
        t = mv.ArrayTable(16, updater="momentum_sgd")
        base = t._m if hasattr(t, "_m") else t
        opt = AddOption(momentum=0.5)
        for _ in range(3):
            base.wait(base.add_async(np.ones(base.shape, np.float32), opt))
        assert base._addq_inflight == 0 and not base._addq
        # sequential momentum: smooth=.5,.75,.875 -> data = -2.125
        np.testing.assert_allclose(t.get(), -2.125, rtol=1e-6)

    def test_get_out_buffer(self):
        t = mv.ArrayTable(10, init=np.arange(10, dtype=np.float32))
        out = np.zeros(10, np.float32)
        ret = t.get(out=out)
        assert ret is out
        np.testing.assert_allclose(out, np.arange(10))

    def test_int_table_uses_default_updater(self):
        t = mv.ArrayTable(16, dtype=np.int32, updater="sgd")
        assert t.updater.name == "default"
        t.add(np.ones(16, np.int32))
        np.testing.assert_array_equal(t.get(), 1)

    def test_init_value(self):
        init = np.full(32, 3.0, np.float32)
        t = mv.ArrayTable(32, init=init)
        np.testing.assert_allclose(t.get(), 3.0)

    def test_store_load_roundtrip(self):
        t = mv.ArrayTable(50, updater="adagrad")
        t.add(np.random.default_rng(0).normal(size=50).astype(np.float32),
              AddOption(learning_rate=0.1, rho=0.1))
        buf = io.BytesIO()
        t.store(buf)
        snapshot = t.get().copy()
        t.add(np.ones(50, np.float32))
        buf.seek(0)
        t.load(buf)
        np.testing.assert_allclose(t.get(), snapshot, rtol=1e-6)


class TestUpdaters:
    def test_sgd(self):
        t = mv.ArrayTable(8, updater="sgd",
                          init=np.full(8, 1.0, np.float32))
        t.add(np.full(8, 0.25, np.float32))
        np.testing.assert_allclose(t.get(), 0.75)

    def test_momentum(self):
        t = mv.ArrayTable(4, updater="momentum_sgd")
        opt = AddOption(momentum=0.5)
        t.add(np.ones(4, np.float32), opt)
        # smooth = 0.5*0 + 0.5*1 = 0.5 ; data = -0.5
        np.testing.assert_allclose(t.get(), -0.5)
        t.add(np.ones(4, np.float32), opt)
        # smooth = 0.5*0.5 + 0.5*1 = 0.75 ; data = -1.25
        np.testing.assert_allclose(t.get(), -1.25)

    def test_adagrad(self):
        t = mv.ArrayTable(4, updater="adagrad")
        opt = AddOption(learning_rate=1.0, rho=1.0)
        t.add(np.full(4, 2.0, np.float32), opt)
        # G = 4 ; step = 2/sqrt(4) = 1
        np.testing.assert_allclose(t.get(), -1.0, rtol=1e-5)

    def test_adam_moves_against_gradient(self):
        t = mv.ArrayTable(4, updater="adam")
        for _ in range(3):
            t.add(np.full(4, 1.0, np.float32), AddOption(learning_rate=0.1))
        assert np.all(t.get() < 0)

    def test_custom_updater_registration(self):
        class Doubling(mv.Updater):
            name = "doubling"

            def apply(self, data, state, delta, opt):
                return data + 2 * delta, state

        mv.register_updater("doubling", Doubling)
        t = mv.ArrayTable(4, updater="doubling")
        t.add(np.ones(4, np.float32))
        np.testing.assert_allclose(t.get(), 2.0)


class TestMatrixTable:
    def test_whole_table(self):
        m = mv.MatrixTable(12, 6)
        delta = np.arange(72, dtype=np.float32).reshape(12, 6)
        m.add(delta)
        np.testing.assert_allclose(m.get(), delta)

    def test_row_ops(self):
        # ref Test/main.cpp TestMatrix: row-batch get/add correctness.
        m = mv.MatrixTable(100, 8)
        ids = [3, 50, 99]
        vals = np.ones((3, 8), np.float32) * np.array([[1], [2], [3]],
                                                      np.float32)
        m.add_rows(ids, vals)
        got = m.get_rows(ids)
        np.testing.assert_allclose(got, vals)
        # untouched rows stay zero
        np.testing.assert_allclose(m.get_row(0), 0.0)
        full = m.get()
        np.testing.assert_allclose(full[50], 2.0)

    def test_duplicate_ids_accumulate(self):
        m = mv.MatrixTable(10, 4)
        m.add_rows([2, 2, 5], np.ones((3, 4), np.float32))
        np.testing.assert_allclose(m.get_row(2), 2.0)
        np.testing.assert_allclose(m.get_row(5), 1.0)

    def test_row_update_is_local_for_momentum(self):
        # Updater state of untouched rows must not decay (ref server applies
        # the updater only to received rows).
        m = mv.MatrixTable(10, 4, updater="momentum_sgd")
        opt = AddOption(momentum=0.5)
        m.add_rows([1], np.ones((1, 4), np.float32), opt)
        m.add_rows([2], np.ones((1, 4), np.float32), opt)
        # row 1 got exactly one momentum step: -0.5
        np.testing.assert_allclose(m.get_row(1), -0.5)
        np.testing.assert_allclose(m.get_row(2), -0.5)

    def test_random_init(self):
        m = mv.MatrixTable(20, 10, seed=42, init_scale=0.5)
        vals = m.get()
        assert np.all(np.abs(vals) <= 0.5)
        assert np.std(vals) > 0.05

    def test_out_of_range(self):
        m = mv.MatrixTable(10, 4)
        with pytest.raises(IndexError):
            m.get_rows([10])

    def test_large_row_batch_buckets(self):
        m = mv.MatrixTable(64, 4)
        ids = np.arange(33)
        vals = np.ones((33, 4), np.float32)
        m.add_rows(ids, vals)
        np.testing.assert_allclose(m.get_rows(ids), 1.0)


class TestKVTable:
    def test_add_get(self):
        # ref Test/main.cpp TestKV
        kv = mv.KVTable()
        kv.add([1, 5, 9], [10, 20, 30])
        kv.add([1], [5])
        assert kv[1] == 15
        assert kv.get([5, 9]) == {5: 20, 9: 30}
        assert kv.get()[1] == 15

    def test_store_load(self):
        kv = mv.KVTable()
        kv.add([7, 3], [1.0, 2.0])
        buf = io.BytesIO()
        kv.store(buf)
        kv2 = mv.KVTable()
        buf.seek(0)
        kv2.load(buf)
        assert kv2[7] == 1 and kv2[3] == 2


class TestAggregate:
    def test_single_process_identity(self):
        # ref Test/main.cpp TestAllreduce (-ma mode): with one worker,
        # MV_Aggregate is identity.
        data = np.arange(16, dtype=np.float32)
        out = mv.aggregate(data.copy())
        np.testing.assert_allclose(out, data)


class TestReviewRegressions:
    def test_get_rows_with_many_duplicates(self):
        # regression: duplicate-heavy get batch larger than padded_rows
        init = np.tile(np.arange(10, dtype=np.float32)[:, None], (1, 4))
        m = mv.MatrixTable(10, 4, init=init)
        ids = [3] * 20 + [7] * 5
        rows = m.get_rows(ids)
        assert rows.shape == (25, 4)
        np.testing.assert_allclose(rows[:20], 3.0)
        np.testing.assert_allclose(rows[20:], 7.0)

    def test_aggregate_noncontiguous_inplace(self):
        mat = np.arange(16, dtype=np.float32).reshape(4, 4)
        col = mat[:, 0]  # strided view
        out = mv.aggregate(col)
        np.testing.assert_allclose(mat[:, 0], [0, 4, 8, 12])
        assert out.base is mat or out is col


