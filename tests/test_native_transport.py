"""Native C++ transport/serving loop (native/mv_ps.cpp + ps/native.py).

The whole async battery already runs THROUGH the native plane when
libmv_ps.so is present (ps_native defaults on), so these tests target
what that battery can't see: A/B equivalence against the pure-python
plane, the punt paths (compressed wires, stateful updaters, sparse
protocol) crossing the C++/Python boundary, native error replies, and the
C++-side stats. Skips cleanly where no toolchain built the .so.
"""

import numpy as np
import pytest

from multiverso_tpu.ps import native as ps_native
from multiverso_tpu.ps import service as svc
from multiverso_tpu.ps.service import FileRendezvous, PSContext, PSService
from multiverso_tpu.ps.tables import (AsyncArrayTable, AsyncMatrixTable,
                                      AsyncSparseMatrixTable)
from multiverso_tpu.updaters import AdaGradUpdater
from multiverso_tpu.utils import config

pytestmark = pytest.mark.skipif(not ps_native.available(),
                                reason="libmv_ps.so unavailable")


def _world(tmp_path, n=2, sub="rdv"):
    rdv = FileRendezvous(str(tmp_path / sub))
    return [PSContext(r, n, PSService(r, n, rdv)) for r in range(n)]


@pytest.fixture
def two_ranks(tmp_path):
    """Native-only override of conftest's plane-parametrized fixture:
    these tests assert native-specific behavior (server handles, pins,
    C-served stats), meaningless on the python plane."""
    ctxs = _world(tmp_path)
    yield ctxs
    for c in ctxs:
        c.close()


class TestNativeServing:
    def test_native_server_is_live(self, two_ranks):
        assert two_ranks[0].service._native is not None
        t = AsyncMatrixTable(10, 4, name="nl", ctx=two_ranks[0])
        assert t._native_ok
        assert t._shard._native_ref is not None

    def test_ab_python_plane_equivalence(self, tmp_path):
        """The same op sequence through the native plane and the pure-
        python plane (ps_native off) must produce identical state."""
        results = {}
        for native in (True, False):
            config.set_flag("ps_native", native)
            try:
                ctxs = _world(tmp_path, sub=f"rdv{int(native)}")
                t0 = AsyncMatrixTable(12, 3, name="ab", ctx=ctxs[0])
                t1 = AsyncMatrixTable(12, 3, name="ab", ctx=ctxs[1])
                assert t0._native_ok == native
                assert (ctxs[0].service._native is not None) == native
                rng = np.random.default_rng(0)
                for k in range(5):
                    ids = rng.choice(12, size=4, replace=False)
                    t0.add_rows(ids, rng.normal(size=(4, 3)).astype(
                        np.float32))
                    t1.add_rows(ids[::-1], np.ones((4, 3), np.float32))
                t1.add(np.full((12, 3), 0.25, np.float32))
                results[native] = (t0.get(), t1.get_rows(np.arange(12)))
                for c in ctxs:
                    c.close()
            finally:
                config.reset_flags()
        np.testing.assert_allclose(results[True][0], results[False][0],
                                   rtol=1e-6)
        np.testing.assert_allclose(results[True][1], results[False][1],
                                   rtol=1e-6)

    def test_native_stats_count_served_ops(self, two_ranks):
        t0 = AsyncMatrixTable(10, 2, name="ns", ctx=two_ranks[0])
        AsyncMatrixTable(10, 2, name="ns", ctx=two_ranks[1])
        before = t0._shard.stat_adds
        t0.add_rows([0, 1], np.ones((2, 2), np.float32))   # shard 0 add
        assert t0._shard.stat_adds == before + 1
        assert t0._shard._stat_adds == 0   # python path untouched

    def test_array_table_rides_native(self, two_ranks):
        a0 = AsyncArrayTable(8, name="na", ctx=two_ranks[0])
        a1 = AsyncArrayTable(8, name="na", ctx=two_ranks[1])
        a0.add(np.arange(8, dtype=np.float32))
        a1.add(np.ones(8, np.float32))
        np.testing.assert_allclose(a1.get(),
                                   np.arange(8, dtype=np.float32) + 1)


class TestPuntPaths:
    def test_bf16_wire_punts_and_works(self, two_ranks):
        """bf16-compressed payloads can't be served natively; they must
        punt to the python handler under the native shard mutex and apply
        correctly."""
        t0 = AsyncMatrixTable(10, 4, name="pw", wire="bf16",
                              ctx=two_ranks[0])
        t1 = AsyncMatrixTable(10, 4, name="pw", wire="bf16",
                              ctx=two_ranks[1])
        assert not t0._native_ok           # client side: python conns
        assert t0._shard._native_ref is not None   # server side: native
        t0.add_rows([7], np.full((1, 4), 2.0, np.float32))   # remote owner
        np.testing.assert_allclose(t1.get_rows([7])[0], 2.0)

    def test_stateful_updater_punts(self, two_ranks):
        """AdaGrad shards aren't host-backed-linear: every op punts to the
        python jitted path through the C++ conn threads."""
        t0 = AsyncMatrixTable(10, 4, name="pa",
                              updater=AdaGradUpdater(num_workers=2),
                              ctx=two_ranks[0])
        t1 = AsyncMatrixTable(10, 4, name="pa",
                              updater=AdaGradUpdater(num_workers=2),
                              ctx=two_ranks[1])
        assert t0._shard._native_ref is None
        t0.add_rows([2, 7], np.ones((2, 4), np.float32))
        got = t1.get_rows([2, 7])
        assert np.all(got < 0)   # adagrad: w -= lr * g / sqrt(g2 + eps)

    def test_sparse_protocol_over_native_server(self, two_ranks):
        """The stale-row protocol end to end with C++ serving BOTH sides:
        adds set dirty bits in C, sparse gets read+clear them and reply
        [mask, stale rows] in C — same wire the python server speaks."""
        t0 = AsyncSparseMatrixTable(10, 4, name="psp", ctx=two_ranks[0])
        t1 = AsyncSparseMatrixTable(10, 4, name="psp", ctx=two_ranks[1])
        assert t0._shard._native_ref is not None   # dirty bits live in C++
        ids = np.array([1, 6])
        first = t1.get_rows_sparse(ids, worker_id=1)
        np.testing.assert_allclose(first, 0.0)
        assert t1.last_transfer_rows == 2          # initial pull: all stale
        again = t1.get_rows_sparse(ids, worker_id=1)
        assert t1.last_transfer_rows == 0          # clean: nothing moved
        assert again.shape == (2, 4)
        t0.add_rows([6], np.ones((1, 4), np.float32))   # python conn add
        t1.add_rows([1], np.full((1, 4), 3.0, np.float32))
        t0.flush(), t1.flush()
        got = t1.get_rows_sparse(ids, worker_id=1)
        assert t1.last_transfer_rows == 2          # both rows re-dirtied
        np.testing.assert_allclose(got[0], 3.0)
        np.testing.assert_allclose(got[1], 1.0)
        # per-worker isolation: worker 0 still sees everything stale
        got0 = t0.get_rows_sparse(ids, worker_id=0)
        assert t0.last_transfer_rows == 2
        np.testing.assert_allclose(got0, got)

    def test_sparse_get_served_natively_not_punted(self, two_ranks):
        """The sparse branch must be handled in C++ (no punt): assert by
        sending a sparse get for a natively-registered shard and checking
        the python handler was never invoked."""
        t0 = AsyncSparseMatrixTable(8, 2, name="psn", ctx=two_ranks[0])
        t1 = AsyncSparseMatrixTable(8, 2, name="psn", ctx=two_ranks[1])
        calls = []
        orig = t0._shard.handle

        def spy(*a, **k):
            calls.append(a[0])
            return orig(*a, **k)

        # re-register the spy THROUGH the service wrapper machinery
        two_ranks[0].service.register_handler("psn", spy,
                                              shard=t0._shard)
        t1.get_rows_sparse(np.array([0, 1]), worker_id=1)  # rank0's shard
        t1.add_rows([0], np.ones((1, 2), np.float32))
        t1.flush()
        t1.get_rows_sparse(np.array([0, 1]), worker_id=1)
        assert calls == []   # everything served in C++

    def test_checkpoint_roundtrip_over_native(self, two_ranks, tmp_path):
        t0 = AsyncMatrixTable(10, 4, name="ck", ctx=two_ranks[0])
        AsyncMatrixTable(10, 4, name="ck", ctx=two_ranks[1])
        t0.add_rows(np.arange(10),
                    np.arange(40, dtype=np.float32).reshape(10, 4))
        want = t0.get()
        with open(tmp_path / "ck.npz", "wb") as f:
            t0.store(f)
        t0.add(np.ones((10, 4), np.float32))     # diverge
        with open(tmp_path / "ck.npz", "rb") as f:
            t0.load(f)
        np.testing.assert_allclose(t0.get(), want)


    def test_malformed_punted_body_gets_fast_err_reply(self, two_ranks):
        """A frame whose header is sane but whose body fails to parse is
        punted by C++ and must come back as a FAST error reply bound to
        the header's msg_id — the python plane kills such connections
        immediately; silently dropping here would park the peer for the
        full ps_timeout (advisor r4 finding, ps/service.py _punt)."""
        import socket
        import time

        from multiverso_tpu.ps import wire

        host, port = two_ranks[0].service.addr.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=10)
        try:
            bad = b"{definitely not json"
            frame = wire._HEADER.pack(wire.MAGIC, 0x7F, 0, 42, len(bad),
                                      0, len(bad)) + bad
            t0 = time.monotonic()
            s.sendall(frame)
            msg_type, msg_id, meta, _ = wire.recv(s)
            took = time.monotonic() - t0
            assert msg_type == svc.MSG_REPLY_ERR
            assert msg_id == 42
            assert "WireError" in meta.get("error", "")
            assert took < 5.0, f"ERR reply took {took:.1f}s"
        finally:
            s.close()

    def test_state_roundtrip_under_load_through_restart(self, tmp_path):
        """VERDICT r4 item 8: GET_STATE/SET_STATE ride the C++->Python
        punt path (mv_ps.cpp serves only hot ops). A checkpoint taken
        while counted adds stream on the same connections must succeed
        (per-conn FIFO keeps the punts ordered among the adds), and a
        killed-and-restarted owner must get its updater accumulators
        back through the SET_STATE punt — state equality, not just row
        ops (ref: the abandoned MV_LoadTable plan, Test/main.cpp:302-316,
        that this framework claims to have made real)."""
        import io
        import threading
        import time

        import jax

        config.set_flag("ps_timeout", 20.0)
        config.set_flag("ps_connect_timeout", 5.0)
        config.set_flag("ps_reconnect_backoff", 0.3)
        rdv = FileRendezvous(str(tmp_path / "rdv_state"))
        ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
        new_ctx1 = None
        try:
            t0 = AsyncMatrixTable(10, 4, name="st", ctx=ctxs[0],
                                  updater="adagrad")
            AsyncMatrixTable(10, 4, name="st", ctx=ctxs[1],
                             updater="adagrad")
            # rows 5-9 (rank 1's shard) get deterministic traffic, then
            # quiesce — the snapshot content under test
            t0.add_rows(np.arange(5, 10), np.ones((5, 4), np.float32))
            t0.flush()
            want_rows = t0.get_rows(np.arange(5, 10)).copy()

            # hammer rank 0's rows from 2 threads WHILE store() punts
            # GET_STATE through the same native conns
            stop = threading.Event()

            def hammer():
                while not stop.is_set():
                    t0.add_rows_async(np.arange(5),
                                      np.ones((5, 4), np.float32))

            threads = [threading.Thread(target=hammer) for _ in range(2)]
            for th in threads:
                th.start()
            try:
                buf = io.BytesIO()
                t0.store(buf)
            finally:
                stop.set()
                for th in threads:
                    th.join()
            t0.flush()

            # diverge rank 1's shard after the snapshot; the restore must
            # wipe this
            t0.add_rows(np.arange(5, 10),
                        np.full((5, 4), 7.0, np.float32))
            t0.flush()

            # rank 1 dies and restarts as a NEW incarnation on the same
            # rendezvous (new port); survivors re-resolve with backoff
            ctxs[1].close()
            new_ctx1 = PSContext(1, 2, PSService(1, 2, rdv))
            t1b = AsyncMatrixTable(10, 4, name="st", ctx=new_ctx1,
                                   updater="adagrad")
            deadline = time.monotonic() + 60
            while True:
                try:
                    t0.load(io.BytesIO(buf.getvalue()))
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.3)

            # row data equals the snapshot (divergence wiped)...
            np.testing.assert_allclose(t0.get_rows(np.arange(5, 10)),
                                       want_rows)
            # ...and the restarted owner's adagrad accumulators equal the
            # checkpointed ones bit-for-bit (SET_STATE round-trip)
            stream = io.BytesIO(buf.getvalue())
            np.load(stream)                      # data
            np.load(stream)                      # state marker header
            saved_states = []
            for _ in range(2):
                n = int(np.load(stream)[0])
                saved_states.append([np.load(stream) for _ in range(n)])
            live = [np.asarray(x)
                    for x in jax.tree.leaves(t1b._shard._ustate)]
            assert len(live) == len(saved_states[1]) > 0
            for a, b in zip(saved_states[1], live):
                np.testing.assert_array_equal(a, b)
            # the plane stays usable after the whole dance
            t0.add_rows([7], np.ones((1, 4), np.float32))
            t0.flush()
        finally:
            ctxs[0].close()
            if new_ctx1 is not None:
                new_ctx1.close()


class TestNativeClientErrors:
    def test_out_of_shard_get_errors_cleanly(self, two_ranks):
        """A C++-served error reply must surface as NativeConnError with
        the server's message, and leave the connection usable."""
        AsyncMatrixTable(10, 2, name="er", ctx=two_ranks[0])
        conn = ps_native.NativeConn(two_ranks[0].service.addr, 5.0, 10.0)
        try:
            meta_b = b'{"table": "er"}'
            out = np.empty((1, 2), np.float32)
            mid = conn.get_send(svc.MSG_GET_ROWS, meta_b,
                                np.array([99], np.int64), out)
            with pytest.raises(ps_native.NativeConnError,
                               match="outside shard"):
                conn.get_wait(mid, 10.0)
            # connection still healthy: a valid get succeeds
            mid = conn.get_send(svc.MSG_GET_ROWS, meta_b,
                                np.array([1], np.int64), out)
            conn.get_wait(mid, 10.0)
            np.testing.assert_allclose(out, 0.0)
        finally:
            conn.close()

    def test_dead_peer_surfaces_pspeererror(self, tmp_path):
        ctxs = _world(tmp_path)
        t0 = AsyncMatrixTable(10, 2, name="dp", ctx=ctxs[0])
        AsyncMatrixTable(10, 2, name="dp", ctx=ctxs[1])
        t0.add_rows([7], np.ones((1, 2), np.float32))   # warm remote conn
        ctxs[1].close()                                  # "kill" rank 1
        with pytest.raises(svc.PSPeerError):
            for _ in range(20):   # first failure may land on either path
                t0.add_rows([7], np.ones((1, 2), np.float32))
        ctxs[0].close()
