"""URI-dispatched stream layer: local + remote (fsspec) backends.

The reference's remote backend is HDFS (ref src/io/hdfs_stream.cpp:1-157,
exercised only in the Docker battery against a live namenode); here the
remote seam is fsspec, and the fake-FS tier uses its ``memory://`` backend —
the same code path gs:// takes, minus the network.
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu import checkpoint
from multiverso_tpu.io.stream import TextReader, open_stream


def _clear_memfs():
    import fsspec
    fs = fsspec.filesystem("memory")
    fs.store.clear()


class TestLocalStream:
    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "sub" / "blob.bin")  # parent dir auto-created
        with open_stream(p, "wb") as s:
            s.write(b"hello multiverso")
        with open_stream("file://" + p, "rb") as s:
            assert s.read() == b"hello multiverso"

    def test_bad_scheme_raises(self):
        with pytest.raises(Exception):
            open_stream("no-such-scheme-xyz://bucket/obj", "rb")


class TestMemoryStream:
    """memory:// is the fake-FS stand-in for gs:// (same fsspec dispatch)."""

    def setup_method(self):
        _clear_memfs()

    def test_roundtrip(self):
        with open_stream("memory://bucket/dir/blob.bin", "wb") as s:
            s.write(b"\x00\x01remote")
        with open_stream("memory://bucket/dir/blob.bin", "rb") as s:
            assert s.read() == b"\x00\x01remote"

    def test_numpy_save_load(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        with open_stream("memory://bucket/arr.npy", "wb") as s:
            np.save(s, arr, allow_pickle=False)
        with open_stream("memory://bucket/arr.npy", "rb") as s:
            np.testing.assert_array_equal(np.load(s), arr)

    def test_text_reader(self):
        with open_stream("memory://bucket/corpus.txt", "wb") as s:
            s.write("line one\nline two\nline three\n".encode())
        lines = list(TextReader("memory://bucket/corpus.txt"))
        assert lines == ["line one", "line two", "line three"]


class TestRemoteCheckpoint:
    """Checkpoint save/restore through the remote stream layer — the
    capability the reference used HDFS for (ref io.h URI dispatch +
    hdfs_stream.cpp), proven here over the same fsspec seam gs:// rides."""

    def setup_method(self):
        _clear_memfs()

    def test_save_restore_memory_uri(self):
        mv.init()
        try:
            t = mv.ArrayTable(16, name="ckpt_arr")
            t.add(np.arange(16, dtype=np.float32))
            kv = mv.KVTable(name="ckpt_kv")
            kv.add([3, 5], [1.0, 2.0])
            path = checkpoint.save("memory://ckpt-bucket/run1", tag="step10")
            assert path.startswith("memory://")
            t.add(np.ones(16, np.float32))          # diverge
            kv.add([3], [9.0])
            n = checkpoint.restore("memory://ckpt-bucket/run1", tag="step10")
            assert n >= 2
            np.testing.assert_allclose(t.get(),
                                       np.arange(16, dtype=np.float32))
            assert kv.get([3, 5]) == {3: 1.0, 5: 2.0}
        finally:
            mv.shutdown()
