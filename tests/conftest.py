"""Test fixture: an 8-device virtual CPU mesh.

The reference's integration tier simulates a cluster with ``mpirun -np 4`` on
one host (SURVEY §4); the TPU-native analogue is
``--xla_force_host_platform_device_count=8`` on CPU — 8 virtual devices stand
in for 8 chips, so every sharding/collective path compiles and runs exactly as
it would on a pod slice.
"""

# The TPU plugin may already be registered by a site hook that imported jax
# at interpreter startup, so plain env vars are too late — force_cpu_mesh
# uses jax.config, which takes effect as long as no backend has been
# initialized yet.
from multiverso_tpu.utils.platform import force_cpu_mesh

force_cpu_mesh(8)

import pytest  # noqa: E402

# ---------------------------------------------------------------------- #
# Test tiering (SURVEY §4): the core tier must stay under ~5 min on the
# 8-device CPU mesh so CI and judges can run it wholesale; the big
# model-family / multi-process modules are the `slow` tier
# (``-m slow`` / excluded with ``-m "not slow"``).
# ---------------------------------------------------------------------- #
SLOW_MODULES = {
    "test_multiprocess",      # spawns N JAX subprocesses
    "test_multiprocess_async",  # spawns N async-PS subprocesses
    "test_we_async",          # WE PS-block training across 4 processes
    "test_transformer",       # full model family incl. ring/zigzag/beam
    "test_pipeline",          # GPipe + interleaved PP training runs
    "test_moe",               # expert-parallel training runs
    "test_quantization",      # quantized decode of a trained LM
    "test_resnet",            # CIFAR ResNet trainer
    "test_tp",                # TP/FSDP transformer training
    "test_flash_attention",   # flash kernel vs oracle sweeps
    "test_harness",           # full tier-2 battery incl. 2-process run
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__.rpartition(".")[2] in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(params=["native", "python"])
def two_ranks(request, tmp_path):
    """Two async-PS contexts sharing a file rendezvous — a 2-rank world in
    one process; every cross-rank op crosses a real localhost socket. The
    single-process tier-2 fixture for the uncoordinated plane.

    Parametrized over BOTH wire planes: the native C++ transport (the
    default everywhere libmv_ps builds) and the pure-python plane
    (ps_native off) — the fallback must not rot just because the fast
    path serves the battery. Where no toolchain built the library the
    "native" param degrades to python and simply duplicates coverage."""
    from multiverso_tpu.ps.service import (FileRendezvous, PSContext,
                                           PSService)
    from multiverso_tpu.utils import config
    if request.param == "python":
        config.set_flag("ps_native", False)
    rdv = FileRendezvous(str(tmp_path / "rdv"))
    ctxs = [PSContext(r, 2, PSService(r, 2, rdv)) for r in range(2)]
    yield ctxs
    for c in ctxs:
        c.close()


@pytest.fixture(autouse=True)
def _fresh_runtime():
    """Reset flags + Zoo between tests (the reference restarts processes)."""
    from multiverso_tpu.utils import config
    from multiverso_tpu.utils.dashboard import Dashboard
    from multiverso_tpu.zoo import Zoo
    yield
    zoo = Zoo.get()
    if zoo.started:
        zoo.stop()
    config.reset_flags()
    Dashboard.reset()
    # telemetry plane: a test that enabled tracing/export must not leak
    # spans or a running exporter thread into its neighbors
    from multiverso_tpu.telemetry import aggregator as _aggregator
    from multiverso_tpu.telemetry import exporter as _exporter
    from multiverso_tpu.telemetry import flightrec as _flightrec
    from multiverso_tpu.telemetry import trace as _trace
    from multiverso_tpu.telemetry import watchdog as _watchdog
    # no final poll: the service a leaked aggregator is bound to may be
    # gone, and teardown must not wait out probe timeouts; same rule
    # for a leaked shard checkpointer's final save
    _aggregator.stop_global(final=False)
    from multiverso_tpu.ps import failover as _failover
    _failover.stop_global(final=False)
    _exporter.stop_global()
    _trace.TRACER.reset()
    _trace.TRACER.enabled = False
    # step profiler: drop records/aggregates and disable (a test that
    # enabled step_profile must not leak steps into its neighbors)
    from multiverso_tpu.telemetry import profiler as _profiler
    _profiler.reset()
    # memory plane: stop a leaked sampler thread and drop the ledger's
    # sample history / verdict episodes / peaks (a test's deliberate
    # leak must not verdict a neighbor's sweep). Registrations stay:
    # they are weakrefs — dead components self-prune — and the
    # import-time module gauges (checkpoint.py) register only once.
    from multiverso_tpu.telemetry import memstats as _memstats
    _memstats.reset()
    # device plane: drop transfer/collective/compile counters and the
    # hygiene report (a test's synthetic SPMD warning must not dirty a
    # neighbor's clean-report assertion); the jax listener stays (it
    # re-reads enabled) and reset() restores the default-on gate
    from multiverso_tpu.telemetry import devstats as _devstats
    _devstats.reset()
    # fault-injection plane (ISSUE 14): disarm — one test's chaos
    # scenario must not inject into its neighbors' wires
    from multiverso_tpu.ps import faults as _faults
    _faults.disarm()
    # mesh data plane (ISSUE 15): drop the process-colocation registry
    # and any stacked shard groups — a leaked service must not stay
    # routable, and a plane's pooled device array must not outlive its
    # test (services that closed cleanly already unregistered)
    from multiverso_tpu.ps import spmd as _spmd
    _spmd.reset_registry()
    # flight-recorder plane: drop the ring/in-flight table and stop the
    # watchdog so one test's wedged ops can't trip a neighbor's verdict;
    # unpin the logger's rank stamp too (first-caller-wins, like the
    # tracer — a rank-R test must not stamp every later test's records)
    _watchdog.reset()
    _flightrec.reset()
    # tenant attribution plane (ISSUE 18): drop per-tenant counters,
    # ledger episodes and any thread-local tenant override — one test's
    # storm must not verdict (or attribute into) a neighbor's sweep
    from multiverso_tpu.telemetry import tenants as _tenants
    _tenants.reset()
    from multiverso_tpu.utils import log as _log
    _log.reset_rank()
