"""Multi-process integration: N coordinated JAX processes on one host — the
TPU-era analogue of the reference's ``mpirun -np N`` fixture (SURVEY §4
tier 2; Docker CI ran kv/array/net/barrier at np=4)."""

import json
import os
import socket
import subprocess
import sys

import pytest

_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_DIR)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nprocs", [2, 4])
def test_process_cluster(nprocs):
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_DIR, "multiprocess_worker.py"),
             coordinator, str(nprocs), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True)
        for pid in range(nprocs)
    ]
    results = {}
    errors = []
    for pid, p in enumerate(procs):
        try:
            stdout, stderr = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            p.kill()
            pytest.fail(f"process {pid} timed out")
        if p.returncode != 0:
            errors.append(f"pid {pid} rc={p.returncode}\n{stderr[-2000:]}")
            continue
        for line in stdout.splitlines():
            if line.startswith("RESULT "):
                results[pid] = json.loads(line[len("RESULT "):])
    if errors:
        if any("distributed" in e or "initialize" in e for e in errors):
            pytest.skip("jax.distributed unavailable in this environment: "
                        + errors[0][:200])
        pytest.fail("\n".join(errors))

    assert set(results) == set(range(nprocs))
    tri = nprocs * (nprocs + 1) / 2  # sum of each rank's (rank+1)
    for pid, r in results.items():
        assert r["rank"] == pid
        assert r["size"] == nprocs
        assert r["num_workers"] == nprocs
        assert r["devices"] == 2 * nprocs  # nprocs x 2 local cpu devices
        # aggregate of rank+1 over all ranks
        assert r["aggregate"] == [tri] * 4
        # kv: rank r adds keys 0..r, value 10 each -> key k has 10*(N-k)
        assert r["kv"] == {str(k): 10.0 * (nprocs - k)
                           for k in range(nprocs)}
        # aggregated Get sees the same server-summed view
        assert r["kv_global"] == r["kv"]
        # matrix collective row add of rank+1 in both rows
        assert r["matrix_rows"] == [[tri] * 4, [tri] * 4]
        # union-of-ids collective: rank p adds rows {p, p+1} with value p+1
        expect_union = [(k + 1 if k < nprocs else 0) + (k if k >= 1 else 0)
                        for k in range(nprocs + 1)]
        assert r["matrix_union"] == [float(v) for v in expect_union]
        # sparse dirty bits cover the union: every rank added 1.0 to its own
        # row, and every rank must observe ALL of them fresh
        assert r["sparse_union"] == [1.0] * nprocs + [0.0]
        # the multi-host rendezvous path was actually taken
        assert r["rendezvous"] == "JaxRendezvous"
        # async plane over the coordinator KV store: rank p pushed its 8
        # disjoint rows (value 1) p+1 times -> sum = 8*4*tri
        assert r["async_row_sum"] == 8 * 4 * tri
        # sharedvar: every worker pushed +1 -> merged value N everywhere
        assert r["sharedvar"] == [float(nprocs)] * 4


_SSP_WORKER = """
import json, sys, time
sys.path.insert(0, {repo!r})
from multiverso_tpu.ssp import SSPClock

wid = int(sys.argv[1])
clk = SSPClock({clocks!r}, staleness=1, num_workers=2, worker_id=wid,
               poll=0.005, timeout=30.0)
history = []
for _ in range(10):
    time.sleep(0.0 if wid == 0 else 0.02)   # worker 0 is the fast one
    c = clk.tick()
    history.append([c, min(clk.peer_clocks().values())])
print("RESULT " + json.dumps(history))
"""


def test_two_process_ssp_bound(tmp_path):
    """Two real processes under the staleness-1 bound: neither may return
    from tick(c) while the other is below c - 1."""
    clocks = str(tmp_path / "clocks")
    script = _SSP_WORKER.format(repo=_REPO, clocks=clocks)
    procs = [subprocess.Popen([sys.executable, "-c", script, str(wid)],
                              stdout=subprocess.PIPE, text=True)
             for wid in range(2)]
    histories = {}
    try:
        for wid, p in enumerate(procs):
            try:
                stdout, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                pytest.fail(f"ssp worker {wid} timed out (bound deadlock?)")
            assert p.returncode == 0
            for line in stdout.splitlines():
                if line.startswith("RESULT "):
                    histories[wid] = json.loads(line[len("RESULT "):])
    finally:
        for p in procs:  # no orphans on any failure path
            if p.poll() is None:
                p.kill()
                p.wait()
    assert set(histories) == {0, 1}
    for wid, hist in histories.items():
        assert len(hist) == 10
        for clock, min_peer in hist:
            assert min_peer >= clock - 1, (wid, clock, min_peer)
    # the fast worker must have actually been held back by the bound at
    # some point (otherwise the test proves nothing)
    fast = histories[0]
    assert any(clock - min_peer >= 1 for clock, min_peer in fast)
