"""Native C++ data pipeline vs the Python reference implementations
(mv_data.cpp; ref reader.cpp/dictionary.cpp territory)."""

import numpy as np
import pytest

from multiverso_tpu import native
from multiverso_tpu.data.dictionary import Dictionary
from multiverso_tpu.models import word2vec as w2v

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


@pytest.fixture
def corpus_file(tmp_path):
    text = ("the quick brown fox jumps over the lazy dog " * 200 +
            "pack my box with five dozen liquor jugs " * 100)
    p = tmp_path / "c.txt"
    p.write_text(text)
    return str(p), text


class TestNativeCorpus:
    def test_matches_python_dictionary(self, corpus_file):
        path, text = corpus_file
        nc = native.NativeCorpus(path, min_count=5)
        pd = Dictionary.build(text.split(), min_count=5)
        assert nc.vocab_size == len(pd)
        assert nc.words() == pd.words
        np.testing.assert_array_equal(nc.counts(), pd.counts)
        np.testing.assert_array_equal(nc.ids(), pd.encode(text.split()))
        assert nc.total_tokens == len(text.split())

    def test_min_count_prunes(self, corpus_file):
        path, text = corpus_file
        nc = native.NativeCorpus(path, min_count=150)
        # only the 'the' (400) and the 9-word *200 sentence words (200 each)
        assert nc.vocab_size == 8  # 'the' + 7 other words at 200; dog/fox...
        assert all(c >= 150 for c in nc.counts())

    def test_max_vocab(self, corpus_file):
        path, _ = corpus_file
        nc = native.NativeCorpus(path, min_count=1, max_vocab=3)
        assert nc.vocab_size == 3

    def test_missing_file(self):
        with pytest.raises(IOError):
            native.NativeCorpus("/nonexistent/file.txt")


class TestNativeSubsample:
    def test_distribution_matches_python(self):
        rng = np.random.default_rng(0)
        counts = np.array([50_000, 5_000, 50], dtype=np.int64)
        ids = rng.choice(3, p=counts / counts.sum(), size=30_000)
        native_kept = native.subsample(ids, counts, t=1e-3, seed=1)
        d = Dictionary(min_count=1)
        d.counts = counts
        py_kept = d.subsample(ids.astype(np.int64), t=1e-3, seed=1)
        # independent RNGs: compare survival rates, not exact sets
        for w in range(3):
            n_nat = np.sum(native_kept == w)
            n_py = np.sum(py_kept == w)
            denom = max(np.sum(ids == w), 1)
            assert abs(n_nat - n_py) / denom < 0.05


class TestNativePairs:
    def test_pair_multiset_matches_python(self):
        ids = np.arange(50, dtype=np.int64) % 7
        nc, nx = native.generate_pairs(ids, window=2, dynamic=False)
        pc, px = w2v.generate_pairs(ids, window=2, dynamic=False)
        assert nc.size == pc.size
        nat = sorted(zip(nc.tolist(), nx.tolist()))
        py = sorted(zip(pc.tolist(), px.tolist()))
        assert nat == py

    def test_dynamic_window_bounds(self):
        ids = np.arange(200, dtype=np.int64)
        c, x = native.generate_pairs(ids, window=5, seed=3, dynamic=True)
        assert 0 < c.size <= 2 * 5 * 200
        assert np.all(np.abs(c - x) <= 5)

    def test_deterministic_given_seed(self):
        ids = np.arange(100, dtype=np.int64)
        a = native.generate_pairs(ids, 3, seed=7)
        b = native.generate_pairs(ids, 3, seed=7)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])


class TestNativeLibsvm:
    def test_parse(self):
        out = native.parse_libsvm_line(b"2 0:1.5 4:-2.0", 6)
        assert out is not None
        label, x = out
        assert label == 2
        np.testing.assert_allclose(x, [1.5, 0, 0, 0, -2.0, 0])

    def test_comment_and_empty(self):
        assert native.parse_libsvm_line(b"# hi", 4) is None
        assert native.parse_libsvm_line(b"   ", 4) is None
