"""Device-plane observability (ISSUE 12, telemetry/devstats.py): the
transfer chokepoint, collective spans, mesh-keyed compile attribution,
the per-device live-arrays rollup, the SPMD compile-hygiene capture,
the MSG_STATS "devices" block on both wire planes, every renderer's
mixed-version (block-absent) path, the scale harness's E_n oracle, and
the new check_obs_surface coverage rules."""

import json
import os
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from multiverso_tpu.telemetry import devstats  # noqa: E402
from multiverso_tpu.telemetry import flightrec  # noqa: E402


# ---------------------------------------------------------------------- #
# E_n oracle (tools/bench_scale.efficiency_curve is pure)
# ---------------------------------------------------------------------- #
class TestEfficiencyOracle:
    def test_perfect_linear_scaling_is_all_ones(self):
        from tools.bench_scale import efficiency_curve
        out = efficiency_curve({1: 100.0, 2: 200.0, 4: 400.0, 8: 800.0})
        assert out["efficiency"] == {1: 1.0, 2: 1.0, 4: 1.0, 8: 1.0}
        assert out["efficiency_min"] == 1.0

    def test_hand_computed_curve(self):
        from tools.bench_scale import efficiency_curve
        # E_n = T_n / (n * T_1): 150/(2*100)=0.75, 240/(4*100)=0.6
        out = efficiency_curve({1: 100.0, 2: 150.0, 4: 240.0})
        assert out["efficiency"][2] == pytest.approx(0.75)
        assert out["efficiency"][4] == pytest.approx(0.6)
        assert out["efficiency_min"] == pytest.approx(0.6)

    def test_string_keys_accepted(self):
        # JSON round-trips turn int keys into strings; the oracle must
        # not care which spelling it gets
        from tools.bench_scale import efficiency_curve
        out = efficiency_curve({"1": 100.0, "2": 100.0})
        assert out["efficiency"][2] == pytest.approx(0.5)

    def test_missing_or_zero_baseline_yields_none(self):
        from tools.bench_scale import efficiency_curve
        assert efficiency_curve({2: 100.0})["efficiency_min"] is None
        assert efficiency_curve({1: 0.0, 2: 1.0})["efficiency_min"] is None
        assert efficiency_curve({})["efficiency_min"] is None

    def test_superlinear_points_allowed(self):
        # cache effects can push E_n above 1; the oracle records, the
        # regression flag (higher-is-better) only cares about drops
        from tools.bench_scale import efficiency_curve
        out = efficiency_curve({1: 100.0, 2: 250.0})
        assert out["efficiency"][2] == pytest.approx(1.25)


# ---------------------------------------------------------------------- #
# mesh labels + hygiene classification (pure)
# ---------------------------------------------------------------------- #
class TestMeshLabelAndClassify:
    def test_label_spellings(self):
        assert devstats.mesh_label(None) == "unmeshed"
        assert devstats.mesh_label("{'mv': 4}") == "{'mv': 4}"
        assert devstats.mesh_label({"mv": 4}) == "{'mv': 4}"

    def test_label_of_real_mesh(self):
        import jax
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("mv",))
        assert devstats.mesh_label(mesh) == "{'mv': 2}"

    def test_classification_vocabulary(self):
        cl = devstats.classify_compile_warning
        assert cl("SPMD rematerialization triggered") == "remat"
        assert cl("could not infer sharding for op") == "sharding-fallback"
        assert cl("Falling back to REPLICATED sharding") \
            == "sharding-fallback"
        assert cl("Some donated buffers were not usable") == "donation"
        assert cl("SPMD pipelining note from xla") == "spmd"
        # noise is NOT a finding
        assert cl("DeprecationWarning: jax.tree_map is deprecated") is None
        assert cl("") is None


class TestHygieneCapture:
    def test_synthetic_spmd_warning_becomes_report_entry(self):
        import warnings
        with devstats.capture_hygiene("fn_a", mesh={"mv": 4}) as scope:
            warnings.warn("sharding propagation could not infer "
                          "sharding; falling back to replicated")
        assert len(scope.entries) == 1
        rep = devstats.hygiene_report()
        assert rep["clean"] is False
        (e,) = rep["findings"]
        assert e["fn"] == "fn_a" and e["mesh"] == "{'mv': 4}"
        assert e["category"] == "sharding-fallback"
        assert rep["checked"][0]["captured"] == 1

    def test_clean_compile_yields_empty_report(self):
        import jax
        import jax.numpy as jnp
        with devstats.capture_hygiene("fn_clean", mesh={"mv": 1}):
            jax.jit(lambda x: x * 2)(jnp.ones(3)).block_until_ready()
        rep = devstats.hygiene_report()
        assert rep["clean"] is True and rep["findings"] == []
        assert rep["checked"][0]["fn"] == "fn_clean"

    def test_jax_logger_messages_are_captured_too(self):
        import logging
        with devstats.capture_hygiene("fn_log", mesh={"mv": 2}):
            logging.getLogger("jax").warning(
                "spmd partition fell back somewhere")
        rep = devstats.hygiene_report()
        assert rep["clean"] is False
        assert rep["findings"][0]["category"] == "sharding-fallback" \
            or rep["findings"][0]["category"] == "spmd"

    def test_noise_does_not_dirty_the_report(self):
        import warnings
        with devstats.capture_hygiene("fn_noise", mesh={"mv": 2}):
            warnings.warn("user warning about nothing in particular")
        rep = devstats.hygiene_report()
        assert rep["clean"] is True
        assert rep["checked"][0]["captured"] == 1
        assert rep["checked"][0]["findings"] == 0

    def test_dump_hygiene_writes_json(self, tmp_path):
        import warnings
        with devstats.capture_hygiene("fn_d", mesh={"mv": 8}):
            warnings.warn("rematerialization inserted")
        path = devstats.dump_hygiene(str(tmp_path), rank=3)
        assert os.path.basename(path) == "compile-hygiene-rank3.json"
        with open(path) as f:
            rep = json.load(f)
        assert rep["rank"] == 3 and rep["clean"] is False


# ---------------------------------------------------------------------- #
# per-device census rollup (fixture-injected; no live backend needed)
# ---------------------------------------------------------------------- #
class _FakeShard:
    def __init__(self, device, nbytes):
        self.device = device
        self.data = type("D", (), {"nbytes": nbytes})()


class _FakeSharded:
    def __init__(self, shards):
        self.addressable_shards = shards


class _FakeSingle:
    def __init__(self, device, nbytes):
        self.addressable_shards = None
        self.nbytes = nbytes
        self._device = device

    def devices(self):
        return {self._device}


class TestDeviceRollup:
    def test_hand_built_fixture_grouping(self):
        arrays = [
            _FakeSharded([_FakeShard("cpu:0", 100),
                          _FakeShard("cpu:1", 300)]),
            _FakeSingle("cpu:0", 50),
            _FakeSharded([_FakeShard("cpu:1", 7)]),
        ]
        per = devstats.device_rollup(arrays)
        assert per == {"cpu:0": {"bytes": 150, "arrays": 2},
                       "cpu:1": {"bytes": 307, "arrays": 2}}

    def test_broken_entry_skipped_not_fatal(self):
        class Broken:
            @property
            def addressable_shards(self):
                raise RuntimeError("donated mid-walk")

        per = devstats.device_rollup([Broken(),
                                      _FakeSingle("cpu:0", 9)])
        assert per == {"cpu:0": {"bytes": 9, "arrays": 1}}

    def test_live_backend_rollup_charges_devices(self):
        import jax
        import jax.numpy as jnp
        a = jnp.ones((128, 8), jnp.float32) + 1  # keep a live result
        per = devstats.device_rollup()
        assert per, "live rollup found no arrays"
        total = sum(g["bytes"] for g in per.values())
        assert total >= a.nbytes


# ---------------------------------------------------------------------- #
# transfer chokepoint + collective spans
# ---------------------------------------------------------------------- #
class TestTransfersAndSpans:
    def test_per_direction_counters(self):
        devstats.note_transfer(100, "h2d")
        devstats.note_transfer(50, "h2d")
        devstats.note_transfer(7, "d2h")
        snap = devstats.stats_snapshot()
        assert snap["transfers"]["h2d"] == {"ops": 2, "bytes": 150}
        assert snap["transfers"]["d2h"] == {"ops": 1, "bytes": 7}

    def test_unknown_direction_raises(self):
        with pytest.raises(ValueError):
            devstats.note_transfer(1, "sideways")

    def test_h2d_feeds_profiler_delta(self):
        # the PR-9 counter this chokepoint generalizes is gated on the
        # step_profile flag like every profiler site
        from multiverso_tpu.telemetry import profiler
        from multiverso_tpu.utils import config
        config.set_flag("step_profile", True)
        profiler.configure()
        before = profiler.jax_counters().get("transfer_bytes", 0)
        devstats.note_transfer(4096, "h2d")
        assert profiler.jax_counters()["transfer_bytes"] - before == 4096

    def test_span_lands_dashboard_flightrec_and_tally(self):
        from multiverso_tpu.utils.dashboard import Dashboard
        with devstats.collective_span("test_op", 2048, mesh={"mv": 2}):
            pass
        snap = devstats.stats_snapshot()
        assert snap["collectives"]["test_op"]["calls"] == 1
        assert snap["collectives"]["test_op"]["bytes"] == 2048
        assert Dashboard.get("coll[test_op].calls").count == 1
        assert Dashboard.get("coll[test_op].bytes").count == 2048
        # ring slots are (seq, mono, kind, peer, msg_type, msg_id,
        # nbytes, note)
        evs = [r for r in flightrec.RECORDER.snapshot()
               if r[2] in (flightrec.EV_COLL_BEGIN,
                           flightrec.EV_COLL_END)]
        assert len(evs) == 2
        assert all(r[7] == "coll.test_op" for r in evs)
        assert all(r[6] == 2048 for r in evs)

    def test_flag_off_is_null_context_and_dark_counters(self):
        from multiverso_tpu.utils import config
        config.set_flag("devstats", False)
        devstats.configure()
        try:
            assert not devstats.enabled()
            ctx = devstats.collective_span("off_op", 1)
            assert ctx is devstats._NULL
            with ctx:
                pass
            devstats.note_transfer(5, "d2h")   # counters stay dark
            snap = devstats.stats_snapshot()
            assert snap is None
        finally:
            config.set_flag("devstats", True)
            devstats.configure()

    def test_snapshot_none_when_nothing_happened(self):
        # fresh state, no transfers/collectives/compiles: the block is
        # OMITTED from payloads, not emitted empty (device_rollup may
        # still see live arrays from neighbors — tolerate that shape)
        snap = devstats.stats_snapshot()
        if snap is not None:
            assert set(snap) >= {"per_device"} or snap.get("per_device")


# ---------------------------------------------------------------------- #
# collectives integration: spans + the mapped-callable cache
# ---------------------------------------------------------------------- #
class TestCollectivesRecord:
    def test_all_ops_record_spans_and_results_hold(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from multiverso_tpu.parallel import collectives as C
        n = 2
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("mv",))
        x = jnp.arange(n * 4, dtype=jnp.float32)
        out = np.asarray(C.all_reduce(x, mesh=mesh))
        np.testing.assert_allclose(out, np.arange(8.).reshape(2, 4)
                                    .sum(axis=0))
        np.testing.assert_allclose(np.asarray(C.all_gather(x, mesh=mesh)),
                                    np.arange(8.))
        np.testing.assert_allclose(
            np.asarray(C.reduce_scatter(x, mesh=mesh)), np.arange(8.))
        np.testing.assert_allclose(
            np.asarray(C.broadcast(x, root=1, mesh=mesh)),
            np.arange(8.)[4:])
        snap = devstats.stats_snapshot()
        for op in ("all_reduce", "all_gather", "reduce_scatter",
                   "broadcast"):
            assert snap["collectives"][op]["calls"] == 1, op
            assert snap["collectives"][op]["bytes"] == x.nbytes

    def test_mapped_cache_stops_percall_recompiles(self):
        # the bug devstats caught: rebuilding the shard_map closure per
        # call recompiled EVERY collective call. With the cache, calls
        # 2..k add zero compiles for an unchanged (op, mesh, shape).
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from multiverso_tpu.parallel import collectives as C
        devstats.configure(0)   # install the mesh-keyed listener
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("mv",))
        x = jnp.ones(64, jnp.float32)
        C.all_reduce(x, mesh=mesh).block_until_ready()   # compile once

        def compiles():
            snap = devstats.stats_snapshot() or {}
            return sum(c.get("compiles", 0) for c in
                       (snap.get("compiles_by_mesh") or {}).values())

        before = compiles()
        for _ in range(3):
            C.all_reduce(x, mesh=mesh).block_until_ready()
        assert compiles() == before, \
            "steady-state collective calls recompiled"


# ---------------------------------------------------------------------- #
# MSG_STATS "devices" block: local + over-socket on both wire planes
# ---------------------------------------------------------------------- #
class TestStatsBlock:
    def test_local_payload_carries_block_after_activity(self, two_ranks):
        devstats.note_transfer(640, "h2d")
        payload = two_ranks[0].service.stats_payload()
        assert payload["devices"]["transfers"]["h2d"]["bytes"] == 640

    def test_over_socket_both_planes(self, two_ranks):
        # two_ranks is parametrized native/python — one test body
        # covers both wire planes. DevStats is process-global, so the
        # in-process peer reports the same block (the documented
        # collapse, deduped by (host, pid) in the cluster merge).
        with devstats.collective_span("sock_op", 96, mesh={"mv": 2}):
            pass
        st = two_ranks[0].service.stats_oneshot(1)
        assert st["devices"]["collectives"]["sock_op"]["bytes"] == 96

    def test_mvtop_live_world_shows_device_panel(self, two_ranks,
                                                 tmp_path):
        # the ISSUE-12 acceptance shape: collectives visible in mvtop
        # from a LIVE world — real one-shot probe sockets, both wire
        # planes (two_ranks param), no fixture payloads
        from tools import mvtop
        devstats.note_transfer(2048, "h2d")
        with devstats.collective_span("live_op", 4096, mesh={"mv": 2}):
            pass
        addrs = mvtop.read_addrs(str(tmp_path / "rdv"))
        assert sorted(addrs) == [0, 1]
        rec = mvtop.poll(addrs, timeout=5.0)
        assert rec["devices"]["totals"]["coll_calls"] >= 1
        out = mvtop.render(rec)
        assert "devices:" in out and "live_op:1" in out
        # ...and in mv_dev_* Prometheus text from the same live payload
        from multiverso_tpu.telemetry.exporter import prometheus_text
        st = two_ranks[0].service.stats_oneshot(0)
        text = prometheus_text(st)
        assert 'mv_dev_collective_calls{op="live_op"' in text

    def test_absent_block_stays_absent(self, two_ranks):
        # a rank with devstats off emits NO devices key — the
        # mixed-version shape every consumer must render
        from multiverso_tpu.utils import config
        config.set_flag("devstats", False)
        devstats.configure()
        try:
            payload = two_ranks[0].service.stats_payload()
            assert "devices" not in payload
        finally:
            config.set_flag("devstats", True)
            devstats.configure()


# ---------------------------------------------------------------------- #
# cluster merge + renderers (incl. the mixed-version/absent paths)
# ---------------------------------------------------------------------- #
def _stats(rank, pid, devices=None):
    st = {"rank": rank, "addr": f"127.0.0.1:90{rank}", "pid": pid,
          "monitors": {}, "shards": {}}
    if devices is not None:
        st["devices"] = devices
    return st


_DEV_A = {
    "transfers": {"h2d": {"ops": 3, "bytes": 3000},
                  "d2h": {"ops": 1, "bytes": 100}},
    "collectives": {"all_reduce": {"calls": 4, "bytes": 4096,
                                   "ms": 12.5}},
    "compiles_by_mesh": {"{'mv': 2}": {"compiles": 2,
                                       "compile_s": 1.25}},
    "per_device": {"cpu:0": {"bytes": 512, "arrays": 2}},
}


class TestMergeAndRender:
    def test_merge_cluster_devices_ranks_and_totals(self):
        from multiverso_tpu.telemetry import aggregator
        health = {0: {"status": "ok"}, 1: {"status": "ok"}}
        stats = {0: _stats(0, pid=10, devices=_DEV_A),
                 1: _stats(1, pid=11, devices=_DEV_A)}
        rec = aggregator.merge_cluster(stats, health, world=2)
        assert set(rec["devices"]["ranks"]) == {"0", "1"}
        t = rec["devices"]["totals"]
        # two distinct processes: summed
        assert t["h2d_bytes"] == 6000 and t["d2h_bytes"] == 200
        assert t["coll_calls"] == 8 and t["coll_bytes"] == 8192
        assert t["compiles"] == 4 and t["device_bytes"] == 1024

    def test_merge_dedupes_same_process(self):
        from multiverso_tpu.telemetry import aggregator
        health = {0: {"status": "ok"}, 1: {"status": "ok"}}
        stats = {0: _stats(0, pid=10, devices=_DEV_A),
                 1: _stats(1, pid=10, devices=_DEV_A)}  # same pid
        rec = aggregator.merge_cluster(stats, health, world=2)
        t = rec["devices"]["totals"]
        assert t["h2d_bytes"] == 3000 and t["coll_calls"] == 4

    def test_merge_without_blocks_has_no_devices_key(self):
        from multiverso_tpu.telemetry import aggregator
        health = {0: {"status": "ok"}}
        rec = aggregator.merge_cluster({0: _stats(0, pid=10)}, health,
                                       world=1)
        assert "devices" not in rec

    def test_mvtop_renders_device_panel(self):
        from multiverso_tpu.telemetry import aggregator
        from tools import mvtop
        health = {0: {"status": "ok"}, 1: {"status": "ok"}}
        stats = {0: _stats(0, pid=10, devices=_DEV_A),
                 1: _stats(1, pid=11)}       # rank 1: NO block
        rec = aggregator.merge_cluster(stats, health, world=2)
        out = mvtop.render(rec)
        assert "devices:" in out and "all_reduce:4" in out
        assert "{'mv': 2}" in out

    def test_mvtop_renders_without_devices_block(self):
        # mixed-version cluster: NO rank carries the block — the
        # explicit no-KeyError-panels satellite
        from multiverso_tpu.telemetry import aggregator
        from tools import mvtop
        health = {0: {"status": "ok"}, 1: {"status": "ok"}}
        stats = {0: _stats(0, pid=10), 1: _stats(1, pid=11)}
        rec = aggregator.merge_cluster(stats, health, world=2)
        out = mvtop.render(rec)
        assert "devices:" not in out
        assert "rank" in out   # the health table still rendered

    def test_dump_metrics_renders_rank_and_cluster_devices(self):
        from multiverso_tpu.telemetry import aggregator
        from tools import dump_metrics
        rank_rec = dict(_stats(0, pid=10, devices=_DEV_A), ts=1.0)
        out = dump_metrics.format_record(rank_rec)
        assert "devices.transfers" in out and "all_reduce" in out
        health = {0: {"status": "ok"}}
        rec = aggregator.merge_cluster(
            {0: _stats(0, pid=10, devices=_DEV_A)}, health, world=1)
        out = dump_metrics.format_record(rec)
        assert "devices(cluster):" in out

    def test_dump_metrics_renders_without_devices(self):
        from tools import dump_metrics
        out = dump_metrics.format_record(dict(_stats(0, pid=10), ts=1.0))
        assert "devices" not in out
        from multiverso_tpu.telemetry import aggregator
        rec = aggregator.merge_cluster({0: _stats(0, pid=10)},
                                       {0: {"status": "ok"}}, world=1)
        assert "devices" not in dump_metrics.format_record(rec)

    def test_exporter_emits_mv_dev_gauges(self):
        from multiverso_tpu.telemetry.exporter import prometheus_text
        text = prometheus_text({"rank": 0, "monitors": {}, "shards": {},
                                "devices": _DEV_A})
        assert 'mv_dev_transfer_bytes{direction="h2d",rank="0"} 3000' \
            in text
        assert 'mv_dev_collective_calls{op="all_reduce",rank="0"} 4' \
            in text
        assert "mv_dev_compiles{mesh=\"{'mv': 2}\",rank=\"0\"} 2" in text
        assert 'mv_dev_live_bytes{device="cpu:0",rank="0"} 512' in text
        # absent block: no mv_dev_ series at all, no error
        text = prometheus_text({"rank": 0, "monitors": {}, "shards": {}})
        assert "mv_dev_" not in text

    def test_mvprof_hygiene_report_render(self, tmp_path):
        import warnings
        from tools import mvprof
        with devstats.capture_hygiene("fn_x", mesh={"mv": 4}):
            warnings.warn("remat triggered by spmd partitioner")
        devstats.dump_hygiene(str(tmp_path), rank=0)
        reports = mvprof.collect_hygiene([str(tmp_path)])
        assert len(reports) == 1 and reports[0]["clean"] is False
        out = mvprof.render_hygiene(reports)
        assert "FINDING [remat]" in out and "fn_x" in out
        # main() renders hygiene even with no step records
        assert mvprof.main([str(tmp_path)]) == 0


# ---------------------------------------------------------------------- #
# run_bench: efficiency regression flags + BENCH_HISTORY trajectory
# ---------------------------------------------------------------------- #
class TestRunBenchScale:
    def test_synthetic_efficiency_regression_flagged(self):
        from tools.run_bench import flag_regressions
        prev = {"extra": {"scale": {"efficiency_min": 0.8,
                                    "t1_rows_per_s": 4000}}}
        worse = {"extra": {"scale": {"efficiency_min": 0.3,
                                     "t1_rows_per_s": 3900}}}
        flags = flag_regressions(prev, worse)
        assert len(flags) == 1
        assert "mesh scaling efficiency" in flags[0]
        # a baseline drop flags on its own key
        t1_drop = {"extra": {"scale": {"efficiency_min": 0.78,
                                       "t1_rows_per_s": 1200}}}
        flags = flag_regressions(prev, t1_drop)
        assert len(flags) == 1
        assert "single-shard baseline" in flags[0]
        # same record: clean; missing scale block: skipped
        assert flag_regressions(prev, prev) == []
        assert flag_regressions({"extra": {}}, worse) == []

    def test_per_point_efficiency_regression_flagged(self):
        """ISSUE 15: E_2 / E_4 are tracked as their OWN keys — a drop
        at one point must flag even when the curve's min (a different
        point) holds."""
        from tools.run_bench import flag_regressions
        prev = {"extra": {"scale": {"efficiency_min": 0.1,
                                    "e2": 0.8, "e4": 0.4,
                                    "t1_rows_per_s": 4000}}}
        e2_drop = {"extra": {"scale": {"efficiency_min": 0.1,
                                       "e2": 0.3, "e4": 0.4,
                                       "t1_rows_per_s": 4000}}}
        flags = flag_regressions(prev, e2_drop)
        assert len(flags) == 1 and "E_2" in flags[0]
        e4_drop = {"extra": {"scale": {"efficiency_min": 0.1,
                                       "e2": 0.8, "e4": 0.15,
                                       "t1_rows_per_s": 4000}}}
        flags = flag_regressions(prev, e4_drop)
        assert len(flags) == 1 and "E_4" in flags[0]

    def test_history_entry_and_append(self, tmp_path):
        from tools.run_bench import append_history, history_entry
        rec = {"complete": True, "truncated": False,
               "regressions": ["x regressed"],
               "headline": {"value": 123.4, "unit": "w/s",
                            "vs_baseline": 1.01,
                            "extra": {"scale": {"efficiency_min": 0.7,
                                                "t1_rows_per_s": 100},
                                      "we": {"words_per_s": 5.0}}}}
        ent = history_entry(rec, "/x/BENCH_r07.json", ts=1000.0)
        assert ent["record"] == "BENCH_r07.json"
        assert ent["metrics"]["scale.efficiency_min"] == 0.7
        assert ent["metrics"]["scale.t1_rows_per_s"] == 100
        assert ent["metrics"]["we.words_per_s"] == 5.0
        assert ent["regressions"] == ["x regressed"]
        hist = tmp_path / "BENCH_HISTORY.jsonl"
        append_history(ent, str(hist))
        append_history(dict(ent, ts=2000.0), str(hist))
        lines = [json.loads(ln) for ln in
                 hist.read_text().splitlines()]
        assert len(lines) == 2 and lines[0]["ts"] == 1000.0

    def test_dump_metrics_history_render_and_diff(self, tmp_path):
        from tools import dump_metrics
        hist = tmp_path / "BENCH_HISTORY.jsonl"
        a = {"ts": 1.0, "record": "BENCH_r06.json", "complete": True,
             "truncated": False, "value": 100.0, "unit": "w/s",
             "vs_baseline": 1.0, "regressions": [],
             "metrics": {"scale.efficiency_min": 0.8}}
        b = dict(a, ts=2.0, record="BENCH_r07.json",
                 metrics={"scale.efficiency_min": 0.4},
                 regressions=["mesh scaling efficiency (min E_n): ..."])
        hist.write_text(json.dumps(a) + "\n" + json.dumps(b) + "\n")
        recs = dump_metrics.load_records(str(hist))
        assert all(dump_metrics.is_history_record(r) for r in recs)
        table = dump_metrics.format_history_records(recs)
        assert "BENCH_r06.json" in table and "BENCH_r07.json" in table
        assert "FLAG:" in table
        diff = dump_metrics.diff_history_records(recs[0], recs[1])
        assert "scale.efficiency_min" in diff
        assert "0.8" in diff and "0.4" in diff
        # a non-history record is NOT misdetected
        assert not dump_metrics.is_history_record(
            {"rank": 0, "monitors": {}})


# ---------------------------------------------------------------------- #
# check_obs_surface: the two new rules
# ---------------------------------------------------------------------- #
class TestObsSurfaceRules:
    def test_repo_collective_coverage_clean(self):
        from tools.check_obs_surface import collective_coverage_findings
        assert collective_coverage_findings() == []

    def test_dark_collective_op_caught(self):
        from tools.check_obs_surface import collective_coverage_findings
        dark = ("def new_collective(x, mesh=None):\n"
                "    return _shard_map(lambda v: v, mesh=mesh,\n"
                "                      in_specs=None, out_specs=None)(x)\n")
        finds = collective_coverage_findings(
            sources=(("multiverso_tpu/parallel/collectives.py", "all"),),
            source_text={"multiverso_tpu/parallel/collectives.py": dark})
        assert len(finds) == 1 and "new_collective" in finds[0]

    def test_host_helper_without_shard_map_is_exempt(self):
        from tools.check_obs_surface import collective_coverage_findings
        helper = "def shape_helper(x):\n    return x.shape\n"
        finds = collective_coverage_findings(
            sources=(("multiverso_tpu/parallel/ring.py", "shard_map"),),
            source_text={"multiverso_tpu/parallel/ring.py": helper})
        assert finds == []

    def test_repo_regression_keys_all_produced(self):
        from tools.check_obs_surface import (regression_key_findings,
                                             regression_paths)
        paths = regression_paths()
        # the tables parsed: the scale keys this PR added are present
        assert ("scale", "efficiency_min") in paths
        assert regression_key_findings() == []

    def test_disarmed_regression_key_caught(self):
        from tools.check_obs_surface import regression_key_findings
        finds = regression_key_findings(
            paths=[("scale", "renamed_away_key")],
            producer_text='extra["scale"] = {"efficiency_min": 1}')
        assert len(finds) == 1
        assert "renamed_away_key" in finds[0]
        # a produced path passes
        assert regression_key_findings(
            paths=[("scale", "efficiency_min")],
            producer_text='x = {"scale": {"efficiency_min": 1}}') == []


# ---------------------------------------------------------------------- #
# the scale harness itself: tier-1 smoke at 1->2 shards
# ---------------------------------------------------------------------- #
def test_bench_scale_smoke_two_points():
    """ISSUE 12 acceptance smoke: the harness records T_1/T_2 with E_2
    computed in-run, per-point skew/stall from the aggregator/profiler,
    quiesced collective cost, mesh-keyed compile attribution, and the
    SPMD hygiene gate asserted CLEAN — all through the real subprocess
    spawn path bench.py uses."""
    import bench
    r = bench.bench_scale_curve(seconds=0.8, shards="1,2")
    assert r["shards"] == [1, 2]
    c1, c2 = r["curve"]["1"], r["curve"]["2"]
    assert c1["rows_per_s"] > 0 and c2["rows_per_s"] > 0
    assert c1["skew"] == pytest.approx(1.0, abs=0.5)
    assert r["efficiency"]["1"] == 1.0
    assert 0 < r["efficiency"]["2"] == r["efficiency_min"]
    assert r["t1_rows_per_s"] == c1["rows_per_s"]
    # ISSUE 15: constant offered load at every point, the per-point
    # E_n scalars feeding run_bench, and the mesh-data-plane gates —
    # bit-parity vs the 1-shard oracle and zero steady recompiles —
    # asserted through the real subprocess path
    assert c1["workers"] == c2["workers"] == r["workers"]
    assert r["e2"] == r["efficiency"]["2"]
    assert r["fanout"] is True and r["spmd_stack"] is True
    assert r["parity_bit_for_bit"] is True
    assert r["steady_recompiles"] == 0
    # the stacked SPMD plane compiled under its own mesh label
    assert any(k.startswith("{'shards':")
               for k in r["compiles_by_mesh"])
    # the hygiene gate RAN and passed for both mesh shapes
    assert r["hygiene_clean"] is True and r["hygiene_checked"] >= 2
    # device-plane attribution came back mesh-keyed
    assert "{'mv': 2}" in r["compiles_by_mesh"]
    assert r["collectives"]["all_reduce"]["calls"] > 0
    assert c2["all_reduce_ms"] > 0
    # the h2d upload of the model delta crossed the chokepoint
    assert r["transfers"]["h2d"]["bytes"] > 0
