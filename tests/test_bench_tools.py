"""The bench workers themselves are load-bearing: the driver's official
run is the round's artifact of record, and a broken tool records an
error dict instead of a number. Smoke every multi-process bench path at
minimal scale (seconds, np=2) through the REAL spawn/collect machinery.
"""

import os
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import bench  # noqa: E402  (repo-root module, not a package)


@pytest.mark.parametrize("pattern", ["strided", "local", "paced"])
def test_async_ps_worker_patterns(pattern):
    r = bench._run_async_ps_world(2, "none", 1.0, pattern=pattern)
    assert r["rows_per_sec"] > 0
    assert r["get_p99_ms"] >= r["get_p50_ms"] > 0
    if pattern in ("local", "paced"):
        # pooled-percentile path engaged (raw samples were reported)
        assert r["p99_over_p50"] > 0 and r["n_lat_samples"] > 0
    if pattern == "paced":
        # offered load is 150 add+get pairs/s plane-wide, 1024 rows each
        # way: 150 * 2 * 1024 rows/s
        assert r["rows_per_sec"] == pytest.approx(150 * 2 * 1024,
                                                  rel=0.15)


def test_aggregate_worker_all_variants():
    r = bench.bench_aggregate_path(world=2, mb=1.0)
    for k in ("process_sum_ms", "allgather_ms", "allgather_bf16_ms",
              "allgather_1bit_ms"):
        assert r[k] > 0, r
    for k in ("speedup", "bf16_vs_plain", "1bit_vs_plain", "1bit_vs_bf16"):
        assert np.isfinite(r[k]), r


def test_we_async_worker_tiny():
    """Tier-1 smoke of the full pipelined bench path (ISSUE 11): the
    np=2 measured run takes the producer-queue + training-cache path
    with the step profiler's stall/attribution gates asserted IN-RUN by
    the worker, and the parity stage (world=1, pipeline vs oracle)
    asserts bit-identical embedding digests — so this tiny run proves
    every in-run gate actually executes, not just that numbers exist."""
    r = bench.bench_we_async(world=2, n_tokens=30_000)
    assert r["words_per_sec_aggregate"] > 0
    assert len(r["words_per_sec_per_worker"]) == 2
    assert np.isfinite(r["loss_mean"])
    # the ISSUE-11 gates ran: bit parity vs the unpipelined oracle...
    assert r["parity"] == {"ok": True, "tokens": 30_000}
    # ...the platform-gated words/s floor (recorded; enforced on TPU)...
    assert r["perf_gate"]["target_words_per_s"] == 2_000_000
    assert r["perf_gate"]["enforced"] is False        # CPU bench box
    # ...and the training cache actually served on the measured run
    assert r["train_cache"]["hit_rate"] is not None
    # profiler gates (attribution >= 0.90, stall < 0.2, zero steady
    # recompiles) are asserted inside the workers; the profile block
    # surviving to the record means they passed. The block must EXIST:
    # the measured run always brackets steps (_prof.step per block), so
    # a missing block means the worker's zero-steps guard skipped every
    # in-run gate — the acceptance gates going silently dark, not a
    # benign config difference
    assert r.get("profile"), "profiler recorded no steps — in-run gates skipped"
    assert r["profile"]["stall_fraction"] < 0.2
    assert r["profile"]["attributed_fraction"] >= 0.90


def test_array_table_bench_smoke():
    """Tier-1 smoke of the full bench_array_table path at toy scale: a
    wire-codec regression (encode kernel, get cache, topk plane) surfaces
    here instead of only in a full driver bench run. Asserts the
    dashboard reports all four benched tables' counters."""
    import multiverso_tpu as mv
    from multiverso_tpu.utils.dashboard import Dashboard

    mv.init()
    r = bench.bench_array_table(size=10_000, iters=2)
    assert r["add_p50_ms"] > 0 and r["get_p50_ms"] > 0
    for mode in ("bf16", "1bit", "topk"):
        assert r["wire_filtered"][mode]["add_p50_ms"] > 0, mode
        assert r["wire_filtered"][mode]["get_p50_ms"] > 0, mode
    # the repeat-get loop must actually hit the version cache
    assert r["get_cache_hits"] >= 2
    snap = Dashboard.snapshot()
    for name in ("bench_array", "bench_array_bf16", "bench_array_1bit",
                 "bench_array_topk"):
        for op in ("add", "get"):
            key = f"table[{name}].{op}"
            assert key in snap and snap[key].count > 0, key


def test_dump_metrics_tool(tmp_path):
    """tools/dump_metrics smoke: show/diff real exporter records and
    wrap a JSONL trace for Perfetto — the bench-comparison workflow the
    telemetry plane exists for."""
    import json
    import time

    from multiverso_tpu.telemetry.exporter import MetricsExporter
    from multiverso_tpu.utils.dashboard import Dashboard, monitor
    from tools.dump_metrics import (diff_records, format_record,
                                    load_records, main, pick_record,
                                    to_perfetto)

    def payload():
        return {"rank": 0,
                "monitors": {n: s.hist_dict()
                             for n, s in Dashboard.snapshot().items()},
                "notes": {"n": "x = 1"},
                "shards": {"t": {"kind": "row", "adds": 2,
                                 "queue_depth": 0}}}

    with monitor("tool.op"):
        time.sleep(0.001)
    exp = MetricsExporter(0, str(tmp_path), 0.0, payload)
    exp.export_once()
    with monitor("tool.op"):
        pass
    exp.export_once()
    path = str(tmp_path / "metrics-rank0.jsonl")
    recs = load_records(path)
    assert len(recs) == 2
    text = format_record(pick_record(recs))
    assert "tool.op" in text and "p50" in text and "shard[t]" in text
    dtext = diff_records(recs[0], recs[1])
    assert "tool.op" in dtext and "p50 b/a" in dtext
    # trace wrap: JSONL events -> Perfetto envelope
    tpath = str(tmp_path / "trace.jsonl")
    with open(tpath, "w") as f:
        f.write(json.dumps({"name": "s", "ph": "X", "ts": 1, "dur": 2,
                            "pid": 0, "tid": 1, "args": {}}) + "\n")
    out = str(tmp_path / "trace.json")
    assert to_perfetto(tpath, out) == 1
    with open(out) as f:
        env = json.load(f)
    assert env["traceEvents"][0]["name"] == "s"
    # CLI entry points return 0
    assert main(["show", path]) == 0
    assert main(["diff", path, path]) == 0


def test_bench_truncation_recording(tmp_path):
    """The SIGTERM salvage exits bench.TRUNCATED_EXIT (documented,
    nonzero, distinct from a hard failure) and tools/run_bench records
    the distinction — a timeout-truncated run can never masquerade as a
    complete one."""
    import json

    from tools.run_bench import last_json_line, record

    assert bench.TRUNCATED_EXIT not in (0, 1)
    headline = {"metric": "m", "value": 1.0, "vs_baseline": 1.0,
                "extra": {"truncated": "bench interrupted by signal 15"}}
    out = "log noise\n" + json.dumps(headline) + "\n"
    rec = record(bench.TRUNCATED_EXIT, out)
    assert rec["truncated"] and not rec["complete"]
    assert rec["headline"]["value"] == 1.0
    complete = {"metric": "m", "value": 2.0, "vs_baseline": 1.0,
                "extra": {}}
    rec2 = record(0, json.dumps(complete))
    assert not rec2["truncated"] and rec2["complete"]
    # belt: the headline's own salvage marker flags truncation even if
    # the exit status was lost by a wrapper — and the record can never
    # be simultaneously complete and truncated
    rec3 = record(0, out)
    assert rec3["truncated"] and not rec3["complete"]
    assert last_json_line("no json here") is None


def test_flightrec_dumps_recorded(tmp_path, monkeypatch):
    """PR-4 CI satellite: the bench SIGTERM salvage dumps the flight
    recorder, and tools/run_bench records which dump files a run left —
    a truncated run is diagnosable from the recorded artifact alone."""
    import json

    from multiverso_tpu.telemetry import flightrec
    from tools.run_bench import collect_flightrec_dumps, record

    # the salvage hook itself (separable from the live signal handler)
    monkeypatch.setenv("MV_FLIGHTREC_DIR", str(tmp_path))
    flightrec.record(flightrec.EV_STATE, note="pre-salvage traffic")
    path = bench._flightrec_salvage_dump(15)
    assert path is not None and os.path.exists(path)
    with open(path) as f:
        recs = [json.loads(x) for x in f]
    assert recs[0]["reason"].startswith("bench salvage: signal 15")
    assert any(r.get("ev") == "signal" for r in recs)
    # ...and the recording side: the dump listing lands in the artifact
    dumps = collect_flightrec_dumps(str(tmp_path))
    assert dumps == [os.path.basename(path)]
    rec = record(bench.TRUNCATED_EXIT, "{}", flightrec_dumps=dumps)
    assert rec["truncated"] and rec["flightrec_dumps"] == dumps
    # a clean run with no dump dir records an empty listing, not a crash
    assert collect_flightrec_dumps(str(tmp_path / "never-made")) == []
    assert record(0, "{}")["flightrec_dumps"] == []
    # review regression: the dump dir is reused across runs — a stale
    # dump from a PREVIOUS run must not be attributed to this one
    import time as _time
    assert collect_flightrec_dumps(str(tmp_path),
                                   since=_time.time() + 60) == []
    assert collect_flightrec_dumps(str(tmp_path), since=0.0) == dumps


def test_get_rows_bench_smoke():
    """Tier-1 smoke of tools/bench_get_rows.py (ISSUE 5 read-path bench)
    at toy scale through the REAL subprocess spawn/collect machinery:
    both parity gates are in-run assertions, so a pass here means the
    coalesced and chunk-streamed planes returned exact bytes."""
    import json
    import subprocess

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "bench_get_rows.py"),
         "30", "2000"],
        capture_output=True, text=True, timeout=240, env=env, cwd=_REPO)
    assert out.returncode == 0, out.stderr[-800:]
    line = [x for x in out.stdout.splitlines()
            if x.startswith("RESULT ")][-1]
    r = json.loads(line[len("RESULT "):])
    assert r["parity_bit_for_bit"] and r["chunk_parity_bit_for_bit"]
    assert r["small_get_on_p50_ms"] > 0 and r["small_get_off_p50_ms"] > 0
    assert r["big_get_chunked_ms"] > 0
    # the fan-in phase must have actually deduped something
    assert r["fanout_frames"] < r["fanout_gets"]


def test_run_bench_regression_flagging():
    """ISSUE 5 CI satellite: run_bench FLAGS (never fails) a >2x
    latency regression of the get/small-add planes vs the previous
    recorded BENCH file, and skips keys either side is missing."""
    from tools.run_bench import flag_regressions

    prev = {"extra": {
        "get_rows_plane": {"small_get_on_p50_ms": 0.5,
                           "small_get_off_p50_ms": 0.6,
                           "big_get_chunked_ms": 20.0},
        "small_add_send_window": {"window_on_p50_ms": 0.04},
    }}
    same = flag_regressions(prev, prev)
    assert same == []
    worse = {"extra": {
        "get_rows_plane": {"small_get_on_p50_ms": 1.2,   # 2.4x: flagged
                           "small_get_off_p50_ms": 0.9,  # 1.5x: fine
                           "big_get_chunked_ms": 90.0},  # 4.5x: flagged
        "small_add_send_window": {"window_on_p50_ms": 0.05},
    }}
    flags = flag_regressions(prev, worse)
    assert len(flags) == 2
    assert any("coalesced small-get p50" in f for f in flags)
    assert any("chunked big-get" in f for f in flags)
    # missing keys (older record / errored sub-bench) are skipped
    assert flag_regressions(None, worse) == []
    assert flag_regressions({"extra": {}}, worse) == []
    assert flag_regressions(
        prev, {"extra": {"get_rows_plane": {"error": "boom"}}}) == []


def test_run_bench_flags_skew_growth():
    """ISSUE 6 satellite: when both records carry a cluster snapshot
    (the stats aggregator ran), >2x run-over-run shard-skew growth is
    FLAGGED (never fails the run); missing/partial cluster data is
    skipped like any other absent key. Worker-level cluster blocks
    (e.g. small_add_send_window.cluster) are scanned too."""
    from tools.run_bench import flag_regressions

    def rec(skew, nested=False):
        cluster = {"tables": {"we": {"adds": 10, "skew": skew}}}
        extra = ({"small_add_send_window": {"cluster": cluster}}
                 if nested else {"cluster": cluster})
        return {"extra": extra}

    assert flag_regressions(rec(1.1), rec(1.9)) == []       # 1.7x: fine
    flags = flag_regressions(rec(1.1), rec(2.5))            # 2.3x
    assert len(flags) == 1 and "table[we] shard skew" in flags[0]
    # nested worker-level cluster blocks count as well
    flags = flag_regressions(rec(1.1, nested=True), rec(2.5, nested=True))
    assert len(flags) == 1 and "shard skew" in flags[0]
    # one side missing the cluster record: skipped, never flagged
    assert flag_regressions({"extra": {}}, rec(9.0)) == []
    assert flag_regressions(rec(1.0), {"extra": {}}) == []


def test_run_bench_flags_serving_regressions():
    """ISSUE 8 satellite: run_bench FLAGS (never fails) a >2x
    run-over-run growth of the serving plane's inference p99 AND a >2x
    served-QPS DROP (the higher-is-better mirror); missing serving data
    (errored bench, older record) is skipped."""
    from tools.run_bench import flag_regressions

    def rec(p99, qps):
        return {"extra": {"serving": {"infer_p99_ms": p99,
                                      "served_qps": qps}}}

    assert flag_regressions(rec(5.0, 1000), rec(9.0, 900)) == []
    # p99 grew 2.4x: flagged
    flags = flag_regressions(rec(5.0, 1000), rec(12.0, 1000))
    assert len(flags) == 1 and "serving inference p99" in flags[0]
    # served QPS dropped 2.5x: flagged (higher-is-better direction)
    flags = flag_regressions(rec(5.0, 1000), rec(5.0, 400))
    assert len(flags) == 1 and "serving served QPS" in flags[0]
    assert "drop" in flags[0]
    # QPS GROWTH is never flagged, nor is missing data
    assert flag_regressions(rec(5.0, 1000), rec(5.0, 9000)) == []
    assert flag_regressions({"extra": {}}, rec(12.0, 100)) == []
    assert flag_regressions(
        rec(5.0, 1000), {"extra": {"serving": {"error": "boom"}}}) == []


def test_run_bench_flags_we_words_drop():
    """ISSUE 11 satellite: a >2x run-over-run DROP of the WE async
    plane's words/s (extra.we.words_per_s, higher-is-better direction)
    is FLAGGED — never fails the run; growth and missing data are
    skipped. This is the tracked scale-trajectory metric for ROADMAP
    item 2."""
    from tools.run_bench import flag_regressions

    def rec(wps):
        return {"extra": {"we": {"words_per_s": wps, "parity_ok": 1}}}

    assert flag_regressions(rec(2.0e6), rec(1.5e6)) == []
    flags = flag_regressions(rec(2.0e6), rec(0.8e6))
    assert len(flags) == 1 and "WE async words/s" in flags[0]
    assert "drop" in flags[0]
    # growth is never flagged, nor is missing data on either side
    assert flag_regressions(rec(0.5e6), rec(3.0e6)) == []
    assert flag_regressions({"extra": {}}, rec(1.0e6)) == []
    assert flag_regressions(rec(1.0e6), {"extra": {}}) == []


def test_run_bench_flags_chaos_recovery_growth():
    """ISSUE 7 satellite: >2x run-over-run growth of the chaos bench's
    recovery-time-to-full-throughput (extra.chaos.recovery_s) is
    FLAGGED — never fails the run — mirroring the skew flag; missing
    chaos data (bench errored, older record) is skipped."""
    from tools.run_bench import flag_regressions

    def rec(recovery_s):
        return {"extra": {"chaos": {"recovery_s": recovery_s,
                                    "ops_lost": 0}}}

    assert flag_regressions(rec(4.0), rec(6.0)) == []        # 1.5x: fine
    flags = flag_regressions(rec(4.0), rec(9.0))             # 2.25x
    assert len(flags) == 1
    assert "chaos failover recovery time" in flags[0]
    # missing on either side (errored chaos bench, older record): skip
    assert flag_regressions({"extra": {}}, rec(9.0)) == []
    assert flag_regressions(
        rec(4.0), {"extra": {"chaos": {"error": "boom"}}}) == []
