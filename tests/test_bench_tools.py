"""The bench workers themselves are load-bearing: the driver's official
run is the round's artifact of record, and a broken tool records an
error dict instead of a number. Smoke every multi-process bench path at
minimal scale (seconds, np=2) through the REAL spawn/collect machinery.
"""

import os
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import bench  # noqa: E402  (repo-root module, not a package)


@pytest.mark.parametrize("pattern", ["strided", "local", "paced"])
def test_async_ps_worker_patterns(pattern):
    r = bench._run_async_ps_world(2, "none", 1.0, pattern=pattern)
    assert r["rows_per_sec"] > 0
    assert r["get_p99_ms"] >= r["get_p50_ms"] > 0
    if pattern in ("local", "paced"):
        # pooled-percentile path engaged (raw samples were reported)
        assert r["p99_over_p50"] > 0 and r["n_lat_samples"] > 0
    if pattern == "paced":
        # offered load is 150 add+get pairs/s plane-wide, 1024 rows each
        # way: 150 * 2 * 1024 rows/s
        assert r["rows_per_sec"] == pytest.approx(150 * 2 * 1024,
                                                  rel=0.15)


def test_aggregate_worker_all_variants():
    r = bench.bench_aggregate_path(world=2, mb=1.0)
    for k in ("process_sum_ms", "allgather_ms", "allgather_bf16_ms",
              "allgather_1bit_ms"):
        assert r[k] > 0, r
    for k in ("speedup", "bf16_vs_plain", "1bit_vs_plain", "1bit_vs_bf16"):
        assert np.isfinite(r[k]), r


def test_we_async_worker_tiny():
    r = bench.bench_we_async(world=2, n_tokens=30_000)
    assert r["words_per_sec_aggregate"] > 0
    assert len(r["words_per_sec_per_worker"]) == 2
    assert np.isfinite(r["loss_mean"])
