"""Data-parallel MLP with delta-sync shared parameters.

The TPU-era equivalent of the reference's Theano examples
(ref: binding/python/examples/theano/logistic_regression.py and cnn.py — a
local training loop wrapped with ``mv_shared``/``mv_sync`` so N workers train
ASGD with deltas merged through an ArrayTable). Here the local loop is plain
JAX+optax-style SGD and the wrap is ``multiverso_tpu.sharedvar.mv_shared``:
run one process per worker (multi-controller) and the sync() calls merge
deltas through the shared table; single-process it degenerates to local SGD.

Run: python examples/mlp_data_parallel.py
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")  # repo-root execution

import multiverso_tpu as mv
from multiverso_tpu.models.logreg import synthetic_dataset
from multiverso_tpu.sharedvar import mv_shared


def init_mlp(key, sizes):
    params = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        k1, key = jax.random.split(key)
        params.append({
            "w": jax.random.normal(k1, (a, b)) * np.sqrt(2.0 / a),
            "b": jnp.zeros((b,)),
        })
    return params


def apply_mlp(params, x):
    h = x
    for layer in params[:-1]:
        h = jax.nn.relu(h @ layer["w"] + layer["b"])
    out = params[-1]
    return h @ out["w"] + out["b"]


def main():
    mv.init()
    x, y = synthetic_dataset(4096, 32, 5, seed=mv.worker_id())
    xt, yt = synthetic_dataset(1024, 32, 5, seed=100)
    params = init_mlp(jax.random.key(0), [32, 64, 5])
    shared = mv_shared(params, name="mlp_params")
    params = shared.get()

    @jax.jit
    def step(params, xb, yb):
        def loss_fn(p):
            logits = apply_mlp(p, xb)
            onehot = jax.nn.one_hot(yb, 5)
            return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits),
                                     axis=-1))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        return jax.tree.map(lambda p, g: p - 0.1 * g, params, grads), loss

    sync_every, batch = 8, 256
    for epoch in range(6):
        for i in range(0, len(y), batch):
            params, loss = step(params,
                                jnp.asarray(x[i:i + batch]),
                                jnp.asarray(y[i:i + batch]))
            if (i // batch) % sync_every == sync_every - 1:
                params = shared.sync(params)   # ASGD delta merge
        params = shared.sync(params)
        acc = float(jnp.mean((jnp.argmax(apply_mlp(params, jnp.asarray(xt)),
                                         -1) == jnp.asarray(yt))))
        print(f"epoch {epoch}: loss {float(loss):.4f}  test acc {acc:.4f}")
    mv.shutdown()


if __name__ == "__main__":
    main()
