-- LuaJIT FFI binding for multiverso_tpu's C ABI (libmultiverso.so).
--
-- Mirrors the load pattern of the reference Lua binding
-- (ref: binding/lua/init.lua:7-67 — ffi.cdef over c_api.h then
-- ffi.load('multiverso')). The C ABI here bridges into the JAX/TPU runtime
-- (see multiverso_tpu/native/mv_capi.cpp); build it with
--   make -C multiverso_tpu/native capi
-- Runtime coverage, in order of strength:
--   * tests/test_lua_binding.py executes THIS FILE under a real Lua
--     interpreter (lupa, with an ffi->ctypes bridge) and ports the
--     reference test battery (binding/lua/test.lua) — it activates
--     automatically wherever lupa is installed (the zero-egress build
--     image cannot install it, so it skips there);
--   * the C driver (multiverso_tpu/native/mv_capi_test.c, `make
--     capi_test`) calls every symbol below with assertions;
--   * tests/test_lua_cdef.py pins this cdef to the .so exports AND to
--     the mv_capi.cpp signatures type-for-type, both directions.

local ffi = require('ffi')

ffi.cdef[[
typedef void* TableHandler;
void MV_Init(int* argc, char** argv);
void MV_ShutDown();
void MV_Barrier();
int  MV_NumWorkers();
int  MV_WorkerId();
int  MV_ServerId();
void MV_NewArrayTable(int size, TableHandler* out);
void MV_GetArrayTable(TableHandler handler, float* data, int size);
void MV_AddArrayTable(TableHandler handler, float* data, int size);
void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size);
void MV_NewAsyncArrayTable(int size, TableHandler* out);
void MV_NewAsyncMatrixTable(int num_row, int num_col, TableHandler* out);
void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out);
void MV_GetMatrixTableAll(TableHandler handler, float* data, int size);
void MV_AddMatrixTableAll(TableHandler handler, float* data, int size);
void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data, int size);
void MV_GetMatrixTableByRows(TableHandler handler, float* data, int size, int row_ids[], int row_ids_n);
void MV_AddMatrixTableByRows(TableHandler handler, float* data, int size, int row_ids[], int row_ids_n);
void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data, int size, int row_ids[], int row_ids_n);
]]

local lib = ffi.load('multiverso')

local M = {}

function M.init() lib.MV_Init(nil, nil) end
function M.shutdown() lib.MV_ShutDown() end
function M.barrier() lib.MV_Barrier() end
function M.num_workers() return lib.MV_NumWorkers() end
function M.worker_id() return lib.MV_WorkerId() end

local ArrayTable = {}
ArrayTable.__index = ArrayTable

function M.new_array_table(size)
  local h = ffi.new('TableHandler[1]')
  lib.MV_NewArrayTable(size, h)
  return setmetatable({ handler = h[0], size = size }, ArrayTable)
end

function ArrayTable:get(buf)
  buf = buf or ffi.new('float[?]', self.size)
  lib.MV_GetArrayTable(self.handler, buf, self.size)
  return buf
end

function ArrayTable:add(buf)
  lib.MV_AddArrayTable(self.handler, buf, self.size)
end

function ArrayTable:add_async(buf)
  lib.MV_AddAsyncArrayTable(self.handler, buf, self.size)
end

-- Uncoordinated (async-PS plane) array table — beyond the reference C
-- API; the row/element accessors are the same, only the constructor
-- differs (every process owns a shard served by its PSService).
function M.new_async_array_table(size)
  local h = ffi.new('TableHandler[1]')
  lib.MV_NewAsyncArrayTable(size, h)
  return setmetatable({ handler = h[0], size = size }, ArrayTable)
end

local MatrixTable = {}
MatrixTable.__index = MatrixTable

function M.new_matrix_table(num_row, num_col)
  local h = ffi.new('TableHandler[1]')
  lib.MV_NewMatrixTable(num_row, num_col, h)
  return setmetatable({ handler = h[0], num_row = num_row,
                        num_col = num_col, size = num_row * num_col },
                      MatrixTable)
end

function MatrixTable:get(buf)
  buf = buf or ffi.new('float[?]', self.size)
  lib.MV_GetMatrixTableAll(self.handler, buf, self.size)
  return buf
end

function MatrixTable:add(buf)
  lib.MV_AddMatrixTableAll(self.handler, buf, self.size)
end

function MatrixTable:add_async(buf)
  lib.MV_AddAsyncMatrixTableAll(self.handler, buf, self.size)
end

-- Async-plane matrix table (see new_async_array_table); same accessors.
function M.new_async_matrix_table(num_row, num_col)
  local h = ffi.new('TableHandler[1]')
  lib.MV_NewAsyncMatrixTable(num_row, num_col, h)
  return setmetatable({ handler = h[0], num_row = num_row,
                        num_col = num_col, size = num_row * num_col },
                      MatrixTable)
end

-- row batch ops: `rows` is a 0-based int array (ref MatrixTableHandler)
function MatrixTable:get_rows(rows, n, buf)
  buf = buf or ffi.new('float[?]', n * self.num_col)
  lib.MV_GetMatrixTableByRows(self.handler, buf, n * self.num_col, rows, n)
  return buf
end

function MatrixTable:add_rows(buf, rows, n)
  lib.MV_AddMatrixTableByRows(self.handler, buf, n * self.num_col, rows, n)
end

function MatrixTable:add_rows_async(buf, rows, n)
  lib.MV_AddAsyncMatrixTableByRows(self.handler, buf, n * self.num_col,
                                   rows, n)
end

return M
