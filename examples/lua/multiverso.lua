-- LuaJIT FFI binding for multiverso_tpu's C ABI (libmultiverso.so).
--
-- Mirrors the load pattern of the reference Lua binding
-- (ref: binding/lua/init.lua:7-67 — ffi.cdef over c_api.h then
-- ffi.load('multiverso')). The C ABI here bridges into the JAX/TPU runtime
-- (see multiverso_tpu/native/mv_capi.cpp); build it with
--   make -C multiverso_tpu/native capi
-- This file ships as an untested example: the build image has no LuaJIT.

local ffi = require('ffi')

ffi.cdef[[
typedef void* TableHandler;
void MV_Init(int* argc, char** argv);
void MV_ShutDown();
void MV_Barrier();
int  MV_NumWorkers();
int  MV_WorkerId();
int  MV_ServerId();
void MV_NewArrayTable(int size, TableHandler* out);
void MV_GetArrayTable(TableHandler handler, float* data, int size);
void MV_AddArrayTable(TableHandler handler, float* data, int size);
void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size);
void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out);
void MV_GetMatrixTableAll(TableHandler handler, float* data, int size);
void MV_AddMatrixTableAll(TableHandler handler, float* data, int size);
void MV_GetMatrixTableByRows(TableHandler handler, float* data, int size,
                             int row_ids[], int row_ids_n);
void MV_AddMatrixTableByRows(TableHandler handler, float* data, int size,
                             int row_ids[], int row_ids_n);
]]

local lib = ffi.load('multiverso')

local M = {}

function M.init() lib.MV_Init(nil, nil) end
function M.shutdown() lib.MV_ShutDown() end
function M.barrier() lib.MV_Barrier() end
function M.num_workers() return lib.MV_NumWorkers() end
function M.worker_id() return lib.MV_WorkerId() end

local ArrayTable = {}
ArrayTable.__index = ArrayTable

function M.new_array_table(size)
  local h = ffi.new('TableHandler[1]')
  lib.MV_NewArrayTable(size, h)
  return setmetatable({ handler = h[0], size = size }, ArrayTable)
end

function ArrayTable:get(buf)
  buf = buf or ffi.new('float[?]', self.size)
  lib.MV_GetArrayTable(self.handler, buf, self.size)
  return buf
end

function ArrayTable:add(buf)
  lib.MV_AddArrayTable(self.handler, buf, self.size)
end

return M
