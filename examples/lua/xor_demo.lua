-- XOR MLP demo over the multiverso C ABI — the reference's Lua demo
-- (ref binding/lua/demos/xor/xor-multiverso.lua: a data-parallel Torch
-- MLP whose parameters live in an ArrayTable) rebuilt in PLAIN Lua so it
-- needs no Torch: a 2-4-1 sigmoid MLP, parameters synced through the
-- table with the delta-push convention (worker computes new weights
-- locally, pushes new-old, pulls the merged state — the same pattern as
-- the Python sharedvar binding, ref theano_ext/sharedvar.py:38-50).
--
-- Runs under LuaJIT (ffi) or under lupa via the tests' ffi bridge:
--   tests/test_lua_binding.py::test_lua_xor_demo_converges
-- Returns the final mean-squared error (must fall well under 0.05).

local ffi = require('ffi')
local mv = require('multiverso')

local function new_buf(n)
  return ffi.new('float[?]', n)
end

-- 2-4-1 MLP: W1[4][2], b1[4], W2[4], b2  => 17 params
local NP = 17
local X = { {0, 0}, {0, 1}, {1, 0}, {1, 1} }
local Y = { 0, 1, 1, 0 }

local function sigmoid(z)
  return 1.0 / (1.0 + math.exp(-z))
end

-- forward + backward on the full XOR batch; returns (loss, grad[17])
local function grad_step(p)
  local g = {}
  for i = 1, NP do g[i] = 0.0 end
  local loss = 0.0
  for s = 1, 4 do
    local x1, x2, y = X[s][1], X[s][2], Y[s]
    local h, zh = {}, {}
    for j = 0, 3 do
      zh[j] = p[j * 2 + 1] * x1 + p[j * 2 + 2] * x2 + p[8 + j + 1]
      h[j] = sigmoid(zh[j])
    end
    local zo = p[17]
    for j = 0, 3 do zo = zo + p[12 + j + 1] * h[j] end
    local o = sigmoid(zo)
    local err = o - y
    loss = loss + 0.5 * err * err
    local do_ = err * o * (1 - o)
    for j = 0, 3 do
      g[12 + j + 1] = g[12 + j + 1] + do_ * h[j]
      local dh = do_ * p[12 + j + 1] * h[j] * (1 - h[j])
      g[j * 2 + 1] = g[j * 2 + 1] + dh * x1
      g[j * 2 + 2] = g[j * 2 + 2] + dh * x2
      g[8 + j + 1] = g[8 + j + 1] + dh
    end
    g[17] = g[17] + do_
  end
  return loss / 4, g
end

local function run(iters, lr)
  iters = iters or 3000
  lr = lr or 2.0
  mv.init()
  local t = mv.new_array_table(NP)

  -- master-init convention (ref tables.py:50-57): worker 0 seeds the
  -- table with the initial weights, everyone else contributes zeros.
  -- Fixed asymmetric values, NOT math.random: XOR has local minima and
  -- Lua RNG streams differ across interpreters — the demo must converge
  -- deterministically everywhere it runs.
  local seed_w = { 0.5, -0.4, -0.6, 0.3, 0.7, 0.2, -0.3, -0.8,
                   0.1, -0.2, 0.3, -0.1, 0.6, -0.7, 0.5, -0.4, 0.05 }
  local init = new_buf(NP)
  if mv.worker_id() == 0 then
    for i = 0, NP - 1 do init[i] = seed_w[i + 1] end
  end
  t:add(init)
  mv.barrier()

  local cur = t:get()
  local p = {}
  local last_loss = 1e9
  for it = 1, iters do
    for i = 1, NP do p[i] = cur[i - 1] end
    local loss, g = grad_step(p)
    last_loss = loss
    -- local step, then push (new - old) = -lr*grad as the delta
    local delta = new_buf(NP)
    for i = 1, NP do delta[i - 1] = -lr * g[i] end
    t:add(delta)
    cur = t:get(cur)
  end
  mv.shutdown()
  return last_loss
end

return { run = run }
