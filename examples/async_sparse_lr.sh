#!/bin/sh
# Uncoordinated sparse FTRL LR, 4 OS processes, straight from the app CLI —
# the reference's flagship sparse workload (hash-keyed FTRL tables,
# ref Applications/LogisticRegression/src/model/ps_model.cpp:24-41) on the
# async plane. Each rank trains its own data shard, then tests the
# jointly-trained model (four accuracy lines — one per rank's final view).
set -e
cd "$(dirname "$0")/.."
RDV=$(mktemp -d)
WORK=$(mktemp -d)
PIDS=""
# `|| true`: set -e applies INSIDE the trap (dash); on a clean run kill
# fails (pids gone) and would abort the trap (rc 1, dirs leaked)
trap 'kill $PIDS 2>/dev/null || true; rm -rf "$RDV" "$WORK"' EXIT

python - "$WORK" <<'PY'
import sys
from multiverso_tpu.models import logreg
x, y = logreg.synthetic_dataset(2048, 12, 2, seed=42)
for r in range(4):
    with open(f"{sys.argv[1]}/train_{r}.svm", "w") as f:
        for xi, yi in zip(x[r::4], y[r::4]):
            feats = " ".join(f"{j}:{v:.5f}" for j, v in enumerate(xi))
            f.write(f"{yi} {feats}\n")
PY

for R in 0 1 2 3; do
  cat > "$WORK/lr_$R.config" <<CFG
input_size=12
output_size=2
sparse=true
async_ps=true
updater_type=ftrl
learning_rate=0.1
train_epoch=3
minibatch_size=64
train_file=$WORK/train_$R.svm
test_file=$WORK/train_0.svm
CFG
  # one host, four processes: each on the CPU backend (one chip can't be
  # shared); -ps_* runtime flags launch the uncoordinated plane
  JAX_PLATFORMS=cpu python -m multiverso_tpu.apps.logistic_regression \
      "$WORK/lr_$R.config" -ps_rank=$R -ps_world=4 -ps_rendezvous="$RDV" &
  PIDS="$PIDS $!"
done
for P in $PIDS; do wait "$P"; done
echo "async sparse FTRL LR demo: 4 workers done"
