"""XOR MLP, data-parallel through a MatrixTable — the reference's Lua demo.

TPU-era re-make of the reference's ``binding/lua/demos/xor`` workload: a tiny
2-4-1 MLP learns XOR while every worker pushes gradient deltas to (and pulls
parameters from) shared tables, exactly the handler surface the Lua/Torch FFI
binding exposes (ref binding/lua/ArrayTableHandler.lua /
MatrixTableHandler.lua; demo loop in demos/xor/xor_multiverso.lua). Here the
handler layer is ``multiverso_tpu.handlers`` and the math is JAX; run one
process per worker for real data parallelism (multi-controller), or
single-process for the smoke-test below.

Run: python examples/xor_mlp.py
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")  # repo-root execution

import multiverso_tpu as mv
from multiverso_tpu.handlers import ArrayTableHandler

X = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
Y = np.array([[0], [1], [1], [0]], np.float32)

SIZES = [(2, 4), (4,), (4, 1), (1,)]  # w1, b1, w2, b2
TOTAL = sum(int(np.prod(s)) for s in SIZES)


def unflatten(flat):
    out, i = [], 0
    for s in SIZES:
        n = int(np.prod(s))
        out.append(flat[i: i + n].reshape(s))
        i += n
    return out


def forward(flat, x):
    w1, b1, w2, b2 = unflatten(flat)
    h = jnp.tanh(x @ w1 + b1)
    return jax.nn.sigmoid(h @ w2 + b2)


def loss_fn(flat, x, y):
    p = forward(flat, x)
    return -jnp.mean(y * jnp.log(p + 1e-7) + (1 - y) * jnp.log(1 - p + 1e-7))


def main():
    mv.init()
    rng = np.random.default_rng(mv.worker_id())
    init = rng.normal(0, 0.5, TOTAL).astype(np.float32)
    # master-init convention (ref tables.py:50-57): worker 0 pushes the
    # initial weights, the rest push zeros
    params = ArrayTableHandler(TOTAL, init_value=init, name="xor_params")

    lr, sync_frequency, rounds = 0.5, 50, 20
    x, y = jnp.asarray(X), jnp.asarray(Y)

    @jax.jit
    def local_rounds(flat):
        """sync_frequency local GD steps between table syncs (the LR app's
        bounded-staleness pattern, apps/logistic_regression.py)."""
        def body(_, f):
            return f - lr * jax.grad(loss_fn)(f, x, y)
        return jax.lax.fori_loop(0, sync_frequency, body, flat)

    for r in range(rounds):
        flat = jnp.asarray(params.get())
        new = local_rounds(flat)
        # push the *delta*; the server-side default updater adds it
        params.add(np.asarray(new - flat))
        if r % 5 == 0:
            print(f"round {r:3d} loss {float(loss_fn(new, x, y)):.4f}")
    flat = jnp.asarray(params.get())
    pred = np.asarray(forward(flat, x)).round().astype(int).ravel()
    print("prediction:", pred.tolist(), "target:", Y.ravel().astype(int).tolist())
    ok = (pred == Y.ravel()).all()
    print("XOR", "SOLVED" if ok else "NOT SOLVED")
    mv.shutdown()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
