"""Small CNN trained BSP data-parallel via worker_step.

The TPU-era equivalent of the reference's Theano CNN example
(ref: binding/python/examples/theano/cnn.py — MNIST convnet with params
synced through Multiverso). Here 4 logical workers on a (worker, shard) mesh
each grab a batch shard; gradients meet in one in-graph pmean and the table's
SGD updater applies the merged step — the whole thing is a single compiled
SPMD program per step.

Run: python examples/cnn_worker_map.py [mnist_dir]
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

import multiverso_tpu as mv
from multiverso_tpu.parallel.worker_map import make_worker_mesh, worker_step


def init_cnn(key, num_classes=10):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "conv1": jax.random.normal(k1, (3, 3, 1, 16)) * 0.2,
        "conv2": jax.random.normal(k2, (3, 3, 16, 32)) * 0.1,
        "dense": jax.random.normal(k3, (32, num_classes)) * 0.1,
        "bias": jnp.zeros((num_classes,)),
    }


def apply_cnn(params, x):
    def conv(h, w, stride):
        return jax.lax.conv_general_dilated(
            h, w, (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    h = jax.nn.relu(conv(x, params["conv1"], 2))
    h = jax.nn.relu(conv(h, params["conv2"], 2))
    h = jnp.mean(h, axis=(1, 2))
    return h @ params["dense"] + params["bias"]


def main():
    n_dev = len(jax.devices())
    n_workers = max(d for d in (4, 2, 1) if n_dev % d == 0)
    mesh = make_worker_mesh(n_workers)
    mv.init(mesh=mesh)
    print(f"{n_workers} logical workers over {n_dev} devices")

    from multiverso_tpu.io import mnist
    data_dir = sys.argv[1] if len(sys.argv) > 1 else ""
    if data_dir and mnist.available(data_dir):
        x, y = mnist.load(data_dir, flatten=False)
        x, y = x[:8192], y[:8192]
        size, classes = 28, 10
    else:
        print("no MNIST dir; synthetic data")
        from multiverso_tpu.models.resnet import synthetic_cifar
        x, y = synthetic_cifar(4096, size=16, classes=10, seed=0)
        x = x.mean(axis=-1, keepdims=True)  # grayscale
        size, classes = 16, 10

    params = init_cnn(jax.random.key(0), classes)
    flat = np.concatenate([np.asarray(l).reshape(-1)
                           for l in jax.tree.leaves(params)])
    shapes = [np.shape(l) for l in jax.tree.leaves(params)]
    treedef = jax.tree.structure(params)
    table = mv.ArrayTable(flat.size, updater="sgd", init=flat, name="cnn")

    def unflatten(v):
        leaves, off = [], 0
        for s in shapes:
            n = int(np.prod(s))
            leaves.append(v[off:off + n].reshape(s))
            off += n
        return jax.tree.unflatten(treedef, leaves)

    def grad_fn(params_flat, batch):
        p = unflatten(params_flat[: flat.size])
        def loss_fn(p):
            logits = apply_cnn(p, batch["x"])
            onehot = jax.nn.one_hot(batch["y"], classes)
            return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits),
                                     axis=-1))
        loss, g = jax.value_and_grad(loss_fn)(p)
        gflat = jnp.concatenate([l.reshape(-1) for l in jax.tree.leaves(g)])
        return loss, jnp.zeros_like(params_flat).at[: flat.size].set(gflat)

    step = jax.jit(worker_step(table, grad_fn, learning_rate=0.2))
    state = table.state
    batch_size = 256
    for epoch in range(4):
        for i in range(0, len(y) - batch_size + 1, batch_size):
            batch = {"x": jnp.asarray(x[i:i + batch_size]),
                     "y": jnp.asarray(y[i:i + batch_size])}
            state, loss = step(state, batch)
        print(f"epoch {epoch}: loss {float(loss):.4f}")
    table.adopt(state)

    p = unflatten(table.get())
    acc = float(jnp.mean((jnp.argmax(apply_cnn(p, jnp.asarray(x[:1024])), -1)
                          == jnp.asarray(y[:1024]))))
    print(f"train accuracy: {acc:.4f}")
    mv.shutdown()


if __name__ == "__main__":
    main()
