"""Long-context LM training with ring attention over a (dp, sp) mesh.

The capability demo the reference never had (it predates transformers —
SURVEY §5 "long-context: absent"): a decoder-only LM whose sequence axis is
context-parallel over the mesh, so per-chip attention memory is
O((S/n_chips)^2) and sequence length scales with chips. Batch rides the dp
axis; K/V blocks rotate over the sp axis via ``ppermute`` (ICI ring).

Run: python examples/long_context_lm.py   (8 virtual CPU devices stand in
for 8 chips; the same code runs unchanged on a TPU pod slice.)
"""

import sys

import numpy as np

sys.path.insert(0, ".")  # repo-root execution

import jax

if "--tpu" not in sys.argv:
    # default to the 8-virtual-device CPU mesh (checking the live backend
    # would *initialize* it, claiming the real chip just to ask its name)
    from multiverso_tpu.utils.platform import force_cpu_mesh
    force_cpu_mesh(8)

from jax.sharding import Mesh

import multiverso_tpu as mv
from multiverso_tpu.models import transformer as tf

SEQ, BATCH, STEPS = 256, 4, 40


def synthetic_text(n, seed=0):
    """A noisy periodic token stream — learnable but not trivial."""
    rng = np.random.default_rng(seed)
    base = np.tile(np.arange(16, dtype=np.int32), n // 16 + 1)[:n]
    noise = rng.integers(0, 16, n).astype(np.int32)
    keep = rng.random(n) < 0.9
    return np.where(keep, base, noise)


def main():
    devices = np.asarray(jax.devices())
    dp = 2 if devices.size % 2 == 0 and devices.size > 1 else 1
    mesh = Mesh(devices.reshape(dp, devices.size // dp), ("dp", "sp"))
    mv.init(mesh=mesh)

    cfg = tf.TransformerConfig(vocab_size=16, dim=64, num_heads=4,
                               num_layers=2, max_seq=SEQ, attn="ring",
                               seq_axis="sp", batch_axis="dp")
    params = tf.init_params(cfg, seed=0)

    stream = synthetic_text(BATCH * (SEQ + 1))
    chunks = stream[: BATCH * (SEQ + 1)].reshape(BATCH, SEQ + 1)
    tokens = tf.shard_batch(chunks[:, :-1], cfg, mesh)
    targets = tf.shard_batch(chunks[:, 1:], cfg, mesh)

    step = jax.jit(tf.make_train_step(cfg, learning_rate=0.3))
    for i in range(STEPS):
        params, loss = step(params, tokens, targets)
        if i % 10 == 0 or i == STEPS - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}")

    assert float(loss) < 1.0, "LM failed to learn the periodic stream"
    print(f"long-context LM ok: seq={SEQ} over {mesh.shape['sp']} "
          f"sequence shards x {mesh.shape['dp']} data shards")
    mv.shutdown()


if __name__ == "__main__":
    main()
