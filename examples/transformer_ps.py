"""Transformer LM trained through the parameter server: the reference's
core recipe (params in a table, delta-sync ASGD — ref theano_ext
sharedvar.py:38-50 / lasagne_ext param_manager.py) applied to the modern
model family.

The LM's whole parameter pytree lives in one sharded ArrayTable
(`SharedPytree`). Each "worker" (process, or this demo's simulated round)
trains locally with the flash-attention fused step and periodically
delta-syncs: Add(current - last) then Get. With multiple processes
(`mv.net_init`) this is data-parallel ASGD with no other code changes —
the same loop the reference's MNIST/Lasagne examples run.

Run: python examples/transformer_ps.py
"""

import sys

sys.path.insert(0, ".")  # repo-root execution

import jax
import jax.numpy as jnp
import numpy as np

import multiverso_tpu as mv
from multiverso_tpu.models import transformer as tfm
from multiverso_tpu.sharedvar import SharedPytree


def main(steps: int = 40, sync_every: int = 5) -> float:
    mv.init()
    cfg = tfm.TransformerConfig(vocab_size=64, dim=32, num_heads=4,
                                num_layers=2, max_seq=32, attn="flash")
    params = tfm.init_params(cfg, seed=0)
    shared = SharedPytree(params, name="lm_params")
    params = shared.get()

    step = jax.jit(tfm.make_train_step(cfg, 0.3))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, (8, 33)).astype(np.int32)
    tok, tgt = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])

    loss = None
    for i in range(steps):
        params, loss = step(params, tok, tgt)
        if (i + 1) % sync_every == 0:
            # push local progress, pull the merged global state
            params = shared.sync(params)
            mv.log.info("step %d, loss %.4f (synced)", i + 1, float(loss))
    final = float(loss)
    mv.shutdown()
    print(f"transformer-PS ok: final loss {final:.4f} "
          f"(delta-sync every {sync_every} steps)")
    return final


if __name__ == "__main__":
    main()
