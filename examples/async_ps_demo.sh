#!/bin/sh
# Uncoordinated async-PS demo: 4 OS processes (no JAX coordinator), each
# training its own data blocks of a shared word2vec corpus against
# row-sharded async tables — the reference's defining workflow
# (mpirun -np 4 distributed_wordembedding), rebuilt TPU-native.
# Mirrors tests/we_async_worker.py, runnable by hand.
set -e
cd "$(dirname "$0")/.."
RDV=$(mktemp -d)
trap 'rm -rf "$RDV"' EXIT
PIDS=""
for RANK in 0 1 2 3; do
  python tests/we_async_worker.py "$RDV" 4 "$RANK" &
  PIDS="$PIDS $!"
done
# wait per-pid: a bare `wait` always exits 0, hiding worker crashes
for P in $PIDS; do
  wait "$P"
done
echo "async PS demo: 4 workers done (rendezvous $RDV)"
