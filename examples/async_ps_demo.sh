#!/bin/sh
# Uncoordinated async-PS demo: 4 OS processes (no JAX coordinator), each
# training its own data blocks of a shared word2vec corpus against
# row-sharded async tables — the reference's defining workflow
# (mpirun -np 4 distributed_wordembedding), rebuilt TPU-native.
# Mirrors tests/we_async_worker.py, runnable by hand.
#
# The wire rides the native C++ transport when libmv_ps.so builds
# (auto-built on first use); MV_PS_NATIVE=0 ./async_ps_demo.sh forces
# the pure-python plane for an A/B.
set -e
cd "$(dirname "$0")/.."
# the workers live under tests/, so python's script-dir sys.path entry is
# tests/ — the repo root must come from PYTHONPATH
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
export PYTHONPATH
RDV=$(mktemp -d)
PIDS=""
# kill stragglers before deleting their rendezvous dir (a crashed rank
# must not leave the others polling a vanished directory)
# `|| true`: set -e applies INSIDE the trap (dash), so a clean run —
# where every pid already exited and kill fails — would otherwise abort
# the trap mid-way (rc 1, rendezvous dir leaked)
trap 'kill $PIDS 2>/dev/null || true; rm -rf "$RDV"' EXIT
for RANK in 0 1 2 3; do
  python tests/we_async_worker.py "$RDV" 4 "$RANK" &
  PIDS="$PIDS $!"
done
# wait per-pid: a bare `wait` always exits 0, hiding worker crashes
for P in $PIDS; do
  wait "$P"
done
echo "async PS demo: 4 workers done (rendezvous $RDV)"
