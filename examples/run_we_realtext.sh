#!/bin/sh
# Tier-4 word2vec run on REAL text, CLI end-to-end (ref Applications/
# WordEmbedding/example/run.bat trained text8; here the committed
# text8-normalized real-prose shard is materialized first — an actual
# text8 file via MV_TEXT8 is preferred automatically).
set -e
cd "$(dirname "$0")/.."
corpus=$(python -c "from multiverso_tpu.io import realtext; print(realtext.materialize())")
python -m multiverso_tpu.apps.word_embedding \
  -train_file "$corpus" -output /tmp/realtext_vec.txt \
  -size 128 -window 5 -negative 5 -min_count 5 -epoch 3
echo "embeddings written to /tmp/realtext_vec.txt"
