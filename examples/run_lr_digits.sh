#!/bin/sh
# Tier-4 convergence run, CLI end-to-end (ref Applications/
# LogisticRegression/example/run.sh — which downloaded MNIST; here
# mnist_dir=auto picks the best REAL digit data in the image, or real
# MNIST idx files via MV_MNIST_DIR). Expected: test accuracy >= 0.93.
set -e
cd "$(dirname "$0")/.."
cfg=$(mktemp)
cat > "$cfg" <<EOF
mnist_dir=auto
minibatch_size=64
learning_rate=0.05
train_epoch=30
objective_type=softmax
updater_type=sgd
EOF
python -m multiverso_tpu.apps.logistic_regression "$cfg"
rm -f "$cfg"
