"""Pipeline-parallel LM training over a (dp, pp) mesh, GPipe and
interleaved schedules.

The reference's only "pipeline" is double-buffered communication/compute
overlap (SURVEY §2.10 — async_buffer.h, ps_model.cpp GetPipelineTable);
layer pipelining is the strategy its parameter-server design could not
express. Here the layer stack is split across the ``pp`` mesh axis,
microbatches ride a ``ppermute`` ring, and ``jax.grad`` differentiates
straight through the ring — the backward pass drains the pipeline in the
transposed schedule with no hand-written reverse code. Setting
``pp_chunks > 1`` switches to the interleaved virtual-chunk schedule
(each device holds V non-contiguous chunks; bubble shrinks V-fold).

Run: python examples/pipelined_lm.py   (8 virtual CPU devices stand in
for 8 chips; the same code runs unchanged on a TPU pod slice.)
"""

import sys

import numpy as np

sys.path.insert(0, ".")  # repo-root execution

import jax

if "--tpu" not in sys.argv:
    from multiverso_tpu.utils.platform import force_cpu_mesh
    force_cpu_mesh(8)

import jax.numpy as jnp
from jax.sharding import Mesh

import multiverso_tpu as mv
from multiverso_tpu.models import transformer as tfm


def main() -> int:
    devices = np.asarray(jax.devices())
    dp = 2 if devices.size % 2 == 0 else 1
    devices = devices.reshape(dp, devices.size // dp)
    mesh = Mesh(devices, ("dp", "pp"))
    mv.init(mesh=mesh)

    pp = devices.shape[1]
    cfg = tfm.TransformerConfig(
        vocab_size=256, dim=64, num_heads=4, num_layers=2 * pp, max_seq=32,
        attn="local", batch_axis="dp",
        pp_chunks=2,   # interleaved: pp devices x 2 chunks x 1 layer
        remat=True)    # recompute layers in backward (GPipe memory trade)
    params = tfm.init_params(cfg, seed=0)
    stacked = tfm.shard_params_pp(
        tfm.stack_pp_params(params, cfg, n_stages=pp), mesh=mesh, cfg=cfg)

    # the interleaved schedule runs a fixed n_micro == pp extent
    step = jax.jit(tfm.make_pp_train_step(cfg, n_micro=pp,
                                          learning_rate=0.1, mesh=mesh))

    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (pp * dp, cfg.max_seq + 1))
    tok = jnp.asarray(toks[:, :-1].astype(np.int32))
    tgt = jnp.asarray(toks[:, 1:].astype(np.int32))

    for i in range(20):
        stacked, loss = step(stacked, tok, tgt)
        if i % 5 == 0:
            print(f"step {i:3d}  loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f}")

    # interop: fold back to the plain [L, ...] layout for decoding
    plain = tfm.unstack_pp_params(stacked, cfg=cfg)
    out = tfm.generate(plain, tok[:2, :4],
                       cfg._replace(batch_axis=None, pp_chunks=1), 8)
    print("decoded shape:", out.shape)
    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
