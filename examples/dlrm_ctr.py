"""DLRM-style CTR training on the sharded parameter-server tables.

The workload class the PS design exists for (the reference's sparse-FTRL
LR path and 21M-vocab WordEmbedding tables): every categorical field
lives in ONE row-sharded MatrixTable, the dot-interaction MLP in one
ArrayTable, and a single jitted step does gather -> grad -> duplicate-
accumulating scatter -> server-side AdaGrad.

Run: python examples/dlrm_ctr.py [--epochs N] [--samples N]
(8 virtual CPU devices stand in for 8 chips; the same code runs
unchanged on a TPU pod slice. The size args exist so the tier-1 smoke
test can drive a short real run — tests/test_dlrm.py.)
"""

import sys

import numpy as np

sys.path.insert(0, ".")  # repo-root execution

import jax

if "--tpu" not in sys.argv:
    from multiverso_tpu.utils.platform import force_cpu_mesh
    force_cpu_mesh(8)

import jax.numpy as jnp

import multiverso_tpu as mv
from multiverso_tpu.models import dlrm
from multiverso_tpu.updaters import AddOption


def _arg(name: str, default: int) -> int:
    """--name N from argv (the example's only knobs; everything else
    routes through mv.init like the app mains)."""
    if name in sys.argv:
        return int(sys.argv[sys.argv.index(name) + 1])
    return default


def main() -> int:
    epochs = _arg("--epochs", 8)
    samples = _arg("--samples", 16384)
    mv.init()
    cfg = dlrm.DLRMConfig(vocab_sizes=(2000, 2000, 500, 100), embed_dim=16,
                          dense_dim=8, bottom_mlp=(32, 16), top_mlp=(32, 1))
    emb = mv.MatrixTable(dlrm.total_rows(cfg), cfg.embed_dim,
                         updater="adagrad", seed=0, init_scale=0.05,
                         name="ctr_embeddings")
    flat, meta = dlrm.flatten_mlp(dlrm.init_mlp_params(cfg, 0))
    mlp = mv.ArrayTable(flat.size, updater="adagrad", init=flat,
                        name="ctr_mlp")
    cat, dense, labels = dlrm.synthetic_ctr(cfg, samples, seed=1)

    opt = AddOption(learning_rate=0.2, rho=0.1)
    step = jax.jit(dlrm.make_train_step(cfg, emb, mlp, meta, opt, opt),
                   donate_argnums=(0, 1))
    es = jax.tree.map(jnp.copy, emb.state)
    ms = jax.tree.map(jnp.copy, mlp.state)
    bs = 512
    for epoch in range(epochs):
        tot, nb = 0.0, 0
        for i in range(0, len(labels), bs):
            es, ms, loss = step(es, ms, jnp.asarray(cat[i:i + bs]),
                                jnp.asarray(dense[i:i + bs]),
                                jnp.asarray(labels[i:i + bs]))
            tot, nb = tot + float(loss), nb + 1
        print(f"epoch {epoch}  bce {tot / nb:.4f}")
    emb.adopt(es)
    mlp.adopt(ms)

    # evaluate with pulled tables (the PS read path)
    mlp_params = dlrm.unflatten_mlp(jnp.asarray(mlp.get()[:flat.size]), meta)
    ids = (cat + dlrm.field_offsets(cfg)[None, :]).reshape(-1)
    rows = emb.get_rows(ids).reshape(len(labels), len(cfg.vocab_sizes),
                                     cfg.embed_dim)
    logits = dlrm.forward(mlp_params, jnp.asarray(rows),
                          jnp.asarray(dense), cfg)
    acc = float(np.mean((np.asarray(logits) > 0) == (labels > 0.5)))
    print(f"train accuracy {acc:.4f}  "
          f"(base rate {max(labels.mean(), 1 - labels.mean()):.4f})")
    mv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
